"""The cluster front-end router: flow-affine steering across N
daemon replicas.

Reference: upstream clustermesh has no packet router — kube-proxy/XDP
ECMP spreads flows across nodes and each node's agent enforces
locally.  The serving tier needs the same property made explicit: a
front end that pins a connection (forward AND reply directions) to
ONE node, so that node's private CT owns the flow, while spreading
the aggregate across the cluster.  ``flow_shard_ids`` (the RSS
analogue the sharded single-node path already uses) supplies the
direction-invariant hash; this module adds the NODE layer on top:

- a fixed SLOT space (``slot_factor`` slots per initially-configured
  node) the hash maps into, and a mutable ``slot -> owner`` table so
  membership changes move EXACTLY the affected share
  (consistent-hashing-lite): failover re-pins only the dead node's
  slots, and live scale-out (ISSUE 13, ``cluster/scale.py``) steals
  a fair share of slots for the new node WITHOUT re-hashing anyone
  else's flows.  The slot count is a multiple of the initial node
  count, so the initial layout (slot ``s`` -> node ``s % n``) routes
  identically to the PR 8 direct ``hash % n`` scheme;
- a bounded per-node FORWARD QUEUE between the router and each
  node's admission queue — the cluster-level backpressure point.
  Overflow sheds by drop-tail, counted (``router_overflow``) and
  surfaced as ``REASON_CLUSTER_OVERFLOW`` DROP events through a live
  node's monitor plane, never silently;
- one forwarder thread per node draining its queue into
  ``node.submit`` (the "router" thread-affinity domain; in
  process-per-node mode the submit is a socket send+ack on the
  shared transport — the forwarder then also carries the
  ``transport`` domain).  Forward-path latency (enqueue ->
  delivered, queue wait + transport round trip) lands in a log2
  histogram for the bench's percentiles;
- ``fail_over``: re-pin a dead node's slots and migrate its queued
  (and requeued in-flight) chunks onto the peer; rows the peer's
  queue cannot absorb are counted ``failover_dropped``; rows a
  SIGKILLed worker process admitted but never verdicted are counted
  ``crash_dropped`` (``account_crash_loss`` — the process-mode
  ledger's honesty term, computed from the node's last data-channel
  ACK);
- ``freeze`` / ``resume`` + ``wait_quiesced``: the scale-out
  migration window — a frozen router parks submitters (bounded) while
  the forwarders drain, so a CT snapshot taken inside the window is
  complete for the slots about to move;
- PIPELINED FORWARDING (ISSUE 17): with ``forward_window > 1`` a
  process-mode node's forwarder no longer blocks on a per-frame ack —
  it streams sequenced frames until the node's send window is full
  (``ProcessNode.enable_window``) and the credit comes back on the
  worker's CUMULATIVE ack, which retires every frame up to the acked
  sequence at once.  Delivery accounting moves with the credit:
  ``forwarded`` / ``forward_latency`` / ``_inflight`` for a windowed
  frame are settled by the ack callback (:meth:`_on_node_ack`), not
  the forwarder's send return, so enqueue->acked latency stays the
  honest number and ``wait_quiesced`` still means "every admitted row
  delivered AND acknowledged".  A channel that dies with frames in
  flight hands them back exactly once (:meth:`_on_window_broken`) —
  requeued at the front for failover's queue migration, so the ledger
  identity below is unchanged by the window;
- ``remove_node``: live scale-IN (the inverse of ``add_node``) — the
  victim's slots re-pin onto the surviving nodes (fewest-loaded
  first), the victim's forwarder retires, and the caller
  (``cluster/scale.py``) migrates exactly the moved slots' CT.

The cluster-wide no-silent-loss ledger this module anchors::

    submitted == sum(per-node accounted) + router_overflow
                 + failover_dropped + crash_dropped   (after stop)

where each node's own ledger (``submitted == verdicts + shed +
recovery_dropped``) accounts everything the router handed it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..serving import ServingError
from ..serving.stats import LatencyHistogram

# on_overflow(node_idx, retained rows or None, exact count): surface
# router sheds on a (live) node's monitor/metrics plane.  Called from
# forwarder threads and stop() — never from submit(), which only
# counts (the shed path must not pay event synthesis).
OverflowFn = Callable[[int, Optional[np.ndarray], int], None]

# Drop counters this module may increment.  The CTA008 checker pins
# every ``*_overflow`` / ``*_dropped`` increment in cluster/ to this
# tuple AND requires a ``cilium_cluster_<name>_total`` registry
# series per entry — a new drop site cannot ship uncounted.
DROP_COUNTERS = ("router_overflow", "failover_dropped",
                 "crash_dropped", "crypto_dropped")

# bounded retention of shed rows for DROP-event surfacing (the count
# is exact either way — same discipline as admission sheds)
SHED_RETAIN = 512

# slots per initially-configured node (DaemonConfig
# cluster_slot_factor overrides): the granularity of failover re-pin
# and scale-out share stealing
SLOT_FACTOR = 16

# a frozen router (scale-out migration window) parks submitters at
# most this long before failing loudly — a stuck migration must not
# wedge every caller forever
FREEZE_DEADLINE_S = 30.0


class ClusterRouter:
    """Flow-affine steering + bounded forwarding for N node replicas.

    ``nodes`` are handles with ``.name``, ``.alive`` and
    ``.submit(rows) -> int`` (``ClusterNode`` / ``ProcessNode`` in
    production; tests pass fakes).  ``start()`` spawns one forwarder
    thread per node; ``stop(drain=True)`` forwards everything still
    queued before returning."""

    # Lock discipline: ONE lock (the condition's) guards the whole
    # routing state — the slot table flips atomically with the queue
    # migration during failover, so a torn read cannot route a chunk
    # to a node whose queue was already drained.
    # guarded-by: _lock: _slot_owner, _owner_arr, _chunks, _pending,
    # guarded-by: _lock: _oflow_rows, _oflow_n, _stopping, submitted,
    # guarded-by: _lock: router_overflow, failover_dropped, forwarded,
    # guarded-by: _lock: _suspect, crash_dropped, _frozen, _inflight,
    # guarded-by: _lock: forward_latency, _nchunks, _retired,
    # guarded-by: _lock: _win_swept, crypto_dropped

    def __init__(self, nodes: Sequence, forward_depth: int,
                 on_overflow: Optional[OverflowFn] = None,
                 shed_retain: int = SHED_RETAIN,
                 slot_factor: int = SLOT_FACTOR,
                 trace_sample: int = 0, span_store=None,
                 forward_window: int = 1):
        if not nodes:
            raise ValueError("cluster router needs at least one node")
        self.nodes = list(nodes)
        self.n_nodes = len(self.nodes)
        self.forward_depth = int(forward_depth)
        if self.forward_depth < 1:
            raise ValueError("forward_depth must be >= 1")
        slot_factor = int(slot_factor)
        if slot_factor < 1:
            raise ValueError("slot_factor must be >= 1")
        self._on_overflow = on_overflow
        self._shed_retain = int(shed_retain)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # slot s (the FIXED flow hash space) -> owning node index.
        # n_slots is a multiple of the initial node count, so the
        # initial s % n layout routes exactly like hash % n (PR 8
        # semantics); failover and scale-out mutate ownership only.
        # The numpy mirror serves the vectorized submit path; both
        # flip together under the lock.
        self.n_slots = slot_factor * self.n_nodes
        self._slot_owner: List[int] = [s % self.n_nodes
                                       for s in range(self.n_slots)]
        self._owner_arr = np.asarray(self._slot_owner, dtype=np.int64)
        self._chunks: List[list] = [[] for _ in self.nodes]
        self._pending = [0] * self.n_nodes
        # rows a forwarder popped and is delivering right now (the
        # quiesce condition: pending AND inflight both zero)
        self._inflight = [0] * self.n_nodes
        # per-node shed surfacing backlog (bounded rows, exact count)
        self._oflow_rows: List[list] = [[] for _ in self.nodes]
        self._oflow_n = [0] * self.n_nodes
        # a forwarder whose submit raised parks its node as suspect
        # until failover re-pins or stop() sweeps
        self._suspect = [False] * self.n_nodes
        # scale-in leaves the index in place (ledger continuity) but a
        # retired node routes nothing and its forwarder has exited
        self._retired = [False] * self.n_nodes
        # ISSUE 17 pipelining: frames-in-flight credit window per
        # node.  Indices whose node handle grew a send window
        # (ProcessNode.enable_window) — membership is fixed before the
        # forwarder thread starts, so forwarders read it lock-free.
        self.forward_window = max(int(forward_window), 1)
        self._windowed: set = set()
        # nodes whose undrained in-flight rows stop() already counted
        # failover_dropped: a later broken-window hand-back for them
        # is span-loss only, never a requeue (no double-count)
        self._win_swept: set = set()
        self._frozen = False
        self._stopping = False
        self._threads: List[threading.Thread] = []
        self.submitted = 0
        self.router_overflow = 0
        self.failover_dropped = 0
        # rows a crashed (SIGKILLed) worker admitted but never
        # verdicted — see account_crash_loss
        self.crash_dropped = 0
        # rows in sealed frames the worker REJECTED (decrypt failure,
        # replay, stale epoch — ISSUE 18): delivered but never
        # admitted, counted here via the node's reject callback
        self.crypto_dropped = 0
        self.forwarded = [0] * self.n_nodes
        # enqueue -> delivered µs (queue wait + node submit / socket
        # round trip): the bench's forward-path percentiles
        self.forward_latency = LatencyHistogram()
        # ISSUE 14 cross-process span stitching: every trace_sample'th
        # APPENDED chunk carries a TraceCtx through the forward path
        # (frame + ack echo in process mode); completed spans land in
        # span_store (obs/relay.ClusterSpanStore).  0 = off — the
        # hot-path cost is one int compare per appended chunk.
        self._trace_sample = int(trace_sample)
        self.span_store = span_store
        self._nchunks = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        if self._threads:
            raise ServingError("cluster router already started")
        for i in range(self.n_nodes):
            self._spawn_forwarder(i)

    def _spawn_forwarder(self, idx: int) -> None:
        # thread-affinity: api
        # holds: nothing — callers serialize (start / add_node)
        node = self.nodes[idx]
        if hasattr(node, "set_reject_cb"):
            # ISSUE 18 encrypted channel: a worker's crypto-reject
            # (NACK) lands here — the frame was DELIVERED but its
            # rows were never admitted, a counted flow-visible drop
            node.set_reject_cb(
                lambda n_rows, reason, ctx=None, i=idx:
                    self._on_crypto_reject(i, n_rows, reason, ctx))
        if (self.forward_window > 1 and idx not in self._windowed
                and hasattr(node, "enable_window")):
            # windowed membership is decided HERE, before the thread
            # exists — the forwarder reads _windowed without the lock
            node.enable_window(
                self.forward_window,
                on_ack=lambda entries, i=idx:
                    self._on_node_ack(i, entries),
                on_broken=lambda entries, i=idx:
                    self._on_window_broken(i, entries))
            self._windowed.add(idx)
        t = threading.Thread(target=self._forward_loop, args=(idx,),
                             daemon=True,
                             name=f"cluster-fwd-{node.name}")
        self._threads.append(t)
        t.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> dict:
        # thread-affinity: api
        """Stop the forwarders; with ``drain`` every queued chunk is
        offered to its (current) owner synchronously first — rows a
        dead owner can no longer take are counted
        ``failover_dropped``, so the ledger closes exactly."""
        with self._cv:
            self._stopping = True
            self._frozen = False
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads = []
        if drain:
            with self._cv:
                retired = list(self._retired)
            for idx in range(self.n_nodes):
                if retired[idx]:
                    continue
                windowed = idx in self._windowed
                while True:
                    with self._cv:
                        if not self._chunks[idx]:
                            break
                        chunk, t_enq, ctx = self._chunks[idx].pop(0)
                        self._pending[idx] -= len(chunk)
                    node = self.nodes[idx]
                    try:
                        if windowed:
                            # windowed delivery settles on the ack:
                            # forwarded/latency/span land in
                            # _on_node_ack, loss on a broken channel
                            # comes back via _on_window_broken and is
                            # swept below
                            with self._cv:
                                self._inflight[idx] += len(chunk)
                            if ctx is not None:
                                ctx.node = node.name
                                ctx.t_fwd = time.monotonic()
                            node.submit(chunk, trace=ctx, t_enq=t_enq)
                        else:
                            if ctx is not None \
                                    and self.span_store is not None:
                                # span lost at stop (sync path has no
                                # ack to complete it here)
                                self.span_store.drop_span(ctx)
                            node.submit(chunk)
                            with self._cv:
                                self.forwarded[idx] += len(chunk)
                    except Exception:  # noqa: BLE001 — a dead/terminal
                        # node at stop: its loss is counted, not raised
                        with self._cv:
                            if windowed:
                                self._inflight[idx] -= len(chunk)
                            self.failover_dropped += len(chunk)
                        if windowed and ctx is not None \
                                and self.span_store is not None:
                            self.span_store.drop_span(ctx)
            # close every open window: force the worker-side flush
            # timer's hand, then wait for the cumulative acks so the
            # ledger below reflects every delivered frame
            for idx in sorted(self._windowed):
                if retired[idx]:
                    continue
                node = self.nodes[idx]
                try:
                    node.ack_flush()
                except Exception:  # noqa: BLE001 — dead channel: the
                    pass  # broken-window sweep below accounts it
                try:
                    node.drain_window(timeout)
                except Exception:  # noqa: BLE001
                    pass
                # a window that did NOT drain (dead worker holding
                # the channel half-open, or the timeout) still owes
                # its in-flight rows to the ledger: count them lost
                # NOW and mark the node swept — the late hand-back
                # when its channel finally closes must not resurrect
                # rows the ledger already closed over
                with self._cv:
                    left = self._inflight[idx]
                    if left:
                        self.failover_dropped += left
                        self._inflight[idx] = 0
                        self._win_swept.add(idx)
            # a channel that broke during the drain handed its
            # in-flight frames back to the queue — no forwarder is
            # left to retry them, so their loss is counted now
            lost_spans = []
            with self._cv:
                for idx in range(self.n_nodes):
                    while self._chunks[idx]:
                        chunk, _t_enq, ctx = self._chunks[idx].pop(0)
                        self._pending[idx] -= len(chunk)
                        self.failover_dropped += len(chunk)
                        if ctx is not None:
                            lost_spans.append(ctx)
            if self.span_store is not None:
                for ctx in lost_spans:
                    self.span_store.drop_span(ctx)
        self._flush_overflow_all()
        return self.snapshot()

    # -- the enqueue path (the cluster tier's hot path) ----------------
    def submit(self, rows: np.ndarray) -> int:
        """Offer header rows; returns how many entered a forward
        queue.  Never blocks in steady state: per-node overflow sheds
        drop-tail, counted exactly (rows retained for DROP surfacing
        up to the retention bound); the one exception is a FROZEN
        router (a live scale-out migration window, bounded by
        ``FREEZE_DEADLINE_S``), which parks the caller until the slot
        table settles — blocking beats misrouting a flow whose CT is
        mid-migration.  Chunks are COPIED in — callers may reuse
        their buffers immediately.  (Thin unannotated wrapper: the
        annotated hot path is :meth:`_route` — a generic name like
        ``submit`` must not carry the ``router`` affinity or the
        call graph's name-match fallback would taint every other
        ``.submit`` call in the repo.)"""
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError(
                f"cluster submit wants [n, N_COLS] rows, got shape "
                f"{rows.shape}")
        return self._route(rows)

    def _route(self, rows: np.ndarray) -> int:
        # thread-affinity: router
        """The enqueue hot path: flow-hash + per-node bounded queue
        append, one lock window, no allocation beyond the admitted
        copies (CTA003 purity-scanned from here)."""
        from ..parallel.mesh import flow_shard_ids

        ids = flow_shard_ids(rows, self.n_slots)
        admitted = 0
        t_enq = time.monotonic()
        with self._cv:
            deadline = None
            while self._frozen and not self._stopping:
                if deadline is None:
                    deadline = time.monotonic() + FREEZE_DEADLINE_S
                self._cv.wait(0.05)
                # checked every lap, NOT only on wait timeout: a
                # suspect node's requeue path notify_all()s each
                # retry, and a notified wait would otherwise starve
                # the deadline forever
                if (self._frozen and not self._stopping
                        and time.monotonic() > deadline):
                    raise ServingError(
                        "cluster router frozen past the migration "
                        "deadline — scale-out wedged")
            if self._stopping:
                raise ServingError("cluster router is stopped")
            self.submitted += len(rows)
            owners = self._owner_arr[ids]
            for o in np.unique(owners):
                o = int(o)
                sub = rows[owners == o]
                space = self.forward_depth - self._pending[o]
                take = min(max(space, 0), len(sub))
                if take:
                    ctx = None
                    if self._trace_sample > 0 \
                            and self.span_store is not None:
                        if self._nchunks % self._trace_sample == 0:
                            ctx = self.span_store.allocate_span(
                                take, t_enq)
                        self._nchunks += 1
                    self._chunks[o].append(
                        (np.array(sub[:take], copy=True), t_enq,
                         ctx))
                    self._pending[o] += take
                    admitted += take
                lost = len(sub) - take
                if lost:
                    self.router_overflow += lost
                    self._oflow_n[o] += lost
                    room = self._shed_retain - sum(
                        len(r) for r in self._oflow_rows[o])
                    if room > 0:
                        self._oflow_rows[o].append(
                            np.array(sub[take:take + room], copy=True))
            self._cv.notify_all()
        return admitted

    # -- forwarders ----------------------------------------------------
    def _forward_loop(self, idx: int) -> None:
        # thread-affinity: router
        node = self.nodes[idx]
        windowed = idx in self._windowed  # fixed before thread start
        while True:
            with self._cv:
                while (not self._stopping and not self._retired[idx]
                       and (not node.alive or self._suspect[idx]
                            or (not self._chunks[idx]
                                and not self._oflow_n[idx]))):
                    # parked: dead/suspect node (failover will steal
                    # the queue), retired node (scale-in), or simply
                    # nothing to do
                    self._cv.wait(0.05)
                    if node.alive and self._suspect[idx]:
                        self._suspect[idx] = False  # healed
                if self._stopping or self._retired[idx]:
                    return
                chunk = t_enq = ctx = None
                if self._chunks[idx]:
                    chunk, t_enq, ctx = self._chunks[idx].pop(0)
                    self._pending[idx] -= len(chunk)
                    # additive, not assignment: a windowed node keeps
                    # rows in flight across many forwarder laps until
                    # the cumulative ack retires them
                    self._inflight[idx] += len(chunk)
                oflow_rows, oflow_n = self._take_oflow_locked(idx)
            if chunk is not None:
                try:
                    if ctx is not None:
                        # span stitching: stamp the forward stage and
                        # ride the chunk; the node fills recv/admit
                        # (ack echo in process mode, direct stamps
                        # in thread mode)
                        ctx.node = node.name
                        ctx.t_fwd = time.monotonic()
                    if windowed:
                        # pipelined: submit returns once the frame is
                        # ON THE WIRE (blocking only while the send
                        # window is out of credit).  forwarded /
                        # latency / inflight settle in _on_node_ack
                        # when the cumulative ack covers this frame —
                        # after this call the ack thread owns ctx.
                        node.submit(chunk, trace=ctx, t_enq=t_enq)
                    else:
                        if ctx is not None:
                            node.submit(chunk, trace=ctx)
                        else:
                            node.submit(chunk)
                        with self._cv:
                            self.forwarded[idx] += len(chunk)
                            self._inflight[idx] -= len(chunk)
                            self.forward_latency.record(
                                (time.monotonic() - t_enq) * 1e6)
                            self._cv.notify_all()
                        if ctx is not None:
                            ctx.t_ack = time.monotonic()
                            # commit counts an echo-less span as
                            # dropped
                            self.span_store.commit_span(ctx)
                except Exception:  # noqa: BLE001 — crashed/terminal
                    # node: requeue AT THE FRONT and park as suspect;
                    # failover's queue migration (or stop's drain)
                    # claims the chunk with its loss accounted.  A
                    # windowed submit that raised never entered the
                    # send window (SendWindow.drop unwinds a failed
                    # send), so this requeue cannot double with
                    # _on_window_broken's.
                    with self._cv:
                        self._chunks[idx].insert(0, (chunk, t_enq,
                                                     ctx))
                        self._pending[idx] += len(chunk)
                        self._inflight[idx] -= len(chunk)
                        self._suspect[idx] = True
                        self._cv.notify_all()
            if oflow_n and self._on_overflow is not None:
                self._surface(idx, oflow_rows, oflow_n)

    def _on_node_ack(self, idx: int, entries: list) -> None:
        # thread-affinity: transport
        """Credit return: the node's cumulative ack just covered
        ``entries`` (``(n_rows, t_enq, ctx)`` in send order) —
        delivery accounting for windowed frames lands here, with the
        SAME enqueue->acked semantics the sync path's blocking submit
        measured, so the bench's p50 comparison is honest."""
        now = time.monotonic()
        with self._cv:
            for n_rows, t_enq, _ctx in entries:
                self.forwarded[idx] += n_rows
                self._inflight[idx] -= n_rows
                self.forward_latency.record((now - t_enq) * 1e6)
            self._cv.notify_all()
        if self.span_store is not None:
            for _n, _t, ctx in entries:
                if ctx is not None:
                    ctx.t_ack = now
                    # commit counts an echo-less span as dropped
                    self.span_store.commit_span(ctx)

    def _on_window_broken(self, idx: int, entries: list) -> None:
        # thread-affinity: transport
        """The node's data channel died with ``entries``
        (``(rows, t_enq, ctx)`` ascending by sequence) sent but never
        acked.  They were never admitted by the worker — the last
        cumulative ack is the final word — so they re-enter the queue
        AT THE FRONT (order preserved) for failover's migration or
        stop's sweep to account.  Called exactly once per channel
        (``ProcessNode`` hands the window back via ``take_all``).
        A node stop() already SWEPT (its undrained in-flight rows
        counted ``failover_dropped``) only loses spans here — the
        rows are closed ledger, requeuing would double-count."""
        with self._cv:
            if idx in self._win_swept:
                swept = True
            else:
                swept = False
                for rows, t_enq, ctx in reversed(entries):
                    self._chunks[idx].insert(0, (rows, t_enq, ctx))
                    self._pending[idx] += len(rows)
                    self._inflight[idx] -= len(rows)
                self._suspect[idx] = True
            self._cv.notify_all()
        if swept and self.span_store is not None:
            for _rows, _t_enq, ctx in entries:
                if ctx is not None:
                    self.span_store.drop_span(ctx)

    def _take_oflow_locked(self, idx: int):
        # thread-affinity: router, api -- forwarder flush + the stop
        # path's final sweep; callers hold _lock
        # holds: _lock
        rows, self._oflow_rows[idx] = self._oflow_rows[idx], []
        n, self._oflow_n[idx] = self._oflow_n[idx], 0
        return rows, n

    def _surface(self, idx: int, rows_list: list, count: int) -> None:
        # thread-affinity: router, api
        rows = (np.concatenate(rows_list) if rows_list else None)
        try:
            self._on_overflow(idx, rows, count)
        except Exception:  # noqa: BLE001 — surfacing is best-effort;
            pass  # the exact count already lives in router_overflow

    def _flush_overflow_all(self) -> None:
        # thread-affinity: api
        for idx in range(self.n_nodes):
            with self._cv:
                rows_list, n = self._take_oflow_locked(idx)
            if n and self._on_overflow is not None:
                self._surface(idx, rows_list, n)

    # -- failover ------------------------------------------------------
    def fail_over(self, dead_idx: int,
                  peer_idx: Optional[int]) -> dict:
        # thread-affinity: api
        """Re-pin every slot the dead node owns onto ``peer_idx`` and
        migrate its queued chunks (including any chunk a forwarder
        requeued mid-crash).  Rows the peer's queue cannot absorb —
        or all of them when no peer is left — are counted
        ``failover_dropped``.  Atomic under the router lock: no
        submit can route into the dead queue mid-migration."""
        moved = dropped = 0
        with self._cv:
            for s in range(len(self._slot_owner)):
                if self._slot_owner[s] == dead_idx:
                    self._slot_owner[s] = (peer_idx if peer_idx
                                           is not None else dead_idx)
            self._owner_arr = np.asarray(self._slot_owner,
                                         dtype=np.int64)
            while self._chunks[dead_idx]:
                chunk, t_enq, ctx = self._chunks[dead_idx].pop(0)
                self._pending[dead_idx] -= len(chunk)
                take = 0
                if peer_idx is not None:
                    space = (self.forward_depth
                             - self._pending[peer_idx])
                    take = min(max(space, 0), len(chunk))
                if take:
                    # a WHOLLY-moved chunk keeps its trace ctx (the
                    # span completes on the peer); a split one drops
                    # it — half a chunk's hop timings would lie
                    self._chunks[peer_idx].append(
                        (chunk[:take], t_enq,
                         ctx if take == len(chunk) else None))
                    self._pending[peer_idx] += take
                    moved += take
                    if ctx is not None and take != len(chunk) \
                            and self.span_store is not None:
                        self.span_store.drop_span(ctx)
                elif ctx is not None and self.span_store is not None:
                    self.span_store.drop_span(ctx)
                lost = len(chunk) - take
                if lost:
                    self.failover_dropped += lost
                    dropped += lost
            # shed-surfacing backlog follows the flows to the peer
            # (the dead node's monitor plane is gone)
            if peer_idx is not None and self._oflow_n[dead_idx]:
                self._oflow_rows[peer_idx].extend(
                    self._oflow_rows[dead_idx])
                self._oflow_n[peer_idx] += self._oflow_n[dead_idx]
                self._oflow_rows[dead_idx] = []
                self._oflow_n[dead_idx] = 0
            self._suspect[dead_idx] = False
            self._cv.notify_all()
        return {"moved": moved, "dropped": dropped}

    def account_crash_loss(self, count: int) -> int:
        # thread-affinity: api
        """Count rows a crashed worker process ADMITTED (acked over
        the data channel) but never turned into verdicts — the delta
        between the last ack's ``submitted`` and its accounted
        counters (``cluster/process.py`` computes it; a SIGKILL
        leaves no other witness).  Returns the count, clamped at
        zero, so the cluster ledger closes exactly over the
        corpse."""
        count = max(int(count), 0)
        if count:
            with self._cv:
                self.crash_dropped += count
        return count

    def _on_crypto_reject(self, idx: int, n_rows: int, reason: str,
                          ctx=None) -> None:
        # thread-affinity: transport -- the node's data-channel
        # reader (sync submit or ack reader), via set_reject_cb
        """Account one worker crypto-reject (ISSUE 18).  The rows
        reached the worker but were never admitted — a counted
        ``crypto_dropped``, NOT a requeue (retrying a frame the
        worker's replay window already saw would just reject again).
        In pipelined mode the NACK also popped the frame from the
        send window, so its in-flight debt retires here; sync mode's
        forwarder settles its own in-flight accounting."""
        with self._cv:
            if n_rows and idx not in self._win_swept:
                # a node stop() already swept counted its in-flight
                # rows failover_dropped; a late NACK for one of them
                # must not count the rows twice
                self.crypto_dropped += n_rows
                if idx in self._windowed:
                    self._inflight[idx] -= n_rows
            self._cv.notify_all()
        if ctx is not None and self.span_store is not None:
            self.span_store.drop_span(ctx)

    # -- live scale-out (cluster/scale.py drives this) -----------------
    def freeze(self) -> None:
        # thread-affinity: api
        """Park new submits (bounded — see :meth:`submit`) while a
        migration recomputes slot ownership.  Forwarders keep
        draining, so :meth:`wait_quiesced` converges."""
        with self._cv:
            self._frozen = True

    def resume(self) -> None:
        # thread-affinity: api
        with self._cv:
            self._frozen = False
            self._cv.notify_all()

    def wait_quiesced(self, timeout: float = 30.0,
                      nodes: Optional[Sequence[int]] = None) -> bool:
        # thread-affinity: api
        """Block until the given nodes' forward queues are empty AND
        no chunk is mid-delivery — every row the router admitted has
        been DELIVERED to its node.  Delivered is not verdicted: rows
        may still sit in the node's own admission ring, so a caller
        that needs CT completeness (``cluster/scale.py``) must also
        wait for the node ledgers to catch up."""
        idxs = (list(nodes) if nodes is not None
                else list(range(self.n_nodes)))
        deadline = time.monotonic() + timeout
        with self._cv:
            while any(self._pending[i] or self._inflight[i]
                      for i in idxs):
                # deadline checked every lap (a notified wait must
                # not starve it — see _route's freeze park)
                if time.monotonic() > deadline:
                    return False
                self._cv.wait(0.05)
            return True

    def add_node(self, node) -> List[int]:
        # thread-affinity: api
        """Grow the router by one node: extend the per-node state,
        steal a fair share of slots (⌊n_slots / new_n⌋, taken
        round-robin from the current owners with the most slots so
        the layout stays balanced), flip the table atomically, and
        spawn the new forwarder.  Returns the moved slot ids — the
        caller (``cluster/scale.py``) migrates exactly those slots'
        CT.  Call FROZEN + quiesced: the atomic flip keeps routing
        correct either way, but CT continuity for moved flows needs
        the donors drained first."""
        with self._cv:
            new_idx = self.n_nodes
            self.nodes.append(node)
            self.n_nodes += 1
            self._chunks.append([])
            self._pending.append(0)
            self._inflight.append(0)
            self._oflow_rows.append([])
            self._oflow_n.append(0)
            self._suspect.append(False)
            self._retired.append(False)
            self.forwarded.append(0)
            share = self.n_slots // self.n_nodes
            counts = {}
            for owner in self._slot_owner:
                counts[owner] = counts.get(owner, 0) + 1
            moved: List[int] = []
            while len(moved) < share:
                donor = max(counts, key=lambda o: (counts[o], -o))
                if counts[donor] <= 1:
                    break  # never strip a node's last slot
                for s in range(self.n_slots):
                    if self._slot_owner[s] == donor:
                        self._slot_owner[s] = new_idx
                        counts[donor] -= 1
                        moved.append(s)
                        break
            self._owner_arr = np.asarray(self._slot_owner,
                                         dtype=np.int64)
            self._cv.notify_all()
        if self._threads:  # started router: the new node forwards too
            self._spawn_forwarder(new_idx)
        return moved

    def remove_node(self, idx: int) -> List[int]:
        # thread-affinity: api
        """Live scale-IN: re-pin every slot ``idx`` owns onto the
        surviving live nodes (fewest-loaded first, so the layout stays
        balanced), retire the forwarder, and return the moved slot ids
        — the caller (``cluster/scale.py``) migrates exactly those
        slots' CT to each slot's NEW owner.  Call FROZEN + quiesced
        (window drained): the victim's queue is normally empty; any
        residue is migrated like failover would, counted if a
        survivor's queue cannot absorb it.  The index stays in place —
        a retired node keeps its ledger row but routes nothing."""
        with self._cv:
            if self._retired[idx]:
                raise ServingError(
                    f"node index {idx} is already retired")
            survivors = [i for i in range(self.n_nodes)
                         if i != idx and not self._retired[i]
                         and self.nodes[i].alive]
            if not survivors:
                raise ServingError(
                    "cannot retire the last live node")
            counts = {i: 0 for i in survivors}
            for o in self._slot_owner:
                if o in counts:
                    counts[o] += 1
            moved: List[int] = []
            for s in range(self.n_slots):
                if self._slot_owner[s] == idx:
                    tgt = min(counts, key=lambda i: (counts[i], i))
                    self._slot_owner[s] = tgt
                    counts[tgt] += 1
                    moved.append(s)
            self._owner_arr = np.asarray(self._slot_owner,
                                         dtype=np.int64)
            # residual queue (quiesced callers hit the fast path:
            # it's empty) — migrate to the least-loaded survivor
            while self._chunks[idx]:
                chunk, t_enq, ctx = self._chunks[idx].pop(0)
                self._pending[idx] -= len(chunk)
                pend = self._pending
                tgt = min(counts, key=lambda i: (pend[i], i))
                space = self.forward_depth - self._pending[tgt]
                take = min(max(space, 0), len(chunk))
                if take:
                    self._chunks[tgt].append(
                        (chunk[:take], t_enq,
                         ctx if take == len(chunk) else None))
                    self._pending[tgt] += take
                lost = len(chunk) - take
                if lost:
                    self.failover_dropped += lost
                if ctx is not None and take != len(chunk) \
                        and self.span_store is not None:
                    self.span_store.drop_span(ctx)
            # shed-surfacing backlog follows the flows
            if self._oflow_n[idx]:
                tgt = survivors[0]
                self._oflow_rows[tgt].extend(self._oflow_rows[idx])
                self._oflow_n[tgt] += self._oflow_n[idx]
                self._oflow_rows[idx] = []
                self._oflow_n[idx] = 0
            self._retired[idx] = True
            self._suspect[idx] = False
            self._cv.notify_all()
        return moved

    def slots_of(self, idx: int) -> List[int]:
        # thread-affinity: any
        with self._cv:
            return [s for s, o in enumerate(self._slot_owner)
                    if o == idx]

    # -- reading -------------------------------------------------------
    def pending_total(self) -> int:
        # thread-affinity: any
        with self._cv:
            return sum(self._pending) + sum(self._inflight)

    def snapshot(self) -> dict:
        # thread-affinity: any
        with self._cv:
            lat = self.forward_latency
            snap = {
                "submitted": self.submitted,
                "forwarded": list(self.forwarded),
                "pending": list(self._pending),
                "inflight": list(self._inflight),
                "retired": list(self._retired),
                "forward-window": self.forward_window,
                "router-overflow": self.router_overflow,
                "failover-dropped": self.failover_dropped,
                "crash-dropped": self.crash_dropped,
                "crypto-dropped": self.crypto_dropped,
                "n-slots": self.n_slots,
                "slot-owner": list(self._slot_owner),
                "forward-latency-us": {
                    "p50": lat.percentile(0.50),
                    "p95": lat.percentile(0.95),
                    "p99": lat.percentile(0.99),
                    "max": round(lat.max_us, 1),
                    "count": lat.count,
                },
                "trace": (self.span_store.span_stats()
                          if self.span_store is not None else None),
            }
        # window/credit counters live on the node handles (their own
        # locks) — read outside the router lock
        acks = coalesced = stalls = frames = 0
        for idx in sorted(self._windowed):
            try:
                ts = self.nodes[idx].transport_stats()
            except Exception:  # noqa: BLE001 — a dead handle still
                continue  # counts: skip only on a torn read
            acks += int(ts.get("acks", 0))
            coalesced += int(ts.get("acks-coalesced", 0))
            stalls += int(ts.get("window-stalls", 0))
            frames += int(ts.get("inflight-frames", 0))
        snap["window"] = {
            "acks": acks,
            "acks-coalesced": coalesced,
            "window-stalls": stalls,
            "inflight-frames": frames,
        }
        # ISSUE 18 encrypted channel: parent-side seal/open counters
        # summed over every encrypted node handle (None when the
        # cluster runs plaintext — the surfaces omit the block)
        crypto = None
        for node in self.nodes:
            try:
                cs = node.transport_stats().get("crypto")
            except Exception:  # noqa: BLE001 — torn read on a dead
                continue  # handle: skip, counters only
            if cs is None:
                continue
            if crypto is None:
                crypto = {"sealed": 0, "opened": 0, "rejected": 0,
                          "replays": 0, "rotations": 0, "epoch": 0}
            for k in ("sealed", "opened", "rejected", "replays",
                      "rotations"):
                crypto[k] += int(cs.get(k, 0))
            crypto["epoch"] = max(crypto["epoch"],
                                  int(cs.get("epoch", 0)))
        snap["crypto"] = crypto
        return snap
