"""Cluster membership: node liveness on top of the health plane, and
kvstore-propagated policy so every replica converges on one ruleset.

Reference: upstream cilium-health probes every registered node and
``clustermesh-apiserver`` / kvstoremesh fan cluster state through the
kvstore.  Here the node registry + probe mesh (``health/``) already
exist; this module adds the two cluster-serving pieces on top:

- :class:`ClusterMembership` — a periodic liveness sweep over the
  node replicas with a DEATH THRESHOLD (consecutive failed probes)
  and an exactly-once ``on_death`` hook the failover orchestrator
  hangs off.  The probe site (``infra/faults.py`` ``cluster.probe``)
  makes node death INJECTABLE and deterministic: an armed
  ``cluster.probe=1x1@K`` fault CRASHES the K-th probed node (probe
  order is fixed), after which the health-driven path detects and
  fails it over exactly as it would a organic death.
- :class:`ClusterPolicySync` — policy rules ride the same kvstore
  plane identities replicate over (``cilium/state/policy/v1``):
  ``publish`` bumps a revision, every node's watch applies it once
  (including the publisher's own — exactly-once via the revision
  guard), so all replicas enforce the same ruleset within the
  convergence window the kvstore transport provides.

THREAD AFFINITY NOTE: the prober runs on its own thread, declared
``api`` — the annotation vocabulary's control-plane family (API
handlers, CLI, tests' main thread, and now cluster orchestration).
Failover work it triggers (CT replay, runtime kill, router re-pin)
is control-plane work and reuses the ``api``-declared surfaces
(``ct_restore``, ``runtime.stop`` ...) without widening them.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..infra import faults

POLICY_PREFIX = "cilium/state/policy/v1"
POLICY_KEY = f"{POLICY_PREFIX}/rules"


class ClusterMembership:
    """Liveness sweep + death detection over the node replicas.

    ``on_death(name, detail)`` fires EXACTLY ONCE per node, from the
    prober thread (or the caller's thread via
    :meth:`declare_dead`)."""

    # guarded-by: _lock: _failures, _dead, _first_fail, _latency_ms,
    # guarded-by: _lock: _probes, nodes

    def __init__(self, nodes: Sequence,
                 probe_interval_s: float,
                 death_threshold: int,
                 on_death: Callable[[str, dict], None],
                 node_registry=None):
        self.nodes = list(nodes)
        self.probe_interval_s = float(probe_interval_s)
        self.death_threshold = int(death_threshold)
        if self.probe_interval_s <= 0:
            raise ValueError("probe_interval_s must be > 0")
        if self.death_threshold < 1:
            raise ValueError("death_threshold must be >= 1")
        self._on_death = on_death
        self._registry = node_registry
        self._lock = threading.Lock()
        self._failures: Dict[str, int] = {}
        self._first_fail: Dict[str, float] = {}
        self._latency_ms: Dict[str, float] = {}
        self._dead: Dict[str, dict] = {}
        self._probes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="cluster-membership")
        self._thread.start()

    def stop(self) -> None:
        # thread-affinity: api
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
            self._thread = None

    def add_node(self, node) -> None:
        # thread-affinity: api
        """A scale-out replica joins the sweep (cluster/scale.py).
        The probe loop iterates a snapshot per sweep, so appending
        under the lock is enough."""
        with self._lock:
            self.nodes.append(node)

    def remove_node(self, name: str) -> bool:
        # thread-affinity: api
        """Scale-IN: take ``name`` out of the sweep WITHOUT declaring
        it dead — a retired node must not trigger failover when its
        process exits.  Returns whether the node was swept.  Probe
        bookkeeping for the name is cleared so a future replica
        reusing it starts clean."""
        with self._lock:
            before = len(self.nodes)
            self.nodes = [n for n in self.nodes if n.name != name]
            self._failures.pop(name, None)
            self._first_fail.pop(name, None)
            self._latency_ms.pop(name, None)
            return len(self.nodes) != before

    # -- probing -------------------------------------------------------
    def _probe_loop(self) -> None:
        # thread-affinity: api -- the membership prober is a
        # control-plane thread (see module doc)
        while not self._stop.wait(self.probe_interval_s):
            self.probe_all()

    def probe_all(self) -> None:
        # thread-affinity: api
        """One sweep: probe every not-yet-dead node in fixed order.
        The ``cluster.probe`` fault site fires per probe; an injected
        fault CRASHES the probed node (deterministic node death for
        chaos tests) and the probe records the failure."""
        with self._lock:
            sweep = list(self.nodes)
        for node in sweep:
            with self._lock:
                if node.name in self._dead:
                    continue
                self._probes += 1
            ok, err = True, ""
            t0 = time.perf_counter()
            try:
                faults.check(faults.SITE_CLUSTER_PROBE)
                ok = bool(node.probe())
                if not ok:
                    err = "probe returned unhealthy"
            except faults.InjectedFault as e:
                node.crash(f"injected node death ({e})")
                ok, err = False, str(e)
            except Exception as e:  # noqa: BLE001 — a probe transport
                ok, err = False, f"{type(e).__name__}: {e}"  # fault
            latency_ms = (time.perf_counter() - t0) * 1e3
            declare = None
            with self._lock:
                self._latency_ms[node.name] = round(latency_ms, 3)
                if ok:
                    self._failures[node.name] = 0
                    self._first_fail.pop(node.name, None)
                    continue
                n = self._failures.get(node.name, 0) + 1
                self._failures[node.name] = n
                self._first_fail.setdefault(node.name,
                                            time.monotonic())
                if n >= self.death_threshold:
                    declare = {
                        "cause": err[:200],
                        "consecutive-failures": n,
                        "detect-ms": round(
                            (time.monotonic()
                             - self._first_fail[node.name]) * 1e3, 3),
                    }
            if declare is not None:
                self.declare_dead(node.name, declare)

    def declare_dead(self, name: str, detail: Optional[dict] = None
                     ) -> bool:
        # thread-affinity: api
        """Mark ``name`` dead and fire ``on_death`` exactly once.
        Returns False when the node was already declared (the hook
        does not re-fire)."""
        detail = dict(detail or {})
        with self._lock:
            if name in self._dead:
                return False
            detail.setdefault("declared-at", time.time())
            self._dead[name] = detail
        if self._registry is not None:
            try:
                self._registry.annotate(name, {"cluster-state": "dead"})
            except Exception:  # noqa: BLE001 — registry annotation is
                pass  # advisory; death handling must not die on it
        try:
            self._on_death(name, detail)
        except Exception:  # noqa: BLE001 — a failing failover (e.g.
            # a crash-stop join timing out behind a wedged compile)
            # must not kill the prober thread: LATER node deaths
            # still have to be detected, and the failure must be
            # loud — this is an incident, not steady state
            import logging

            logging.getLogger(__name__).exception(
                "cluster failover for %s failed", name)
        return True

    # -- reading -------------------------------------------------------
    def is_dead(self, name: str) -> bool:
        # thread-affinity: any
        with self._lock:
            return name in self._dead

    def dead_nodes(self) -> List[str]:
        # thread-affinity: any
        with self._lock:
            return sorted(self._dead)

    def statuses(self) -> List[dict]:
        # thread-affinity: any
        with self._lock:
            out = []
            for node in self.nodes:
                d = self._dead.get(node.name)
                out.append({
                    "name": node.name,
                    "state": "dead" if d is not None else "live",
                    "consecutive-failures":
                        self._failures.get(node.name, 0),
                    "probe-latency-ms":
                        self._latency_ms.get(node.name),
                    **({"death": d} if d is not None else {}),
                })
            return out


class ClusterPolicySync:
    """One node's end of the kvstore policy plane: watch the policy
    key, apply each revision exactly once (the publisher applies its
    own write through the same watch — no special-casing).

    Application is DEFERRED to a dedicated applier thread, never run
    on the kvstore client's watch-dispatcher thread: a policy import
    regenerates every endpoint, which takes the allocator lock — and
    a caller holding that lock inside ``allocate()`` is itself
    waiting for an identity watch-mirror event that only the SAME
    single dispatcher thread can deliver.  Inline application
    deadlocks the node; the applier thread breaks the cycle (the
    dispatcher only parses + parks)."""

    # guarded-by: _lock: _applied_rev, _pending

    def __init__(self, kv, daemon):
        self._daemon = daemon
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._applied_rev = 0
        self._pending = None  # newest unapplied (rev, rules)
        self._thread = threading.Thread(target=self._apply_loop,
                                        daemon=True,
                                        name="cluster-policy-sync")
        self._thread.start()
        self._cancel = kv.watch_prefix(POLICY_KEY, self._on_event,
                                       replay=True)

    def _on_event(self, ev) -> None:
        # thread-affinity: any -- kvstore watch dispatcher thread:
        # parse + park ONLY (see class doc)
        if ev.kind == "delete":
            return
        try:
            body = json.loads(ev.value.decode())
            rev = int(body["rev"])
            rules = body["rules"]
        except (ValueError, KeyError, TypeError):
            return  # a malformed publish must not kill the watcher
        with self._lock:
            if rev <= self._applied_rev or (
                    self._pending is not None
                    and rev <= self._pending[0]):
                return
            self._pending = (rev, rules)
        self._wake.set()

    def _apply_loop(self) -> None:
        # thread-affinity: api -- the policy applier is a
        # control-plane thread of its own
        while True:
            self._wake.wait()
            if self._stop.is_set():
                return
            with self._lock:
                pending, self._pending = self._pending, None
                self._wake.clear()
            if pending is None:
                continue
            rev, rules = pending
            try:
                self._daemon.policy_import(rules)
            except Exception:  # noqa: BLE001 — one bad ruleset must
                continue  # not kill the sync plane (rev not applied)
            with self._lock:
                self._applied_rev = max(self._applied_rev, rev)

    @property
    def applied_rev(self) -> int:
        with self._lock:
            return self._applied_rev

    def close(self) -> None:
        self._cancel()
        self._stop.set()
        self._wake.set()
        self._thread.join(5.0)


def publish_policy(kv, rev: int, rules) -> None:
    """Publisher side: write revision ``rev`` of the cluster ruleset
    (every node's :class:`ClusterPolicySync` applies it once)."""
    kv.update(POLICY_KEY,
              json.dumps({"rev": int(rev), "rules": rules}).encode())
