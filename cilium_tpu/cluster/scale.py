"""Live cluster scale-out: grow a SERVING cluster by one replica
with CT continuity, plus the queue-depth autoscale controller.

Reference: production clusters don't only shrink (node death ->
failover, PR 8) — they GROW under load, and upstream's answer is
"add a node, the kvstore converges it, ECMP re-spreads".  A stateful
serving tier must also move connection state for the flows that
re-spread.  ``scale_out`` is the PR 8 failover proof run in REVERSE
(ROADMAP item 3):

1. BUILD the newcomer off to the side (thread replica or spawned
   worker process per ``cluster_mode``) while the cluster keeps
   serving: replay the endpoint journal in registration order (ids
   agree by construction), let the kvstore watch replay converge
   policy + identities, ``daemon.start()``, run the warm-up
   discipline, start its serving session;
2. FREEZE the router (new submits park, bounded) and wait until
   every forward queue and in-flight chunk drains AND every donor's
   own packet ledger catches up — delivered is not verdicted: a row
   can sit in a donor's admission ring past the router quiesce, and
   its CT entry appears only when the drain loop verdicts it.  Only
   then is a CT snapshot complete for every row ever admitted;
3. RE-PIN a fair slot share (``router.add_node``: ⌊slots/new_n⌋
   slots stolen round-robin from the largest owners, table flipped
   atomically) — no other node's flows move;
4. MIGRATE the moved slots' CT: each donor snapshots, the parent
   selects exactly the moved slots' entries
   (``parallel.mesh.ct_rows_slot_ids`` — the same commutative hash
   packets route by, computed from CT key words), and the newcomer
   merges them (snapshot/concat/restore, the failover path).
   Donors keep their residue (flow-affine routing means they never
   see those flows again; aging sweeps it) and NEVER recompile a
   serving executable;
5. RESUME.  The pause window is the blackout analogue and lands in
   the scale-out record; the cluster ledger is untouched (frozen
   submits waited instead of shedding), so it stays EXACT across
   the transition.

``scale_in`` (ISSUE 17 — ROADMAP item 3 residue b) is failover MINUS
the death: freeze, quiesce (with a pipelined data channel that means
"the victim's send window is fully ACKED", not just "queue empty"),
snapshot the victim's CT, re-pin its slots onto the survivors
(``router.remove_node``, fewest-loaded first), ship each moved
slot's CT entries to the slot's NEW owner, retire the worker
cleanly (stop_serving retains its final ledger — the victim stays in
``cluster.nodes`` so the cluster ledger closes over it), resume.
Survivors NEVER recompile a serving executable.

``ClusterAutoscaler`` drives the same path automatically: a named
controller (``infra/controller.py`` — the repo's reconciliation
primitive) samples forward-queue occupancy; ``ticks`` consecutive
samples over ``high_frac`` of ``forward_depth`` trigger one
``add_node()`` (serialized, budget-capped by ``max_nodes``); with
``low_frac`` > 0, ``ticks`` consecutive samples under ``low_frac``
trigger one ``remove_node()`` (floor-capped by ``min_nodes``).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from ..infra.controller import Controller
from ..serving import ServingError

# a newcomer must converge policy/identities within this many
# cluster convergence windows before joining the router
_JOIN_CONVERGENCE_WINDOWS = 3


def scale_out(cluster, timeout: float = 60.0) -> dict:
    """Add one replica to a live serving cluster (see module doc).
    Returns the scale-out record; raises when the cluster is not
    serving or the newcomer cannot converge."""
    from . import ClusterServing  # noqa: F401 — typing/doc anchor

    if cluster.router is None or not cluster._started:
        raise ServingError("scale_out needs a started cluster")
    if cluster._stopped:
        raise ServingError("cluster already stopped")
    with cluster._scale_lock:
        t0 = time.monotonic()
        idx = len(cluster.nodes)
        name = f"{cluster._node_prefix}{idx}"
        node = cluster._build_node(idx, name)
        try:
            if cluster.mode == "process":
                node.wait_ready()
            # replay the endpoint journal in order: same sequence =>
            # same ids as every existing replica
            for ep_name, ips, labels in cluster._endpoints:
                node.add_endpoint(ep_name, ips, labels)
            # policy converges via the kvstore watch replay (the
            # newcomer's ClusterPolicySync replays the newest
            # revision); identities via the allocator watch mirror
            deadline = time.monotonic() + min(
                timeout,
                _JOIN_CONVERGENCE_WINDOWS
                * cluster.convergence_deadline_s)
            while node.applied_policy_rev() < cluster._policy_rev:
                if time.monotonic() > deadline:
                    raise ServingError(
                        f"scale-out node {name} never converged to "
                        f"policy rev {cluster._policy_rev}")
                time.sleep(0.005)
            node.start_node()
            kw = cluster._serving_kwargs or {}
            cluster._warm_nodes(
                [node], kw.get('trace_sample', 0),
                kw.get('ring_capacity', 1 << 15))
            node.start_serving(**(cluster._serving_kwargs or {}))
        except BaseException:
            # a newcomer that failed to join must not leak a worker
            node.shutdown()
            raise
        t_built = time.monotonic()
        r = cluster.router
        # survivors must not pay a recompile for the join: pin their
        # dispatch-compile counts across the migration
        donors_compiles0 = {
            n.name: (n.dispatch_compiles() or {}).get(
                "dispatch_compiles")
            for n in cluster.nodes if n.alive}
        r.freeze()
        t_frozen = time.monotonic()
        joined = False
        try:
            try:
                if not r.wait_quiesced(timeout=timeout):
                    raise ServingError(
                        "scale-out: router never quiesced (a wedged "
                        "node holds the migration hostage)")
                if not _wait_nodes_drained(cluster, timeout):
                    raise ServingError(
                        "scale-out: a donor never verdicted its "
                        "admitted rows (the CT snapshot would miss "
                        "flows still in its admission ring)")
                moved = r.add_node(node)
                joined = True
                node.idx = idx
                cluster.nodes.append(node)
                cluster._by_name[name] = node
                cluster.membership.add_node(node)
                # CT migration: donors -> newcomer, exactly the
                # moved slots' entries
                migrated = _migrate_ct(cluster, node, moved,
                                       r.n_slots)
            finally:
                r.resume()
        except BaseException:
            # the join failed BEFORE the node entered the router: a
            # running-but-unregistered worker would be unreachable
            # by cluster.shutdown() and leak forever (with autoscale
            # on, one per retried hot streak).  Once joined, the
            # node is the cluster's to tear down — never kill a
            # routable replica from an error path
            if not joined:
                node.shutdown()
            raise
        t_done = time.monotonic()
        donors_compiles1 = {
            n.name: (n.dispatch_compiles() or {}).get(
                "dispatch_compiles")
            for n in cluster.nodes[:-1] if n.alive}
        rec = {
            "node": name,
            "nodes-after": len(cluster.nodes),
            "moved-slots": len(moved),
            "ct-migrated-entries": migrated,
            "build-ms": round((t_built - t0) * 1e3, 3),
            "pause-ms": round((t_done - t_frozen) * 1e3, 3),
            "total-ms": round((t_done - t0) * 1e3, 3),
            "survivor-recompiles": sum(
                1 for k, v in donors_compiles1.items()
                if donors_compiles0.get(k) is not None
                and v is not None and v != donors_compiles0[k]),
            "at": time.time(),
        }
        cluster.scale_events.append(rec)
        from ..obs.flightrec import KIND_NODE_SCALEOUT

        node.record_incident(KIND_NODE_SCALEOUT, rec)
        return rec


def _wait_nodes_drained(cluster, timeout: float) -> bool:
    """Inside the frozen window the router queues are empty
    (``wait_quiesced``), but rows it already DELIVERED may still sit
    in a donor's admission ring — CT entries appear only when the
    node's drain loop verdicts them.  Wait until every live node's
    packet ledger catches up (submitted == verdicts + shed +
    recovery_dropped): with the router frozen nothing new arrives,
    so the lag is bounded by the batcher's max-wait plus dispatch."""
    deadline = time.monotonic() + timeout
    while True:
        lagging = False
        for n in cluster.nodes:
            if not n.alive:
                continue
            fe = n.front_end()
            if not fe:
                continue
            ft = fe.get("fault-tolerance", {})
            acc = (fe.get("verdicts", 0) + fe.get("shed", 0)
                   + ft.get("recovery-dropped", 0))
            if fe.get("submitted", 0) > acc:
                lagging = True
                break
        if not lagging:
            return True
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)


def _migrate_ct(cluster, new_node, moved_slots: List[int],
                n_slots: int) -> int:
    """Ship the moved slots' CT entries from their donors to the
    newcomer.  Runs inside the frozen+quiesced window: every entry
    for a moved slot already exists on its donor, and no new one can
    appear until resume."""
    from ..parallel.mesh import ct_rows_slot_ids

    if not moved_slots:
        return 0
    moved = np.asarray(sorted(moved_slots), dtype=np.int64)
    total = 0
    rows_out = []
    for donor in cluster.nodes[:-1]:
        if not donor.alive:
            continue
        rows = donor.snapshot_ct(trigger="scale-out")
        if rows is None or not len(rows):
            continue
        slots = ct_rows_slot_ids(rows, n_slots)
        mask = np.isin(slots, moved)
        if mask.any():
            rows_out.append(np.asarray(rows)[mask])
    if rows_out:
        ship = np.concatenate(rows_out)
        new_node.merge_ct(ship)
        total = int(len(ship))
    return total


def scale_in(cluster, name: Optional[str] = None,
             timeout: float = 60.0) -> dict:
    """Remove one replica from a live serving cluster (see module
    doc).  ``name`` defaults to the highest-index live node (the
    autoscaler's retire order — last in, first out).  Returns the
    scale-in record; raises when fewer than two nodes are live."""
    if cluster.router is None or not cluster._started:
        raise ServingError("scale_in needs a started cluster")
    if cluster._stopped:
        raise ServingError("cluster already stopped")
    with cluster._scale_lock:
        t0 = time.monotonic()
        live = [n for n in cluster.nodes if n.alive]
        if len(live) < 2:
            raise ServingError(
                "scale_in needs at least two live nodes")
        if name is None:
            victim = live[-1]
        else:
            victim = cluster.node(name)
            if not victim.alive:
                raise ServingError(
                    f"scale_in victim {name} is not alive")
        vidx = cluster.nodes.index(victim)
        r = cluster.router
        # survivors must not pay a recompile for the retire: pin
        # their dispatch-compile counts across the migration
        survivors0 = {
            n.name: (n.dispatch_compiles() or {}).get(
                "dispatch_compiles")
            for n in cluster.nodes
            if n.alive and n is not victim}
        r.freeze()
        t_frozen = time.monotonic()
        try:
            # quiesce: every admitted row DELIVERED AND (pipelined
            # channel) ACKED — the victim's send window is empty, so
            # its last cumulative ack covers everything it was sent
            if not r.wait_quiesced(timeout=timeout):
                raise ServingError(
                    "scale-in: router never quiesced (the victim's "
                    "window holds unacked frames)")
            if not _wait_nodes_drained(cluster, timeout):
                raise ServingError(
                    "scale-in: a node never verdicted its admitted "
                    "rows (the CT snapshot would miss flows still "
                    "in its admission ring)")
            # the victim's CT, complete by the quiesce above, BEFORE
            # its slots move (snapshot_ct ships rows to the parent
            # in process mode — the worker is about to retire)
            ct_rows = victim.snapshot_ct(trigger="scale-in")
            moved = r.remove_node(vidx)
            cluster.membership.remove_node(victim.name)
            migrated = _migrate_ct_out(cluster, ct_rows, moved,
                                       r.n_slots, r)
        finally:
            r.resume()
        # retire the worker OUTSIDE the frozen window: the survivors
        # own every slot already; the victim serves nothing.
        # stop_serving retains the final front-end snapshot — the
        # victim stays in cluster.nodes (and _by_name) so the
        # cluster ledger closes over its verdicts
        victim.stop_serving()
        victim.shutdown()
        victim.alive = False
        t_done = time.monotonic()
        survivors1 = {
            n.name: (n.dispatch_compiles() or {}).get(
                "dispatch_compiles")
            for n in cluster.nodes
            if n.alive and n is not victim}
        rec = {
            "kind": "scale-in",
            "node": victim.name,
            "nodes-after": sum(1 for n in cluster.nodes if n.alive),
            "moved-slots": len(moved),
            "ct-migrated-entries": migrated,
            "pause-ms": round((t_done - t_frozen) * 1e3, 3),
            "total-ms": round((t_done - t0) * 1e3, 3),
            "survivor-recompiles": sum(
                1 for k, v in survivors1.items()
                if survivors0.get(k) is not None
                and v is not None and v != survivors0[k]),
            "at": time.time(),
        }
        cluster.scale_events.append(rec)
        from ..obs.flightrec import KIND_NODE_SCALEIN

        survivor = next((n for n in cluster.nodes if n.alive), None)
        if survivor is not None:
            survivor.record_incident(KIND_NODE_SCALEIN, rec)
        return rec


def _migrate_ct_out(cluster, ct_rows, moved_slots: List[int],
                    n_slots: int, router) -> int:
    """Ship the retiring victim's CT entries for the moved slots to
    each slot's NEW owner (the inverse of :func:`_migrate_ct`, which
    fans IN to one newcomer).  Runs inside the frozen+quiesced
    window, after the slot table flipped — the table IS the
    destination map."""
    from ..parallel.mesh import ct_rows_slot_ids

    if ct_rows is None or not len(ct_rows) or not moved_slots:
        return 0
    rows = np.asarray(ct_rows)
    slots = ct_rows_slot_ids(rows, n_slots)
    owner_of = router.snapshot()["slot-owner"]
    total = 0
    for tgt_idx in sorted({owner_of[s] for s in moved_slots}):
        tgt_slots = np.asarray(
            [s for s in moved_slots if owner_of[s] == tgt_idx],
            dtype=np.int64)
        mask = np.isin(slots, tgt_slots)
        if mask.any():
            cluster.nodes[tgt_idx].merge_ct(rows[mask])
            total += int(mask.sum())
    return total


class ClusterAutoscaler:
    """Queue-depth-driven scale-out on the repo's controller infra.

    One named :class:`~cilium_tpu.infra.controller.Controller`
    samples the router's forward queues each ``interval_s``; when
    the fullest queue has been over ``high_frac * forward_depth``
    for ``ticks`` consecutive samples and the cluster is under
    ``max_nodes``, it runs ONE ``add_node()`` (the controller's
    single thread serializes; a failed scale-out backs off on the
    controller's own failure backoff)."""

    # guarded-by: _lock: _streak, _cold_streak, triggered,
    # guarded-by: _lock: triggered_down, last_error

    def __init__(self, cluster, high_frac: float, ticks: int,
                 max_nodes: int, interval_s: float,
                 low_frac: float = 0.0, min_nodes: int = 1):
        self._cluster = cluster
        self.high_frac = float(high_frac)
        self.ticks = int(ticks)
        self.max_nodes = int(max_nodes)
        self.interval_s = float(interval_s)
        # low watermark for scale-IN: `ticks` consecutive samples
        # with EVERY queue under low_frac * forward_depth retire one
        # node (0 disables — the conservative default: shrinking a
        # stateful tier moves CT)
        self.low_frac = float(low_frac)
        self.min_nodes = int(min_nodes)
        self._lock = threading.Lock()
        self._streak = 0
        self._cold_streak = 0
        self.triggered = 0
        self.triggered_down = 0
        self.last_error: Optional[str] = None
        self._controller: Optional[Controller] = None

    def start(self) -> None:
        # thread-affinity: api
        self._controller = Controller(
            "cluster-autoscale", self._tick, self.interval_s)
        self._controller.start()

    def stop(self) -> None:
        # thread-affinity: api
        if self._controller is not None:
            self._controller.stop()
            self._controller = None

    def _tick(self) -> None:
        # thread-affinity: api -- the controller's own thread
        c = self._cluster
        r = c.router
        if r is None or c._stopped:
            return
        snap = r.snapshot()
        depth = max(snap["pending"]) if snap["pending"] else 0
        hot = depth >= self.high_frac * r.forward_depth
        cold = (self.low_frac > 0
                and depth <= self.low_frac * r.forward_depth)
        with self._lock:
            self._streak = self._streak + 1 if hot else 0
            self._cold_streak = self._cold_streak + 1 if cold else 0
            # the budget caps LIVE replicas: a SIGKILLed corpse
            # stays in c.nodes for its retained ledgers but consumes
            # no capacity — counting it would wedge the autoscaler
            # below max_nodes forever after a failover
            alive = sum(1 for n in c.nodes if n.alive)
            fire = (self._streak >= self.ticks
                    and alive < self.max_nodes)
            fire_down = (not fire
                         and self._cold_streak >= self.ticks
                         and alive > self.min_nodes)
            if fire:
                self._streak = 0
                self._cold_streak = 0
                # counted at FIRE time (before the node appears in
                # c.nodes): an observer seeing the new node must
                # also see the trigger that built it
                self.triggered += 1
            elif fire_down:
                self._cold_streak = 0
                self.triggered_down += 1
        if not fire and not fire_down:
            return
        try:
            if fire:
                c.add_node()
            else:
                c.remove_node()
            with self._lock:
                self.last_error = None
        except Exception as e:  # noqa: BLE001 — surfaced in stats +
            # the controller's failure backoff; the next hot/cold
            # streak retries
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"
            raise

    def stats(self) -> dict:
        # thread-affinity: any
        with self._lock:
            return {
                "high-frac": self.high_frac,
                "low-frac": self.low_frac,
                "ticks": self.ticks,
                "max-nodes": self.max_nodes,
                "min-nodes": self.min_nodes,
                "streak": self._streak,
                "cold-streak": self._cold_streak,
                "triggered": self.triggered,
                "triggered-down": self.triggered_down,
                **({"last-error": self.last_error}
                   if self.last_error else {}),
            }
