"""The anomaly model: identity embedding + MLP head (pure jax pytree).

North-star hook: the embedding table's rows are INITIALIZED from each
identity's label set (feature-hashed multi-hot projected to the
embedding dim), i.e. the SelectorCache identity->labels mapping
compiles into the table — label-similar workloads start near each
other before any gradient step.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .features import FEAT_DIM


_FIELDS = ("embed", "w1", "b1", "w2", "b2", "w3", "b3",
           "feat_mean", "feat_prec", "nov_thresh")

# sentinel threshold meaning "novelty stats not fitted" — with
# feat_prec all zeros d2 is identically 0, so the novelty branch
# contributes sigmoid(-huge) ~ 0 and scoring is purely supervised
NOV_DISABLED = 1e9


@jax.tree_util.register_pytree_node_class
@dataclass
class AnomalyModel:
    """Supervised head + benign-novelty detector.

    The supervised MLP learns the labeled attack kinds; the novelty
    half (Mahalanobis distance over flow features, fit on BENIGN
    traffic only — no label leakage) flags deviations from the benign
    manifold, which is what generalizes to attack kinds never seen in
    training (the held-out-kind evaluation)."""

    embed: jnp.ndarray  # [V, D] identity embedding table
    w1: jnp.ndarray  # [D + FEAT_DIM, H]
    b1: jnp.ndarray
    w2: jnp.ndarray  # [H, H]
    b2: jnp.ndarray
    w3: jnp.ndarray  # [H, 1]
    b3: jnp.ndarray
    feat_mean: jnp.ndarray  # [FEAT_DIM] benign feature mean
    feat_prec: jnp.ndarray  # [FEAT_DIM, FEAT_DIM] benign precision
    nov_thresh: jnp.ndarray  # [] benign d2 high quantile

    def tree_flatten(self):
        return (tuple(getattr(self, f) for f in _FIELDS), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def label_embedding_init(labels_by_row: Dict[int, Tuple[str, ...]],
                         n_rows: int, dim: int,
                         seed: int = 7) -> np.ndarray:
    """Identity labels -> embedding rows by feature hashing.

    Each label string hashes to ``dim`` signed buckets; a row is the
    normalized sum over its labels, so identities sharing labels get
    correlated rows (the SelectorCache compilation)."""
    table = np.zeros((n_rows, dim), dtype=np.float32)
    for row, labels in labels_by_row.items():
        if row >= n_rows:
            continue
        v = np.zeros(dim, dtype=np.float32)
        for lab in labels:
            h = hashlib.blake2b(f"{seed}:{lab}".encode(),
                                digest_size=8).digest()
            idx = int.from_bytes(h[:4], "little") % dim
            sign = 1.0 if h[4] & 1 else -1.0
            v[idx] += sign
        norm = np.linalg.norm(v)
        if norm > 0:
            table[row] = v / norm
    return table


def init_params(rng: jax.Array, n_rows: int, dim: int = 32,
                hidden: int = 64,
                labels_by_row: Optional[Dict[int, Tuple[str, ...]]] = None
                ) -> AnomalyModel:
    k1, k2, k3 = jax.random.split(rng, 3)
    if labels_by_row is not None:
        embed = jnp.asarray(label_embedding_init(labels_by_row, n_rows,
                                                 dim))
    else:
        embed = jax.random.normal(k1, (n_rows, dim)) * 0.05
    fan_in = dim + FEAT_DIM
    return AnomalyModel(
        embed=embed.astype(jnp.float32),
        w1=jax.random.normal(k1, (fan_in, hidden)) * (2.0 / fan_in) ** 0.5,
        b1=jnp.zeros(hidden),
        w2=jax.random.normal(k2, (hidden, hidden)) * (2.0 / hidden) ** 0.5,
        b2=jnp.zeros(hidden),
        w3=jax.random.normal(k3, (hidden, 1)) * (2.0 / hidden) ** 0.5,
        b3=jnp.zeros(1),
        feat_mean=jnp.zeros(FEAT_DIM),
        feat_prec=jnp.zeros((FEAT_DIM, FEAT_DIM)),
        nov_thresh=jnp.asarray(NOV_DISABLED, dtype=jnp.float32),
    )


def forward(params: AnomalyModel, id_row: jnp.ndarray,
            feats: jnp.ndarray) -> jnp.ndarray:
    """-> anomaly logits [N].  bfloat16 matmuls on the MXU, float32
    accumulation/output."""
    e = params.embed[id_row]  # [N, D] gather
    x = jnp.concatenate([e, feats], axis=1).astype(jnp.bfloat16)
    h = jax.nn.relu(x @ params.w1.astype(jnp.bfloat16)
                    + params.b1.astype(jnp.bfloat16))
    h = jax.nn.relu(h @ params.w2.astype(jnp.bfloat16)
                    + params.b2.astype(jnp.bfloat16))
    logit = h @ params.w3.astype(jnp.bfloat16) + params.b3.astype(
        jnp.bfloat16)
    return logit[:, 0].astype(jnp.float32)


def bce_loss(params: AnomalyModel, id_row: jnp.ndarray,
             feats: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logits = forward(params, id_row, feats)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def novelty_d2(params: AnomalyModel, feats: jnp.ndarray) -> jnp.ndarray:
    """Mahalanobis distance^2 of each row from the benign manifold."""
    d = feats - params.feat_mean
    return jnp.einsum("nf,fg,ng->n", d, params.feat_prec, d)


def score_packets(params: AnomalyModel, id_row: jnp.ndarray,
                  feats: jnp.ndarray) -> jnp.ndarray:
    """Per-packet anomaly score in [0, 1]: the max of the supervised
    probability and the benign-novelty score (each catches what the
    other misses — the novelty half is what fires on attack kinds
    absent from training)."""
    p = jax.nn.sigmoid(forward(params, id_row, feats))
    d2 = novelty_d2(params, feats)
    scale = params.nov_thresh * 0.25 + 1e-6
    nov = jax.nn.sigmoid((d2 - params.nov_thresh) / scale)
    # unfitted stats (NOV_DISABLED sentinel): the novelty branch must
    # contribute EXACTLY zero, or max() floors every low supervised
    # score at sigmoid(-4) and collapses their ranking
    nov = jnp.where(params.nov_thresh >= NOV_DISABLED, 0.0, nov)
    return jnp.maximum(p, nov)


def fit_novelty(params: AnomalyModel, feats: np.ndarray,
                ridge: float = 1e-3,
                quantile: float = 0.995) -> AnomalyModel:
    """Fit the benign novelty stats from a benign feature sample
    (labels never consulted): mean + ridge-regularized precision +
    the d2 threshold at the given benign quantile."""
    from dataclasses import replace

    x = np.asarray(feats, dtype=np.float64)
    mu = x.mean(axis=0)
    xc = x - mu
    cov = xc.T @ xc / max(len(x) - 1, 1)
    cov += ridge * np.eye(cov.shape[0])
    prec = np.linalg.inv(cov)
    d2 = np.einsum("nf,fg,ng->n", xc, prec, xc)
    thresh = float(np.quantile(d2, quantile))
    return replace(
        params,
        feat_mean=jnp.asarray(mu, dtype=jnp.float32),
        feat_prec=jnp.asarray(prec, dtype=jnp.float32),
        nov_thresh=jnp.asarray(max(thresh, 1e-3), dtype=jnp.float32))


def save_model(path: str, params: AnomalyModel) -> None:
    """Persist to .npz (part of the agent checkpoint family).  The
    feature-schema width rides along so a checkpoint trained before a
    FEAT_DIM bump fails loudly at load, not with an opaque matmul
    shape error at inference."""
    np.savez_compressed(
        path, feat_dim=np.asarray(FEAT_DIM, dtype=np.int32),
        **{k: np.asarray(v) for k, v in zip(
            _FIELDS, params.tree_flatten()[0])})


def load_model(path: str) -> AnomalyModel:
    z = np.load(path)
    # checkpoints before feat_dim stamping: infer from w1's fan-in
    saved_dim = (int(z["feat_dim"]) if "feat_dim" in z.files
                 else int(z["w1"].shape[0] - z["embed"].shape[1]))
    if saved_dim != FEAT_DIM:
        raise ValueError(
            f"anomaly model {path!r} was trained with FEAT_DIM="
            f"{saved_dim}, but this build uses FEAT_DIM={FEAT_DIM}; "
            "retrain required (ml/train.py)")
    kw = {}
    for k in _FIELDS:
        if k in z.files:
            kw[k] = jnp.asarray(z[k])
    # pre-novelty checkpoints: supervised-only scoring
    kw.setdefault("feat_mean", jnp.zeros(FEAT_DIM))
    kw.setdefault("feat_prec", jnp.zeros((FEAT_DIM, FEAT_DIM)))
    kw.setdefault("nov_thresh", jnp.asarray(NOV_DISABLED,
                                            dtype=jnp.float32))
    return AnomalyModel(**kw)
