"""CIC-IDS2017-style anomaly evaluation: labeled pcap -> AUC.

Reference: BASELINE.md's measured metric is "anomaly AUC on CIC-IDS2017
pcap replay vs eBPF drops".  The real dataset cannot ship in-repo, so
this module (a) synthesizes a labeled capture with the same attack
taxonomy (port scans, SYN floods, exfiltration) against benign
steady-state traffic, and (b) evaluates ANY labeled capture of the
same shape: a pcap plus a label sidecar.

Sidecar formats accepted by :func:`load_labels`:
- ``.npz`` — arrays ``labels`` [N] (1=attack), optional ``dir``/``ep``
  per-packet ingest metadata (direction/endpoint are not wire bytes).
- ``.csv`` — CIC-IDS2017 flow-CSV style: columns for the 5-tuple +
  ``Label`` (anything not BENIGN counts as attack); packets match by
  5-tuple.

Run standalone (fresh process, fetch-free hot loop — see bench.py on
why that matters on tunneled TPU hosts):
``python -m cilium_tpu.ml.evaluate`` prints ONE JSON line
``{"metric": "anomaly_auc", ...}``.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP3,
    COL_EP,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
)


def synth_labeled_capture(pcap_path: str, labels_path: str, world,
                          n: int = 65536, seed: int = 1,
                          attack_frac: float = 0.25,
                          kinds=(0, 1, 2)) -> None:
    """Write a labeled pcap + npz sidecar with the synthetic attack mix
    (the in-repo stand-in for CIC-IDS2017).  ``kinds`` selects which
    attack kinds appear (per-kind held-out evaluation)."""
    from ..core.packets import HeaderBatch
    from ..core.pcap import write_pcap
    from .train import synth_labeled_traffic

    rng = np.random.default_rng(seed)
    hdr, labels = synth_labeled_traffic(world, n, rng,
                                        attack_frac=attack_frac,
                                        kinds=kinds)
    write_pcap(pcap_path, HeaderBatch(hdr))
    np.savez_compressed(labels_path, labels=labels,
                        dir=hdr[:, COL_DIR].astype(np.uint8),
                        ep=hdr[:, COL_EP].astype(np.uint16))


def load_labels(path: str, hdr: np.ndarray) -> np.ndarray:
    """Label sidecar -> per-packet labels aligned with ``hdr`` rows.

    Also applies ``dir``/``ep`` ingest metadata from npz sidecars onto
    the header tensor in place (direction is not recoverable from wire
    bytes alone)."""
    if path.endswith(".npz"):
        z = np.load(path)
        labels = np.asarray(z["labels"], dtype=np.float32)
        if len(labels) != len(hdr):
            raise ValueError(
                f"label count {len(labels)} != packet count {len(hdr)}")
        if "dir" in z:
            hdr[:, COL_DIR] = z["dir"]
        if "ep" in z:
            hdr[:, COL_EP] = z["ep"]
        return labels
    # CIC-IDS2017 flow CSV: map 5-tuples to labels
    import csv
    import ipaddress

    flow_label: Dict[tuple, float] = {}
    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        cols = {c.strip().lower(): c for c in reader.fieldnames or ()}

        def col(row, *names):
            for nm in names:
                c = cols.get(nm)
                if c is not None:
                    return row[c].strip()
            raise KeyError(names)

        for row in reader:
            try:
                key = (int(ipaddress.ip_address(
                           col(row, "source ip", "src ip"))),
                       int(ipaddress.ip_address(
                           col(row, "destination ip", "dst ip"))),
                       int(col(row, "source port", "src port")),
                       int(col(row, "destination port", "dst port")),
                       int(col(row, "protocol")))
            except (ValueError, KeyError):
                continue
            lab = col(row, "label").upper()
            flow_label[key] = 0.0 if lab == "BENIGN" else 1.0
    labels = np.zeros(len(hdr), dtype=np.float32)
    for i in range(len(hdr)):
        src, dst = int(hdr[i, COL_SRC_IP3]), int(hdr[i, COL_DST_IP3])
        sp, dp = int(hdr[i, COL_SPORT]), int(hdr[i, COL_DPORT])
        proto = int(hdr[i, COL_PROTO])
        lab = flow_label.get((src, dst, sp, dp, proto))
        if lab is None:
            # CSVs record flows in one direction; reply packets of a
            # bidirectional attack flow must inherit its label, not
            # default to benign
            lab = flow_label.get((dst, src, dp, sp, proto), 0.0)
        labels[i] = lab
    return labels


def score_capture(model, world, hdr: np.ndarray,
                  batch_size: int = 4096, now: int = 50_000
                  ) -> np.ndarray:
    """Replay a header tensor through the real datapath and score every
    packet.  Fetch-free until the single final device->host copy."""
    import jax
    import jax.numpy as jnp

    from ..datapath.verdict import datapath_step
    from .features import flow_features
    from .model import score_packets

    dp_step = jax.jit(datapath_step, donate_argnums=0)

    @jax.jit
    def score(params, hdr_b, out_b):
        id_row, feats = flow_features(hdr_b, out_b)
        return score_packets(params, id_row, feats)

    n = len(hdr)
    pad = (-n) % batch_size
    if pad:
        # pad rows are MASKED via datapath_step's valid argument — a
        # replayed duplicate would mutate conntrack counters/metrics
        # with phantom packets and pollute world.state
        hdr = np.concatenate([hdr, np.repeat(hdr[-1:], pad, axis=0)])
    valid_full = np.ones(len(hdr), dtype=bool)
    if pad:
        valid_full[n:] = False
    state = world.state
    chunks = []
    for i in range(0, len(hdr), batch_size):
        jb = jnp.asarray(hdr[i:i + batch_size])
        vb = jnp.asarray(valid_full[i:i + batch_size])
        out, state = dp_step(state, jb, jnp.uint32(now + i), vb)
        chunks.append(score(model, jb, out))
    world.state = state
    scores = np.asarray(jnp.concatenate(chunks))  # the one fetch
    return scores[:n]


def evaluate_capture(model, world, pcap_path: str,
                     labels_path: str) -> dict:
    """pcap + labels -> {"anomaly_auc": ...} (BASELINE eval config #5)."""
    from ..core.pcap import read_pcap
    from .train import auc

    batch = read_pcap(pcap_path)
    hdr = batch.data
    labels = load_labels(labels_path, hdr)
    scores = score_capture(model, world, hdr)
    return {
        "anomaly_auc": round(float(auc(scores, labels)), 4),
        "packets": int(len(hdr)),
        "attack_packets": int((labels > 0.5).sum()),
    }


def score_scenario(model, world, scenario, ep: int = 0,
                   n_batches: int = 8,
                   threshold: float = 0.8) -> dict:
    """Replay a registered adversarial scenario's deterministic
    traffic (``testing/workloads.py`` — ``syn_flood``,
    ``port_scan``, ...) through the real datapath and score it
    (ISSUE 12 satellite: the r05 anomaly models must SEE the
    scenario engine's synthetic attacks, not just their own training
    generator).  Returns score statistics the tests assert against a
    benign baseline."""
    hdr = np.concatenate(list(
        itertools.islice(scenario.iter_batches(ep), n_batches)))
    scores = score_capture(model, world, hdr)
    return {
        "scenario": scenario.name,
        "packets": int(len(hdr)),
        "mean_score": round(float(scores.mean()), 4),
        "p95_score": round(float(np.percentile(scores, 95)), 4),
        "flagged_frac": round(
            float((scores >= threshold).mean()), 4),
        "scores": scores,
    }


def fit_novelty_from_world(params, world, seed: int = 99,
                           batches: int = 8, batch: int = 4096):
    """Fit the benign-novelty stats: run BENIGN-ONLY traffic (incl.
    the hard-negative patterns) through the datapath and hand the
    features to fit_novelty.  Labels are never consulted — nothing
    about held-out attack kinds can leak in."""
    import jax
    import jax.numpy as jnp

    from ..datapath.verdict import datapath_step
    from .features import flow_features
    from .model import fit_novelty
    from .train import synth_labeled_traffic

    dp_step = jax.jit(datapath_step, donate_argnums=0)
    rng = np.random.default_rng(seed)
    state = world.state
    chunks = []
    for b in range(batches):
        hdr, _ = synth_labeled_traffic(world, batch, rng,
                                       attack_frac=0.0)
        jb = jnp.asarray(hdr)
        out, state = dp_step(state, jb, jnp.uint32(90_000 + b))
        _, feats = flow_features(jb, out)
        chunks.append(feats)
    world.state = state
    benign = np.asarray(jnp.concatenate(chunks))  # one fetch
    return fit_novelty(params, benign)


def train_and_evaluate(n_identities: int = 1024, train_steps: int = 150,
                       train_batch: int = 4096, eval_packets: int = 65536,
                       seed: int = 0, model_out: Optional[str] = None,
                       workdir: Optional[str] = None,
                       holdout_kind: int = 2) -> dict:
    """The full BASELINE config-#5 pipeline, honestly scored.

    Training sees every attack kind EXCEPT ``holdout_kind``; the
    evaluation reports AUC per kind on kind-pure captures (through the
    pcap reader, proving the capture path).  The per-kind number on
    the held-out kind is the generalization result; the same-mix
    number is a smoke test (train and eval draw from the same
    generator) and is labeled as such."""
    import tempfile

    import jax

    from ..testing.fixtures import build_world
    from .model import init_params, save_model
    from .train import ATTACK_KINDS, train

    world = build_world(n_identities=n_identities, n_rules=16,
                        ct_capacity=1 << 18)
    labels_by_row = {
        world.row_map.row(i.numeric_id): tuple(str(l) for l in i.labels)
        for i in world.alloc.all_identities()}
    params = init_params(jax.random.PRNGKey(seed),
                         world.row_map.capacity,
                         labels_by_row=labels_by_row)
    train_kinds = tuple(k for k in ATTACK_KINDS if k != holdout_kind)
    params, losses = train(params, world, steps=train_steps,
                           batch=train_batch, seed=seed,
                           kinds=train_kinds)
    params = fit_novelty_from_world(params, world, seed=seed + 99)
    workdir = workdir or tempfile.mkdtemp(prefix="cilium-anomaly-")

    # per-kind captures: each eval pcap carries ONE attack kind (plus
    # the hard-negative benign mix), so each AUC isolates one kind
    auc_by_kind = {}
    pcap = sidecar = None
    for kind, kname in ATTACK_KINDS.items():
        pcap_k = os.path.join(workdir, f"eval_{kname}.pcap")
        sidecar_k = os.path.join(workdir, f"eval_{kname}.npz")
        per_kind_n = max(eval_packets // len(ATTACK_KINDS), 4096)
        synth_labeled_capture(pcap_k, sidecar_k, world, n=per_kind_n,
                              seed=seed + 1 + kind, kinds=(kind,))
        r = evaluate_capture(params, world, pcap_k, sidecar_k)
        auc_by_kind[kname] = r["anomaly_auc"]
        if kind == holdout_kind:
            pcap, sidecar = pcap_k, sidecar_k

    # the legacy same-mix smoke number (train kinds only)
    pcap_mix = os.path.join(workdir, "eval_mix.pcap")
    sidecar_mix = os.path.join(workdir, "eval_mix.npz")
    synth_labeled_capture(pcap_mix, sidecar_mix, world,
                          n=eval_packets, seed=seed + 17,
                          kinds=train_kinds)
    smoke = evaluate_capture(params, world, pcap_mix, sidecar_mix)

    holdout_name = ATTACK_KINDS[holdout_kind]
    result = {
        # headline = generalization to the UNSEEN attack kind
        "anomaly_auc": auc_by_kind[holdout_name],
        "auc_heldout_kind": auc_by_kind[holdout_name],
        "holdout_kind": holdout_name,
        "auc_by_kind": auc_by_kind,
        "auc_same_mix_smoke": smoke["anomaly_auc"],
        "smoke_note": ("same-mix AUC shares the generator with "
                       "training; it is a smoke test, not a result"),
        "packets": smoke["packets"],
        "attack_packets": smoke["attack_packets"],
        "train_kinds": [ATTACK_KINDS[k] for k in train_kinds],
        "train_steps": train_steps,
        "final_loss": round(losses[-1], 4),
        "eval_pcap": pcap,
    }
    if model_out:
        save_model(model_out, params)
        result["model"] = model_out
    return result


def round_robin_holdouts(**kwargs) -> dict:
    """Train three models, each with one attack kind held out, and
    report every held-out AUC (r03 verdict: one holdout number carried
    the whole generalization claim).  The headline is the MINIMUM —
    the weakest unseen-kind generalization."""
    from .train import ATTACK_KINDS

    per_holdout = {}
    details = {}
    for kind, kname in ATTACK_KINDS.items():
        r = train_and_evaluate(holdout_kind=kind, **kwargs)
        per_holdout[kname] = r["auc_heldout_kind"]
        details[kname] = {
            "auc_by_kind": r["auc_by_kind"],
            "auc_same_mix_smoke": r["auc_same_mix_smoke"],
            "final_loss": r["final_loss"],
        }
    worst = min(per_holdout, key=per_holdout.get)
    return {
        "anomaly_auc": per_holdout[worst],
        "holdout_kind": worst,
        "auc_heldout_by_kind": per_holdout,
        "auc_heldout_mean": round(sum(per_holdout.values())
                                  / len(per_holdout), 4),
        "per_holdout_detail": details,
        "note": ("round-robin holdout: three trainings, each scored on "
                 "the kind it never saw; headline = worst kind"),
    }


def train_on_capture(params, world, hdr: np.ndarray,
                     labels: np.ndarray, epochs: int = 4,
                     batch: int = 4096, lr: float = 3e-3,
                     now: int = 10_000):
    """Supervised training on a REAL labeled capture slice: replay it
    through the datapath in time order (CT state builds up the way it
    did on the wire), one optimizer step per batch, ``epochs`` passes.
    Returns (params with novelty fitted on the slice's BENIGN rows,
    final loss)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..datapath.verdict import datapath_step
    from .features import flow_features
    from .model import fit_novelty
    from .train import make_train_step

    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(optimizer)
    dp_step = jax.jit(datapath_step, donate_argnums=0)
    feat_fn = jax.jit(flow_features)
    state = world.state
    loss = None
    benign_feats = []
    n = (len(hdr) // batch) * batch  # full batches only
    for e in range(epochs):
        for i in range(0, n, batch):
            jb = jnp.asarray(hdr[i:i + batch])
            out, state = dp_step(state, jb,
                                 jnp.uint32(now + e * n + i))
            id_row, feats = feat_fn(jb, out)
            params, opt_state, loss = step_fn(
                params, opt_state, id_row, feats,
                jnp.asarray(labels[i:i + batch]))
            if e == epochs - 1:
                benign_feats.append(feats)
    world.state = state
    feats_h = np.asarray(jnp.concatenate(benign_feats))  # one fetch
    benign = feats_h[labels[:n] < 0.5]
    params = fit_novelty(params, benign)
    return params, float(np.asarray(loss)) if loss is not None else None


def evaluate_real_dataset(pcap_path: str, labels_path: str,
                          local_cidr: str = "192.168.10.0/24",
                          n_identities: int = 256,
                          train_frac: float = 0.7,
                          epochs: int = 4, batch: int = 4096,
                          seed: int = 0) -> dict:
    """BASELINE config #5 on a REAL labeled pcap (CIC-IDS2017 CSV
    schema): the capture replays through the wire parsers
    (core/pcap.py) into header tensors, the first ``train_frac`` of
    packets (time order — never shuffled across the boundary) trains
    the model on the sidecar labels, and the held-out tail is scored.

    ``local_cidr`` supplies the ingest metadata a wire-only capture
    lacks: packets sourced inside it are egress of the monitored
    network (CIC-IDS2017's victim LAN is 192.168.10.0/24)."""
    import ipaddress

    import jax

    from ..core.pcap import read_pcap
    from ..testing.fixtures import build_world
    from .model import init_params
    from .train import auc

    world = build_world(n_identities=n_identities, n_rules=16,
                        ct_capacity=1 << 18)
    hdr = read_pcap(pcap_path).data
    labels = load_labels(labels_path, hdr)
    net = ipaddress.ip_network(local_cidr)
    mask = int(net.netmask)
    base = int(net.network_address)
    src_local = (hdr[:, COL_SRC_IP3] & mask) == base
    dst_local = (hdr[:, COL_DST_IP3] & mask) == base
    hdr[:, COL_DIR] = np.where(src_local & ~dst_local, 1, 0)

    n_train = int(len(hdr) * train_frac)
    params = init_params(jax.random.PRNGKey(seed),
                         world.row_map.capacity)
    params, final_loss = train_on_capture(
        params, world, hdr[:n_train], labels[:n_train],
        epochs=epochs, batch=batch)
    scores = score_capture(params, world, hdr[n_train:],
                           batch_size=batch)
    tail = labels[n_train:]
    return {
        "anomaly_auc": round(float(auc(scores, tail)), 4),
        "source": "real-pcap",
        "pcap": pcap_path,
        "packets": int(len(hdr)),
        "train_packets": int(n_train),
        "eval_packets": int(len(hdr) - n_train),
        "eval_attack_packets": int((tail > 0.5).sum()),
        "final_loss": final_loss,
        "note": ("time-ordered train/eval split through the real "
                 "parsers and datapath; labels from the CIC-schema "
                 "sidecar"),
    }


def _find_real_dataset():
    """File gate for the real-dataset path: env vars first, then the
    conventional data/ location."""
    pcap = os.environ.get("CILIUM_TPU_CIC_PCAP")
    labels = os.environ.get("CILIUM_TPU_CIC_LABELS")
    if pcap and labels and os.path.exists(pcap) \
            and os.path.exists(labels):
        return pcap, labels
    root = os.path.join(os.path.dirname(__file__), "..", "..", "data")
    for ext in (".csv", ".npz"):
        p = os.path.join(root, "cic-ids2017.pcap")
        l = os.path.join(root, "cic-ids2017" + ext)
        if os.path.exists(p) and os.path.exists(l):
            return p, l
    return None, None


def main() -> None:
    pcap, labels = _find_real_dataset()
    if pcap:
        result = evaluate_real_dataset(pcap, labels)
        print(json.dumps({
            "metric": "anomaly_auc",
            "value": result["anomaly_auc"],
            "unit": "auc",
            **{k: v for k, v in result.items()
               if k != "anomaly_auc"},
        }))
        return
    result = round_robin_holdouts()
    print(json.dumps({
        "metric": "anomaly_auc",
        "value": result["anomaly_auc"],
        "unit": "auc",
        "source": ("synthetic fallback (no CIC-IDS2017 on disk; set "
                   "CILIUM_TPU_CIC_PCAP/CILIUM_TPU_CIC_LABELS)"),
        **{k: v for k, v in result.items() if k != "anomaly_auc"},
    }))


if __name__ == "__main__":
    main()
