"""Training: data-parallel over the device mesh + synthetic labeled
traffic (the CIC-IDS2017-style replay stands in; the real dataset is
not shippable in-repo).

The train step runs under ``shard_map``: batch sharded over the
``data`` axis, params replicated, gradients ``psum``-ed — the classic
dp recipe.  Attack patterns synthesized: port scans (one source
sweeping many ports, tiny SYNs), volumetric floods (many sources, one
service), and exfiltration (huge egress transfers) against the benign
steady-state mix from ``testing.fixtures.bench_traffic``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP3,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    N_COLS,
    TCP_ACK,
    TCP_SYN,
)
from .model import AnomalyModel, bce_loss

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P


ATTACK_KINDS = {0: "portscan", 1: "flood", 2: "exfil"}


def synth_labeled_traffic(world, n: int, rng: np.random.Generator,
                          attack_frac: float = 0.25,
                          kinds: Tuple[int, ...] = (0, 1, 2),
                          hard_negatives: bool = True,
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """-> (hdr [n, N_COLS] uint32, labels [n] float32 1=attack).

    ``kinds`` restricts which attack kinds appear (held-out-kind
    evaluation trains on a subset and tests generalization on the
    rest).  ``hard_negatives`` injects BENIGN traffic that resembles
    attacks along single features — reconnect storms (SYN bursts to a
    real service) and bulk transfers (MTU-size pushes on a well-known
    port) — so separability must come from feature conjunctions, not
    one trivial column."""
    import ipaddress

    from ..testing.fixtures import bench_traffic

    hdr = bench_traffic(world, n, rng)
    labels = np.zeros(n, dtype=np.float32)
    n_attack = int(n * attack_frac)
    idx = rng.choice(n, n_attack, replace=False)
    kind_of = rng.choice(np.asarray(kinds, dtype=np.int64), n_attack)
    ips = np.array([int(ipaddress.IPv4Address(ip))
                    for ip in world.pod_ips], dtype=np.uint32)
    scanner = ips[0]
    victim = ips[1]
    for i, kind in zip(idx, kind_of):
        labels[i] = 1.0
        if kind == 0:  # port scan: tiny SYNs sweeping the port space
            hdr[i, COL_SRC_IP3] = rng.choice(ips[:8])  # several scanners
            hdr[i, COL_DPORT] = rng.integers(1, 65535)
            hdr[i, COL_FLAGS] = TCP_SYN
            hdr[i, COL_LEN] = rng.integers(40, 60)
            hdr[i, COL_PROTO] = 6
        elif kind == 1:  # flood: spoofed sources hammering one service
            hdr[i, COL_SRC_IP3] = rng.choice(ips)
            hdr[i, COL_DST_IP3] = victim
            hdr[i, COL_DPORT] = 80
            hdr[i, COL_FLAGS] = TCP_SYN
            hdr[i, COL_LEN] = rng.integers(40, 60)
            hdr[i, COL_PROTO] = 6
        else:  # exfiltration: huge egress pushes to odd ports
            hdr[i, COL_DIR] = 1
            hdr[i, COL_DPORT] = rng.integers(20000, 65000)
            hdr[i, COL_FLAGS] = TCP_ACK | 0x08  # PSH|ACK
            hdr[i, COL_LEN] = rng.integers(1400, 1500)
            hdr[i, COL_PROTO] = 6
    if hard_negatives:
        # benign rows that share single attack features
        benign = np.nonzero(labels == 0)[0]
        n_hard = len(benign) // 5
        hard = rng.choice(benign, n_hard, replace=False)
        half = n_hard // 2
        # reconnect storm: SYNs to a real service port, normal sizes
        storm = hard[:half]
        hdr[storm, COL_DPORT] = 5432
        hdr[storm, COL_FLAGS] = TCP_SYN
        hdr[storm, COL_LEN] = rng.integers(52, 80, len(storm))
        # bulk transfer: MTU-size PSH|ACK egress on a well-known port
        bulk = hard[half:]
        hdr[bulk, COL_DIR] = 1
        hdr[bulk, COL_DPORT] = 443
        hdr[bulk, COL_FLAGS] = TCP_ACK | 0x08
        hdr[bulk, COL_LEN] = rng.integers(1400, 1500, len(bulk))
    return hdr, labels


def make_train_step(optimizer, mesh: Optional[Mesh] = None,
                    axis: str = "data") -> Callable:
    """Build the jitted train step.  With a mesh: dp via shard_map
    (batch sharded, params replicated, grads psum'd)."""

    def _step(params, opt_state, id_row, feats, labels):
        loss, grads = jax.value_and_grad(bce_loss)(params, id_row,
                                                   feats, labels)
        if mesh is not None:
            grads = jax.tree.map(partial(jax.lax.pmean, axis_name=axis),
                                 grads)
            loss = jax.lax.pmean(loss, axis)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(_step)

    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis, None), P(axis)),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(sharded)


def train(params: AnomalyModel, world, steps: int = 200,
          batch: int = 4096, lr: float = 3e-3,
          mesh: Optional[Mesh] = None, seed: int = 0,
          now: int = 1000,
          kinds: Tuple[int, ...] = (0, 1, 2)) -> Tuple[AnomalyModel, list]:
    """Train on synthetic labeled traffic run through the real
    datapath (features include CT state, so the model sees what the
    device sees).  ``kinds`` restricts the attack kinds seen in
    training (held-out-kind evaluation)."""
    from ..datapath.verdict import datapath_step
    from .features import flow_features

    rng = np.random.default_rng(seed)
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(optimizer, mesh)
    dp_step = jax.jit(datapath_step, donate_argnums=0)
    state = world.state
    losses = []
    for s in range(steps):
        hdr, labels = synth_labeled_traffic(world, batch, rng,
                                            kinds=kinds)
        jhdr = jnp.asarray(hdr)
        out, state = dp_step(state, jhdr, jnp.uint32(now + s))
        id_row, feats = flow_features(jhdr, out)
        params, opt_state, loss = step_fn(params, opt_state, id_row,
                                          feats, jnp.asarray(labels))
        losses.append(loss)  # stays on device: the training loop is
        # fetch-free (a per-step float() would sync the tunnel)
    world.state = state
    if losses:
        losses = [float(x) for x in np.asarray(jnp.stack(losses))]
    return params, losses


def auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """ROC AUC by rank statistic (no sklearn dependency)."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels)
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    if len(pos) == 0 or len(neg) == 0:
        return float("nan")
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(len(order), dtype=np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    # average ties
    allscores = np.concatenate([pos, neg])
    sorted_scores = allscores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and \
                sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    r_pos = ranks[:len(pos)].sum()
    n_pos, n_neg = len(pos), len(neg)
    return float((r_pos - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
