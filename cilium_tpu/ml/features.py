"""Flow feature extraction — on-device, straight from the datapath
tensors (no host round trip on the hot path).

Features mirror what CIC-IDS2017-style flow classifiers consume
(packet sizes, flags, ports, direction, CT state) with the remote
identity handled separately as an embedding index (the SelectorCache
-derived table in ``ml.model``).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
)
from ..datapath.verdict import OUT_CT, OUT_ID_ROW, OUT_REASON, OUT_VERDICT

FEAT_DIM = 20


def flow_features(hdr: jnp.ndarray, out: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Header + out tensors -> (id_row [N] int32, feats [N, FEAT_DIM]
    float32 in roughly [0, 1])."""
    hdr = hdr.astype(jnp.uint32)
    proto = hdr[:, COL_PROTO].astype(jnp.float32)
    dport = hdr[:, COL_DPORT].astype(jnp.float32)
    sport = hdr[:, COL_SPORT].astype(jnp.float32)
    length = hdr[:, COL_LEN].astype(jnp.float32)
    flags = hdr[:, COL_FLAGS]
    dirn = hdr[:, COL_DIR].astype(jnp.float32)
    ct = out[:, OUT_CT].astype(jnp.float32)

    def bit(b):
        return ((flags >> b) & 1).astype(jnp.float32)

    feats = jnp.stack([
        (proto == 6).astype(jnp.float32),
        (proto == 17).astype(jnp.float32),
        (proto == 1).astype(jnp.float32) + (proto == 58).astype(
            jnp.float32),
        jnp.log1p(dport) / 12.0,
        jnp.log1p(sport) / 12.0,
        (dport < 1024).astype(jnp.float32),  # well-known port
        jnp.log1p(length) / 12.0,
        (length < 100).astype(jnp.float32),  # tiny packets (scans)
        bit(0),  # FIN
        bit(1),  # SYN
        bit(2),  # RST
        bit(3),  # PSH
        bit(4),  # ACK
        dirn,
        (ct == 0).astype(jnp.float32),  # NEW
        (ct == 1).astype(jnp.float32),  # ESTABLISHED
        (ct == 2).astype(jnp.float32),  # REPLY
        # the POLICY's judgment (BASELINE's metric is anomaly vs eBPF
        # drops): a scan sweeping random ports lands in default-deny,
        # while benign bursts target allowed services — the
        # denied×unusual-port conjunction is what separates held-out
        # portscan traffic from reconnect-storm hard negatives
        (out[:, OUT_VERDICT] == 1).astype(jnp.float32),  # allowed
        (out[:, OUT_REASON] == 2).astype(jnp.float32),  # default-deny
        jnp.ones_like(dirn),  # bias
    ], axis=1)
    return out[:, OUT_ID_ROW].astype(jnp.int32), feats
