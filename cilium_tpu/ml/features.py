"""Flow feature extraction — on-device, straight from the datapath
tensors (no host round trip on the hot path).

Features mirror what CIC-IDS2017-style flow classifiers consume
(packet sizes, flags, ports, direction, CT state) with the remote
identity handled separately as an embedding index (the SelectorCache
-derived table in ``ml.model``).

Rate aggregates (r05): per-packet columns cannot see a flood — one
flood SYN to victim:80 is indistinguishable from a benign SYN — so
the row also carries BATCH aggregates over hashed traffic keys,
computed as segment sums on device (one scatter-add + one gather per
aggregate, fused by XLA):

- (dst, dport, proto) key: how much of this batch converges on one
  service (log count), how SYN-heavy and how NEW-heavy that
  convergence is, and how spread its sources/source-ports are (the
  modal-share proxies below) — the flood signature;
- (src, proto) key: how many NEW SYNs one source emits and how spread
  its destination ports are — the scan signature.

On a sharded mesh each shard aggregates its own rows (documented:
per-shard aggregates approximate the global ones; the batch axis is
the sequence axis of this framework).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP3,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
)
from ..datapath.verdict import OUT_CT, OUT_ID_ROW, OUT_REASON, OUT_VERDICT

FEAT_DIM = 27

_N_BUCKETS = 4096  # hashed segment space for the batch aggregates


def _bucket(*words) -> jnp.ndarray:
    """Fold uint32 words into [0, _N_BUCKETS) segment ids."""
    h = jnp.zeros_like(words[0])
    for i, w in enumerate(words):
        h = (h ^ (w * jnp.uint32(0x9E3779B1 + 2 * i))) * jnp.uint32(
            0x85EBCA77)
    h = h ^ (h >> 15)
    return (h & jnp.uint32(_N_BUCKETS - 1)).astype(jnp.int32)


def _seg_count(key: jnp.ndarray, weight: jnp.ndarray) -> jnp.ndarray:
    """Per-row gather of the per-segment sum of ``weight``."""
    sums = jnp.zeros(_N_BUCKETS, dtype=jnp.float32).at[key].add(weight)
    return sums[key]


def flow_features(hdr: jnp.ndarray, out: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Header + out tensors -> (id_row [N] int32, feats [N, FEAT_DIM]
    float32 in roughly [0, 1])."""
    hdr = hdr.astype(jnp.uint32)
    proto = hdr[:, COL_PROTO].astype(jnp.float32)
    dport = hdr[:, COL_DPORT].astype(jnp.float32)
    sport = hdr[:, COL_SPORT].astype(jnp.float32)
    length = hdr[:, COL_LEN].astype(jnp.float32)
    flags = hdr[:, COL_FLAGS]
    dirn = hdr[:, COL_DIR].astype(jnp.float32)
    ct = out[:, OUT_CT].astype(jnp.float32)

    def bit(b):
        return ((flags >> b) & 1).astype(jnp.float32)

    syn = bit(1)
    is_new = (ct == 0).astype(jnp.float32)

    # -- batch rate aggregates (see module doc) -----------------------
    one = jnp.ones_like(proto)
    svc = _bucket(hdr[:, COL_DST_IP3], hdr[:, COL_DPORT],
                  hdr[:, COL_PROTO])
    svc_n = _seg_count(svc, one)
    svc_syn = _seg_count(svc, syn) / svc_n
    svc_new = _seg_count(svc, is_new) / svc_n
    # modal-share proxies for spread: a sub-key's share of its service
    # key is ~1 for one heavy client and ~1/k under k-way spread —
    # spoofed-source floods push BOTH toward 0
    src_share = _seg_count(
        _bucket(hdr[:, COL_DST_IP3], hdr[:, COL_DPORT],
                hdr[:, COL_PROTO], hdr[:, COL_SRC_IP3]), one) / svc_n
    sport_share = _seg_count(
        _bucket(hdr[:, COL_DST_IP3], hdr[:, COL_DPORT],
                hdr[:, COL_PROTO], hdr[:, COL_SPORT]), one) / svc_n
    scan = _bucket(hdr[:, COL_SRC_IP3], hdr[:, COL_PROTO])
    scan_newsyn = _seg_count(scan, syn * is_new)
    dport_share = _seg_count(
        _bucket(hdr[:, COL_SRC_IP3], hdr[:, COL_PROTO],
                hdr[:, COL_DPORT]), one) / jnp.maximum(
        _seg_count(scan, one), 1.0)

    feats = jnp.stack([
        (proto == 6).astype(jnp.float32),
        (proto == 17).astype(jnp.float32),
        (proto == 1).astype(jnp.float32) + (proto == 58).astype(
            jnp.float32),
        jnp.log1p(dport) / 12.0,
        jnp.log1p(sport) / 12.0,
        (dport < 1024).astype(jnp.float32),  # well-known port
        jnp.log1p(length) / 12.0,
        (length < 100).astype(jnp.float32),  # tiny packets (scans)
        bit(0),  # FIN
        syn,  # SYN
        bit(2),  # RST
        bit(3),  # PSH
        bit(4),  # ACK
        dirn,
        is_new,  # NEW
        (ct == 1).astype(jnp.float32),  # ESTABLISHED
        (ct == 2).astype(jnp.float32),  # REPLY
        # the POLICY's judgment (BASELINE's metric is anomaly vs eBPF
        # drops): a scan sweeping random ports lands in default-deny,
        # while benign bursts target allowed services — the
        # denied×unusual-port conjunction is what separates held-out
        # portscan traffic from reconnect-storm hard negatives
        (out[:, OUT_VERDICT] == 1).astype(jnp.float32),  # allowed
        (out[:, OUT_REASON] == 2).astype(jnp.float32),  # default-deny
        # rate aggregates (r05, flood/scan signatures)
        jnp.log1p(svc_n) / 12.0,
        svc_syn,
        svc_new,
        src_share,
        sport_share,
        jnp.log1p(scan_newsyn) / 12.0,
        dport_share,
        jnp.ones_like(dirn),  # bias
    ], axis=1)
    return out[:, OUT_ID_ROW].astype(jnp.int32), feats
