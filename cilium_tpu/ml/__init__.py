"""Learned flow classification: the anomaly side of the north-star.

BASELINE.md: "policy evaluation is a learned + rule-encoded classifier
... pkg/policy's SelectorCache and identity->label mapping compile into
the model's embedding table; verdicts and anomaly scores flow back via
pkg/monitor."  The rule-encoded half is the dense verdict tensor
(authoritative — packets drop only on rule verdicts); this package is
the learned half: an identity-embedding + MLP anomaly scorer over
datapath flow features, trained data-parallel over the device mesh.
The anomaly score is ADVISORY (never overrides a rule allow), keeping
the <=1% divergence gate intact by construction.
"""

from .features import FEAT_DIM, flow_features  # noqa: F401
from .model import (  # noqa: F401
    AnomalyModel,
    forward,
    init_params,
    label_embedding_init,
    fit_novelty,
    load_model,
    novelty_d2,
    save_model,
    score_packets,
)
from .train import auc, make_train_step, synth_labeled_traffic, train  # noqa: F401
from .scorer import AnomalyScorer  # noqa: F401
