"""AnomalyScorer: the learned path wired into the monitor plane.

North-star: "verdicts and anomaly scores flow back via pkg/monitor."
The scorer consumes EventBatches (a MonitorAgent consumer), scores
them with the trained model, and keeps rolling statistics + the most
anomalous recent flows.  Scores are ADVISORY: they never mutate
verdicts (rule verdicts stay authoritative, preserving the divergence
gate); operators read them via /anomaly or `cilium-tpu anomaly`.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from ..monitor.api import EventBatch
from .model import AnomalyModel, score_packets


class AnomalyScorer:
    def __init__(self, params: AnomalyModel, row_of_identity,
                 threshold: float = 0.8, top_k: int = 64):
        """``row_of_identity``: numeric identity -> embedding row
        (IdentityRowMap.row)."""
        import jax

        self.params = params
        self.row_of_identity = row_of_identity
        self.threshold = threshold
        self.top_k = top_k
        self._score = jax.jit(score_packets)
        self._lock = threading.Lock()
        self.scored = 0
        self.flagged = 0
        self._score_sum = 0.0
        self._top: List[Tuple[float, dict]] = []

    def consume(self, batch: EventBatch) -> np.ndarray:
        """Score a batch; returns sigmoid scores [N]."""
        import jax.numpy as jnp

        from ..monitor.api import materialize
        from .features import flow_features

        if len(batch) == 0:
            return np.zeros(0, dtype=np.float32)
        # rebuild the device inputs from the SoA batch
        out_cols = np.stack([
            batch.verdict.astype(np.uint32),
            batch.proxy_port.astype(np.uint32),
            batch.ct_state.astype(np.uint32),
            np.asarray([self.row_of_identity(int(i))
                        for i in batch.identity], dtype=np.uint32),
            batch.reason.astype(np.uint32),
            batch.msg_type.astype(np.uint32),
        ], axis=1)
        id_row, feats = flow_features(jnp.asarray(batch.hdr),
                                      jnp.asarray(out_cols))
        scores = np.asarray(self._score(self.params, id_row, feats))
        hot = np.nonzero(scores >= self.threshold)[0]
        with self._lock:
            self.scored += len(scores)
            self.flagged += len(hot)
            self._score_sum += float(scores.sum())
            for i in hot[:32]:
                ev = materialize(batch, int(i))
                self._top.append((float(scores[i]), {
                    "score": round(float(scores[i]), 4),
                    "src": f"{ev.src_ip}:{ev.sport}",
                    "dst": f"{ev.dst_ip}:{ev.dport}",
                    "proto": ev.proto,
                    "identity": ev.identity,
                    "time": ev.timestamp,
                }))
            self._top.sort(key=lambda t: -t[0])
            del self._top[self.top_k:]
        return scores

    def stats(self) -> dict:
        with self._lock:
            return {
                "scored": self.scored,
                "flagged": self.flagged,
                "threshold": self.threshold,
                "mean-score": round(self._score_sum / self.scored, 4)
                if self.scored else 0.0,
                "top": [rec for _, rec in self._top[:10]],
            }
