"""ClusterMesh: multi-cluster identity/ipcache federation.

Reference: upstream ``pkg/clustermesh`` — the agent opens a watch into
EVERY remote cluster's etcd (via clustermesh-apiserver) and mirrors
remote nodes, identities, and endpoints locally, so policies can
select peers cluster-wide.  TPU-first mapping: each remote cluster is
another kvstore handle; remote identities replay through the local
allocator (namespaced into a per-cluster numeric range so clusters'
id spaces cannot collide) and remote endpoint IPs upsert the ipcache
— both landing as the same incremental tensor patches local churn
uses.  DCN is the transport the stores ride in a real deployment; the
mesh logic is transport-agnostic.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..kvstore.allocator import DEFAULT_PREFIX
from ..kvstore.store import InMemoryKVStore, KVEvent
from ..labels import Label, LabelSet

# Remote identities are remapped into per-cluster ranges so two
# clusters' numeric spaces never collide locally (reference: clustermesh
# requires disjoint identity ranges / uses cluster-id bits 16-23).
CLUSTER_ID_SHIFT = 16
MAX_CLUSTER_ID = 255


class RemoteCluster:
    """One remote cluster's watches (identities + ipcache)."""

    def __init__(self, name: str, cluster_id: int, kv: InMemoryKVStore,
                 allocator, upsert_ipcache: Callable[[str, int], None],
                 delete_ipcache: Callable[[str], None]):
        if not 1 <= cluster_id <= MAX_CLUSTER_ID:
            raise ValueError(f"cluster id {cluster_id} out of range")
        self.name = name
        self.cluster_id = cluster_id
        self._allocator = allocator
        self._upsert = upsert_ipcache
        self._delete = delete_ipcache
        self._lock = threading.Lock()
        self._ip_identity: Dict[str, int] = {}
        self._cancels = [
            kv.watch_prefix(f"{DEFAULT_PREFIX}/id/", self._on_identity),
            kv.watch_prefix("cilium/state/ip/v1/", self._on_ip),
        ]

    def _remap(self, remote_numeric: int) -> int:
        return (self.cluster_id << CLUSTER_ID_SHIFT) | (
            remote_numeric & ((1 << CLUSTER_ID_SHIFT) - 1))

    def _on_identity(self, ev: KVEvent) -> None:
        if ev.kind == "delete":
            return  # remote GC; local refcounts drive removal
        remote_num = int(ev.key.rsplit("/", 1)[1])
        local_num = self._remap(remote_num)
        if self._allocator.lookup_by_id(local_num) is not None:
            return
        labels = LabelSet(
            list(LabelSet.parse(
                *[s for s in ev.value.decode().split(";") if s]).labels)
            + [Label("k8s", "io.cilium.k8s.policy.cluster",
                     self.name)])
        self._allocator.restore_identity(local_num, labels)

    def _on_ip(self, ev: KVEvent) -> None:
        """Remote endpoint IP -> identity mapping (the ipcache shared
        store: ``cilium/state/ip/v1/<ip>`` -> remote numeric id)."""
        ip = ev.key.rsplit("/", 1)[1]
        suffix = "/128" if ":" in ip else "/32"
        if ev.kind == "delete":
            with self._lock:
                self._ip_identity.pop(ip, None)
            self._delete(ip + suffix)
            return
        local_num = self._remap(int(ev.value))
        with self._lock:
            self._ip_identity[ip] = local_num
        self._upsert(ip + suffix, local_num)

    def num_mirrored(self) -> int:
        with self._lock:
            return len(self._ip_identity)

    def close(self) -> None:
        for c in self._cancels:
            c()


class ClusterMesh:
    """The local end: one RemoteCluster per peer (pkg/clustermesh)."""

    def __init__(self, allocator, upsert_ipcache, delete_ipcache):
        self._allocator = allocator
        self._upsert = upsert_ipcache
        self._delete = delete_ipcache
        self._remotes: Dict[str, RemoteCluster] = {}

    def connect(self, name: str, cluster_id: int,
                kv: InMemoryKVStore) -> RemoteCluster:
        if name in self._remotes:
            raise ValueError(f"cluster {name!r} already connected")
        rc = RemoteCluster(name, cluster_id, kv, self._allocator,
                           self._upsert, self._delete)
        self._remotes[name] = rc
        return rc

    def disconnect(self, name: str) -> bool:
        rc = self._remotes.pop(name, None)
        if rc is None:
            return False
        rc.close()
        return True

    def status(self) -> List[dict]:
        return [{"name": rc.name, "cluster-id": rc.cluster_id,
                 "ips-mirrored": rc.num_mirrored()}
                for rc in self._remotes.values()]


def publish_endpoint_ip(kv: InMemoryKVStore, ip: str,
                        numeric_id: int) -> None:
    """Agent side of the ipcache shared store: announce a local
    endpoint's IP -> identity for remote clusters to mirror."""
    kv.update(f"cilium/state/ip/v1/{ip}", str(numeric_id).encode())


def withdraw_endpoint_ip(kv: InMemoryKVStore, ip: str) -> None:
    kv.delete(f"cilium/state/ip/v1/{ip}")
