"""API client over the unix socket (the CLI's transport).

Reference: upstream cilium ``api/v1/client`` (go-swagger generated)
talking to ``/var/run/cilium/cilium.sock``.
"""

from __future__ import annotations

import http.client
import json
import socket
from typing import Optional

DEFAULT_SOCKET = "/tmp/cilium-tpu/cilium.sock"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 30.0):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        try:
            s.connect(self._socket_path)
        except OSError as e:  # missing socket == agent down
            raise ConnectionRefusedError(
                f"no agent on {self._socket_path}: {e}") from e
        self.sock = s


class APIError(Exception):
    def __init__(self, status: int, body):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class APIClient:
    def __init__(self, socket_path: str = DEFAULT_SOCKET):
        self.socket_path = socket_path

    def _request(self, method: str, path: str, body=None):
        conn = _UnixHTTPConnection(self.socket_path)
        try:
            payload = json.dumps(body) if body is not None else None
            headers = {"Content-Type": "application/json"} if payload \
                else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            data = (json.loads(raw) if "json" in ctype
                    else raw.decode())
            if resp.status >= 400:
                raise APIError(resp.status, data)
            return data
        finally:
            conn.close()

    # typed verbs (mirroring api/v1 client surface)
    def healthz(self):
        return self._request("GET", "/healthz")

    def config(self):
        return self._request("GET", "/config")

    def policy_get(self):
        return self._request("GET", "/policy")

    def policy_put(self, rules):
        return self._request("PUT", "/policy", rules)

    def policy_delete(self, labels):
        return self._request("DELETE", "/policy", {"labels": labels})

    def endpoint_list(self):
        return self._request("GET", "/endpoint")

    def endpoint_get(self, ep_id: int):
        return self._request("GET", f"/endpoint/{ep_id}")

    def endpoint_create(self, name: str, ips, labels):
        return self._request("PUT", f"/endpoint/{name}",
                             {"name": name, "ips": list(ips),
                              "labels": list(labels)})

    def endpoint_delete(self, ep_id: int):
        return self._request("DELETE", f"/endpoint/{ep_id}")

    def identity_list(self):
        return self._request("GET", "/identity")

    def map_list(self):
        return self._request("GET", "/map")

    def egress_list(self):
        return self._request("GET", "/egress")

    def map_get(self, name: str):
        return self._request("GET", f"/map/{name}")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def flows(self, **params):
        q = "&".join(f"{k}={v}" for k, v in params.items()
                     if v is not None)
        return self._request("GET", f"/flows{'?' + q if q else ''}")

    def debuginfo(self):
        return self._request("GET", "/debuginfo")

    def config_patch(self, options: dict):
        return self._request("PATCH", "/config", options)

    def service_list(self):
        return self._request("GET", "/service")

    def service_upsert(self, name: str, frontend: str, backends,
                       protocol: int = 6):
        return self._request("PUT", f"/service/{name}",
                             {"frontend": frontend,
                              "backends": list(backends),
                              "protocol": protocol})

    def service_delete(self, name: str):
        return self._request("DELETE", f"/service/{name}")

    def fqdn_cache(self):
        return self._request("GET", "/fqdn/cache")

    def cluster_status(self):
        return self._request("GET", "/cluster/status")

    def cluster_scale(self, down: bool = False,
                      node: "Optional[str]" = None):
        """Live scale-out/in (PUT /cluster/scale): add one replica,
        or with ``down`` retire one (``node`` picks the victim;
        default the highest-index live node).  Returns the scale
        record."""
        body = None
        if down:
            body = {"down": True}
            if node is not None:
                body["node"] = node
        return self._request("PUT", "/cluster/scale", body)

    def cluster_rotate(self, grace_s: "Optional[float]" = None):
        """Cluster-wide key-epoch rotation (PUT /cluster/rotate,
        ISSUE 18): re-key every live encrypted channel under the
        grace window, serving uninterrupted.  Returns the rotation
        record (epoch, per-node acks, wall ms)."""
        body = ({"grace-s": float(grace_s)}
                if grace_s is not None else None)
        return self._request("PUT", "/cluster/rotate", body)

    # -- the cluster observability relay (ISSUE 14) --------------------
    def cluster_metrics(self) -> str:
        """One exposition text, every series node-labelled."""
        return self._request("GET", "/cluster/metrics")

    def cluster_flows(self, **params):
        q = "&".join(f"{k}={v}" for k, v in params.items()
                     if v is not None)
        return self._request(
            "GET", f"/cluster/flows{'?' + q if q else ''}")

    def cluster_top(self, top: int = 16):
        return self._request("GET", f"/cluster/top?top={top}")

    def cluster_trace(self, limit: int = 32):
        return self._request("GET",
                             f"/cluster/trace?limit={limit}")

    def cluster_sysdump(self):
        return self._request("GET", "/cluster/sysdump")

    def cluster_health(self):
        return self._request("GET", "/cluster/health")

    def proxy_listeners(self):
        return self._request("GET", "/proxy")

    def proxy_stats(self):
        return self._request("GET", "/proxy/stats")

    def serving_stats(self):
        return self._request("GET", "/serving")

    def debug_traces(self, limit: int = 64):
        return self._request("GET", f"/debug/traces?limit={limit}")

    def flows_aggregate(self, top: int = 16):
        return self._request("GET", f"/flows/aggregate?top={top}")

    def sysdump(self, trigger: bool = False):
        return self._request(
            "GET", "/debug/sysdump" + ("?trigger=1" if trigger
                                       else ""))

    def metrics_inventory(self):
        return self._request("GET", "/metrics/inventory")

    def metrics_history(self, series=None, since: float = 0.0):
        """Windowed in-process metrics history (ISSUE 19): fast +
        slow downsample tiers for the declared series subset."""
        q = []
        if series:
            q.append("series=" + ",".join(series))
        if since:
            q.append(f"since={since}")
        return self._request(
            "GET",
            "/metrics/history" + ("?" + "&".join(q) if q else ""))

    def slo(self):
        """This node's SLO verdict + per-SLO burn evaluations."""
        return self._request("GET", "/slo")

    def cluster_slo(self):
        """Merged node-labeled cluster health verdict."""
        return self._request("GET", "/cluster/slo")

    def xds_status(self):
        return self._request("GET", "/xds")
