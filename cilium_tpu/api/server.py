"""REST API server on a unix socket (cilium.sock analogue).

Reference: upstream cilium ``api/v1`` REST API + the daemon handlers
in ``daemon/cmd`` (``GET/PUT /policy``, ``GET /endpoint``, ...).
Implemented with the stdlib http machinery over ``AF_UNIX``.
"""

from __future__ import annotations

import json
import os
import re
import socketserver
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..agent.daemon import Daemon
from ..flow import FlowFilter


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


# jax's profiler is process-global state; one capture at a time
_PROFILE_LOCK = threading.Lock()

_NO_CLUSTER = ("not part of a cluster serving tier "
               "(start_cluster_serving)")


class APIServer:
    def __init__(self, daemon: Daemon, socket_path: str):
        self.daemon = daemon
        self.socket_path = socket_path
        self._server: Optional[_UnixHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        handler = _make_handler(self.daemon)
        self._server = _UnixHTTPServer(self.socket_path, handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="api-server")
        self._thread.start()

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)


def _make_handler(daemon: Daemon):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # silence per-request stderr logging
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def _send(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            if n == 0:
                return None
            return json.loads(self.rfile.read(n))

        def do_GET(self) -> None:  # noqa: N802
            url = urllib.parse.urlparse(self.path)
            q = urllib.parse.parse_qs(url.query)
            path = url.path.rstrip("/") or "/"
            try:
                if path == "/healthz":
                    self._send(200, daemon.status())
                elif path == "/config":
                    cfg = daemon.config
                    self._send(200, {
                        "node-name": cfg.node_name,
                        "backend": cfg.backend,
                        "ct-capacity": cfg.ct_capacity,
                        "ct-gc-interval": cfg.ct_gc_interval,
                        "flow-ring-capacity": cfg.flow_ring_capacity,
                        "enable-hubble": cfg.enable_hubble,
                    })
                elif path == "/policy":
                    self._send(200, daemon.policy_get())
                elif path == "/endpoint":
                    self._send(200, [ep.to_dict()
                                     for ep in daemon.endpoints.list()])
                elif m := re.fullmatch(r"/endpoint/(\d+)", path):
                    ep = daemon.endpoints.get(int(m.group(1)))
                    if ep is None:
                        self._send(404, {"error": "endpoint not found"})
                    else:
                        self._send(200, ep.to_dict())
                elif path == "/identity":
                    self._send(200, [
                        {"id": i.numeric_id,
                         "labels": [str(l) for l in i.labels]}
                        for i in daemon.allocator.all_identities()])
                elif m := re.fullmatch(r"/identity/(\d+)", path):
                    ident = daemon.allocator.lookup_by_id(int(m.group(1)))
                    if ident is None:
                        self._send(404, {"error": "identity not found"})
                    else:
                        self._send(200, {
                            "id": ident.numeric_id,
                            "labels": [str(l) for l in ident.labels]})
                elif path == "/map":
                    self._send(200, _map_list(daemon))
                elif path == "/map/ipcache":
                    self._send(200, [
                        {"cidr": e.cidr, "identity": e.identity,
                         "source": e.source}
                        for e in daemon.ipcache.entries()])
                elif path == "/map/ct":
                    from ..datapath.conntrack import \
                        ct_entries_from_snapshot

                    limit = int(q.get("limit", ["1000"])[0])
                    self._send(200, ct_entries_from_snapshot(
                        daemon.loader.ct_snapshot(), limit))
                elif path == "/map/lb":
                    limit = int(q.get("limit", ["1000"])[0])
                    self._send(200, daemon.socklb_entries(limit))
                elif path == "/map/auth":
                    self._send(200, daemon.loader.auth_entries())
                elif path == "/egress":
                    # expanded egress-gateway rules (cilium egress
                    # list): one row per (pod IP, destCIDR, egress IP)
                    self._send(200, [
                        {"source": s, "destination": c,
                         "egress-ip": e}
                        for s, c, e in daemon._egress_rules()])
                elif path == "/map/nat":
                    from ..service.nat import nat_entries_from_snapshot

                    snap = daemon.loader.nat_snapshot()
                    if snap is None:
                        self._send(200, [])
                    else:
                        limit = int(q.get("limit", ["1000"])[0])
                        self._send(200, nat_entries_from_snapshot(
                            snap, limit))
                elif m := re.fullmatch(r"/map/policy/(\d+)", path):
                    self._send(200, _policy_map(daemon, int(m.group(1))))
                elif path == "/metrics":
                    self._send_text(200, _metrics_text(daemon))
                elif path == "/metrics/inventory":
                    # the registry's self-description: every series
                    # /metrics can serve, with type + help (the
                    # README metric-inventory table's source)
                    self._send(200, daemon.registry.inventory())
                elif path == "/metrics/history":
                    # the SLO plane's retained series rings
                    # (?series=a,b&since=epoch; `cilium-tpu history`
                    # reads this)
                    series = [s for s in
                              q.get("series", [""])[0].split(",")
                              if s] or None
                    since = float(q.get("since", ["0"])[0])
                    self._send(200, daemon.history_snapshot(
                        series=series, since=since))
                elif path == "/slo":
                    # the SLO plane's verdict + per-objective burn
                    # evaluation (`cilium-tpu slo` reads this)
                    self._send(200, daemon.slo_snapshot())
                elif path == "/debug/traces":
                    # the sampled span plane + compile-event log
                    # (cilium-tpu trace reads this)
                    limit = int(q.get("limit", ["64"])[0])
                    self._send(200, daemon.debug_traces(limit=limit))
                elif path == "/flows":
                    self._send(200, _flows(daemon, q))
                elif path == "/flows/aggregate":
                    # the flow analytics plane: windowed per-identity
                    # aggregates, verdict matrix, top-K talkers,
                    # spike state (`cilium-tpu top` reads this)
                    top = int(q.get("top", ["16"])[0])
                    self._send(200, daemon.flows_aggregate(top=top))
                elif path == "/debug/sysdump":
                    # the incident flight recorder: list bundles +
                    # incident history; ?trigger=1 captures a manual
                    # bundle first (bypasses the auto rate limit)
                    if q.get("trigger", ["0"])[0] in ("1", "true"):
                        out = daemon.sysdump_now()
                        if out["written"] is None and \
                                not out["enabled"]:
                            self._send(400, {
                                "error": "sysdump disabled: run the "
                                "agent with --sysdump-dir"})
                            return
                        self._send(200, out)
                    else:
                        self._send(200, {
                            "enabled": daemon.flightrec.enabled,
                            "bundles": daemon.flightrec.list_bundles(),
                            "incidents": daemon.flightrec.incidents(),
                            "stats": daemon.flightrec.stats()})
                elif path == "/proxy":
                    # redirect listeners + their L7 rule shapes (the
                    # xDS NetworkPolicy view; reference: pkg/envoy)
                    self._send(200, daemon.proxy.listeners())
                elif path == "/proxy/stats":
                    # the L7 plane's ledger + per-plugin parse
                    # percentiles (ISSUE 16)
                    self._send(200, daemon.proxy_stats())
                elif path == "/xds":
                    # the SotW push-surface status an external proxy
                    # subscribes to (proxy/xds.py); snapshot() instead
                    # of discover() — the long-poll would hang forever
                    # on a fresh daemon at version 0
                    self._send(200, daemon.xds.snapshot())
                elif path == "/service":
                    self._send(200, [s.to_dict()
                                     for s in daemon.services.list()])
                elif path == "/fqdn/cache":
                    self._send(200, daemon.fqdn.entries())
                elif path == "/cluster/health":
                    if daemon.health is None:
                        self._send(404, {"error": "no cluster (run "
                                         "with a shared kvstore)"})
                    else:
                        self._send(200, daemon.health.to_dict())
                elif path == "/cluster/status":
                    # the clustermesh serving tier (one answer from
                    # any member node's socket)
                    if daemon._cluster is None:
                        self._send(404, {
                            "error": "not part of a cluster serving "
                                     "tier (start_cluster_serving)"})
                    else:
                        self._send(200, daemon._cluster.status())
                elif path == "/cluster/metrics":
                    # the cluster observability relay (ISSUE 14): one
                    # exposition, every series node-labelled, relay
                    # scrape meta-series appended
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        self._send_text(
                            200, daemon._cluster.obs.cluster_metrics())
                elif path == "/cluster/flows":
                    # merged time-ordered flows from every node
                    # (hubble-relay parity; each dict carries
                    # node_name)
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        n = int(q.get("number", ["100"])[0])
                        oldest = q.get("oldest_first",
                                       ["0"])[0] in ("1", "true")
                        self._send(200,
                                   daemon._cluster.obs.cluster_flows(
                                       number=n, oldest_first=oldest))
                elif path == "/cluster/top":
                    # analytics top-K merged across nodes
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        top = int(q.get("top", ["16"])[0])
                        self._send(200,
                                   daemon._cluster.obs.cluster_top(
                                       top=top))
                elif path == "/cluster/trace":
                    # stitched cross-process spans + per-node tracer
                    # summaries
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        limit = int(q.get("limit", ["32"])[0])
                        self._send(200,
                                   daemon._cluster.obs.cluster_trace(
                                       limit=limit))
                elif path == "/cluster/sysdump":
                    # the cluster sysdump archive: every worker
                    # bundle + the parent bundle + a manifest
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        try:
                            self._send(
                                200,
                                daemon._cluster.cluster_sysdump())
                        except Exception as e:
                            self._send(400, {"error": str(e)})
                elif path == "/cluster/slo":
                    # the relay's merged cluster health verdict:
                    # worst-of over per-node SLO verdicts,
                    # node-labeled (`cilium-tpu cluster slo`)
                    if daemon._cluster is None:
                        self._send(404, {"error": _NO_CLUSTER})
                    else:
                        self._send(
                            200, daemon._cluster.obs.cluster_slo())
                elif path == "/serving":
                    # serving front-end telemetry (queue wait, pad
                    # efficiency, verdicts/sec, latency percentiles)
                    self._send(200, daemon.serving_stats())
                elif path == "/anomaly":
                    if daemon.anomaly is None:
                        self._send(404, {"error": "anomaly scoring "
                                         "not enabled"})
                    else:
                        self._send(200, daemon.anomaly.stats())
                elif path == "/debug/profile":
                    # the pprof-endpoint analogue: capture an XLA/jax
                    # profiler trace (viewable in TensorBoard/Perfetto).
                    # The jax profiler is process-global and cannot
                    # nest; overlapping requests get 409 busy.
                    import tempfile

                    import jax

                    if not _PROFILE_LOCK.acquire(blocking=False):
                        self._send(409, {"error": "a profile capture "
                                         "is already in progress"})
                        return
                    try:
                        seconds = float(q.get("seconds", ["1.0"])[0])
                        seconds = min(max(seconds, 0.1), 30.0)
                        out_dir = q.get("dir", [None])[0] or \
                            tempfile.mkdtemp(prefix="cilium-profile-")
                        import time as _t

                        with jax.profiler.trace(out_dir):
                            _t.sleep(seconds)
                    finally:
                        _PROFILE_LOCK.release()
                    self._send(200, {"trace-dir": out_dir,
                                     "seconds": seconds})
                elif path == "/debuginfo":
                    self._send(200, {
                        "status": daemon.status(),
                        "policy": daemon.policy_get(),
                        "subsystems": {
                            "monitor-lost": {
                                n: daemon.monitor.lost_count(n)
                                for n in ("hubble", "metrics")},
                        },
                    })
                else:
                    self._send(404, {"error": f"no such path {path}"})
            except Exception as e:  # surface handler bugs as 500s
                self._send(500, {"error": str(e)})

        def do_PUT(self) -> None:  # noqa: N802
            path = self.path.rstrip("/")
            try:
                if path == "/policy":
                    rev = daemon.policy_import(self._body())
                    self._send(200, {"revision": rev})
                elif path == "/cluster/scale":
                    # live scale-out (ISSUE 13) / scale-in
                    # (ISSUE 17): grow or shrink the serving tier
                    # this node belongs to.  Body {"down": true
                    # [, "node": name]} retires a replica; empty or
                    # {"down": false} adds one
                    if daemon._cluster is None:
                        self._send(404, {
                            "error": "not part of a cluster serving "
                                     "tier (start_cluster_serving)"})
                    else:
                        body = self._body() or {}
                        if body.get("down"):
                            self._send(200, daemon._cluster.
                                       remove_node(body.get("node")))
                        else:
                            self._send(200,
                                       daemon._cluster.add_node())
                elif path == "/cluster/rotate":
                    # cluster-wide key-epoch rotation (ISSUE 18):
                    # re-key every live encrypted channel under the
                    # grace window, live serving uninterrupted.
                    # Body {"grace-s": f} overrides the config knob
                    if daemon._cluster is None:
                        self._send(404, {
                            "error": "not part of a cluster serving "
                                     "tier (start_cluster_serving)"})
                    else:
                        body = self._body() or {}
                        self._send(200, daemon._cluster.rotate_epoch(
                            grace_s=body.get("grace-s")))
                elif m := re.fullmatch(r"/endpoint/([\w.-]+)", path):
                    body = self._body() or {}
                    ep = daemon.add_endpoint(
                        body.get("name", m.group(1)),
                        tuple(body.get("ips", ())),
                        body.get("labels", []),
                        named_ports=body.get("named-ports"))
                    self._send(201, ep.to_dict())
                elif m := re.fullmatch(r"/service/([\w.-]+)", path):
                    body = self._body() or {}
                    frontend = body.get("frontend")
                    if not isinstance(frontend, str) or ":" not in \
                            frontend:
                        self._send(400, {"error": "frontend must be "
                                         "an 'ip:port' string"})
                        return
                    svc = daemon.services.upsert(
                        m.group(1), frontend,
                        body.get("backends", ()),
                        protocol=int(body.get("protocol", 6)))
                    self._send(201, svc.to_dict())
                else:
                    self._send(404, {"error": f"no such path {path}"})
            except Exception as e:
                self._send(500, {"error": str(e)})

        def do_PATCH(self) -> None:  # noqa: N802
            path = self.path.rstrip("/")
            try:
                if path == "/config":
                    # runtime-mutable options (reference: REST PATCH
                    # /config mutates a subset of DaemonConfig)
                    body = self._body() or {}
                    changed = daemon.patch_config(body)
                    self._send(200, {"changed": changed})
                elif m := re.fullmatch(r"/endpoint/(\d+)/config", path):
                    # per-endpoint enforcement mode + options
                    # (reference: pkg/option endpoint options)
                    body = self._body() or {}
                    ok = daemon.endpoints.update_config(
                        int(m.group(1)),
                        enforcement=body.get("policy-enforcement"),
                        options=body.get("options"))
                    self._send(200 if ok else 404, {"updated": ok})
                else:
                    self._send(404, {"error": f"no such path {path}"})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": str(e)})

        def do_DELETE(self) -> None:  # noqa: N802
            path = self.path.rstrip("/")
            try:
                if path == "/policy":
                    body = self._body() or {}
                    rev = daemon.policy_delete(body.get("labels", []))
                    self._send(200, {"revision": rev})
                elif m := re.fullmatch(r"/endpoint/(\d+)", path):
                    ok = daemon.endpoints.remove(int(m.group(1)))
                    self._send(200 if ok else 404, {"removed": ok})
                elif m := re.fullmatch(r"/service/([\w.-]+)", path):
                    ok = daemon.services.delete(m.group(1))
                    self._send(200 if ok else 404, {"removed": ok})
                else:
                    self._send(404, {"error": f"no such path {path}"})
            except Exception as e:
                self._send(500, {"error": str(e)})

    return Handler


def _map_list(daemon: Daemon) -> list:
    """GET /map — the BPF-maps listing analogue."""
    out = [{"name": "cilium_ipcache",
            "entries": len(daemon.ipcache.entries())}]
    loader = daemon.loader
    if getattr(loader, "state", None) is not None:
        from ..datapath.conntrack import ct_live_count

        out.append({"name": "cilium_ct_global",
                    "entries": ct_live_count(loader.state.ct),
                    "capacity": loader.state.ct.capacity})
        v = loader.state.policy.verdict
        out.append({"name": "cilium_policy",
                    "shape": list(v.shape)})
    return out


def _policy_map(daemon: Daemon, ep_id: int) -> list:
    """GET /map/policy/{ep} — the `bpf policy get` listing: the
    realized policy-map entries for one endpoint."""
    from ..policy.mapstate import PROTO_NAMES

    ep = daemon.endpoints.get(ep_id)
    if ep is None:
        return []
    pol = daemon.repo.resolve(ep.labels, named_ports=ep.named_ports)
    out = []
    for ms in (pol.ingress, pol.egress):
        for key, entry in ms.to_entries().items():
            out.append({
                "direction": "ingress" if key.direction == 0 else "egress",
                "identity": key.identity,
                "proto": PROTO_NAMES.get(key.proto, str(key.proto)),
                "dport": (str(key.dport_lo) if key.dport_lo == key.dport_hi
                          else f"{key.dport_lo}-{key.dport_hi}"),
                "verdict": {0: "deny", 1: "allow", 2: "deny",
                            3: "redirect"}[entry.verdict],
                "proxy-port": entry.proxy_port,
                "derived-from": list(entry.derived_from),
            })
    return out


def _metrics_text(daemon: Daemon) -> str:
    """Prometheus exposition — every series comes from the ONE
    unified registry (obs/registry.py).  Kept as a function (not
    inlined into the handler) because tests and tooling import it;
    the exposition text itself is built nowhere but the registry
    (enforced by scripts/check_metrics_registry.py)."""
    return daemon.registry.render()


def _flows(daemon: Daemon, q: dict) -> list:
    """GET /flows with the shared filter vocabulary (`cilium-tpu
    flows` and `top` speak the same flags): verdict/port/protocol/
    source_ip/destination_ip/since/identity map straight onto
    FlowFilter fields (`identity` = the flow's remote security
    identity — the only identity column the ring stores)."""
    f = FlowFilter(
        verdict=int(q["verdict"][0]) if "verdict" in q else None,
        port=int(q["port"][0]) if "port" in q else None,
        protocol=int(q["protocol"][0]) if "protocol" in q else None,
        source_ip=q.get("source_ip", [None])[0],
        destination_ip=q.get("destination_ip", [None])[0],
        since=float(q["since"][0]) if "since" in q else None,
        identity=int(q["identity"][0]) if "identity" in q else None,
    )
    n = int(q.get("number", ["100"])[0])
    filters = [f] if any(
        v is not None for v in (f.verdict, f.port, f.protocol,
                                f.source_ip, f.destination_ip,
                                f.since, f.identity)) else []
    return [fl.to_dict() for fl in daemon.observer.get_flows(filters, n)]
