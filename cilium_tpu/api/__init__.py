"""Agent REST API over a unix socket.

Reference: upstream cilium ``api/v1`` (go-swagger REST served on
``/var/run/cilium/cilium.sock``) — the surface the ``cilium`` CLI
speaks.  Routes mirror the reference's verbs: /healthz, /policy,
/endpoint, /identity, /map, /metrics, /flows, /config, /debuginfo.
"""

from .server import APIServer  # noqa: F401
from .client import APIClient  # noqa: F401
