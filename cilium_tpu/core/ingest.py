"""Host ingest: raw wire frames <-> header tensors, at line rate.

Reference: cilium's packets arrive as kernel skbs and are parsed by
native code (bpf/lib/eth.h, ipv4.h, l4.h); the TPU analogue receives
raw frames on the host, parses them natively
(cilium_tpu/native/ingest.cpp), and ships fixed-size header tensors to
the device.  This module provides:

- :func:`frames_from_batch` — render a header tensor as length-prefixed
  ethernet frames (vectorized; the benchmark's packet source, and the
  inverse of the ingest parser — used to prove parse fidelity).
- :func:`parse_frames` — frames -> header rows, native C++ fast path
  with a Python fallback.
"""

from __future__ import annotations

import numpy as np

from .packets import (
    COL_DPORT,
    COL_DST_IP3,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    N_COLS,
)

# fixed ipv4 frame: 4B length prefix + 14B eth + 20B ip + 20B l4 room
FRAME_LEN = 54
_REC_LEN = 4 + FRAME_LEN


def frames_from_batch(hdr: np.ndarray) -> bytes:
    """Header tensor [N, N_COLS] (IPv4 rows) -> length-prefixed
    ethernet frame stream.

    The IP header declares COL_LEN as the total length while the frame
    carries only headers (truncated-capture style, like a snaplen'd
    pcap), so ``parse -> frames -> parse`` round-trips every column the
    datapath reads.  EP/DIR are ingest-side metadata, not wire bytes —
    the parser stamps them per stream."""
    hdr = np.ascontiguousarray(hdr, dtype=np.uint32)
    n = hdr.shape[0]
    assert hdr.shape[1] == N_COLS
    buf = np.zeros((n, _REC_LEN), dtype=np.uint8)
    # u32le length prefix
    buf[:, 0] = FRAME_LEN
    # ethernet: zero macs, ethertype 0x0800
    buf[:, 4 + 12] = 0x08
    buf[:, 4 + 13] = 0x00
    ip = buf[:, 18:38]
    ip[:, 0] = 0x45
    total = hdr[:, COL_LEN].astype(np.uint16)
    ip[:, 2] = (total >> 8).astype(np.uint8)
    ip[:, 3] = (total & 0xFF).astype(np.uint8)
    ip[:, 8] = 64  # ttl
    ip[:, 9] = hdr[:, COL_PROTO].astype(np.uint8)
    src = hdr[:, COL_SRC_IP3]
    dst = hdr[:, COL_DST_IP3]
    for b in range(4):
        ip[:, 12 + b] = ((src >> (8 * (3 - b))) & 0xFF).astype(np.uint8)
        ip[:, 16 + b] = ((dst >> (8 * (3 - b))) & 0xFF).astype(np.uint8)
    l4 = buf[:, 38:58]
    proto = hdr[:, COL_PROTO]
    sport = hdr[:, COL_SPORT].astype(np.uint16)
    dport = hdr[:, COL_DPORT].astype(np.uint16)
    has_ports = (proto == 6) | (proto == 17) | (proto == 132)
    l4[:, 0] = np.where(has_ports, sport >> 8, 0).astype(np.uint8)
    l4[:, 1] = np.where(has_ports, sport & 0xFF, 0).astype(np.uint8)
    l4[:, 2] = np.where(has_ports, dport >> 8, 0).astype(np.uint8)
    l4[:, 3] = np.where(has_ports, dport & 0xFF, 0).astype(np.uint8)
    # tcp flags byte; icmp type byte
    l4[:, 13] = np.where(proto == 6, hdr[:, COL_FLAGS] & 0xFF, 0
                         ).astype(np.uint8)
    is_icmp = (proto == 1) | (proto == 58)
    l4[:, 0] = np.where(is_icmp, dport & 0xFF, l4[:, 0]).astype(np.uint8)
    return buf.tobytes()


def parse_frames(buf: bytes, ep: int = 0,
                 direction: int = 0) -> np.ndarray:
    """Length-prefixed frame stream -> [N, N_COLS] header rows.

    Native C++ when available, Python fallback otherwise."""
    from .. import native

    rows = native.parse_frames(buf, ep, direction)
    if rows is None:
        rows = native.parse_frames_py(buf, ep, direction)
    return rows
