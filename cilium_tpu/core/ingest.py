"""Host ingest: raw wire frames <-> header tensors, at line rate.

Reference: cilium's packets arrive as kernel skbs and are parsed by
native code (bpf/lib/eth.h, ipv4.h, l4.h); the TPU analogue receives
raw frames on the host, parses them natively
(cilium_tpu/native/ingest.cpp), and ships fixed-size header tensors to
the device.  This module provides:

- :func:`frames_from_batch` — render a header tensor as length-prefixed
  ethernet frames (vectorized; the benchmark's packet source, and the
  inverse of the ingest parser — used to prove parse fidelity).
- :func:`parse_frames` — frames -> header rows, native C++ fast path
  with a Python fallback.
"""

from __future__ import annotations

import numpy as np

from .packets import (
    COL_DPORT,
    COL_DST_IP3,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    N_COLS,
)

# fixed ipv4 frame: 4B length prefix + 14B eth + 20B ip + 20B l4 room
FRAME_LEN = 54
_REC_LEN = 4 + FRAME_LEN


def frames_from_batch(hdr: np.ndarray) -> bytes:
    """Header tensor [N, N_COLS] (IPv4 rows) -> length-prefixed
    ethernet frame stream.

    The IP header declares COL_LEN as the total length while the frame
    carries only headers (truncated-capture style, like a snaplen'd
    pcap), so ``parse -> frames -> parse`` round-trips every column the
    datapath reads.  EP/DIR are ingest-side metadata, not wire bytes —
    the parser stamps them per stream."""
    hdr = np.ascontiguousarray(hdr, dtype=np.uint32)
    n = hdr.shape[0]
    assert hdr.shape[1] == N_COLS
    buf = np.zeros((n, _REC_LEN), dtype=np.uint8)
    # u32le length prefix
    buf[:, 0] = FRAME_LEN
    # ethernet: zero macs, ethertype 0x0800
    buf[:, 4 + 12] = 0x08
    buf[:, 4 + 13] = 0x00
    ip = buf[:, 18:38]
    ip[:, 0] = 0x45
    total = hdr[:, COL_LEN].astype(np.uint16)
    ip[:, 2] = (total >> 8).astype(np.uint8)
    ip[:, 3] = (total & 0xFF).astype(np.uint8)
    ip[:, 8] = 64  # ttl
    ip[:, 9] = hdr[:, COL_PROTO].astype(np.uint8)
    src = hdr[:, COL_SRC_IP3]
    dst = hdr[:, COL_DST_IP3]
    for b in range(4):
        ip[:, 12 + b] = ((src >> (8 * (3 - b))) & 0xFF).astype(np.uint8)
        ip[:, 16 + b] = ((dst >> (8 * (3 - b))) & 0xFF).astype(np.uint8)
    l4 = buf[:, 38:58]
    proto = hdr[:, COL_PROTO]
    sport = hdr[:, COL_SPORT].astype(np.uint16)
    dport = hdr[:, COL_DPORT].astype(np.uint16)
    has_ports = (proto == 6) | (proto == 17) | (proto == 132)
    l4[:, 0] = np.where(has_ports, sport >> 8, 0).astype(np.uint8)
    l4[:, 1] = np.where(has_ports, sport & 0xFF, 0).astype(np.uint8)
    l4[:, 2] = np.where(has_ports, dport >> 8, 0).astype(np.uint8)
    l4[:, 3] = np.where(has_ports, dport & 0xFF, 0).astype(np.uint8)
    # tcp flags byte; icmp type byte
    l4[:, 13] = np.where(proto == 6, hdr[:, COL_FLAGS] & 0xFF, 0
                         ).astype(np.uint8)
    is_icmp = (proto == 1) | (proto == 58)
    l4[:, 0] = np.where(is_icmp, dport & 0xFF, l4[:, 0]).astype(np.uint8)
    return buf.tobytes()


def wide_frames_from_batch(hdr: np.ndarray) -> bytes:
    """Header tensor -> frames, WIDE-path edition: renders IPv4 rows,
    IPv6 rows (COL_FAMILY == 6, full 128-bit addresses), and
    FLAG_RELATED rows as ICMPv4 destination-unreachable errors whose
    payload EMBEDS the row's tuple — the inverse of the parser's
    RELATED transform (core/pcap.py build_row), so
    ``parse_frames(wide_frames_from_batch(h))`` reproduces the tuple
    columns.  Vectorized: per-class fixed-size records scattered into a
    ragged stream via a length mask (no per-packet Python)."""
    from .packets import COL_DST_IP0, COL_FAMILY, COL_SRC_IP0, FLAG_RELATED

    hdr = np.ascontiguousarray(hdr, dtype=np.uint32)
    n = hdr.shape[0]
    fam6 = hdr[:, COL_FAMILY] == 6
    rel = (hdr[:, COL_FLAGS] & FLAG_RELATED) != 0
    related = rel & ~fam6
    related6 = rel & fam6
    is_v6 = fam6 & ~rel
    is_v4 = ~fam6 & ~rel

    V4_REC, V6_REC, REL_REC = 4 + 54, 4 + 74, 4 + 70
    REL6_REC = 4 + 110  # eth + outer v6 + icmp6 + embedded v6 + l4
    buf = np.zeros((n, REL6_REC), dtype=np.uint8)
    lens = np.select([related6, is_v6, related],
                     [REL6_REC, V6_REC, REL_REC], V4_REC)

    # plain IPv4 rows reuse the single-family renderer
    if is_v4.any():
        v4 = np.frombuffer(frames_from_batch(hdr[is_v4]),
                           dtype=np.uint8).reshape(-1, V4_REC)
        buf[is_v4, :V4_REC] = v4

    def _be16(x):
        return (x >> 8).astype(np.uint8), (x & 0xFF).astype(np.uint8)

    if is_v6.any():
        h = hdr[is_v6]
        m = buf[is_v6]
        m[:, 0] = 74  # length prefix (u32le, low byte)
        m[:, 4 + 12], m[:, 4 + 13] = 0x86, 0xDD
        ip = m[:, 18:58]
        ip[:, 0] = 0x60
        pay = np.maximum(h[:, COL_LEN], 40) - 40
        ip[:, 4], ip[:, 5] = _be16(pay.astype(np.uint16))
        ip[:, 6] = h[:, COL_PROTO].astype(np.uint8)
        ip[:, 7] = 64
        for w in range(4):
            for b in range(4):
                sh = 8 * (3 - b)
                ip[:, 8 + 4 * w + b] = ((h[:, COL_SRC_IP0 + w] >> sh)
                                        & 0xFF).astype(np.uint8)
                ip[:, 24 + 4 * w + b] = ((h[:, COL_DST_IP0 + w] >> sh)
                                         & 0xFF).astype(np.uint8)
        l4 = m[:, 58:78]
        l4[:, 0], l4[:, 1] = _be16(h[:, COL_SPORT].astype(np.uint16))
        l4[:, 2], l4[:, 3] = _be16(h[:, COL_DPORT].astype(np.uint16))
        l4[:, 13] = np.where(h[:, COL_PROTO] == 6,
                             h[:, COL_FLAGS] & 0xFF, 0).astype(np.uint8)
        buf[is_v6] = m

    if related.any():
        h = hdr[related]
        m = buf[related]
        m[:, 0] = 70
        m[:, 4 + 12], m[:, 4 + 13] = 0x08, 0x00
        out_ip = m[:, 18:38]  # outer: some router -> the row's dst
        out_ip[:, 0] = 0x45
        out_ip[:, 2], out_ip[:, 3] = 0, 56  # 20 + 8 icmp + 20 + 8
        out_ip[:, 8], out_ip[:, 9] = 64, 1  # ICMP
        out_ip[:, 12:16] = [10, 0, 99, 99]  # the erroring router
        for b in range(4):
            out_ip[:, 16 + b] = ((h[:, COL_SRC_IP3] >> (8 * (3 - b)))
                                 & 0xFF).astype(np.uint8)
        m[:, 38] = 3  # ICMP type 3 (dest unreachable), code 0
        emb = m[:, 46:66]  # embedded original IPv4 header
        emb[:, 0] = 0x45
        emb[:, 2], emb[:, 3] = 0, 28
        emb[:, 8], emb[:, 9] = 64, h[:, COL_PROTO].astype(np.uint8)
        for b in range(4):
            sh = 8 * (3 - b)
            emb[:, 12 + b] = ((h[:, COL_SRC_IP3] >> sh) & 0xFF
                              ).astype(np.uint8)
            emb[:, 16 + b] = ((h[:, COL_DST_IP3] >> sh) & 0xFF
                              ).astype(np.uint8)
        el4 = m[:, 66:74]
        el4[:, 0], el4[:, 1] = _be16(h[:, COL_SPORT].astype(np.uint16))
        el4[:, 2], el4[:, 3] = _be16(h[:, COL_DPORT].astype(np.uint16))
        buf[related] = m

    if related6.any():
        h = hdr[related6]
        m = buf[related6]
        m[:, 0] = 110
        m[:, 4 + 12], m[:, 4 + 13] = 0x86, 0xDD

        def _v6hdr(dst_slice, nxt, paylen, src_words, dst_words):
            dst_slice[:, 0] = 0x60
            dst_slice[:, 4], dst_slice[:, 5] = _be16(
                np.full(len(h), paylen, dtype=np.uint16))
            dst_slice[:, 6] = nxt
            dst_slice[:, 7] = 64
            for w in range(4):
                for b in range(4):
                    sh = 8 * (3 - b)
                    dst_slice[:, 8 + 4 * w + b] = (
                        (src_words[:, w] >> sh) & 0xFF).astype(np.uint8)
                    dst_slice[:, 24 + 4 * w + b] = (
                        (dst_words[:, w] >> sh) & 0xFF).astype(np.uint8)

        src_w = h[:, COL_SRC_IP0:COL_SRC_IP0 + 4]
        dst_w = h[:, COL_DST_IP0:COL_DST_IP0 + 4]
        router = np.zeros_like(src_w)
        router[:, 0], router[:, 3] = 0x20010DB8, 0x9999  # the router
        # outer: router -> original sender, next header 58 (ICMPv6),
        # payload = 8 icmp6 + 40 embedded v6 + 8 l4
        _v6hdr(m[:, 18:58], 58, 56, router, src_w)
        m[:, 58] = 1  # ICMPv6 type 1 (dest unreachable), code 0
        nxt = h[:, COL_PROTO].astype(np.uint8)
        _v6hdr(m[:, 66:106], 0, 8, src_w, dst_w)
        m[:, 66 + 6] = nxt  # embedded next header = original proto
        el4 = m[:, 106:114]
        el4[:, 0], el4[:, 1] = _be16(h[:, COL_SPORT].astype(np.uint16))
        el4[:, 2], el4[:, 3] = _be16(h[:, COL_DPORT].astype(np.uint16))
        buf[related6] = m

    keep = np.arange(REL6_REC)[None, :] < lens[:, None]
    return buf[keep].tobytes()


def parse_frames(buf: bytes, ep: int = 0, direction: int = 0,
                 out: np.ndarray = None) -> np.ndarray:
    """Length-prefixed frame stream -> [N, N_COLS] header rows.

    Native C++ when available, Python fallback otherwise.  ``out``: a
    reused [max_rows, N_COLS] u32 buffer for transfer-bound callers
    (page-registration cache; the return is then a VIEW of it)."""
    from .. import native

    rows = native.parse_frames(buf, ep, direction, out=out)
    if rows is None:
        rows = native.parse_frames_py(buf, ep, direction)
        if out is not None:
            out[:len(rows)] = rows
            rows = out[:len(rows)]
    return rows
