"""Header-tensor core: the packet representation of the TPU datapath.

Reference: upstream cilium's per-packet context is a ``struct __sk_buff``
parsed in ``bpf/lib/ipv4.h``/``l4.h``; here packets are rows of a fixed
[N, N_COLS] uint32 tensor so the whole datapath runs batched on the MXU.
"""

from .packets import (  # noqa: F401
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_DST_IP3,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    COL_SRC_IP3,
    N_COLS,
    TCP_ACK,
    TCP_FIN,
    TCP_PSH,
    TCP_RST,
    TCP_SYN,
    HeaderBatch,
    ip_to_words,
    make_batch,
    synth_batch,
    words_to_ip,
)
from .pcap import read_pcap, write_pcap  # noqa: F401
