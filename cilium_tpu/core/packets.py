"""Packet header tensor schema + synthetic traffic generation.

Reference: upstream cilium parses each packet in-kernel
(``bpf/lib/ipv4.h``, ``bpf/lib/ipv6.h``, ``bpf/lib/l4.h``) into a
5-tuple + flags used by conntrack and policy.  TPU-first redesign: a
*batch* of packets is one ``[N, N_COLS] uint32`` tensor ("header
tensor"); every datapath stage is a vectorized op over the batch axis.

Column layout (all uint32):

====  ==========  =====================================================
col   name        contents
====  ==========  =====================================================
0-3   SRC_IP0-3   128-bit source IP, 4 big-endian words.  IPv4 lives in
                  word 3 (words 0-2 zero), i.e. IPv4-mapped layout.
4-7   DST_IP0-3   128-bit destination IP, same layout.
8     SPORT       L4 source port (0 when the proto has no ports)
9     DPORT       L4 destination port / ICMP type
10    PROTO       IP protocol number (6 TCP, 17 UDP, 1 ICMP, ...)
11    FLAGS       TCP flags byte (0 otherwise)
12    LEN         IP total length in bytes
13    FAMILY      4 or 6
14    EP          local endpoint id (dense row; which policy applies)
15    DIR         0 ingress / 1 egress (relative to endpoint EP)
====  ==========  =====================================================
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import numpy as np

COL_SRC_IP0 = 0
COL_SRC_IP3 = 3
COL_DST_IP0 = 4
COL_DST_IP3 = 7
COL_SPORT = 8
COL_DPORT = 9
COL_PROTO = 10
COL_FLAGS = 11
COL_LEN = 12
COL_FAMILY = 13
COL_EP = 14
COL_DIR = 15
N_COLS = 16

# --- packed wire format (the h2d fast path) ---------------------------
#
# The wide [N, 16] u32 tensor costs 64 B/packet over the host->device
# link — the measured end-to-end bottleneck (the tunnel sustains only
# ~200 MB/s for fresh buffers).  IPv4 traffic therefore ships as
# [N, 4] u32 "packed" rows (16 B/packet) and unpacks on device inside
# the fused step (unpack_hdr below), a 4x ingest-bandwidth win:
#
#   w0 = src ip (v4, big-endian value)
#   w1 = dst ip
#   w2 = sport << 16 | dport
#   w3 = proto << 24 | tcp_flags << 16 | ip total length
#
# EP/DIR/FAMILY are stream metadata (one value per ingest stream, like
# the per-endpoint tc hook in the reference), passed as scalars to the
# packed step.  IPv6 frames take the wide path.
PACKED_COLS = 4
PACKED_SRC = 0
PACKED_DST = 1
PACKED_PORTS = 2
PACKED_META = 3

TCP_FIN = 0x01
TCP_SYN = 0x02
TCP_RST = 0x04
TCP_PSH = 0x08
TCP_ACK = 0x10

# COL_FLAGS bit 8 (above the TCP flags byte): this row is an ICMP
# ERROR whose columns carry the EMBEDDED (original) packet's 5-tuple —
# the conntrack lookup relates it to the original flow (CT_RELATED,
# reference: bpf/lib/conntrack.h ICMP error handling).  On the packed
# 16 B wire format the flag rides BIT 15 of the length half-word
# (META_RELATED_BIT): lengths cap at 0x7FFF, a no-op for any real MTU,
# and ICMPv4 errors relate on the fast path too (r04; previously a
# documented divergence).  v6 ICMP errors remain wide-path (the packed
# format is IPv4-only).
FLAG_RELATED = 0x100
META_RELATED_BIT = 1 << 15  # within the META length half-word
META_LEN_MASK = 0x7FFF

# VXLAN / Geneve UDP ports (reference: bpf_overlay.c decap; Linux
# defaults).  Overlay frames decap at ingest: the row carries the
# INNER packet's tuple.
VXLAN_PORT = 8472
GENEVE_PORT = 6081

# Protocols whose CT tuple carries no ports (ICMP/ICMPv6: echo req and
# reply must share a tuple modulo direction swap).  Flow steering and
# CT key construction MUST use the same normalization — both call
# normalize_ports below.
PORTLESS_PROTOS = (1, 58)


def normalize_ports(xp, proto, sport, dport):
    """Zero the ports of portless protocols (xp = np or jnp)."""
    portless = (proto == PORTLESS_PROTOS[0]) | (proto == PORTLESS_PROTOS[1])
    return xp.where(portless, 0, sport), xp.where(portless, 0, dport)

def pack_rows(hdr: np.ndarray, out: Optional[np.ndarray] = None
              ) -> np.ndarray:
    """Wide IPv4 header rows [N, N_COLS] -> packed rows [N, PACKED_COLS].

    Inverse of :func:`unpack_hdr`; EP/DIR/FAMILY columns are dropped
    (stream metadata).  ``out`` may be a reused buffer."""
    hdr = np.asarray(hdr, dtype=np.uint32)
    n = hdr.shape[0]
    if out is None:
        out = np.empty((n, PACKED_COLS), dtype=np.uint32)
    p = out[:n]
    p[:, PACKED_SRC] = hdr[:, COL_SRC_IP3]
    p[:, PACKED_DST] = hdr[:, COL_DST_IP3]
    p[:, PACKED_PORTS] = (hdr[:, COL_SPORT] << 16) | (hdr[:, COL_DPORT]
                                                      & 0xFFFF)
    related = ((hdr[:, COL_FLAGS] & FLAG_RELATED) != 0).astype(np.uint32)
    p[:, PACKED_META] = ((hdr[:, COL_PROTO] << 24)
                         | ((hdr[:, COL_FLAGS] & 0xFF) << 16)
                         | (related << 15)
                         | np.minimum(hdr[:, COL_LEN], META_LEN_MASK))
    return p


def _unpack_hdr_xp(xp, packed, ep, dirn):
    """The packed->wide bit layout, ONCE, over xp = np or jnp — the
    device unpack (:func:`unpack_hdr`) and the host event join
    (:func:`unpack_rows_np`) must never drift apart on the wire
    format (same discipline as normalize_ports)."""
    packed = packed.astype(xp.uint32)
    src = packed[:, PACKED_SRC]
    z = xp.zeros_like(src)
    return xp.stack([
        z, z, z, src,
        z, z, z, packed[:, PACKED_DST],
        packed[:, PACKED_PORTS] >> 16,
        packed[:, PACKED_PORTS] & 0xFFFF,
        packed[:, PACKED_META] >> 24,
        ((packed[:, PACKED_META] >> 16) & 0xFF)
        | (((packed[:, PACKED_META] >> 15) & 1) << 8),  # FLAG_RELATED
        packed[:, PACKED_META] & META_LEN_MASK,
        xp.full_like(src, 4),
        xp.full_like(src, xp.uint32(ep)),
        xp.full_like(src, xp.uint32(dirn)),
    ], axis=1)


def unpack_hdr(packed, ep, dirn):
    """Packed rows [N, 4] -> wide header tensor [N, N_COLS] (jax).

    Runs on device inside the fused packed step; XLA fuses the stack
    into the downstream gathers so the wide tensor is never
    materialized in HBM.  ``ep``/``dirn`` are scalars (stream
    metadata)."""
    import jax.numpy as jnp

    return _unpack_hdr_xp(jnp, packed, ep, dirn)


def unpack_rows_np(packed: np.ndarray, ep: int, dirn: int) -> np.ndarray:
    """Packed rows [N, 4] -> wide header rows [N, N_COLS], host numpy.

    The host inverse of :func:`pack_rows` — the SAME bit-layout
    definition as the device unpack (:func:`_unpack_hdr_xp`): the
    serving path retains only the PACKED rows per batch window, and
    the event join reconstructs wide columns for just the few rows
    the ring compaction kept."""
    packed = np.asarray(packed, dtype=np.uint32)
    return _unpack_hdr_xp(np, packed, int(ep), int(dirn))


def pack_eligibility(hdr: np.ndarray,
                     n: Optional[int] = None) -> Tuple[bool, int, int]:
    """May ``hdr[:n]`` ship as packed 16 B rows VERDICT-IDENTICALLY?

    Returns ``(eligible, ep, dirn)``.  Eligible means: IPv4 in the
    mapped layout (src/dst words 0-2 zero), every field inside its
    packed wire width (ports 16 bit, proto 8 bit, flags 8 bit +
    RELATED, len <= 0x7FFF — capping would change what the datapath
    sees), and ONE (ep, dir) stream (they ride as scalars, the
    per-endpoint tc hook analogue).  Anything else takes the wide
    fallback shape."""
    h = np.asarray(hdr)[:n]
    if len(h) == 0:
        return False, 0, 0
    ep, dirn = int(h[0, COL_EP]), int(h[0, COL_DIR])
    ok = (
        (h[:, COL_FAMILY] == 4).all()
        and not h[:, COL_SRC_IP0:COL_SRC_IP3].any()
        and not h[:, COL_DST_IP0:COL_DST_IP3].any()
        and (h[:, COL_SPORT] < (1 << 16)).all()
        and (h[:, COL_DPORT] < (1 << 16)).all()
        and (h[:, COL_PROTO] < (1 << 8)).all()
        and not (h[:, COL_FLAGS] & ~np.uint32(0xFF | FLAG_RELATED)).any()
        and (h[:, COL_LEN] <= META_LEN_MASK).all()
        and (h[:, COL_EP] == ep).all()
        and (h[:, COL_DIR] == dirn).all()
    )
    return bool(ok), ep, dirn


IPAddr = Union[str, int, ipaddress.IPv4Address, ipaddress.IPv6Address]


def ip_to_words(ip: IPAddr) -> Tuple[int, int, int, int]:
    """IP address -> 4 big-endian uint32 words (IPv4 in word 3)."""
    addr = ipaddress.ip_address(ip)
    n = int(addr)
    if addr.version == 4:
        return (0, 0, 0, n)
    return ((n >> 96) & 0xFFFFFFFF, (n >> 64) & 0xFFFFFFFF,
            (n >> 32) & 0xFFFFFFFF, n & 0xFFFFFFFF)


def words_to_ip(words: Sequence[int], family: int = 4) -> str:
    if family == 4:
        return str(ipaddress.IPv4Address(int(words[3])))
    n = (int(words[0]) << 96) | (int(words[1]) << 64) | \
        (int(words[2]) << 32) | int(words[3])
    return str(ipaddress.IPv6Address(n))


@dataclass
class HeaderBatch:
    """A batch of parsed packet headers (host-side view of the tensor)."""

    data: np.ndarray  # [N, N_COLS] uint32

    def __post_init__(self):
        assert self.data.ndim == 2 and self.data.shape[1] == N_COLS
        self.data = np.ascontiguousarray(self.data, dtype=np.uint32)

    def __len__(self) -> int:
        return self.data.shape[0]

    def col(self, c: int) -> np.ndarray:
        return self.data[:, c]

    def describe(self, i: int) -> str:
        r = self.data[i]
        fam = int(r[COL_FAMILY])
        return (f"{words_to_ip(r[COL_SRC_IP0:COL_SRC_IP3 + 1], fam)}:"
                f"{r[COL_SPORT]} -> "
                f"{words_to_ip(r[COL_DST_IP0:COL_DST_IP3 + 1], fam)}:"
                f"{r[COL_DPORT]} proto={r[COL_PROTO]} "
                f"flags={r[COL_FLAGS]:#x} len={r[COL_LEN]} "
                f"ep={r[COL_EP]} dir={'egress' if r[COL_DIR] else 'ingress'}")


def make_batch(rows: Sequence[dict]) -> HeaderBatch:
    """Build a HeaderBatch from dicts: {src, dst, sport, dport, proto,
    flags, length, ep, dir}.  ``src``/``dst`` accept any IP form."""
    out = np.zeros((len(rows), N_COLS), dtype=np.uint32)
    for i, r in enumerate(rows):
        sw = ip_to_words(r.get("src", 0))
        dw = ip_to_words(r.get("dst", 0))
        fam = 6 if (sw[:3] != (0, 0, 0) or dw[:3] != (0, 0, 0)
                    or r.get("family") == 6) else 4
        out[i, COL_SRC_IP0:COL_SRC_IP3 + 1] = sw
        out[i, COL_DST_IP0:COL_DST_IP3 + 1] = dw
        out[i, COL_SPORT] = r.get("sport", 0)
        out[i, COL_DPORT] = r.get("dport", 0)
        out[i, COL_PROTO] = r.get("proto", 6)
        out[i, COL_FLAGS] = r.get("flags", TCP_SYN if r.get("proto", 6) == 6
                                  else 0)
        out[i, COL_LEN] = r.get("length", 64)
        out[i, COL_FAMILY] = r.get("family", fam)
        out[i, COL_EP] = r.get("ep", 0)
        out[i, COL_DIR] = r.get("dir", 0)
    return HeaderBatch(out)


def synth_batch(
    n: int,
    rng: Optional[np.random.Generator] = None,
    n_hosts: int = 256,
    subnet: int = 0x0A000000,  # 10.0.0.0
    dports: Optional[np.ndarray] = None,
    protos: Optional[np.ndarray] = None,
    ep: int = 0,
    direction: int = 0,
) -> HeaderBatch:
    """Synthesize a plausible IPv4 traffic batch (the benchmark's
    packet-gen; reference analogue: bpf/tests crafted packets)."""
    rng = rng or np.random.default_rng(0)
    out = np.zeros((n, N_COLS), dtype=np.uint32)
    src = subnet + rng.integers(1, n_hosts + 1, n, dtype=np.uint32)
    dst = subnet + rng.integers(1, n_hosts + 1, n, dtype=np.uint32)
    out[:, COL_SRC_IP3] = src
    out[:, COL_DST_IP3] = dst
    out[:, COL_SPORT] = rng.integers(1024, 61000, n, dtype=np.uint32)
    if dports is None:
        out[:, COL_DPORT] = rng.choice(
            np.array([80, 443, 8080, 53, 22, 5432], dtype=np.uint32), n)
    else:
        out[:, COL_DPORT] = rng.choice(dports.astype(np.uint32), n)
    if protos is None:
        out[:, COL_PROTO] = rng.choice(
            np.array([6, 6, 6, 17, 1], dtype=np.uint32), n)
    else:
        out[:, COL_PROTO] = rng.choice(protos.astype(np.uint32), n)
    is_tcp = out[:, COL_PROTO] == 6
    out[:, COL_FLAGS] = np.where(
        is_tcp,
        rng.choice(np.array([TCP_SYN, TCP_ACK, TCP_ACK | TCP_PSH],
                            dtype=np.uint32), n),
        0,
    )
    out[:, COL_SPORT] = np.where(out[:, COL_PROTO] == 1, 0,
                                 out[:, COL_SPORT])
    out[:, COL_DPORT] = np.where(
        out[:, COL_PROTO] == 1,
        rng.integers(0, 2, n, dtype=np.uint32) * 8,  # echo req/reply
        out[:, COL_DPORT])
    out[:, COL_LEN] = rng.integers(60, 1500, n, dtype=np.uint32)
    out[:, COL_FAMILY] = 4
    out[:, COL_EP] = ep
    out[:, COL_DIR] = direction
    return HeaderBatch(out)
