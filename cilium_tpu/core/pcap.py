"""pcap ingest/egest: raw capture files <-> header tensors.

Reference: upstream cilium's bpf test harness crafts packets as byte
arrays (``bpf/tests``) and Hubble replays captures; here a classic
libpcap file parses straight into the ``[N, N_COLS]`` header tensor
(the datapath's wire format), and a HeaderBatch can be written back out
as a valid pcap for interop with tcpdump/wireshark.

Pure Python (struct) — this is the control-plane ingest path; the bulk
benchmark path synthesizes batches directly on-host (core.packets) or
on-device.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from .packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    N_COLS,
    HeaderBatch,
)

PCAP_MAGIC = 0xA1B2C3D4
PCAP_MAGIC_SWAPPED = 0xD4C3B2A1
LINKTYPE_ETHERNET = 1
LINKTYPE_RAW = 101

ETH_P_IP = 0x0800
ETH_P_IPV6 = 0x86DD


def _parse_l4(proto: int, payload: bytes) -> Tuple[int, int, int]:
    """Return (sport, dport, tcp_flags)."""
    if proto in (6, 17, 132) and len(payload) >= 4:
        sport, dport = struct.unpack_from("!HH", payload, 0)
        flags = payload[13] if proto == 6 and len(payload) >= 14 else 0
        return sport, dport, flags
    if proto in (1, 58) and len(payload) >= 2:
        return 0, payload[0], 0  # ICMP: dport column carries the type
    return 0, 0, 0


class FragTracker:
    """IPv4 fragment association (reference: the datapath fragmap,
    ``bpf/lib/ipv4.h ipv4_handle_fragmentation`` + ``pkg/maps/fragmap``).

    The first fragment of a datagram carries the L4 header; later
    fragments don't — without tracking they'd parse with garbage
    ports.  The first fragment records (src, dst, proto, ipid) ->
    l4-prefix; mid-fragments resolve through it; a miss is a skip
    (upstream: DROP_FRAG_NOT_FOUND).  Bounded FIFO like the
    reference's LRU fragmap."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._map: dict = {}

    def record(self, key: tuple, l4_prefix: bytes) -> None:
        if key not in self._map and len(self._map) >= self.capacity:
            self._map.pop(next(iter(self._map)))  # FIFO evict
        self._map[key] = l4_prefix

    def lookup(self, key: tuple) -> Optional[bytes]:
        return self._map.get(key)


# module-level tracker: fragments of one datagram may straddle parse
# calls (the kernel fragmap is long-lived for the same reason)
_FRAGS = FragTracker()


def _parse_ip_one(pkt: bytes, frags=None
                  ) -> Optional[Tuple[int, bytes, bytes, int, bytes, int]]:
    """Parse ONE IP header (no decap) -> (family, src16, dst16, proto,
    l4payload, ip_total_len).  IPv4 fragments resolve their L4 ports
    through the fragment tracker; an unresolvable mid-fragment returns
    None (parse-stage drop).  ``frags=False`` disables fragment
    tracking entirely — REQUIRED for ICMP-quoted inner headers, which
    are attacker-controlled bytes: recording them would let a forged
    ICMP error poison the tracker with chosen ports."""
    if len(pkt) < 20:
        return None
    ver = pkt[0] >> 4
    if ver == 4:
        ihl = (pkt[0] & 0xF) * 4
        if ihl < 20 or len(pkt) < ihl:
            return None
        proto = pkt[9]
        total = struct.unpack_from("!H", pkt, 2)[0]
        src = b"\x00" * 12 + pkt[12:16]
        dst = b"\x00" * 12 + pkt[16:20]
        l4 = pkt[ihl:]
        fo_field = struct.unpack_from("!H", pkt, 6)[0]
        frag_off = fo_field & 0x1FFF
        more = bool(fo_field & 0x2000)
        if (frag_off or more) and proto in (6, 17, 132) \
                and frags is not False:
            frags = frags if frags is not None else _FRAGS
            key = (pkt[12:16], pkt[16:20], proto, pkt[4:6])
            if frag_off == 0:  # first fragment: carries the L4 header
                # zero-pad to 8 bytes: the native tracker stores a
                # fixed 8-byte prefix, and a shorter record would make
                # mid-fragment port parsing diverge between parsers
                frags.record(key, (l4[:8] + b"\x00" * 8)[:8])
            else:  # mid/last fragment: no L4 header on the wire
                prefix = frags.lookup(key)
                if prefix is None:
                    return None  # DROP_FRAG_NOT_FOUND analogue
                l4 = prefix
        return 4, src, dst, proto, l4, total
    if ver == 6 and len(pkt) >= 40:
        proto = pkt[6]
        payload_len = struct.unpack_from("!H", pkt, 4)[0]
        return 6, pkt[8:24], pkt[24:40], proto, pkt[40:], 40 + payload_len
    return None


def _decap_overlay(proto: int, l4: bytes) -> Optional[bytes]:
    """UDP VXLAN/Geneve payload -> inner IP packet bytes, or None.

    Reference: ``bpf_overlay.c`` decap — the datapath verdicts the
    INNER packet; the outer header is transport."""
    from .packets import GENEVE_PORT, VXLAN_PORT

    if proto != 17 or len(l4) < 8:
        return None
    dport = struct.unpack_from("!H", l4, 2)[0]
    payload = l4[8:]
    if dport == VXLAN_PORT:
        if len(payload) < 8 + 14:
            return None
        inner_eth = payload[8:]  # 8B VXLAN header (flags + VNI)
    elif dport == GENEVE_PORT:
        if len(payload) < 8:
            return None
        optlen = (payload[0] & 0x3F) * 4
        if len(payload) < 8 + optlen + 14:
            return None
        inner_eth = payload[8 + optlen:]
    else:
        return None
    ethertype = struct.unpack_from("!H", inner_eth, 12)[0]
    if ethertype not in (ETH_P_IP, ETH_P_IPV6):
        return None
    return inner_eth[14:]


# ICMP error types whose payload embeds the original packet's header
# (reference: icmp_is_error / bpf conntrack related handling)
_ICMP4_ERRORS = (3, 4, 5, 11, 12)
_ICMP6_ERRORS = (1, 2, 3, 4)


def _related_tuple(fam: int, proto: int, l4: bytes):
    """For ICMP errors: -> (src16, dst16, inner_proto, sport, dport)
    of the EMBEDDED original packet, or None."""
    if len(l4) < 8 + 20:
        return None
    t = l4[0]
    if not ((proto == 1 and t in _ICMP4_ERRORS)
            or (proto == 58 and t in _ICMP6_ERRORS)):
        return None
    # frags=False: the quoted header is attacker-controlled — fragment
    # tracking on it would be a poisoning vector (and the native parser
    # likewise parses quoted headers without fragment logic)
    inner = _parse_ip_one(l4[8:], frags=False)
    if inner is None:
        return None
    ifam, isrc, idst, iproto, il4, _ = inner
    if ifam != fam:
        return None
    isport = idport = 0
    if iproto in (6, 17, 132) and len(il4) >= 4:
        isport, idport = struct.unpack_from("!HH", il4, 0)
    elif iproto in (1, 58) and len(il4) >= 2:
        idport = il4[0]
    return isrc, idst, iproto, isport, idport


def _parse_ip(pkt: bytes
              ) -> Optional[Tuple[int, bytes, bytes, int, bytes, int]]:
    """Parse an IP packet, decapsulating VXLAN/Geneve overlays ->
    (family, src16, dst16, proto, l4payload, ip_total_len).
    ``ip_total_len`` is the header-declared IP length (COL_LEN)."""
    parsed = _parse_ip_one(pkt)
    if parsed is None:
        return None
    for _ in range(2):  # bounded decap depth
        fam, src, dst, proto, l4, total = parsed
        inner = _decap_overlay(proto, l4)
        if inner is None:
            return parsed
        deeper = _parse_ip_one(inner)
        if deeper is None:
            return parsed
        parsed = deeper
    return parsed


def build_row(parsed, ep: int, direction: int,
              related: bool = True) -> np.ndarray:
    """(family, src16, dst16, proto, l4, total) -> one header row,
    including the CT_RELATED transform: an ICMP error row carries the
    EMBEDDED packet's tuple + FLAG_RELATED (reference: conntrack
    relates ICMP errors to the original flow).  ``related=False``
    keeps the OUTER tuple (the packed fast path's semantics — the
    16 B wire format has no RELATED bit, see packets.FLAG_RELATED)."""
    from .packets import FLAG_RELATED

    fam, src, dst, proto, l4, ip_len = parsed
    sport, dport, flags = _parse_l4(proto, l4)
    rel = _related_tuple(fam, proto, l4) if related else None
    if rel is not None:
        src, dst, proto, sport, dport = rel
        flags = FLAG_RELATED
    row = np.zeros(N_COLS, dtype=np.uint32)
    row[COL_SRC_IP0:COL_SRC_IP0 + 4] = np.frombuffer(
        src, dtype=">u4").astype(np.uint32)
    row[COL_DST_IP0:COL_DST_IP0 + 4] = np.frombuffer(
        dst, dtype=">u4").astype(np.uint32)
    row[COL_SPORT] = sport
    row[COL_DPORT] = dport
    row[COL_PROTO] = proto
    row[COL_FLAGS] = flags
    row[COL_LEN] = ip_len
    row[COL_FAMILY] = fam
    row[COL_EP] = ep
    row[COL_DIR] = direction
    return row


def read_pcap(path: str, ep: int = 0, direction: int = 0) -> HeaderBatch:
    """Parse a pcap file into a HeaderBatch (non-IP frames are skipped).

    Uses the native C++ parser (cilium_tpu/native) when the toolchain
    is available; the Python path below is the fallback AND the
    reference the native parser is equivalence-tested against."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 24:
        return HeaderBatch(np.zeros((0, N_COLS), dtype=np.uint32))
    from .. import native

    try:
        rows = native.parse_pcap_bytes(data, ep, direction)
    except ValueError:
        raise ValueError(f"{path}: not a pcap file") from None
    if rows is not None:
        return HeaderBatch(rows)
    magic = struct.unpack_from("<I", data, 0)[0]
    if magic == PCAP_MAGIC:
        endian = "<"
    elif magic == PCAP_MAGIC_SWAPPED:
        endian = ">"
    else:
        raise ValueError(f"{path}: not a pcap file (magic {magic:#x})")
    linktype = struct.unpack_from(endian + "I", data, 20)[0]
    rows: List[np.ndarray] = []
    off = 24
    while off + 16 <= len(data):
        _, _, caplen, origlen = struct.unpack_from(endian + "IIII", data, off)
        off += 16
        if off + caplen > len(data):  # truncated record: stop (native
            break                     # parser parity)
        frame = data[off:off + caplen]
        off += caplen
        if linktype == LINKTYPE_ETHERNET:
            if len(frame) < 14:
                continue
            ethertype = struct.unpack_from("!H", frame, 12)[0]
            # skip VLAN tags
            l3off = 14
            while ethertype in (0x8100, 0x88A8) and len(frame) >= l3off + 4:
                ethertype = struct.unpack_from("!H", frame, l3off + 2)[0]
                l3off += 4
            if ethertype not in (ETH_P_IP, ETH_P_IPV6):
                continue
            ip = frame[l3off:]
        elif linktype == LINKTYPE_RAW:
            ip = frame
        else:
            continue
        parsed = _parse_ip(ip)
        if parsed is None:
            continue
        rows.append(build_row(parsed, ep, direction))
    if not rows:
        return HeaderBatch(np.zeros((0, N_COLS), dtype=np.uint32))
    return HeaderBatch(np.stack(rows))


def write_pcap(path: str, batch: HeaderBatch) -> None:
    """Write a HeaderBatch as a LINKTYPE_RAW pcap (synthetic payloads)."""
    out = bytearray()
    out += struct.pack("<IHHiIII", PCAP_MAGIC, 2, 4, 0, 0, 65535,
                       LINKTYPE_RAW)
    for i in range(len(batch)):
        r = batch.data[i]
        fam = int(r[COL_FAMILY])
        proto = int(r[COL_PROTO])
        # declare the batch's LEN in the IP header (truncated capture
        # style: caplen < origlen) so read_pcap round-trips COL_LEN
        if fam == 4:
            total = max(int(r[COL_LEN]), 20 + _l4_len(proto))
            ip = struct.pack("!BBHHHBBH4s4s",
                             0x45, 0, total, i & 0xFFFF, 0, 64, proto, 0,
                             int(r[COL_SRC_IP0 + 3]).to_bytes(4, "big"),
                             int(r[COL_DST_IP0 + 3]).to_bytes(4, "big"))
            origlen = total
        else:
            src = b"".join(int(r[COL_SRC_IP0 + j]).to_bytes(4, "big")
                           for j in range(4))
            dst = b"".join(int(r[COL_DST_IP0 + j]).to_bytes(4, "big")
                           for j in range(4))
            origlen = max(int(r[COL_LEN]), 40 + _l4_len(proto))
            ip = struct.pack("!IHBB16s16s", 0x60000000, origlen - 40,
                             proto, 64, src, dst)
        ip += _l4_bytes(proto, int(r[COL_SPORT]), int(r[COL_DPORT]),
                        int(r[COL_FLAGS]))
        out += struct.pack("<IIII", 0, 0, len(ip), max(len(ip), origlen))
        out += ip
    with open(path, "wb") as f:
        f.write(bytes(out))


def _l4_len(proto: int) -> int:
    if proto == 6:
        return 20
    if proto in (17, 132, 1, 58):
        return 8
    return 0


def _l4_bytes(proto: int, sport: int, dport: int, flags: int) -> bytes:
    if proto == 6:
        return struct.pack("!HHIIBBHHH", sport, dport, 0, 0, 0x50, flags,
                           65535, 0, 0)
    if proto in (17, 132):
        return struct.pack("!HHHH", sport, dport, 8, 0)
    if proto in (1, 58):
        return struct.pack("!BBHI", dport, 0, 0, 0)
    return b""
