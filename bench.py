#!/usr/bin/env python
"""Headline benchmark: policy verdicts/sec on one chip.

BASELINE.md north-star: >= 10M policy verdicts/sec on one TPU v5e chip
over the 10k-identity L3/L4 policy set, <= 1% divergence vs the oracle.

Two phases, one JSON line:

1. **device** — the fused pipeline (ipcache LPM -> conntrack -> policy
   -> ct-create -> events) replaying pre-staged device batches: the
   kernel-rate ceiling (headline metric, matches BASELINE's
   verdicts/s/chip definition).
2. **end_to_end** — the honest number: raw ethernet frames in host
   memory -> native C++ parse -> header tensor -> device_put -> fused
   pipeline -> device event ring (compacted drops/verdicts/sampled
   traces, monitor/ring.py) -> single host drain.  Non-replayed
   traffic (every batch distinct), advancing clock.

   The event-ring architecture mirrors the reference (the kernel
   streams *events* through the perf ring and counts the rest in the
   metricsmap; it does not copy every packet to userspace).  It also
   sidesteps a measured harness artifact: on the tunneled-TPU bench
   host, ANY device->host fetch permanently degrades subsequent
   executions by ~4.5 s each (axon tunnel pathology, measured and
   reported below as d2h_artifact) — so the hot loop must be
   fetch-free, which the ring design is anyway.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline",
"end_to_end": {...}} — extra keys carry the e2e numbers + bottleneck
split.
"""

import json
import time

import numpy as np

# 262144 packets/batch: on the tunneled harness, per-dispatch latency
# dominates the e2e path — doubling the batch from 128k measured
# 16.4M -> 39.8M burst / 8.6M -> 30.3M sustained verdicts/s at
# unchanged h2d bytes/packet
BATCH = 1 << 18


def _pow2_cap(n_events: int) -> int:
    """Smallest power-of-two ring capacity holding ``n_events``
    (EventRing.create asserts 2^k)."""
    return 1 << max(0, int(n_events) - 1).bit_length()
BASELINE_PPS = 10_000_000.0  # north-star target


def paired_legs(baseline_fn, candidate_fn, reps: int = 3) -> dict:
    """The bench "machine weather" convention promoted into tooling
    (ISSUE 11 satellite): run ``baseline_fn``/``candidate_fn``
    INTERLEAVED rep-by-rep with the pair order ALTERNATING per rep
    (whichever leg runs second in a pair reads a few percent faster
    on this box — thermal/cache settling — so a fixed order
    masquerades as a real difference), and report the PER-PAIR ratios
    and their spread alongside the best absolute legs.  A ratio of
    two legs from the SAME pair survives weather a best-vs-best
    ratio does not: a throttle window slows both legs together.

    Each fn returns ``pps`` (float) or ``(pps, extra)``; ``extra``
    of the best rep per side rides the result.  Returns::

        {"baseline_pps", "candidate_pps",          # best-of-reps
         "pairs": [candidate/baseline per rep],    # the honest view
         "ratio_best", "ratio_median", "spread",
         "baseline_extra", "candidate_extra"}
    """
    base_best = cand_best = 0.0
    base_extra = cand_extra = None
    pairs = []
    for rep in range(reps):
        legs = [("b", baseline_fn), ("c", candidate_fn)]
        if rep % 2:
            legs.reverse()
        res = {}
        for name, fn in legs:
            out = fn()
            res[name] = out if isinstance(out, tuple) else (out, None)
        b, be = res["b"]
        c, ce = res["c"]
        pairs.append(c / b if b else None)
        if b > base_best:
            base_best, base_extra = b, be
        if c > cand_best:
            cand_best, cand_extra = c, ce
    ratios = sorted(r for r in pairs if r is not None)
    return {
        "baseline_pps": round(base_best),
        "candidate_pps": round(cand_best),
        "pairs": [None if r is None else round(r, 4) for r in pairs],
        "ratio_best": round(ratios[-1], 4) if ratios else None,
        "ratio_median": (round(ratios[len(ratios) // 2], 4)
                         if ratios else None),
        "spread": (round(ratios[-1] - ratios[0], 4)
                   if ratios else None),
        "baseline_extra": base_extra,
        "candidate_extra": cand_extra,
    }


def bench_device(world, jnp, datapath_step_jit, iters=10):
    # iters 20 -> 10 in r05: the phase now runs in its own BOUNDED
    # subprocess, and its one end-of-phase occupancy fetch pays the
    # tunnel's ~12 s/dispatch first-fetch toll — 74 dispatches keep
    # the phase inside its timeout while the measured per-step time
    # (and so the headline rate) is unchanged.
    from cilium_tpu.datapath.conntrack import ST_FREE, V_STATE

    from cilium_tpu.testing.fixtures import bench_traffic

    rng = np.random.default_rng(0)
    pool = [jnp.asarray(bench_traffic(world, BATCH, rng))
            for _ in range(4)]
    state = world.state
    now = 1_000
    t_warm = time.perf_counter()
    for b in pool:  # warmup: compile + seed steady-state CT
        out, state = datapath_step_jit(state, b, jnp.uint32(now))
    out.block_until_ready()
    warm_dt = time.perf_counter() - t_warm
    # 7 repetitions, MEDIAN as the headline + full envelope: the
    # tunneled harness shows 2-3x run-to-run dispatch variance, and a
    # single sample can misread a faster kernel as a regression
    n_reps = 7
    reps = []
    for _rep in range(n_reps):
        t0 = time.perf_counter()
        for i in range(iters):
            now += 1
            out, state = datapath_step_jit(state, pool[i % 4],
                                           jnp.uint32(now))
        out.block_until_ready()
        reps.append(time.perf_counter() - t0)
    dt = sorted(reps)[n_reps // 2]  # median of 7
    # occupancy WITHOUT a d2h fetch of the table (any fetch poisons
    # subsequent dispatch latency on tunneled hosts): count on device,
    # fetch one scalar at the very end of the whole bench instead.
    occupied = jnp.sum(state.ct.table[:, V_STATE] != ST_FREE)
    detail = {
        "ct_capacity": int(state.ct.capacity),
        "ct_occupied_dev": occupied,  # resolved at print time
        "batch_size": BATCH,
        "iters": iters,
        "warmup_ms": round(warm_dt * 1e3, 1),
        "step_ms": round(dt / iters * 1e3, 3),
        "rep_pps": sorted(round(BATCH * iters / r) for r in reps),
        "roofline": _roofline(dt / iters),
        "note": ("median of 7 reps (tunnel dispatch variance); device "
                 "rate depends on CT capacity + occupancy "
                 "(probe-gather locality)"),
    }
    return BATCH * iters / dt, state, now, detail


def _roofline(step_s: float) -> dict:
    """Modeled HBM traffic of the fused step (bytes/packet, upper
    bound on unique gather/scatter traffic) -> achieved GB/s.  r03
    verdict item: the CT probe was ~2176 B/pkt (two full [16, 17]
    windows) + a 16-step insert loop (~1728 B/pkt); the r04
    fingerprint diet (conntrack.py _probe_fp) cuts both."""
    from cilium_tpu.datapath.conntrack import (N_CAND, N_CAND_INS,
                                               N_PROBE, ROW_WORDS)

    b = {
        "hdr_read": 16 * 4,
        "ct_fp_windows": 2 * N_PROBE * 4,  # fwd+rev fingerprint gathers
        "ct_candidate_rows": 2 * N_CAND * ROW_WORDS * 4,
        "ct_insert_gathers": N_CAND_INS * (ROW_WORDS + 10) * 4,
        "ct_insert_scatter": ROW_WORDS * 4 + 4,  # one winner row + fp
        "ct_refresh_rmw": 32,
        "policy_gathers": 5 * 4,  # ep/proto/class/verdict/ct_proxy
        "lpm_gathers": 3 * 4,
        "out_write": 24,
        "metrics_scatter": 8,
    }
    per_pkt = sum(b.values())
    old_per_pkt = (16 * 4 + 2 * N_PROBE * ROW_WORDS * 4
                   + N_PROBE * (ROW_WORDS + 10) * 4 + ROW_WORDS * 4
                   + 32 + 20 + 12 + 24 + 8)
    return {
        "modeled_bytes_per_pkt": per_pkt,
        "breakdown": b,
        "r03_kernel_bytes_per_pkt": old_per_pkt,
        "traffic_ratio": round(old_per_pkt / per_pkt, 2),
        "modeled_bytes_per_step": per_pkt * BATCH,
        "achieved_gb_per_s": round(per_pkt * BATCH / step_s / 1e9, 1),
        "note": ("upper bound: counts every gather/scatter as unique "
                 "HBM traffic; v5e-class HBM is ~819 GB/s"),
    }


def bench_end_to_end(world, state, now0, jax, jnp, datapath_step_jit,
                     iters=16, sustain_iters=24):
    # sustain_iters=24 at the 256k batch moves the same packet volume
    # as r03's 48 batches of 128k — the sustained claim holds at
    # bounded wall time when the tunnel is in its degraded mode
    """Host frames -> device verdicts + event ring; one drain at end.

    The ingest path is the PACKED pipeline (core/packets.py PACKED_*):
    native C++ parses raw frames straight into reused 16 B/packet
    transfer buffers (page-registration-cache friendly), the device
    unpacks inside the fused serve step (datapath + ring compaction,
    one dispatch per batch).  The wide 64 B/packet format measured
    ~210 MB/s h2d on the tunneled bench host = a 3.3M pps ceiling;
    packed quadruples it — that is the r02->r03 end-to-end fix."""
    from cilium_tpu import native
    from cilium_tpu.core.ingest import frames_from_batch
    from cilium_tpu.monitor.ring import (EventRing, ring_drain,
                                         serve_step_packed_jit)
    from cilium_tpu.testing.fixtures import steady_flow_pool, steady_traffic

    rng = np.random.default_rng(1)
    # bounded flow pool: replaying it once establishes the steady state
    # (95% established / 5% new / 2% scan-drops thereafter)
    pool = steady_flow_pool(world, 2 * BATCH, rng)
    # distinct traffic every iteration — nothing replays
    n_bufs = max(iters, sustain_iters)
    frame_bufs = [frames_from_batch(steady_traffic(pool, BATCH, rng))
                  for _ in range(n_bufs)]
    wire_bytes = sum(len(b) for b in frame_bufs[:iters])

    # rotating packed transfer buffers: reuse keeps host pages warm and
    # registered with the transfer runtime (measured ~5x h2d win over
    # fresh allocations on the tunneled host)
    out_pool = [np.empty((BATCH + 64, 4), dtype=np.uint32)
                for _ in range(4)]

    # parse-stage rate alone (for the bottleneck split); warm first so
    # the one-time g++ compile/dlopen of the native lib isn't timed
    use_native = native.available()

    def parse_packed(buf, i):
        if use_native:
            rows, _, _ = native.parse_frames_packed(buf, out_pool[i % 4])
        else:
            rows, _, _ = native.parse_frames_packed_py(buf,
                                                       out_pool[i % 4])
        return rows

    parse_packed(frame_bufs[0], 0)
    t0 = time.perf_counter()
    for i, buf in enumerate(frame_bufs[:8]):
        rows0 = parse_packed(buf, i)
    parse_dt = time.perf_counter() - t0
    parse_pps = 8 * BATCH / parse_dt

    # ring sized FROM the run length (~7.5k compacted events/batch:
    # 5% new-flow verdicts + 2% drops + sampled traces; bound by
    # BATCH/16) so the zero-loss claim holds for any iters/
    # sustain_iters a caller passes; both the timed and sustained runs
    # (plus one warmup append) land in the ring before the drain
    n_appends = iters + n_bufs + 1
    cap = _pow2_cap(n_appends * (BATCH // 16))
    ring = EventRing.create(cap)
    # warmup: establish the pool's flows in CT + compile the e2e shapes
    # — NO host fetch (see module doc)
    for chunk in pool.reshape(2, BATCH, -1):
        out, state = datapath_step_jit(state, jnp.asarray(chunk),
                                       jnp.uint32(now0))
    zero = jnp.uint32(0)
    state, ring = serve_step_packed_jit(
        state, ring, jax.device_put(rows0), jnp.uint32(now0), zero,
        zero, zero)
    ring.cursor.block_until_ready()

    def run(bufs, base):
        t0 = time.perf_counter()
        nonlocal state, ring
        for i, buf in enumerate(bufs):
            rows = parse_packed(buf, i)  # host: native C++, reused buf
            dev = jax.device_put(rows)  # h2d (async, 16 B/packet)
            state, ring = serve_step_packed_jit(
                state, ring, dev, jnp.uint32(base + i), jnp.uint32(i),
                zero, zero)
        ring.cursor.block_until_ready()
        return time.perf_counter() - t0

    dt = run(frame_bufs[:iters], now0 + 1)
    # sustained: a longer run past any transfer-buffer burst window
    dt_sustained = run(frame_bufs, now0 + 1 + iters)

    # The FIRST d2h fetch of the process pays a one-time tunnel sync
    # cost that scales with the number of prior dispatches (~4s per
    # executed batch on this harness; measured r02/r03) — absorb it
    # with a scalar fetch so the drain below shows the monitor's
    # STEADY-STATE cadence (sub-second; on directly-attached TPUs the
    # sync artifact does not exist at all).
    t0 = time.perf_counter()
    _ = np.asarray(state.metrics)
    sync_dt = time.perf_counter() - t0

    # the monitor's drain: fetch + decode the ring, outside the hot loop
    t0 = time.perf_counter()
    events, total, lost = ring_drain(ring)
    drain_dt = time.perf_counter() - t0

    return {
        "verdicts_per_sec": round(BATCH * iters / dt),
        "vs_target_10M": round(BATCH * iters / dt / BASELINE_PPS, 3),
        "sustained_pps": round(BATCH * len(frame_bufs) / dt_sustained),
        "sustained_batches": len(frame_bufs),
        "wire_gbps": round(wire_bytes * 8 / dt / 1e9, 2),
        "parse_stage_pps": round(parse_pps),
        "h2d_bytes_per_pkt": 16,
        "native_ingest": use_native,
        "batches": iters,
        "batch_size": BATCH,
        "events_streamed": int(total),
        "events_lost": int(lost),
        "first_fetch_sync_ms": round(sync_dt * 1e3, 1),
        "ring_drain_ms": round(drain_dt * 1e3, 1),
        "ring_drain_events_per_sec": round(int(total) / drain_dt)
        if drain_dt > 0 else None,
    }, state


def bench_end_to_end_wide(world, state, now0, jax, jnp, iters=12):
    """The WIDE path end-to-end: 64 B/packet header rows carrying the
    semantics the packed 16 B format declares out of scope — IPv6
    flows (TCAM LPM, 128-bit CT keys) and ICMP-error RELATED rows
    (embedded-tuple conntrack association).  r03 verdict: the
    v6/RELATED-correct path had NO perf claim; this block is it."""
    from cilium_tpu.core.ingest import parse_frames, wide_frames_from_batch
    from cilium_tpu.core.packets import COL_FAMILY, COL_FLAGS, FLAG_RELATED
    from cilium_tpu.monitor.ring import (EventRing, ring_drain,
                                         serve_step_jit)
    from cilium_tpu.testing.fixtures import wide_flow_pool, wide_traffic

    rng = np.random.default_rng(4)
    pool = wide_flow_pool(world, BATCH, rng)
    batches = [wide_traffic(pool, BATCH, rng) for _ in range(iters)]
    frame_bufs = [wide_frames_from_batch(b) for b in batches]
    wire_bytes = sum(len(b) for b in frame_bufs)
    frac_v6 = float(np.mean([np.mean(b[:, COL_FAMILY] == 6)
                             for b in batches]))
    frac_rel = float(np.mean([np.mean((b[:, COL_FLAGS] & FLAG_RELATED)
                                      != 0) for b in batches]))

    # rotating WIDE transfer buffers: same page-registration-cache
    # trick as the packed path — without it the 16 MB/batch h2d of
    # fresh numpy arrays collapses to ~1.5 MB/s on the tunneled host
    out_pool = [np.empty((BATCH + 64, 16), dtype=np.uint32)
                for _ in range(4)]

    # parse-stage rate alone (mixed v4/v6/ICMP-error frames)
    parse_frames(frame_bufs[0], out=out_pool[0])
    t0 = time.perf_counter()
    for i, buf in enumerate(frame_bufs[:4]):
        rows0 = parse_frames(buf, out=out_pool[i % 4])
    parse_pps = 4 * BATCH / (time.perf_counter() - t0)

    cap = _pow2_cap((iters + 2) * (BATCH // 8))
    # warmup: establish the dual-stack pool + compile the wide shapes
    # (throwaway ring: the pool replay is one solid batch of NEW-flow
    # verdict events that would swamp the measured ring)
    ring = EventRing.create(cap)
    state, ring = serve_step_jit(state, ring, jnp.asarray(pool),
                                 jnp.uint32(now0), jnp.uint32(0))
    state, ring = serve_step_jit(state, ring,
                                 jax.device_put(rows0),
                                 jnp.uint32(now0), jnp.uint32(0))
    ring.cursor.block_until_ready()
    ring = EventRing.create(cap)

    t0 = time.perf_counter()
    for i, buf in enumerate(frame_bufs):
        rows = parse_frames(buf, out=out_pool[i % 4])  # 64 B/pkt rows
        dev = jax.device_put(rows)
        state, ring = serve_step_jit(state, ring, dev,
                                     jnp.uint32(now0 + 1 + i),
                                     jnp.uint32(i))
    ring.cursor.block_until_ready()
    dt = time.perf_counter() - t0

    # absorb the tunnel d2h debt accrued over this phase's dispatches
    # with a scalar fetch, so drain_ms reports the DECODE, not the
    # harness artifact (see bench_end_to_end)
    t0 = time.perf_counter()
    _ = np.asarray(state.metrics)
    sync_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    events, total, lost = ring_drain(ring)
    drain_dt = time.perf_counter() - t0
    return {
        "verdicts_per_sec": round(BATCH * iters / dt),
        "vs_target_10M": round(BATCH * iters / dt / BASELINE_PPS, 3),
        "phase_sync_ms": round(sync_dt * 1e3, 1),
        "h2d_bytes_per_pkt": 64,
        "frac_v6": round(frac_v6, 4),
        "frac_related": round(frac_rel, 4),
        "parse_stage_pps": round(parse_pps),
        "wire_gbps": round(wire_bytes * 8 / dt / 1e9, 2),
        "batches": iters,
        "events_streamed": int(total),
        "events_lost": int(lost),
        "ring_drain_ms": round(drain_dt * 1e3, 1),
    }, state


def bench_ring_steady_state(world, state, now0, jax, jnp, batches=24,
                            drain_every=4, ring_cap=None,
                            fresh_frac=32):
    """Sustained monitor-plane cadence with OVERLAPPED drains: the
    host fetches window N-1 (AsyncRingDrainer, monitor/ring.py) while
    the device steps window N — the production double-buffered drain
    loop, replacing r04's blocking per-window fetch (drain_ms_median
    10.3 s of queued-dispatch sync debt on the tunneled harness).
    Loss accounting stays per window: every window starts on a fresh
    ring, so its fetched cursor is its append count and loss is
    ``max(0, appended - capacity)``.

    Traffic is generated ON DEVICE from a pre-staged flow pool (one
    gather + sport churn per batch, fused into the serve step): this
    phase measures the MONITOR plane — verdict + ring append +
    concurrent drain — and host->device ingest is the e2e phases' job.
    On the tunneled harness the two cannot be measured together in one
    process (measured r02-r05: a d2h fetch pays ~1 s per intervening
    4 MB h2d put, an artifact absent on directly-attached TPUs);
    1/``fresh_frac`` of each batch gets rotating source ports, so CT
    sees a steady NEW-flow churn and the ring a production event mix.
    """
    from functools import partial

    from cilium_tpu.core.packets import COL_SPORT
    from cilium_tpu.datapath.verdict import datapath_step
    from cilium_tpu.monitor.ring import (AsyncRingDrainer, ring_append,
                                         serve_step_jit)
    from cilium_tpu.testing.fixtures import steady_flow_pool

    if ring_cap is None:
        # a drain window carries ~5% of its packets as events (3% NEW
        # verdicts at fresh_frac=32 + 2% scan drops + sampled traces);
        # the ring sizes at 6.25% of the window — headroom without
        # paying double the drain bandwidth for padding
        ring_cap = _pow2_cap(drain_every * (BATCH // 16))
    rng = np.random.default_rng(5)
    pool = jnp.asarray(steady_flow_pool(world, 2 * BATCH, rng))
    fresh_n = BATCH // fresh_frac

    @partial(jax.jit, donate_argnums=(0, 1),
             static_argnames=("trace_sample",))
    def serve_gen_step(st, ring, pool, i, now, trace_sample=1024):
        # batch i = a rotating window of the pool (established flows)
        # + a slice of never-seen source ports (NEW churn)
        idx = (i * jnp.uint32(40503) + jnp.arange(BATCH,
                                                  dtype=jnp.uint32)
               ) % jnp.uint32(pool.shape[0])
        hdr = pool[idx.astype(jnp.int32)]
        fresh_sport = (jnp.uint32(33000)
                       + (i * jnp.uint32(fresh_n)
                          + jnp.arange(fresh_n, dtype=jnp.uint32))
                       % jnp.uint32(30000))
        hdr = hdr.at[:fresh_n, COL_SPORT].set(fresh_sport)
        out, st = datapath_step(st, hdr, now)
        ring = ring_append(ring, out, i, trace_sample=trace_sample)
        return st, ring

    zero = jnp.uint32(0)
    drainer = AsyncRingDrainer(ring_cap)
    # establish the POOL's flows first (throwaway ring): the steady
    # state this phase measures is 95% established traffic — without
    # this, the first windows are solid NEW-verdict floods and the
    # "loss" is a warmup artifact, not a drain-cadence property
    ring = drainer.fresh()
    state, ring = serve_step_jit(state, ring, pool,
                                 jnp.uint32(now0), zero)
    state, ring = serve_gen_step(state, ring, pool, zero,
                                 jnp.uint32(now0))
    ring.cursor.block_until_ready()
    # absorb the accumulated tunnel warmup debt off the clock (the
    # first d2h of a process pays a fixed cost scaling with uploaded
    # state on this harness)
    t0 = time.perf_counter()
    _ = np.asarray(state.metrics)
    sync_ms = round((time.perf_counter() - t0) * 1e3, 1)
    # raw d2h bandwidth with NO queued dispatches (we just synced):
    # the denominator that shows whether the drain transfer is
    # link-optimal or leaving bandwidth on the table
    t0 = time.perf_counter()
    _ = np.asarray(ring.buf)
    raw_dt = time.perf_counter() - t0
    raw_mbps = ring_cap * 4 * 2 / raw_dt / 1e6
    ring = drainer.fresh()

    collect_times = []
    stall_times = []
    t_run = time.perf_counter()
    for i in range(batches):
        state, ring = serve_gen_step(state, ring, pool,
                                     jnp.uint32(1 + i),
                                     jnp.uint32(now0 + 1 + i))
        if (i + 1) % drain_every == 0:
            # collect window N-1 (already streamed to host while this
            # window was stepping), then hand the filled ring to the
            # async fetch and keep serving on a fresh one
            t0 = time.perf_counter()
            drainer.collect()
            collect_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            ring = drainer.swap(ring)
            stall_times.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    drainer.collect()  # the last in-flight window
    collect_times.append(time.perf_counter() - t0)
    dt = time.perf_counter() - t_run
    drained_mb = drainer.windows * ring_cap * 8 / 1e6
    med_collect = sorted(collect_times)[len(collect_times) // 2]
    med_stall = sorted(stall_times)[len(stall_times) // 2]
    # the DESIGN's steady-state cost per window is the transfer
    # (collect) overlapped with the window's steps; the swap stall is
    # the tunnel's queued-dispatch flush (measured r05: ~10 s per
    # queued dispatch after the process's first d2h, absent on
    # directly-attached TPUs).  Report both and the stall-corrected
    # projection so the artifact cannot masquerade as the design.
    window_pkts = drain_every * BATCH
    ring_bytes = ring_cap * 8
    return {
        "sustained_pps_with_drains": round(BATCH * batches / dt),
        "projected_pps_direct_attach": round(
            window_pkts / max(med_collect, 1e-6)),
        # the DESIGN numbers: what the drain costs per packet on the
        # wire, and the rate any given host link sustains.  8 B/event
        # x ~5% event mix = ~0.4 B/pkt; the tunnel's ~4 MB/s d2h is
        # the only reason the projection above sits in the MPps range
        # (PCIe-class links are 3 orders wider).
        "drain_bytes_per_pkt": round(ring_bytes / window_pkts, 3),
        "raw_d2h_mbps": round(raw_mbps, 2),
        # collect (transfer + decode, overlapped with compute) vs a
        # BLOCKING raw fetch of the same bytes: > 1 means the
        # double-buffered path beats a synchronous fetch outright;
        # it is NOT a link-utilization fraction (the numerator
        # includes decode, the raw fetch includes per-transfer
        # latency)
        "collect_vs_raw_fetch_ratio": round(
            (ring_bytes / max(med_collect, 1e-6) / 1e6) / raw_mbps, 3),
        "projected_pps_at_1GBps_link": round(
            window_pkts / (ring_bytes / 1e9)),
        "batches": batches,
        "drain_every": drain_every,
        "ring_capacity": ring_cap,
        "windows_drained": int(drainer.windows),
        "events_drained": int(drainer.events),
        "window_lost": int(drainer.lost),
        "fresh_flow_frac": round(1 / fresh_frac, 3),
        "drained_mb": round(drained_mb, 1),
        "drain_transfer_ms_median": round(med_collect * 1e3, 1),
        "tunnel_stall_ms_median": round(med_stall * 1e3, 1),
        "pre_phase_sync_ms": sync_ms,
        "note": ("double-buffered drain: collect(window N-1) + async "
                 "swap while window N steps; per-window loss "
                 "accounting on a bounded ring (8 B/event packed "
                 "wire format); traffic generated on device from a "
                 "pre-staged pool — ingest is the e2e phases' "
                 "measurement.  sustained_pps includes the tunnel's "
                 "queued-dispatch flush stall at each swap (a harness "
                 "artifact, see tunnel_stall_ms_median); "
                 "projected_pps_direct_attach = window packets over "
                 "the measured drain TRANSFER time, the number the "
                 "same loop is bounded by without the tunnel"),
    }, state


def bench_full_readback(world, state, now0, jax, jnp,
                        datapath_step_jit, iters=2):
    """The naive path (full out tensor fetched per batch) — measures
    the harness's d2h artifact; runs LAST because the first fetch
    permanently degrades this process's executions (~4.5s each on the
    tunneled bench host; sub-ms on directly-attached TPUs)."""
    from cilium_tpu.core.ingest import frames_from_batch, parse_frames
    from cilium_tpu.testing.fixtures import bench_traffic

    rng = np.random.default_rng(2)
    bufs = [frames_from_batch(bench_traffic(world, BATCH, rng))
            for _ in range(iters)]
    t0 = time.perf_counter()
    for i, buf in enumerate(bufs):
        rows = parse_frames(buf)
        out, state = datapath_step_jit(state, jax.device_put(rows),
                                       jnp.uint32(now0 + i))
        np.asarray(out)  # full 24B/pkt readback
    dt = time.perf_counter() - t0
    return {
        "verdicts_per_sec": round(BATCH * iters / dt),
        "note": "full per-packet readback; dominated by the harness "
                "d2h artifact on tunneled TPUs",
    }


def bench_l7(batch: int = 4096, iters: int = 24, n_exact: int = 192,
             n_regex: int = 16) -> dict:
    """Eval config #4 (wrk2-style): HTTP request verdicts through the
    L7 proxy — featurize + device match tensors + access records, the
    full per-request path.  The reference config drives Envoy+proxylib
    at 10k RPS; `vs_wrk2_10k` scores against that rate.

    r03 verdict: 5 rules + 63% denies exercised mostly the cheap deny
    path.  Now: ``n_exact`` literal rules (device tensors) +
    ``n_regex`` regex rules (host fallback), and the request mix
    reports how often the per-request Python fallback actually runs —
    the real bound for non-admitted traffic."""
    from cilium_tpu.policy.api import L7Rules
    from cilium_tpu.proxy import L7Proxy

    rules = [{"method": ("GET", "POST", "PUT", "DELETE")[i % 4],
              "path": f"/api/v{i % 3}/resource{i}"}
             for i in range(n_exact)]
    rules += [{"method": "GET", "path": f"/static/{i}/.*"}
              for i in range(n_regex)]
    l7 = L7Rules.from_dict({"http": rules})
    proxy = L7Proxy()
    proxy.update([type("P", (), {"redirects": [(10000, "bench", l7)]})()])
    rng = np.random.default_rng(3)
    reqs = []
    for _ in range(batch):
        r = rng.random()
        if r < 0.70:  # admitted by a device-tensor literal rule
            i = int(rng.integers(0, n_exact))
            reqs.append({"method": ("GET", "POST", "PUT", "DELETE")[i % 4],
                         "path": f"/api/v{i % 3}/resource{i}",
                         "host": "db.svc"})
        elif r < 0.85:  # admitted only by a regex rule (host fallback)
            i = int(rng.integers(0, n_regex))
            reqs.append({"method": "GET", "path": f"/static/{i}/app.js",
                         "host": "db.svc"})
        else:  # denied (still pays the fallback scan before the 403)
            reqs.append({"method": "DELETE", "path": "/etc/passwd",
                         "host": "db.svc"})
    proxy.handle_http(10000, reqs)  # warm/compile
    proxy.requests_total = proxy.requests_denied = 0
    proxy.host_fallback_checked = proxy.host_fallback_allowed = 0
    t0 = time.perf_counter()
    for _ in range(iters):
        proxy.handle_http(10000, reqs)
    dt = time.perf_counter() - t0
    rps = batch * iters / dt
    return {
        "requests_per_sec": round(rps),
        "vs_wrk2_10k": round(rps / 10_000.0, 1),
        "n_rules": n_exact + n_regex,
        "n_regex_rules": n_regex,
        "denied_frac": round(proxy.requests_denied
                             / proxy.requests_total, 3),
        "host_fallback_frac": round(proxy.host_fallback_checked
                                    / proxy.requests_total, 3),
        "host_fallback_hit_frac": round(proxy.host_fallback_allowed
                                        / max(proxy.host_fallback_checked,
                                              1), 3),
        "batch": batch,
    }


def bench_l7_redirect(batch=1024, iters=6, reps=3) -> dict:
    """The ``l7_redirect`` rung (ISSUE 16): paired-leg redirect
    overhead through LIVE serving.  Baseline leg serves SYN traffic
    against a plain L4 allow on port 80; the candidate leg serves the
    IDENTICAL traffic shape against the same policy WITH an HTTP rule
    — every row then verdicts REDIRECT, emits its verdict event, and
    detours through the L7 worker pool (parse + per-rule verdict),
    and the candidate's wall clock includes waiting for the pool to
    drain what the leg submitted.  The paired ratio is the honest
    cost of making REDIRECT a real serving outcome; both legs ride
    :func:`paired_legs` so machine weather cancels per pair."""
    import ipaddress

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY, COL_FLAGS,
                                         COL_LEN, COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS, TCP_SYN)

    def build(with_l7):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 16,
                                flow_ring_capacity=1 << 13,
                                serving_bucket_ladder=(batch,),
                                serving_queue_depth=1 << 14))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        tp = {"ports": [{"port": "80", "protocol": "TCP"}]}
        if with_l7:
            tp["rules"] = {"http": [{"method": "GET"}]}
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                "toPorts": [tp]}],
        }])
        d.start_serving(ring_capacity=1 << 13, trace_sample=0,
                        drain_every=1)
        return d, db.id

    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))
    counters = {"base": 0, "redir": 0}

    def rows_for(n, key, ep):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        start = counters[key]
        counters[key] += n
        # fresh sport per row: every packet is a NEW flow, so each
        # redirect verdict emits its event and detours the pool —
        # the exact path whose overhead this rung defends
        rows[:, COL_SPORT] = 1024 + (start + np.arange(n)) % 60000
        rows[:, COL_DPORT] = 80
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_SYN
        rows[:, COL_LEN] = 64
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = ep
        return rows

    d_base, ep_b = build(False)
    d_red, ep_r = build(True)
    try:
        # warm both executables (same bucket shape, but the first
        # dispatch of each daemon pays compile)
        d_base.serve_batch(rows_for(batch, "base", ep_b))
        d_red.serve_batch(rows_for(batch, "redir", ep_r))

        def leg(d, ep, key):
            def run():
                t0 = time.perf_counter()
                for _ in range(iters):
                    d.serve_batch(rows_for(batch, key, ep))
                # the candidate pays its detour in full: wall time
                # includes the pool draining this leg's tasks (the
                # baseline's plane never sees a row — no-op)
                plane = d._l7plane
                if plane is not None:
                    deadline = time.monotonic() + 30.0
                    while plane.pool.pending \
                            and time.monotonic() < deadline:
                        time.sleep(0.0005)
                return batch * iters / (time.perf_counter() - t0)
            return run

        out = paired_legs(leg(d_base, ep_b, "base"),
                          leg(d_red, ep_r, "redir"), reps=reps)
        st = d_red.stop_serving()
        d_base.stop_serving()
        out["l7"] = st.get("l7")
        out["batch"] = batch
        out["packets_per_leg"] = batch * iters
        return out
    finally:
        d_base.shutdown()
        d_red.shutdown()


def _run_l7_phase() -> None:
    """--l7: the L7 proxy-plane phase standalone (one JSON line).
    Also writes BENCH_l7.json next to this file — schema-checked by
    the CTA012 machinery (importable ``check_bench`` in
    ``cilium_tpu.analysis.proxy_lint``)."""
    import os

    from cilium_tpu.proxy import registry as l7registry

    redirect = bench_l7_redirect()
    out = {
        "schema": "bench-l7-v1",
        # paired-leg redirect overhead: candidate (redirect + pool
        # drain) over baseline (plain L4 allow), same traffic shape
        "redirect_overhead": redirect,
        # per-plugin parse+verdict percentiles recorded by the
        # candidate leg's workers through the registry seam
        "parse_latency_by_plugin": l7registry.latency_snapshot(),
        # the offline proxy microbench rides along (eval config #4)
        "offline_http": bench_l7(),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_l7.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def bench_socket_lb(n_services=512, iters=9) -> dict:
    """Socket-LB delta (SURVEY §2a bpf_sock row): per-packet LB cost
    on ESTABLISHED traffic, flow-cached probe (service/socklb.py) vs
    the per-packet [N, S] frontend compare + Maglev (lb_stage)."""
    import jax
    import jax.numpy as jnp

    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_FAMILY, COL_PROTO,
                                         COL_SPORT, COL_SRC_IP3,
                                         N_COLS)
    from cilium_tpu.service import ServiceManager
    from cilium_tpu.service.socklb import SockLBTable, socklb_stage_jit

    m = ServiceManager()
    for i in range(n_services):
        vip = f"172.16.{i // 256}.{i % 256}"
        m.upsert(f"svc{i}", f"{vip}:80",
                 [f"10.1.{i // 256}.{i % 256}:8080",
                  f"10.2.{i // 256}.{i % 256}:8080"])
    t = m.tensors()
    rng = np.random.default_rng(11)
    hdr = np.zeros((BATCH, N_COLS), dtype=np.uint32)
    hdr[:, COL_FAMILY] = 4
    hdr[:, COL_SRC_IP3] = rng.integers(1, 2**31, BATCH)
    svc_rows = rng.random(BATCH) < 0.5
    vip_ips = np.asarray(t.svc_ip)
    hdr[:, COL_DST_IP3] = np.where(
        svc_rows, rng.choice(vip_ips, BATCH),
        rng.integers(1, 2**31, BATCH))
    hdr[:, COL_DPORT] = np.where(svc_rows, 80,
                                 rng.integers(1, 65535, BATCH))
    hdr[:, COL_SPORT] = rng.integers(1024, 65535, BATCH)
    hdr[:, COL_PROTO] = 6
    jhdr = jnp.asarray(hdr)
    now = jnp.uint32(100)

    # LOOP stage iterations inside ONE dispatch (lax.fori_loop): on
    # the tunneled harness per-dispatch overhead is ~20-30 ms, so any
    # per-dispatch timing of a sub-ms stage measures the harness (r05
    # measured both paths pinned at the dispatch floor and reported a
    # nonsense speedup <1).  One dispatch of LOOP iterations is the
    # compute-only comparison.
    LOOP = 32
    from functools import partial

    from cilium_tpu.service import lb_stage
    from cilium_tpu.service.socklb import CONNECT_CAP, socklb_stage

    # `t` rides as an ARGUMENT: closing over it inlines the Maglev
    # table as an HLO constant, and past ~2k services the serialized
    # program exceeds the tunnel's remote-compile request limit
    @jax.jit
    def brute_loop(t, hdr0):
        # thread hdr through so iterations cannot be hoisted (the
        # stage is pure); post-rewrite rows still pay the same [N, S]
        # compare, which is the cost being measured
        def body(_i, h):
            h2, _hits, _nobe = lb_stage(t, h)
            return h2
        return jax.lax.fori_loop(0, LOOP, body, hdr0)

    @partial(jax.jit, donate_argnums=0)
    def cached_loop(tbl, t, hdr0):
        # fold the rewritten header + hit mask into a carried scalar:
        # without a live use, XLA dead-code-eliminates the DNAT
        # rewrite selects/scatters from the cached path while the
        # brute loop (which threads h) pays them — an unfair compare
        def body(_i, carry):
            tb, acc = carry
            h2, hits, _nobe, tb2 = socklb_stage(tb, t, hdr0, now)
            return tb2, (acc + h2[:, COL_DST_IP3].sum()
                         + h2[:, COL_DPORT].sum()
                         + hits.sum().astype(jnp.uint32))
        return jax.lax.fori_loop(0, LOOP, body,
                                 (tbl, jnp.uint32(0)))

    def median_time(fn, reps=iters):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            ts.append((time.perf_counter() - t0) / LOOP)
        return sorted(ts)[len(ts) // 2]

    jax.block_until_ready(brute_loop(t, jhdr))  # compile
    dt_compare = median_time(lambda: brute_loop(t, jhdr))

    tbl = SockLBTable.create(1 << 20)
    box = [tbl]
    _, _, _, box[0] = socklb_stage_jit(box[0], t, jhdr, now)  # compile
    # warm the flow cache in connect-buffer-sized slices: a single
    # full-batch step has BATCH >> CONNECT_CAP misses and takes the
    # resolve-only fallback (nothing caches) — production flows
    # arrive gradually, which the sliced warmup models
    for i in range(0, BATCH, CONNECT_CAP):
        _h, hit, _nb, box[0] = socklb_stage_jit(
            box[0], t, jhdr[i:i + CONNECT_CAP], now)
    _h, hit, _nb, box[0] = socklb_stage_jit(box[0], t, jhdr, now)
    jax.block_until_ready(hit)  # cache now holds every flow

    box[0], _acc = cached_loop(box[0], t, jhdr)  # compile
    jax.block_until_ready(box[0].fp)

    def cached_step():
        box[0], acc = cached_loop(box[0], t, jhdr)
        return acc

    dt_cached = median_time(cached_step)
    return {
        "n_services": n_services,
        "batch": BATCH,
        "looped_iterations": LOOP,
        "per_packet_compare_pps": round(BATCH / dt_compare),
        "flow_cached_pps": round(BATCH / dt_cached),
        "note": ("established-path LB: connect-time resolution cached "
                 "per flow (bpf_sock analogue) vs per-packet [N,S] "
                 "frontend compare + Maglev.  The cached path is O(1) "
                 "in the service count (probe window + candidate "
                 "gathers); the compare is O(S) per packet — run with "
                 "several n_services to see the flat-vs-linear split. "
                 "The semantic contract is affinity either way: "
                 "cached flows keep their backend across backend-set "
                 "changes."),
    }


def bench_socket_lb_scaling(counts=(512, 4096)) -> dict:
    """Socket-LB at several service counts: the flow cache's flat
    cost vs the per-packet compare's O(S) growth (the design claim a
    single-point speedup number cannot carry)."""
    points = [bench_socket_lb(n_services=s, iters=5) for s in counts]
    return {
        "points": [{k: p[k] for k in ("n_services",
                                      "per_packet_compare_pps",
                                      "flow_cached_pps")}
                   for p in points],
        "note": points[-1]["note"],
    }


def bench_encryption(mb: int = 8, iters: int = 9) -> dict:
    """Transparent-encryption throughput (host-side, no TPU): seal +
    open of batch-sized buffers through the native ChaCha20-Poly1305
    (native/crypto.cpp).  The unit of encryption is the BATCH (one
    AEAD per batch, DIVERGENCES #24), so GiB/s here bounds the
    node-to-node encrypted plane; at 16 B/packet packed frames,
    1 GiB/s ~ 67M packets/s.  Without the native library (no g++)
    the pure-Python fallback is orders of magnitude slower, so the
    buffer shrinks to keep the phase bounded."""
    from cilium_tpu.encryption import EncryptedChannel, NodeKeypair
    from cilium_tpu.native import crypto

    if not crypto.available():
        mb, iters = 1, 3  # python-fallback path: keep it bounded

    a, b = NodeKeypair(), NodeKeypair()
    ca = EncryptedChannel(a, b.public)
    cb = EncryptedChannel(b, a.public)
    buf = bytes(np.random.default_rng(5).bytes(mb << 20))
    ts_seal, ts_open = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        frame = ca.seal(buf)
        ts_seal.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        out = cb.open(frame)
        ts_open.append(time.perf_counter() - t0)
        assert out == buf
    seal_gbps = (mb / (1 << 10)) / sorted(ts_seal)[len(ts_seal) // 2]
    open_gbps = (mb / (1 << 10)) / sorted(ts_open)[len(ts_open) // 2]
    return {
        "native": crypto.available(),
        "buffer_mb": mb,
        "seal_gb_per_s": round(seal_gbps, 3),
        "open_gb_per_s": round(open_gbps, 3),
        "packed_pps_bound": round(min(seal_gbps, open_gbps)
                                  * (1 << 30) / 16),
    }


def _run_socklb_phase() -> None:
    """--socklb: the socket-LB scaling phase standalone (one JSON
    line)."""
    print(json.dumps(bench_socket_lb_scaling()))


def bench_serving(offline_batches=16, paced_seconds=2.0) -> dict:
    """Serving front-end phase: sustained verdicts/sec under Poisson
    arrivals through the admission queue + adaptive batcher
    (cilium_tpu/serving) vs the OFFLINE serve_batch ceiling (perfect
    pre-assembled full buckets) — the first entry in the BENCH
    trajectory.  Deliberately bounded and CPU-runnable
    (JAX_PLATFORMS=cpu): the number it defends is the front end's
    OVERHEAD RATIO (serving_vs_offline), which is platform-relative;
    absolute pps is whatever the backend does.

    The ingress side runs the PACKED 16 B/packet h2d path (PR 2
    tentpole): BENCH_serving.json records the packed-vs-wide batch
    split and measured h2d bytes/packet alongside the ratio.  Both
    sides are measured 3x INTERLEAVED and compared best-of-3 —
    single-shot CPU wall timings swing +-15%, and the ratio must
    measure the front end, not scheduling weather.

    Since PR 5 the overload legs run with EVENT DECODE ENABLED: the
    headline ``sustained_pps`` at the production-default
    ``trace_sample=1024`` (PR 4 measured with events disabled
    outright), plus a dedicated DECODE-UNDER-LOAD leg
    (``sustained_pps_decode``, ``trace_sample=1``: every admitted
    packet appends a ring event, every event is
    fetched/decoded/joined/emitted on the async event plane's
    worker).  ``d2h_bytes_per_event`` + ``event_join_lag_us`` come
    from that leg's best rep, and ``d2h_scaling`` contrasts the
    occupancy-bounded gather against the legacy full-capacity copy
    at LOW occupancy, where the diet matters."""
    import ipaddress

    import jax

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY, COL_FLAGS,
                                         COL_LEN, COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS, TCP_ACK)

    LADDER = (512, 2048, 8192)
    # superbatch_k=8 (ISSUE 11): the overload legs run the K-batch
    # fused dispatch as the production default — the drain loop takes
    # what is queued, so batches-per-dispatch floats with queue depth
    # and the dedicated bench_superbatch pair pins it at K
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 16,
                            flow_ring_capacity=1 << 14,
                            serving_queue_depth=1 << 15,
                            serving_bucket_ladder=LADDER,
                            serving_max_wait_us=2000.0,
                            serving_superbatch_k=8))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "web"}}],
                     "toPorts": [{"ports": [{"port": "5432",
                                             "protocol": "TCP"}]}]}],
    }])
    rng = np.random.default_rng(7)
    B = LADDER[-1]
    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))
    # bounded flow universe: after warmup the mix is established
    # traffic (trace_sample=0 keeps it off the event ring)
    sports = (1024 + rng.permutation(50000)[:4096]).astype(np.uint32)

    def batch(n):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        rows[:, COL_SPORT] = rng.choice(sports, n)
        rows[:, COL_DPORT] = 5432
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_ACK
        rows[:, COL_LEN] = 512
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = db.id
        return rows

    from cilium_tpu.core.packets import pack_eligibility, pack_rows

    # ---- warm every compiled shape once (shared by all reps):
    # wide + packed ladders at trace_sample=0 (the offline ceiling)
    # AND trace_sample=1 (the decode-under-load ingress side) —
    # trace_sample is a static arg, so each value is its own
    # executable and an unwarmed one would bill XLA compile time to
    # the first timed rep
    for ts in (0, 1024, 1):
        # the ts>0 sessions mirror the overload legs' 2^16 ring —
        # the gather executables key on (rung, shards, capacity)
        d.start_serving(ring_capacity=(1 << 16) if ts else (1 << 15),
                        trace_sample=ts, packed=True)
        for b in LADDER:
            d.serve_batch(batch(b), valid=np.ones(b, dtype=bool))
            w = batch(b)
            ok, ep, dirn = pack_eligibility(w)
            assert ok, "bench traffic must be packed-eligible"
            d.serve_batch(pack_rows(w), valid=np.ones(b, dtype=bool),
                          packed_meta=(ep, dirn))
        if ts:
            # fill one whole drain window at full occupancy so the
            # top ring-gather rung (the one the timed overload legs
            # hit) compiles here, not inside a timed rep
            w = batch(B)
            ok, ep, dirn = pack_eligibility(w)
            pw = pack_rows(w)
            for _ in range(4):
                d.serve_batch(pw.copy(),
                              valid=np.ones(B, dtype=bool),
                              packed_meta=(ep, dirn))
            # superbatch executables (the K-batch scan, ISSUE 11):
            # the overload legs run superbatch_k=8, and WHICH K rungs
            # a leg hits depends on live queue depth — warm every
            # rung here so none pays its XLA compile in a timed rep
            from cilium_tpu.serving.batcher import SuperBatch

            for K in (2, 4, 8):
                sb = SuperBatch(
                    hdr=np.stack([pw] * K),
                    valid=np.ones((K, B), dtype=bool),
                    bucket=B, arrivals=[], packed=True,
                    eps=np.full(K, ep, np.uint32),
                    dirns=np.full(K, dirn, np.uint32))
                d.serve_superbatch(sb)
        d.stop_serving()

    valid = np.ones(B, dtype=bool)
    chunks = [batch(max(int(rng.poisson(4096.0)), 1))
              for _ in range(32)]
    target = offline_batches * B

    def rep_offline() -> float:
        """Offline ceiling: perfect pre-assembled full WIDE buckets."""
        d.start_serving(trace_sample=0)
        t0 = time.perf_counter()
        for _ in range(offline_batches):
            d.serve_batch(batch(B), valid=valid)
        dt = time.perf_counter() - t0
        d.stop_serving()
        return offline_batches * B / dt

    def rep_overload(span_sample=0, trace_sample=1024, agg=True):
        """Overload: Poisson chunks offered until the target volume
        is ADMITTED, backing off only when the queue is full —
        offered load exceeds capacity, so sheds are expected and
        counted.  The ingress runtime ships eligible buckets packed
        (16 B/packet h2d) with event decode ENABLED
        (``trace_sample=1024`` is the production default;
        ``trace_sample=1`` is the decode-under-load leg — every
        admitted packet appends a ring event; either way the async
        event plane fetches the occupancy-bounded gather and
        decodes/joins/emits on its worker, off the dispatch path).
        ``span_sample`` arms the obs span tracer (the trace-overhead
        leg); 0 keeps the production default (tracer None, one
        is-None branch on the hot path).  ``agg`` toggles the FLOW
        ANALYTICS plane (windowed per-identity aggregation + top-K
        sketches on the event-join worker, PR 6): True is the
        production default and the headline legs run with it; the
        dedicated agg-vs-no-agg pair at ``trace_sample=1`` isolates
        its dispatch-path cost (agg_overhead_ratio — the aggregation
        itself runs off-path, so the ratio defends ~1.0)."""
        # 2^16 ring: a full drain window (drain_every=4 x 8192-row
        # buckets at trace_sample=1) is half the capacity, so the
        # bench measures the gather diet, never lap loss
        d.analytics.enabled = bool(agg)
        d.start_serving(ring_capacity=1 << 16,
                        trace_sample=trace_sample,
                        ingress=True, packed=True,
                        span_sample=span_sample or None)
        admitted = offered = i = 0
        t0 = time.perf_counter()
        try:
            while admitted < target:
                c = chunks[i % len(chunks)]
                i += 1
                got = d.submit(c)
                offered += len(c)
                admitted += got
                if got < len(c):
                    time.sleep(0.0005)  # queue full: backpressure
            stats = d.stop_serving()  # drains everything admitted
        finally:
            d.analytics.enabled = True  # the production default
        dt = time.perf_counter() - t0
        fe = stats["front-end"]
        return fe["verdicts"] / dt, fe, offered, stats["event-plane"]

    # ---- best-of-3 INTERLEAVED: rep k runs offline then overload
    # back to back, so both sides sample the same machine weather.
    # fe/offered come from the SAME rep as the reported max pps —
    # mixed-provenance telemetry would mislead anyone correlating
    # the ratio with the shed/queue-wait numbers
    offline_pps = sustained_pps = decode_pps = traced_pps = 0.0
    noagg_pps = aggdec_pps = 0.0
    agg_pairs = []  # per-rep (noagg, agg) adjacent-leg ratios
    fe = offered = fe_traced = ev = dec_ev = agg_stats = None
    # untimed ingress warm leg: the very first overload leg of a run
    # pays residual warmth (first partial-bucket shapes, thread/alloc
    # steady state) that a timed pair member must not absorb
    rep_overload(agg=False)
    for k in range(3):
        offline_pps = max(offline_pps, rep_offline())
        # the PR 6 agg pair: the HEADLINE leg runs at production
        # defaults (trace_sample=1024, flow analytics ENABLED —
        # windowed counters, both top-K sketches, and the spike
        # detector see every decoded event AND every shed drop
        # batch, on the event-join worker), its baseline is the
        # identical overload with the analytics plane OFF.  The
        # ratio between the two is the dispatch-path cost of
        # aggregation (defended ~1.0: the drain thread only pays the
        # O(1) monitor-consumer reference park; worker-side CPU is
        # duty-cycle capped by flow_agg_max_duty).  The pair
        # ALTERNATES order per rep — measured on this box, whichever
        # leg runs second in a pair reads a few percent faster
        # (thermal/cache settling), so a fixed order masquerades as
        # aggregation cost; alternation cancels it in the median
        def agg_leg():
            nonlocal sustained_pps, fe, offered, ev, agg_stats
            s0 = d.analytics.stats()
            pps, rep_fe, rep_offered, rep_ev = rep_overload()
            if pps > sustained_pps:
                sustained_pps, fe, offered, ev = (pps, rep_fe,
                                                  rep_offered,
                                                  rep_ev)
                # THIS leg's analytics activity (counters are
                # daemon-lifetime cumulative — a raw snapshot would
                # conflate every earlier agg-enabled leg)
                s1 = d.analytics.stats()
                agg_stats = {k: (s1[k] - s0[k]
                                 if type(s1[k]) is int
                                 and type(s0.get(k)) is int
                                 else s1[k])
                             for k in s1}
            return pps

        def noagg_leg():
            nonlocal noagg_pps
            pps_na, _, _, _ = rep_overload(agg=False)
            noagg_pps = max(noagg_pps, pps_na)
            return pps_na

        if k % 2 == 0:
            pps, pps_na = agg_leg(), noagg_leg()
        else:
            pps_na, pps = noagg_leg(), agg_leg()
        agg_pairs.append(pps_na / pps)
        # the PR 5 decode-under-load leg: every packet an event —
        # the event plane's worker decodes ~all of the admitted
        # volume while the drain thread keeps dispatching (agg off:
        # PR 5 semantics)
        pps_dec, _, _, rep_dec_ev = rep_overload(trace_sample=1,
                                                 agg=False)
        if pps_dec > decode_pps:
            decode_pps, dec_ev = pps_dec, rep_dec_ev
        # the stress contrast: per-packet events AND aggregation —
        # the worst case the duty governor exists for, reported as
        # a secondary honesty number (not the acceptance ratio)
        pps_ad, _, _, _ = rep_overload(trace_sample=1)
        aggdec_pps = max(aggdec_pps, pps_ad)
        # the obs satellite's guard leg: the SAME overload rep with
        # 1-in-64 span tracing armed, interleaved so both legs see
        # the same machine weather.  trace_overhead_ratio ~ 1.0
        # documents the sampled cost; the DISABLED cost is the
        # default path above (tracer None) and is what the pre/post
        # bench comparison defends
        pps_tr, rep_fe_tr, _, _ = rep_overload(span_sample=64)
        if pps_tr > traced_pps:
            traced_pps, fe_traced = pps_tr, rep_fe_tr

    # ---- paced: Poisson arrivals at ~50% of the offline rate — the
    # latency-percentile run (at overload, queue wait just measures
    # queue depth).  Analytics OFF: this leg's percentiles are the
    # PR 5 decode-latency trajectory (trace_sample=1 is already a
    # stress shape, not the production default) — the aggregation
    # cost has its own dedicated pair above
    d.analytics.enabled = False
    d.start_serving(ring_capacity=1 << 16, trace_sample=1,
                    ingress=True, packed=True)
    rate = max(offline_pps * 0.5, 1.0)
    t_end = time.perf_counter() + paced_seconds
    i = 0
    while time.perf_counter() < t_end:
        c = chunks[i % len(chunks)]
        i += 1
        d.submit(c)
        time.sleep(float(rng.exponential(len(c) / rate)))
    paced_out = d.stop_serving()
    d.analytics.enabled = True
    paced = paced_out["front-end"]
    paced_ev = paced_out["event-plane"]

    # ---- d2h scaling contrast: the same LOW-occupancy window (one
    # 512-row bucket per drain tick on the 2^16 ring) fetched via the
    # occupancy-bounded gather vs the legacy full-capacity copy —
    # the bytes-per-event gap IS the tentpole's d2h diet
    scaling = {"ring_capacity": 1 << 16}
    for label, g in (("gather", True), ("fullcopy", False)):
        d.start_serving(ring_capacity=1 << 16, drain_every=1,
                        trace_sample=1, packed=True, event_gather=g)
        b = LADDER[0]
        for _ in range(4):
            d.serve_batch(batch(b), valid=np.ones(b, dtype=bool))
        sc = d.stop_serving()["event-plane"]
        scaling[f"{label}_bytes_per_event"] = sc["d2h-bytes-per-event"]
    d.shutdown()

    return {
        "offline_pps": round(offline_pps),
        "sustained_pps": round(sustained_pps),
        "serving_vs_offline": round(sustained_pps / offline_pps, 3),
        "offered": offered,
        "admitted": fe["admitted"],
        "shed": fe["shed"],
        "shed_drop_events": fe["shed-events"],
        "batch_shapes": fe["batch-shapes"],
        "pad_efficiency": fe["pad-efficiency"],
        # the h2d link scoreboard (PR 2 tentpole): bytes/packet on
        # the wire and how many batches shipped packed vs wide
        "h2d_bytes_per_packet": fe["h2d"]["bytes-per-packet"],
        "packed_batches": fe["h2d"]["packed-batches"],
        "wide_batches": fe["h2d"]["wide-batches"],
        # the superbatch scoreboard of the HEADLINE leg (ISSUE 11):
        # overload legs run superbatch_k=8, so batches-per-dispatch
        # floats with live queue depth; the dedicated "superbatch"
        # section (bench_superbatch) is the pinned-K acceptance pair
        "superbatch_k": 8,
        "dispatches": fe["dispatch"]["dispatches"],
        "batches_per_dispatch":
            fe["dispatch"]["batches-per-dispatch"],
        # the d2h link scoreboard (PR 5 tentpole): event decode is ON
        # in every overload/paced leg (sustained_pps at the
        # production-default trace_sample=1024; sustained_pps_decode
        # with EVERY packet an event), the fetch is the
        # occupancy-bounded gather, and decode/join/emit run on the
        # event-join worker off the dispatch path
        "event_decode": "enabled (trace_sample=1024 headline; "
                        "decode leg trace_sample=1)",
        # decode ratio keeps its PR 5 meaning (events-per-packet vs
        # events-sampled, both with analytics OFF): the denominator
        # is the no-agg production-default leg, not the analytics-
        # enabled headline
        "sustained_pps_decode": round(decode_pps),
        "decode_overhead_ratio": round(decode_pps / noagg_pps, 4)
        if noagg_pps else None,
        # the flow analytics scoreboard (PR 6 tentpole): the
        # HEADLINE runs at production defaults with aggregation ON;
        # sustained_pps_noagg is the identical overload with it OFF,
        # so agg_overhead_ratio = noagg/agg defends <= 1.05 (the
        # dispatch path only pays the O(1) reference park; worker
        # CPU is duty-capped by flow_agg_max_duty).  The *_aggdecode
        # pair is the per-packet-event stress contrast (every packet
        # decoded AND aggregated) — the governor's worst case,
        # reported for honesty, not the acceptance gate
        "flow_agg": "headline at production defaults WITH "
                    "aggregation; sustained_pps_noagg = same leg "
                    "with analytics off",
        "sustained_pps_noagg": round(noagg_pps),
        "agg_overhead_ratio": round(sorted(agg_pairs)[1], 4)
        if len(agg_pairs) == 3 else None,
        "agg_overhead_ratio_pairs": [round(r, 4) for r in agg_pairs],
        "sustained_pps_aggdecode": round(aggdec_pps),
        "aggdecode_vs_decode_ratio": round(decode_pps / aggdec_pps, 4)
        if aggdec_pps else None,
        "flow_agg_stats": agg_stats,
        "d2h_bytes_per_event": dec_ev["d2h-bytes-per-event"],
        "event_join_lag_us": dec_ev["join-lag-us"],
        "event_windows": {"joined": dec_ev["windows-joined"],
                          "dropped": dec_ev["windows-dropped"],
                          "ring-lost": dec_ev["ring-lost"],
                          "events-joined": dec_ev["events-joined"]},
        "paced_d2h_bytes_per_event": paced_ev["d2h-bytes-per-event"],
        "d2h_scaling": scaling,
        "bucket_ladder": list(LADDER),
        "max_wait_us": 2000.0,
        "overload_queue_wait_us": fe["queue-wait-us"],
        "paced_latency_us": paced["latency-us"],
        "paced_queue_wait_us": paced["queue-wait-us"],
        "paced_pad_efficiency": paced["pad-efficiency"],
        # obs plane: sustained pps with 1-in-64 span tracing armed
        # (best-of-3, interleaved with the untraced leg) and the
        # resulting overhead ratio; span counts prove the traces
        # actually flowed
        "sustained_pps_traced": round(traced_pps),
        "trace_overhead_ratio": round(traced_pps / sustained_pps, 4)
        if sustained_pps else None,
        "trace_spans_completed": (fe_traced or {}).get(
            "trace", {}).get("completed"),
        "platform": jax.default_backend(),
        "note": ("serving front end (admission queue + power-of-two "
                 "bucket batcher + drain loop, PACKED 16 B/packet "
                 "h2d, EVENT DECODE enabled on the async event "
                 "plane: headline at the production-default "
                 "trace_sample=1024, decode-under-load leg at "
                 "trace_sample=1) vs offline pre-assembled wide "
                 "buckets at trace_sample=0; serving_vs_offline is "
                 "the front end's overhead ratio, best-of-3 "
                 "interleaved; sheds are counted monitor DROP "
                 "events (REASON_INGRESS_OVERFLOW); d2h_scaling "
                 "contrasts the occupancy-bounded gather with the "
                 "legacy full-capacity copy at low ring occupancy; "
                 "agg_overhead_ratio is the median of order-"
                 "alternated adjacent-leg pairs (production-default "
                 "overload, analytics on vs off; aggregation runs "
                 "on the event-join worker, duty-capped, so the "
                 "ratio defends the dispatch path staying "
                 "untouched).  CAVEAT: every single-run ratio here "
                 "(trace_overhead_ratio, the agg pairs, "
                 "serving_vs_offline) divides two wall-clock "
                 "measurements on a shared CPU box whose weather "
                 "swings far beyond the documented +-15%; judge "
                 "ratios across runs (the agg pairs field exposes "
                 "the per-rep spread for exactly this reason), "
                 "never from one leg"),
    }


def bench_superbatch(reps: int = 3, bucket: int = 512,
                     k: int = 16, n_buckets: int = 192) -> dict:
    """The ISSUE 11 acceptance pair: sustained drain throughput with
    K-batch superbatch dispatch vs the K=1 leg of the SAME
    interleaved run (``paired_legs``), at one shared bucket ladder.

    Measurement shape: the queue is pre-filled with the whole leg's
    volume in large doorbell chunks and the drain loop consumes it
    flat out — the purest view of per-dispatch cost, with zero
    producer interference and batches-per-dispatch pinned at the
    configured K.  The bucket is deliberately SMALL (512): on the
    CPU backend the datapath math runs orders of magnitude slower
    than on a TPU while the Python per-dispatch cost is identical,
    so the dispatch-bound regime a real TPU sits in at EVERY bucket
    is reproduced on CPU at the small rung (at 8192 the CPU "device"
    math dominates and the same pair reads ~1.25x — recorded as
    ``ratio_top_bucket`` for honesty)."""
    import ipaddress

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY,
                                         COL_FLAGS, COL_LEN,
                                         COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS,
                                         TCP_ACK)

    def build(B, depth_buckets):
        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 16,
            flow_ring_capacity=1 << 14,
            serving_queue_depth=depth_buckets * B,
            serving_bucket_ladder=(B,),
            serving_max_wait_us=2000.0))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"app": "web"}}],
                "toPorts": [{"ports": [{"port": "5432",
                                        "protocol": "TCP"}]}]}],
        }])
        rng = np.random.default_rng(31)
        src = int(ipaddress.IPv4Address("10.0.1.1"))
        dst = int(ipaddress.IPv4Address("10.0.2.1"))
        sports = (1024
                  + rng.permutation(50000)[:4096]).astype(np.uint32)

        def batch(n):
            rows = np.zeros((n, N_COLS), dtype=np.uint32)
            rows[:, COL_SRC_IP3] = src
            rows[:, COL_DST_IP3] = dst
            rows[:, COL_SPORT] = rng.choice(sports, n)
            rows[:, COL_DPORT] = 5432
            rows[:, COL_PROTO] = 6
            rows[:, COL_FLAGS] = TCP_ACK
            rows[:, COL_LEN] = 512
            rows[:, COL_FAMILY] = 4
            rows[:, COL_EP] = db.id
            return rows

        # big doorbell chunks: the fill must outrun the drain so the
        # queue actually holds K ready buckets
        chunk = max(4096, B)
        filler = [batch(chunk)
                  for _ in range(depth_buckets * B // chunk)]
        return d, filler

    def leg_fn(d, filler, kk):
        total = sum(len(c) for c in filler)

        def leg():
            d.start_serving(ring_capacity=1 << 16,
                            trace_sample=1024, ingress=True,
                            packed=True, superbatch_k=kk)
            rt = d._serving["runtime"]
            t0 = time.perf_counter()
            for c in filler:
                d.submit(c)
            deadline = t0 + 120.0
            while (rt.stats.verdicts < total
                   and time.perf_counter() < deadline):
                time.sleep(0.001)
            dt = time.perf_counter() - t0
            fe = d.stop_serving()["front-end"]
            ft = fe["fault-tolerance"]
            exact = fe["submitted"] == (fe["verdicts"] + fe["shed"]
                                        + ft["recovery-dropped"])
            return fe["verdicts"] / dt, {
                "batches_per_dispatch":
                    fe["dispatch"]["batches-per-dispatch"],
                "superbatches": fe["dispatch"]["superbatches"],
                "ledger_exact": exact,
            }

        return leg

    # -- the acceptance pair at the dispatch-bound rung --------------
    d, filler = build(bucket, n_buckets)
    base, cand = leg_fn(d, filler, 1), leg_fn(d, filler, k)
    base()
    cand()  # warm both executables outside the timed pairs
    pair = paired_legs(base, cand, reps=reps)
    comp = d.loader.compile_log.summary()
    d.shutdown()

    # -- the honesty contrast at the big rung: CPU "device" math
    # dominates there, so the same pair reads much lower ------------
    d2, filler2 = build(8192, 24)
    base2, cand2 = leg_fn(d2, filler2, 1), leg_fn(d2, filler2, 8)
    base2()
    cand2()
    top = paired_legs(base2, cand2, reps=1)
    d2.shutdown()

    ce, be = pair["candidate_extra"], pair["baseline_extra"]
    return {
        "bucket_ladder": [bucket],
        "k": k,
        "sustained_pps": pair["candidate_pps"],
        "sustained_pps_k1": pair["baseline_pps"],
        "ratio_pairs": pair["pairs"],
        "ratio_best": pair["ratio_best"],
        "ratio_median": pair["ratio_median"],
        "spread": pair["spread"],
        "batches_per_dispatch": ce["batches_per_dispatch"],
        "superbatches": ce["superbatches"],
        "ledger_exact": bool(ce["ledger_exact"]
                             and be["ledger_exact"]),
        "compile_violations": comp["violations"],
        "ratio_top_bucket": top["ratio_best"],
        "top_bucket_pps": {"k1": top["baseline_pps"],
                           "k8": top["candidate_pps"]},
        "note": ("pre-filled-queue drain legs, K=%d vs K=1 "
                 "interleaved per pair (paired_legs); bucket %d is "
                 "the dispatch-bound rung on CPU — the honest proxy "
                 "for TPU behavior at every bucket, where device "
                 "math is microseconds and Python dispatch is the "
                 "ceiling; ratio_top_bucket shows the same pair at "
                 "8192 where the CPU datapath math dominates"
                 % (k, bucket)),
    }


def bench_recovery() -> dict:
    """--recovery: fault-tolerance latency phase (ISSUE 3).  Measures,
    best-of-3 INTERLEAVED (CPU wall timings swing +-15%, so each rep
    runs all three scenarios back to back and the minimum is
    reported):

    - ``restart_recovery_ms``: injected dispatch death -> first
      healthy dispatch of the restarted drain loop;
    - ``hang_detect_ms``: injected dispatch hang -> watchdog restart
      recorded (the detection latency the deadline knob governs);
    - ``demotion_ms``: injected packed-path fault streak -> first
      successful dispatch on the demoted (wide) rung;
    - ``promotion_ms``: cooldown start -> first batch after
      re-promotion to the packed rung.

    CPU-bounded and deterministic (seeded injector); each scenario
    uses a FRESH daemon so compile warmup is inside the rep and
    excluded from the measured windows (warm batches run first)."""
    import ipaddress

    import jax

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY,
                                         COL_FLAGS, COL_LEN,
                                         COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS, TCP_ACK)

    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))

    def batch(n, ep_id):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        rows[:, COL_SPORT] = (20000 + np.arange(n)) % 60000
        rows[:, COL_DPORT] = 5432
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_ACK
        rows[:, COL_LEN] = 512
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = ep_id
        return rows

    def fresh(spec, **over):
        cfg = dict(backend="tpu", ct_capacity=1 << 14,
                   flow_ring_capacity=1 << 13,
                   serving_queue_depth=4096,
                   serving_bucket_ladder=(512,),
                   serving_max_wait_us=500.0,
                   serving_dispatch_deadline_ms=100.0,
                   serving_restart_budget=8,
                   serving_restart_backoff_ms=1.0,
                   serving_demote_threshold=1,
                   serving_promote_after=2,
                   serving_promote_cooldown_s=0.05,
                   fault_injection=spec, fault_seed=7)
        cfg.update(over)
        d = Daemon(DaemonConfig(**cfg))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"app": "web"}}],
                "toPorts": [{"ports": [{"port": "5432",
                                        "protocol": "TCP"}]}]}],
        }])
        return d, db

    def pump_until(rt, pred, tmax=30.0):
        t0 = time.perf_counter()
        while not pred():
            if time.perf_counter() - t0 > tmax:
                raise TimeoutError("recovery bench stalled")
            time.sleep(0.001)
        return time.perf_counter()

    def rep_restart() -> float:
        """dispatch death -> first healthy post-restart dispatch."""
        d, db = fresh("serving.dispatch=1x1@1")
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        rows = batch(512, db.id)
        d.submit(rows)  # warm (compile outside the window)
        pump_until(rt, lambda: rt.stats.verdicts >= 512)
        t0 = time.perf_counter()
        d.submit(rows)  # dies
        d.submit(rows)  # dispatches after the restart
        t1 = pump_until(rt, lambda: rt.stats.verdicts >= 1024)
        d.stop_serving()
        d.shutdown()
        return (t1 - t0) * 1e3

    def rep_hang_detect() -> float:
        """hang start -> watchdog restart recorded (deadline 100ms)."""
        d, db = fresh("serving.dispatch=1x1@1~3")
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        rows = batch(512, db.id)
        d.submit(rows)
        pump_until(rt, lambda: rt.stats.verdicts >= 512)
        t0 = time.perf_counter()
        d.submit(rows)  # hangs
        t1 = pump_until(rt, lambda: rt.stats.restarts >= 1,
                        tmax=10.0)
        d.stop_serving()
        d.shutdown()
        return (t1 - t0) * 1e3

    def rep_ladder() -> tuple:
        """(demotion_ms, promotion_ms): packed fault -> first wide
        dispatch; cooldown -> first post-promotion batch."""
        d, db = fresh("loader.serve_packed=1x1@1",
                      serving_dispatch_deadline_ms=5000.0)
        d.start_serving(trace_sample=0, ingress=True, packed=True)
        rt = d._serving["runtime"]
        rows = batch(512, db.id)
        d.submit(rows)  # warm the packed rung
        pump_until(rt, lambda: rt.stats.verdicts >= 512)
        t0 = time.perf_counter()
        d.submit(rows)  # faults -> demote (threshold 1) -> retried
        t1 = pump_until(rt, lambda: rt.stats.verdicts >= 1024)
        demote_ms = (t1 - t0) * 1e3
        lad = d._serving["ladder"]
        assert lad.rung == "wide", "bench expected a demotion"
        t2 = time.perf_counter()
        n = 2
        while lad.rung != "single":  # healthy batches + cooldown
            d.submit(rows)
            n += 1
            pump_until(rt, lambda: rt.stats.verdicts >= n * 512)
            time.sleep(0.02)
        t3 = time.perf_counter()
        d.stop_serving()
        d.shutdown()
        return demote_ms, (t3 - t2) * 1e3

    restart_ms = hang_ms = demote_ms = promote_ms = float("inf")
    for _ in range(3):  # best-of-3 interleaved
        restart_ms = min(restart_ms, rep_restart())
        hang_ms = min(hang_ms, rep_hang_detect())
        dm, pm = rep_ladder()
        demote_ms = min(demote_ms, dm)
        promote_ms = min(promote_ms, pm)

    import jax as _jax

    return {
        "restart_recovery_ms": round(restart_ms, 2),
        "hang_detect_ms": round(hang_ms, 2),
        "dispatch_deadline_ms": 100.0,
        "demotion_ms": round(demote_ms, 2),
        "promotion_ms": round(promote_ms, 2),
        "promote_cooldown_ms": 50.0,
        "restart_backoff_ms": 1.0,
        "platform": _jax.default_backend(),
        "note": ("fault injected -> first healthy dispatch, best-of-3"
                 " interleaved; hang_detect is watchdog-deadline"
                 " governed (deadline 100ms), demotion includes the"
                 " demoted rung's first-dispatch compile,"
                 " promotion includes the configured 50ms cooldown"),
    }


def _run_recovery_phase() -> None:
    """--recovery: the fault-tolerance latency phase standalone (one
    JSON line).  Also writes BENCH_recovery.json next to this file;
    runs bounded under JAX_PLATFORMS=cpu."""
    import os

    out = bench_recovery()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_recovery.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def _run_serving_phase() -> None:
    """--serving: the serving front-end phase standalone (one JSON
    line).  Also writes BENCH_serving.json next to this file — the
    artifact that seeds the BENCH trajectory; runs bounded under
    JAX_PLATFORMS=cpu."""
    import os

    out = bench_serving()
    # the ISSUE 11 acceptance pair: K-batch superbatch dispatch vs
    # the K=1 leg of the same interleaved run (paired_legs), plus a
    # top-level ratio mirror for the trajectory reader
    out["superbatch"] = bench_superbatch()
    out["superbatch_ratio"] = out["superbatch"]["ratio_best"]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_serving.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def _hist_pct_delta(before, after, p, max_us_hint=None):
    """Percentile over the DELTA of two log2-µs histogram snapshots —
    lets a leg report its own p99 out of a cumulative histogram
    without a reset API.  Delegates to LatencyHistogram.percentile on
    a throwaway instance so the bucket convention and interpolation
    can never drift from the registry/CLI numbers."""
    from cilium_tpu.serving.stats import LatencyHistogram

    h = LatencyHistogram()
    h.buckets = [b - a for a, b in zip(before, after)]
    h.count = sum(h.buckets)
    if h.count <= 0:
        return None
    h.max_us = (float(max_us_hint) if max_us_hint
                else float("inf"))
    v = h.percentile(p)
    return round(v, 3) if v is not None else None


def bench_churn(target_packets=81920, reps=3, churn_hz=200.0) -> dict:
    """--churn: live policy/identity churn under serving (ISSUE 10)
    -> BENCH_churn.json.

    Two legs per rep, INTERLEAVED (rep k runs no-churn then churn
    back to back so both sample the same machine weather; best-of-3
    per leg):

    - NO-CHURN OVERLOAD: the PR 1-style sustained leg on the packed
      path at one bucket rung — the baseline ``sustained_pps``.
    - CHURN OVERLOAD: the same loop while the seeded
      ``identity_churn`` scenario (testing/workloads.py) mints and
      withdraws label-selected peer identities at ``churn_hz`` from
      the driver thread — every op is a patch_identity +
      patch_ipcache publish pair against the live tables.

    Reported: ``sustained_pps_churn`` vs ``sustained_pps`` (the
    churn tax), ``update_visible_p50/p99_us`` (mutation entry ->
    published generation, measured per op by the driver),
    ``swap_stall_p99_us`` (dispatch-lock hold per publish flip, from
    the churn legs' delta of the loader's cumulative histogram), the
    generation/swap totals, ``ledger_exact`` (every leg's
    ``submitted == verdicts + shed + recovery_dropped``), and
    ``compile_violations`` — the one-executable guard must stay at
    zero through churn (identity churn never retraces the serving
    executables; that IS the delta-compile story)."""
    import ipaddress

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY,
                                         COL_FLAGS, COL_LEN,
                                         COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS,
                                         TCP_ACK)
    from cilium_tpu.testing.workloads import make_scenario

    BUCKET = 2048
    d = Daemon(DaemonConfig(
        backend="tpu", ct_capacity=1 << 16,
        flow_ring_capacity=1 << 14,
        serving_queue_depth=1 << 15,
        serving_bucket_ladder=(BUCKET,),
        serving_max_wait_us=2000.0))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "5432",
                                     "protocol": "TCP"}]}]},
            {"fromEndpoints": [{"matchLabels": {"churn": "yes"}}],
             "toPorts": [{"ports": [{"port": "5432",
                                     "protocol": "TCP"}]}]},
        ],
    }])
    d.start()
    sc = make_scenario("identity_churn", seed=23, n_slots=16,
                       zipf_a=1.3, rate_hz=churn_hz)
    rng = np.random.default_rng(23)
    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))
    sports = (1024 + rng.permutation(50000)[:4096]).astype(np.uint32)

    def batch(n):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        rows[:, COL_SPORT] = rng.choice(sports, n)
        rows[:, COL_DPORT] = 5432
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_ACK
        rows[:, COL_LEN] = 512
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = db.id
        return rows

    chunks = [batch(max(int(rng.poisson(1024.0)), 1))
              for _ in range(32)]

    # warm: the serving executables at this rung AND the patch
    # publish ops (first .at[].set per shape pays a tiny compile)
    d.start_serving(ring_capacity=1 << 14, trace_sample=0,
                    packed=True, ingress=True)
    d.submit(batch(BUCKET))
    live = {}
    ops_warm = iter(sc.iter_ops())
    for _ in range(4):
        sc.apply(d, next(ops_warm), live)
    t0 = time.perf_counter()
    while (d._serving["runtime"].stats.verdicts < BUCKET
           and time.perf_counter() - t0 < 120):
        time.sleep(0.005)
    d.stop_serving()
    # superbatch executables for the K>1 churn legs (ISSUE 11): warm
    # each K rung's packed scan so no timed leg pays an XLA compile
    from cilium_tpu.core.packets import pack_eligibility, pack_rows
    from cilium_tpu.serving.batcher import SuperBatch

    w = batch(BUCKET)
    ok, ep, dirn = pack_eligibility(w)
    pw = pack_rows(w)
    d.start_serving(ring_capacity=1 << 14, trace_sample=0,
                    packed=True)
    for K in (2, 4, 8):
        d.serve_superbatch(SuperBatch(
            hdr=np.stack([pw] * K),
            valid=np.ones((K, BUCKET), dtype=bool),
            bucket=BUCKET, arrivals=[], packed=True,
            eps=np.full(K, ep, np.uint32),
            dirns=np.full(K, dirn, np.uint32)))
    d.stop_serving()
    # warmup identities must not leak into the measured legs' worlds
    sc.drain(d, live)

    def overload_leg(churn: bool, superbatch_k: int = 1):
        q = None
        d.start_serving(ring_capacity=1 << 14, trace_sample=0,
                        packed=True, ingress=True,
                        superbatch_k=superbatch_k)
        q = d._serving["runtime"].queue
        ops = iter(sc.iter_ops())
        leg_live = {}
        op_lat = []
        next_op = time.perf_counter()
        submitted = 0
        t0 = time.perf_counter()
        while submitted < target_packets:
            for c in chunks:
                if submitted >= target_packets:
                    break
                submitted += d.submit(c.copy())
                if q.pending > (1 << 15) // 2:
                    while q.pending > (1 << 15) // 4:
                        if churn and time.perf_counter() >= next_op:
                            break
                        time.sleep(0.001)
                if churn and time.perf_counter() >= next_op:
                    next_op += sc.interval_s
                    t1 = time.perf_counter()
                    sc.apply(d, next(ops), leg_live)
                    op_lat.append((time.perf_counter() - t1) * 1e6)
        fe = d.stop_serving()["front-end"]
        dt = time.perf_counter() - t0
        ft = fe["fault-tolerance"]
        exact = fe["submitted"] == (fe["verdicts"] + fe["shed"]
                                    + ft["recovery-dropped"])
        # drain the leg's surviving identities so legs are
        # independent worlds
        sc.drain(d, leg_live)
        return fe["verdicts"] / dt, {
            "op_lat": op_lat, "exact": exact,
            "bpd": fe["dispatch"]["batches-per-dispatch"]}

    # paired-leg harness (ISSUE 11 satellite): each pair runs
    # no-churn/churn back to back with alternating order, ratios are
    # per-pair — weather slows both legs of a pair together.  Two
    # pairs: the K=1 trajectory leg and the K=8 superbatch leg, the
    # latter recording update-visible latency at superbatch
    # granularity (one dispatch pins a generation for K batches)
    lat_by_k = {1: [], 8: []}
    state = {"exact": True, "ops": 0}

    def make_leg(churn: bool, k: int):
        def fn():
            pps, extra = overload_leg(churn, superbatch_k=k)
            state["exact"] = state["exact"] and extra["exact"]
            if churn:
                lat_by_k[k].extend(extra["op_lat"])
                state["ops"] += len(extra["op_lat"])
            return pps, extra
        return fn

    stall_before = list(d.loader.tables.swap_stall.buckets)
    pair_k1 = paired_legs(make_leg(False, 1), make_leg(True, 1),
                          reps=reps)
    pair_k8 = paired_legs(make_leg(False, 8), make_leg(True, 8),
                          reps=reps)
    stall_after = list(d.loader.tables.swap_stall.buckets)
    stall_p99 = _hist_pct_delta(
        stall_before, stall_after, 0.99,
        max_us_hint=d.loader.tables.swap_stall.max_us)
    ts = d.loader.table_stats()
    comp = d.loader.compile_log.summary()
    d.shutdown()
    lat1 = (np.asarray(lat_by_k[1]) if lat_by_k[1]
            else np.zeros(1))
    lat8 = (np.asarray(lat_by_k[8]) if lat_by_k[8]
            else np.zeros(1))
    return {
        "schema": "bench-churn-v1",
        "best_of": reps,
        "sustained_pps": pair_k1["baseline_pps"],
        "sustained_pps_churn": pair_k1["candidate_pps"],
        # per-pair median, not best/best: the paired harness's
        # whole point (pairs + spread recorded alongside)
        "churn_ratio": pair_k1["ratio_median"],
        "churn_ratio_pairs": pair_k1["pairs"],
        "churn_ratio_spread": pair_k1["spread"],
        "churn_ops": state["ops"],
        "churn_rate_hz": churn_hz,
        "update_visible_p50_us": round(
            float(np.percentile(lat1, 50)), 1),
        "update_visible_p99_us": round(
            float(np.percentile(lat1, 99)), 1),
        # the K=8 superbatch legs (ISSUE 11): generation pinning at
        # superbatch granularity — one dispatch pins one table
        # generation for K batches, so update-visible latency is the
        # number to watch as K grows
        "superbatch_k": 8,
        "sustained_pps_k8": pair_k8["baseline_pps"],
        "sustained_pps_churn_k8": pair_k8["candidate_pps"],
        "churn_ratio_k8": pair_k8["ratio_median"],
        "churn_ratio_k8_pairs": pair_k8["pairs"],
        "batches_per_dispatch_k8":
            (pair_k8["candidate_extra"] or {}).get("bpd"),
        "update_visible_p50_us_k8": round(
            float(np.percentile(lat8, 50)), 1),
        "update_visible_p99_us_k8": round(
            float(np.percentile(lat8, 99)), 1),
        "swap_stall_p99_us": stall_p99,
        "swaps": ts["swaps"],
        "generation": ts["generation"],
        "delta_attaches": ts["delta-attaches"],
        "patches": ts["patches"],
        "ledger_exact": state["exact"],
        "compile_violations": comp["violations"],
        "note": ("churn legs mint/withdraw label-selected peer "
                 "identities (2 publish flips per op) from the "
                 "driver thread during the packed overload leg; "
                 "update-visible latency measured per op by the "
                 "driver, swap stall from the loader histogram's "
                 "leg delta; paired-leg harness: ratios are per-pair "
                 "medians over %d order-alternated no-churn/churn "
                 "pairs (pairs + spread recorded), at K=1 and at "
                 "superbatch K=8" % reps),
    }


def _run_churn_phase() -> None:
    """--churn: the live-churn phase standalone (one JSON line).
    Also writes BENCH_churn.json next to this file; schema-checked
    by the CTA009 bench machinery."""
    import os

    out = bench_churn()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_churn.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def bench_scenarios(seed: int = 31) -> dict:
    """--scenarios: the adversarial scenario engine (ISSUE 12) ->
    BENCH_scenarios.json.

    Every registered scenario (``testing/workloads.SCENARIOS``) runs
    once through the shared :func:`run_scenario` driver against a
    fresh daemon built from the scenario's own ``daemon_overrides``
    (the pressure shape it declares — a 1k-entry CT map for
    ``syn_flood``, a 256-port SNAT pool for ``nat_exhaustion``) and
    is judged against its DECLARED pass criteria.  Per-scenario
    sustained pps, shed fraction, pressure counters, and pass/fail
    land in the artifact; ``all_passed`` is the regression gate.
    Schema-checked by the CTA010 machinery (importable
    ``check_bench`` in ``cilium_tpu.analysis.scenario_lint``).

    CPU-bounded numbers (the standing caveat): pps here defends the
    DRIVER's honesty (ledger exact under each hostile shape), not
    device throughput — --serving/--churn own the speed story."""
    from cilium_tpu.testing.workloads import (SCENARIOS,
                                              make_scenario,
                                              run_scenario,
                                              scenario_daemon)

    results = {}
    for name in sorted(SCENARIOS):
        sc = make_scenario(name, seed=seed)
        if getattr(sc, "cluster_ops", False):
            # cluster-facade op streams (rotation_storm's epoch
            # bumps) have no plain-daemon leg — the soak gate's
            # encrypted cluster leg owns them (ISSUE 18)
            continue
        d = None
        try:
            # construction/start INSIDE the guard: one scenario's
            # bad daemon shape must not abort the whole sweep either
            d = scenario_daemon(sc, map_pressure_interval=0.25)
            d.start()
            r = run_scenario(d, sc)
            m = r["metrics"]
            results[name] = {
                "seed": r["seed"],
                "criteria": r["criteria"],
                "checks": r["checks"],
                "passed": r["passed"],
                "sustained_pps": m["sustained_pps"],
                "shed_frac": m["shed_frac"],
                "p99_us": m["p99_us"],
                "packets": m["submitted"],
                "ops_applied": m["ops_applied"],
                "ct_insert_drops": m["ct_insert_drops"],
                "nat_failures": m["nat_failures"],
                "drop_frac": m["drop_frac"],
                "pressure_state": d.pressure.stats()["state"],
                "pressure_episodes": d.pressure.stats()["episodes"],
            }
        except Exception as e:  # one hostile shape failing must not
            results[name] = {  # hide the rest of the sweep
                "seed": seed, "criteria": dict(sc.criteria),
                "checks": {}, "passed": False,
                "sustained_pps": 0.0, "shed_frac": None,
                "error": f"{type(e).__name__}: {e}"[:200],
            }
        finally:
            if d is not None:
                d.shutdown()
    return {
        "schema": "bench-scenarios-v1",
        "scenarios": results,
        "all_passed": all(r.get("passed") for r in results.values()),
        "note": ("each scenario runs the shared run_scenario driver "
                 "against a fresh daemon built from its own "
                 "daemon_overrides and is judged against its "
                 "DECLARED criteria; pps is CPU-bounded and defends "
                 "ledger exactness under hostile shapes, not device "
                 "throughput"),
    }


def _run_scenarios_phase() -> None:
    """--scenarios: the adversarial scenario phase standalone (one
    JSON line).  Also writes BENCH_scenarios.json next to this file;
    schema-checked by the CTA010 bench machinery."""
    import os

    out = bench_scenarios()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_scenarios.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def bench_cluster(target_packets=98304, reps=3) -> dict:
    """--cluster: the clustermesh serving tier phase (ISSUE 8 +
    ISSUE 13 + ISSUE 17) -> BENCH_cluster.json.

    Eight legs, CPU-bounded and deterministic:

    - SCALING-vs-NODES, PER MODE (``thread`` and ``process``):
      sustained verdicts/sec through the cluster front end at
      N = 1 / 2 / 3 replicas, measured with the ``paired_legs``
      harness (interleaved rep-by-rep, pair order ALTERNATING, the
      per-pair ratios + spread shipped alongside best absolutes) —
      scaling_nK is the PAIR-MEDIAN of nK/n1 ratios, not a
      best-vs-best.  Thread mode is the PR 8 shape (replicas share
      one GIL — the curve documents the contention penalty); process
      mode is one worker PROCESS per node forwarding over real
      sockets, the shape where N nodes buy N cores.  HONESTY FLOOR:
      ``host_cores`` records ``os.cpu_count()`` — a 1-core host
      cannot show N-core speedups in ANY mode (processes time-slice
      one core); on such hosts the process curve's claim is
      "adding nodes no longer makes the cluster SLOWER" (vs the
      thread curve's sub-1.0), and the linear-speedup claim needs a
      multi-core host.

    - FORWARD-PATH LATENCY: enqueue -> delivered percentiles from
      the router's histogram (queue wait + node submit / socket
      round trip), per mode, taken from the N=3 legs.

    - FAILOVER BLACKOUT (process mode — the PR 8 proof re-made
      against a real SIGKILL): a 3-worker cluster under sustained
      load; one worker is SIGKILLed and health-detected, the
      parent-retained CT snapshot replays onto the peer, the router
      re-pins, and the ledger closes exactly with the corpse's
      admitted-but-unresolved rows counted ``crash_dropped``.

    - LIVE SCALE-OUT (process mode): ``add_node()`` on the serving
      cluster — build/converge/warm off to the side, freeze +
      quiesce, slot re-pin + CT migration, resume; the pause window
      and survivor recompile count ship in the artifact.

    v3 legs (ISSUE 17 — the pipelined data channel):

    - PIPELINED THROUGHPUT (process mode, ONE node, small frames):
      window=1 (the PR 13 sync-ack protocol, byte-identical wire)
      vs window=8 (credit-windowed streaming with coalesced acks),
      through the ``paired_legs`` harness — ``pipelined_speedup``
      is the PAIR-MEDIAN of windowed/sync ratios.  Small frames on
      purpose: the channel is ACK-CADENCE-bound, the regime the
      window exists for (big frames amortize the RTT and hide it).
      Same ``host_cores`` honesty floor as the scaling curve: the
      overlap win needs parent and worker on separate cores — a
      1-core host shows only the ack-coalescing share of it.

    - FORWARD-LATENCY p50 AT LOW LOAD, sync vs pipelined: one small
      frame at a time, fully landed before the next — the window
      must not buy throughput by selling latency
      (``latency_p50_ratio`` is pipelined/sync; target <= 1.5x —
      what the worker's flush-on-drain ack exists for).
      Both sides measure the SAME enqueue->acked interval (the sync
      path's blocking submit and the windowed path's cumulative-ack
      retire record into one histogram).

    - SIGKILL MID-WINDOW (process mode): the corpse dies with the
      credit window OPEN — sent-but-unacked frames outstanding.
      The last cumulative ack is the final word; everything past it
      requeues to the failover peer or lands ``crash_dropped``, and
      the ledger closes EXACTLY (the property test's claim, re-made
      against a real process corpse under real load).

    - LIVE SCALE-IN (process mode): ``remove_node()`` on the
      serving cluster — freeze + quiesce (window drained), victim
      CT migrated out, slots re-pinned onto survivors, victim
      retired; the pause window and the ZERO survivor-recompile
      count ship in the artifact.

    v4 legs (ISSUE 18 — the encrypted data channel):

    - ENCRYPTED THROUGHPUT (process mode, ONE node, the shipped
      window): ``cluster_encrypt=False`` vs ``True`` through the
      ``paired_legs`` harness — ``encrypted_ratio`` is the
      PAIR-MEDIAN of encrypted/plaintext rates, the AEAD toll
      honestly measured on the same wire at the same window (one
      seal per frame + one open per ack on the parent, the mirror
      pair on the worker).

    - SEAL/OPEN LATENCY: per-op percentiles for one bucket-sized
      packed wire buffer through ``EncryptedChannel`` directly (no
      cluster in the loop) — the per-frame cost floor an operator
      pays for ``cluster_encrypt=True``.

    - SIGKILL MID-ROTATION (process mode, encrypted): the corpse
      dies CONCURRENT with a cluster-wide ``rotate_epoch`` under an
      open window.  Whatever interleaving lands (rotation acked
      then killed, killed mid-ack, killed before), the survivors
      carry the new epoch, every undecryptable/unacked frame's rows
      are counted (``crypto_dropped``/``crash_dropped``), and the
      ledger closes EXACTLY — the chaos gate's claim, re-made as a
      shipped artifact."""
    import ipaddress
    import os as _os
    import threading as _threading

    from cilium_tpu.agent import DaemonConfig
    from cilium_tpu.cluster import ClusterServing
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY,
                                         COL_FLAGS, COL_LEN,
                                         COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS, TCP_ACK)

    BUCKET = 2048
    rng = np.random.default_rng(11)
    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))
    sports = (1024 + rng.permutation(50000)[:4096]).astype(np.uint32)

    def cfg(**over):
        base = dict(backend="tpu", ct_capacity=1 << 14,
                    flow_ring_capacity=1 << 13,
                    serving_queue_depth=1 << 15,
                    serving_bucket_ladder=(BUCKET,),
                    serving_max_wait_us=1000.0,
                    serving_restart_backoff_ms=1.0,
                    cluster_forward_depth=1 << 15,
                    cluster_probe_interval_s=0.05,
                    cluster_death_threshold=2)
        base.update(over)
        return DaemonConfig(**base)

    def batch(n, db_id):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        rows[:, COL_SPORT] = rng.choice(sports, n)
        rows[:, COL_DPORT] = 5432
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_ACK
        rows[:, COL_LEN] = 512
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = db_id
        return rows

    RULES = [{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [
            {"matchLabels": {"app": "web"}}],
            "toPorts": [{"ports": [{"port": "5432",
                                    "protocol": "TCP"}]}]}],
    }]

    def build(n_nodes, mode, **over):
        c = ClusterServing(nodes=n_nodes,
                           config=cfg(cluster_mode=mode, **over))
        c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        rev = c.policy_import(RULES)
        assert c.wait_policy(rev, timeout=30)
        c.start(trace_sample=0, packed=True, ring_capacity=1 << 15)
        return c, db

    fwd_latency = {}

    def leg(n_nodes, mode):
        """One scaling leg: an untimed settle wave (post-bring-up
        allocator/thread steady state), then offer chunks until
        target_packets are ADMITTED (backpressure-paced) and time to
        the last verdict LANDING — stop()/teardown cost (control
        RPCs, worker reaping) never bills the throughput number."""
        c, db = build(n_nodes, mode)
        try:
            chunks = [batch(BUCKET, db.id) for _ in range(8)]

            def accounted():
                return c.ledger()["per-node-accounted"]

            for i in range(4):  # settle wave, untimed
                c.submit(chunks[i])
            t0 = time.perf_counter()
            while accounted() < 4 * BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("settle wave stalled")
                time.sleep(0.002)
            base = accounted()
            admitted = i = 0
            t0 = time.perf_counter()
            while admitted < target_packets:
                got = c.submit(chunks[i % len(chunks)])
                admitted += got
                i += 1
                if got < BUCKET:
                    time.sleep(0.0005)  # router/queue full
            while accounted() - base < admitted:
                if time.perf_counter() - t0 > 300:
                    raise TimeoutError("scaling leg stalled")
                time.sleep(0.002)
            dt = time.perf_counter() - t0
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            if n_nodes == 3:
                fwd_latency[mode] = (st["cluster"]["router"]
                                     or {}).get("forward-latency-us")
            return admitted / dt
        finally:
            c.shutdown()
            # settle: worker teardown (process reap, socket close)
            # must not bleed CPU into the next leg's timed window
            time.sleep(0.5)

    modes_out = {}
    ledger_ok = True
    for mode in ("thread", "process"):
        # untimed warm leg: executables + thread/alloc steady state
        # must not bill the first timed rep (process workers warm
        # their own caches inside bring-up, off the timed window)
        leg(1, mode)
        n1_best = 0.0
        curve = {}
        for n_nodes in (2, 3):
            pair = paired_legs(lambda m=mode: leg(1, m),
                               lambda m=mode, n=n_nodes: leg(n, m),
                               reps=reps)
            n1_best = max(n1_best, pair["baseline_pps"])
            curve[n_nodes] = pair
        modes_out[mode] = {
            "sustained_pps_n1": round(n1_best),
            "sustained_pps_n2": curve[2]["candidate_pps"],
            "sustained_pps_n3": curve[3]["candidate_pps"],
            "scaling_n2": curve[2]["ratio_median"],
            "scaling_n3": curve[3]["ratio_median"],
            "scaling_n2_pairs": curve[2]["pairs"],
            "scaling_n3_pairs": curve[3]["pairs"],
            "scaling_n2_spread": curve[2]["spread"],
            "scaling_n3_spread": curve[3]["spread"],
            "forward_latency_us": fwd_latency.get(mode),
        }

    def failover_rep() -> dict:
        """SIGKILL failover under load, process mode: the PR 8
        blackout/CT-replay numbers against a real process corpse."""
        c, db = build(3, "process")
        try:
            warm = batch(BUCKET, db.id)
            c.submit(warm)
            t0 = time.perf_counter()
            while c.ledger()["per-node-accounted"] < BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("cluster bench stalled")
                time.sleep(0.002)
            c.snapshot_now()  # parent-retained replica per worker
            c.node("node1").proc.kill()  # raw SIGKILL mid-serve
            while not c.membership.is_dead("node1"):
                c.submit(batch(BUCKET, db.id))
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("death never detected")
                time.sleep(0.002)
            while c.failovers_total() < 1:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("failover never completed")
                time.sleep(0.002)
            rec = c.failover.snapshot()[0]
            # post-failover: the survivors keep serving
            c.submit(batch(BUCKET, db.id))
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            return {
                "blackout_ms": rec["blackout-ms"],
                "detect_ms": rec["detect-ms"],
                "ct_entries": rec["ct-replayed-entries"],
                "failover_dropped":
                    st["ledger"]["failover-dropped"],
                "crash_dropped": st["ledger"]["crash-dropped"],
                "ledger_exact": st["ledger"]["exact"],
            }
        finally:
            c.shutdown()

    fo = [failover_rep() for _ in range(reps)]
    best = min(fo, key=lambda r: r["blackout_ms"])
    ledger_ok = ledger_ok and all(r["ledger_exact"] for r in fo)

    def scale_out_leg() -> dict:
        """add_node() on a live 2-worker cluster under established
        flows: the pause window + CT migration + survivor compile
        counts, ledger exact across the transition."""
        c, db = build(2, "process")
        try:
            c.submit(batch(BUCKET, db.id))
            t0 = time.perf_counter()
            while c.ledger()["per-node-accounted"] < BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("scale-out leg stalled")
                time.sleep(0.002)
            rec = c.add_node()
            c.submit(batch(BUCKET, db.id))
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            return {
                "pause_ms": rec["pause-ms"],
                "build_ms": rec["build-ms"],
                "moved_slots": rec["moved-slots"],
                "ct_migrated_entries": rec["ct-migrated-entries"],
                "survivor_recompiles": rec["survivor-recompiles"],
                "ledger_exact": st["ledger"]["exact"],
            }
        finally:
            c.shutdown()

    so = scale_out_leg()
    ledger_ok = ledger_ok and so["ledger_exact"]

    # -- v3: the pipelined data channel (ISSUE 17) --------------------
    FRAME = 128       # small frames: the channel is ack-cadence-
    WAVE_FRAMES = 128  # bound, the regime the window exists for
    WAVES = 9
    WINDOW = cfg().cluster_forward_window  # the shipped default

    def window_leg(window: int, encrypt: bool = False) -> float:
        """Per-node forward throughput through ONE process-mode
        channel at the given credit window.  window=1 degenerates to
        the PR 13 sync-ack protocol (one frame in flight, one ack
        per frame, byte-identical wire) — the baseline side of the
        paired legs.  The timed interval per WAVE is push-from-idle
        to all-RETIRED (sync: the blocking submit returned = acked;
        windowed: the cumulative ack covered it) — the channel rate,
        with the worker's verdict pipeline draining UNTIMED between
        waves so the verdict executor's throughput does not cap both
        sides into a false tie.  Median-of-waves damps scheduler
        weather (this leg is switch-cost-sensitive on small hosts).
        HONESTY FLOOR: the overlap win (parent packs frame k+1 while
        the worker admits frame k) needs parent and worker on
        SEPARATE cores; a 1-core host time-slices them and the
        measured win shrinks to what ack-coalescing alone buys
        (fewer wakeups + 1/ack_every of the ack legs) — the >=2x
        claim needs ``host_cores`` >= 2, same convention as the
        scaling curve.  ``encrypt=True`` runs the identical leg
        with the channel sealed (the v4 paired comparison)."""
        c, db = build(1, "process", cluster_forward_window=window,
                      cluster_encrypt=encrypt)
        try:
            frames = [batch(FRAME, db.id) for _ in range(16)]
            wave_rows = WAVE_FRAMES * FRAME

            def accounted():
                return c.ledger()["per-node-accounted"]

            def fwd():
                # dirty read on purpose: a locked snapshot() in the
                # poll loop would stall the ack reader's retire path
                # and bill the contention to the thing measured
                return sum(c.router.forwarded)

            for i in range(8):  # settle wave, untimed
                c.submit(frames[i % len(frames)])
            t0 = time.perf_counter()
            while accounted() < 8 * FRAME:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("window settle stalled")
                time.sleep(0.002)
            rates = []
            for w in range(WAVES):
                # drain: worker queue empty before the timed push
                t0 = time.perf_counter()
                while accounted() < 8 * FRAME + w * wave_rows:
                    if time.perf_counter() - t0 > 120:
                        raise TimeoutError("window drain stalled")
                    time.sleep(0.002)
                f0 = fwd()
                t0 = time.perf_counter()
                for i in range(WAVE_FRAMES):
                    got = c.submit(frames[i % len(frames)])
                    assert got == FRAME, "router backpressured"
                while fwd() - f0 < wave_rows:
                    if time.perf_counter() - t0 > 120:
                        raise TimeoutError("window wave stalled")
                    time.sleep(0.0005)
                rates.append(wave_rows / (time.perf_counter() - t0))
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            rates.sort()
            return rates[len(rates) // 2]
        finally:
            c.shutdown()
            time.sleep(0.5)

    window_leg(WINDOW)  # untimed warm leg
    pipe = paired_legs(lambda: window_leg(1),
                       lambda: window_leg(WINDOW), reps=reps)

    def latency_leg(window: int) -> float:
        """Forward-latency p50 at LOW load: ONE small frame at a
        time, fully landed before the next, idle gaps in between —
        the regime where the worker's flush-on-drain acks each
        frame immediately (channel empty after the admit) and the
        window must not cost latency over the sync baseline."""
        c, db = build(1, "process", cluster_forward_window=window)
        try:
            def accounted():
                return c.ledger()["per-node-accounted"]

            done = 0
            for _ in range(192):
                c.submit(batch(64, db.id))
                done += 64
                t0 = time.perf_counter()
                while accounted() < done:
                    if time.perf_counter() - t0 > 60:
                        raise TimeoutError("latency leg stalled")
                    time.sleep(0.0005)
                time.sleep(0.002)  # low load: idle gap per frame
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            lat = (st["cluster"]["router"]
                   or {})["forward-latency-us"]
            return float(lat["p50"])
        finally:
            c.shutdown()
            time.sleep(0.5)

    lat_sync = latency_leg(1)
    lat_pipe = latency_leg(WINDOW)

    def sigkill_mid_window_rep() -> dict:
        """SIGKILL a worker with the credit window OPEN — frames
        sent-but-unacked at the corpse.  The last cumulative ack is
        the final word; everything past it requeues to the failover
        peer or lands ``crash_dropped``, and the ledger closes
        EXACTLY — the property test's claim against a real corpse
        under real load."""
        c, db = build(2, "process")
        try:
            c.submit(batch(BUCKET, db.id))
            t0 = time.perf_counter()
            while c.ledger()["per-node-accounted"] < BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("mid-window warm stalled")
                time.sleep(0.002)
            c.snapshot_now()  # parent-retained replica per worker
            # open the window: a burst of small frames, then the
            # kill lands while they are in flight
            for _ in range(64):
                c.submit(batch(FRAME, db.id))
            win = (c.router.snapshot().get("window") or {})
            inflight_at_kill = win.get("inflight-frames", 0)
            c.node("node1").proc.kill()  # raw SIGKILL mid-window
            while not c.membership.is_dead("node1"):
                c.submit(batch(FRAME, db.id))
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("death never detected")
                time.sleep(0.002)
            while c.failovers_total() < 1:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("failover never completed")
                time.sleep(0.002)
            c.submit(batch(BUCKET, db.id))  # survivor keeps serving
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            return {
                "inflight_frames_at_kill": inflight_at_kill,
                "crash_dropped": st["ledger"]["crash-dropped"],
                "failover_dropped":
                    st["ledger"]["failover-dropped"],
                "ledger_exact": st["ledger"]["exact"],
            }
        finally:
            c.shutdown()

    skw = [sigkill_mid_window_rep() for _ in range(reps)]
    ledger_ok = ledger_ok and all(r["ledger_exact"] for r in skw)

    def scale_in_leg() -> dict:
        """remove_node() on a live 3-worker cluster under
        established flows: quiesce (window drained), victim CT
        migrated onto survivors, slots re-pinned, victim retired —
        with ZERO survivor recompiles and the ledger exact across
        the transition."""
        c, db = build(3, "process")
        try:
            c.submit(batch(BUCKET, db.id))
            t0 = time.perf_counter()
            while c.ledger()["per-node-accounted"] < BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("scale-in leg stalled")
                time.sleep(0.002)
            rec = c.remove_node()
            c.submit(batch(BUCKET, db.id))
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            return {
                "pause_ms": rec["pause-ms"],
                "moved_slots": rec["moved-slots"],
                "ct_migrated_entries": rec["ct-migrated-entries"],
                "survivor_recompiles": rec["survivor-recompiles"],
                "ledger_exact": st["ledger"]["exact"],
            }
        finally:
            c.shutdown()

    si = scale_in_leg()
    ledger_ok = ledger_ok and si["ledger_exact"]

    # -- v4: the encrypted data channel (ISSUE 18) --------------------
    enc = paired_legs(lambda: window_leg(WINDOW, encrypt=False),
                      lambda: window_leg(WINDOW, encrypt=True),
                      reps=reps)

    def crypto_latency() -> tuple:
        """Per-op seal/open percentiles through the channel itself
        (no cluster in the loop): one bucket-sized packed wire
        buffer (BUCKET packets x 16 B), the unit the transport
        actually seals."""
        from cilium_tpu.encryption import (EncryptedChannel,
                                           NodeKeypair)

        a, b = NodeKeypair(), NodeKeypair()
        tx = EncryptedChannel(a, b.public)
        rx = EncryptedChannel(b, a.public)
        payload = np.ascontiguousarray(
            batch(BUCKET, 1)[:, :4]).tobytes()
        seal_ns, open_ns = [], []
        t_end = time.perf_counter() + 2.0  # time-boxed: the pure-
        # python fallback must not stall the phase
        for _ in range(512):
            t0 = time.perf_counter_ns()
            frame = tx.seal(payload)
            t1 = time.perf_counter_ns()
            rx.open(frame)
            t2 = time.perf_counter_ns()
            seal_ns.append(t1 - t0)
            open_ns.append(t2 - t1)
            if time.perf_counter() > t_end and len(seal_ns) >= 32:
                break

        def pct(v):
            v = sorted(v)
            return {"p50": round(v[len(v) // 2] / 1e3, 2),
                    "p90": round(v[(len(v) * 9) // 10] / 1e3, 2),
                    "p99": round(v[(len(v) * 99) // 100] / 1e3, 2),
                    "n": len(v),
                    "payload_bytes": len(payload)}

        return pct(seal_ns), pct(open_ns)

    seal_lat, open_lat = crypto_latency()

    def sigkill_mid_rotation_rep() -> dict:
        """SIGKILL one worker CONCURRENT with rotate_epoch on an
        encrypted 2-worker cluster with the window open: survivors
        carry the new epoch, the corpse's debt is counted, ledger
        exact (the chaos gate's claim as a shipped number)."""
        c, db = build(2, "process", cluster_encrypt=True)
        try:
            c.submit(batch(BUCKET, db.id))
            t0 = time.perf_counter()
            while c.ledger()["per-node-accounted"] < BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("mid-rotation warm stalled")
                time.sleep(0.002)
            c.snapshot_now()  # parent-retained replica per worker
            for _ in range(64):  # open the window
                c.submit(batch(FRAME, db.id))
            killer = _threading.Thread(
                target=lambda: (time.sleep(0.002),
                                c.node("node1").proc.kill()))
            killer.start()
            rot = c.rotate_epoch()  # races the kill: any
            # interleaving must land counted, never hung
            killer.join()
            while not c.membership.is_dead("node1"):
                c.submit(batch(FRAME, db.id))
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("death never detected")
                time.sleep(0.002)
            while c.failovers_total() < 1:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("failover never completed")
                time.sleep(0.002)
            c.submit(batch(BUCKET, db.id))  # survivor at new epoch
            st = c.stop()
            assert st["ledger"]["exact"], st["ledger"]
            return {
                "epoch": rot["epoch"],
                "rotation_acked": rot["acked"],
                "rotation_failed": [f["node"]
                                    for f in rot.get("failed", ())],
                "crash_dropped": st["ledger"]["crash-dropped"],
                "crypto_dropped": st["ledger"]["crypto-dropped"],
                "failover_dropped":
                    st["ledger"]["failover-dropped"],
                "ledger_exact": st["ledger"]["exact"],
            }
        finally:
            c.shutdown()

    skr = [sigkill_mid_rotation_rep() for _ in range(reps)]
    ledger_ok = ledger_ok and all(r["ledger_exact"] for r in skr)

    proc = modes_out["process"]
    return {
        "schema": "bench-cluster-v4",
        "best_of": reps,
        "host_cores": _os.cpu_count(),
        "mode": "process",  # the headline curve below
        "sustained_pps_n1": proc["sustained_pps_n1"],
        "sustained_pps_n2": proc["sustained_pps_n2"],
        "sustained_pps_n3": proc["sustained_pps_n3"],
        "scaling_n2": proc["scaling_n2"],
        "scaling_n3": proc["scaling_n3"],
        "modes": modes_out,
        "forward_latency_us": fwd_latency.get("process"),
        "failover_blackout_ms": best["blackout_ms"],
        "failover_detect_ms": best["detect_ms"],
        "failover_ct_entries": best["ct_entries"],
        "failover_dropped": best["failover_dropped"],
        "failover_crash_dropped": best["crash_dropped"],
        "failover_mode": "process",
        "failover_reps": fo,
        "scale_out": so,
        # -- v3: the pipelined data channel (ISSUE 17) ----------------
        "forward_window": WINDOW,
        "pipelined_speedup": pipe["ratio_median"],
        "pipelined_speedup_pairs": pipe["pairs"],
        "pipelined_speedup_spread": pipe["spread"],
        "latency_p50_sync_us": lat_sync,
        "latency_p50_pipelined_us": lat_pipe,
        "latency_p50_ratio": (round(lat_pipe / lat_sync, 4)
                              if lat_sync else None),
        # headline rep: the one killed with the MOST frames in
        # flight — the deepest mid-window corpse the run produced
        "sigkill_mid_window": max(
            skw, key=lambda r: r["inflight_frames_at_kill"]),
        "sigkill_mid_window_reps": skw,
        "scale_in": si,
        # -- v4: the encrypted data channel (ISSUE 18) ----------------
        "encrypted_pps": enc["candidate_pps"],
        "plaintext_pps": enc["baseline_pps"],
        "encrypted_ratio": enc["ratio_median"],
        "encrypted_ratio_pairs": enc["pairs"],
        "encrypted_ratio_spread": enc["spread"],
        "seal_latency_us": seal_lat,
        "open_latency_us": open_lat,
        # headline rep: the one whose rotation saw a FAILED node —
        # the deepest kill/rotate interleaving the run produced
        "sigkill_mid_rotation": max(
            skr, key=lambda r: (len(r["rotation_failed"]),
                                r["crypto_dropped"])),
        "sigkill_mid_rotation_reps": skr,
        "ledger_exact": ledger_ok,
    }


def _run_cluster_phase() -> None:
    """--cluster: the clustermesh serving tier phase standalone (one
    JSON line).  Also writes BENCH_cluster.json next to this file —
    schema-checked by CTA008 (scripts/check_cluster_ledger.py);
    bounded under JAX_PLATFORMS=cpu."""
    import os

    out = bench_cluster()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_cluster.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def bench_obs(target_packets=1 << 20, reps=3) -> dict:
    """--obs: the cluster observability relay phase (ISSUE 14) ->
    BENCH_obs.json.

    One question, answered the paired-leg way: what does the relay
    COST?  Two legs on an identical 2-worker process cluster under
    the same backpressure-paced load, interleaved order-alternating
    (``paired_legs``):

    - OFF: ``cluster_obs_interval_s=0`` (no scrape loop),
      ``cluster_trace_sample=0`` (no trace context on the wire);
    - ON: a 0.25 s scrape cadence (every tick pulls each worker's
      registry exposition + flow tail + top-K + tracer + incidents
      over the control channel) AND 1-in-64 forwarded chunks carrying
      cross-process trace context.

    ``scrape_overhead_ratio`` is the PAIR-MEDIAN of on/off — the
    acceptance floor is >= 0.95.  What makes it hold structurally
    (not by luck) is the relay's scrape DUTY GOVERNOR
    (``obs/relay.SCRAPE_DUTY``, 2%): a worker answering
    ``obs_scrape`` spends its own core rendering the registry
    (including a device metricsmap fetch that waits out queued
    dispatches) / draining analytics / materializing the flow tail —
    ~0.2-0.4 s per sweep on this saturated 1-core box, and the RTT
    percentiles shipped here ARE that cost.  The loop therefore
    treats ``interval_s`` as a cadence CEILING and stretches its
    delay to keep sweep time under the duty fraction — the
    flow-analytics ``max_duty`` idiom one level up.  The timed
    window is sized to several seconds so it reads the governed
    steady state, not a single worst-case sweep: ungoverned 0.25 s
    cadence measured 0.72-0.77 on this box (that experiment is why
    the governor exists), governed runs clear the floor.

    v2 (ISSUE 19) adds two more numbers:

    - ``sampler_overhead_ratio``: a second paired-leg pair, single
      daemon under the same ingress overload, the SLO plane's
      sampler (history rings + burn evaluation, the `slo-sampler`
      thread) armed at an aggressive 0.25 s cadence vs off.  The
      same duty governor (``slo_max_duty``) defends this ratio: a
      tick's cost stretches the next delay, so sampling never
      claims more than the duty fraction of wall clock.
    - ``burn_detect_s``: detection latency of the shipped
      multi-window config for a seeded admission-shed burst, on a
      FAKE 10 s-tick timeline (deterministic — it characterizes the
      window math, not machine weather): fake seconds from the
      burst to the serving-availability SLO's page verdict."""
    import ipaddress

    from cilium_tpu.agent import DaemonConfig
    from cilium_tpu.cluster import ClusterServing
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                         COL_EP, COL_FAMILY,
                                         COL_FLAGS, COL_LEN,
                                         COL_PROTO, COL_SPORT,
                                         COL_SRC_IP3, N_COLS, TCP_ACK)

    BUCKET = 2048
    rng = np.random.default_rng(14)
    src = int(ipaddress.IPv4Address("10.0.1.1"))
    dst = int(ipaddress.IPv4Address("10.0.2.1"))
    sports = (1024 + rng.permutation(50000)[:4096]).astype(np.uint32)

    def cfg(obs: bool):
        return DaemonConfig(
            backend="tpu", ct_capacity=1 << 14,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=1 << 15,
            serving_bucket_ladder=(BUCKET,),
            serving_max_wait_us=1000.0,
            serving_restart_backoff_ms=1.0,
            cluster_forward_depth=1 << 15,
            cluster_probe_interval_s=0.25,
            cluster_death_threshold=2,
            cluster_mode="process",
            cluster_obs_interval_s=0.25 if obs else 0.0,
            cluster_trace_sample=64 if obs else 0)

    def batch(n, db_id):
        rows = np.zeros((n, N_COLS), dtype=np.uint32)
        rows[:, COL_SRC_IP3] = src
        rows[:, COL_DST_IP3] = dst
        rows[:, COL_SPORT] = rng.choice(sports, n)
        rows[:, COL_DPORT] = 5432
        rows[:, COL_PROTO] = 6
        rows[:, COL_FLAGS] = TCP_ACK
        rows[:, COL_LEN] = 512
        rows[:, COL_FAMILY] = 4
        rows[:, COL_EP] = db_id
        return rows

    RULES = [{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [
            {"matchLabels": {"app": "web"}}],
            "toPorts": [{"ports": [{"port": "5432",
                                    "protocol": "TCP"}]}]}],
    }]
    extras = {"ledger_exact": True}

    def leg(obs: bool):
        c = ClusterServing(nodes=2, config=cfg(obs))
        try:
            c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
            db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
            rev = c.policy_import(RULES)
            assert c.wait_policy(rev, timeout=30)
            c.start(trace_sample=0, packed=True,
                    ring_capacity=1 << 15)
            chunks = [batch(BUCKET, db.id) for _ in range(8)]

            def accounted():
                return c.ledger()["per-node-accounted"]

            for i in range(4):  # settle wave, untimed
                c.submit(chunks[i])
            t0 = time.perf_counter()
            while accounted() < 4 * BUCKET:
                if time.perf_counter() - t0 > 120:
                    raise TimeoutError("obs settle wave stalled")
                time.sleep(0.002)
            base = accounted()
            admitted = i = 0
            t0 = time.perf_counter()
            while admitted < target_packets:
                got = c.submit(chunks[i % len(chunks)])
                admitted += got
                i += 1
                if got < BUCKET:
                    time.sleep(0.0005)
            while accounted() - base < admitted:
                if time.perf_counter() - t0 > 300:
                    raise TimeoutError("obs leg stalled")
                time.sleep(0.002)
            dt = time.perf_counter() - t0
            st = c.stop()
            extras["ledger_exact"] = (extras["ledger_exact"]
                                      and st["ledger"]["exact"])
            if obs:
                ob = st.get("obs") or {}
                extras["obs"] = {
                    "scrapes": ob.get("scrapes"),
                    "scrape_errors": ob.get("scrape-errors"),
                    "rtt_us": ob.get("rtt-us"),
                    "spans": (ob.get("spans") or {}),
                }
            return admitted / dt
        finally:
            c.shutdown()
            time.sleep(0.5)

    leg(False)  # untimed warm leg (executable/thread steady state)
    pair = paired_legs(lambda: leg(False), lambda: leg(True),
                       reps=reps)

    # ---- sampler tax (ISSUE 19): ONE daemon, same ingress overload
    # loop, the history+SLO sampler armed at a 0.25 s cadence vs
    # stopped — paired order-alternating like the relay legs.  One
    # daemon (not one per leg) so both legs share executables and
    # thread steady state; only the `slo-sampler` thread differs.
    from cilium_tpu.agent import Daemon

    s_target = max(target_packets // 4, 64 * BUCKET)
    d = Daemon(DaemonConfig(
        backend="tpu", ct_capacity=1 << 14,
        flow_ring_capacity=1 << 13,
        serving_queue_depth=1 << 15,
        serving_bucket_ladder=(BUCKET,),
        serving_max_wait_us=1000.0,
        history_interval=0.25))
    try:
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db_l = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        # warm occupancy sample (the daemon.start idiom): the armed
        # sampler reads the occupancy gauges, and their executable
        # must compile before any timed serving window
        d.pressure.sample()
        chunks = [batch(BUCKET, db_l.id) for _ in range(8)]

        def sampler_leg(armed: bool):
            if armed:
                d.slo.start()
            else:
                d.slo.stop()
            d.start_serving(ring_capacity=1 << 15, trace_sample=0,
                            ingress=True, packed=True)
            admitted = i = 0
            t0 = time.perf_counter()
            while admitted < s_target:
                got = d.submit(chunks[i % len(chunks)])
                admitted += got
                i += 1
                if got < BUCKET:
                    time.sleep(0.0005)  # queue full: backpressure
            stats = d.stop_serving()  # drains everything admitted
            dt = time.perf_counter() - t0
            return stats["front-end"]["verdicts"] / dt

        sampler_leg(False)  # untimed warm (compiles + steady state)
        spair = paired_legs(lambda: sampler_leg(False),
                            lambda: sampler_leg(True), reps=reps)
        sampler_ticks = d.slo.ticks
    finally:
        d.slo.stop()
        d.shutdown()

    burn_detect_s = _obs_burn_detect(batch, RULES, BUCKET)
    ob = extras.get("obs") or {}
    return {
        "schema": "bench-obs-v2",
        "best_of": reps,
        "sustained_pps_noobs": pair["baseline_pps"],
        "sustained_pps_obs": pair["candidate_pps"],
        "scrape_overhead_ratio": pair["ratio_median"],
        "scrape_overhead_pairs": pair["pairs"],
        "scrape_overhead_spread": pair["spread"],
        "scrape_rtt_us": ob.get("rtt_us"),
        "scrapes_total": ob.get("scrapes"),
        "scrape_errors": ob.get("scrape_errors"),
        "stitched_spans": (ob.get("spans") or {}).get("committed"),
        "spans_dropped": (ob.get("spans") or {}).get("dropped"),
        "ledger_exact": extras["ledger_exact"],
        "sampler_overhead_ratio": spair["ratio_median"],
        "sampler_overhead_pairs": spair["pairs"],
        "sampler_overhead_spread": spair["spread"],
        "sampler_pps_off": spair["baseline_pps"],
        "sampler_pps_armed": spair["candidate_pps"],
        "sampler_ticks": sampler_ticks,
        "burn_detect_s": burn_detect_s,
    }


def _obs_burn_detect(batch, rules, bucket) -> float:
    """``burn_detect_s``: fake seconds from a seeded admission-shed
    burst to the serving-availability SLO's page verdict, at the
    shipped multi-window config on a 10 s tick cadence.

    Deterministic by construction: the engine's clocks are
    injectable, so the timeline is fake (the number characterizes
    the burn-rate window math, not this box), while the COUNTERS are
    real — a healthy baseline covers the slow window, then a burst
    overflows the admission queue and the real shed ledger (exact,
    flushed by the drain thread) is what burns the budget."""
    from cilium_tpu.agent import Daemon, DaemonConfig

    d = Daemon(DaemonConfig(
        backend="tpu", ct_capacity=1 << 14,
        flow_ring_capacity=1 << 13,
        serving_queue_depth=1 << 15,
        serving_bucket_ladder=(bucket,),
        serving_max_wait_us=1000.0,
        history_interval=0.0))  # no sampler thread: tick() driven
    try:
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(rules)
        d.pressure.sample()  # occupancy executable, pre-session
        d.start_serving(ring_capacity=1 << 15, trace_sample=0,
                        ingress=True, packed=True)
        step, t, w0 = 10.0, 0.0, 1.7e9
        # healthy baseline covering the slow window: 256-row chunks
        # can never overflow the 2^15 queue even undrained, so the
        # burst below is the FIRST shed the ledger ever sees
        rows = batch(256, db.id)
        for _ in range(int(d.config.slo_slow_window / step) + 1):
            d.submit(rows.copy())
            d.slo.tick(now=t, wall=w0 + t)
            t += step
        ev = d.slo.last["evals"]["serving-availability"]
        assert ev["state"] == "ok", ev
        t_burst = t
        burst = [batch(bucket, db.id) for _ in range(8)]
        shed = 0
        for i in range(64):
            shed += bucket - d.submit(burst[i % len(burst)].copy())
        assert shed > 0, "burst never overflowed admission"
        # the exact shed ledger flushes on drain activity — wait for
        # the registry (what the sampler reads) to surface all of it
        t0 = time.perf_counter()
        while (d.registry.sample(("cilium_serving_shed_total",))
               .get("cilium_serving_shed_total", 0)) < shed:
            if time.perf_counter() - t0 > 120:
                raise TimeoutError("shed ledger never surfaced")
            time.sleep(0.002)
        detect = None
        for _ in range(60):
            t += step
            out = d.slo.tick(now=t, wall=w0 + t)
            if (out["evals"]["serving-availability"]["state"]
                    == "page"):
                detect = t - t_burst
                break
        assert detect is not None, "seeded burst never paged"
        d.stop_serving()
        return detect
    finally:
        d.shutdown()


def _run_obs_phase() -> None:
    """--obs: the cluster observability relay phase standalone (one
    JSON line).  Also writes BENCH_obs.json next to this file —
    schema-checked by CTA014 (analysis/slo_lint.check_bench);
    bounded under JAX_PLATFORMS=cpu."""
    import os

    out = bench_obs()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_obs.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


def bench_anomaly() -> dict:
    """BASELINE eval config #5 in a SUBPROCESS: a fresh process gets a
    fresh tunnel session, so the training loop (fetch-free) and this
    process's phases cannot degrade each other."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "cilium_tpu.ml.evaluate"],
            capture_output=True, text=True, timeout=1800)
        line = proc.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:  # bench must still print its JSON line
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _phase_subprocess(flag: str, timeout: int = 1800) -> dict:
    """Run one bench phase in a FRESH process (fresh tunnel session).

    The r02-documented axon pathology: the first device->host fetch of
    a process permanently degrades every subsequent dispatch by
    ~4.5 s — so any transfer phase that runs AFTER another phase's
    end-of-run drain measures the artifact, not the design (verified:
    e2e #1 in a process does 37M pps, e2e #2 does 0.1M).  Each
    drain-bounded phase therefore gets its own process."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, __file__, flag],
            capture_output=True, text=True, timeout=timeout)
        line = proc.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _run_device_phase() -> None:
    """--device: the fused-pipeline headline phase standalone (one
    JSON line).  Ends with the process's single d2h fetch (occupancy
    scalar), which pays the whole phase's queued-dispatch toll —
    bounded here instead of compounding into the e2e phase."""
    import jax.numpy as jnp

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21,
                        n_v6=256)
    dev_pps, state, _now, detail = bench_device(world, jnp,
                                                datapath_step_jit)
    detail["ct_occupied"] = int(np.asarray(detail.pop("ct_occupied_dev")))
    print(json.dumps({"pps": round(dev_pps), "detail": detail}))


def _run_e2e_phase() -> None:
    """--e2e: the packed ingest end-to-end phase standalone (one JSON
    line).  Fresh process = fresh CT (its pool warmup establishes the
    steady state); r04 ran it after the device phase in-process, so
    its CT carried ~1M background entries — the fresh-process number
    has slightly lighter probe pressure (noted in the output)."""
    import jax
    import jax.numpy as jnp

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21,
                        n_v6=256)
    out, _state = bench_end_to_end(world, world.state, 1_001, jax,
                                   jnp, datapath_step_jit)
    out["fresh_process"] = True
    print(json.dumps(out))


def _run_artifact_phase() -> None:
    """--artifact: the naive fetch-per-batch path standalone (one
    JSON line)."""
    import jax
    import jax.numpy as jnp

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21)
    out = bench_full_readback(world, world.state, 1_000, jax, jnp,
                              datapath_step_jit)
    print(json.dumps(out))


def _run_wide_phase() -> None:
    """--wide: the wide-path phase standalone (one JSON line)."""
    import jax
    import jax.numpy as jnp

    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21,
                        n_v6=256)
    out, _state = bench_end_to_end_wide(world, world.state, 1_000,
                                        jax, jnp)
    print(json.dumps(out))


def _run_ring_phase() -> None:
    """--ring: the steady-drain phase standalone (one JSON line)."""
    import jax
    import jax.numpy as jnp

    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21)
    out, _state = bench_ring_steady_state(world, world.state, 1_000,
                                          jax, jnp)
    print(json.dumps(out))


def main() -> None:
    # r05: EVERY tpu phase runs in its own bounded subprocess.  Two
    # reasons: (a) each process's first d2h fetch pays the tunnel's
    # ~12 s per prior big dispatch, so phases must not inherit each
    # other's dispatch debt (r04 paid the device phase's 144-dispatch
    # debt inside the e2e phase — tens of minutes in one unbounded
    # fetch); (b) a wedged tunnel RPC now costs ONE phase its
    # timeout, not the whole bench — the JSON line always prints.
    device = _phase_subprocess("--device", timeout=2100)
    e2e = _phase_subprocess("--e2e", timeout=2100)
    e2e_wide = _phase_subprocess("--wide")
    ring_ss = _phase_subprocess("--ring")
    socklb = _phase_subprocess("--socklb")
    serving = _phase_subprocess("--serving")
    recovery = _phase_subprocess("--recovery")
    cluster = _phase_subprocess("--cluster")
    obs = _phase_subprocess("--obs")
    churn = _phase_subprocess("--churn")
    scenarios = _phase_subprocess("--scenarios")
    artifact = _phase_subprocess("--artifact")
    l7 = _phase_subprocess("--l7")
    anomaly = bench_anomaly()
    encryption = bench_encryption()
    dev_pps = device.get("pps", 0) or 0
    print(json.dumps({
        "metric": "policy_verdicts_per_sec_per_chip",
        "value": round(dev_pps),
        "unit": "verdicts/s",
        "vs_baseline": round(dev_pps / BASELINE_PPS, 3),
        "device_detail": device.get("detail", device),
        "end_to_end": e2e,
        "end_to_end_wide": e2e_wide,
        "ring_steady_state": ring_ss,
        "socket_lb": socklb,
        "serving": serving,
        "recovery": recovery,
        "cluster": cluster,
        "obs": obs,
        "churn": churn,
        "scenarios": scenarios,
        "d2h_artifact": artifact,
        "l7": l7,
        "encryption": encryption,
        "anomaly_auc": anomaly.get("value"),
        "anomaly": anomaly,
    }))


if __name__ == "__main__":
    import sys

    if "--device" in sys.argv:
        _run_device_phase()
    elif "--e2e" in sys.argv:
        _run_e2e_phase()
    elif "--artifact" in sys.argv:
        _run_artifact_phase()
    elif "--wide" in sys.argv:
        _run_wide_phase()
    elif "--ring" in sys.argv:
        _run_ring_phase()
    elif "--socklb" in sys.argv:
        _run_socklb_phase()
    elif "--serving" in sys.argv:
        _run_serving_phase()
    elif "--recovery" in sys.argv:
        _run_recovery_phase()
    elif "--cluster" in sys.argv:
        _run_cluster_phase()
    elif "--obs" in sys.argv:
        _run_obs_phase()
    elif "--churn" in sys.argv:
        _run_churn_phase()
    elif "--scenarios" in sys.argv:
        _run_scenarios_phase()
    elif "--l7" in sys.argv:
        _run_l7_phase()
    else:
        main()
