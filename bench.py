#!/usr/bin/env python
"""Headline benchmark: policy verdicts/sec on one chip.

BASELINE.md north-star: >= 10M policy verdicts/sec on one TPU v5e chip
over the 10k-identity L3/L4 policy set, <= 1% divergence vs the oracle.

Runs the full fused pipeline (ipcache LPM -> conntrack -> policy ->
ct-create -> events) on synthetic steady-state traffic (95% established
/ 5% new flows), replaying a pool of pre-generated batches.  Prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import bench_traffic, build_world

    batch_size = 1 << 17  # 131072 packets/batch
    n_pool = 4
    iters = 30

    world = build_world(n_identities=10_000, ct_capacity=1 << 21)
    rng = np.random.default_rng(0)
    pool = [jnp.asarray(bench_traffic(world, batch_size, rng))
            for _ in range(n_pool)]
    state = world.state
    now = jnp.uint32(1_000)

    # warmup: compile + populate CT with the steady-state flows
    for b in pool:
        out, state = datapath_step_jit(state, b, now)
    out.block_until_ready()

    t0 = time.perf_counter()
    for i in range(iters):
        out, state = datapath_step_jit(state, pool[i % n_pool], now)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    pps = batch_size * iters / dt
    baseline = 10_000_000.0  # north-star target
    print(json.dumps({
        "metric": "policy_verdicts_per_sec_per_chip",
        "value": round(pps),
        "unit": "verdicts/s",
        "vs_baseline": round(pps / baseline, 3),
    }))


if __name__ == "__main__":
    main()
