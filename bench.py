#!/usr/bin/env python
"""Headline benchmark: policy verdicts/sec on one chip.

BASELINE.md north-star: >= 10M policy verdicts/sec on one TPU v5e chip
over the 10k-identity L3/L4 policy set, <= 1% divergence vs the oracle.

Two phases, one JSON line:

1. **device** — the fused pipeline (ipcache LPM -> conntrack -> policy
   -> ct-create -> events) replaying pre-staged device batches: the
   kernel-rate ceiling (headline metric, matches BASELINE's
   verdicts/s/chip definition).
2. **end_to_end** — the honest number: raw ethernet frames in host
   memory -> native C++ parse -> header tensor -> device_put -> fused
   pipeline -> device event ring (compacted drops/verdicts/sampled
   traces, monitor/ring.py) -> single host drain.  Non-replayed
   traffic (every batch distinct), advancing clock.

   The event-ring architecture mirrors the reference (the kernel
   streams *events* through the perf ring and counts the rest in the
   metricsmap; it does not copy every packet to userspace).  It also
   sidesteps a measured harness artifact: on the tunneled-TPU bench
   host, ANY device->host fetch permanently degrades subsequent
   executions by ~4.5 s each (axon tunnel pathology, measured and
   reported below as d2h_artifact) — so the hot loop must be
   fetch-free, which the ring design is anyway.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline",
"end_to_end": {...}} — extra keys carry the e2e numbers + bottleneck
split.
"""

import json
import time

import numpy as np

BATCH = 1 << 17  # 131072 packets/batch
BASELINE_PPS = 10_000_000.0  # north-star target


def bench_device(world, jnp, datapath_step_jit, iters=20):
    from cilium_tpu.testing.fixtures import bench_traffic

    rng = np.random.default_rng(0)
    pool = [jnp.asarray(bench_traffic(world, BATCH, rng))
            for _ in range(4)]
    state = world.state
    now = 1_000
    for b in pool:  # warmup: compile + seed steady-state CT
        out, state = datapath_step_jit(state, b, jnp.uint32(now))
    out.block_until_ready()
    t0 = time.perf_counter()
    for i in range(iters):
        now += 1
        out, state = datapath_step_jit(state, pool[i % 4],
                                       jnp.uint32(now))
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return BATCH * iters / dt, state, now


def bench_end_to_end(world, state, now0, jax, jnp, datapath_step_jit,
                     iters=16):
    """Host frames -> device verdicts + event ring; one drain at end."""
    from cilium_tpu import native
    from cilium_tpu.core.ingest import frames_from_batch, parse_frames
    from cilium_tpu.monitor.ring import (EventRing, ring_append_jit,
                                         ring_drain)
    from cilium_tpu.testing.fixtures import steady_flow_pool, steady_traffic

    rng = np.random.default_rng(1)
    # bounded flow pool: replaying it once establishes the steady state
    # (95% established / 5% new / 2% scan-drops thereafter)
    pool = steady_flow_pool(world, 2 * BATCH, rng)
    # distinct traffic every iteration — nothing replays
    frame_bufs = [frames_from_batch(steady_traffic(pool, BATCH, rng))
                  for _ in range(iters)]
    wire_bytes = sum(len(b) for b in frame_bufs)

    # parse-stage rate alone (for the bottleneck split); warm first so
    # the one-time g++ compile/dlopen of the native lib isn't timed
    native.available()
    parse_frames(frame_bufs[0][: 1 << 12])
    t0 = time.perf_counter()
    rows0 = parse_frames(frame_bufs[0])
    parse_dt = time.perf_counter() - t0
    parse_pps = len(rows0) / parse_dt

    ring = EventRing.create(1 << 18)
    # warmup: establish the pool's flows in CT + compile the e2e shapes
    # — NO host fetch (see module doc)
    for chunk in pool.reshape(2, BATCH, -1):
        out, state = datapath_step_jit(state, jnp.asarray(chunk),
                                       jnp.uint32(now0))
    out, state = datapath_step_jit(state, jnp.asarray(rows0),
                                   jnp.uint32(now0))
    ring = ring_append_jit(ring, out, jnp.uint32(0))
    ring.cursor.block_until_ready()

    # two dispatches per batch (step, append) pipelines better through
    # the tunnel than the fused serve_step on this harness; real
    # deployments should prefer monitor.ring.serve_step_jit (one
    # dispatch, compaction fused into the datapath executable)
    t0 = time.perf_counter()
    for i, buf in enumerate(frame_bufs):
        rows = parse_frames(buf)  # host: native C++
        dev = jax.device_put(rows)  # h2d (async)
        out, state = datapath_step_jit(state, dev,
                                       jnp.uint32(now0 + 1 + i))
        ring = ring_append_jit(ring, out, jnp.uint32(i + 1))
    ring.cursor.block_until_ready()
    dt = time.perf_counter() - t0

    # the monitor's drain: the ONE host fetch, outside the hot loop
    t0 = time.perf_counter()
    events, total, lost = ring_drain(ring)
    drain_dt = time.perf_counter() - t0

    return {
        "verdicts_per_sec": round(BATCH * iters / dt),
        "vs_target_10M": round(BATCH * iters / dt / BASELINE_PPS, 3),
        "wire_gbps": round(wire_bytes * 8 / dt / 1e9, 2),
        "parse_stage_pps": round(parse_pps),
        "native_ingest": native.available(),
        "batches": iters,
        "batch_size": BATCH,
        "events_streamed": int(total),
        "events_lost": int(lost),
        "ring_drain_ms": round(drain_dt * 1e3, 1),
    }, state


def bench_full_readback(world, state, now0, jax, jnp,
                        datapath_step_jit, iters=2):
    """The naive path (full out tensor fetched per batch) — measures
    the harness's d2h artifact; runs LAST because the first fetch
    permanently degrades this process's executions (~4.5s each on the
    tunneled bench host; sub-ms on directly-attached TPUs)."""
    from cilium_tpu.core.ingest import frames_from_batch, parse_frames
    from cilium_tpu.testing.fixtures import bench_traffic

    rng = np.random.default_rng(2)
    bufs = [frames_from_batch(bench_traffic(world, BATCH, rng))
            for _ in range(iters)]
    t0 = time.perf_counter()
    for i, buf in enumerate(bufs):
        rows = parse_frames(buf)
        out, state = datapath_step_jit(state, jax.device_put(rows),
                                       jnp.uint32(now0 + i))
        np.asarray(out)  # full 24B/pkt readback
    dt = time.perf_counter() - t0
    return {
        "verdicts_per_sec": round(BATCH * iters / dt),
        "note": "full per-packet readback; dominated by the harness "
                "d2h artifact on tunneled TPUs",
    }


def bench_anomaly() -> dict:
    """BASELINE eval config #5 in a SUBPROCESS: a fresh process gets a
    fresh tunnel session, so the training loop (fetch-free) and this
    process's phases cannot degrade each other."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "cilium_tpu.ml.evaluate"],
            capture_output=True, text=True, timeout=900)
        line = proc.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:  # bench must still print its JSON line
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def main() -> None:
    import jax
    import jax.numpy as jnp

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=10_000, ct_capacity=1 << 21)
    dev_pps, state, now = bench_device(world, jnp, datapath_step_jit)
    e2e, state = bench_end_to_end(world, state, now + 1, jax, jnp,
                                  datapath_step_jit)
    artifact = bench_full_readback(world, state, now + 100, jax, jnp,
                                   datapath_step_jit)
    anomaly = bench_anomaly()
    print(json.dumps({
        "metric": "policy_verdicts_per_sec_per_chip",
        "value": round(dev_pps),
        "unit": "verdicts/s",
        "vs_baseline": round(dev_pps / BASELINE_PPS, 3),
        "end_to_end": e2e,
        "d2h_artifact": artifact,
        "anomaly_auc": anomaly.get("value"),
        "anomaly": anomaly,
    }))


if __name__ == "__main__":
    main()
