"""Process-per-node cluster serving (ISSUE 13 tentpole): real worker
processes behind the flow-affine router, row forwarding over real
sockets, and SIGKILL chaos with the cluster ledger exact.

Acceptance:
(a) a 2-process cluster serves with the cluster-wide ledger EXACT,
    eligible chunks riding the packed 16 B/packet wire;
(b) mid-forward SIGKILL (a raw ``proc.kill()``, not a cooperative
    crash): the health path detects the corpse, failover replays the
    parent-retained CT snapshot onto the peer, replies for
    pre-failover flows pass the peer's egress enforcement (metrics
    delta: zero new drops), and the ledger closes EXACTLY with the
    admitted-but-unresolved rows counted ``crash_dropped`` and the
    in-flight frame's rows migrated/counted by failover;
(c) process mode skips cleanly where multiprocessing spawn is
    unavailable, and rejects configs it cannot honor.

Cost discipline: worker processes pay their own jax init (~10 s per
build on CPU), so the file runs ONE process-cluster lifecycle and
proves (a)+(b) inside it.  Named to sort early (the tier-1
budget-truncation convention)."""

import time

import numpy as np
import pytest

from cilium_tpu.agent import DaemonConfig
from cilium_tpu.cluster import ClusterServing
from cilium_tpu.cluster.process import spawn_available
from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch

pytestmark = [
    pytest.mark.cluster,
    pytest.mark.skipif(not spawn_available(),
                       reason="multiprocessing 'spawn' unavailable"),
]

RULES_EGRESS_ENFORCED = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
    "egress": [{
        "toEndpoints": [{"matchLabels": {"app": "db"}}],
        "toPorts": [{"ports": [{"port": "1", "protocol": "TCP"}]}],
    }],
}]


def _config(**over):
    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_restart_backoff_ms=1.0,
               cluster_probe_interval_s=0.1,
               cluster_death_threshold=2,
               cluster_forward_depth=8192,
               cluster_mode="process",
               # ISSUE 14: stitch every 8th forwarded chunk; scrape
               # on demand (the compact obs leg below — the full
               # relay lifecycle lives in test_cluster_obs)
               cluster_trace_sample=8,
               cluster_obs_interval_s=0.0)
    cfg.update(over)
    return DaemonConfig(**cfg)


def _fwd(db_id, n=128, base=20000):
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base + i,
             dport=5432, proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _rep(db_id, n=128, base=20000):
    return make_batch([
        dict(src="10.0.2.1", dst="10.0.1.1", sport=5432,
             dport=base + i, proto=6, flags=TCP_ACK, ep=db_id, dir=1)
        for i in range(n)]).data


def _wait(pred, timeout=60.0, tick=0.01):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


class TestProcessClusterConfig:
    def test_process_mode_requires_remote_kvstore(self):
        with pytest.raises(ValueError, match="remote"):
            ClusterServing(nodes=1, config=_config(
                cluster_kvstore="memory"))

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="cluster_mode"):
            ClusterServing(nodes=1, config=_config(
                cluster_mode="fiber"))


@pytest.mark.chaos
class TestProcessClusterLifecycle:
    """One full process-cluster lifecycle: serve -> mid-forward
    SIGKILL -> health-path failover -> CT-replay continuity -> exact
    ledger.  (One build: worker jax init dominates the budget.)"""

    def test_serve_sigkill_failover_ledger_exact(self):
        c = ClusterServing(nodes=2, config=_config())
        try:
            c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
            db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
            rev = c.policy_import(RULES_EGRESS_ENFORCED)
            assert c.wait_policy(rev, timeout=30)
            c.start(trace_sample=0, packed=True,
                    ring_capacity=1 << 10)
            # every replica is a REAL process
            pids = {n.proc.pid for n in c.nodes}
            assert len(pids) == 2 and all(p for p in pids)
            # -- (a) serve: ledger exact, packed wire used ----------
            rows = _fwd(db.id)
            assert c.submit(rows) == 128
            assert _wait(lambda:
                         c.ledger()["per-node-accounted"] >= 128)
            for n in c.nodes:
                ts = n.transport_stats()
                assert ts["frames"] >= 1
                assert ts["frames-packed"] == ts["frames"], (
                    "single-stream chunks must ride the packed "
                    "16 B/packet wire")
                # ISSUE 18: cluster_encrypt defaults OFF and the
                # plaintext wire must be byte-identical to the
                # PR 17 protocol — no crypto block in the stats,
                # and the last frame that crossed the socket is a
                # plain encode_rows product (decoding it and
                # re-encoding the pieces reproduces the exact
                # bytes; a sealed frame would fail the decode)
                assert "crypto" not in ts
                wire = n._last_wire
                if wire is not None:
                    from cilium_tpu.cluster.transport import (
                        decode_rows_seq, encode_rows)
                    drows, meta, trace, seq = decode_rows_seq(wire)
                    assert encode_rows(
                        drows, packed_meta=meta, trace=trace,
                        seq=seq) == wire
            # -- ISSUE 14 compact obs leg: the relay's merged views
            # over the LIVE workers (real control-channel scrape +
            # cross-process span stitching; the full relay
            # lifecycle incl. sysdump is test_cluster_obs) --------
            assert c.obs.scrape_now() == {"node0": True,
                                          "node1": True}
            text = c.obs.cluster_metrics()
            for node in ("node0", "node1"):
                assert (f'cilium_serving_verdicts_total{{'
                        f'node="{node}"}}') in text
            samples = [l for l in text.splitlines()
                       if l and not l.startswith("#")]
            assert len(samples) == len(set(samples))
            stitched = c.obs.cluster_trace()["stitched"]
            assert stitched["committed"] > 0
            assert all(sp["monotonic"]
                       for sp in stitched["spans"])
            c.snapshot_now()  # parent-retained CT replica per node
            m0 = {n.name: n.metrics().sum(axis=1) for n in c.nodes}
            # -- (b) mid-forward SIGKILL ----------------------------
            victim = c.nodes[1]
            victim.proc.kill()  # raw SIGKILL: no goodbye, frames may
            # be mid-flight; the forwarder's requeue + the last-ack
            # crash accounting must absorb all of it
            sent = 128
            t0 = time.monotonic()
            k = 0
            while not c.membership.dead_nodes():
                c.submit(_fwd(db.id, base=40000 + 128 * k))
                sent += 128
                k += 1
                assert time.monotonic() - t0 < 60, "death undetected"
                time.sleep(0.02)
            assert c.membership.dead_nodes() == ["node1"]
            # ISSUE 14: scraping the corpse degrades (ok 0), never
            # wedges, and the survivor's series keep serving
            res = c.obs.scrape_now()
            assert res["node1"] is False and res["node0"] is True
            assert ('cilium_cluster_node_scrape_ok{node="node1"} 0'
                    in c.obs.cluster_metrics())
            assert _wait(lambda: c.failovers_total() == 1)
            rec = c.failover.snapshot()[0]
            assert rec["dead"] == "node1" and rec["peer"] == "node0"
            # the parent-retained snapshot replayed onto the peer
            assert rec["ct-replayed-entries"] > 0
            # -- replies for pre-failover flows pass the peer's
            # egress enforcement via the replayed CT ----------------
            c.submit(_rep(db.id))
            sent += 128
            assert _wait(lambda: c.forward_pending() == 0)
            st = c.stop()
            led = st["ledger"]
            assert led["exact"], led
            assert led["submitted"] == sent
            # SIGKILL accounting: whatever the corpse had admitted
            # beyond its last-acked resolved counters is crash
            # loss — counted, surfaced, never silent
            assert led["crash-dropped"] == rec["crash-dropped-rows"]
            fe_dead = st["per-node"]["node1"]["front-end"]
            assert fe_dead["submitted"] >= (
                fe_dead["verdicts"] + fe_dead["shed"])
            # zero NEW drops on the survivor across the reply wave
            m1 = c.nodes[0].metrics().sum(axis=1)
            delta = m1 - m0["node0"]
            drops = {i: int(d) for i, d in enumerate(delta)
                     if i and d}
            assert not drops, (
                f"CT continuity broken across SIGKILL: {drops}")
            # the registry on the survivor carries the crash counter
            assert c.crash_dropped_total() == led["crash-dropped"]
        finally:
            c.shutdown()
        # shutdown reaps every worker
        for n in c.nodes:
            assert not n.proc.is_alive()
