"""toServices egress rules (reference: pkg/k8s
TranslateToServicesRule): a k8sService / k8sServiceSelector reference
expands to the service's clusterIP + ready backend IPs as toCIDRSet
peers, re-expanded on Service/Endpoints churn, and fails CLOSED when
the service vanishes.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.datapath.verdict import (REASON_FORWARDED,
                                         REASON_POLICY_DEFAULT_DENY)


def _daemon(backend="interpreter"):
    d = Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12))
    # the namespace label the PodWatcher would fold in (CNP subject
    # selectors are namespace-scoped)
    d.add_endpoint("cli", ("10.0.9.9",), [
        "k8s:app=cli", "k8s:io.kubernetes.pod.namespace=default"])
    return d


def _cnp(to_services):
    return {
        "kind": "CiliumNetworkPolicy",
        "metadata": {"name": "allow-svc", "namespace": "default"},
        "spec": {
            "endpointSelector": {"matchLabels": {"app": "cli"}},
            "egress": [{"toServices": to_services}],
        },
    }


def _svc(name="db", ns="default", cluster_ip="172.20.0.50",
         labels=None):
    return {"kind": "Service",
            "metadata": {"name": name, "namespace": ns,
                         **({"labels": labels} if labels else {})},
            "spec": {"clusterIP": cluster_ip,
                     "ports": [{"port": 5432, "protocol": "TCP"}]}}


def _eps(name="db", ns="default", ips=("10.0.2.1",)):
    return {"kind": "Endpoints",
            "metadata": {"name": name, "namespace": ns},
            "subsets": [{
                "addresses": [{"ip": ip} for ip in ips],
                "ports": [{"port": 5432, "protocol": "TCP"}],
            }]}


def _flow(d, dst, sport, now):
    ep = d.endpoints.lookup_by_ip("10.0.9.9")
    ev = d.process_batch(make_batch([
        dict(src="10.0.9.9", dst=dst, sport=sport, dport=5432,
             proto=6, flags=TCP_SYN, ep=ep.id, dir=1)
    ]).data, now=now)
    return int(ev.reason[0])


class TestToServices:
    def test_named_service_expands_and_enforces(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc())
        hub.dispatch("add", _eps())
        hub.dispatch("add", _cnp(
            [{"k8sService": {"serviceName": "db",
                             "namespace": "default"}}]))
        # the expansion minted a CIDR identity + ipcache route for
        # the backend; only the stranger needs a manual mapping
        d.upsert_ipcache("10.0.3.3/32", 4002)
        # backend allowed, stranger denied
        assert _flow(d, "10.0.2.1", 41000, 50) == REASON_FORWARDED
        assert _flow(d, "10.0.3.3", 41001,
                     51) == REASON_POLICY_DEFAULT_DENY
        # the derived rule shows toCIDRSet with clusterIP + backend
        from cilium_tpu.policy.api import rule_to_dict
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        cidrs = {c["cidr"] for c in egress["toCIDRSet"]}
        assert cidrs == {"172.20.0.50/32", "10.0.2.1/32"}
        assert "toServices" not in egress

    def test_endpoints_churn_re_expands(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc())
        hub.dispatch("add", _eps())
        hub.dispatch("add", _cnp(
            [{"k8sService": {"serviceName": "db",
                             "namespace": "default"}}]))
        d.upsert_ipcache("10.0.2.9/32", 4003)
        assert _flow(d, "10.0.2.9", 41010,
                     50) == REASON_POLICY_DEFAULT_DENY
        # the service scales out; the new backend joins the peer set
        hub.dispatch("update", _eps(ips=("10.0.2.1", "10.0.2.9")))
        assert _flow(d, "10.0.2.9", 41011, 51) == REASON_FORWARDED

    def test_service_delete_fails_closed(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc())
        hub.dispatch("add", _eps())
        hub.dispatch("add", _cnp(
            [{"k8sService": {"serviceName": "db",
                             "namespace": "default"}}]))
        assert _flow(d, "10.0.2.1", 41020, 50) == REASON_FORWARDED
        hub.dispatch("delete", _svc())
        hub.dispatch("delete", _eps())
        # no peers left: the entry matches NOTHING (not everything)
        assert _flow(d, "10.0.2.1", 41021,
                     51) == REASON_POLICY_DEFAULT_DENY
        from cilium_tpu.policy.api import rule_to_dict
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        assert {c["cidr"] for c in egress["toCIDRSet"]} == {
            "0.0.0.0/32"}

    def test_selector_matches_service_labels_across_namespaces(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc(labels={"tier": "db"}))
        hub.dispatch("add", _eps())
        hub.dispatch("add", _svc(name="db2", ns="prod",
                                 cluster_ip="172.20.0.60",
                                 labels={"tier": "db"}))
        hub.dispatch("add", _eps(name="db2", ns="prod",
                                 ips=("10.0.5.1",)))
        hub.dispatch("add", _svc(name="web", cluster_ip="172.20.0.70",
                                 labels={"tier": "web"}))
        hub.dispatch("add", _eps(name="web", ips=("10.0.6.1",)))
        hub.dispatch("add", _cnp([{"k8sServiceSelector": {
            "selector": {"matchLabels": {"tier": "db"}}}}]))
        from cilium_tpu.policy.api import rule_to_dict
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        cidrs = {c["cidr"] for c in egress["toCIDRSet"]}
        assert cidrs == {"172.20.0.50/32", "10.0.2.1/32",
                         "172.20.0.60/32", "10.0.5.1/32"}
        # namespace-scoped selector: only the default-ns service
        hub.dispatch("update", _cnp([{"k8sServiceSelector": {
            "selector": {"matchLabels": {"tier": "db"}},
            "namespace": "default"}}]))
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        assert {c["cidr"] for c in egress["toCIDRSet"]} == {
            "172.20.0.50/32", "10.0.2.1/32"}

    def test_selector_match_expressions_enforced(self):
        """matchExpressions must constrain (not be silently dropped):
        {app=db} AND {env In [prod]} selects only the prod service."""
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc(labels={"app": "db",
                                         "env": "staging"}))
        hub.dispatch("add", _eps())
        hub.dispatch("add", _svc(name="dbp", cluster_ip="172.20.0.60",
                                 labels={"app": "db", "env": "prod"}))
        hub.dispatch("add", _eps(name="dbp", ips=("10.0.5.1",)))
        hub.dispatch("add", _cnp([{"k8sServiceSelector": {
            "selector": {
                "matchLabels": {"app": "db"},
                "matchExpressions": [{"key": "env", "operator": "In",
                                      "values": ["prod"]}]}}}]))
        from cilium_tpu.policy.api import rule_to_dict
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        assert {c["cidr"] for c in egress["toCIDRSet"]} == {
            "172.20.0.60/32", "10.0.5.1/32"}
        # an expressions-only selector works too (Exists)
        hub.dispatch("update", _cnp([{"k8sServiceSelector": {
            "selector": {"matchExpressions": [
                {"key": "env", "operator": "Exists"}]}}}]))
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        assert {c["cidr"] for c in egress["toCIDRSet"]} == {
            "172.20.0.50/32", "10.0.2.1/32",
            "172.20.0.60/32", "10.0.5.1/32"}

    def test_unchanged_expansion_skips_reimport(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _svc())
        hub.dispatch("add", _eps())
        hub.dispatch("add", _cnp(
            [{"k8sService": {"serviceName": "db",
                             "namespace": "default"}}]))
        rev = d.repo.revision
        # an unrelated service appears: expansion unchanged, no
        # repository churn
        hub.dispatch("add", _svc(name="other",
                                 cluster_ip="172.20.0.99"))
        hub.dispatch("add", _eps(name="other", ips=("10.0.7.1",)))
        assert d.repo.revision == rev

    def test_direct_import_rejected(self):
        d = _daemon()
        with pytest.raises(ValueError, match="toServices"):
            d.policy_import([{
                "endpointSelector": {"matchLabels": {"app": "cli"}},
                "egress": [{"toServices": [{"k8sService": {
                    "serviceName": "db", "namespace": "default"}}]}],
            }])
