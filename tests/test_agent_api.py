"""Agent control plane + REST API + CLI tests.

Covers the SURVEY.md §3.1/§3.3 call stacks: daemon wiring, endpoint
add/remove + regeneration, identity-churn invalidation, policy import
round trip, checkpoint/restore, the Loader seam (tpu vs interpreter
backends agreeing), the API server/client, and the CLI.
"""

import json
import os
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch
from cilium_tpu.infra import Controller, Trigger
from cilium_tpu.monitor.api import MSG_DROP, MSG_POLICY_VERDICT, MSG_TRACE

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
    ],
    "labels": ["db-policy"],
}]


def _mk_daemon(backend="tpu", **kw) -> Daemon:
    return Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12,
                               **kw))


def _pkt(src, dst, dport, ep, dirn=0, flags=TCP_SYN, sport=40000):
    return dict(src=src, dst=dst, sport=sport, dport=dport, proto=6,
                flags=flags, ep=ep, dir=dirn)


class TestDaemon:
    def test_end_to_end_policy_enforcement(self):
        d = _mk_daemon()
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        batch = make_batch([
            _pkt("10.0.1.1", "10.0.2.1", 5432, db.id),  # allowed
            _pkt("10.0.1.1", "10.0.2.1", 22, db.id),  # default deny
        ])
        evb = d.process_batch(batch.data, now=10)
        assert list(evb.verdict) == [1, 0]
        assert list(evb.msg_type) == [MSG_POLICY_VERDICT, MSG_DROP]
        # flows landed in hubble
        flows = d.observer.get_flows(number=10)
        assert len(flows) == 2
        assert flows[1].verdict == 1 and flows[0].verdict == 0
        # identities enriched from the allocator
        assert any("app=web" in l for l in flows[1].source.labels)
        st = d.status()
        assert st["forwarded"] == 1 and st["endpoints"]["total"] == 2

    def test_identity_churn_regenerates(self):
        """A NEW pod matching an existing selector must be allowed
        without any rule change (regression: peer sets frozen at
        resolve time)."""
        d = _mk_daemon()
        d._started = True  # enable churn-invalidation wiring
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        web1 = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        out = d.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=5)
        assert out.verdict[0] == 1
        # new identity (different labels, still app=web via extra label)
        web2 = d.add_endpoint("web-2", ("10.0.1.2",),
                              ["k8s:app=web", "k8s:zone=b"])
        out = d.process_batch(make_batch(
            [_pkt("10.0.1.2", "10.0.2.1", 5432, db.id, sport=40001)]).data,
            now=6)
        assert out.verdict[0] == 1, "new identity not granted by selector"

    def test_endpoint_remove_denies(self):
        d = _mk_daemon()
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        d.policy_import(RULES)
        d.endpoints.remove(web.id)
        # web's ipcache entry is gone: traffic resolves to world ->
        # not selected by the rule -> default deny
        out = d.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=5)
        assert out.verdict[0] == 0

    def test_backends_agree(self):
        """The Loader seam: tpu and interpreter daemons produce the
        same verdicts (the fake-datapath proof)."""
        results = {}
        for backend in ("tpu", "interpreter"):
            d = _mk_daemon(backend)
            db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
            d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
            d.policy_import(RULES)
            batch = make_batch([
                _pkt("10.0.1.1", "10.0.2.1", 5432, db.id),
                _pkt("10.0.1.1", "10.0.2.1", 80, db.id),
                _pkt("10.0.1.1", "10.0.2.1", 5432, db.id,
                     flags=TCP_ACK, sport=40002),
            ])
            evb = d.process_batch(batch.data, now=20)
            results[backend] = (list(evb.verdict), list(evb.ct_state),
                                list(evb.identity))
        assert results["tpu"] == results["interpreter"]

    def test_ct_gc_controller(self):
        d = _mk_daemon()
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        d.policy_import(RULES)
        d.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=5)
        assert d.loader.gc(now=5) == 0  # still alive
        assert d.loader.gc(now=10_000) == 1  # SYN lifetime expired


class TestCheckpointRestore:
    def test_round_trip(self, tmp_path):
        state_dir = str(tmp_path / "state")
        d = _mk_daemon()
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        d.policy_import(RULES)
        # establish a connection pre-restart
        d.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=30)
        ids_before = {i.numeric_id: str(i.labels)
                      for i in d.allocator.all_identities()}
        d.checkpoint(state_dir)

        d2 = _mk_daemon()
        assert d2.restore(state_dir)
        ids_after = {i.numeric_id: str(i.labels)
                     for i in d2.allocator.all_identities()}
        assert ids_before == ids_after  # numerics survive restart
        assert d2.policy_get()["rules"] == d.policy_get()["rules"]
        assert len(d2.endpoints.list()) == 2
        # the restored CT keeps the established connection: a non-SYN
        # packet of the old flow is EST, not policy-evaluated
        db2 = [e for e in d2.endpoints.list() if e.name == "db-1"][0]
        out = d2.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db2.id,
                  flags=TCP_ACK)]).data, now=35)
        assert out.ct_state[0] == 1  # CT_ESTABLISHED from snapshot


class TestAPIandCLI:
    @pytest.fixture()
    def served(self, tmp_path):
        from cilium_tpu.api import APIClient, APIServer

        d = _mk_daemon()
        sock = "/tmp/ciltpu-test.sock"
        server = APIServer(d, sock)
        server.start()
        yield d, APIClient(sock), sock
        server.stop()

    def test_rest_round_trip(self, served):
        d, c, sock = served
        assert c.healthz()["version"]
        c.endpoint_create("db-1", ["10.0.2.1"], ["k8s:app=db"])
        c.endpoint_create("web-1", ["10.0.1.1"], ["k8s:app=web"])
        rev = c.policy_put(RULES)["revision"]
        assert c.policy_get()["revision"] == rev
        eps = c.endpoint_list()
        assert {e["name"] for e in eps} == {"db-1", "web-1"}
        db_id = [e for e in eps if e["name"] == "db-1"][0]["id"]
        d.process_batch(make_batch(
            [_pkt("10.0.1.1", "10.0.2.1", 5432, db_id)]).data, now=3)
        flows = c.flows(number=5)
        assert len(flows) == 1 and flows[0]["verdict"] == "FORWARDED"
        ct = c.map_get("ct")
        assert len(ct) == 1 and ct[0]["dport"] == 5432
        pol = c.map_get(f"policy/{db_id}")
        assert any(e["verdict"] == "allow" and e["dport"] == "5432"
                   for e in pol)
        metrics = c.metrics()
        assert "cilium_policy_revision" in metrics
        assert "hubble_flows_processed_total" in metrics
        assert c.debuginfo()["status"]["endpoints"]["total"] == 2
        # deletes
        assert c.policy_delete(["db-policy"])["revision"] > rev
        assert c.endpoint_delete(db_id)["removed"] is True

    def test_cli(self, served, capsys):
        d, c, sock = served
        from cilium_tpu.cli.main import main

        c.endpoint_create("db-1", ["10.0.2.1"], ["k8s:app=db"])
        assert main(["--socket", sock, "status"]) == 0
        out = capsys.readouterr().out
        assert "Agent:" in out and "Endpoints: 1" in out
        assert main(["--socket", sock, "endpoint", "list"]) == 0
        assert "db-1" in capsys.readouterr().out
        assert main(["--socket", sock, "identity"]) == 0
        assert "app=db" in capsys.readouterr().out
        assert main(["--socket", sock, "version"]) == 0
        # policy import via file
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(RULES, f)
        assert main(["--socket", sock, "policy", "import", f.name]) == 0
        assert "Revision" in capsys.readouterr().out
        assert main(["--socket", sock, "bpf", "ipcache"]) == 0
        assert "10.0.2.1/32" in capsys.readouterr().out
        # L7/xDS plane verbs (r04): an L7 policy creates a listener;
        # xds shows the pushed resources
        l7_rules = [{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                "toPorts": [{"ports": [{"port": "80",
                                        "protocol": "TCP"}],
                             "rules": {"http": [{"method": "GET"}]}}],
            }],
        }]
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f2:
            json.dump(l7_rules, f2)
        assert main(["--socket", sock, "policy", "import",
                     f2.name]) == 0
        capsys.readouterr()
        assert main(["--socket", sock, "proxy"]) == 0
        assert "http-rules" in capsys.readouterr().out
        assert main(["--socket", sock, "proxy", "xds"]) == 0
        out = capsys.readouterr().out
        assert "xDS version" in out and "app=db" in out
        os.unlink(f.name)
        os.unlink(f2.name)

    def test_cli_agent_unreachable(self, capsys):
        from cilium_tpu.cli.main import main

        assert main(["--socket", "/tmp/nope-9x.sock", "status"]) == 1
        assert "not reachable" in capsys.readouterr().err


class TestInfra:
    def test_controller_backoff_status(self):
        calls = []

        def fail():
            calls.append(1)
            raise RuntimeError("kaboom")

        c = Controller("t", fail, interval=100)
        assert c.run_once() is False
        assert c.status.consecutive_failures == 1
        assert "kaboom" in c.status.last_error

        ok = Controller("t2", lambda: calls.append(2), interval=100)
        assert ok.run_once() is True
        assert ok.status.success_count == 1

    def test_trigger_coalesces(self):
        runs = []
        t = Trigger(lambda: runs.append(1))
        t.trigger()
        t.trigger()
        assert len(runs) == 2  # idle triggers run synchronously
