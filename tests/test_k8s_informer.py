"""k8s informer client (VERDICT r04 item 8): LIST + streaming WATCH
with resourceVersion resume against a stub apiserver over real HTTP,
driving the existing K8sWatcherHub — the agent bootstraps endpoints +
policy from the apiserver end to end.
"""

import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.k8s.informer import K8sClient
from cilium_tpu.kvstore import InMemoryKVStore
from cilium_tpu.testing.stub_apiserver import StubAPIServer


def _pod(name, ip, labels, node="node-1", ns="default"):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": labels},
            "spec": {"nodeName": node, "containers": []},
            "status": {"podIP": ip}}


def _cnp():
    return {"kind": "CiliumNetworkPolicy",
            "metadata": {"name": "db-allow", "namespace": "default"},
            "spec": {
                "endpointSelector": {"matchLabels": {"app": "db"}},
                "ingress": [{
                    "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                    "toPorts": [{"ports": [
                        {"port": "5432", "protocol": "TCP"}]}]}],
            }}


def _wait(cond, timeout=30.0, msg=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


@pytest.fixture()
def world():
    stub = StubAPIServer()
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                            node_name="node-1"),
               kvstore=InMemoryKVStore())
    client = K8sClient(stub.url, d.k8s_watchers())
    yield stub, d, client
    client.stop()
    stub.close()


class TestBootstrap:
    def test_agent_bootstraps_endpoints_and_policy(self, world):
        stub, d, client = world
        # state EXISTS before the agent attaches (the restart case:
        # LIST must deliver it)
        stub.add(_pod("db-0", "10.0.2.1", {"app": "db"}))
        stub.add(_pod("web-0", "10.0.1.1", {"app": "web"}))
        stub.add(_cnp())
        client.start()
        _wait(lambda: len(d.endpoints.list()) == 2,
              msg="pods -> endpoints")
        _wait(lambda: d.repo.revision > 1, msg="CNP imported")

        db = d.endpoints.lookup_by_ip("10.0.2.1")
        tick = iter(range(40000, 60000))

        def verdicts():
            s, now = next(tick), 10 + next(tick) % 100
            ev = d.process_batch(make_batch([
                dict(src="10.0.1.1", dst="10.0.2.1", sport=s,
                     dport=5432, proto=6, flags=TCP_SYN, ep=db.id,
                     dir=0),
                dict(src="10.0.1.1", dst="10.0.2.1", sport=s + 1,
                     dport=9999, proto=6, flags=TCP_SYN, ep=db.id,
                     dir=0),
            ]).data, now=now)
            return [int(v) for v in ev.verdict]

        # regeneration runs on the trigger thread after the CNP event;
        # converge on the enforced state, then pin it
        _wait(lambda: verdicts() == [1, 0], msg="policy enforced")
        assert verdicts() == [1, 0]

    def test_live_watch_events_flow(self, world):
        stub, d, client = world
        client.start()
        _wait(lambda: all(r.resource_version is not None
                          for r in client.reflectors),
              msg="initial LISTs")
        stub.add(_pod("db-0", "10.0.2.1", {"app": "db"}))
        _wait(lambda: len(d.endpoints.list()) == 1,
              msg="watch ADDED -> endpoint")
        stub.delete(_pod("db-0", "10.0.2.1", {"app": "db"}))
        _wait(lambda: len(d.endpoints.list()) == 0,
              msg="watch DELETED -> endpoint removed")

    def test_compaction_forces_relist_and_recovers(self, world):
        stub, d, client = world
        stub.add(_pod("db-0", "10.0.2.1", {"app": "db"}))
        client.start()
        _wait(lambda: len(d.endpoints.list()) == 1, msg="bootstrap")
        pods = next(r for r in client.reflectors if r.kind == "Pod")
        lists_before = pods.lists
        # kill history, then mutate: the resumed watch gets 410 and
        # must re-LIST to see the new pod
        stub.compact()
        stub.add(_pod("web-0", "10.0.1.1", {"app": "web"}))
        _wait(lambda: len(d.endpoints.list()) == 2, timeout=30,
              msg="post-compaction re-LIST delivers")
        assert pods.lists > lists_before

    def test_nonlocal_pods_are_ignored(self, world):
        stub, d, client = world
        client.start()
        stub.add(_pod("other", "10.0.9.9", {"app": "x"},
                      node="node-2"))
        stub.add(_pod("mine", "10.0.2.1", {"app": "db"}))
        _wait(lambda: len(d.endpoints.list()) == 1, msg="local only")
        time.sleep(0.3)
        assert len(d.endpoints.list()) == 1
