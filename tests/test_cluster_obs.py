"""Cluster observability relay (ISSUE 14 tentpole): node-labeled
merged metrics, hubble-relay-style merged flows, cluster sysdump,
and cross-process trace stitching.

Acceptance (split by cost):
(a) UNITS (no daemon): exposition merging injects correctly-escaped
    ``node`` labels with families grouped and HELP/TYPE deduped;
    registry registration asserts name validity/uniqueness and
    render() escapes label values; traced transport frames/acks
    round-trip; the span store's ledger is exact; the nodehost op
    vocabulary is timeout-bounded (CTA011's floor, pinned here);
    flow.proto carries native drop reasons (DIVERGENCES #15 closed).
(b) THREAD-MODE integration (cheap): a live 2-node cluster serves
    the merged views + the HTTP surface (/cluster/metrics, /flows,
    /top, /trace, /sysdump) from a member daemon's socket; a crashed
    node degrades to scrape_ok 0 with last-known-good series inside
    the staleness bound and dropped past it.
(c) PROCESS-MODE lifecycles (``slow`` lap — worker jax init
    dominates; TIER-1 process-mode obs coverage rides the compact
    leg folded into ``test_cluster_process``'s single lifecycle):
    scrape over the real control channel, stitched cross-process
    spans with monotonic stages, the cluster sysdump tar with every
    worker bundle + parent + manifest, a SIGKILL MID-SCRAPE chaos
    leg (the relay marks the corpse un-scrapeable, keeps serving
    the survivors, never blocks the router, and the cluster ledger
    still closes exactly), and the 3-node full acceptance.

Named to sort early (the tier-1 budget-truncation convention).
"""

import json
import os
import tarfile
import time

import numpy as np
import pytest

from cilium_tpu.obs.registry import MetricsRegistry, escape_label_value
from cilium_tpu.obs.relay import (SPAN_HOPS, ClusterSpanStore,
                                  TraceCtx, merge_expositions)

pytestmark = [pytest.mark.cluster, pytest.mark.obs]


def _wait(pred, timeout=60.0, tick=0.01):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


# ---------------------------------------------------------------------
# (a) units
# ---------------------------------------------------------------------
class TestExpositionMerge:
    def test_node_label_injection_and_family_grouping(self):
        texts = {
            "node0": ("# HELP cilium_x things\n"
                      "# TYPE cilium_x counter\n"
                      "cilium_x 5\n"
                      "# HELP cilium_y labelled\n"
                      "# TYPE cilium_y counter\n"
                      'cilium_y{reason="policy"} 2\n'),
            "node1": ("# HELP cilium_x things\n"
                      "# TYPE cilium_x counter\n"
                      "cilium_x 7\n"
                      "# HELP cilium_y labelled\n"
                      "# TYPE cilium_y counter\n"
                      'cilium_y{reason="policy"} 3\n'),
        }
        lines = merge_expositions(texts)
        assert 'cilium_x{node="node0"} 5' in lines
        assert 'cilium_x{node="node1"} 7' in lines
        assert 'cilium_y{node="node0",reason="policy"} 2' in lines
        assert 'cilium_y{node="node1",reason="policy"} 3' in lines
        # HELP/TYPE once per family, samples contiguous under them
        assert lines.count("# TYPE cilium_x counter") == 1
        ix = lines.index("# TYPE cilium_x counter")
        assert lines[ix + 1].startswith("cilium_x{")
        assert lines[ix + 2].startswith("cilium_x{")
        # no duplicate series after injection
        samples = [l for l in lines if not l.startswith("#")]
        assert len(samples) == len(set(samples))

    def test_histogram_family_samples_stay_grouped(self):
        text = ("# HELP cilium_h lat\n"
                "# TYPE cilium_h histogram\n"
                'cilium_h_bucket{le="1"} 1\n'
                'cilium_h_bucket{le="+Inf"} 2\n'
                "cilium_h_sum 3.0\n"
                "cilium_h_count 2\n")
        lines = merge_expositions({"a": text, "b": text})
        ix = lines.index("# TYPE cilium_h histogram")
        tail = lines[ix + 1:ix + 9]
        assert all(l.startswith("cilium_h") for l in tail)
        assert 'cilium_h_bucket{node="a",le="1"} 1' in tail
        assert 'cilium_h_count{node="b"} 2' in tail

    def test_node_name_escaping(self):
        evil = 'no"de\\one\n'
        lines = merge_expositions({evil: "# TYPE m counter\nm 1\n"})
        sample = [l for l in lines if not l.startswith("#")][0]
        assert sample == 'm{node="no\\"de\\\\one\\n"} 1'
        assert "\n" not in sample

    def test_escape_label_value_order(self):
        # backslash first, then quote, then newline (spec order) —
        # a quote-then-backslash order would double-escape
        assert escape_label_value('a\\"b\nc') == 'a\\\\\\"b\\nc'


class TestRegistryHygiene:
    def test_duplicate_registration_rejected(self):
        reg = MetricsRegistry()
        reg.counter("cilium_t_total", "h", lambda: 1)
        with pytest.raises(ValueError, match="twice"):
            reg.counter("cilium_t_total", "h", lambda: 1)

    def test_invalid_series_name_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="not a valid"):
            reg.counter("cilium bad name", "h", lambda: 1)
        with pytest.raises(ValueError, match="not a valid"):
            reg.gauge("9starts_with_digit", "h", lambda: 1)
        with pytest.raises(ValueError, match="not a valid"):
            # $ would match before the trailing newline; the guard
            # must use \Z (review-round regression)
            reg.counter("cilium_trailing_newline\n", "h", lambda: 1)

    def test_render_escapes_label_values(self):
        reg = MetricsRegistry()
        reg.gauge("cilium_esc", "h",
                  lambda: [({"k": 'v"1\\2\n3'}, 7)])
        text = reg.render()
        assert 'cilium_esc{k="v\\"1\\\\2\\n3"} 7' in text
        # the exposition stays line-parseable
        for line in text.splitlines():
            assert "\n" not in line


class TestSpanStore:
    def _ctx(self, store, complete=True):
        ctx = store.allocate_span(64, 1.0)
        ctx.node = "node0"
        ctx.t_fwd = 2.0
        if complete:
            ctx.t_recv, ctx.t_admit, ctx.t_ack = 3.0, 4.0, 5.0
        return ctx

    def test_ledger_exact_and_monotonic(self):
        store = ClusterSpanStore(capacity=4)
        for _ in range(6):
            store.commit_span(self._ctx(store))
        store.drop_span(self._ctx(store, complete=False))
        st = store.span_stats()
        assert st["sampled"] == st["committed"] + st["dropped"]
        assert st["committed"] == 6 and st["dropped"] == 1
        snap = store.snapshot_spans()
        assert len(snap["spans"]) == 4  # ring capacity, newest wins
        for sp in snap["spans"]:
            assert sp["monotonic"]
            assert set(sp["hops-us"]) == set(SPAN_HOPS)

    def test_incomplete_span_counts_dropped_not_committed(self):
        store = ClusterSpanStore()
        ctx = self._ctx(store, complete=False)  # no ack echo
        ctx.t_ack = 6.0
        store.commit_span(ctx)
        st = store.span_stats()
        assert st["committed"] == 0 and st["dropped"] == 1


class TestTracedTransport:
    def test_traced_frame_round_trip(self):
        from cilium_tpu.cluster.transport import (decode_rows,
                                                  decode_rows_ex,
                                                  encode_rows)

        rows = np.arange(32, dtype=np.uint32).reshape(8, 4)
        payload = encode_rows(rows, packed_meta=(3, 1),
                              trace=(42, 1.5, 2.5))
        out, meta, trace = decode_rows_ex(payload)
        assert np.array_equal(out, rows) and meta == (3, 1)
        assert trace == (42, 1.5, 2.5)
        # the legacy two-tuple surface drops the context, not the rows
        out2, meta2 = decode_rows(payload)
        assert np.array_equal(out2, rows) and meta2 == (3, 1)
        # untraced frames decode with trace None
        _, _, none = decode_rows_ex(encode_rows(rows,
                                                packed_meta=(3, 1)))
        assert none is None

    def test_traced_ack_round_trip(self):
        from cilium_tpu.cluster.transport import (ACK_SIZE,
                                                  ACK_TRACED_SIZE,
                                                  pack_ack,
                                                  unpack_ack,
                                                  unpack_ack_ex)

        plain = pack_ack(5, 10, 6, 2, 1)
        assert len(plain) == ACK_SIZE
        assert unpack_ack(plain) == (5, 10, 6, 2, 1)
        traced = pack_ack(5, 10, 6, 2, 1, trace=(7, 1.25, 2.75))
        assert len(traced) == ACK_TRACED_SIZE
        ledger, echo = unpack_ack_ex(traced)
        assert ledger == (5, 10, 6, 2, 1)
        assert echo == (7, 1.25, 2.75)
        # the legacy surface tolerates the traced size
        assert unpack_ack(traced) == (5, 10, 6, 2, 1)

    def test_torn_traced_frame_is_loud(self):
        from cilium_tpu.cluster.transport import (FrameError,
                                                  decode_rows_ex,
                                                  encode_rows)

        rows = np.zeros((4, 4), dtype=np.uint32)
        payload = encode_rows(rows, trace=(1, 1.0, 2.0))
        with pytest.raises(FrameError):
            decode_rows_ex(payload[:20])  # mid-trace-block cut
        with pytest.raises(FrameError):
            decode_rows_ex(payload[:-3])  # torn body


# the nodehost control-op vocabulary: every op named HERE (CTA011
# requires a test referencing each op; this table-driven pin is that
# reference for the whole wire contract, and the live ops are driven
# end-to-end by the process-mode lifecycle below)
EXPECTED_OPS = (
    "ready", "probe", "add_endpoint", "policy_rev", "has_identity",
    "start_node", "warm", "start_serving", "front_end",
    "stop_serving", "metrics", "metricsmap", "obs_scrape", "sysdump",
    "slo", "history",
    "map_pressure", "compile_stats", "ct_snapshot", "ct_merge",
    "record_incident", "publish_drops", "shutdown", "ack_flush",
    "rotate_epoch",
)


class TestNodehostOpDiscipline:
    def test_op_vocabulary_pinned_and_timeout_bounded(self):
        from cilium_tpu.cluster.nodehost import OP_TIMEOUTS, _NodeHost

        assert set(_NodeHost._OPS) == set(EXPECTED_OPS), (
            "control-op vocabulary changed: update EXPECTED_OPS "
            "(and the CTA011 coverage it pins)")
        assert set(OP_TIMEOUTS) == set(_NodeHost._OPS)
        for op, bound in OP_TIMEOUTS.items():
            assert isinstance(bound, (int, float)) and bound > 0, op

    def test_cta011_live_repo_clean(self):
        from cilium_tpu.analysis.driver import run_analysis

        result = run_analysis(checkers=["nodehost-ops"])
        assert [f.render() for f in result["findings"]] == []

    def test_cta014_bench_schema(self, tmp_path):
        # the BENCH_obs gate moved to slo_lint (CTA014) with the
        # ISSUE 19 v2 schema (sampler-overhead paired legs +
        # burn-detection latency)
        from cilium_tpu.analysis.slo_lint import (BENCH_OBS_KEYS,
                                                  check_bench)

        good = {k: 1 for k in BENCH_OBS_KEYS}
        good["schema"] = "bench-obs-v2"
        p = tmp_path / "BENCH_obs.json"
        p.write_text(json.dumps(good))
        assert check_bench(str(p)) == []
        bad = dict(good)
        del bad["sampler_overhead_ratio"]
        bad["schema"] = "bench-obs-v1"
        p.write_text(json.dumps(bad))
        msgs = check_bench(str(p))
        assert any("sampler_overhead_ratio" in m for m in msgs)
        assert any("schema" in m for m in msgs)


class TestNativeDropReasonFidelity:
    """DIVERGENCES #15 satellite: repo-native drop reasons survive
    the binary flow.proto round trip (field 3 carries the native
    code; decode prefers it over the lossy field-25 enum)."""

    def _flow(self, reason):
        from cilium_tpu.flow.flow import Flow, FlowEndpoint

        return Flow(
            time=123.456, uuid=7, verdict=0, drop_reason=reason,
            event_type=1, is_reply=False, traffic_direction=0,
            proto=6, flags=0x02, length=64,
            source=FlowEndpoint(ip="10.0.1.1", port=1234),
            destination=FlowEndpoint(ip="10.0.2.1", port=5432,
                                     identity=1011,
                                     labels=("k8s:app=db",),
                                     pod_name="ns/db",
                                     endpoint_id=3))

    def test_every_native_reason_round_trips(self):
        from cilium_tpu.flow.flow import DROP_REASON_DESC
        from cilium_tpu.flow.proto import decode_flow, encode_flow

        for reason, name in DROP_REASON_DESC.items():
            d = decode_flow(encode_flow(self._flow(reason),
                                        node_name="node1"))
            assert d["drop_reason"] == reason
            assert d["drop_reason_desc"] == name
            assert d["node_name"] == "node1"
            assert d["verdict"] == "DROPPED"

    def test_relay_merge_keeps_native_reasons(self):
        from cilium_tpu.flow.proto import decode_flow, encode_flow
        from cilium_tpu.flow.relay import Relay

        class _Peer:  # Observer-protocol peer yielding wire decodes
            def __init__(self, reason):
                self._d = decode_flow(encode_flow(
                    TestNativeDropReasonFidelity()._flow(reason)))

            def get_flows(self, filters=(), number=100,
                          oldest_first=False, blacklist=()):
                return [self._d]

        relay = Relay({"a": _Peer(9), "b": _Peer(12)})
        merged = relay.get_flows(number=10)
        descs = {d["drop_reason_desc"] for d in merged}
        assert descs == {"INGRESS_QUEUE_OVERFLOW",
                         "CLUSTER_ROUTER_OVERFLOW"}
        assert {d["node_name"] for d in merged} == {"a", "b"}


class TestOnDemandFreshness:
    """Review-round regression: with the periodic loop DISABLED
    (interval 0), queries must RE-sweep once the cached snapshot
    outgrows ON_DEMAND_MAX_AGE_S — the first cut scraped only on an
    empty cache, so merged views froze at the first query and went
    permanently empty past the staleness bound while scrape_ok
    still read 1."""

    class _Peer:
        name = "node0"
        alive = True

        def __init__(self):
            self.scrapes = 0

        def obs_scrape(self, cursor=0, flows=512, top=16):
            self.scrapes += 1
            return {"metrics-text": "# TYPE m counter\nm 1\n",
                    "flows": [], "cursor": 0, "top": None,
                    "trace": None, "incidents": []}

    def test_disabled_loop_requeries_past_age_bound(self,
                                                    monkeypatch):
        import cilium_tpu.obs.relay as relay_mod
        from cilium_tpu.obs.relay import ClusterObsRelay

        peer = self._Peer()
        relay = ClusterObsRelay(lambda: [peer], interval_s=0.0)
        monkeypatch.setattr(relay_mod, "ON_DEMAND_MAX_AGE_S", 0.05)
        relay.cluster_metrics()
        assert peer.scrapes == 1
        relay.cluster_metrics()  # fresh: bursts share one sweep
        assert peer.scrapes == 1
        time.sleep(0.06)
        text = relay.cluster_metrics()  # aged out: re-sweeps
        assert peer.scrapes == 2
        assert 'm{node="node0"} 1' in text
        # cluster_trace answers on a fresh relay too (it shares
        # _ensure_scraped with the other merged views)
        relay2 = ClusterObsRelay(lambda: [self._Peer()],
                                 interval_s=0.0)
        out = relay2.cluster_trace()
        assert "nodes" in out


class TestFlowsSince:
    def test_cursor_tail_semantics(self):
        from cilium_tpu.flow.observer import Observer

        obs = Observer(capacity=8)
        hdr = np.zeros(obs.hdr.shape[1], dtype=np.uint32)
        for i in range(5):
            obs.append_l7(hdr, {"type": "REQUEST"}, 1, 0,
                          float(i))
        flows, cur = obs.flows_since(0)
        assert len(flows) == 5 and cur == 5
        # nothing new: empty tail, cursor stands
        flows, cur2 = obs.flows_since(cur)
        assert flows == [] and cur2 == 5
        for i in range(5, 12):  # wrap the 8-ring
            obs.append_l7(hdr, {"type": "REQUEST"}, 1, 0,
                          float(i))
        flows, cur3 = obs.flows_since(cur)
        # seq 5..11 wanted; the ring holds the newest 8 (4..11), so
        # all 7 are still present, oldest first
        assert [f.uuid for f in flows] == list(range(5, 12))
        assert cur3 == 12
        # a lagging cursor sees only what survived the lap
        flows, _ = obs.flows_since(0)
        assert [f.uuid for f in flows] == list(range(4, 12))


class TestClusterFlowsCliFilters:
    """`flows --cluster` applies the SHARED filter vocabulary
    CLIENT-side over the merged dicts — every accepted flag must
    actually filter (review-round: --protocol was parsed but
    silently dropped on the cluster branch)."""

    FLOWS = [
        {"time": 10.0, "uuid": "a", "node_name": "node0",
         "verdict": "FORWARDED", "Summary": "tcp-allow",
         "l4": {"TCP": {"source_port": 1111,
                        "destination_port": 5432}},
         "source": {"identity": 100}, "destination": {"identity": 7}},
        {"time": 11.0, "uuid": "b", "node_name": "node1",
         "verdict": "DROPPED", "Summary": "udp-drop",
         "l4": {"UDP": {"source_port": 2222,
                        "destination_port": 53}},
         "source": {"identity": 200}, "destination": {"identity": 7}},
    ]

    def _run(self, capsys, monkeypatch, **over):
        import argparse

        from cilium_tpu.cli import main as cli

        class _Stub:
            def cluster_flows(_s, number=0, oldest_first=1):
                return list(self.FLOWS)

        monkeypatch.setattr(cli, "_client", lambda args: _Stub())
        ns = dict(socket="unused", cluster=True, number=10,
                  json=False, follow=False, interval=1.0,
                  verdict=None, port=None, protocol=None,
                  identity=None, since=None)
        ns.update(over)
        assert cli.cmd_flows(argparse.Namespace(**ns)) == 0
        return capsys.readouterr().out

    def test_protocol_filters_cluster_flows(self, capsys,
                                            monkeypatch):
        out = self._run(capsys, monkeypatch, protocol=17)
        assert "udp-drop" in out and "tcp-allow" not in out
        out = self._run(capsys, monkeypatch, protocol=6)
        assert "tcp-allow" in out and "udp-drop" not in out

    def test_verdict_and_identity_filter(self, capsys, monkeypatch):
        out = self._run(capsys, monkeypatch, verdict=2)
        assert "udp-drop" in out and "tcp-allow" not in out
        out = self._run(capsys, monkeypatch, identity=100)
        assert "tcp-allow" in out and "udp-drop" not in out


# ---------------------------------------------------------------------
# (b) thread-mode integration
# ---------------------------------------------------------------------
RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432",
                                "protocol": "TCP"}]}],
    }],
}]

# the per-node floor asserted in the merged exposition: one sample
# per node per series (the ISSUE 14 acceptance shape)
NODE_SERIES_FLOOR = (
    "cilium_datapath_packets_total",
    "cilium_serving_verdicts_total",
    "cilium_policy_generation",
    "cilium_flow_agg_windows_total",
    "cilium_incidents_total",
)


def _mk_config(**over):
    from cilium_tpu.agent import DaemonConfig

    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_restart_backoff_ms=1.0,
               cluster_probe_interval_s=0.1,
               cluster_death_threshold=2,
               cluster_forward_depth=8192,
               cluster_obs_interval_s=0.0,  # scrape on demand /
               # explicitly — deterministic tests
               cluster_trace_sample=1)
    cfg.update(over)
    return DaemonConfig(**cfg)


def _batch(db_id, n=128, base=20000, sport_stride=1):
    from cilium_tpu.core import TCP_SYN, make_batch

    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1",
             sport=base + i * sport_stride, dport=5432, proto=6,
             flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _build_cluster(nodes, ring_capacity=1 << 10, **cfg_over):
    from cilium_tpu.cluster import ClusterServing

    c = ClusterServing(nodes=nodes, config=_mk_config(**cfg_over))
    c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    rev = c.policy_import(RULES)
    assert c.wait_policy(rev, timeout=30)
    c.start(trace_sample=0, packed=True,
            ring_capacity=ring_capacity)
    return c, db


def _assert_cluster_exposition(text, node_names):
    """The acceptance shape: every rendered REQUIRED_SERIES appears
    once per node (distinct node labels), the floor series render
    for every node, no duplicate series."""
    from cilium_tpu.analysis.registry_lint import REQUIRED_SERIES

    samples = [l for l in text.splitlines()
               if l and not l.startswith("#")]
    assert len(samples) == len(set(samples)), "duplicate series"
    by_series = {}
    for line in samples:
        name = line.split("{")[0].split(" ")[0]
        by_series.setdefault(name, []).append(line)
    for name in NODE_SERIES_FLOOR:
        for node in node_names:
            assert any(f'node="{node}"' in l
                       for l in by_series.get(name, ())), (
                f"{name} missing for {node}")
    for name in REQUIRED_SERIES:
        lines = by_series.get(name)
        if lines is None:
            continue  # not rendered in this state (e.g. NAT off)
        for node in node_names:
            node_lines = [l for l in lines if f'node="{node}"' in l]
            assert node_lines, f"{name} missing for {node}"
    for node in node_names:
        assert (f'cilium_cluster_node_scrape_ok{{node="{node}"}} 1'
                in samples)


class TestThreadClusterObs:
    def test_merged_views_http_surface_and_staleness(self, tmp_path):
        import urllib.parse

        from cilium_tpu.api.client import APIClient
        from cilium_tpu.api.server import APIServer

        # ring_capacity 1<<11, NOT the 1<<10 every other cluster
        # test warms: executables key on it and jit caches are
        # process-global, so sharing the key would pre-warm
        # test_cluster_scaleout's bring-up pin into a false
        # "warm-up compiled nothing" failure (caught in tier-1)
        c, db = _build_cluster(2, ring_capacity=1 << 11,
                               cluster_kvstore="memory",
                               cluster_obs_stale_after_s=1.5,
                               sysdump_dir=str(tmp_path / "dumps"))
        api = None
        try:
            # spread flows over both nodes (distinct tuples)
            for k in range(4):
                c.submit(_batch(db.id, base=20000 + 512 * k,
                                sport_stride=3))
            assert _wait(lambda: c.ledger()[
                "per-node-accounted"] >= 512)
            for n in c.nodes:
                n.record_incident("manual", {"why": "obs-test"})
            assert c.obs.scrape_now() == {"node0": True,
                                          "node1": True}
            # -- merged exposition (the acceptance shape) -----------
            text = c.obs.cluster_metrics()
            _assert_cluster_exposition(text, ["node0", "node1"])
            # -- merged flows: time-ordered, both nodes represented -
            flows = c.obs.cluster_flows(number=400,
                                        oldest_first=True)
            assert flows
            times = [f["time"] for f in flows]
            assert times == sorted(times)
            assert {f["node_name"] for f in flows} == {"node0",
                                                      "node1"}
            # -- merged top-K ---------------------------------------
            top = c.obs.cluster_top(8)
            assert top["enabled"]
            assert set(top["nodes"]) == {"node0", "node1"}
            # -- stitched spans (thread mode stamps in-process) -----
            tr = c.obs.cluster_trace()
            st = tr["stitched"]
            assert st["committed"] > 0
            assert all(sp["monotonic"] for sp in st["spans"])
            # -- the HTTP surface from a member daemon's socket -----
            sock = str(tmp_path / "cilium.sock")
            api = APIServer(c.nodes[0].daemon, sock)
            api.start()
            cli = APIClient(sock)
            assert 'node="node1"' in cli.cluster_metrics()
            assert cli.cluster_flows(number=5)
            assert cli.cluster_top(4)["enabled"]
            assert cli.cluster_trace()["stitched"]["committed"] > 0
            dump = cli.cluster_sysdump()
            assert os.path.exists(dump["path"])
            with tarfile.open(dump["path"]) as tar:
                names = set(tar.getnames())
                assert {"nodes/node0.json", "nodes/node1.json",
                        "parent.json", "manifest.json"} <= names
                man = json.load(tar.extractfile("manifest.json"))
                assert man["nodes"]["node0"]["ok"]
                assert man["nodes"]["node1"]["ok"]
                bundle = json.load(
                    tar.extractfile("nodes/node0.json"))
                assert bundle["node"] == "node0"
                parent = json.load(tar.extractfile("parent.json"))
                assert parent["cluster"]["ledger"] is not None
            # -- staleness: a crashed node degrades, bounded --------
            c.node("node1").crash("obs staleness test")
            res = c.obs.scrape_now()
            assert res["node1"] is False and res["node0"] is True
            text = c.obs.cluster_metrics()
            # last-known-good inside the bound: node1 series remain
            assert ('cilium_cluster_node_scrape_ok{node="node1"} 0'
                    in text)
            assert 'cilium_serving_verdicts_total{node="node1"}' \
                in text
            time.sleep(1.6)  # past cluster_obs_stale_after_s
            # the periodic loop would have kept refreshing node0;
            # with the loop off, refresh explicitly (node1's retry
            # keeps failing — it is a corpse)
            assert c.obs.scrape_now() == {"node0": True,
                                          "node1": False}
            text = c.obs.cluster_metrics()
            assert ('cilium_cluster_node_scrape_ok{node="node1"} 0'
                    in text)
            assert 'cilium_serving_verdicts_total{node="node1"}' \
                not in text, "stale series must drop past the bound"
            # the survivor keeps rendering
            assert 'cilium_serving_verdicts_total{node="node0"}' \
                in text
        finally:
            if api is not None:
                api.stop()
            c.shutdown()


# ---------------------------------------------------------------------
# (c) process-mode lifecycle
# ---------------------------------------------------------------------
def _spawn_ok():
    from cilium_tpu.cluster.process import spawn_available

    return spawn_available()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.skipif(not _spawn_ok(),
                    reason="multiprocessing 'spawn' unavailable")
class TestProcessClusterObs:
    """One 2-worker process lifecycle: real-socket scrape + stitched
    spans + sysdump + SIGKILL mid-scrape.  SLOW lap: worker jax init
    dominates (~19 s) and tier-1's process-mode obs coverage rides
    the compact leg folded into test_cluster_process's one
    lifecycle (the file's own cost discipline)."""

    def test_scrape_stitch_sysdump_and_sigkill_mid_scrape(
            self, tmp_path):
        from cilium_tpu.cluster import ClusterServing

        c = ClusterServing(nodes=2, config=_mk_config(
            cluster_mode="process",
            cluster_trace_sample=4,
            cluster_obs_interval_s=0.25,
            cluster_obs_stale_after_s=30.0,
            history_interval=0.25))  # workers tick their SLO
        # engines fast enough to hold a real verdict pre-SIGKILL
        c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        rev = c.policy_import(RULES)
        assert c.wait_policy(rev, timeout=30)
        try:
            c.start(trace_sample=0, packed=True,
                    ring_capacity=1 << 10)
            sent = 0
            for k in range(6):
                sent += c.submit(_batch(db.id, base=20000 + 512 * k,
                                        sport_stride=3))
            assert _wait(lambda: c.ledger()[
                "per-node-accounted"] >= sent)
            for n in c.nodes:
                n.record_incident("manual", {"why": "obs-test"})
            assert c.obs.scrape_now() == {"node0": True,
                                          "node1": True}
            # merged exposition over the REAL control channel
            text = c.obs.cluster_metrics()
            _assert_cluster_exposition(text, ["node0", "node1"])
            # merged flows: time-ordered, node-stamped
            flows = c.obs.cluster_flows(number=400,
                                        oldest_first=True)
            times = [f["time"] for f in flows]
            assert times == sorted(times) and flows
            assert {f["node_name"] for f in flows} <= {"node0",
                                                       "node1"}
            # stitched CROSS-PROCESS spans: every stage stamped on
            # its own side of the socket, monotonic end to end
            st = c.obs.cluster_trace()["stitched"]
            assert st["committed"] > 0
            for sp in st["spans"]:
                assert sp["monotonic"], sp
                assert set(sp["hops-us"]) == set(SPAN_HOPS)
                assert all(v >= 0 for v in sp["hops-us"].values())
            # the self-describing metrics op (the raw array moved
            # to `metricsmap`, still served for CT proofs)
            assert "# TYPE cilium_datapath_packets_total" in (
                c.nodes[0].metrics_text() or "")
            assert c.nodes[0].metrics() is not None
            # worker map_pressure/compile/front_end ops stay live
            assert c.nodes[0].map_pressure() is not None
            assert c.nodes[0].dispatch_compiles() is not None
            # cluster sysdump: every worker bundle + parent +
            # manifest in one tar
            rec = c.cluster_sysdump(str(tmp_path / "dumps"))
            with tarfile.open(rec["path"]) as tar:
                names = set(tar.getnames())
                assert {"nodes/node0.json", "nodes/node1.json",
                        "parent.json", "manifest.json"} <= names
                b = json.load(tar.extractfile("nodes/node1.json"))
                assert b["node"] == "node1" and "metrics" in b
            # -- SLO plane over the REAL control channel (ISSUE 19):
            # node-stamped slo/history ops, and the relay's merged
            # cluster verdict with every worker evaluated
            assert _wait(lambda: all(
                n.slo()["verdict"] != "no-data" for n in c.nodes),
                timeout=30)
            s1 = c.nodes[1].slo()
            assert s1["node"] == "node1" and s1["ticks"] >= 2
            assert "serving-availability" in s1["slos"]
            h0 = c.nodes[0].history(
                series=["cilium_serving_submitted_total"])
            assert h0["node"] == "node0"
            assert h0["series"] == ["cilium_serving_submitted_total"]
            assert h0["fast"]
            cs = c.obs.cluster_slo()
            assert cs["node-count"] == 2
            assert cs["unreachable"] == []
            assert all(e["ok"] for e in cs["nodes"].values())
            # -- SIGKILL MID-SCRAPE chaos leg -----------------------
            # (the periodic loop is live — duty-stretched cadence —
            # and the explicit sweep below races the corpse; the
            # relay must degrade, not wedge, and the router must
            # keep serving)
            c.node("node1").proc.kill()
            res = c.obs.scrape_now()
            assert res["node1"] is False
            text = c.obs.cluster_metrics()
            assert ('cilium_cluster_node_scrape_ok{node="node1"} 0'
                    in text)
            # the corpse degrades the merged health verdict NODE-
            # LABELED: counted unreachable with its error, never
            # silently dropped from the denominator (the verdict
            # flip past the staleness bound is pinned deterministic
            # in test_agent_slo's thread-mode leg)
            cs = c.obs.cluster_slo()
            assert "node1" in cs["unreachable"]
            assert cs["nodes"]["node1"]["ok"] is False
            assert cs["nodes"]["node1"]["error"]
            assert cs["nodes"]["node0"]["ok"] is True
            # the router keeps accepting while the corpse is found
            t0 = time.monotonic()
            while not c.membership.dead_nodes():
                c.submit(_batch(db.id, base=40000, sport_stride=3))
                assert time.monotonic() - t0 < 60
                time.sleep(0.02)
            assert _wait(lambda: c.failovers_total() == 1)
            stt = c.stop()
            assert stt["ledger"]["exact"], stt["ledger"]
            # the relay's own stats survived the chaos
            assert stt["obs"]["scrape-errors"] >= 1
            assert stt["obs"]["nodes"]["node1"]["ok"] is False
        finally:
            c.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.skipif(not _spawn_ok(),
                    reason="multiprocessing 'spawn' unavailable")
class TestProcessClusterObsAcceptance:
    """The full ISSUE 14 acceptance: a live THREE-node process
    cluster under load answers every merged view (slow lap — three
    worker jax inits)."""

    def test_three_node_acceptance(self, tmp_path):
        from cilium_tpu.cluster import ClusterServing

        names = ["node0", "node1", "node2"]
        c = ClusterServing(nodes=3, config=_mk_config(
            cluster_mode="process",
            cluster_trace_sample=4,
            cluster_obs_interval_s=0.25))
        c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        rev = c.policy_import(RULES)
        assert c.wait_policy(rev, timeout=30)
        try:
            c.start(trace_sample=0, packed=True,
                    ring_capacity=1 << 10)
            sent = 0
            for k in range(12):
                sent += c.submit(_batch(db.id, base=15000 + 512 * k,
                                        sport_stride=7))
            assert _wait(lambda: c.ledger()[
                "per-node-accounted"] >= sent)
            for n in c.nodes:
                n.record_incident("manual", {"why": "obs-test"})
            assert all(c.obs.scrape_now().values())
            text = c.obs.cluster_metrics()
            _assert_cluster_exposition(text, names)
            flows = c.obs.cluster_flows(number=1000,
                                        oldest_first=True)
            times = [f["time"] for f in flows]
            assert times == sorted(times)
            assert {f["node_name"] for f in flows} == set(names), (
                "flows must merge from ALL nodes")
            st = c.obs.cluster_trace()["stitched"]
            assert st["committed"] > 0
            assert all(sp["monotonic"] for sp in st["spans"])
            rec = c.cluster_sysdump(str(tmp_path / "dumps"))
            with tarfile.open(rec["path"]) as tar:
                got = set(tar.getnames())
                assert {f"nodes/{n}.json" for n in names} <= got
                assert {"parent.json", "manifest.json"} <= got
            top = c.obs.cluster_top(8)
            assert set(top["nodes"]) == set(names)
            stt = c.stop()
            assert stt["ledger"]["exact"], stt["ledger"]
        finally:
            c.shutdown()


class TestL7NodeLabeledStats:
    """ISSUE 17 satellite (PR 16 residue c): the relay's merged
    exposition carries per-plugin L7 parse/verdict latency
    node-labeled — one family, one HELP/TYPE, every live node's
    plugins inside it."""

    class _Peer:
        alive = True

        def __init__(self, name, l7):
            self.name = name
            self._l7 = l7

        def obs_scrape(self, cursor=0, flows=512, top=16):
            return {"metrics-text": "", "flows": [], "cursor": 0,
                    "top": None, "trace": None, "incidents": [],
                    "l7-by-plugin": self._l7}

    def test_merged_exposition_carries_per_plugin_series(self):
        from cilium_tpu.obs.relay import ClusterObsRelay

        peers = [
            self._Peer("node0", {"http": {
                "p50": 10.0, "p95": 20.0, "p99": 30.0,
                "max": 40.0, "count": 5}}),
            self._Peer("node1", {"dns": {
                "p50": 1.5, "p95": 2.5, "p99": 3.5,
                "max": 4.5, "count": 2}}),
        ]
        relay = ClusterObsRelay(lambda: peers, interval_s=0.0)
        text = relay.cluster_metrics()
        assert ('cilium_cluster_l7_parse_latency_us{node="node0",'
                'plugin="http",stat="p50"} 10.0') in text
        assert ('cilium_cluster_l7_parse_latency_us{node="node0",'
                'plugin="http",stat="count"} 5') in text
        assert ('cilium_cluster_l7_parse_latency_us{node="node1",'
                'plugin="dns",stat="p99"} 3.5') in text
        # one family: HELP/TYPE exactly once
        assert text.count(
            "# TYPE cilium_cluster_l7_parse_latency_us") == 1

    def test_family_absent_without_l7_traffic(self):
        from cilium_tpu.obs.relay import ClusterObsRelay

        relay = ClusterObsRelay(
            lambda: [self._Peer("node0", {})], interval_s=0.0)
        text = relay.cluster_metrics()
        assert "cilium_cluster_l7_parse_latency_us" not in text
