"""Service LB / Maglev (SURVEY.md §2b row 18; VERDICT r02 item 9).

Pins the Maglev properties that justify the algorithm (full table,
near-uniform distribution, minimal disruption on backend change) and
the device selection/DNAT semantics.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.core.packets import (
    COL_DPORT,
    COL_DST_IP3,
    COL_FAMILY,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    N_COLS,
)
from cilium_tpu.service import (
    M_DEFAULT,
    ServiceManager,
    lb_stage_jit,
    maglev_table,
)

M = 2039  # a smaller prime for test speed


class TestMaglevTable:
    def test_full_and_in_range(self):
        t = maglev_table([f"10.0.0.{i}:80" for i in range(5)], M)
        assert t.shape == (M,)
        assert (t >= 0).all() and (t < 5).all()

    def test_near_uniform(self):
        n = 7
        t = maglev_table([f"10.0.0.{i}:80" for i in range(n)], M)
        counts = np.bincount(t, minlength=n)
        # Maglev guarantees slot counts within ~1% of each other at
        # table sizes >> backends; allow a loose band
        assert counts.min() > 0.8 * M / n
        assert counts.max() < 1.2 * M / n

    def test_minimal_disruption_on_removal(self):
        keys = [f"10.0.0.{i}:80" for i in range(10)]
        before = maglev_table(keys, M)
        after = maglev_table(keys[:-1], M)  # drop the last backend
        moved = int((before != after).sum())
        lost = int((before == 9).sum())  # slots that HAD to move
        # consistent hashing: barely more slots move than must
        assert moved < lost * 2.0, (moved, lost)

    def test_empty_backends(self):
        t = maglev_table([], M)
        assert (t == -1).all()

    def test_deterministic(self):
        keys = ["a:1", "b:2", "c:3"]
        np.testing.assert_array_equal(maglev_table(keys, M),
                                      maglev_table(keys, M))


def _pkt_rows(n, dst, dport, rng):
    rows = np.zeros((n, N_COLS), dtype=np.uint32)
    rows[:, COL_SRC_IP3] = 0x0A000100 + rng.integers(0, 200, n)
    rows[:, COL_SPORT] = rng.integers(1024, 60000, n)
    rows[:, COL_DST_IP3] = dst
    rows[:, COL_DPORT] = dport
    rows[:, COL_PROTO] = 6
    rows[:, COL_FAMILY] = 4
    return rows


class TestLBStage:
    def _mgr(self):
        mgr = ServiceManager(m=M)
        mgr.upsert("web", "10.96.0.10:80",
                   ["10.0.1.1:8080", "10.0.1.2:8080", "10.0.1.3:8080"])
        mgr.upsert("dns", "10.96.0.53:53",
                   ["10.0.2.1:5353"], protocol=17)
        return mgr

    def test_vip_traffic_is_dnatted(self):
        mgr = self._mgr()
        rng = np.random.default_rng(0)
        vip = 0x0A60000A  # 10.96.0.10
        rows = _pkt_rows(256, vip, 80, rng)
        out, hits, _nb = lb_stage_jit(mgr.tensors(), jnp.asarray(rows))
        out = np.asarray(out)
        assert np.asarray(hits).all()
        # every packet now targets one of the three backends on 8080
        backends = {0x0A000101, 0x0A000102, 0x0A000103}
        assert set(out[:, COL_DST_IP3].tolist()) <= backends
        assert (out[:, COL_DPORT] == 8080).all()
        assert len(set(out[:, COL_DST_IP3].tolist())) == 3  # spread

    def test_flow_affinity(self):
        """Same 5-tuple -> same backend, every time."""
        mgr = self._mgr()
        rng = np.random.default_rng(1)
        rows = _pkt_rows(64, 0x0A60000A, 80, rng)
        t = mgr.tensors()
        out1 = np.asarray(lb_stage_jit(t, jnp.asarray(rows))[0])
        out2 = np.asarray(lb_stage_jit(t, jnp.asarray(rows))[0])
        np.testing.assert_array_equal(out1, out2)

    def test_non_vip_traffic_untouched(self):
        mgr = self._mgr()
        rng = np.random.default_rng(2)
        rows = _pkt_rows(64, 0x0A000042, 80, rng)  # not a VIP
        out, hits, _nb = lb_stage_jit(mgr.tensors(), jnp.asarray(rows))
        assert not np.asarray(hits).any()
        np.testing.assert_array_equal(np.asarray(out), rows)

    def test_proto_must_match(self):
        mgr = self._mgr()
        rng = np.random.default_rng(3)
        rows = _pkt_rows(16, 0x0A600035, 53, rng)  # dns VIP but TCP
        out, hits, _nb = lb_stage_jit(mgr.tensors(), jnp.asarray(rows))
        assert not np.asarray(hits).any()

    def test_vip_with_no_backends_passes_through(self):
        mgr = ServiceManager(m=M)
        mgr.upsert("empty", "10.96.0.99:80", [])
        rng = np.random.default_rng(4)
        rows = _pkt_rows(8, 0x0A600063, 80, rng)
        out, hits, _nb = lb_stage_jit(mgr.tensors(), jnp.asarray(rows))
        assert not np.asarray(hits).any()


class TestDaemonIntegration:
    def test_policy_applies_to_backend_not_vip(self):
        """LB-before-policy ordering: a rule allowing traffic to the
        BACKEND admits VIP-addressed traffic after DNAT."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [
                {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
                 "toPorts": [{"ports": [{"port": "5432",
                                         "protocol": "TCP"}]}]},
            ],
        }])
        d.services.upsert("db-svc", "10.96.0.5:5432",
                          ["10.0.2.1:5432"])
        d.start()
        evb = d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.96.0.5", sport=40000, dport=5432,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        assert list(evb.verdict) == [1]
        # status/introspection surface
        assert d.services.list()[0].to_dict()["backends"][0]["port"] \
            == 5432


class TestWeightedMaglev:
    def test_slot_share_tracks_weights(self):
        keys = [f"10.0.0.{i}:80" for i in range(3)]
        t = maglev_table(keys, M, weights=[1, 1, 2])
        counts = np.bincount(t, minlength=3) / len(t)
        assert abs(counts[0] - 0.25) < 0.02
        assert abs(counts[1] - 0.25) < 0.02
        assert abs(counts[2] - 0.50) < 0.02

    def test_zero_weight_backend_drained(self):
        keys = [f"10.0.0.{i}:80" for i in range(3)]
        t = maglev_table(keys, M, weights=[1, 0, 1])
        assert 1 not in t
        assert set(np.unique(t)) == {0, 2}

    def test_all_zero_weights_empty_table(self):
        t = maglev_table(["10.0.0.1:80"], M, weights=[0])
        assert (t == -1).all()

    def test_uniform_weights_match_unweighted(self):
        keys = [f"10.0.0.{i}:80" for i in range(5)]
        np.testing.assert_array_equal(
            maglev_table(keys, M),
            maglev_table(keys, M, weights=[1] * 5))

    def test_manager_upsert_with_weights(self):
        mgr = ServiceManager(m=1021)
        mgr.upsert("svc", "10.96.0.1:80",
                   ["10.0.0.1:8080", "10.0.0.2:8080"], weights=[3, 1])
        t = mgr.tensors()
        tab = np.asarray(t.maglev[0])
        counts = np.bincount(tab[tab >= 0], minlength=2) / (tab >= 0).sum()
        assert abs(counts[0] - 0.75) < 0.03

    def test_huge_weights_do_not_starve(self):
        """Review r04: backends with large raw weights must still
        share slots proportionally — not fill the table in one turn."""
        keys = [f"10.0.0.{i}:80" for i in range(2)]
        t = maglev_table(keys, M, weights=[5000, 5000])
        counts = np.bincount(t, minlength=2) / len(t)
        assert abs(counts[0] - 0.5) < 0.02
        t = maglev_table(keys, M, weights=[30000, 10000])
        counts = np.bincount(t, minlength=2) / len(t)
        assert abs(counts[0] - 0.75) < 0.02
