"""Config resolution (reference: pkg/option viper flags + config-dir
+ the cilium-config ConfigMap): defaults < config-dir files < env <
explicit flags; unknown keys are errors, not silent defaults."""

import os

import pytest

from cilium_tpu.agent.config import ENV_PREFIX, flag_registry, load_config


class TestFlagRegistry:
    def test_every_daemonconfig_field_is_a_flag(self):
        import dataclasses

        from cilium_tpu.agent.daemon import DaemonConfig

        reg = flag_registry()
        for f in dataclasses.fields(DaemonConfig):
            assert f.name.replace("_", "-") in reg


class TestLoadConfig:
    def test_config_dir_one_file_per_key(self, tmp_path):
        (tmp_path / "node-name").write_text("cfg-node\n")
        (tmp_path / "ct-capacity").write_text("4096")
        (tmp_path / "masquerade").write_text("true")
        (tmp_path / "non-masquerade-cidrs").write_text(
            "10.0.0.0/8, 192.168.0.0/16")
        cfg = load_config(config_dir=str(tmp_path), env={})
        assert cfg.node_name == "cfg-node"
        assert cfg.ct_capacity == 4096
        assert cfg.masquerade is True
        assert cfg.non_masquerade_cidrs == ("10.0.0.0/8",
                                            "192.168.0.0/16")

    def test_precedence_env_over_dir_flags_over_env(self, tmp_path):
        (tmp_path / "node-name").write_text("cfg-node")
        env = {f"{ENV_PREFIX}NODE_NAME": "env-node"}
        assert load_config(config_dir=str(tmp_path),
                           env=env).node_name == "env-node"
        assert load_config(config_dir=str(tmp_path), env=env,
                           node_name="flag-node").node_name == "flag-node"

    def test_unknown_key_raises(self, tmp_path):
        (tmp_path / "no-such-option").write_text("1")
        with pytest.raises(ValueError, match="unknown config option"):
            load_config(config_dir=str(tmp_path), env={})
        with pytest.raises(ValueError, match="unknown config option"):
            load_config(env={}, no_such_flag=1)

    def test_typoed_env_var_raises(self):
        """Review r04: CILIUM_TPU_MASQUERDE=true silently doing
        nothing is the exact failure mode the loader must reject."""
        with pytest.raises(ValueError, match="unknown config option"):
            load_config(env={f"{ENV_PREFIX}MASQUERDE": "true"})

    def test_bad_value_names_source(self, tmp_path):
        (tmp_path / "ct-capacity").write_text("a-lot")
        with pytest.raises(ValueError, match="config-dir"):
            load_config(config_dir=str(tmp_path), env={})

    def test_optional_fields_parse_none_and_values(self):
        cfg = load_config(env={f"{ENV_PREFIX}IDENTITY_LEASE_TTL": "30"})
        assert cfg.identity_lease_ttl == 30.0
        cfg = load_config(env={f"{ENV_PREFIX}IDENTITY_LEASE_TTL": "none"})
        assert cfg.identity_lease_ttl is None

    def test_configmap_hidden_entries_skipped(self, tmp_path):
        # k8s ConfigMap mounts include ..data/..2024_x symlink dirs
        (tmp_path / "node-name").write_text("n")
        (tmp_path / "..data").mkdir()
        hidden = tmp_path / ".hidden"
        hidden.write_text("x")
        cfg = load_config(config_dir=str(tmp_path), env={})
        assert cfg.node_name == "n"

    def test_daemon_boots_from_loaded_config(self, tmp_path):
        from cilium_tpu.agent import Daemon

        (tmp_path / "backend").write_text("interpreter")
        (tmp_path / "node-name").write_text("from-files")
        d = Daemon(load_config(config_dir=str(tmp_path), env={}))
        assert d.config.node_name == "from-files"


class TestDaemonRunConfigDir:
    def test_cli_daemon_resolves_config_dir(self, tmp_path):
        """`cilium-tpu daemon run --config-dir` boots from the mounted
        ConfigMap layout; explicit flags still win (subprocess: the
        run loop blocks forever, so probe the API then kill)."""
        import json
        import os
        import signal
        import subprocess
        import sys
        import time

        cfg_dir = tmp_path / "cfg"
        cfg_dir.mkdir()
        (cfg_dir / "backend").write_text("interpreter")
        (cfg_dir / "node-name").write_text("cfg-name")
        sock = str(tmp_path / "agent.sock")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cilium_tpu.cli.main",
             "--socket", sock, "daemon", "run",
             "--config-dir", str(cfg_dir),
             "--node-name", "flag-name"],  # flag beats config-dir
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            from cilium_tpu.api import APIClient

            deadline = time.time() + 30
            st = None
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"agent died: {proc.communicate()[0][-800:]}")
                try:
                    st = APIClient(sock).healthz()
                    break
                except (ConnectionRefusedError, FileNotFoundError,
                        OSError):
                    time.sleep(0.2)
            assert st is not None, "agent never served the API"
            assert st["node"] == "flag-name"
            assert st["backend"] == "interpreter"  # from config-dir
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
