"""L7 request enforcement (eval config #4; SURVEY.md §2a rows 5-6).

Covers: L7Rules -> match tensors, batched request verdicts (device
exact path + host regex fallback), HTTP allow/deny by method/path/
host, DNS matchName/matchPattern, L7 default deny, the access-record
stream, and the daemon e2e: packet redirect -> request verdicts.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.policy.api import L7Rules
from cilium_tpu.proxy import (
    L7Proxy,
    compile_l7,
    featurize_http,
    l7_verdict,
)
from cilium_tpu.proxy.featurize import KIND_DNS, KIND_HTTP


def _l7(http=None, dns=None) -> L7Rules:
    return L7Rules.from_dict(
        {k: v for k, v in (("http", http), ("dns", dns)) if v})


class TestCompile:
    def test_literal_rules_become_tensor_rows(self):
        t = compile_l7([(10000, "r1", _l7(http=[
            {"method": "GET", "path": "/healthz"},
            {"method": "POST", "path": "/api/v1"},
        ]))])
        assert t.rules.shape[0] == 2
        assert not t.host_matchers
        assert t.ports == frozenset({10000})

    def test_nonprefix_regex_rules_become_host_matchers(self):
        # r05: LITERAL.* compiles to a device prefix row, so only a
        # genuinely-structured regex still needs the host path
        t = compile_l7([(10000, "r1", _l7(http=[
            {"method": "GET", "path": "/api/v[0-9]+/users"},
        ]))])
        assert t.rules.shape[0] == 0
        assert len(t.host_matchers[10000]) == 1

    def test_unknown_method_is_not_widened_to_any(self):
        """r03 review: PURGE (outside the dense method table) must not
        compile to method-any; it takes the host path and still
        constrains the method."""
        t = compile_l7([(10000, "r1", _l7(http=[
            {"method": "PURGE", "path": "/cache"}]))])
        assert t.rules.shape[0] == 0
        assert len(t.host_matchers[10000]) == 1
        p = L7Proxy()
        p.update([type("P", (), {"redirects": [
            (10000, "r1", _l7(http=[{"method": "PURGE",
                                     "path": "/cache"}]))]})()])
        got = p.handle_http(10000, [
            {"method": "PURGE", "path": "/cache"},
            {"method": "GET", "path": "/cache"},
        ])
        assert list(got) == [1, 0]

    def test_dns_name_vs_pattern_split(self):
        t = compile_l7([(10053, "r1", _l7(dns=[
            {"matchName": "example.com"},
            {"matchPattern": "*.example.com"},
        ]))])
        assert t.rules.shape[0] == 1
        assert len(t.host_matchers[10053]) == 1


class TestHTTPVerdicts:
    def _proxy(self, http):
        p = L7Proxy()
        p.update([type("P", (), {
            "redirects": [(10000, "rule", _l7(http=http))]})()])
        return p

    def test_method_and_path_allow_deny(self):
        p = self._proxy([{"method": "GET", "path": "/data"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/data"},     # allow
            {"method": "POST", "path": "/data"},    # wrong method
            {"method": "GET", "path": "/other"},    # wrong path
            {"method": "GET", "path": "/data/x"},   # not the literal
        ])
        assert list(got) == [1, 0, 0, 0]

    def test_method_only_rule_allows_any_path(self):
        p = self._proxy([{"method": "GET"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/anything"},
            {"method": "DELETE", "path": "/anything"},
        ])
        assert list(got) == [1, 0]

    def test_host_constraint(self):
        p = self._proxy([{"method": "GET", "host": "api.internal"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/x", "host": "api.internal"},
            {"method": "GET", "path": "/x", "host": "evil.example"},
        ])
        assert list(got) == [1, 0]

    def test_regex_path_fallback(self):
        p = self._proxy([{"method": "GET", "path": "/api/v[0-9]+/.*"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/api/v1/users"},
            {"method": "GET", "path": "/api/vX/users"},
            {"method": "POST", "path": "/api/v1/users"},
        ])
        assert list(got) == [1, 0, 0]

    def test_mixed_exact_and_regex(self):
        p = self._proxy([{"method": "GET", "path": "/exact"},
                         {"method": "PUT", "path": "/re/.*"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/exact"},
            {"method": "PUT", "path": "/re/anything"},
            {"method": "PUT", "path": "/exact"},
        ])
        assert list(got) == [1, 1, 0]

    def test_unknown_port_passes_through(self):
        p = self._proxy([{"method": "GET"}])
        got = p.handle_http(31337, [{"method": "DELETE", "path": "/"}])
        assert list(got) == [1]

    def test_records_emitted(self):
        p = self._proxy([{"method": "GET", "path": "/ok"}])
        recs = []
        p.on_record(recs.append)
        p.handle_http(10000, [{"method": "GET", "path": "/ok"},
                              {"method": "POST", "path": "/no"}])
        assert len(recs) == 2
        assert recs[0].status == 200 and recs[0].verdict == 1
        assert recs[1].status == 403 and recs[1].verdict == 0
        assert recs[1].method == "POST" and recs[1].path == "/no"
        assert p.requests_total == 2 and p.requests_denied == 1


class TestDNSVerdicts:
    def _proxy(self, dns):
        p = L7Proxy()
        p.update([type("P", (), {
            "redirects": [(10053, "rule", _l7(dns=dns))]})()])
        return p

    def test_match_name_exact(self):
        p = self._proxy([{"matchName": "example.com"}])
        got = p.handle_dns(10053, ["example.com", "example.com.",
                                   "EXAMPLE.COM", "evil.com",
                                   "sub.example.com"])
        assert list(got) == [1, 1, 1, 0, 0]

    def test_match_pattern_glob(self):
        p = self._proxy([{"matchPattern": "*.example.com"}])
        got = p.handle_dns(10053, ["api.example.com", "example.com",
                                   "deep.sub.example.com", "evil.com"])
        # per-label "*" (upstream pkg/fqdn/matchpattern): a wildcard
        # never crosses a dot, so deep.sub.example.com does NOT match
        assert list(got) == [1, 0, 0, 0]

    def test_observe_answer_notifies_fqdn_observers(self):
        p = self._proxy([{"matchName": "example.com"}])
        seen = []
        p.observe_dns(lambda name, ips, ttl: seen.append((name,
                                                          tuple(ips))))
        p.observe_answer("Example.COM.", ["93.184.216.34"], ttl=300)
        assert seen == [("example.com", ("93.184.216.34",))]


class TestKafkaVerdicts:
    def _proxy(self, kafka):
        p = L7Proxy()
        p.update([type("P", (), {
            "redirects": [(19092, "rule", _l7_kafka(kafka))]})()])
        return p

    def test_produce_topic_rule(self):
        p = self._proxy([{"role": "produce", "topic": "orders"}])
        got = p.handle_kafka(19092, [
            {"api_key": "produce", "topic": "orders"},
            {"api_key": "produce", "topic": "secrets"},
            {"api_key": "fetch", "topic": "orders"},
        ])
        assert list(got) == [1, 0, 0]

    def test_topic_only_rule_allows_any_api(self):
        p = self._proxy([{"topic": "orders"}])
        got = p.handle_kafka(19092, [
            {"api_key": "produce", "topic": "orders"},
            {"api_key": "fetch", "topic": "orders"},
            {"api_key": "fetch", "topic": "other"},
        ])
        assert list(got) == [1, 1, 0]

    def test_kafka_seven_flow(self):
        from cilium_tpu.flow import Observer, SevenParser

        p = self._proxy([{"topic": "orders"}])
        obs = Observer(capacity=64)
        p.on_record(SevenParser(obs).consume)
        p.handle_kafka(19092, [{"api_key": "produce",
                               "topic": "denied-topic"}])
        f = obs.get_flows(number=1)[0]
        assert f.l7["kafka"]["topic"] == "denied-topic"
        assert f.l7["kafka"]["error_code"] == 29


def _l7_kafka(kafka) -> L7Rules:
    return L7Rules.from_dict({"kafka": kafka})


RULES_L7 = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                      "rules": {"http": [{"method": "GET",
                                          "path": "/public"}]}}]},
    ],
}]


class TestDaemonE2E:
    def test_redirect_then_request_verdicts(self):
        """The full plane: L3/L4 verdict says REDIRECT with a proxy
        port; requests on that port are L7-enforced."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES_L7)
        d.start()

        evb = d.process_batch(make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=80,
                 proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        assert list(evb.verdict) == [3]  # VERDICT_REDIRECT
        proxy_port = int(evb.proxy_port[0])
        assert proxy_port in d.proxy.ports

        got = d.handle_l7_http(proxy_port, [
            {"method": "GET", "path": "/public"},
            {"method": "GET", "path": "/secret"},
            {"method": "POST", "path": "/public"},
        ], src_identity=web.identity.numeric_id)
        assert list(got) == [1, 0, 0]

    def test_parse_http_bytes_roundtrip(self):
        from cilium_tpu.proxy.featurize import parse_http_bytes

        reqs = parse_http_bytes([
            b"GET /public HTTP/1.1\r\nHost: db.svc\r\n\r\n",
            b"POST /x HTTP/1.1\r\n\r\nbody",
            b"garbage",
        ])
        assert reqs[0] == {"method": "GET", "path": "/public",
                           "host": "db.svc"}
        assert reqs[1]["method"] == "POST" and reqs[1]["host"] == ""
        assert reqs[2] == {}


class TestDevicePrefixRules:
    """r05: LITERAL.* / LITERAL.+ path rules compile to device prefix
    rows (rolling prefix-hash compare) instead of host matchers."""

    def _proxy(self, http):
        p = L7Proxy()
        p.update([type("P", (), {
            "redirects": [(10000, "rule", _l7(http=http))]})()])
        return p

    def test_prefix_rule_compiles_to_device_row(self):
        from cilium_tpu.proxy.l7policy import compile_l7
        from cilium_tpu.policy.api import L7Rules

        l7 = L7Rules.from_dict({"http": [
            {"method": "GET", "path": "/static/.*"}]})
        t = compile_l7([(10000, "r", l7)])
        assert t.n_prefix == 1
        assert not t.host_matchers  # no fallback needed

    def test_prefix_semantics_match_regex(self):
        p = self._proxy([{"method": "GET", "path": "/static/.*"},
                         {"method": "GET", "path": "/api/v1/.+"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/static/app.js"},   # 1
            {"method": "GET", "path": "/static/"},         # 1 (.* empty)
            {"method": "GET", "path": "/static"},          # 0 (no slash)
            {"method": "POST", "path": "/static/app.js"},  # 0 (method)
            {"method": "GET", "path": "/api/v1/x"},        # 1
            {"method": "GET", "path": "/api/v1/"},         # 0 (.+ needs 1)
            {"method": "GET", "path": "/api/v2/x"},        # 0
        ])
        assert list(got) == [1, 1, 0, 0, 1, 0, 0]
        # and nothing fell back to host matchers
        assert p.host_fallback_checked == 0

    def test_long_prefix_falls_back_to_host(self):
        from cilium_tpu.proxy.l7policy import compile_l7
        from cilium_tpu.policy.api import L7Rules

        long = "/" + "a" * 60
        l7 = L7Rules.from_dict({"http": [
            {"method": "GET", "path": long + "/.*"}]})
        t = compile_l7([(10000, "r", l7)])
        assert t.n_prefix == 0
        assert t.host_matchers  # still enforced, host-side

    def test_prefix_with_host_constraint(self):
        p = self._proxy([{"method": "GET", "path": "/files/.*",
                          "host": "cdn.svc"}])
        got = p.handle_http(10000, [
            {"method": "GET", "path": "/files/x", "host": "cdn.svc"},
            {"method": "GET", "path": "/files/x", "host": "evil.svc"},
        ])
        assert list(got) == [1, 0]
