"""Device event ring: compaction semantics, wrap-overwrite, loss
accounting — the eventsmap/perf-ring analogue (monitor/ring.py)."""

import jax.numpy as jnp
import numpy as np

from cilium_tpu.datapath.verdict import (
    EV_DROP,
    EV_TRACE,
    EV_VERDICT,
    N_OUT,
    OUT_EVENT,
)
from cilium_tpu.monitor.ring import (
    COL_BATCH,
    COL_PKT_IDX,
    EventRing,
    ring_append,
    ring_drain,
)


def _out(events):
    """Build an out tensor whose rows carry distinct payloads."""
    n = len(events)
    out = np.zeros((n, N_OUT), dtype=np.uint32)
    out[:, 0] = np.arange(n)  # verdict column doubles as a payload tag
    out[:, OUT_EVENT] = events
    return jnp.asarray(out)


def test_compaction_keeps_drops_and_verdicts():
    ring = EventRing.create(64)
    ev = [EV_TRACE, EV_DROP, EV_TRACE, EV_VERDICT, EV_DROP]
    ring = ring_append(ring, _out(ev), jnp.uint32(7), trace_sample=0)
    rows, total, lost = ring_drain(ring)
    assert total == 3 and lost == 0
    # append order preserved; pkt idx + batch id recorded
    assert list(rows[:, COL_PKT_IDX]) == [1, 3, 4]
    assert set(rows[:, COL_BATCH]) == {7}
    assert list(rows[:, 0]) == [1, 3, 4]


def test_trace_sampling():
    ring = EventRing.create(256)
    ev = [EV_TRACE] * 100
    ring = ring_append(ring, _out(ev), jnp.uint32(0), trace_sample=10)
    rows, total, _ = ring_drain(ring)
    assert total == 10  # packets 0, 10, ..., 90
    assert list(rows[:, COL_PKT_IDX]) == list(range(0, 100, 10))


def test_wrap_overwrite_and_loss():
    ring = EventRing.create(8)
    # 3 batches x 5 drops = 15 events into an 8-slot ring
    for b in range(3):
        ring = ring_append(ring, _out([EV_DROP] * 5), jnp.uint32(b),
                           trace_sample=0)
    rows, total, lost = ring_drain(ring)
    assert total == 15 and lost == 7
    assert len(rows) == 8
    # survivors are the newest 8 in order: batch1 pkts 2-4, batch2 all
    assert [(int(r[COL_BATCH]), int(r[COL_PKT_IDX])) for r in rows] == \
        [(1, 2), (1, 3), (1, 4), (2, 0), (2, 1), (2, 2), (2, 3), (2, 4)]


def test_valid_mask_excludes_padding():
    ring = EventRing.create(64)
    ev = [EV_DROP, EV_DROP, EV_DROP]
    valid = jnp.asarray([True, False, True])
    ring = ring_append(ring, _out(ev), jnp.uint32(1), trace_sample=0,
                       valid=valid)
    rows, total, _ = ring_drain(ring)
    assert total == 2
    assert list(rows[:, COL_PKT_IDX]) == [0, 2]


def test_ring_matches_host_filter_on_pipeline_output():
    """Ring compaction over real datapath output == host-side filter."""
    import jax

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.testing.fixtures import bench_traffic, build_world

    world = build_world(n_identities=128, n_rules=8, ct_capacity=1 << 12)
    rng = np.random.default_rng(3)
    hdr = jnp.asarray(bench_traffic(world, 2048, rng))
    out, _state = datapath_step_jit(world.state, hdr, jnp.uint32(100))
    host_out = np.asarray(out)
    # the live listener table: redirect events carry a 4-bit index
    # into it on the 8 B wire format; the same table restores ports
    from cilium_tpu.datapath.verdict import OUT_PROXY

    ports = np.unique(host_out[:, OUT_PROXY])
    ports = ports[ports != 0].astype(np.uint32)
    ring = EventRing.create(1 << 12)
    ring = ring_append(ring, out, jnp.uint32(0), trace_sample=256,
                       proxy_ports=jnp.asarray(ports))
    rows, total, lost = ring_drain(ring, proxy_ports=ports)
    keep = (host_out[:, OUT_EVENT] != EV_TRACE) | \
        (np.arange(2048) % 256 == 0)
    assert lost == 0
    assert total == int(keep.sum())
    np.testing.assert_array_equal(rows[:, :N_OUT], host_out[keep])
    np.testing.assert_array_equal(rows[:, COL_PKT_IDX],
                                  np.nonzero(keep)[0])


def test_proxy_port_round_trips_through_listener_index():
    """Redirect events store the proxy PORT as a 4-bit index into the
    live listener table (8 B wire rows); decode restores the port."""
    from cilium_tpu.datapath.verdict import OUT_PROXY

    ring = EventRing.create(64)
    out = np.zeros((4, N_OUT), dtype=np.uint32)
    out[:, OUT_EVENT] = EV_VERDICT
    out[:, OUT_PROXY] = [15001, 0, 15003, 15001]
    table = np.asarray([15001, 15003], dtype=np.uint32)
    ring = ring_append(ring, jnp.asarray(out), jnp.uint32(2),
                       trace_sample=0, proxy_ports=jnp.asarray(table))
    rows, total, _ = ring_drain(ring, proxy_ports=table)
    assert total == 4
    assert list(rows[:, OUT_PROXY]) == [15001, 0, 15003, 15001]
    # without the table the index cannot resolve: ports decode as 0
    rows0, _, _ = ring_drain(ring)
    assert list(rows0[:, OUT_PROXY]) == [0, 0, 0, 0]


def test_serve_step_matches_separate_dispatch():
    """Fused serve_step (datapath + ring append in one executable) ==
    step-then-append, state and ring both."""
    import jax

    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.monitor.ring import ring_append, serve_step_jit
    from cilium_tpu.testing.fixtures import bench_traffic, build_world

    w1 = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 10)
    w2 = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 10)
    rng = np.random.default_rng(5)
    hdr = jnp.asarray(bench_traffic(w1, 512, rng))
    r1 = EventRing.create(1 << 10)
    r2 = EventRing.create(1 << 10)

    s1, r1 = serve_step_jit(w1.state, r1, hdr, jnp.uint32(50),
                            jnp.uint32(3), trace_sample=64)
    out, s2 = datapath_step_jit(w2.state, hdr, jnp.uint32(50))
    r2 = ring_append(r2, out, jnp.uint32(3), trace_sample=64)

    a1, t1, l1 = ring_drain(r1)
    a2, t2, l2 = ring_drain(r2)
    np.testing.assert_array_equal(a1, a2)
    assert (t1, l1) == (t2, l2)
    np.testing.assert_array_equal(np.asarray(s1.ct.table),
                                  np.asarray(s2.ct.table))
    np.testing.assert_array_equal(np.asarray(s1.metrics),
                                  np.asarray(s2.metrics))


def test_single_batch_overflow_newest_wins():
    """One append larger than the ring: survivors are exactly the
    newest `capacity` kept events, in order (no duplicate-slot
    scatter nondeterminism)."""
    ring = EventRing.create(8)
    ring = ring_append(ring, _out([EV_DROP] * 20), jnp.uint32(5),
                       trace_sample=0)
    rows, total, lost = ring_drain(ring)
    assert total == 20 and lost == 12
    assert list(rows[:, COL_PKT_IDX]) == list(range(12, 20))


def test_async_drainer_windowed_equivalence():
    """Double-buffered windows collect exactly the events a
    sequential per-window drain would, with per-window loss."""
    from cilium_tpu.datapath.verdict import EV_DROP, N_OUT, OUT_EVENT
    from cilium_tpu.monitor.ring import (AsyncRingDrainer, COL_BATCH,
                                         COL_PKT_IDX, ring_append_jit)

    drainer = AsyncRingDrainer(capacity=64)
    ring = drainer.fresh()
    seen = []
    for w in range(4):
        out = jnp.zeros((32, N_OUT), dtype=jnp.uint32)
        out = out.at[:, OUT_EVENT].set(EV_DROP)  # all kept
        ring = ring_append_jit(ring, out, jnp.uint32(w), trace_sample=0)
        rows, appended, lost = drainer.collect()
        seen.extend((int(r[COL_BATCH]), int(r[COL_PKT_IDX]))
                    for r in rows)
        ring = drainer.swap(ring)
    rows, _, _ = drainer.collect()  # the last in-flight window
    seen.extend((int(r[COL_BATCH]), int(r[COL_PKT_IDX])) for r in rows)
    assert seen == [(w, i) for w in range(4) for i in range(32)]
    assert drainer.windows == 4
    assert drainer.events == 128 and drainer.lost == 0


def test_async_drainer_counts_window_loss():
    from cilium_tpu.datapath.verdict import EV_DROP, N_OUT, OUT_EVENT
    from cilium_tpu.monitor.ring import AsyncRingDrainer, ring_append_jit

    drainer = AsyncRingDrainer(capacity=16)
    ring = drainer.fresh()
    out = jnp.zeros((48, N_OUT), dtype=jnp.uint32)
    out = out.at[:, OUT_EVENT].set(EV_DROP)
    ring = ring_append_jit(ring, out, jnp.uint32(0), trace_sample=0)
    drainer.swap(ring)
    rows, appended, lost = drainer.collect()
    assert appended == 48 and lost == 32 and len(rows) == 16
    assert drainer.lost == 32 and drainer.events == 16
