"""The shared cluster socket transport (ISSUE 13): framing property
tests, row-batch codec round trips, and the socket-backpressure
contract.

Acceptance (satellite: transport test coverage):
(a) framing survives arbitrary partial-read fragmentation (property
    test over random split points);
(b) torn length prefixes / torn bodies / oversized declared lengths
    are LOUD (``FrameError``), never a silent short read or an
    unbounded allocation;
(c) a slow node backpressures through the BOUNDED forward queue into
    counted ``REASON_CLUSTER_OVERFLOW`` sheds — never an unbounded
    buffer anywhere in the path.

Named to sort early (the tier-1 budget-truncation convention)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from cilium_tpu.cluster.transport import (ACK_SIZE, FrameError,
                                          LineFramer, decode_rows,
                                          encode_rows, pack_ack,
                                          recv_frame, send_frame,
                                          shutdown_close, unpack_ack)

pytestmark = pytest.mark.cluster


def _pair():
    a, b = socket.socketpair()
    return a, b


class TestFraming:
    def test_roundtrip_simple(self):
        a, b = _pair()
        try:
            send_frame(a, b"hello")
            assert recv_frame(b) == b"hello"
            send_frame(a, b"")
            assert recv_frame(b) == b""
        finally:
            a.close()
            b.close()

    def test_partial_reads_property(self):
        """Frames survive ANY byte-level fragmentation: the sender
        dribbles the wire bytes one fragment at a time at random
        split points; the receiver reassembles every frame intact."""
        rng = np.random.default_rng(7)
        payloads = [rng.integers(0, 256, size=int(n),
                                 dtype=np.uint8).tobytes()
                    for n in rng.integers(0, 2048, size=32)]
        wire = b"".join(struct.pack(">I", len(p)) + p
                        for p in payloads)
        cuts = sorted(rng.integers(0, len(wire), size=64).tolist())
        frags = [wire[a:b] for a, b in
                 zip([0] + cuts, cuts + [len(wire)])]
        a, b = _pair()
        try:
            def dribble():
                for f in frags:
                    if f:
                        a.sendall(f)
                        time.sleep(0.0005)
                a.close()

            t = threading.Thread(target=dribble, daemon=True)
            t.start()
            got = []
            while True:
                p = recv_frame(b)
                if p is None:
                    break
                got.append(p)
            t.join()
            assert got == payloads
        finally:
            b.close()

    def test_torn_length_prefix_is_loud(self):
        a, b = _pair()
        try:
            a.sendall(b"\x00\x00")  # half a length prefix, then EOF
            a.close()
            with pytest.raises(FrameError, match="mid-frame"):
                recv_frame(b)
        finally:
            b.close()

    def test_torn_body_is_loud(self):
        a, b = _pair()
        try:
            a.sendall(struct.pack(">I", 100) + b"x" * 40)
            a.close()
            with pytest.raises(FrameError):
                recv_frame(b)
        finally:
            b.close()

    def test_oversized_frame_rejected_without_allocation(self):
        """A hostile/corrupt prefix declaring a huge length must be
        rejected from the 4 header bytes alone — the receiver never
        tries to allocate or read the claimed body."""
        a, b = _pair()
        try:
            a.sendall(struct.pack(">I", 1 << 31))
            with pytest.raises(FrameError, match="exceeds max_frame"):
                recv_frame(b)
            # and a tight custom bound enforces the same way
            send_frame(a, b"y" * 64)
            with pytest.raises(FrameError, match="exceeds max_frame"):
                recv_frame(b, max_frame=16)
        finally:
            a.close()
            b.close()

    def test_clean_eof_at_boundary_is_none(self):
        a, b = _pair()
        send_frame(a, b"last")
        a.close()
        try:
            assert recv_frame(b) == b"last"
            assert recv_frame(b) is None
        finally:
            b.close()


class TestLineFramer:
    def test_reassembles_partial_lines(self):
        f = LineFramer()
        assert f.feed(b'{"a"') == []
        assert f.feed(b": 1}\n{") == [b'{"a": 1}']
        assert f.pending == 1
        assert f.feed(b'"b": 2}\n\n{"c"') == [b'{"b": 2}']
        assert f.feed(b": 3}\n") == [b'{"c": 3}']
        assert f.pending == 0

    def test_many_lines_one_read(self):
        f = LineFramer()
        lines = f.feed(b"x\ny\nz\n")
        assert lines == [b"x", b"y", b"z"]


class TestRowCodec:
    def test_wide_roundtrip(self):
        rows = np.arange(64 * 16, dtype=np.uint32).reshape(64, 16)
        out, meta = decode_rows(encode_rows(rows))
        assert meta is None
        assert (out == rows).all()

    def test_packed_roundtrip_carries_stream_scalars(self):
        rows = np.arange(32 * 4, dtype=np.uint32).reshape(32, 4)
        out, meta = decode_rows(
            encode_rows(rows, packed_meta=(7, 1)))
        assert meta == (7, 1)
        assert (out == rows).all()

    def test_shape_mismatch_is_loud(self):
        rows = np.zeros((8, 16), dtype=np.uint32)
        payload = bytearray(encode_rows(rows))
        payload[1:5] = struct.pack(">I", 9)  # lie about n
        with pytest.raises(FrameError, match="declares"):
            decode_rows(bytes(payload))

    def test_short_header_is_loud(self):
        with pytest.raises(FrameError, match="shorter"):
            decode_rows(b"\x01\x00")

    def test_unknown_kind_is_loud(self):
        rows = np.zeros((2, 16), dtype=np.uint32)
        payload = bytearray(encode_rows(rows))
        payload[0] = 99
        with pytest.raises(FrameError, match="kind"):
            decode_rows(bytes(payload))

    def test_ack_roundtrip(self):
        blob = pack_ack(64, 1 << 40, 12, 3, 4)
        assert len(blob) == ACK_SIZE
        assert unpack_ack(blob) == (64, 1 << 40, 12, 3, 4)
        with pytest.raises(FrameError):
            unpack_ack(blob[:-1])


class TestShutdownClose:
    def test_wakes_blocked_reader(self):
        """The PR 8 close-vs-blocked-syscall discipline, now one
        definition: closing via shutdown_close unblocks a reader
        pinned in recv() on the same fd."""
        a, b = _pair()
        got = []

        def reader():
            got.append(b.recv(1024))

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.05)
        shutdown_close(b)
        t.join(2.0)
        assert not t.is_alive(), "reader stayed wedged past close"
        a.close()
        shutdown_close(None)  # None is a no-op, not a crash


class TestRouterBackpressure:
    def test_slow_node_bounded_queue_counted_sheds(self):
        """A slow consumer must surface as BOUNDED queue growth then
        counted REASON_CLUSTER_OVERFLOW sheds at the router — never
        an unbounded buffer.  (The drop decode path e2e is
        test_cluster_serving's; this pins the bound + the count.)"""
        from cilium_tpu.cluster.router import ClusterRouter

        class SlowNode:
            name = "slow0"
            alive = False  # parked: the fill phase is deterministic
            # (a racing consumer under machine load could otherwise
            # keep up with a slowed submit loop and nothing would
            # overflow)

            def __init__(self):
                self.got = 0

            def submit(self, rows):
                time.sleep(0.02)  # a slow worker once unparked
                self.got += len(rows)
                return len(rows)

        node = SlowNode()
        r = ClusterRouter([node], forward_depth=256)
        r.start()
        rows = np.zeros((128, 16), dtype=np.uint32)
        rows[:, 13] = 4  # COL_FAMILY
        sent = admitted = 0
        for i in range(40):
            rows[:, 8] = 1024 + i  # COL_SPORT: vary the flows
            admitted += r.submit(rows)
            sent += len(rows)
        # the queue filled to its BOUND and no further: every row
        # past it is a counted shed, never an unbounded buffer
        assert r.pending_total() == 256
        assert admitted == 256
        assert r.router_overflow == sent - admitted > 0
        # unpark: the slow consumer drains the bounded backlog
        node.alive = True
        t0 = time.monotonic()
        while r.pending_total() > 0:
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        snap = r.stop(drain=True)
        assert (snap["submitted"]
                == sum(snap["forwarded"]) + snap["router-overflow"])
        assert node.got == admitted
        # forward-path latency histogram saw the slow deliveries
        # (each spent >= the fill wait + the 20 ms submit)
        lat = snap["forward-latency-us"]
        assert lat["count"] == 2  # two 128-row chunks delivered
        assert lat["p50"] >= 2e4  # >= 20 ms
