"""K-batch superbatch dispatch (ISSUE 11): amortize Python dispatch
with a device-resident multi-batch serve loop.

Acceptance covered here:
(a) EQUIVALENCE: ``serve_superbatch`` (one ``lax.scan`` dispatch over
    K steps) produces byte-identical ring events, CT evolution, and
    metricsmap to K sequential ``serve``/``serve_packed`` dispatches
    — wide and packed, with per-step partial valid masks;
(b) ASSEMBLY: ``assemble_super`` collects K ready full buckets in one
    exception-atomic dequeue, rounds K DOWN to the power-of-two
    ladder (no empty steps), and falls back to the single-batch path
    below two full buckets — low-load behavior byte-identical;
(c) LADDER: K is a rung property — demotion shrinks K before it ever
    changes mode, promotion walks the exact inverse, the floor is the
    last mode at K=1, and the default ``k_ladder=(1,)`` keeps the
    pre-superbatch ladder byte-identical;
(d) RUNTIME: the ingress drain loop dispatches superbatches with the
    no-silent-loss ledger exact, batches-per-dispatch > 1, sampled
    spans completing, and a lost in-flight superbatch accounting ALL
    K batches' rows;
(e) COMPILE-LOG INVARIANT at (rung, mode, K): each K is exactly one
    executable per bucket rung, a re-sweep retraces nothing, and a
    K-ladder retrace would surface as a loud violation.

Discipline mirrors test_serving_faults: seeded schedules, one ladder
rung, bounded polling.  Named test_dispatch_* so it sorts early
(the tier-1 budget truncates the alphabet tail on this box).
"""

import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch
from cilium_tpu.core.packets import (N_COLS, pack_eligibility,
                                     pack_rows)
from cilium_tpu.infra import faults
from cilium_tpu.monitor.ring import AsyncRingDrainer, ring_drain
from cilium_tpu.serving import (AdaptiveBatcher, FallbackLadder,
                                IngressQueue,
                                validate_superbatch_config)
from cilium_tpu.serving.batcher import AssembledBatch, SuperBatch
from cilium_tpu.serving.ladder import RUNG_SHARDED, RUNG_SINGLE, \
    RUNG_WIDE

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "web"}}],
                 "toPorts": [{"ports": [{"port": "5432",
                                         "protocol": "TCP"}]}]}],
}]


def _daemon(fault_spec=None, **over):
    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_dispatch_deadline_ms=500.0,
               serving_restart_budget=4,
               serving_restart_backoff_ms=1.0,
               fault_injection=fault_spec, fault_seed=1)
    cfg.update(over)
    d = Daemon(DaemonConfig(**cfg))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _wait(pred, timeout=30.0, tick=0.002):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


def _traffic(db_id, n, sport0, flags=TCP_SYN, dport=5432):
    rows = [dict(src="10.0.1.1", dst="10.0.2.1", sport=sport0 + i,
                 dport=dport if i % 3 else 9999, proto=6,
                 flags=flags, ep=db_id, dir=0) for i in range(n)]
    return make_batch(rows).data


def _assert_ledger(fe):
    ft = fe["fault-tolerance"]
    assert fe["submitted"] == (fe["verdicts"] + fe["shed"]
                               + ft["recovery-dropped"]), (
        f"ledger broken: {fe['submitted']} != {fe['verdicts']} + "
        f"{fe['shed']} + {ft['recovery-dropped']}")
    return ft


# ---------------------------------------------------------------------
class TestSuperbatchKernelEquivalence:
    """serve_superbatch == K sequential serve dispatches, bit-exact:
    same ring rows, same CT, same metricsmap.  The scan captures ONE
    state, so this also proves the fused path cannot interleave table
    reads mid-superbatch."""

    B, K = 64, 4

    def _hdrs(self, db_id):
        hdrs = np.stack([_traffic(db_id, self.B, 20000 + 100 * k)
                         for k in range(self.K)])
        valid = np.ones((self.K, self.B), dtype=bool)
        valid[self.K - 1, self.B // 2:] = False  # partial last step
        return hdrs, valid

    def _sequential(self, hdrs, valid, packed):
        d, db = _daemon()
        drainer = AsyncRingDrainer(1 << 12, gather=False)
        ring = drainer.fresh()
        for k in range(len(hdrs)):
            if packed:
                ok, ep, dirn = pack_eligibility(hdrs[k])
                assert ok
                ring, _ = d.loader.serve_packed(
                    ring, pack_rows(hdrs[k]), 100, k, ep, dirn,
                    trace_sample=1, valid=valid[k])
            else:
                ring, _ = d.loader.serve(ring, hdrs[k], 100, k,
                                         trace_sample=1,
                                         valid=valid[k])
        rows, appended, _ = ring_drain(ring)
        out = (rows, appended, d.loader.ct_snapshot(),
               d.loader.metrics())
        d.shutdown()
        return out

    def _super(self, hdrs, valid, packed):
        d, db = _daemon()
        drainer = AsyncRingDrainer(1 << 12, gather=False)
        ring = drainer.fresh()
        if packed:
            metas = [pack_eligibility(h) for h in hdrs]
            phdr = np.stack([pack_rows(h) for h in hdrs])
            ring, _ = d.loader.serve_superbatch(
                ring, phdr, 100, 0,
                eps=np.asarray([m[1] for m in metas]),
                dirns=np.asarray([m[2] for m in metas]),
                trace_sample=1, valid=valid, packed=True)
        else:
            ring, _ = d.loader.serve_superbatch(
                ring, hdrs, 100, 0, trace_sample=1, valid=valid)
        rows, appended, _ = ring_drain(ring)
        out = (rows, appended, d.loader.ct_snapshot(),
               d.loader.metrics())
        d.shutdown()
        return out

    def test_wide_superbatch_matches_sequential(self):
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        hdrs, valid = self._hdrs(db_id)
        r1, a1, ct1, m1 = self._sequential(hdrs, valid, packed=False)
        r2, a2, ct2, m2 = self._super(hdrs, valid, packed=False)
        assert a1 == a2 and a1 > 0
        assert np.array_equal(r1, r2)
        assert np.array_equal(ct1, ct2)
        assert np.array_equal(m1, m2)

    def test_packed_superbatch_matches_sequential(self):
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        hdrs, valid = self._hdrs(db_id)
        r1, a1, ct1, m1 = self._sequential(hdrs, valid, packed=True)
        r2, a2, ct2, m2 = self._super(hdrs, valid, packed=True)
        assert a1 == a2 and a1 > 0
        assert np.array_equal(r1, r2)
        assert np.array_equal(ct1, ct2)
        assert np.array_equal(m1, m2)

    def test_empty_trailing_step_appends_nothing(self):
        """An all-invalid step (the kernel's empty-step contract)
        touches neither the ring nor CT — K=2 with step 1 dead equals
        the single step alone."""
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        one = _traffic(db_id, self.B, 21000)
        hdrs = np.stack([one, one])  # step 1 masked entirely
        valid = np.ones((2, self.B), dtype=bool)
        valid[1, :] = False
        r2, a2, ct2, _m2 = self._super(hdrs, valid, packed=False)
        r1, a1, ct1, _m1 = self._sequential(
            one[None], np.ones((1, self.B), dtype=bool),
            packed=False)
        assert a1 == a2
        assert np.array_equal(r1, r2)
        assert np.array_equal(ct1, ct2)


# ---------------------------------------------------------------------
class TestValidateSuperbatchConfig:
    def test_powers_of_two_and_ladder(self):
        assert validate_superbatch_config(1) == (1, (1,))
        assert validate_superbatch_config(8) == (8, (1, 2, 4, 8))
        assert validate_superbatch_config("4") == (4, (1, 2, 4))

    def test_rejects_non_power_of_two(self):
        for bad in (0, -1, 3, 6, 12):
            with pytest.raises(ValueError):
                validate_superbatch_config(bad)

    def test_daemon_construction_validates(self):
        with pytest.raises(ValueError):
            Daemon(DaemonConfig(backend="interpreter",
                                serving_superbatch_k=3))


# ---------------------------------------------------------------------
class TestAssembleSuper:
    def _queue(self, db_id, rows_n, cap=4096):
        q = IngressQueue(cap)
        q.offer(_traffic(db_id, rows_n, 25000))
        return q

    def test_rounds_down_to_power_of_two_full_steps(self):
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        b = AdaptiveBatcher((64,), 500.0)
        q = self._queue(db_id, 64 * 7)  # 7 ready buckets
        sb = b.assemble_super(q, k_max=8)
        assert isinstance(sb, SuperBatch)
        assert sb.k == 4 and sb.bucket == 64  # 7 -> 4, all full
        assert sb.hdr.shape == (4, 64, N_COLS)
        assert sb.valid.all()
        assert q.pending == 64 * 3  # remainder stays queued

    def test_k_max_caps_the_superbatch(self):
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        b = AdaptiveBatcher((64,), 500.0)
        q = self._queue(db_id, 64 * 16)
        sb = b.assemble_super(q, k_max=4)
        assert sb.k == 4

    def test_single_bucket_falls_back_to_assemble(self):
        """Below two full buckets the single-batch path runs —
        byte-identical low-load behavior (partial buckets keep their
        own deadline semantics)."""
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        b = AdaptiveBatcher((64,), 500.0)
        q = self._queue(db_id, 80)  # one full bucket + change
        got = b.assemble_super(q, k_max=8, force=True)
        assert isinstance(got, AssembledBatch)
        assert got.n_valid == 64

    def test_k_max_one_is_the_legacy_path(self):
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        b = AdaptiveBatcher((64,), 500.0)
        q = self._queue(db_id, 64 * 8)
        got = b.assemble_super(q, k_max=1)
        assert isinstance(got, AssembledBatch)

    def test_packed_superbatch_carries_per_step_streams(self):
        """Steps need not share one (ep, dir) stream — each step's
        metadata rides eps/dirns; a single ineligible step demotes
        the WHOLE superbatch to wide."""
        d, db = _daemon()
        db_id = db.id
        d.shutdown()
        b = AdaptiveBatcher((64,), 500.0, pack=True)
        q = IngressQueue(4096)
        q.offer(_traffic(db_id, 64, 26000))
        q.offer(_traffic(9, 64, 27000))  # different ep stream
        sb = b.assemble_super(q, k_max=2)
        assert isinstance(sb, SuperBatch) and sb.packed
        assert sb.hdr.shape == (2, 64, 4)
        assert int(sb.eps[0]) == db_id and int(sb.eps[1]) == 9
        # now an IPv6 (ineligible) second bucket -> wide superbatch
        q.offer(_traffic(db_id, 64, 28000))
        v6 = make_batch([
            dict(src="fd00::1", dst="fd00::2", sport=29000 + i,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db_id,
                 dir=0) for i in range(64)]).data
        q.offer(v6)
        sb = b.assemble_super(q, k_max=2)
        assert isinstance(sb, SuperBatch) and not sb.packed
        assert sb.hdr.shape == (2, 64, N_COLS)

    def test_arena_steps_slots_recycle_independently(self):
        from cilium_tpu.serving import BucketArena

        a = BucketArena(depth=2)
        s1 = a.slot(64, 4, steps=4)
        s2 = a.slot(64, 4)  # single-batch pool: distinct key
        assert s1.shape == (4, 64, 4) and s2.shape == (64, 4)
        assert not np.shares_memory(s1, s2)
        s3 = a.slot(64, 4, steps=4)
        s4 = a.slot(64, 4, steps=4)  # depth-2 round robin
        assert not np.shares_memory(s3, s1)
        assert np.shares_memory(s4, s1)


# ---------------------------------------------------------------------
class TestLadderK:
    def test_default_k_ladder_is_byte_identical(self):
        lad = FallbackLadder([RUNG_SINGLE, RUNG_WIDE])
        assert lad.k == 1 and not lad.degraded
        assert lad.demote() == RUNG_WIDE  # straight to mode demote
        assert lad.at_floor

    def test_k_shrinks_before_mode_changes(self):
        lad = FallbackLadder([RUNG_SINGLE, RUNG_WIDE],
                             k_ladder=(1, 4, 8))
        walk = []
        while not lad.at_floor:
            lad.demote()
            walk.append((lad.rung, lad.k))
        assert walk == [(RUNG_SINGLE, 4), (RUNG_SINGLE, 1),
                        (RUNG_WIDE, 8), (RUNG_WIDE, 4),
                        (RUNG_WIDE, 1)]
        # promotion is the exact inverse
        back = []
        for _ in range(len(walk)):
            lad.promote()
            back.append((lad.rung, lad.k))
        assert back == [(RUNG_WIDE, 4), (RUNG_WIDE, 8),
                        (RUNG_SINGLE, 1), (RUNG_SINGLE, 4),
                        (RUNG_SINGLE, 8)]
        assert not lad.degraded

    def test_sharded_rung_pins_k1(self):
        lad = FallbackLadder([RUNG_SHARDED, RUNG_SINGLE, RUNG_WIDE],
                             k_ladder=(1, 8))
        assert lad.rung == RUNG_SHARDED and lad.k == 1
        assert not lad.degraded  # K=1 IS sharded's best K
        lad.demote()
        assert (lad.rung, lad.k) == (RUNG_SINGLE, 8)

    def test_k_shrink_counts_as_degraded_for_promotion(self):
        lad = FallbackLadder([RUNG_WIDE], k_ladder=(1, 2),
                             promote_after=1, cooldown_s=0.0)
        lad.demote()
        assert lad.degraded and (lad.rung, lad.k) == (RUNG_WIDE, 1)
        assert lad.record_success()
        lad.promote()
        assert (lad.rung, lad.k) == (RUNG_WIDE, 2)
        assert not lad.degraded

    def test_to_dict_carries_k(self):
        lad = FallbackLadder([RUNG_WIDE], k_ladder=(1, 4))
        dd = lad.to_dict()
        assert dd["k"] == 4 and dd["k-ladder"] == [1, 4]


# ---------------------------------------------------------------------
class TestSuperbatchServing:
    """The ingress drain loop end to end at K>1."""

    def _overload(self, d, db, superbatch_k=8, span_sample=None,
                  n_batches=48):
        # pre-generate and submit the WHOLE leg as one doorbell: the
        # queue then provably holds >= K full buckets when the drain
        # loop wakes, so superbatch assembly engages deterministically
        # (row-dict traffic generation is slower than the drain loop,
        # and a trickle would keep falling back to K=1)
        doorbell = _traffic(db.id, n_batches * 64, 30000,
                            flags=TCP_ACK)
        got = []
        d.monitor.register("superbatch", got.append)
        d.start_serving(ring_capacity=1 << 12, drain_every=2,
                        trace_sample=1, packed=True, ingress=True,
                        superbatch_k=superbatch_k,
                        span_sample=span_sample)
        assert d.submit(doorbell) == len(doorbell)
        # let the DRAIN THREAD consume everything before stopping:
        # stop_serving's final sweep dispatches on the caller thread
        # through the K=1 path, which would mask the superbatch leg
        rt = d._serving["runtime"]
        st = rt.stats
        assert _wait(lambda: (st.verdicts + st.shed
                              + st.recovery_dropped)
                     >= len(doorbell), timeout=60)
        stats = d.stop_serving()
        return stats["front-end"], got, stats

    def test_ledger_exact_and_amortized(self):
        d, db = _daemon(serving_queue_depth=1 << 14)
        fe, got, stats = self._overload(d, db)
        ft = _assert_ledger(fe)
        assert ft["restarts"] == 0
        dp = fe["dispatch"]
        assert dp["superbatches"] > 0
        assert dp["batches-per-dispatch"] > 1
        assert dp["superbatch-fill"] == 1.0  # no empty steps ever
        # every admitted row's event is either decoded+delivered or a
        # COUNTED event-plane loss (window drop / ring lap) — the
        # monitor-plane ledger at superbatch granularity
        ev = stats["event-plane"]
        assert (ev["events-joined"] + ev["events-dropped"]
                + ev["ring-lost"]) == fe["verdicts"]
        n_ev = sum(len(b) for b in got)
        assert n_ev == ev["events-joined"] > 0
        assert d.loader.compile_log.summary()["violations"] == 0
        d.shutdown()

    def test_spans_complete_through_superbatch(self):
        """Sampled spans ride superbatch steps: per-step batch ids,
        the event plane's true-join stamping, ledger exact."""
        d, db = _daemon(serving_queue_depth=1 << 14)
        fe, _got, _stats = self._overload(d, db, span_sample=16)
        _assert_ledger(fe)
        assert fe["dispatch"]["superbatches"] > 0
        tr = fe["trace"]
        assert tr["started"] > 0
        assert tr["started"] == tr["completed"] + tr["dropped"]
        assert tr["completed"] > 0
        d.shutdown()

    def test_superbatch_fault_shrinks_k_before_mode(self):
        """A failing superbatch dispatch walks the K ladder: after
        demote_threshold consecutive faults the session shrinks K
        (mode unchanged), the triggering batches retry one-by-one,
        and the ledger stays exact."""
        d, db = _daemon(serving_queue_depth=1 << 14,
                        serving_demote_threshold=2,
                        fault_spec="loader.serve_super=1x2")
        fe, _got, _stats = self._overload(d, db)
        _assert_ledger(fe)
        # stop_serving cleared _serving; the incident history holds
        # the k-demotion record
        inc = [i for i in d.flightrec.incidents()
               if i["kind"] == "ladder-demotion"]
        assert inc, "K-shrink demotion must record an incident"
        det = inc[0]["detail"]
        assert det["from"] == "single@k8"
        assert det["to"] == "single@k4"
        assert fe["fault-tolerance"]["restarts"] == 0
        d.shutdown()

    def test_lost_superbatch_accounts_all_k_batches(self):
        """A drain-thread death with a SUPERBATCH in flight accounts
        all K batches' rows as recovery drops — the no-silent-loss
        ledger at superbatch granularity."""
        d, db = _daemon(serving_queue_depth=1 << 14,
                        fault_spec="serving.dispatch=1x1@4")
        fe, _got, _stats = self._overload(d, db)
        ft = _assert_ledger(fe)
        assert ft["restarts"] >= 1
        assert ft["recovery-dropped"] > 0
        d.shutdown()

    def test_sharded_session_rejects_direct_superbatch(self):
        """The sharded session's ring is per-chip and its state
        mesh-placed: a direct serve_superbatch call must bounce with
        a clear error (mirroring serve_batch's packed-under-mesh
        rejection), not feed them to the single-chip executable."""
        from cilium_tpu.parallel import make_mesh

        d, db = _daemon(serving_bucket_ladder=(64,))
        d.start_serving(trace_sample=0, mesh=make_mesh(8))
        hdr = np.stack([_traffic(db.id, 64, 45000)] * 2)
        sb = SuperBatch(hdr=hdr, valid=np.ones((2, 64), dtype=bool),
                        bucket=64, arrivals=[])
        with pytest.raises(ValueError, match="single-chip"):
            d.serve_superbatch(sb)
        d.stop_serving()
        d.shutdown()

    def test_low_load_falls_back_to_single_batches(self):
        """One bucket at a time: the K=1 fallback — zero
        superbatches, behavior identical to a pre-superbatch
        session."""
        d, db = _daemon(serving_queue_depth=1 << 14)
        d.start_serving(ring_capacity=1 << 12, trace_sample=1,
                        packed=True, ingress=True, superbatch_k=8)
        rt = d._serving["runtime"]
        for i in range(6):
            d.submit(_traffic(db.id, 64, 40000 + 64 * i,
                              flags=TCP_ACK))
            assert _wait(lambda: rt.queue.pending == 0)
        fe = d.stop_serving()["front-end"]
        _assert_ledger(fe)
        assert fe["dispatch"]["superbatches"] == 0
        assert fe["dispatch"]["dispatches"] == fe["batches"]
        d.shutdown()


# ---------------------------------------------------------------------
class TestRecompileGuardSuperbatch:
    """The one-executable invariant extended to (rung, mode, K): each
    K is exactly one executable per bucket rung — a K-ladder retrace
    (the P(axis) trap's cousin) fails here loudly."""

    def test_one_executable_per_rung_mode_and_k(self):
        from cilium_tpu.monitor.ring import (
            serve_superbatch_jit, serve_superbatch_packed_jit)

        d, db = _daemon()
        drainer = AsyncRingDrainer(1 << 12, gather=False)
        K_LADDER = (2, 4)
        # bucket 128 keeps this test's shapes DISTINCT from every
        # other suite in the process (the jit caches are global, so a
        # shared (K, 64, cols) shape would already be compiled and
        # the growth assertions would read zero)
        B = 128
        before_p = serve_superbatch_packed_jit._cache_size()
        before_w = serve_superbatch_jit._cache_size()

        def sweep():
            for K in K_LADDER:
                hdrs = np.stack([_traffic(db.id, B, 50000 + B * k)
                                 for k in range(K)])
                valid = np.ones((K, B), dtype=bool)
                metas = [pack_eligibility(h) for h in hdrs]
                ring = drainer.fresh()
                d.loader.serve_superbatch(
                    ring, np.stack([pack_rows(h) for h in hdrs]),
                    100, 0,
                    eps=np.asarray([m[1] for m in metas]),
                    dirns=np.asarray([m[2] for m in metas]),
                    trace_sample=1, valid=valid, packed=True)
                ring = drainer.fresh()
                d.loader.serve_superbatch(ring, hdrs, 100, 0,
                                          trace_sample=1,
                                          valid=valid)

        sweep()
        grew_p = serve_superbatch_packed_jit._cache_size() - before_p
        grew_w = serve_superbatch_jit._cache_size() - before_w
        assert grew_p == len(K_LADDER), \
            f"{grew_p} packed executables for {len(K_LADDER)} Ks"
        assert grew_w == len(K_LADDER)
        sweep()  # the second sweep must retrace NOTHING
        assert (serve_superbatch_packed_jit._cache_size()
                - before_p) == len(K_LADDER), \
            "re-sweep retraced the packed superbatch step"
        assert (serve_superbatch_jit._cache_size()
                - before_w) == len(K_LADDER)
        # the runtime guard saw each (mode, shape-with-K) once
        comp = d.loader.compile_log.summary()
        assert comp["violations"] == 0
        keys = [(e["mode"], tuple(e["shape"]))
                for e in d.loader.compile_log.snapshot(
                    limit=0)["by-key"]]
        supers = [ks for ks in keys if ks[0].startswith("super-")]
        assert len(supers) == 2 * len(K_LADDER)
        assert len(set(supers)) == len(supers)
        d.shutdown()

    def test_duplicate_k_key_counts_a_violation(self):
        """The guard itself: a second compile for an already-seen
        (mode, shape-with-K) key is a loud violation."""
        from cilium_tpu.obs.compile_log import CompileLog

        log = CompileLog()
        log.record_dispatch("super-packed", (4, 64, 4), 0, 1, 0.01,
                            key_extra=(4096, 1, False, False))
        assert log.summary()["violations"] == 0
        log.record_dispatch("super-packed", (4, 64, 4), 1, 2, 0.01,
                            key_extra=(4096, 1, False, False))
        assert log.summary()["violations"] == 1
