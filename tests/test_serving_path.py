"""The serving-path monitor plane: fused datapath + device event ring
-> async drain -> header join -> MonitorAgent (upstream's perf-ring ->
monitor-agent -> hubble chain, with only compacted events crossing the
device->host link).
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.monitor.api import MSG_DROP, MSG_POLICY_VERDICT, MSG_TRACE

RULES = [{
    "labels": [{"key": "db-policy"}],
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]


def _world():
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _traffic(db_id, base_sport, n=64):
    # half allowed NEW flows, half scan-drops
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1",
             sport=base_sport + i,
             dport=5432 if i % 2 == 0 else 9999,
             proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)
    ]).data


class TestServingPath:
    def test_ring_events_reach_the_monitor(self):
        d, db = _world()
        got = []
        d.monitor.register("test", got.append)
        d.start_serving(ring_capacity=1 << 10, drain_every=2,
                        trace_sample=0)
        for i in range(6):
            d.serve_batch(_traffic(db.id, 20000 + 100 * i), now=10 + i)
        stats = d.stop_serving()

        assert stats["lost"] == 0
        assert stats["windows"] >= 3
        msg = np.concatenate([b.msg_type for b in got])
        verdicts = np.concatenate([b.verdict for b in got])
        # every batch: 32 allowed NEW (PolicyVerdict) + 32 drops
        assert int((msg == MSG_POLICY_VERDICT).sum()) == 6 * 32
        assert int((msg == MSG_DROP).sum()) == 6 * 32
        # trace_sample=0: established traffic stays on device
        assert int((msg == MSG_TRACE).sum()) == 0
        # OUT_VERDICT carries the datapath's forwarding decision:
        # 0 = dropped, 1 = forwarded (3 = redirect)
        assert set(verdicts[msg == MSG_DROP]) == {0}
        assert set(verdicts[msg == MSG_POLICY_VERDICT]) == {1}

    def test_serving_events_match_process_batch_events(self):
        """The serving path's compacted stream == the debug path's
        non-trace events (same traffic, fresh daemons)."""
        d1, db1 = _world()
        d2, db2 = _world()

        def key_set(batches):
            out = set()
            for b in batches:
                for i in range(len(b)):
                    out.add((int(b.msg_type[i]), int(b.verdict[i]),
                             int(b.identity[i]),
                             int(b.hdr[i, 8])))  # COL_SPORT
            return out

        got1 = []
        d1.monitor.register("t", got1.append)
        d1.start_serving(ring_capacity=1 << 10, drain_every=2,
                         trace_sample=0)
        for i in range(4):
            d1.serve_batch(_traffic(db1.id, 30000 + 100 * i),
                           now=10 + i)
        d1.stop_serving()

        got2 = []
        d2.monitor.register("t", got2.append)
        for i in range(4):
            d2.process_batch(_traffic(db2.id, 30000 + 100 * i),
                             now=10 + i)

        assert key_set(got1) == {
            (int(b.msg_type[i]), int(b.verdict[i]), int(b.identity[i]),
             int(b.hdr[i, 8]))
            for b in got2 for i in range(len(b))
            if b.msg_type[i] != MSG_TRACE}

    def test_redirect_events_restore_proxy_port(self):
        """L7 redirects stream their proxy port through the 4-bit
        listener-table index."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                "toPorts": [{
                    "ports": [{"port": "80", "protocol": "TCP"}],
                    "rules": {"http": [{"method": "GET"}]},
                }],
            }],
        }])
        assert d.proxy.ports, "expected an L7 redirect listener"
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(drain_every=1, trace_sample=0)
        d.serve_batch(make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=41000,
                 dport=80, proto=6, flags=TCP_SYN, ep=db.id, dir=0)
        ]).data, now=5)
        d.stop_serving()
        ports = {int(p) for b in got for p in b.proxy_port}
        assert ports & set(d.proxy.ports), \
            f"proxy port lost on the ring wire: {ports}"

    def test_interpreter_backend_refuses_serving(self):
        d = Daemon(DaemonConfig(backend="interpreter"))
        with pytest.raises(RuntimeError, match="tpu"):
            d.start_serving()


class TestServingAcrossRegeneration:
    def test_identity_churn_mid_serving_window(self):
        """Identity churn between serving batches: events of a
        post-churn batch must decode identities minted BY that churn.
        The row-map object is reused and mutated across
        regenerations, so the serving path's numerics snapshot must
        key on the map's version — object identity alone would serve
        the stale pre-churn table forever (r05 regression)."""
        d, db = _world()
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(ring_capacity=1 << 10, drain_every=2,
                        trace_sample=0)
        d.serve_batch(_traffic(db.id, 40000), now=10)
        # churn: a brand-new identity appears and its policy allows
        # it to reach db (regeneration mutates the SAME row map)
        d.add_endpoint("cache", ("10.0.3.1",), ["k8s:app=cache"])
        d.policy_import([{
            "labels": [{"key": "cache-policy"}],
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "cache"}}],
                "toPorts": [{"ports": [
                    {"port": "5432", "protocol": "TCP"}]}],
            }],
        }])
        # post-churn traffic FROM the new identity
        d.serve_batch(make_batch([
            dict(src="10.0.3.1", dst="10.0.2.1", sport=41000 + k,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0)
            for k in range(8)
        ]).data, now=11)
        d.serve_batch(_traffic(db.id, 40200), now=12)
        stats = d.stop_serving()
        assert stats["lost"] == 0
        cache_id = d.endpoints.lookup_by_ip(
            "10.0.3.1").identity.numeric_id
        web_id = d.endpoints.lookup_by_ip(
            "10.0.1.1").identity.numeric_id
        ids = np.concatenate([b.identity for b in got])
        assert len(ids) == 2 * 64 + 8
        # the new identity decodes as ITSELF, not as 0/unknown (a
        # stale numerics snapshot maps its fresh row to 0)
        assert (ids == cache_id).sum() == 8
        assert (ids == web_id).sum() == 2 * 64
