"""CiliumEndpointSlice batching (VERDICT r04 missing #6): the
operator coalesces CiliumEndpoints into <=100-endpoint slices
(FCFS, per-namespace), a burst of endpoint churn costs one write per
touched slice, and the agent-side slice watcher converges on the same
ipcache state as the direct-CEP path.
"""

import time

import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.k8s.informer import CES_RESOURCES, DEFAULT_RESOURCES, \
    K8sClient
from cilium_tpu.kvstore import InMemoryKVStore
from cilium_tpu.operator.ces import CESBatcher, expand_slice
from cilium_tpu.testing.stub_apiserver import StubAPIServer


def _cep(name, ip, ident, ns="default"):
    return {"apiVersion": "cilium.io/v2", "kind": "CiliumEndpoint",
            "metadata": {"name": name, "namespace": ns},
            "status": {"identity": {"id": ident},
                       "networking": {"addressing": [{"ipv4": ip}]}}}


class _Log:
    """publish sink recording (event, slice-name, size)."""

    def __init__(self):
        self.events = []
        self.store = {}

    def __call__(self, event, obj):
        name = obj["metadata"]["name"]
        self.events.append((event, name, len(obj.get("endpoints") or ())))
        if event == "delete":
            self.store.pop(name, None)
        else:
            self.store[name] = obj


class TestGrouping:
    def test_fcfs_fill_250_ceps_three_slices(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=100)
        for i in range(250):
            b.on_add(_cep(f"pod-{i}", f"10.0.{i // 200}.{i % 200}",
                          1000 + i))
        sizes = sorted(b.slice_sizes().values())
        assert sizes == [50, 100, 100]
        total = sum(len(o["endpoints"]) for o in log.store.values())
        assert total == 250

    def test_namespaces_never_share_a_slice(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=100)
        for i in range(5):
            b.on_add(_cep(f"a-{i}", f"10.0.0.{i}", 1000 + i, ns="team-a"))
            b.on_add(_cep(f"b-{i}", f"10.0.1.{i}", 2000 + i, ns="team-b"))
        assert b.slice_count() == 2
        for obj in log.store.values():
            ns = obj["namespace"]
            assert all(c["name"].startswith("a-" if ns == "team-a"
                                            else "b-")
                       for c in obj["endpoints"])

    def test_deletion_holes_refill_fcfs(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=4)
        ceps = [_cep(f"pod-{i}", f"10.0.0.{i}", 1000 + i)
                for i in range(8)]
        for c in ceps:
            b.on_add(c)
        assert b.slice_count() == 2
        # punch two holes in the first slice
        b.on_delete(ceps[0])
        b.on_delete(ceps[1])
        # new endpoints fill the non-full slice, not a third one
        b.on_add(_cep("pod-8", "10.0.0.8", 1008))
        b.on_add(_cep("pod-9", "10.0.0.9", 1009))
        assert b.slice_count() == 2
        assert sorted(b.slice_sizes().values()) == [4, 4]

    def test_empty_slice_is_deleted(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=2)
        ceps = [_cep(f"pod-{i}", f"10.0.0.{i}", 1000 + i)
                for i in range(2)]
        for c in ceps:
            b.on_add(c)
        for c in ceps:
            b.on_delete(c)
        assert b.slice_count() == 0
        assert log.events[-1][0] == "delete"
        assert log.store == {}

    def test_noop_resync_does_not_write(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=100)
        b.on_add(_cep("pod-0", "10.0.0.1", 1000))
        writes = b.slice_writes
        b.on_update(_cep("pod-0", "10.0.0.1", 1000))  # identical
        assert b.slice_writes == writes


class TestCoalescing:
    def test_burst_costs_one_write_per_touched_slice(self):
        log = _Log()
        # long window: nothing publishes until flush, like a burst
        # landing inside one sync interval
        b = CESBatcher(log, max_per_slice=100, sync_interval=30.0)
        try:
            for i in range(150):
                b.on_add(_cep(f"pod-{i}", f"10.0.0.{i % 200}", 1000 + i))
            assert b.slice_writes == 0
            b.flush()
            # 150 endpoint events -> exactly 2 slice writes
            assert b.cep_events == 150
            assert b.slice_writes == 2
        finally:
            b.close()

    def test_background_sync_publishes_without_flush(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=100, sync_interval=0.05)
        try:
            for i in range(20):
                b.on_add(_cep(f"pod-{i}", f"10.0.0.{i}", 1000 + i))
            deadline = time.time() + 5.0
            while time.time() < deadline and not log.store:
                time.sleep(0.02)
            assert log.store, "background sync never published"
            assert sum(len(o["endpoints"]) for o in log.store.values()) \
                == 20
            # 20 events collapsed into a handful of writes, not 20
            assert b.slice_writes <= 3
        finally:
            b.close()


class TestExpand:
    def test_expand_round_trips_core_fields(self):
        log = _Log()
        b = CESBatcher(log, max_per_slice=100)
        b.on_add(_cep("pod-0", "10.0.0.1", 4321, ns="prod"))
        (ces,) = log.store.values()
        (cep,) = expand_slice(ces)
        assert cep["metadata"] == {"name": "pod-0", "namespace": "prod"}
        assert cep["status"]["identity"]["id"] == 4321
        assert cep["status"]["networking"]["addressing"] == \
            [{"ipv4": "10.0.0.1"}]


def _ident(d, ip):
    e = d.ipcache.get(ip + "/32")
    return e.identity if e else None


def _wait(cond, timeout=30.0, msg=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


class TestSliceMigration:
    """The operator's FCFS refill can move an endpoint between slices
    within one sync window; whichever slice update the agent sees
    second must not tear down the entry the other slice carries."""

    @staticmethod
    def _ces(name, eps):
        return {"kind": "CiliumEndpointSlice",
                "metadata": {"name": name}, "namespace": "default",
                "endpoints": eps}

    @staticmethod
    def _core(name, ip, iid):
        return {"name": name, "id": iid,
                "networking": {"addressing": [{"ipv4": ip}]}}

    def test_move_applied_new_slice_first(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12),
                   kvstore=InMemoryKVStore())
        hub = d.k8s_watchers()
        hub.dispatch("add", self._ces(
            "ces-2", [self._core("pod-x", "10.9.0.7", 5007)]))
        assert _ident(d, "10.9.0.7") == 5007
        # migration lands: the RECEIVING slice's update first
        hub.dispatch("update", self._ces(
            "ces-1", [self._core("pod-x", "10.9.0.7", 5007)]))
        hub.dispatch("update", self._ces("ces-2", []))
        assert _ident(d, "10.9.0.7") == 5007, \
            "losing slice's shrink clobbered the migrated entry"
        # a slice DELETE must not clobber either
        hub.dispatch("delete", self._ces("ces-2", []))
        assert _ident(d, "10.9.0.7") == 5007
        # and deleting the owning slice withdraws it
        hub.dispatch("delete", self._ces(
            "ces-1", [self._core("pod-x", "10.9.0.7", 5007)]))
        assert _ident(d, "10.9.0.7") is None


class TestAgentConsumesSlices:
    """Operator publishes slices to the apiserver; a remote agent's
    informer ingests them and lands pod-IP -> identity in its ipcache
    exactly as the direct-CEP path would."""

    @pytest.fixture()
    def world(self):
        stub = StubAPIServer()
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                node_name="node-1"),
                   kvstore=InMemoryKVStore())
        # CES mode: slices REPLACE the per-pod CiliumEndpoint watch
        client = K8sClient(stub.url, d.k8s_watchers(),
                           resources=CES_RESOURCES)
        yield stub, d, client
        client.stop()
        stub.close()

    def test_ces_mode_swaps_the_cep_watch(self):
        kinds = [k for k, _ in CES_RESOURCES]
        assert "CiliumEndpointSlice" in kinds
        assert "CiliumEndpoint" not in kinds
        # default mode is unchanged: per-pod CEPs, no slices
        default_kinds = [k for k, _ in DEFAULT_RESOURCES]
        assert "CiliumEndpoint" in default_kinds
        assert "CiliumEndpointSlice" not in default_kinds

    def test_slice_lands_in_ipcache_and_shrinks(self, world):
        stub, d, client = world
        batcher = CESBatcher.publish_to(stub, max_per_slice=100)
        for i in range(10):
            # remote pods (no local endpoint owns these IPs)
            batcher.on_add(_cep(f"pod-{i}", f"10.9.0.{i}", 5000 + i))
        client.start()
        _wait(lambda: _ident(d, "10.9.0.9") == 5009,
              msg="slice -> ipcache")
        assert _ident(d, "10.9.0.0") == 5000

        # CEP churn: identity change propagates through a slice UPDATE
        batcher.on_update(_cep("pod-0", "10.9.0.0", 7777))
        _wait(lambda: _ident(d, "10.9.0.0") == 7777,
              msg="slice update -> ipcache")

        # endpoint leaves the slice -> its IP is withdrawn
        batcher.on_delete(_cep("pod-1", "10.9.0.1", 5001))
        _wait(lambda: _ident(d, "10.9.0.1") != 5001,
              msg="slice shrink -> ipcache delete")
        # the others stay
        assert _ident(d, "10.9.0.5") == 5005


class TestOperatorInformerCircle:
    def test_cep_to_slice_to_agent_full_circle(self):
        """The production CES topology end to end over real HTTP:
        agents (or tests) publish CiliumEndpoints to the apiserver;
        the OPERATOR's informer watches them and coalesces slices
        back into the apiserver; a remote agent in CES mode consumes
        the slices into its ipcache."""
        from cilium_tpu.k8s.informer import OPERATOR_CES_RESOURCES

        stub = StubAPIServer()
        # operator side: its informer drives the batcher directly
        # (CESBatcher speaks the hub dispatch protocol)
        batcher = CESBatcher.publish_to(stub, max_per_slice=4)
        op_client = K8sClient(stub.url, batcher,
                              resources=OPERATOR_CES_RESOURCES)
        # agent side: CES mode, slices only
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                node_name="node-1"),
                   kvstore=InMemoryKVStore())
        ag_client = K8sClient(stub.url, d.k8s_watchers(),
                              resources=CES_RESOURCES)
        try:
            op_client.start()
            ag_client.start()
            for i in range(10):
                stub.add(_cep(f"pod-{i}", f"10.9.1.{i}", 6000 + i))
            batcher.flush()
            _wait(lambda: _ident(d, "10.9.1.9") == 6009,
                  msg="CEP -> operator slices -> agent ipcache")
            assert _ident(d, "10.9.1.0") == 6000
            # 10 CEPs at 4/slice -> 3 slices in the apiserver
            assert batcher.slice_count() == 3
            # churn round-trips the circle too
            stub.update(_cep("pod-0", "10.9.1.0", 7777))
            batcher.flush()
            _wait(lambda: _ident(d, "10.9.1.0") == 7777,
                  msg="CEP update -> slice update -> agent")
            stub.delete(_cep("pod-1", "10.9.1.1", 6001))
            batcher.flush()
            _wait(lambda: _ident(d, "10.9.1.1") is None,
                  msg="CEP delete -> slice shrink -> agent")
        finally:
            op_client.stop()
            ag_client.stop()
            batcher.close()
            stub.close()
