"""Learned-path tests: embedding init, training convergence, AUC,
mesh data-parallel step, and the advisory scorer wiring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.ml import (
    AnomalyScorer,
    auc,
    flow_features,
    forward,
    init_params,
    label_embedding_init,
    synth_labeled_traffic,
    train,
)
from cilium_tpu.monitor import decode_out
from cilium_tpu.testing.fixtures import build_world


@pytest.fixture(scope="module")
def trained():
    world = build_world(n_identities=64, n_rules=8, ct_capacity=1 << 14)
    labels_by_row = {
        world.row_map.row(i.numeric_id):
            tuple(str(l) for l in i.labels)
        for i in world.alloc.all_identities()}
    params = init_params(jax.random.PRNGKey(0), world.row_map.capacity,
                         labels_by_row=labels_by_row)
    params, losses = train(params, world, steps=60, batch=1024)
    return world, params, losses


def test_label_embedding_correlates():
    rows = {0: ("k8s:app=web", "k8s:ns=prod"),
            1: ("k8s:app=web", "k8s:ns=dev"),
            2: ("k8s:app=db", "k8s:zone=z9")}
    t = label_embedding_init(rows, 4, 64)
    sim01 = float(t[0] @ t[1])
    sim02 = float(t[0] @ t[2])
    assert sim01 > sim02  # shared app=web label -> closer rows
    assert np.allclose(np.linalg.norm(t[:3], axis=1), 1.0, atol=1e-5)


def test_training_converges(trained):
    world, params, losses = trained
    assert losses[-1] < losses[0] * 0.6, losses[::10]


def test_auc_on_heldout(trained):
    world, params, losses = trained
    from cilium_tpu.datapath import datapath_step_jit

    rng = np.random.default_rng(999)
    hdr, labels = synth_labeled_traffic(world, 4096, rng)
    out, world.state = datapath_step_jit(world.state, jnp.asarray(hdr),
                                         jnp.uint32(50_000))
    id_row, feats = flow_features(jnp.asarray(hdr), out)
    scores = np.asarray(forward(params, id_row, feats))
    a = auc(scores, labels)
    assert a > 0.9, f"anomaly AUC too low: {a}"


def test_auc_sanity():
    assert auc(np.array([0.9, 0.8, 0.2, 0.1]),
               np.array([1, 1, 0, 0])) == 1.0
    assert abs(auc(np.array([0.1, 0.9, 0.2, 0.8]),
                   np.array([1, 0, 0, 1])) - 0.5) < 0.51


def test_mesh_dp_train_step():
    """dp via shard_map: one step must run and return replicated
    params; loss ~ equals the unsharded step on the same data."""
    import optax

    from cilium_tpu.ml.train import make_train_step
    from cilium_tpu.parallel import make_mesh

    world = build_world(n_identities=16, n_rules=2, ct_capacity=1 << 12)
    params = init_params(jax.random.PRNGKey(1), world.row_map.capacity)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    rng = np.random.default_rng(3)
    hdr, labels = synth_labeled_traffic(world, 512, rng)
    from cilium_tpu.datapath import datapath_step_jit

    out, world.state = datapath_step_jit(world.state, jnp.asarray(hdr),
                                         jnp.uint32(10))
    id_row, feats = flow_features(jnp.asarray(hdr), out)
    labels_j = jnp.asarray(labels)

    single = make_train_step(opt)
    p1, _, loss1 = single(params, opt_state, id_row, feats, labels_j)

    mesh = make_mesh(8)
    sharded = make_train_step(opt, mesh)
    p8, _, loss8 = sharded(params, opt_state, id_row, feats, labels_j)
    assert abs(float(loss1) - float(loss8)) < 1e-2
    # parameters updated identically (grad pmean == full-batch grad)
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                     p1, p8)
    assert max(jax.tree.leaves(d)) < 1e-2


def test_scorer_advisory(trained):
    """Scores flow back via the monitor plane and never mutate
    verdicts."""
    world, params, losses = trained
    from cilium_tpu.datapath import datapath_step_jit

    rng = np.random.default_rng(77)
    hdr, labels = synth_labeled_traffic(world, 1024, rng)
    out, world.state = datapath_step_jit(world.state, jnp.asarray(hdr),
                                         jnp.uint32(60_000))
    batch = decode_out(np.asarray(out), hdr,
                       world.row_map.numeric_array(), timestamp=1.0)
    scorer = AnomalyScorer(params, world.row_map.row, threshold=0.5)
    scores = scorer.consume(batch)
    assert len(scores) == 1024
    a = auc(scores, labels)
    assert a > 0.85
    st = scorer.stats()
    assert st["scored"] == 1024 and st["flagged"] > 0
    assert len(st["top"]) > 0 and st["top"][0]["score"] >= 0.5
