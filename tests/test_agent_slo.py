"""SLO plane (ISSUE 19 tentpole): in-process metrics history,
multi-window burn-rate alerting, cluster-wide health verdicts.

Acceptance (split by cost):
(a) UNITS (no daemon): the ONE counter-reset definition
    (``obs.history.counters_reset``, shared with the CLI follow
    loop's resync); the two-tier fixed-memory ring with the
    reset-splice (adjusted series stay monotone, resyncs recorded);
    the burn-rate engine over fake timelines — no-data vs
    zero-traffic, the multi-window page premise (a one-tick spike
    cannot alert), one-episode-one-incident hysteresis, all three
    SLO kinds; the adaptive GC-relaxation state machine (never
    mid-episode, compounding, bounded, snaps on pressure entry).
(b) DAEMON integration: the chaos gate — a seeded admission-shed
    burst burns the availability SLO on a fake 10 s timeline:
    exactly one ``slo-burn`` incident per SLO episode, the
    auto-captured sysdump carries the ``slo`` + ``history``
    sections, hysteresis recovery is recorded, zero serving
    recompiles, the packet ledger exact.  Plus the sampler's thread
    identity (``slo-sampler``, never the drain thread) and the
    registry's ``cilium_slo_*`` exposition floor.
(c) THREAD-MODE cluster: per-node verdicts merge worst-of into one
    node-labeled cluster verdict; a crashed node serves last-known
    inside the staleness bound and degrades to no-data past it.
    (The process-mode SIGKILL leg rides test_cluster_obs's one
    process lifecycle — the file cost discipline.)

Named to sort early (the tier-1 budget-truncation convention).
"""

import json
import threading
import time

import pytest

from cilium_tpu.obs.history import (SeriesHistory, counters_reset,
                                    validate_history_config)
from cilium_tpu.obs.slo import (HISTORY_SERIES, STATE_CODES, SLODef,
                                SLOEngine, default_slos,
                                validate_slo_config)

pytestmark = [pytest.mark.obs]


def _wait(pred, timeout=60.0, tick=0.01):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


# ---------------------------------------------------------------------
# (a) units: the shared counter-reset definition
# ---------------------------------------------------------------------
class TestCounterResetDefinition:
    def test_backward_numeric_pair_signals_reset(self):
        assert counters_reset([(3, 10)])
        assert counters_reset([(5, 5), (0.0, 0.5)])

    def test_forward_equal_missing_and_non_numeric_do_not(self):
        assert not counters_reset([(10, 3)])
        assert not counters_reset([(5, 5)])
        assert not counters_reset([(None, 7), (7, None)])
        assert not counters_reset([("a", "b"), ({}, 1)])
        # bools are not counters (False < True is not a restart)
        assert not counters_reset([(False, True)])
        assert not counters_reset([])

    def test_cli_follow_resync_delegates_to_the_one_definition(self):
        # the CLI wrapper only plucks the serving rate keys; the
        # reset SEMANTICS must be obs.history's (a fork would let
        # the ring splice and the follow loop disagree on what a
        # restart looks like)
        from cilium_tpu.cli.main import _counters_reset

        prev = {"submitted": 100, "verdicts": 90,
                "dispatch": {"dispatches": 10},
                "fault-tolerance": {"restarts": 0}}
        cur_fwd = {"submitted": 150, "verdicts": 140,
                   "dispatch": {"dispatches": 15},
                   "fault-tolerance": {"restarts": 0}}
        cur_rst = {"submitted": 5, "verdicts": 4,
                   "dispatch": {"dispatches": 1},
                   "fault-tolerance": {"restarts": 0}}
        assert not _counters_reset(cur_fwd, prev)
        assert _counters_reset(cur_rst, prev)
        # a NESTED counter rewinding alone is enough
        cur_nested = dict(cur_fwd)
        cur_nested["dispatch"] = {"dispatches": 2}
        assert _counters_reset(cur_nested, prev)


# ---------------------------------------------------------------------
# (a) units: the two-tier history ring
# ---------------------------------------------------------------------
def _ring(state, kinds, **kw):
    return SeriesHistory(lambda: dict(state), kinds, **kw)


class TestSeriesHistory:
    def test_two_tiers_fixed_memory(self):
        state = {"c": 0}
        h = _ring(state, {"c": "counter"}, interval_s=10.0, slots=4,
                  slow_every=2, slow_slots=3)
        for i in range(20):
            state["c"] = i
            h.take_sample(now=float(i * 10), wall=1000.0 + i * 10)
        q = h.query()
        # both rings bounded no matter the uptime; total samples
        # keep counting
        assert len(q["fast"]) == 4 and len(q["slow"]) == 3
        assert q["samples"] == 20 and h.stats()["samples"] == 20
        # the slow tier extends the merged window past the fast span
        base, win = h._window(1000.0, now=190.0)
        assert len(win) > 4

    def test_counter_reset_splices_and_records_resync(self):
        state = {"c": 10}
        h = _ring(state, {"c": "counter"}, interval_s=10.0)
        h.take_sample(now=0.0, wall=0.0)
        state["c"] = 15
        h.take_sample(now=10.0, wall=10.0)
        state["c"] = 3  # the restart: raw counter rewound
        rec = h.take_sample(now=20.0, wall=20.0)
        assert rec["resync"] == ["c"]
        state["c"] = 7
        h.take_sample(now=30.0, wall=30.0)
        vals = [r["v"]["c"] for r in h.query()["fast"]]
        # adjusted series continues from where the dead process left
        # it: 10, 15, 15+3, 15+7 — monotone through the splice
        assert vals == [10.0, 15.0, 18.0, 22.0]
        assert h.query()["resyncs"] == 1
        # the windowed delta the SLO math consumes never goes
        # negative across the restart
        d = h.counter_delta("c", 100.0, now=30.0)
        assert d == 12.0

    def test_histogram_reset_splices_bucket_counts(self):
        state = {"h": {"buckets": [2, 3], "count": 5, "sum": 9.0}}
        h = _ring(state, {"h": "histogram"}, interval_s=10.0)
        h.take_sample(now=0.0, wall=0.0)
        # restart: cumulative bucket counts rewound
        state["h"] = {"buckets": [1, 0], "count": 1, "sum": 1.0}
        rec = h.take_sample(now=10.0, wall=10.0)
        assert rec["resync"] == ["h"]
        assert rec["v"]["h"] == {"buckets": [3, 3], "count": 6,
                                 "sum": 10.0}
        d = h.hist_delta("h", 100.0, now=10.0)
        assert d["count"] == 1 and all(b >= 0 for b in d["buckets"])

    def test_query_filters_series_and_since(self):
        state = {"a": 1, "b": 2}
        h = _ring(state, {"a": "gauge", "b": "gauge"},
                  interval_s=10.0)
        h.take_sample(now=0.0, wall=100.0)
        h.take_sample(now=10.0, wall=110.0)
        q = h.query(series=["b", "nope"], since=105.0)
        assert q["series"] == ["b"]  # the filter, minus unknowns
        assert len(q["fast"]) == 1
        assert q["fast"][0]["v"] == {"b": 2}

    def test_validate_history_config(self):
        assert validate_history_config(0, 360, 30, 288)[0] == 0.0
        with pytest.raises(ValueError, match="history_interval"):
            validate_history_config(-1, 360, 30, 288)
        with pytest.raises(ValueError, match="slots"):
            validate_history_config(10, 1, 30, 288)
        with pytest.raises(ValueError, match="slow_every"):
            validate_history_config(10, 360, 0, 288)


# ---------------------------------------------------------------------
# (a) units: the burn-rate engine on fake timelines
# ---------------------------------------------------------------------
def _ratio_engine(state, fired, objective=0.99, **kw):
    kinds = {"x_bad": "counter", "x_total": "counter"}
    h = SeriesHistory(lambda: dict(state), kinds, interval_s=10.0)
    eng = SLOEngine(
        h, [SLODef(name="avail", description="t", kind="ratio",
                   objective=objective, bad=("x_bad",),
                   total="x_total")],
        record_incident=lambda kind, detail: fired.append(
            (kind, detail)),
        interval_s=10.0, fast_window_s=60.0, slow_window_s=600.0,
        page_burn=10.0, warn_burn=2.0, clear_ticks=3, **kw)
    return h, eng


def _run_healthy(state, eng, ticks, t0=0.0, step=10.0, rate=100):
    t = t0
    for _ in range(ticks):
        state["x_total"] += rate
        eng.tick(now=t, wall=1e9 + t)
        t += step
    return t


class TestSLOEngine:
    def test_no_data_vs_zero_traffic(self):
        state = {"x_bad": 0, "x_total": 0}
        eng = _ratio_engine(state, [])[1]
        out = eng.tick(now=0.0, wall=1e9)
        # one record: no window has two datapoints yet
        assert out["evals"]["avail"]["state"] == "no-data"
        out = eng.tick(now=10.0, wall=1e9 + 10)
        ev = out["evals"]["avail"]
        # zero traffic is burn 0 (an idle plane consumes no
        # budget), DISTINCT from no-data
        assert ev["state"] == "ok"
        assert ev["budget-remaining"] == 1.0
        assert out["verdict"] == "ok"

    def test_one_tick_spike_cannot_alert(self):
        # the multi-window premise: a fast-window burn without slow
        # -window evidence is a blip, not an alert
        state = {"x_bad": 0, "x_total": 0}
        fired = []
        eng = _ratio_engine(state, fired)[1]
        t = _run_healthy(state, eng, 61)
        state["x_bad"] += 30  # one bad tick: fast burn ~5x
        state["x_total"] += 100
        out = eng.tick(now=t, wall=1e9 + t)
        ev = out["evals"]["avail"]
        assert ev["fast-burn"] >= 2.0
        assert ev["slow-burn"] < 2.0
        assert ev["state"] == "ok"
        assert fired == []

    def test_page_episode_one_incident_and_hysteresis(self):
        state = {"x_bad": 0, "x_total": 0}
        fired = []
        eng = _ratio_engine(state, fired)[1]
        t = _run_healthy(state, eng, 61)
        # sustained 100%-error burst: both windows cross page
        paged_at = None
        for _ in range(12):
            state["x_bad"] += 100
            state["x_total"] += 100
            out = eng.tick(now=t, wall=1e9 + t)
            t += 10.0
            if out["evals"]["avail"]["state"] == "page":
                paged_at = t
                break
        assert paged_at is not None
        assert out["verdict"] == "page"
        # one episode = ONE incident, however long the storm runs
        for _ in range(3):
            state["x_bad"] += 100
            state["x_total"] += 100
            eng.tick(now=t, wall=1e9 + t)
            t += 10.0
        assert [k for k, _ in fired] == ["slo-burn"]
        assert fired[0][1]["slo"] == "avail"
        assert "avail" in eng.snapshot()["active"]
        # recovery: healthy traffic until the burst slides out of
        # the slow window, then clear_ticks calm evaluations
        for _ in range(80):
            state["x_total"] += 100
            eng.tick(now=t, wall=1e9 + t)
            t += 10.0
        snap = eng.snapshot()
        assert snap["active"] == {}
        assert snap["verdict"] == "ok"
        eps = [e for e in snap["episodes"] if e["slo"] == "avail"]
        assert len(eps) == 1
        assert eps[0]["recovered-at"] > eps[0]["started-at"]
        assert eps[0]["peak-burn"] >= 10.0
        assert len(fired) == 1  # still: recovery fires nothing

    def test_calm_streak_rearms_inside_episode(self):
        # hysteresis: calm ticks below clear_ticks then a re-burn
        # keep the SAME episode open (and fire nothing new)
        state = {"x_bad": 0, "x_total": 0}
        fired = []
        eng = _ratio_engine(state, fired)[1]
        t = _run_healthy(state, eng, 61)
        for _ in range(8):
            state["x_bad"] += 100
            state["x_total"] += 100
            eng.tick(now=t, wall=1e9 + t)
            t += 10.0
        assert len(fired) == 1
        ep = eng.active["avail"]
        ep["calm"] = 2  # one tick short of clear_ticks
        state["x_bad"] += 100  # the storm returns
        state["x_total"] += 100
        eng.tick(now=t, wall=1e9 + t)
        assert eng.active["avail"]["calm"] == 0  # re-armed
        assert len(fired) == 1  # same episode, same incident

    def test_percentile_kind_tail_mass(self):
        # log2 buckets: bucket i holds [2^(i-1), 2^i) µs; threshold
        # 8 µs admits buckets 0..3
        state = {"lat": {"buckets": [0] * 8, "count": 0, "sum": 0.0}}
        h = SeriesHistory(lambda: {"lat": dict(
            state["lat"], buckets=list(state["lat"]["buckets"]))},
            {"lat": "histogram"}, interval_s=10.0)
        eng = SLOEngine(
            h, [SLODef(name="p99", description="t",
                       kind="percentile", objective=0.99,
                       series=("lat",), threshold=8)],
            interval_s=10.0, fast_window_s=60.0,
            slow_window_s=600.0, page_burn=10.0, warn_burn=2.0,
            clear_ticks=3)
        t = 0.0
        for _ in range(61):  # fast mass only: under the threshold
            state["lat"]["buckets"][2] += 100
            state["lat"]["count"] += 100
            eng.tick(now=t, wall=1e9 + t)
            t += 10.0
        assert eng.last["evals"]["p99"]["state"] == "ok"
        for _ in range(12):  # all mass over the threshold
            state["lat"]["buckets"][6] += 100
            state["lat"]["count"] += 100
            out = eng.tick(now=t, wall=1e9 + t)
            t += 10.0
            if out["evals"]["p99"]["state"] == "page":
                break
        assert out["evals"]["p99"]["state"] == "page"

    def test_gauge_kind_worst_series_per_sample(self):
        # one saturated map burns even while its sibling idles
        state = {"m1": 0.1, "m2": 0.1}
        h = SeriesHistory(lambda: dict(state),
                          {"m1": "gauge", "m2": "gauge"},
                          interval_s=10.0)
        eng = SLOEngine(
            h, [SLODef(name="head", description="t", kind="gauge",
                       objective=0.99, series=("m1", "m2"),
                       threshold=0.9)],
            interval_s=10.0, fast_window_s=60.0,
            slow_window_s=600.0, page_burn=10.0, warn_burn=2.0,
            clear_ticks=3)
        t = 0.0
        for _ in range(61):
            eng.tick(now=t, wall=1e9 + t)
            t += 10.0
        assert eng.last["evals"]["head"]["state"] == "ok"
        state["m2"] = 0.97  # sibling m1 stays cold
        for _ in range(70):
            out = eng.tick(now=t, wall=1e9 + t)
            t += 10.0
            if out["evals"]["head"]["state"] == "page":
                break
        assert out["evals"]["head"]["state"] == "page"

    def test_constructor_validates_the_contract(self):
        h = SeriesHistory(lambda: {}, {"a": "counter"})
        with pytest.raises(ValueError, match="outside the declared"):
            SLOEngine(h, [SLODef(name="s", description="t",
                                 kind="ratio", objective=0.9,
                                 bad=("missing",), total="a")])
        with pytest.raises(ValueError, match="unknown kind"):
            SLOEngine(h, [SLODef(name="s", description="t",
                                 kind="nope", objective=0.9,
                                 total="a")])
        with pytest.raises(ValueError, match="objective"):
            SLOEngine(h, [SLODef(name="s", description="t",
                                 kind="ratio", objective=1.5,
                                 total="a")])
        with pytest.raises(ValueError, match="twice"):
            SLOEngine(h, [SLODef(name="s", description="t",
                                 kind="ratio", objective=0.9,
                                 total="a"),
                          SLODef(name="s", description="t",
                                 kind="ratio", objective=0.9,
                                 total="a")])

    def test_validate_slo_config(self):
        with pytest.raises(ValueError, match="slow_window"):
            validate_slo_config(60, 60, 10, 2, 3, 0.05)
        with pytest.raises(ValueError, match="page_burn"):
            validate_slo_config(60, 600, 1, 2, 3, 0.05)
        with pytest.raises(ValueError, match="clear_ticks"):
            validate_slo_config(60, 600, 10, 2, 0, 0.05)
        with pytest.raises(ValueError, match="max_duty"):
            validate_slo_config(60, 600, 10, 2, 3, 1.0)

    def test_shipped_slos_construct_over_the_declared_subset(self):
        # the CTA014 contract, live: every shipped SLO's series is
        # inside HISTORY_SERIES, so the engine constructs
        kinds = {n: "counter" for n in HISTORY_SERIES}
        h = SeriesHistory(lambda: {}, kinds)
        eng = SLOEngine(h, default_slos())
        assert len(eng.slos) == 6
        assert STATE_CODES == {"ok": 0, "no-data": 1, "warn": 2,
                               "page": 3}


# ---------------------------------------------------------------------
# (a) units: adaptive GC relaxation (the pressure monitor's other
# half — tightens under pressure, relaxes back out when calm)
# ---------------------------------------------------------------------
class TestAdaptiveGcRelaxation:
    def _mon(self, state, relaxed, accel, restore):
        from cilium_tpu.datapath.pressure import MapPressureMonitor

        def sf():
            return {"ct": {"occupancy": state["occ"],
                           "insert-drops": state["drops"]},
                    "nat": {"failures": state["nat"]}}

        return MapPressureMonitor(
            sf, accel.append, lambda: restore.append(1),
            ct_threshold=0.85, ct_clear=0.70,
            gc_pressure_interval_s=1.0,
            relax_after_s=10.0, relax_factor=2.0, relax_max=4.0,
            on_relax=relaxed.append)

    def test_calm_streak_compounds_and_caps(self):
        state = {"occ": 0.1, "drops": 0, "nat": 0}
        relaxed, accel, restore = [], [], []
        mon = self._mon(state, relaxed, accel, restore)
        mon.sample(now=0.0)  # streak starts
        mon.sample(now=9.0)
        assert relaxed == []  # not a full relax_after_s yet
        mon.sample(now=10.0)
        assert relaxed == [2.0]
        mon.sample(now=20.0)
        assert relaxed == [2.0, 4.0]  # compounding
        mon.sample(now=40.0)
        assert relaxed == [2.0, 4.0]  # bounded by relax_max
        assert mon.stats()["relax"]["steps"] == 2
        assert mon.stats()["relax"]["multiplier"] == 4.0

    def test_pressure_entry_snaps_multiplier_never_mid_episode(self):
        state = {"occ": 0.1, "drops": 0, "nat": 0}
        relaxed, accel, restore = [], [], []
        mon = self._mon(state, relaxed, accel, restore)
        mon.sample(now=0.0)
        mon.sample(now=10.0)
        assert mon.relax_mult == 2.0
        state["drops"] = 5  # insert-drop delta: pressure episode
        mon.sample(now=20.0)
        assert mon.state == "pressure"
        assert accel == [1.0]  # accelerated cadence took over
        assert mon.relax_mult == 1.0  # snapped back
        # mid-episode: however much time passes, no relax step can
        # fire while the episode is open (the drops keep it hot)
        state["drops"] += 1
        mon.sample(now=200.0)
        state["drops"] += 1
        mon.sample(now=400.0)
        assert mon.state == "pressure"
        assert relaxed == [2.0]
        # episode exits; the streak starts OVER from the recovery
        mon.sample(now=500.0)
        assert mon.state == "ok" and restore == [1]
        mon.sample(now=509.0)
        assert relaxed == [2.0]  # 9 s post-recovery: not yet
        mon.sample(now=510.0)
        assert relaxed == [2.0, 2.0]

    def test_resync_applies_the_relaxed_cadence(self):
        state = {"occ": 0.1, "drops": 0, "nat": 0}
        relaxed, accel, restore = [], [], []
        mon = self._mon(state, relaxed, accel, restore)
        mon.sample(now=0.0)
        mon.sample(now=10.0)
        sched = []
        mon.resync(30.0, sched.append)
        assert sched == [60.0]  # normal interval x multiplier

    def test_validate_relax_config(self):
        from cilium_tpu.datapath.pressure import validate_relax_config

        assert validate_relax_config(0, 2.0, 4.0)[0] == 0.0
        with pytest.raises(ValueError, match="relax_after"):
            validate_relax_config(-1, 2.0, 4.0)
        with pytest.raises(ValueError, match="relax_factor"):
            validate_relax_config(10, 1.0, 4.0)
        with pytest.raises(ValueError, match="relax_max"):
            validate_relax_config(10, 2.0, 1.5)


# ---------------------------------------------------------------------
# (a) units: CLI rendering (stubbed client — the flows-CLI idiom)
# ---------------------------------------------------------------------
class TestSloCli:
    def _ns(self, **over):
        import argparse

        ns = dict(socket="unused", json=False, follow=False,
                  interval=1.0)
        ns.update(over)
        return argparse.Namespace(**ns)

    def test_cmd_slo_renders_verdict_table_and_episodes(
            self, capsys, monkeypatch):
        from cilium_tpu.cli import main as cli

        snap = {
            "enabled": True, "verdict": "page", "ticks": 42,
            "fast-window-s": 60.0, "slow-window-s": 600.0,
            "page-burn": 10.0, "warn-burn": 2.0, "clear-ticks": 3,
            "resyncs": 1,
            "slos": {"serving-availability": {
                "state": "page", "budget-remaining": 0.25,
                "fast-burn": 14.0, "slow-burn": 11.0}},
            "active": {"serving-availability": {
                "peak-burn": 14.0, "calm": 1,
                "started-at": 123.0}},
            "episodes": [{"slo": "dispatch-p99",
                          "duration-s": 30.0, "peak-burn": 12.0}],
        }

        class _Stub:
            def slo(self):
                return snap

        monkeypatch.setattr(cli, "_client", lambda args: _Stub())
        assert cli.cmd_slo(self._ns()) == 0
        out = capsys.readouterr().out
        assert "Verdict:   PAGE" in out
        assert "serving-availability" in out and "14.00x" in out
        assert "BURNING serving-availability" in out
        assert "recovered dispatch-p99" in out

    def test_cmd_history_renders_series_rows(self, capsys,
                                             monkeypatch):
        from cilium_tpu.cli import main as cli

        hist = {
            "interval-s": 10.0, "slow-every": 30, "samples": 3,
            "resyncs": 0, "series": ["a_total", "lat"],
            "fast": [
                {"at": 1.0, "v": {"a_total": 5,
                                  "lat": {"count": 2}}},
                {"at": 2.0, "v": {"a_total": 9,
                                  "lat": {"count": 4}}},
            ],
            "slow": [],
        }

        class _Stub:
            def metrics_history(self, series=None, since=0.0):
                return hist

        monkeypatch.setattr(cli, "_client", lambda args: _Stub())
        assert cli.cmd_history(self._ns(series=[], since=0.0,
                                        number=12)) == 0
        out = capsys.readouterr().out
        assert "a_total" in out and "5 9" in out
        # histograms render their cumulative event count
        assert "lat" in out and "2 4" in out

    def test_cmd_cluster_slo_renders_node_labels(self, capsys,
                                                 monkeypatch):
        from cilium_tpu.cli import main as cli

        merged = {
            "verdict": "no-data", "node-count": 2,
            "unreachable": ["node1"],
            "nodes": {
                "node0": {"ok": True, "stale": False,
                          "age-s": 0.1, "verdict": "ok",
                          "slos": {"serving-availability": "ok"}},
                "node1": {"ok": False, "stale": True, "age-s": 9.0,
                          "verdict": "no-data",
                          "error": "node dead"},
            },
        }

        class _Stub:
            def cluster_slo(self):
                return merged

        monkeypatch.setattr(cli, "_client", lambda args: _Stub())
        assert cli.cmd_cluster(self._ns(action="slo")) == 0
        out = capsys.readouterr().out
        assert "Cluster SLO: NO-DATA (2 nodes, 1 unreachable)" in out
        assert "node1" in out and "node dead" in out


class TestCta014LiveRepo:
    @pytest.mark.analysis
    def test_cta014_live_repo_clean(self):
        from cilium_tpu.analysis.driver import run_analysis

        result = run_analysis(checkers=["slo-contract"])
        assert [f.render() for f in result["findings"]] == []


# ---------------------------------------------------------------------
# (b) daemon integration
# ---------------------------------------------------------------------
from cilium_tpu.agent import Daemon, DaemonConfig  # noqa: E402
from cilium_tpu.core import TCP_SYN, make_batch  # noqa: E402

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]


def _daemon(**over):
    # the chaos-suite (64, 16) shapes: shared XLA executables
    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_restart_backoff_ms=1.0,
               sysdump_min_interval_s=0.0,
               history_interval=0.0)  # tests drive tick() directly
    cfg.update(over)
    d = Daemon(DaemonConfig(**cfg))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _fwd(db_id, n=64, base=20000):
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base + (i % 40000),
             dport=5432, proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _dispatch_compiles(daemon):
    return sum(e["compiles"]
               for e in daemon.loader.compile_log.snapshot(
                   limit=0)["by-key"]
               if e["mode"] != "gather")


@pytest.mark.chaos
class TestSloBurnChaosGate:
    def test_seeded_shed_burst_pages_once_with_sysdump(
            self, tmp_path):
        """The ISSUE 19 acceptance e2e: a REAL admission-shed burst
        (queue overflow, exact ledger) burns the availability SLO on
        a fake 10 s timeline -> exactly one slo-burn incident for the
        episode, whose auto-captured sysdump carries the slo +
        history sections; hysteresis closes the episode and records
        the recovery; zero serving recompiles; the packet ledger
        stays exact."""
        d, db = _daemon(sysdump_dir=str(tmp_path / "dumps"))
        # warm the occupancy executable BEFORE the compile-count
        # baseline (the Daemon.start idiom): the incident capture
        # reads map pressure on its own thread, and its first read
        # compiles
        d.pressure.sample()
        d.start_serving(trace_sample=0, ingress=True,
                        ring_capacity=1 << 13)
        try:
            step, t, w0 = 10.0, 0.0, 1.7e9
            # healthy baseline covering the slow window: 64-row
            # chunks can never overflow the 4096 queue undrained
            # before the drain catches up, so shed stays 0
            rt = d._serving["runtime"]
            n_base = int(d.config.slo_slow_window / step) + 1
            for i in range(n_base):
                d.submit(_fwd(db.id, base=20000 + 97 * i))
                d.slo.tick(now=t, wall=w0 + t)
                t += step
            ev = d.slo.last["evals"]["serving-availability"]
            assert ev["state"] == "ok", ev
            # every baseline row drained before the compile-count
            # baseline: the first dispatch's compile is async, and a
            # baseline taken mid-compile would blame the burst for it
            assert _wait(lambda: rt.stats.verdicts >= 64 * n_base,
                         timeout=120)
            c0 = _dispatch_compiles(d)

            # -- the seeded burst: overflow admission for real ------
            t_burst = t
            shed = 0
            for i in range(4000):
                got = d.submit(_fwd(db.id, base=30000 + 61 * i))
                shed += 64 - got
                if shed >= 2048:
                    break
            assert shed >= 2048, "burst never overflowed admission"
            # the exact shed ledger flushes on drain activity — wait
            # for the registry (what the sampler reads) to carry it
            assert _wait(lambda: d.registry.sample(
                ("cilium_serving_shed_total",)).get(
                    "cilium_serving_shed_total", 0) >= shed)

            paged = False
            for _ in range(12):
                t += step
                out = d.slo.tick(now=t, wall=w0 + t)
                if (out["evals"]["serving-availability"]["state"]
                        == "page"):
                    paged = True
                    break
            assert paged, d.slo.last
            assert out["verdict"] == "page"

            def _avail_incidents():
                return [i for i in d.flightrec.incidents()
                        if i["kind"] == "slo-burn"
                        and (i.get("detail") or {}).get("slo")
                        == "serving-availability"]

            # storm ticks: the open episode fires NOTHING new
            for _ in range(3):
                t += step
                d.slo.tick(now=t, wall=w0 + t)
            assert len(_avail_incidents()) == 1
            inc = _avail_incidents()[0]
            assert inc["detail"]["fast-burn"] >= 10.0

            # -- the auto-captured sysdump carries the evidence -----
            assert _wait(lambda: any(
                "slo-burn" in b["name"]
                for b in d.flightrec.list_bundles()), timeout=30)
            path = next(
                b["path"] for b in d.flightrec.list_bundles()
                if "slo-burn" in b["name"])
            with open(path) as f:
                b = json.load(f)
            assert b["incident"]["kind"] == "slo-burn"
            assert b["slo"]["verdict"] == "page"
            assert b["slo"]["active"], b["slo"]
            # the retained series window the burn was computed over
            assert b["history"]["fast"]
            assert any("cilium_serving_shed_total" in r["v"]
                       for r in b["history"]["fast"])

            # -- hysteresis recovery: burst slides out of the slow
            # window, clear_ticks calm evaluations close the episode
            for _ in range(80):
                t += step
                d.submit(_fwd(db.id, base=50000))
                d.slo.tick(now=t, wall=w0 + t)
            snap = d.slo_snapshot()
            assert snap["node"] == d.config.node_name
            assert "serving-availability" not in snap["active"]
            eps = [e for e in snap["episodes"]
                   if e["slo"] == "serving-availability"]
            assert len(eps) == 1
            assert eps[0]["recovered-at"] > eps[0]["started-at"]
            assert (snap["slos"]["serving-availability"]["state"]
                    == "ok")
            # STILL one incident for the whole episode
            assert len(_avail_incidents()) == 1

            # zero serving recompiles across burst + recovery
            assert _dispatch_compiles(d) == c0
            # the burn verdicts reached the exposition floor
            text = d.registry.render()
            assert 'cilium_slo_state{slo="serving-availability"}' \
                in text
            assert "cilium_slo_budget_remaining" in text
            assert "cilium_slo_burn_rate" in text

            stats = d.stop_serving()
            fe = stats["front-end"]
            # exact ledger: every offered row dispatched, shed, or
            # recovery-accounted
            assert fe["submitted"] == (
                fe["verdicts"] + fe["shed"]
                + fe["fault-tolerance"]["recovery-dropped"])
            assert fe["shed"] >= shed
        finally:
            d.shutdown()

    def test_sampler_thread_identity_and_restart(self):
        """The sampler is its OWN thread (`slo-sampler`, CTA002
        domain `slo`) — never the drain thread — and the engine is
        restartable (the bench's paired armed/off legs)."""
        d, db = _daemon(history_interval=0.02)
        d.start_serving(trace_sample=0, ingress=True,
                        ring_capacity=1 << 13)
        try:
            names = []
            orig = d.history.take_sample

            def spy(now=None, wall=None):
                names.append(threading.current_thread().name)
                return orig(now=now, wall=wall)

            d.history.take_sample = spy
            d.slo.start()
            d.submit(_fwd(db.id))
            assert _wait(lambda: len(names) >= 2)
            assert set(names) == {"slo-sampler"}
            d.slo.stop()
            n0, t0 = len(names), d.slo.ticks
            d.slo.start()  # restart: a fresh stop event re-arms
            assert _wait(lambda: d.slo.ticks > t0 and
                         len(names) > n0)
            assert set(names) == {"slo-sampler"}
            # serving stats carry the slo + history blocks off the
            # cached evaluation (a stats render never evaluates)
            st = d.serving_stats()
            assert st["slo"]["enabled"] is True
            assert st["history"]["samples"] >= 1
        finally:
            d.slo.stop()
            d.shutdown()


# ---------------------------------------------------------------------
# (c) thread-mode cluster verdict
# ---------------------------------------------------------------------
@pytest.mark.cluster
class TestClusterVerdict:
    def test_worst_of_merge_staleness_and_degradation(self):
        from cilium_tpu.cluster import ClusterServing

        c = ClusterServing(nodes=2, config=DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            # ladder (128,) keeps this bring-up's serving
            # executables shape-distinct from every (64,)-ladder
            # compile-count pin (jit caches are process-global;
            # test_cluster_scaleout's warm oracle must still see
            # its own compiles)
            serving_bucket_ladder=(128,),
            serving_max_wait_us=500.0,
            serving_restart_backoff_ms=1.0,
            cluster_probe_interval_s=0.1,
            cluster_death_threshold=2,
            cluster_obs_interval_s=0.0,  # verdicts on demand —
            # deterministic
            cluster_obs_stale_after_s=0.5,
            history_interval=0.0))  # ticks injected below
        try:
            c.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
            db = c.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
            rev = c.policy_import(RULES)
            assert c.wait_policy(rev, timeout=30)
            # serving must be LIVE: the serving-ledger collectors
            # sample None (-> no-data) outside a session
            c.start(trace_sample=0, packed=True,
                    ring_capacity=1 << 10)
            del db
            # two fake-timeline ticks spanning both windows give
            # every node a real OK verdict
            for n in c.nodes:
                n.daemon.slo.tick(now=0.0, wall=1.7e9)
                n.daemon.slo.tick(now=601.0, wall=1.7e9 + 601)
            cs = c.obs.cluster_slo()
            assert cs["verdict"] == "ok"
            assert cs["node-count"] == 2
            assert cs["unreachable"] == []
            assert set(cs["nodes"]) == {"node0", "node1"}
            for ent in cs["nodes"].values():
                assert ent["ok"] and ent["verdict"] == "ok"
                assert ent["slos"]["serving-availability"] == "ok"
            # node-stamped per-node surfaces (the one shared
            # definition behind both node modes)
            assert c.nodes[1].slo()["node"] == "node1"
            h = c.nodes[0].history(
                series=["cilium_serving_submitted_total"])
            assert h["node"] == "node0"
            assert h["series"] == ["cilium_serving_submitted_total"]

            # -- a dead node: last-known verdict INSIDE the bound,
            # but counted unreachable and node-labeled
            c.node("node1").crash("slo verdict test")
            cs = c.obs.cluster_slo()
            assert cs["unreachable"] == ["node1"]
            assert cs["nodes"]["node1"]["ok"] is False
            assert cs["nodes"]["node1"]["error"]
            assert cs["nodes"]["node1"]["verdict"] == "ok"
            assert cs["verdict"] == "ok"  # PR 14 staleness rule

            # -- past the bound: the corpse degrades the CLUSTER
            # verdict to no-data, worst-of over node verdicts
            time.sleep(0.6)
            cs = c.obs.cluster_slo()
            assert cs["nodes"]["node1"]["stale"] is True
            assert cs["nodes"]["node1"]["verdict"] == "no-data"
            assert cs["verdict"] == "no-data"
            assert cs["nodes"]["node0"]["verdict"] == "ok"
        finally:
            c.shutdown()
