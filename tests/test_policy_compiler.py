"""Compiler-vs-oracle equivalence: the dense tensors must reproduce the
MapState oracle exactly (the in-repo analogue of the eBPF verdict-
divergence gate in BASELINE.md — gated at 0% here)."""

import numpy as np
import pytest

from cilium_tpu.labels import LabelSet
from cilium_tpu.identity import CachingIdentityAllocator
from cilium_tpu.policy import (
    IdentityRowMap,
    PolicyRepository,
    compile_policy,
)
from cilium_tpu.policy.mapstate import N_PROTO, IP_PROTO_NUMBERS

DB = LabelSet.parse("k8s:app=db")
WEB = LabelSet.parse("k8s:app=web")

RULES = [
    {
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
            {"fromEndpoints": [{"matchLabels": {"tier": "cache"}}]},
            {"toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]},
            {"fromCIDR": ["10.1.0.0/16"],
             "toPorts": [{"ports": [{"port": "8000", "endPort": 8999,
                                     "protocol": "ANY"}]}]},
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                          "rules": {"http": [{"method": "GET"}]}}]},
        ],
        "ingressDeny": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "22", "protocol": "TCP"}]}]},
        ],
        "egress": [
            {"toEntities": ["world"],
             "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}]}]},
        ],
    },
    {
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [
            {"toEndpoints": [{"matchLabels": {"app": "db"}}]},
        ],
    },
]


@pytest.fixture
def setup():
    alloc = CachingIdentityAllocator()
    repo = PolicyRepository(alloc)
    # a spread of identities, some matching, some not
    for i in range(40):
        alloc.allocate(LabelSet.parse(f"k8s:app=svc{i}", "k8s:ns=default"))
    alloc.allocate(WEB)
    alloc.allocate(DB)
    alloc.allocate(LabelSet.parse("k8s:tier=cache"))
    repo.add_obj(RULES)
    policies = [repo.resolve(DB), repo.resolve(WEB)]
    row_map = IdentityRowMap(capacity=256)
    for ident in alloc.all_identities():
        row_map.add(ident.numeric_id)
    tensors = compile_policy(policies, row_map)
    return repo, policies, tensors, row_map


def test_tensor_matches_oracle_exhaustive_classes(setup):
    """Check every (identity-row, proto, class-representative-port)."""
    repo, policies, tensors, row_map = setup
    rng = np.random.default_rng(0)
    numerics = [row_map.numeric(r) for r in range(row_map.n_rows)]
    for pi, pol in enumerate(policies):
        for di in (0, 1):
            ms = pol.mapstate(di)
            for proto in range(N_PROTO):
                for (lo, hi, cls) in tensors.class_intervals[proto]:
                    # representative ports: ends + a random interior point
                    ports = {lo, hi - 1}
                    if hi - lo > 2:
                        ports.add(int(rng.integers(lo, hi)))
                    for port in ports:
                        for row, numeric in enumerate(numerics):
                            want_v, want_p = ms.lookup(numeric, proto, port)
                            lcls = tensors.class_map[pi, cls]
                            packed = tensors.verdict[pi, di, row, lcls]
                            got_v = packed & 0xFF
                            got_p = packed >> 8
                            assert got_v == want_v, (
                                pi, di, numeric, proto, port)
                            if want_v == 3:
                                assert got_p == want_p


def test_lookup_np_random_packets(setup):
    repo, policies, tensors, row_map = setup
    rng = np.random.default_rng(1)
    n = 5000
    pol_rows = rng.integers(0, len(policies), n)
    dirs = rng.integers(0, 2, n)
    rows = rng.integers(0, row_map.n_rows, n)
    ip_protos = rng.choice([6, 17, 1, 132, 47, 50], n)  # incl GRE/ESP
    ports = rng.integers(0, 65536, n)
    got_v, got_p = tensors.lookup_np(pol_rows, dirs, rows,
                                     ip_protos, ports)
    proto_dense = tensors.proto_table[ip_protos]
    for i in range(n):
        pol = policies[pol_rows[i]]
        numeric = row_map.numeric(int(rows[i]))
        want_v, want_p = pol.mapstate(int(dirs[i])).lookup(
            numeric, int(proto_dense[i]), int(ports[i]))
        assert got_v[i] == want_v, i
        if want_v == 3:
            assert got_p[i] == want_p


def test_unknown_identity_row0(setup):
    repo, policies, tensors, row_map = setup
    # row 0 = unknown identity: only wildcard rules apply
    v, _ = tensors.lookup_np(np.array([0]), np.array([0]), np.array([0]),
                             np.array([6]), np.array([443]))
    assert v[0] == 1  # L4-only wildcard-peer allow on 443/TCP
    v, _ = tensors.lookup_np(np.array([0]), np.array([0]), np.array([0]),
                             np.array([6]), np.array([5432]))
    assert v[0] == 0  # no wildcard coverage -> default deny


def test_proto_table():
    from cilium_tpu.policy.compiler import make_proto_table
    t = make_proto_table()
    assert t[6] == 0 and t[17] == 1 and t[1] == 2 and t[132] == 3
    assert t[47] == 4  # GRE -> OTHER
