"""The async event plane (ISSUE 5): off-hot-path event-join worker +
occupancy-bounded ring drain.

Acceptance properties covered here:

- NO DECODE ON THE DRAIN THREAD: under a serving load, every
  ``decode_ring_rows`` call runs on the event-join worker (tier-1
  regression for the tentpole's whole point);
- WRAP-AROUND EQUIVALENCE: a drain window that crosses the ring's lap
  boundary gathers/decodes identically via the bucketed device path
  and the legacy full-copy path (property test over cursor totals);
- D2H DIET: drain bytes scale with the window's event count, not the
  ring capacity (the gather-vs-fullcopy contrast);
- LAP LOSS is counted (``cilium_ring_lost_total``) and surfaced, with
  a deliberately-lagged consumer;
- NO SILENT LOSS under chaos: worker death/restart (the
  ``eventplane.join`` fault site), bounded-window-queue overflow, and
  stop-with-windows-in-flight all keep ``submitted == joined +
  dropped`` exact on the event plane AND ``submitted == verdicts +
  shed + recovery_dropped`` exact on the packet ledger.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.infra import faults
from cilium_tpu.monitor.ring import (GATHER_MIN_RUNG, RING_WORDS,
                                     AsyncRingDrainer, EventRing,
                                     _start_window)
from cilium_tpu.serving.eventplane import DrainWindow, EventJoinWorker

# ---------------------------------------------------------------------
# EventJoinWorker unit tests: pure threads + fakes, no jax
# ---------------------------------------------------------------------


class _FakeRing:
    """Stands in for monitor.ring.RingWindow in worker unit tests:
    the worker itself only reads the accounting attributes."""

    def __init__(self, appended=4, lost=0, nbytes=64):
        self.appended = appended
        self.lost = lost
        self.d2h_bytes = nbytes
        self.t_swap = time.monotonic()


def _win(appended=4, lost=0, nbytes=64):
    return DrainWindow(_FakeRing(appended, lost, nbytes), {}, {}, 0)


def _wait(pred, timeout=30.0, tick=0.002):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


class TestWorkerLedger:
    def test_joins_and_stop_drain(self):
        joined = []
        w = EventJoinWorker(joined.append, queue_depth=8)
        w.start()
        for i in range(5):
            assert w.submit(_win(appended=i + 1))
        st = w.stop(drain=True)
        assert len(joined) == 5
        assert st["windows-submitted"] == 5
        assert st["windows-joined"] == 5
        assert st["windows-dropped"] == 0
        assert st["events-joined"] == 1 + 2 + 3 + 4 + 5
        assert st["d2h-bytes"] == 5 * 64
        assert st["join-lag-us"]["count"] == 5
        # post-drain the ledger is exact and nothing is pending
        assert st["windows-pending"] == 0

    def test_bounded_queue_overflow_drops_oldest_counted(self):
        started, release = threading.Event(), threading.Event()
        dropped = []

        def slow_join(win):
            started.set()
            release.wait(10)

        w = EventJoinWorker(slow_join, drop_fn=dropped.append,
                            queue_depth=2)
        w.start()
        assert w.submit(_win())  # worker picks this up and blocks
        assert started.wait(5)
        assert w.submit(_win(appended=2))  # queued 1/2
        assert w.submit(_win(appended=3))  # queued 2/2
        # overflow drops the OLDEST queued window (2), admits the new
        assert w.submit(_win(appended=7))
        assert w.submit(_win(appended=9))  # drops (3)
        assert w.overflows == 2
        assert len(dropped) == 2
        assert w.last_drop_cause == "window queue full"
        release.set()
        st = w.stop(drain=True)
        assert st["windows-submitted"] == 5
        assert st["windows-joined"] == 3
        assert st["windows-dropped"] == 2
        # ...and the dropped events are the OLDEST two's
        assert st["events-dropped"] == 2 + 3

    def test_contained_join_failure_keeps_worker_alive(self):
        joined, dropped = [], []

        def join(win):
            if win.appended == 13:
                raise ValueError("poison window")
            joined.append(win)

        w = EventJoinWorker(join, drop_fn=dropped.append)
        w.start()
        w.submit(_win(appended=13))
        w.submit(_win(appended=1))
        st = w.stop(drain=True)
        # one window lost (counted, cause recorded), no restart
        # burned, the plane lived on and joined the next
        assert len(joined) == 1 and len(dropped) == 1
        assert st["windows-dropped"] == 1
        assert st["worker-restarts"] == 0
        assert "join failed" in st["last-drop-cause"]
        assert "error" not in st

    def test_death_restarts_under_budget(self):
        # the injection site raises OUTSIDE the per-window
        # containment -> thread death -> restart (the drain-loop
        # watchdog discipline, applied to the join plane)
        inj = faults.arm("eventplane.join=1x1@1", seed=1)
        joined = []
        try:
            w = EventJoinWorker(joined.append, restart_budget=3)
            w.start()
            w.submit(_win())  # skipped by @1: joins
            w.submit(_win(appended=5))  # dies: counted drop + restart
            # a death DURING stop is deliberately terminal (no
            # restart burned on a plane being shut down), so let the
            # restart land before stopping
            assert _wait(lambda: w.restarts >= 1)
            w.submit(_win())  # the restarted thread joins
            st = w.stop(drain=True)
        finally:
            faults.disarm(inj)
        assert len(joined) == 2
        assert st["worker-restarts"] == 1
        assert st["windows-dropped"] == 1
        assert st["events-dropped"] == 5
        assert "worker died" in st["last-drop-cause"]
        assert "error" not in st

    def test_budget_exhaustion_is_terminal_and_swept(self):
        inj = faults.arm("eventplane.join=1x8", seed=1)
        try:
            w = EventJoinWorker(lambda win: None, restart_budget=1)
            w.start()
            w.submit(_win())  # dies (restart 1/1)
            _wait(lambda: w.restarts >= 1)
            w.submit(_win())  # dies again: budget gone -> terminal
            _wait(lambda: w.error is not None)
            # a terminal worker drops further submits, counted
            assert not w.submit(_win())
            st = w.stop(drain=True)
        finally:
            faults.disarm(inj)
        assert st["error"] and "exhausted" in st["error"]
        assert st["windows-submitted"] == 3
        assert st["windows-joined"] + st["windows-dropped"] == 3
        assert st["windows-dropped"] >= 2

    def test_stop_sweeps_hung_join_no_double_count(self):
        """A join wedged past stop()'s timeout is claimed and counted
        dropped (submitted == joined + dropped still exact, pending
        0); when the wedged join_fn finally returns it must NOT also
        count the window joined."""
        started = threading.Event()
        release = threading.Event()

        def join(w):
            started.set()
            release.wait(10.0)

        w = EventJoinWorker(join, queue_depth=4)
        w.start()
        assert w.submit(_win())
        assert started.wait(5.0)
        out = w.stop(drain=True, timeout=0.3)
        assert out["windows-submitted"] == 1
        assert out["windows-dropped"] == 1
        assert out["windows-joined"] == 0
        assert out["windows-pending"] == 0
        release.set()  # let the wedged join land late
        assert _wait(lambda: not w._thread.is_alive(), timeout=5.0)
        st = w.stats()
        assert st["windows-joined"] == 0  # late join didn't recount
        assert st["windows-dropped"] == 1

    def test_stop_without_drain_sweeps_counted(self):
        started, release = threading.Event(), threading.Event()

        def slow_join(win):
            started.set()
            release.wait(10)

        w = EventJoinWorker(slow_join, queue_depth=8)
        w.start()
        w.submit(_win())
        assert started.wait(5)
        w.submit(_win())
        w.submit(_win())
        release.set()
        st = w.stop(drain=False)
        assert st["windows-submitted"] == 3
        # the in-join window may finish; the queued ones are swept
        assert st["windows-joined"] + st["windows-dropped"] == 3
        assert st["windows-dropped"] >= 2


# ---------------------------------------------------------------------
# Occupancy-bounded gather == legacy full copy (property over cursors)
# ---------------------------------------------------------------------


def _packed_row(i: int) -> np.ndarray:
    """A distinguishable wire row for global event index ``i``:
    event bits 0b01 (occupied), id_row/pkt_idx derived from ``i``."""
    w0 = np.uint32((1 << 3) | ((i & 0xFFFF) << 16)
                   | ((i % 11) & 0xF) << 5)
    w1 = np.uint32(i & 0x7FFFF)
    return np.array([w0, w1], dtype=np.uint32)


def _ring_with_total(cap: int, total: int, base: int = 0) -> EventRing:
    """A synthetic ring after ``total`` appends: slot ``i & mask``
    holds the NEWEST event with that residue (exactly what the device
    scatter leaves behind), cursor carries the 64-bit total."""
    import jax.numpy as jnp

    buf = np.full((cap, RING_WORDS), 0xFFFFFFFF, dtype=np.uint32)
    for i in range(max(0, total - cap), total):
        buf[i & (cap - 1)] = _packed_row(base + i)
    cursor = np.array([total & 0xFFFFFFFF, total >> 32],
                      dtype=np.uint32)
    return EventRing(buf=jnp.asarray(buf), cursor=jnp.asarray(cursor))


class TestGatherEquivalence:
    # cursor totals walking every regime: empty, sub-rung, rung
    # boundaries, just-below/at/above capacity (the lap boundary),
    # deep into the second and third laps
    TOTALS = (0, 1, 5, 63, 64, 65, 100, 127, 128, 129, 200, 255, 256,
              257, 300, 383, 384, 511, 512, 525)

    @pytest.mark.parametrize("total", TOTALS)
    def test_gather_matches_fullcopy(self, total):
        cap = 128
        rows = {}
        meta = {}
        for gather in (True, False):
            d = AsyncRingDrainer(cap, gather=gather)
            fresh = d.swap(_ring_with_total(cap, total))
            assert fresh.capacity == cap
            r, appended, lost = d.collect()
            rows[gather] = r
            meta[gather] = (appended, lost, d.events, d.lost)
        np.testing.assert_array_equal(rows[True], rows[False])
        assert meta[True] == meta[False]
        # and both agree with first principles
        appended, lost = meta[True][0], meta[True][1]
        assert appended == total
        assert lost == max(0, total - cap)
        assert len(rows[True]) == min(total, cap)

    def test_window_d2h_bytes_scale_with_occupancy(self):
        cap = 1 << 12
        # 3 events: the gather ships one GATHER_MIN_RUNG bucket, the
        # full copy ships the whole ring regardless
        wg, _ = AsyncRingDrainer(cap, gather=True).swap_window(
            _ring_with_total(cap, 3))
        wf, _ = AsyncRingDrainer(cap, gather=False).swap_window(
            _ring_with_total(cap, 3))
        assert wg.rung == GATHER_MIN_RUNG
        assert wg.d2h_bytes == GATHER_MIN_RUNG * RING_WORDS * 4 + 8
        assert wf.d2h_bytes == cap * RING_WORDS * 4 + 8
        assert wg.d2h_bytes * 32 < wf.d2h_bytes
        rg = wg.fetch()[0]
        rf = wf.fetch()[0]
        np.testing.assert_array_equal(rg, rf)
        # empty window: nothing crosses the link at all
        we, _ = AsyncRingDrainer(cap, gather=True).swap_window(
            _ring_with_total(cap, 0))
        assert we.d2h_bytes == 0 and we.buf is None

    @pytest.mark.parametrize("totals", [(0, 0), (5, 0), (0, 9),
                                        (40, 70), (64, 130), (150, 3)])
    def test_sharded_window_gather_matches_fullcopy(self, totals):
        """Per-chip rings: a [S*cap] buffer + [S, 2] cursor window
        decodes identically via both paths, per-shard wrap included
        (the rung is COMMON across shards — max occupancy)."""
        import jax.numpy as jnp

        cap, S = 64, 2
        bufs, curs = [], []
        for s, total in enumerate(totals):
            r = _ring_with_total(cap, total, base=1000 * s)
            bufs.append(np.asarray(r.buf))
            curs.append(np.asarray(r.cursor))

        class _Sharded:
            buf = jnp.asarray(np.concatenate(bufs))
            cursor = jnp.asarray(np.stack(curs))

        out = {}
        for gather in (True, False):
            w = _start_window(_Sharded(), cap, S, None, None, gather,
                              None)
            rows, shards, appended, lost = w.fetch()
            out[gather] = (rows, shards, appended, lost)
        np.testing.assert_array_equal(out[True][0], out[False][0])
        np.testing.assert_array_equal(out[True][1], out[False][1])
        assert out[True][2:] == out[False][2:]
        assert out[True][2] == sum(totals)
        assert out[True][3] == sum(max(0, t - cap) for t in totals)


# ---------------------------------------------------------------------
# End-to-end: the serving daemon on the tpu backend
# ---------------------------------------------------------------------
from cilium_tpu.agent import Daemon, DaemonConfig  # noqa: E402
from cilium_tpu.core import TCP_SYN, make_batch  # noqa: E402

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]


def _daemon(fault_spec=None, **over):
    # ONE 64-wide ladder rung: shared XLA executables with the chaos
    # suite (same (64, 16) shapes), so this file adds ~no compile cost
    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_dispatch_deadline_ms=500.0,
               serving_restart_budget=4,
               fault_injection=fault_spec, fault_seed=1)
    cfg.update(over)
    d = Daemon(DaemonConfig(**cfg))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _fwd(db_id, n=64, base=20000):
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base + i,
             dport=5432, proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _assert_ledgers(out):
    fe = out["front-end"]
    ft = fe["fault-tolerance"]
    assert fe["submitted"] == (fe["verdicts"] + fe["shed"]
                               + ft["recovery-dropped"])
    ev = out["event-plane"]
    assert ev["windows-submitted"] == (ev["windows-joined"]
                                       + ev["windows-dropped"])
    assert ev["windows-pending"] == 0
    return fe, ev


class TestNoDecodeOnDrainThread:
    def test_decode_runs_only_on_the_worker(self, monkeypatch):
        """THE tier-1 regression for the tentpole: under a serving
        load with per-packet events, every ``decode_ring_rows`` call
        happens on the event-join worker — the drain thread's event
        work is the 8-byte cursor sync + a queue push, nothing
        else."""
        import cilium_tpu.monitor.api as mon_api

        seen = []
        real = mon_api.decode_ring_rows

        def spy(*a, **k):
            seen.append(threading.current_thread().name)
            return real(*a, **k)

        monkeypatch.setattr(mon_api, "decode_ring_rows", spy)
        d, db = _daemon()
        d.start_serving(trace_sample=1, ingress=True, drain_every=2)
        rt = d._serving["runtime"]
        for i in range(4):
            d.submit(_fwd(db.id, base=20000 + 100 * i))
        assert _wait(lambda: rt.stats.verdicts >= 256)
        worker = d._serving["eventplane"]
        assert _wait(lambda: worker.windows_joined >= 1)
        out = d.stop_serving()
        fe, ev = _assert_ledgers(out)
        assert ev["events-joined"] >= 256  # decode actually ran
        assert seen, "no decode observed — the spy never fired"
        bad = [n for n in seen
               if not n.startswith("serving-eventjoin")]
        assert not bad, f"event decode ran on {sorted(set(bad))}"
        d.shutdown()


class TestLapLoss:
    def test_lagged_consumer_loss_counted_and_surfaced(self):
        """A deliberately-lagged consumer: drain cadence spans more
        events than the ring holds, so the window laps and the host
        computes ``appended - capacity`` loss — counted in the
        event-plane ledger and exported as
        ``cilium_ring_lost_total``."""
        d, db = _daemon()
        # 64-slot ring, 4 batches x 64 events per window: 192 of the
        # 256 appended events are lapped before the swap
        d.start_serving(ring_capacity=64, drain_every=4,
                        trace_sample=1)
        for i in range(4):
            d.serve_batch(_fwd(db.id, base=21000 + 100 * i),
                          valid=np.ones(64, dtype=bool))
        # the 5th serve ticks the drain (seq - last_tick >= 4)
        d.serve_batch(_fwd(db.id, base=25000),
                      valid=np.ones(64, dtype=bool))
        worker = d._serving["eventplane"]
        assert _wait(lambda: worker.windows_joined >= 1)
        st = d.serving_stats()["event-plane"]
        assert st["ring-lost"] == 192
        assert st["events-joined"] == 64
        # satellite surface: the metrics registry while serving
        prom = d.registry.render()
        assert "cilium_ring_lost_total 192" in prom
        assert "cilium_serving_d2h_bytes_total" in prom
        assert "cilium_serving_event_join_lag_us_count" in prom
        out = d.stop_serving()
        ev = out["event-plane"]
        assert ev["windows-submitted"] == (ev["windows-joined"]
                                           + ev["windows-dropped"])
        assert ev["ring-lost"] == 192  # the last window didn't lap
        d.shutdown()

    def test_stale_window_join_refused_never_corrupts(self):
        """The arena-horizon guard: a window whose join starts after
        the producer dispatched past the recycling horizon is
        REFUSED (a counted drop) — its record references may point
        at recycled slots, and a silent join would publish events
        attributed to the wrong packets."""
        d, db = _daemon()
        d.start_serving(drain_every=2, trace_sample=1)
        d.serve_batch(_fwd(db.id), valid=np.ones(64, dtype=bool))
        s = d._serving
        window, s["ring"] = s["drainer"].swap_window(s["ring"])
        stale = DrainWindow(window, {}, {}, 0,
                            seq=s["seq"] - s["join_horizon"] - 1)
        with pytest.raises(RuntimeError, match="arena horizon"):
            d._event_join(stale)
        # the refusal rolled the drainer's delivered credit back:
        # ring.events must not count events the monitor never got
        assert s["drainer"].events == 0
        d.stop_serving()
        d.shutdown()

    def test_gather_off_matches_and_costs_capacity(self):
        """event_gather=False is the legacy wire: same decoded
        events, full-capacity d2h bytes — the contrast that proves
        the diet is the gather, not the async plane."""
        per_event = {}
        for gather in (True, False):
            d, db = _daemon()
            d.start_serving(ring_capacity=1 << 12, drain_every=4,
                            trace_sample=1, event_gather=gather)
            for i in range(4):
                d.serve_batch(_fwd(db.id, base=22000 + 100 * i),
                              valid=np.ones(64, dtype=bool))
            out = d.stop_serving()
            ev = out["event-plane"]
            assert ev["events-joined"] == 256
            assert ev["ring-lost"] == 0
            per_event[gather] = ev["d2h-bytes-per-event"]
            d.shutdown()
        # gather: 256 events ship one 256-rung bucket (8 B/event +
        # cursor) = 16x fewer bytes than the 4096-slot full copy
        assert per_event[True] <= 16
        assert per_event[False] >= (1 << 12) * RING_WORDS * 4 / 256
        assert per_event[True] * 8 < per_event[False]


@pytest.mark.chaos
class TestEventPlaneChaos:
    def test_worker_death_restart_ledger_exact(self):
        """The ``eventplane.join`` fault site kills the worker
        mid-plane; the thread restarts under the budget, the dead
        join's window is a COUNTED drop, its spans are evicted (the
        tracer ledger stays exact), and the packet ledger never
        notices."""
        d, db = _daemon(fault_spec="eventplane.join=1x1@1")
        d.start_serving(trace_sample=1, ingress=True, drain_every=2,
                        span_sample=16)
        rt = d._serving["runtime"]
        worker = d._serving["eventplane"]
        for i in range(6):
            d.submit(_fwd(db.id, base=23000 + 100 * i))
        assert _wait(lambda: rt.stats.verdicts >= 384)
        assert _wait(lambda: worker.restarts >= 1)
        tracer = d._serving["tracer"]
        out = d.stop_serving()
        fe, ev = _assert_ledgers(out)
        assert ev["worker-restarts"] == 1
        assert ev["windows-dropped"] >= 1
        assert "worker died" in ev["last-drop-cause"]
        # the dropped window's spans were evicted, not leaked
        ts = tracer.stats()
        assert ts["started"] == ts["completed"] + ts["dropped"]
        d.shutdown()

    def test_overflow_and_stop_with_windows_in_flight(self):
        """A hung join stalls the plane: windows pile into the
        bounded queue, overflow drops are counted, and
        ``stop_serving`` over the backlog still reconciles exactly
        (drain joins what it can, the sweep counts the rest)."""
        d, db = _daemon(fault_spec="eventplane.join=1~0.15")
        d.start_serving(trace_sample=1, ingress=True, drain_every=1,
                        window_queue_depth=1)
        rt = d._serving["runtime"]
        worker = d._serving["eventplane"]
        for i in range(10):
            d.submit(_fwd(db.id, base=24000 + 50 * i))
            _wait(lambda: rt.queue.pending == 0, timeout=5)
        assert _wait(lambda: rt.stats.verdicts >= 640)
        # stop while the plane still holds queued/hung windows
        out = d.stop_serving()
        fe, ev = _assert_ledgers(out)
        assert ev["windows-submitted"] >= 10
        if ev["queue-overflows"]:
            assert ev["windows-dropped"] >= ev["queue-overflows"]
        d.shutdown()

    def test_terminal_worker_degrades_not_crashes(self):
        """Budget exhausted mid-serve: the event plane goes terminal
        (drops counted, error surfaced), but dispatch keeps verdicting
        and span tracing falls back to completion-boundary stamping
        instead of leaking into a dead queue."""
        d, db = _daemon(fault_spec="eventplane.join=1x8",
                        serving_restart_budget=1)
        d.start_serving(trace_sample=1, ingress=True, drain_every=1,
                        span_sample=8)
        rt = d._serving["runtime"]
        worker = d._serving["eventplane"]
        for i in range(8):
            d.submit(_fwd(db.id, base=26000 + 50 * i))
            _wait(lambda: rt.queue.pending == 0, timeout=5)
        assert _wait(lambda: worker.error is not None)
        # serving survives the dead event plane
        d.submit(_fwd(db.id, base=27000))
        assert _wait(lambda: rt.stats.verdicts >= 576)
        st = d.serving_stats()["event-plane"]
        assert "error" in st and "exhausted" in st["error"]
        tracer = d._serving["tracer"]
        out = d.stop_serving()
        fe, ev = _assert_ledgers(out)
        assert fe["verdicts"] >= 576  # packets never stopped
        ts = tracer.stats()
        assert ts["started"] == ts["completed"] + ts["dropped"]
        d.shutdown()
