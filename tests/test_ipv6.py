"""IPv6 end-to-end coverage: the v6 side of every datapath stage
(TCAM LPM, 128-bit CT keys, flow rendering) plus a randomized
device-vs-oracle divergence run — v4 has the 102k-packet gate; this
is the v6 counterpart.
"""

import numpy as np

import jax.numpy as jnp

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, TCP_ACK, make_batch
from cilium_tpu.core.packets import (
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    N_COLS,
    HeaderBatch,
    ip_to_words,
)


RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
        {"fromCIDR": ["2001:db8:aaaa::/48"],
         "toPorts": [{"ports": [{"port": "8080", "protocol": "TCP"}]}]},
    ],
}]


def _pkt6(src, dst, dport, ep, dirn=0, flags=TCP_SYN, sport=40000):
    return dict(src=src, dst=dst, sport=sport, dport=dport, proto=6,
                flags=flags, ep=ep, dir=dirn, family=6)


class TestIPv6Daemon:
    def _mk(self, backend):
        d = Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12))
        web = d.add_endpoint("web-1", ("2001:db8:1::10",),
                             ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("2001:db8:1::20",),
                            ["k8s:app=db"])
        d.policy_import(RULES)
        d.start()
        return d, web, db

    def test_v6_policy_and_ct_lifecycle(self):
        outs = {}
        for backend in ("tpu", "interpreter"):
            d, web, db = self._mk(backend)
            batch = make_batch([
                # selector allow (web -> db :5432)
                _pkt6("2001:db8:1::10", "2001:db8:1::20", 5432, db.id),
                # CIDR allow (v6 TCAM longest-prefix)
                _pkt6("2001:db8:aaaa::7", "2001:db8:1::20", 8080,
                      db.id, sport=40001),
                # outside the CIDR: default deny
                _pkt6("2001:db8:bbbb::7", "2001:db8:1::20", 8080,
                      db.id, sport=40002),
                # wrong port: default deny
                _pkt6("2001:db8:1::10", "2001:db8:1::20", 22, db.id,
                      sport=40003),
            ]).data
            evb = d.process_batch(batch, now=10)
            v1 = list(evb.verdict)
            # established continuation forwards without policy (TRACE)
            evb2 = d.process_batch(make_batch([
                _pkt6("2001:db8:1::10", "2001:db8:1::20", 5432, db.id,
                      flags=TCP_ACK),
            ]).data, now=20)
            from cilium_tpu.monitor.api import MSG_TRACE

            outs[backend] = (v1, list(evb2.verdict),
                             list(evb2.msg_type))
            d.shutdown()
        for backend, (v1, v2, msg) in outs.items():
            assert v1 == [1, 1, 0, 0], (backend, v1)
            assert v2 == [1] and msg == [MSG_TRACE], backend
        assert outs["tpu"] == outs["interpreter"]

    def test_v6_flow_rendering(self):
        d, web, db = self._mk("tpu")
        evb = d.process_batch(make_batch([
            _pkt6("2001:db8:1::10", "2001:db8:1::20", 5432, db.id),
        ]).data, now=10)
        f = d.observer.get_flows(number=1)[0]
        j = f.to_dict()
        assert j["IP"]["source"] == "2001:db8:1::10"
        assert j["IP"]["destination"] == "2001:db8:1::20"
        assert j["l4"]["TCP"]["destination_port"] == 5432
        d.shutdown()


def _v6_traffic(rng, n, ep=0):
    """Randomized v6 batch over a small address space (flows recur)."""
    out = np.zeros((n, N_COLS), dtype=np.uint32)
    hosts = [f"2001:db8:1::{h:x}" for h in range(1, 40)] + [
        f"2001:db8:aaaa::{h:x}" for h in range(1, 10)] + [
        f"2001:db8:ffff::{h:x}" for h in range(1, 5)]
    for i in range(n):
        src = hosts[int(rng.integers(0, len(hosts)))]
        out[i, COL_SRC_IP0:COL_SRC_IP0 + 4] = ip_to_words(src)
        out[i, COL_DST_IP0:COL_DST_IP0 + 4] = ip_to_words(
            "2001:db8:1::20")
    out[:, COL_SPORT] = 1024 + rng.integers(0, 500, n)
    out[:, COL_DPORT] = rng.choice(
        np.array([5432, 8080, 22, 443], dtype=np.uint32), n)
    out[:, COL_PROTO] = rng.choice(
        np.array([6, 6, 17, 58], dtype=np.uint32), n)
    is_tcp = out[:, COL_PROTO] == 6
    out[:, COL_FLAGS] = np.where(
        is_tcp, rng.choice(np.array([TCP_SYN, TCP_ACK],
                                    dtype=np.uint32), n), 0)
    is_icmp6 = out[:, COL_PROTO] == 58
    out[:, COL_SPORT] = np.where(is_icmp6, 0, out[:, COL_SPORT])
    out[:, COL_DPORT] = np.where(
        is_icmp6, 128 + rng.integers(0, 2, n), out[:, COL_DPORT])
    out[:, COL_LEN] = rng.integers(60, 1500, n)
    out[:, COL_FAMILY] = 6
    out[:, COL_EP] = ep
    return out


def test_v6_divergence_randomized():
    """Device vs oracle over randomized v6 traffic incl. ICMPv6 and
    CT churn: 0% divergence (the v6 counterpart of the 102k v4 gate,
    smaller because the v6 TCAM is O(prefixes) per packet in the
    oracle)."""
    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.identity.allocator import CachingIdentityAllocator
    from cilium_tpu.labels import LabelSet
    from cilium_tpu.policy import (IdentityRowMap, PolicyRepository,
                                   compile_policy)
    from cilium_tpu.datapath.lpm import compile_lpm
    from cilium_tpu.datapath.verdict import build_state
    from cilium_tpu.testing import OracleDatapath

    alloc = CachingIdentityAllocator()
    repo = PolicyRepository(alloc)
    web = alloc.allocate(LabelSet.parse("k8s:app=web"))
    db = alloc.allocate(LabelSet.parse("k8s:app=db"))
    repo.add_obj(RULES)
    pol = repo.resolve(LabelSet.parse("k8s:app=db"))

    ipcache = {"2001:db8:1::10/128": web.numeric_id,
               "2001:db8:1::/64": db.numeric_id}
    # CIDR identities the policy allocated resolve through the TCAM
    for ident in alloc.all_identities():
        for lab in ident.labels:
            if lab.source == "cidr" and ":" in lab.key:
                ipcache[lab.key] = ident.numeric_id

    row_map = IdentityRowMap(capacity=256)
    for ident in alloc.all_identities():
        row_map.add(ident.numeric_id)
    tensors = compile_policy([pol], row_map)
    lpm = compile_lpm({c: row_map.row(i) for c, i in ipcache.items()})
    state = build_state(tensors, lpm, np.zeros(4096, dtype=np.int32),
                        ct_capacity=1 << 12)
    oracle = OracleDatapath({0: pol}, ipcache)
    row_to_num = row_map.numeric_array()

    rng = np.random.default_rng(6)
    now = 100
    total = div = 0
    for b in range(8):
        data = _v6_traffic(rng, 1024)
        out, state = datapath_step_jit(state, jnp.asarray(data),
                                       jnp.uint32(now))
        out = np.asarray(out)
        want = oracle.step(HeaderBatch(data), now)
        for i, w in enumerate(want):
            got = (int(out[i, 0]), int(out[i, 1]), int(out[i, 2]),
                   int(row_to_num[out[i, 3]]), int(out[i, 4]),
                   int(out[i, 5]))
            if got != (w.verdict, w.proxy, w.ct, w.identity, w.reason,
                       w.event):
                div += 1
        total += len(want)
        now += int(rng.integers(1, 40))
    assert total >= 8000
    assert div == 0, f"{div}/{total} v6 packets diverged"
