"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The sharded datapath (CT sharded by flow hash, tables replicated) must
agree packet-for-packet with the sequential oracle — the multi-node
analogue of the divergence gate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.core import make_batch, TCP_ACK, TCP_SYN
from cilium_tpu.parallel import (
    flow_shard_ids,
    make_mesh,
    make_sharded_step,
    route_by_flow,
    shard_state,
)

from tests.test_verdict_divergence import _random_batch, world  # noqa: F401


def test_flow_hash_symmetric():
    fwd = make_batch([dict(src="10.0.1.1", dst="10.0.2.9", sport=1234,
                           dport=80, proto=6)])
    rev = make_batch([dict(src="10.0.2.9", dst="10.0.1.1", sport=80,
                           dport=1234, proto=6)])
    a = flow_shard_ids(fwd.data, 8)
    b = flow_shard_ids(rev.data, 8)
    assert a[0] == b[0]


def test_flow_hash_spreads():
    batch = _random_batch(np.random.default_rng(0), 512)
    ids = flow_shard_ids(batch.data, 8)
    counts = np.bincount(ids, minlength=8)
    assert (counts > 20).all(), counts  # roughly uniform


def test_sharded_step_matches_oracle(world):  # noqa: F811
    state, oracle, row_to_numeric = world
    assert len(jax.devices()) == 8, "conftest must force 8 cpu devices"
    mesh = make_mesh(8)
    state = shard_state(state, mesh)
    step = make_sharded_step(mesh)
    rng = np.random.default_rng(11)
    now = 5000
    for _ in range(4):
        batch = _random_batch(rng, 256)
        routed, valid, orig, _ovf = route_by_flow(batch.data, 8)
        out, state = step(state, jnp.asarray(routed), jnp.uint32(now),
                          jnp.asarray(valid))
        out = np.asarray(out)
        want = oracle.step(batch, now)
        n_div = 0
        for j in range(len(routed)):
            if orig[j] < 0:
                continue
            w = want[orig[j]]
            got = (int(out[j, 0]), int(out[j, 1]), int(out[j, 2]),
                   int(row_to_numeric[out[j, 3]]), int(out[j, 4]),
                   int(out[j, 5]))
            exp = (w.verdict, w.proxy, w.ct, w.identity, w.reason, w.event)
            if got != exp:
                n_div += 1
        assert n_div == 0, f"{n_div} diverged"
        now += 3


def test_replicated_counters_agree(world):  # noqa: F811
    """Metrics/drop counters are psum-replicated: one global total."""
    state, oracle, row_to_numeric = world
    mesh = make_mesh(8)
    state = shard_state(state, mesh)
    step = make_sharded_step(mesh)
    batch = _random_batch(np.random.default_rng(3), 256)
    routed, valid, orig, _ovf = route_by_flow(batch.data, 8)
    out, state = step(state, jnp.asarray(routed), jnp.uint32(10),
                      jnp.asarray(valid))
    total = int(np.asarray(state.metrics).sum())
    assert total == int(valid.sum())  # every real packet counted once
