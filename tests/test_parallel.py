"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The sharded datapath (CT sharded by flow hash, tables replicated) must
agree packet-for-packet with the sequential oracle — the multi-node
analogue of the divergence gate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.core import make_batch, TCP_ACK, TCP_SYN
from cilium_tpu.parallel import (
    add_route_overflow,
    flow_shard_ids,
    make_mesh,
    make_sharded_step,
    route_by_flow,
    shard_state,
)

from tests.test_verdict_divergence import _random_batch, world  # noqa: F401


def test_flow_hash_symmetric():
    fwd = make_batch([dict(src="10.0.1.1", dst="10.0.2.9", sport=1234,
                           dport=80, proto=6)])
    rev = make_batch([dict(src="10.0.2.9", dst="10.0.1.1", sport=80,
                           dport=1234, proto=6)])
    a = flow_shard_ids(fwd.data, 8)
    b = flow_shard_ids(rev.data, 8)
    assert a[0] == b[0]


def test_flow_hash_spreads():
    batch = _random_batch(np.random.default_rng(0), 512)
    ids = flow_shard_ids(batch.data, 8)
    counts = np.bincount(ids, minlength=8)
    assert (counts > 20).all(), counts  # roughly uniform


def test_flow_hash_symmetric_over_normalize_ports_space():
    """Property (PR 2 satellite): for RANDOM tuples across the
    normalize_ports space — porty protocols with real ports, portless
    protocols (ICMP/ICMPv6) with arbitrary type/code junk in the port
    columns — forward and reply packets always land on the same
    shard.  Portless protocols are the trap: an ICMP echo request
    carries dport=8 while its reply carries dport=0, so steering that
    hashed raw ports would split the flow across shards and the reply
    would miss its CT entry."""
    from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP0,
                                         COL_DST_IP3, COL_SPORT,
                                         COL_SRC_IP0, COL_SRC_IP3,
                                         N_COLS)

    rng = np.random.default_rng(77)
    n = 2048
    fwd = np.zeros((n, N_COLS), dtype=np.uint32)
    for w in range(4):
        fwd[:, COL_SRC_IP0 + w] = rng.integers(0, 1 << 32, n,
                                               dtype=np.uint32)
        fwd[:, COL_DST_IP0 + w] = rng.integers(0, 1 << 32, n,
                                               dtype=np.uint32)
    fwd[:, COL_SPORT] = rng.integers(0, 1 << 16, n, dtype=np.uint32)
    fwd[:, COL_DPORT] = rng.integers(0, 1 << 16, n, dtype=np.uint32)
    fwd[:, 10] = rng.choice(
        np.array([6, 17, 132, 1, 58, 47], dtype=np.uint32), n)
    # the reply: src/dst and ports swapped; for portless protos ALSO
    # scramble the ports entirely (echo reply type != request type)
    rev = fwd.copy()
    rev[:, COL_SRC_IP0:COL_SRC_IP3 + 1] = \
        fwd[:, COL_DST_IP0:COL_DST_IP3 + 1]
    rev[:, COL_DST_IP0:COL_DST_IP3 + 1] = \
        fwd[:, COL_SRC_IP0:COL_SRC_IP3 + 1]
    rev[:, COL_SPORT] = fwd[:, COL_DPORT]
    rev[:, COL_DPORT] = fwd[:, COL_SPORT]
    portless = (fwd[:, 10] == 1) | (fwd[:, 10] == 58)
    rev[portless, COL_SPORT] = rng.integers(
        0, 1 << 16, int(portless.sum()), dtype=np.uint32)
    rev[portless, COL_DPORT] = rng.integers(
        0, 1 << 16, int(portless.sum()), dtype=np.uint32)
    for shards in (2, 8, 16):
        np.testing.assert_array_equal(flow_shard_ids(fwd, shards),
                                      flow_shard_ids(rev, shards))


def test_route_overflow_counts_and_decodes(world):  # noqa: F811
    """route_by_flow overflow -> add_route_overflow lands the EXACT
    count under REASON_ROUTE_OVERFLOW (ingress column) without
    touching any other counter, and the code decodes to names at the
    monitor and flow layers.  (The serving-path end-to-end version —
    overflow as DROP events through a live daemon — lives in
    test_serving_sharded.py.)"""
    from cilium_tpu.datapath.verdict import REASON_ROUTE_OVERFLOW
    from cilium_tpu.flow.flow import DROP_REASON_DESC
    from cilium_tpu.monitor.api import DROP_REASON_NAMES

    state, _oracle, _r2n = world
    # one elephant flow, tiny blocks: everything past one block drops
    batch = make_batch([dict(src="10.0.1.1", dst="10.0.2.9",
                             sport=999, dport=80, proto=6)] * 64).data
    routed, valid, orig, n_ovf = route_by_flow(batch, 8, block=4)
    assert n_ovf == 60 and int(valid.sum()) == 4
    # kept rows preserve their original identity
    assert (orig[valid] >= 0).all()
    before = np.asarray(state.metrics).copy()
    state = add_route_overflow(state, n_ovf)
    delta = np.asarray(state.metrics).astype(np.int64) - before
    assert delta[REASON_ROUTE_OVERFLOW, 0] == 60
    assert delta.sum() == 60  # nothing else moved
    assert DROP_REASON_NAMES[REASON_ROUTE_OVERFLOW] \
        == "Shard queue overflow"
    assert DROP_REASON_DESC[REASON_ROUTE_OVERFLOW] == "QUEUE_OVERFLOW"


def test_sharded_step_matches_oracle(world):  # noqa: F811
    state, oracle, row_to_numeric = world
    assert len(jax.devices()) == 8, "conftest must force 8 cpu devices"
    mesh = make_mesh(8)
    state = shard_state(state, mesh)
    step = make_sharded_step(mesh)
    rng = np.random.default_rng(11)
    now = 5000
    for _ in range(4):
        batch = _random_batch(rng, 256)
        routed, valid, orig, _ovf = route_by_flow(batch.data, 8)
        out, state = step(state, jnp.asarray(routed), jnp.uint32(now),
                          jnp.asarray(valid))
        out = np.asarray(out)
        want = oracle.step(batch, now)
        n_div = 0
        for j in range(len(routed)):
            if orig[j] < 0:
                continue
            w = want[orig[j]]
            got = (int(out[j, 0]), int(out[j, 1]), int(out[j, 2]),
                   int(row_to_numeric[out[j, 3]]), int(out[j, 4]),
                   int(out[j, 5]))
            exp = (w.verdict, w.proxy, w.ct, w.identity, w.reason, w.event)
            if got != exp:
                n_div += 1
        assert n_div == 0, f"{n_div} diverged"
        now += 3


def test_replicated_counters_agree(world):  # noqa: F811
    """Metrics/drop counters are psum-replicated: one global total."""
    state, oracle, row_to_numeric = world
    mesh = make_mesh(8)
    state = shard_state(state, mesh)
    step = make_sharded_step(mesh)
    batch = _random_batch(np.random.default_rng(3), 256)
    routed, valid, orig, _ovf = route_by_flow(batch.data, 8)
    out, state = step(state, jnp.asarray(routed), jnp.uint32(10),
                      jnp.asarray(valid))
    total = int(np.asarray(state.metrics).sum())
    assert total == int(valid.sum())  # every real packet counted once
