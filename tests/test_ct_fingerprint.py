"""Fingerprint-filtered CT probe: exactness vs the full-window probe.

The fingerprint array is a memory-traffic optimization only — every
test here asserts bit-identical semantics with the unfiltered probe,
including the adversarial cases that force the ``lax.cond`` fallbacks
(candidate overflow on lookup, expired-other-key reclaim on insert).
Reference behavior under test: ``bpf/lib/conntrack.h`` ct_lookup/
ct_create probe loop (SURVEY.md §2a:90).
"""

import jax.numpy as jnp
import numpy as np

from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch
from cilium_tpu.datapath import CTTable
from cilium_tpu.datapath.conntrack import (
    CT_ESTABLISHED,
    CT_NEW,
    CT_REPLY,
    KEY_WORDS,
    LIFETIME_SYN,
    N_CAND,
    ST_FREE,
    V_EXPIRES,
    V_STATE,
    _fp_mix,
    _fp_mix_np,
    _hash,
    _hash_np,
    _probe,
    _probe_fp,
    ct_fp_from_table,
    ct_gc,
    ct_keys_jit,
    ct_live_count,
    ct_lookup_jit,
    ct_update_jit,
)


def _flows(n, seed=0):
    rng = np.random.default_rng(seed)
    rows = [dict(src=f"10.{rng.integers(0, 200)}.{i // 250}.{i % 250 + 1}",
                 dst="10.200.0.1", sport=int(rng.integers(1024, 60000)),
                 dport=443, proto=6, flags=TCP_SYN) for i in range(n)]
    return make_batch(rows)


def _seed_table(n=512, cap=1 << 12, now=100):
    ct = CTTable.create(cap)
    hdr = jnp.asarray(_flows(n).data)
    fwd, rev = ct_keys_jit(hdr)
    res, slot, rep = ct_lookup_jit(ct, fwd, rev, jnp.uint32(now))
    ct = ct_update_jit(ct, hdr, fwd, res, slot, rep,
                       do_create=jnp.ones(n, bool),
                       proxy_port=jnp.zeros(n, jnp.uint32),
                       now=jnp.uint32(now))
    return ct, hdr, fwd, rev


class TestFingerprintProbe:
    def test_fp_probe_matches_full_probe_on_hits_and_misses(self):
        ct, hdr, fwd, rev = _seed_table()
        now = jnp.uint32(101)
        for keys in (fwd, rev):
            f0, s0 = _probe(ct.table, keys, now)
            f1, s1, ovf = _probe_fp(ct.table, ct.fp, keys, now)
            assert not bool(jnp.any(ovf))
            np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))
            np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))

    def test_live_slots_carry_key_fingerprint(self):
        ct, *_ = _seed_table()
        table = np.asarray(ct.table)
        fp = np.asarray(ct.fp)
        live = table[:, V_STATE] != ST_FREE
        expect = ct_fp_from_table(table)
        np.testing.assert_array_equal(fp[live], expect[live])
        assert (fp[~live] == 0).all()

    def test_candidate_overflow_falls_back_exact(self):
        # the trap: the true entry sits at window position N_CAND+1
        # while every slot's fingerprint matches the key — the first
        # N_CAND candidates are all false positives, so the filtered
        # probe alone would MISS; the overflow flag must fire and
        # ct_lookup's cond fallback must still find the entry
        from cilium_tpu.datapath.conntrack import ROW_WORDS

        cap = 64
        hdr = jnp.asarray(_flows(1).data)
        fwd, rev = ct_keys_jit(hdr)
        key = np.asarray(fwd)[0]
        pos = N_CAND + 1
        slot = int((_hash_np(key[None, :])[0] + pos) % cap)
        table = np.zeros((cap, ROW_WORDS), dtype=np.uint32)
        table[slot, :KEY_WORDS] = key
        table[slot, V_STATE] = 2  # ST_ESTABLISHED
        table[slot, V_EXPIRES] = 10_000
        key_fp = _fp_mix_np(_hash_np(key[None, :]))[0]
        ct = CTTable(table=jnp.asarray(table),
                     fp=jnp.full((cap,), key_fp, dtype=jnp.uint32),
                     dropped=jnp.zeros((), jnp.uint32))
        now = jnp.uint32(100)
        f1, s1, ovf = _probe_fp(ct.table, ct.fp, fwd, now)
        assert bool(ovf[0]) and not bool(f1[0])  # the trap is sprung...
        res, got_slot, rep = ct_lookup_jit(ct, fwd, rev, now)
        assert int(res[0]) == CT_ESTABLISHED  # ...and the cond saves it
        assert int(got_slot[0]) == slot

    def test_insert_reclaims_expired_other_key_slots(self):
        # fill a single-window table with flows, expire them all, and
        # insert fresh keys WITHOUT a GC sweep: the fingerprint filter
        # can't see expired-other-key slots, so the claim must ride the
        # full-loop fallback — old probe semantics (expired slots are
        # immediately claimable) preserved
        cap = 16  # one probe window == the whole table
        ct = CTTable.create(cap)
        old = jnp.asarray(_flows(8, seed=1).data)
        fwd, rev = ct_keys_jit(old)
        now = jnp.uint32(100)
        res, slot, rep = ct_lookup_jit(ct, fwd, rev, now)
        ct = ct_update_jit(ct, old, fwd, res, slot, rep,
                           do_create=jnp.ones(8, bool),
                           proxy_port=jnp.zeros(8, jnp.uint32), now=now)
        n_old = ct_live_count(ct)
        assert n_old > 0
        later = jnp.uint32(100 + LIFETIME_SYN + 1)  # all expired, unswept
        assert int(np.asarray(ct.fp != 0).sum()) == n_old  # stale fps
        new = jnp.asarray(_flows(4, seed=2).data)
        nfwd, nrev = ct_keys_jit(new)
        res, slot, rep = ct_lookup_jit(ct, nfwd, nrev, later)
        assert (np.asarray(res) == CT_NEW).all()
        ct = ct_update_jit(ct, new, nfwd, res, slot, rep,
                           do_create=jnp.ones(4, bool),
                           proxy_port=jnp.zeros(4, jnp.uint32), now=later)
        assert int(np.asarray(ct.dropped)) == 0
        res2, _s, _r = ct_lookup_jit(ct, nfwd, nrev, later)
        assert (np.asarray(res2) == CT_ESTABLISHED).all()
        # reclaimed slots' fingerprints now belong to the new keys
        table = np.asarray(ct.table)
        live = table[:, V_STATE] != ST_FREE
        np.testing.assert_array_equal(
            np.asarray(ct.fp)[live], ct_fp_from_table(table)[live])

    def test_gc_clears_fingerprints(self):
        ct, hdr, fwd, rev = _seed_table(n=64, cap=1 << 10)
        later = jnp.uint32(100 + LIFETIME_SYN + 1)
        ct2, n = ct_gc(ct, later)
        assert int(np.asarray(n)) > 0
        fp = np.asarray(ct2.fp)
        state = np.asarray(ct2.table[:, V_STATE])
        assert (fp[state == ST_FREE] == 0).all()

    def test_probe_equivalence_fuzz_through_lifecycle(self):
        """Randomized gate: across batches of inserts, refreshes,
        expiries, and GC sweeps, the fingerprint probe must equal the
        full-window probe on EVERY key, hit or miss."""
        rng = np.random.default_rng(42)
        cap = 1 << 10  # small: forces collisions + window pressure
        ct = CTTable.create(cap)
        now = 100
        universe = _flows(600, seed=7)  # ~60% occupancy at peak
        for step in range(12):
            pick = rng.choice(600, 128, replace=False)
            hdr = jnp.asarray(universe.data[pick])
            fwd, rev = ct_keys_jit(hdr)
            t = jnp.uint32(now)
            res, slot, rep = ct_lookup_jit(ct, fwd, rev, t)
            ct = ct_update_jit(ct, hdr, fwd, res, slot, rep,
                               do_create=jnp.ones(128, bool),
                               proxy_port=jnp.zeros(128, jnp.uint32),
                               now=t)
            # equivalence sweep over the WHOLE universe
            afwd, arev = ct_keys_jit(jnp.asarray(universe.data))
            for keys in (afwd, arev):
                f0, s0 = _probe(ct.table, keys, t)
                f1, s1, ovf = _probe_fp(ct.table, ct.fp, keys, t)
                f1 = np.asarray(f1) | np.asarray(ovf)  # ovf -> full
                # where no overflow, results must match exactly
                clean = ~np.asarray(ovf)
                np.testing.assert_array_equal(np.asarray(f0)[clean],
                                              np.asarray(f1)[clean])
                np.testing.assert_array_equal(
                    np.asarray(s0)[clean & np.asarray(f0)],
                    np.asarray(s1)[clean & np.asarray(f0)])
            now += rng.integers(1, 40)  # let lifetimes expire mid-run
            if step % 4 == 3:
                ct, _n = ct_gc(ct, jnp.uint32(now))

    def test_host_fp_mix_mirrors_device(self):
        keys = np.asarray(_seed_table(n=32)[2])
        h_dev = np.asarray(_fp_mix(_hash(jnp.asarray(keys))))
        h_np = _fp_mix_np(_hash_np(keys))
        np.testing.assert_array_equal(h_dev, h_np)
        assert h_np.min() >= 1 and h_np.max() <= 255
