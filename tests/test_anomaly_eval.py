"""Anomaly evaluation pipeline (BASELINE eval config #5): labeled
capture -> datapath replay -> scores -> AUC, plus the CIC-style CSV
label loader and the CLI verbs."""

import json

import numpy as np
import pytest

from cilium_tpu.ml.evaluate import (
    evaluate_capture,
    load_labels,
    synth_labeled_capture,
    train_and_evaluate,
)


def test_train_and_evaluate_end_to_end(tmp_path):
    """Small config: the pipeline's headline is now the HELD-OUT
    attack-kind AUC (training never saw that kind); the supervised
    half covers the trained kinds and the benign-novelty half must
    carry the held-out one."""
    result = train_and_evaluate(n_identities=128, train_steps=40,
                                train_batch=1024, eval_packets=8192,
                                model_out=str(tmp_path / "m.npz"),
                                workdir=str(tmp_path))
    assert result["holdout_kind"] == "exfil"
    assert result["holdout_kind"] not in result["train_kinds"]
    assert result["auc_heldout_kind"] > 0.9  # generalization, honest
    for kind in result["train_kinds"]:
        assert result["auc_by_kind"][kind] > 0.95
    assert result["auc_same_mix_smoke"] > 0.95
    assert (tmp_path / "m.npz").exists()
    # the model artifact reloads (incl. novelty stats) and re-scores
    # the held-out capture
    from cilium_tpu.ml.model import load_model
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=128, n_rules=16,
                        ct_capacity=1 << 14)
    sidecar = result["eval_pcap"].replace(".pcap", ".npz")
    again = evaluate_capture(load_model(str(tmp_path / "m.npz")), world,
                             result["eval_pcap"], sidecar)
    assert again["anomaly_auc"] > 0.9


def test_csv_label_loader(tmp_path):
    """CIC-IDS2017-style flow CSV maps 5-tuples to labels."""
    from cilium_tpu.core.packets import make_batch

    batch = make_batch([
        dict(src="10.0.0.1", dst="10.0.0.2", sport=1111, dport=80,
             proto=6),
        dict(src="10.0.0.3", dst="10.0.0.2", sport=2222, dport=22,
             proto=6),
        dict(src="10.0.0.9", dst="10.0.0.2", sport=3333, dport=443,
             proto=6),
    ])
    csv_path = tmp_path / "labels.csv"
    csv_path.write_text(
        "Source IP, Destination IP, Source Port, Destination Port,"
        " Protocol, Label\n"
        "10.0.0.1,10.0.0.2,1111,80,6,BENIGN\n"
        "10.0.0.3,10.0.0.2,2222,22,6,SSH-Patator\n")
    labels = load_labels(str(csv_path), batch.data)
    assert list(labels) == [0.0, 1.0, 0.0]  # unknown flow -> benign


def test_npz_sidecar_restores_ingest_metadata(tmp_path):
    from cilium_tpu.core.pcap import read_pcap
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 12)
    pcap = str(tmp_path / "c.pcap")
    side = str(tmp_path / "c.npz")
    synth_labeled_capture(pcap, side, world, n=2048, seed=3)
    hdr = read_pcap(pcap).data
    from cilium_tpu.core.packets import COL_DIR

    assert hdr[:, COL_DIR].max() == 0  # wire bytes carry no direction
    labels = load_labels(side, hdr)
    assert len(labels) == 2048 and labels.sum() > 0
    assert hdr[:, COL_DIR].max() == 1  # sidecar restored egress rows


def test_cli_anomaly_synth_and_score(tmp_path, capsys):
    from cilium_tpu.cli.main import main

    pcap = str(tmp_path / "x.pcap")
    labels = str(tmp_path / "x.npz")
    rc = main(["anomaly", "synth", "--pcap", pcap, "--labels", labels,
               "--number", "4096"])
    assert rc == 0
    rc = main(["anomaly", "score", "--pcap", pcap, "--labels", labels])
    assert rc == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["packets"] == 4096
    assert 0.0 <= payload["anomaly_auc"] <= 1.0
