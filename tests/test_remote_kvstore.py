"""Networked kvstore transport (VERDICT r03 item 1).

The distributed plane was protocol-complete but in-process; these
tests prove the SAME allocator/daemon/operator code runs over a
socket — including as separate OS processes — with reconnect and
lease-expiry behavior (the etcd semantics the reference leans on:
pkg/kvstore/etcd.go).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.kvstore import (
    InMemoryKVStore,
    KVStoreAllocatorBackend,
    KVStoreServer,
    RemoteKVStore,
)
from cilium_tpu.labels import LabelSet


@pytest.fixture
def server(tmp_path):
    srv = KVStoreServer(path=str(tmp_path / "kv.sock"), lease_tick=0.05)
    yield srv
    srv.close()


def _client(server, **kw):
    return RemoteKVStore(server.address, **kw)


class TestRemoteSemantics:
    def test_kv_ops_round_trip(self, server):
        c = _client(server)
        assert c.get("a") is None
        rev1 = c.update("a", b"1")
        rev2 = c.update("a", b"2")
        assert rev2 > rev1
        assert c.get("a") == b"2"
        assert c.create_only("a", b"x") is False
        assert c.create_only("b", b"3") is True
        assert c.list_prefix("") == {"a": b"2", "b": b"3"}
        assert c.delete("a") is True
        assert c.delete("a") is False
        c.close()

    def test_watch_replay_and_live_events(self, server):
        c1, c2 = _client(server), _client(server)
        c1.update("pre/x", b"1")
        seen = []
        cancel = c2.watch_prefix("pre/", lambda ev: seen.append(
            (ev.kind, ev.key, ev.value)))
        deadline = time.time() + 2
        while len(seen) < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert ("create", "pre/x", b"1") in seen  # replay
        c1.update("pre/y", b"2")
        c1.delete("pre/x")
        deadline = time.time() + 2
        while len(seen) < 3 and time.time() < deadline:
            time.sleep(0.01)
        kinds = [(k, key) for k, key, _ in seen]
        assert ("create", "pre/y") in kinds
        assert ("delete", "pre/x") in kinds
        cancel()
        c1.update("pre/z", b"3")
        time.sleep(0.1)
        assert not any(key == "pre/z" for _, key, _ in seen)
        c1.close()
        c2.close()

    def test_lease_expires_without_traffic(self, server):
        """A crashed client's leased keys must die on the server's
        ticker — no other client traffic required."""
        c = _client(server)
        c.update("leased", b"v", lease_ttl=0.15)
        c.close()  # the "crash": nobody refreshes
        c2 = _client(server)
        assert c2.get("leased") == b"v"
        time.sleep(0.4)
        assert c2.get("leased") is None
        c2.close()

    def test_keepalive_refreshes_lease(self, server):
        c = _client(server)
        c.update("hb", b"v", lease_ttl=0.2)
        for _ in range(4):
            time.sleep(0.1)
            assert c.keepalive("hb", 0.2)
        assert c.get("hb") == b"v"
        c.close()

    def test_reconnect_retries_call_and_resubscribes_watch(self, server):
        c = _client(server)
        seen = []
        c.watch_prefix("w/", lambda ev: seen.append(ev.key))
        c.update("w/a", b"1")
        # sever every connection server-side (network blip)
        for conn in list(server._conns):
            conn.close()
        # calls ride the transparent retry after re-dial
        assert c.get("w/a") == b"1"
        c.update("w/b", b"2")
        deadline = time.time() + 3
        while "w/b" not in seen and time.time() < deadline:
            time.sleep(0.01)
        assert "w/b" in seen  # the watch survived the reconnect
        assert "w/a" in seen
        c.close()


class TestClusterOverSocket:
    def test_two_daemons_agree_over_socket(self, server):
        """The r02/r03 identity-agreement test, verbatim logic, with
        networked store handles — zero changes to allocator/daemon
        code (the transport-agnostic-protocol proof)."""
        kva, kvb = _client(server), _client(server)
        da = Daemon(DaemonConfig(node_name="a", backend="interpreter"),
                    kvstore=kva)
        db_d = Daemon(DaemonConfig(node_name="b", backend="interpreter"),
                      kvstore=kvb)
        web = da.allocator.allocate(
            LabelSet.parse("k8s:app=web", "k8s:role=web"))
        deadline = time.time() + 3
        got = None
        while got is None and time.time() < deadline:
            got = db_d.allocator.lookup_by_id(web.numeric_id)
            time.sleep(0.01)
        assert got is not None and got.labels == web.labels
        web_b = db_d.allocator.allocate(
            LabelSet.parse("k8s:app=web", "k8s:role=web"))
        assert web_b.numeric_id == web.numeric_id
        da.shutdown()
        db_d.shutdown()
        kva.close()
        kvb.close()

    def test_operator_gc_over_socket(self, server):
        from cilium_tpu.operator import Operator

        kv1, kv2 = _client(server), _client(server)
        be = KVStoreAllocatorBackend(kv1, node="agent")
        num = be.allocate("k8s:app=tmp;")
        op = Operator(kv2)
        assert op.sweep()["identities-collected"] == 0
        be.release("k8s:app=tmp;")
        assert op.sweep()["identities-collected"] == 1
        be.close()
        op.close()
        kv1.close()
        kv2.close()


def _spawn_child(socket_path, node, labels, lease_ttl="0.3"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "cilium_tpu.testing.cluster_child",
         socket_path, node, labels, lease_ttl],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        text=True)


class TestOSProcesses:
    def test_three_processes_share_one_store(self, tmp_path):
        """Server + two agents as SEPARATE OS PROCESSES + operator in
        this one: agents agree on identity numerics over the socket,
        enforce the same verdict; killing an agent expires its leased
        refs so identity GC sweeps (crash recovery)."""
        from cilium_tpu.operator import Operator

        sock = str(tmp_path / "kv.sock")
        srv_proc = subprocess.Popen(
            [sys.executable, "-m", "cilium_tpu.kvstore.remote",
             "--socket", sock],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        children = []
        try:
            assert json.loads(srv_proc.stdout.readline())["address"] == \
                ["unix", sock]
            a = _spawn_child(sock, "node-a", "k8s:app=web,k8s:role=web")
            b = _spawn_child(sock, "node-b", "k8s:app=web,k8s:role=web")
            children = [a, b]
            outs = []
            for p in children:
                line = p.stdout.readline()
                assert line, p.stderr.read()
                outs.append(json.loads(line))
            by_node = {o["node"]: o for o in outs}
            # cluster-wide agreement on the numeric, same verdict
            assert by_node["node-a"]["identity"] == \
                by_node["node-b"]["identity"]
            assert by_node["node-a"]["verdict"] == [1]
            assert by_node["node-b"]["verdict"] == [1]

            op_kv = RemoteKVStore(("unix", sock))
            op = Operator(op_kv)
            # both agents alive: their web identity is referenced
            assert op.sweep()["identities-collected"] == 0

            # crash node-b; its leased refs expire, node-a's keepalive
            # holds its own
            b.kill()
            b.wait(timeout=10)
            time.sleep(1.0)  # > lease_ttl (0.3s) + server tick
            assert op.sweep()["identities-collected"] == 0  # a holds on
            a.kill()
            a.wait(timeout=10)
            deadline = time.time() + 5
            collected = 0
            while collected == 0 and time.time() < deadline:
                time.sleep(0.2)
                collected = op.sweep()["identities-collected"]
            # every agent gone -> all refs expired -> identity GC
            # sweeps web AND each agent's db endpoint identity
            assert collected >= 1
            op.close()
            op_kv.close()
        finally:
            for p in children:
                if p.poll() is None:
                    p.kill()
            srv_proc.send_signal(signal.SIGINT)
            try:
                srv_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                srv_proc.kill()

    def test_killed_and_restarted_agent_rejoins(self, tmp_path):
        """An agent that dies and comes back re-adopts the SAME
        identity numeric from the store (restore path over the
        network)."""
        sock = str(tmp_path / "kv.sock")
        srv = KVStoreServer(path=sock, lease_tick=0.05)
        try:
            a = _spawn_child(sock, "node-a", "k8s:app=web", "5.0")
            first = json.loads(a.stdout.readline())
            a.kill()
            a.wait(timeout=10)
            # restart before the (5s) lease expires: numeric survives
            a2 = _spawn_child(sock, "node-a", "k8s:app=web", "5.0")
            second = json.loads(a2.stdout.readline())
            a2.kill()
            a2.wait(timeout=10)
            assert first["identity"] == second["identity"]
        finally:
            srv.close()


class TestWatchEventBatching:
    """ISSUE 17 satellite: the server's writer drain coalesces
    CONSECUTIVE watch pushes into one ``{"wb": [...]}`` frame —
    fewer wakeups under event storms — while a LONE push stays
    byte-identical to the pre-batching wire and responses never
    reorder against the pushes around them."""

    @staticmethod
    def _push(i):
        return {"w": 1, "k": "create", "key": f"p/{i}",
                "v": None, "rev": i}

    def test_run_of_pushes_becomes_one_wb_line(self):
        from cilium_tpu.kvstore.remote import _Conn

        objs = [self._push(i) for i in range(3)]
        out = _Conn._frame_batch(objs).decode()
        lines = out.strip().split("\n")
        assert len(lines) == 1
        assert json.loads(lines[0]) == {"wb": objs}

    def test_lone_push_is_byte_identical(self):
        from cilium_tpu.kvstore.remote import _Conn

        obj = self._push(7)
        assert _Conn._frame_batch([obj]) \
            == (json.dumps(obj) + "\n").encode()

    def test_response_breaks_the_run_order_preserved(self):
        from cilium_tpu.kvstore.remote import _Conn

        resp = {"i": 5, "r": True}
        objs = [self._push(1), self._push(2), resp, self._push(3)]
        lines = [json.loads(ln) for ln in
                 _Conn._frame_batch(objs).decode().strip()
                 .split("\n")]
        assert lines == [{"wb": [self._push(1), self._push(2)]},
                         resp, self._push(3)]

    def test_burst_fans_out_in_order_e2e(self, server):
        """A mutation burst from one client reaches a watcher on
        another COMPLETE and IN ORDER through the batched wire."""
        c1, c2 = _client(server), _client(server)
        seen = []
        c2.watch_prefix("burst/", lambda ev: seen.append(ev.key),
                        replay=False)
        n = 64
        for i in range(n):
            c1.update(f"burst/{i:03d}", b"x")
        deadline = time.time() + 5
        while len(seen) < n and time.time() < deadline:
            time.sleep(0.01)
        assert seen == [f"burst/{i:03d}" for i in range(n)]
        c1.close()
        c2.close()
