"""Verdict-divergence suite: fused TPU pipeline vs sequential oracle.

The in-repo analogue of BASELINE.md's <=1% divergence-vs-eBPF gate —
gated here at 0%: every packet of every batch must agree on verdict,
proxy port, CT result, remote identity, drop reason, and event type.

Modeled on the reference's bpf/tests (golden packets through
BPF_PROG_RUN) + pkg/policy resolve tests (SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.core import TCP_ACK, TCP_FIN, TCP_SYN, make_batch
from cilium_tpu.core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_EP,
    COL_FLAGS,
    COL_PROTO,
    COL_SPORT,
    HeaderBatch,
    ip_to_words,
    N_COLS,
)
from cilium_tpu.datapath import build_state, datapath_step_jit
from cilium_tpu.datapath.lpm import compile_lpm
from cilium_tpu.identity import CachingIdentityAllocator
from cilium_tpu.labels import LabelSet
from cilium_tpu.policy import IdentityRowMap, PolicyRepository, compile_policy
from cilium_tpu.testing import OracleDatapath

WEB = LabelSet.parse("k8s:app=web")
DB = LabelSet.parse("k8s:app=db")

RULES = [
    {
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
            {"fromCIDR": ["192.168.0.0/16"],
             "toPorts": [{"ports": [{"port": "8000", "endPort": 8999}]}]},
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                          "rules": {"http": [{"method": "GET"}]}}]},
        ],
        "ingressDeny": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "22", "protocol": "TCP"}]}]},
        ],
        "egress": [
            {"toEntities": ["world"],
             "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}]}]},
        ],
    },
    {
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [
            {"toEndpoints": [{"matchLabels": {"app": "db"}}]},
            {"toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]},
        ],
    },
]

WEB_IPS = [f"10.0.1.{i}" for i in range(1, 9)]
DB_IPS = [f"10.0.2.{i}" for i in range(1, 9)]
EXT_IPS = [f"192.168.7.{i}" for i in range(1, 5)] + ["8.8.8.8", "1.1.1.1"]
ALL_IPS = WEB_IPS + DB_IPS + EXT_IPS


# function-scoped: datapath_step_jit donates the state buffers, so each
# test needs its own state (the compiled jit graph is shared anyway)
@pytest.fixture()
def world():
    alloc = CachingIdentityAllocator()
    repo = PolicyRepository(alloc)
    web_id = alloc.allocate(WEB).numeric_id
    db_id = alloc.allocate(DB).numeric_id
    world_id = alloc.allocate(LabelSet.parse("reserved:world")).numeric_id
    repo.add_obj(RULES)
    pol_web = repo.resolve(WEB)
    pol_db = repo.resolve(DB)

    ipcache = {ip + "/32": web_id for ip in WEB_IPS}
    ipcache.update({ip + "/32": db_id for ip in DB_IPS})
    # CIDR identities allocated during resolve (fromCIDR 192.168/16)
    cidr_ident = alloc.allocate_cidr("192.168.0.0/16")
    ipcache["192.168.0.0/16"] = cidr_ident.numeric_id
    ipcache["0.0.0.0/0"] = world_id  # the reference's world catch-all

    row_map = IdentityRowMap(capacity=256)
    for ident in alloc.all_identities():
        row_map.add(ident.numeric_id)
    policies = [pol_web, pol_db]  # policy row 0 = web, 1 = db
    tensors = compile_policy(policies, row_map)
    lpm = compile_lpm({c: row_map.row(i) for c, i in ipcache.items()})
    # -1 = lxcmap-miss sentinel (unregistered endpoint ids drop)
    ep_policy = np.full(4096, -1, dtype=np.int32)
    ep_policy[0] = 0  # ep 0 = a web pod
    ep_policy[1] = 1  # ep 1 = a db pod
    state = build_state(tensors, lpm, ep_policy, ct_capacity=1 << 16)
    oracle = OracleDatapath({0: pol_web, 1: pol_db}, ipcache)
    row_to_numeric = row_map.numeric_array()
    return state, oracle, row_to_numeric


def _compare(state, oracle, row_to_numeric, batch: HeaderBatch, now: int):
    out, state = datapath_step_jit(state, jnp.asarray(batch.data),
                                   jnp.uint32(now))
    out = np.asarray(out)
    want = oracle.step(batch, now)
    n_div = 0
    for i, w in enumerate(want):
        got = (int(out[i, 0]), int(out[i, 1]), int(out[i, 2]),
               int(row_to_numeric[out[i, 3]]), int(out[i, 4]),
               int(out[i, 5]))
        exp = (w.verdict, w.proxy, w.ct, w.identity, w.reason, w.event)
        if got != exp:
            n_div += 1
            if n_div <= 5:
                print(f"DIVERGE pkt {i}: {batch.describe(i)}\n"
                      f"  got  {got}\n  want {exp}")
    assert n_div == 0, f"{n_div}/{len(want)} packets diverged"
    return state


def _random_batch(rng, n) -> HeaderBatch:
    rows = []
    for _ in range(n):
        src = rng.choice(ALL_IPS)
        dst = rng.choice(ALL_IPS)
        proto = int(rng.choice([6, 6, 6, 17, 1, 47]))
        rows.append(dict(
            src=src, dst=dst,
            sport=int(rng.integers(1024, 60000)),
            dport=int(rng.choice([5432, 80, 443, 22, 53, 8080, 8443,
                                  int(rng.integers(1, 65536))])),
            proto=proto,
            flags=int(rng.choice([TCP_SYN, TCP_ACK, TCP_ACK | TCP_FIN]))
            if proto == 6 else 0,
            ep=int(rng.integers(0, 2)),
            dir=int(rng.integers(0, 2)),
        ))
    return make_batch(rows)


def test_random_traffic_zero_divergence(world):
    state, oracle, row_to_numeric = world
    rng = np.random.default_rng(42)
    now = 1000
    for step in range(6):
        batch = _random_batch(rng, 512)
        state = _compare(state, oracle, row_to_numeric, batch, now)
        now += int(rng.integers(1, 30))


def test_conversation_lifecycle(world):
    """SYN -> SYN/ACK -> data -> FIN through both endpoints' hooks,
    exercising NEW/ESTABLISHED/REPLY and the CT fast path."""
    state, oracle, row_to_numeric = world
    now = 50_000
    web, db = WEB_IPS[0], DB_IPS[0]

    def pkt(src, dst, sport, dport, flags, ep, dirn):
        return dict(src=src, dst=dst, sport=sport, dport=dport, proto=6,
                    flags=flags, ep=ep, dir=dirn)

    # the same wire packet seen at web's egress hook and db's ingress hook
    syn_out = pkt(web, db, 33000, 5432, TCP_SYN, 0, 1)
    syn_in = pkt(web, db, 33000, 5432, TCP_SYN, 1, 0)
    ack_back_out = pkt(db, web, 5432, 33000, TCP_SYN | TCP_ACK, 1, 1)
    ack_back_in = pkt(db, web, 5432, 33000, TCP_SYN | TCP_ACK, 0, 0)
    data_out = pkt(web, db, 33000, 5432, TCP_ACK, 0, 1)
    data_in = pkt(web, db, 33000, 5432, TCP_ACK, 1, 0)
    fin_out = pkt(web, db, 33000, 5432, TCP_ACK | TCP_FIN, 0, 1)
    fin_in = pkt(web, db, 33000, 5432, TCP_ACK | TCP_FIN, 1, 0)

    for step_pkts in ([syn_out, syn_in], [ack_back_out, ack_back_in],
                      [data_out, data_in], [fin_out, fin_in]):
        state = _compare(state, oracle, row_to_numeric,
                         make_batch(step_pkts), now)
        now += 1


def test_denied_then_no_ct_entry(world):
    """A denied SYN must not create CT state (reference: ct_create only
    on allow), so a retry is NEW again, not ESTABLISHED."""
    state, oracle, row_to_numeric = world
    now = 90_000
    web, db = WEB_IPS[1], DB_IPS[1]
    deny = dict(src=web, dst=db, sport=40000, dport=22, proto=6,
                flags=TCP_SYN, ep=1, dir=0)
    for _ in range(2):
        state = _compare(state, oracle, row_to_numeric,
                         make_batch([deny]), now)
        now += 1


def test_unregistered_endpoint_drops(world):
    """VERDICT r03 weak #9: an unknown endpoint id is an lxcmap miss —
    DROP with its own reason code on BOTH backends, never judged under
    endpoint 0's policy, and even a live CT entry doesn't forward it."""
    from cilium_tpu.datapath.verdict import (OUT_REASON, OUT_VERDICT,
                                             REASON_NO_ENDPOINT)
    from cilium_tpu.policy.mapstate import VERDICT_DENY

    state, oracle, row_to_numeric = world
    now = 99_000
    web, db = WEB_IPS[4], DB_IPS[4]
    pkt = lambda ep: make_batch([dict(
        src=web, dst=db, sport=40000, dport=5432, proto=6,
        flags=TCP_SYN, ep=ep, dir=0)])
    # registered endpoint: ALLOW, creates CT
    state = _compare(state, oracle, row_to_numeric, pkt(1), now)
    # unknown endpoint, SAME tuple (live CT entry): parity drop
    state = _compare(state, oracle, row_to_numeric, pkt(7), now + 1)
    out, state = datapath_step_jit(state, jnp.asarray(pkt(7).data),
                                   jnp.uint32(now + 2))
    out = np.asarray(out)
    assert int(out[0, OUT_REASON]) == REASON_NO_ENDPOINT
    assert int(out[0, OUT_VERDICT]) == VERDICT_DENY
    # forged OUT-OF-RANGE ep ids must be misses too, not gather clamps
    # onto the boundary rows (r04 review: ep 5000 clamped to 4095 and
    # 2^31 wrapped to 0 — both policy bypasses if those rows are live)
    for forged in (5000, 4095 + 1, 1 << 31):
        state = _compare(state, oracle, row_to_numeric, pkt(forged),
                         now + 3)


def test_same_flow_reply_and_forward_in_one_batch(world):
    """Reply (SYN_SENT->ESTABLISHED) and a forward retransmit of the
    same flow in ONE batch: the monotone scatter-max state combine must
    end ESTABLISHED with the long lifetime, like the sequential oracle
    (regression: snapshot .set scatter could lose the upgrade)."""
    state, oracle, row_to_numeric = world
    now = 97_000
    web, db = WEB_IPS[3], DB_IPS[3]
    syn = dict(src=web, dst=db, sport=42000, dport=5432, proto=6,
               flags=TCP_SYN, ep=1, dir=0)
    state = _compare(state, oracle, row_to_numeric, make_batch([syn]), now)
    # one batch: reply at egress + forward retransmit at ingress
    reply = dict(src=db, dst=web, sport=5432, dport=42000, proto=6,
                 flags=TCP_SYN | TCP_ACK, ep=1, dir=1)
    retrans = dict(src=web, dst=db, sport=42000, dport=5432, proto=6,
                   flags=TCP_SYN, ep=1, dir=0)
    state = _compare(state, oracle, row_to_numeric,
                     make_batch([retrans, reply]), now + 1)
    # past the SYN lifetime but within established lifetime: must hit
    state = _compare(state, oracle, row_to_numeric,
                     make_batch([dict(src=web, dst=db, sport=42000,
                                      dport=5432, proto=6, flags=TCP_ACK,
                                      ep=1, dir=0)]), now + 1000)


def test_redirect_streams_through_proxy(world):
    """L7 HTTP rule: NEW gets REDIRECT + proxy port; established packets
    of the flow keep redirecting via the CT proxy_redirect."""
    state, oracle, row_to_numeric = world
    now = 95_000
    web, db = WEB_IPS[2], DB_IPS[2]
    syn = dict(src=web, dst=db, sport=41000, dport=80, proto=6,
               flags=TCP_SYN, ep=1, dir=0)
    data = dict(src=web, dst=db, sport=41000, dport=80, proto=6,
                flags=TCP_ACK, ep=1, dir=0)
    state = _compare(state, oracle, row_to_numeric, make_batch([syn]), now)
    state = _compare(state, oracle, row_to_numeric, make_batch([data]),
                     now + 1)
