"""Socket-LB analogue (service/socklb.py): connect-time VIP->backend
translation cached per flow — SURVEY §2a's bpf_sock row.

Semantics gates: first-packet resolution equals lb_stage exactly;
cached packets resolve identically without the frontend compare;
established flows KEEP their backend across backend-set changes (the
upstream socket semantics); non-service flows pass through (and their
negative cache entries stop masking once expired); connect bursts
beyond the compact buffer still resolve correctly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.core import TCP_SYN, TCP_ACK, make_batch
from cilium_tpu.core.packets import COL_DPORT, COL_DST_IP3, COL_SPORT
from cilium_tpu.service import ServiceManager, lb_stage
from cilium_tpu.service.socklb import (SockLBTable, socklb_stage,
                                       socklb_stage_jit)


def _svcs(n_backends=3):
    m = ServiceManager()
    m.upsert("web", "172.16.0.10:80",
             [f"10.0.1.{i + 1}:8080" for i in range(n_backends)])
    m.upsert("dns", "172.16.0.53:53",
             ["10.0.2.1:5353"], protocol=17)
    return m


def _flow_rows(n, dst="172.16.0.10", dport=80, proto=6, sport0=41000):
    return make_batch([
        dict(src="10.0.9.9", dst=dst, sport=sport0 + i, dport=dport,
             proto=proto, flags=TCP_SYN, ep=1, dir=1)
        for i in range(n)
    ]).data


class TestSockLB:
    def test_first_packet_matches_lb_stage(self):
        m = _svcs()
        t = m.tensors()
        hdr = _flow_rows(64)
        ref, ref_hit, _ = lb_stage(t, jnp.asarray(hdr))
        tbl = SockLBTable.create(1 << 10)
        got, hit, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr),
                                     jnp.uint32(10))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        np.testing.assert_array_equal(np.asarray(hit),
                                      np.asarray(ref_hit))

    def test_cached_packets_resolve_identically(self):
        m = _svcs()
        t = m.tensors()
        hdr = _flow_rows(32)
        tbl = SockLBTable.create(1 << 10)
        first, _, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr),
                                     jnp.uint32(10))
        # same flows again (ACKs now): must hit the cache and produce
        # the same backends
        hdr2 = hdr.copy()
        again, hit, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr2),
                                       jnp.uint32(20))
        np.testing.assert_array_equal(np.asarray(again),
                                      np.asarray(first))
        assert np.asarray(hit).all()

    def test_established_flows_keep_backend_across_backend_change(self):
        m = _svcs(n_backends=3)
        hdr = _flow_rows(48)
        tbl = SockLBTable.create(1 << 10)
        first, _, _nb, tbl = socklb_stage(tbl, m.tensors(), jnp.asarray(hdr),
                                     jnp.uint32(10))
        first = np.asarray(first)
        # backend set changes: one backend drains away
        m.upsert("web", "172.16.0.10:80",
                 ["10.0.1.1:8080", "10.0.1.2:8080"])
        again, _, _nb, tbl = socklb_stage(tbl, m.tensors(),
                                     jnp.asarray(hdr.copy()),
                                     jnp.uint32(20))
        # cached flows keep their ORIGINAL backend (socket semantics)
        np.testing.assert_array_equal(np.asarray(again), first)
        # a NEW flow resolves against the new set only
        fresh = _flow_rows(8, sport0=55000)
        out, _, _nb, tbl = socklb_stage(tbl, m.tensors(), jnp.asarray(fresh),
                                   jnp.uint32(21))
        dsts = set(int(x) for x in np.asarray(out)[:, COL_DST_IP3])
        import ipaddress

        gone = int(ipaddress.IPv4Address("10.0.1.3"))
        assert gone in set(int(x) for x in first[:, COL_DST_IP3])
        assert gone not in dsts

    def test_non_service_flows_pass_through_and_cache_negative(self):
        m = _svcs()
        t = m.tensors()
        hdr = _flow_rows(16, dst="203.0.113.7", dport=443)
        tbl = SockLBTable.create(1 << 10)
        out, hit, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr),
                                     jnp.uint32(10))
        np.testing.assert_array_equal(np.asarray(out), hdr)
        assert not np.asarray(hit).any()
        # second pass rides the (negative) cache — still pass-through
        out2, hit2, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr.copy()),
                                       jnp.uint32(20))
        np.testing.assert_array_equal(np.asarray(out2), hdr)
        assert not np.asarray(hit2).any()

    def test_connect_burst_beyond_buffer_still_resolves(self):
        from cilium_tpu.service import socklb as mod

        m = _svcs()
        t = m.tensors()
        n = mod.CONNECT_CAP + 512  # every row a new flow: burst path
        hdr = np.asarray(_flow_rows(1)).repeat(n, axis=0)
        hdr[:, COL_SPORT] = 20000 + np.arange(n)
        ref, _, _ = lb_stage(t, jnp.asarray(hdr))
        tbl = SockLBTable.create(1 << 15)
        got, hit, _nb, tbl = socklb_stage(tbl, t, jnp.asarray(hdr),
                                     jnp.uint32(10))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        assert np.asarray(hit).all()

    def test_daemon_serves_services_through_the_flow_cache(self):
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.policy.mapstate import VERDICT_ALLOW

        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(backend=backend,
                                    ct_capacity=1 << 12))
            ep = d.add_endpoint("client", ("10.0.9.9",),
                                ["k8s:app=client"])
            d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
            d.services.upsert("web", "172.16.0.10:80",
                              ["10.0.1.1:8080"])
            d.policy_import([{
                "endpointSelector": {"matchLabels": {"app": "client"}},
                "egress": [{"toEndpoints": [{"matchLabels":
                                             {"app": "web"}}],
                            "toPorts": [{"ports": [
                                {"port": "8080",
                                 "protocol": "TCP"}]}]}],
            }])
            syn = make_batch([dict(src="10.0.9.9", dst="172.16.0.10",
                                   sport=41000, dport=80, proto=6,
                                   flags=TCP_SYN, ep=ep.id,
                                   dir=1)]).data
            ev = d.process_batch(syn, now=5)
            # DNAT before policy: judged against the backend, allowed
            assert int(ev.verdict[0]) == VERDICT_ALLOW, backend
            assert int(ev.hdr[0, COL_DPORT]) == 8080
            ev2 = d.process_batch(
                make_batch([dict(src="10.0.9.9", dst="172.16.0.10",
                                 sport=41000, dport=80, proto=6,
                                 flags=TCP_ACK, ep=ep.id,
                                 dir=1)]).data, now=6)
            assert int(ev2.verdict[0]) == VERDICT_ALLOW, backend
            assert int(ev2.hdr[0, COL_DPORT]) == 8080


class TestSockLBIntrospection:
    def test_bpf_lb_list_shows_cached_flows(self, tmp_path, capsys):
        """/map/lb + `cilium-tpu bpf lb list` decode the live flow
        cache (the `cilium bpf lb list` analogue)."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.api import APIClient, APIServer
        from cilium_tpu.cli.main import main as cli_main

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        ep = d.add_endpoint("client", ("10.0.9.9",), ["k8s:app=client"])
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        d.services.upsert("web", "172.16.0.10:80", ["10.0.1.1:8080"])
        d.process_batch(
            make_batch([dict(src="10.0.9.9", dst="172.16.0.10",
                             sport=41000, dport=80, proto=6,
                             flags=TCP_SYN, ep=ep.id, dir=1)]).data,
            now=5)
        sock = str(tmp_path / "lb.sock")
        server = APIServer(d, sock)
        server.start()
        try:
            entries = APIClient(sock).map_get("lb")
            assert any(e["vip"] == "172.16.0.10" and e["dport"] == 80
                       and e["backend"] == "10.0.1.1:8080"
                       and e["src"] == "10.0.9.9"
                       for e in entries)
            assert cli_main(["--socket", sock, "bpf", "lb",
                             "list"]) == 0
            out = capsys.readouterr().out
            assert "172.16.0.10:80" in out
            assert "backend=10.0.1.1:8080" in out
        finally:
            server.stop()
