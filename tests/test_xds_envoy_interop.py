"""Envoy-shaped xDS interop (VERDICT r04 missing item 5).

The SotW server was only ever golden-tested against straight-line
in-repo calls; this drives it with a client that behaves like Envoy's
grpc_mux over the REAL gRPC stream: initial request with empty
version, ACK every response by echoing version_info + response_nonce,
NACK with error_detail while keeping the last-good version, RECONNECT
carrying the last ACKed version into a fresh stream, and resource
unsubscription by narrowing resource_names.
"""

import json
import queue
import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.kvstore import InMemoryKVStore
from cilium_tpu.proxy.xds import TYPE_URL, serve_xds

METHOD = ("/cilium.NetworkPolicyDiscoveryService/"
          "StreamNetworkPolicies")


def _daemon():
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12),
               kvstore=InMemoryKVStore())
    d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
    return d


def _cnp(port):
    return [{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{}],
                     "toPorts": [{"ports": [
                         {"port": str(port), "protocol": "TCP"}]}]}],
    }]


class EnvoyishMux:
    """The client half of Envoy's SotW grpc_mux, minimally: one
    bidirectional stream, an outbound request queue, ACK/NACK
    bookkeeping (version_info survives NACKs, response_nonce echoes
    the last response)."""

    def __init__(self, channel, version_info=""):
        self.version_info = version_info
        self.nonce = ""
        self._out: "queue.Queue" = queue.Queue()
        self._in: "queue.Queue" = queue.Queue()
        stream = channel.stream_stream(
            METHOD,
            request_serializer=lambda o: json.dumps(o).encode(),
            response_deserializer=lambda b: json.loads(b.decode()))
        resps = stream(iter(self._out.get, None))

        def reader():  # ONE persistent reader: a timed-out recv must
            try:       # not orphan a blocked next() that would swallow
                for r in resps:  # the following response
                    self._in.put(r)
            except Exception:
                pass

        threading.Thread(target=reader, daemon=True).start()

    def send(self, resource_names=(), error_detail=None):
        req = {"type_url": TYPE_URL,
               "version_info": self.version_info,
               "response_nonce": self.nonce}
        if resource_names:
            req["resource_names"] = list(resource_names)
        if error_detail:
            req["error_detail"] = error_detail
        self._out.put(req)

    def recv(self, timeout=10.0):
        try:
            r = self._in.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("no DiscoveryResponse") from None
        self.nonce = r["nonce"]
        return r

    def ack(self, resp):
        self.version_info = resp["version_info"]

    def close(self):
        self._out.put(None)


def test_envoy_shaped_session(tmp_path):
    d = _daemon()
    addr = f"unix://{tmp_path}/xds.sock"
    server = serve_xds(d.xds, addr)
    try:
        ch = grpc.insecure_channel(addr)
        mux = EnvoyishMux(ch)
        # 1. initial request (empty version): full snapshot + ACK
        mux.send()
        r1 = mux.recv()
        assert r1["resources"] and r1["nonce"] == r1["version_info"]
        mux.ack(r1)

        # 2. ACKed and quiet; a policy import pushes a NEW version
        mux.send()
        d.policy_import(_cnp(5432))
        r2 = mux.recv()
        assert int(r2["version_info"]) > int(r1["version_info"])
        names = [res["name"] for res in r2["resources"]]
        assert any("app=db" in n or "db" in n for n in names), names

        # 3. NACK it: version_info stays at last-good, the server
        #    records the rejection and immediately RE-SERVES the
        #    rejected version (the SotW retry — the client is behind)
        mux.send(error_detail="bad listener config")
        r3 = mux.recv()
        assert r3["version_info"] == r2["version_info"]
        assert d.xds.nacks and d.xds.nacks[-1][1].startswith("bad")
        mux.ack(r3)  # accepted on retry
        d.policy_import(_cnp(5433))
        mux.send()
        r3 = mux.recv()
        assert int(r3["version_info"]) > int(r2["version_info"])
        mux.ack(r3)

        # 4. unsubscribe: narrow resource_names to one resource; the
        #    next push carries ONLY it
        keep = [res["name"] for res in r3["resources"]][:1]
        mux.send(resource_names=keep)
        d.policy_import(_cnp(5434))
        r4 = mux.recv()
        assert [res["name"] for res in r4["resources"]] == keep
        mux.ack(r4)
        mux.close()

        # 5. reconnect (Envoy restarts the stream after a drop): the
        #    fresh stream carries the last ACKed version — the server
        #    long-polls (nothing to resend) until the next change
        mux2 = EnvoyishMux(ch, version_info=mux.version_info)
        mux2.send()
        with pytest.raises(TimeoutError):
            mux2.recv(timeout=0.5)  # up to date: no spurious resend
        d.policy_import(_cnp(5435))
        r5 = mux2.recv()
        assert int(r5["version_info"]) > int(r4["version_info"])
        mux2.close()
        ch.close()
    finally:
        server.stop(0)
