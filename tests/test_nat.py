"""Egress masquerade (SNAT schema + stage; SURVEY.md §2a row 3 NAT)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.core.packets import (
    COL_DIR,
    COL_DST_IP3,
    COL_FAMILY,
    COL_SRC_IP3,
    N_COLS,
)
from cilium_tpu.service.nat import NATConfig, snat_stage_jit


def _rows(entries):
    out = np.zeros((len(entries), N_COLS), dtype=np.uint32)
    for i, (src, dst, dirn) in enumerate(entries):
        out[i, COL_SRC_IP3] = src
        out[i, COL_DST_IP3] = dst
        out[i, COL_DIR] = dirn
        out[i, COL_FAMILY] = 4
    return out


POD = 0x0A000201  # 10.0.2.1
PEER = 0x0A000101  # 10.0.1.1 (cluster-internal)
WORLD = 0x08080808  # 8.8.8.8
NODE = 0xC0A80001  # 192.168.0.1


class TestSNAT:
    def test_egress_to_world_masquerades(self):
        t = NATConfig(node_ip="192.168.0.1").compile()
        hdr, masq = snat_stage_jit(t, jnp.asarray(_rows([
            (POD, WORLD, 1),   # egress to world: masquerade
            (POD, PEER, 1),    # egress cluster-internal: keep
            (WORLD, POD, 0),   # ingress: never
        ])))
        hdr = np.asarray(hdr)
        assert list(np.asarray(masq)) == [True, False, False]
        assert hdr[0, COL_SRC_IP3] == NODE
        assert hdr[1, COL_SRC_IP3] == POD
        assert hdr[2, COL_SRC_IP3] == WORLD

    def test_empty_exclusions_masquerade_everything(self):
        """r03 review: an empty non-masquerade list padded with a
        zero row matched every destination and silently disabled
        SNAT."""
        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=()).compile()
        hdr, masq = snat_stage_jit(t, jnp.asarray(_rows([
            (POD, WORLD, 1), (POD, PEER, 1)])))
        assert list(np.asarray(masq)) == [True, True]

    def test_masquerade_without_node_ip_rejected(self):
        from cilium_tpu.agent import Daemon, DaemonConfig

        with pytest.raises(ValueError, match="node_ip"):
            Daemon(DaemonConfig(backend="interpreter",
                                masquerade=True))

    def test_disabled_is_identity(self):
        t = NATConfig(node_ip="192.168.0.1", enabled=False).compile()
        rows = _rows([(POD, WORLD, 1)])
        hdr, masq = snat_stage_jit(t, jnp.asarray(rows))
        np.testing.assert_array_equal(np.asarray(hdr), rows)
        assert not np.asarray(masq).any()

    def test_port_allocation_resolves_sport_collision(self):
        """DIVERGENCES #17 closed: two local endpoints sharing a
        sport toward one destination get DISTINCT node ports from the
        per-node pool, and replies to each reverse-translate to the
        right pod."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch
        from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3,
                                             COL_SPORT, COL_SRC_IP3)
        from cilium_tpu.monitor.api import MSG_TRACE
        from cilium_tpu.service.nat import NAT_PORT_MIN

        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(
                backend=backend, ct_capacity=1 << 12, masquerade=True,
                node_ip="192.168.0.1",
                non_masquerade_cidrs=("10.0.0.0/8",)))
            a = d.add_endpoint("pod-a", ("10.0.2.1",), ["k8s:app=a"])
            b = d.add_endpoint("pod-b", ("10.0.2.2",), ["k8s:app=b"])
            d.start()
            mk = lambda ep, src: make_batch([dict(
                src=src, dst="8.8.8.8", sport=40000, dport=53,
                proto=17, ep=ep.id, dir=1)]).data
            ev_a = d.process_batch(mk(a, "10.0.2.1"), now=5)
            ev_b = d.process_batch(mk(b, "10.0.2.2"), now=6)
            pa = int(ev_a.hdr[0, COL_SPORT])
            pb = int(ev_b.hdr[0, COL_SPORT])
            node = int(ev_a.hdr[0, COL_SRC_IP3])
            assert node == int(
                __import__("ipaddress").IPv4Address("192.168.0.1"))
            assert pa != pb, backend  # the old collision
            assert pa >= NAT_PORT_MIN and pb >= NAT_PORT_MIN

            # replies to each allocated port restore the right pod
            reply = lambda p: make_batch([dict(
                src="8.8.8.8", dst="192.168.0.1", sport=53, dport=p,
                proto=17, ep=a.id, dir=0)]).data
            ra = d.process_batch(reply(pa), now=7)
            rb = d.process_batch(reply(pb), now=8)
            assert int(ra.hdr[0, COL_DST_IP3]) == int(
                __import__("ipaddress").IPv4Address("10.0.2.1")), backend
            assert int(rb.hdr[0, COL_DST_IP3]) == int(
                __import__("ipaddress").IPv4Address("10.0.2.2")), backend
            assert int(ra.hdr[0, COL_DPORT]) == 40000
            # replies hit CT as REPLY of the post-NAT entry (TRACE)
            assert int(ra.msg_type[0]) == MSG_TRACE, backend

    def test_port_allocation_is_stable_per_flow(self):
        """Repeat packets of one flow keep their allocated port (the
        NAT map remembers the translation)."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import make_batch
        from cilium_tpu.core.packets import COL_SPORT

        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12, masquerade=True,
            node_ip="192.168.0.1"))
        a = d.add_endpoint("pod-a", ("10.0.2.1",), ["k8s:app=a"])
        d.start()
        mk = lambda: make_batch([dict(
            src="10.0.2.1", dst="8.8.8.8", sport=41000, dport=53,
            proto=17, ep=a.id, dir=1)]).data
        p1 = int(d.process_batch(mk(), now=5).hdr[0, COL_SPORT])
        p2 = int(d.process_batch(mk(), now=50).hdr[0, COL_SPORT])
        assert p1 == p2

    def test_tpu_and_interpreter_agree_on_allocated_ports(self):
        """Backend parity: same flows (distinct batches) -> same
        allocated ports (same hash, same probe order)."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import make_batch
        from cilium_tpu.core.packets import COL_SPORT

        ports = {}
        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(
                backend=backend, ct_capacity=1 << 12, masquerade=True,
                node_ip="192.168.0.1"))
            a = d.add_endpoint("pod-a", ("10.0.2.1",), ["k8s:app=a"])
            d.start()
            got = []
            for i in range(6):
                pkt = make_batch([dict(
                    src="10.0.2.1", dst="8.8.8.8", sport=42000 + i,
                    dport=53, proto=17, ep=a.id, dir=1)]).data
                got.append(int(
                    d.process_batch(pkt, now=5 + i).hdr[0, COL_SPORT]))
            ports[backend] = got
        assert ports["tpu"] == ports["interpreter"]

    def test_contended_slot_same_batch_backend_parity(self):
        """r04 review: two NEW flows in ONE batch whose hashes collide
        on a slot must get the SAME ports on both backends (the device
        awards contended slots to the lowest batch row — sequential
        order)."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import make_batch
        from cilium_tpu.core.packets import COL_SPORT
        from cilium_tpu.service.nat import (NAT_DEFAULT_CAPACITY,
                                            _nat_hash_py)

        import ipaddress
        mask = NAT_DEFAULT_CAPACITY - 1
        src1 = int(ipaddress.IPv4Address("10.0.2.1"))
        src2 = int(ipaddress.IPv4Address("10.0.2.2"))
        dst = int(ipaddress.IPv4Address("8.8.8.8"))
        dp = (53 << 8) | 17
        h1 = _nat_hash_py((src1, 40000, dst, dp)) & mask
        s2 = next(s for s in range(40000, 60000)
                  if (_nat_hash_py((src2, s, dst, dp)) & mask) == h1)

        ports = {}
        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(
                backend=backend, ct_capacity=1 << 12, masquerade=True,
                node_ip="192.168.0.1"))
            a = d.add_endpoint("pa", ("10.0.2.1",), ["k8s:app=a"])
            b = d.add_endpoint("pb", ("10.0.2.2",), ["k8s:app=b"])
            d.start()
            batch = make_batch([
                dict(src="10.0.2.1", dst="8.8.8.8", sport=40000,
                     dport=53, proto=17, ep=a.id, dir=1),
                dict(src="10.0.2.2", dst="8.8.8.8", sport=s2,
                     dport=53, proto=17, ep=b.id, dir=1),
            ]).data
            ev = d.process_batch(batch, now=5)
            ports[backend] = [int(p) for p in ev.hdr[:, COL_SPORT]]
        assert ports["tpu"] == ports["interpreter"]
        assert ports["tpu"][0] != ports["tpu"][1]

    def test_existing_mapping_beats_expired_earlier_slot(self):
        """r04 review: a live flow's port must NOT change when an
        earlier-probed slot expires — the full-window match scan runs
        before any claim."""
        import jax.numpy as jnp

        from cilium_tpu.service.nat import (NATConfig, NATTable,
                                            NAT_PORT_MIN, NV_EXPIRES,
                                            snat_egress)
        from cilium_tpu.core import make_batch
        from cilium_tpu.core.packets import COL_SPORT
        from cilium_tpu.datapath.conntrack import CTTable

        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=()).compile()
        tbl = NATTable.create(1 << 10)
        ct = CTTable.create(1 << 10)
        pkt = make_batch([dict(src="10.0.2.1", dst="8.8.8.8",
                               sport=40000, dport=53, proto=17,
                               ep=1, dir=1)]).data
        hdr1, tbl, _drop = snat_egress(tbl, t, ct, jnp.asarray(pkt),
                                       jnp.uint32(100))
        p1 = int(np.asarray(hdr1)[0, COL_SPORT])
        slot = p1 - NAT_PORT_MIN
        # expire a DIFFERENT slot earlier in the probe window — if the
        # flow hashed directly to its slot, seed an expired entry one
        # before it and re-hash from there is moot; instead force the
        # general case: mark every other slot expired (they are: the
        # table is empty), and verify the mapping is stable anyway
        from cilium_tpu.service.nat import NAT_LIFETIME_NONTCP

        hdr2, tbl, _drop = snat_egress(tbl, t, ct, jnp.asarray(pkt),
                                       jnp.uint32(250))
        assert int(np.asarray(hdr2)[0, COL_SPORT]) == p1
        assert int(np.asarray(tbl.table)[slot, NV_EXPIRES]) == \
            250 + NAT_LIFETIME_NONTCP

    def test_nat_survives_checkpoint_restore(self, tmp_path):
        """r04 review: replies to allocated node ports must keep
        reverse-translating across an agent restart."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import make_batch
        from cilium_tpu.core.packets import COL_DST_IP3, COL_SPORT

        import ipaddress
        state_dir = str(tmp_path / "st")
        cfg = dict(backend="tpu", ct_capacity=1 << 12, masquerade=True,
                   node_ip="192.168.0.1", state_dir=state_dir)
        d = Daemon(DaemonConfig(**cfg))
        a = d.add_endpoint("pa", ("10.0.2.1",), ["k8s:app=a"])
        d.start()
        out = make_batch([dict(src="10.0.2.1", dst="8.8.8.8",
                               sport=40000, dport=53, proto=17,
                               ep=a.id, dir=1)]).data
        p = int(d.process_batch(out, now=5).hdr[0, COL_SPORT])
        d.checkpoint(state_dir)

        d2 = Daemon(DaemonConfig(**cfg))
        assert d2.restore(state_dir)
        reply = make_batch([dict(src="8.8.8.8", dst="192.168.0.1",
                                 sport=53, dport=p, proto=17,
                                 ep=a.id, dir=0)]).data
        ev = d2.process_batch(reply, now=8)
        assert int(ev.hdr[0, COL_DST_IP3]) == int(
            ipaddress.IPv4Address("10.0.2.1"))
        # pressure signal surfaces in status
        assert "nat" in d2.status()
        assert d2.status()["nat"]["alloc-failed"] == 0

    def test_disabled_is_identity_ct_aware_path(self):
        """ADVICE r03 (low): apply_masquerade (the CT-aware stage the
        loader dispatches) must honor NATTensors.enabled like
        snat_stage does."""
        from cilium_tpu.datapath.verdict import apply_masquerade_jit
        from cilium_tpu.testing.fixtures import build_world

        world = build_world(n_identities=8, n_rules=2,
                            ct_capacity=1 << 10)
        t = NATConfig(node_ip="192.168.0.1", enabled=False).compile()
        rows = _rows([(POD, WORLD, 1)])
        hdr = apply_masquerade_jit(world.state.ct, t,
                                   jnp.asarray(rows), jnp.uint32(5))
        np.testing.assert_array_equal(np.asarray(hdr), rows)
        # interpreter backend parity
        from cilium_tpu.datapath.loader import InterpreterLoader

        il = InterpreterLoader()
        out, dropped = il.masquerade(t, rows, 5)
        np.testing.assert_array_equal(out, rows)
        assert not dropped.any()

    def test_inbound_reply_is_never_masqueraded(self):
        """r03 review: stateless SNAT corrupted replies of INBOUND
        connections.  The CT-aware stage keeps their source, and the
        reply still matches the existing CT entry (TRACE, not a new
        flow).  Both backends agree."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, TCP_ACK, make_batch
        from cilium_tpu.core.packets import COL_SRC_IP3
        from cilium_tpu.monitor.api import MSG_TRACE

        outs = {}
        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(backend=backend,
                                    ct_capacity=1 << 12,
                                    masquerade=True,
                                    node_ip="192.168.0.1"))
            ep = d.add_endpoint("srv-1", ("10.0.2.1",),
                                ["k8s:app=srv"])
            d.policy_import([{
                "endpointSelector": {"matchLabels": {"app": "srv"}},
                "ingress": [{"fromEntities": ["world"],
                             "toPorts": [{"ports": [
                                 {"port": "443",
                                  "protocol": "TCP"}]}]}],
            }])
            d.start()
            # inbound connection from the world
            evb1 = d.process_batch(make_batch([dict(
                src="8.8.8.8", dst="10.0.2.1", sport=50000, dport=443,
                proto=6, flags=TCP_SYN, ep=ep.id, dir=0)]).data,
                now=10)
            assert list(evb1.verdict) == [1]
            # the pod's reply: egress to a non-internal destination —
            # the naive masquerade would rewrite it
            evb2 = d.process_batch(make_batch([dict(
                src="10.0.2.1", dst="8.8.8.8", sport=443, dport=50000,
                proto=6, flags=TCP_ACK, ep=ep.id, dir=1)]).data,
                now=11)
            outs[backend] = (list(evb2.verdict), list(evb2.msg_type),
                             int(evb2.hdr[0, COL_SRC_IP3]))
            d.shutdown()
        for backend, (verdict, msg, src) in outs.items():
            assert verdict == [1], backend
            assert msg == [MSG_TRACE], backend  # matched existing CT
            assert src == POD, (backend, hex(src))  # source KEPT
        assert outs["tpu"] == outs["interpreter"]

    def test_daemon_masquerade_end_to_end(self):
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                masquerade=True,
                                node_ip="192.168.0.1"))
        ep = d.add_endpoint("client-1", ("10.0.2.1",),
                            ["k8s:app=client"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "client"}},
            "egress": [{"toEntities": ["world"]}],
        }])
        d.start()
        evb = d.process_batch(make_batch([dict(
            src="10.0.2.1", dst="8.8.8.8", sport=41000, dport=443,
            proto=6, flags=TCP_SYN, ep=ep.id, dir=1)]).data, now=10)
        assert list(evb.verdict) == [1]
        # the monitor sees the post-NAT source (node IP)
        from cilium_tpu.core.packets import COL_SRC_IP3

        assert int(evb.hdr[0, COL_SRC_IP3]) == NODE
        d.shutdown()

    def test_ct_tracks_post_nat_tuple(self):
        """The CT entry carries the post-NAT tuple so replies (to the
        node IP) match it — the reverse-translation anchor."""
        from cilium_tpu.datapath import datapath_step_jit
        from cilium_tpu.datapath.conntrack import ct_entries_from_snapshot
        from cilium_tpu.testing.fixtures import build_world

        world = build_world(n_identities=16, n_rules=2,
                            ct_capacity=1 << 10)
        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=("10.0.0.0/8",)).compile()
        rows = _rows([(POD, WORLD, 1)])
        rows[0, 8] = 41000  # sport
        rows[0, 9] = 53  # dport
        rows[0, 10] = 17  # udp
        hdr, _ = snat_stage_jit(t, jnp.asarray(rows))
        out, state = datapath_step_jit(world.state, hdr,
                                       jnp.uint32(10))
        entries = ct_entries_from_snapshot(np.asarray(state.ct.table))
        srcs = {e["src"] for e in entries}
        assert "192.168.0.1" in srcs  # post-NAT source tracked


class TestNATMapDisplay:
    def test_nat_entries_decode_and_rest_surface(self, tmp_path):
        """`cilium bpf nat list` (r04): live NAT slots decode to the
        original tuple + allocated node port, served over /map/nat."""
        import jax.numpy as jnp

        from cilium_tpu.api import APIClient, APIServer
        from cilium_tpu.core import make_batch
        from cilium_tpu.service.nat import (NATConfig, NATTable,
                                            NAT_PORT_MIN,
                                            nat_entries_from_snapshot,
                                            snat_egress)
        from cilium_tpu.datapath.conntrack import CTTable

        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=()).compile()
        tbl = NATTable.create(1 << 10)
        ct = CTTable.create(1 << 10)
        pkt = make_batch([dict(src="10.0.2.1", dst="8.8.8.8",
                               sport=40000, dport=53, proto=17,
                               ep=1, dir=1)]).data
        _hdr, tbl, _drop = snat_egress(tbl, t, ct, jnp.asarray(pkt),
                                jnp.uint32(100))
        [e] = nat_entries_from_snapshot(np.asarray(tbl.table))
        assert e["src"] == "10.0.2.1" and e["sport"] == 40000
        assert e["dst"] == "8.8.8.8" and e["dport"] == 53
        assert e["proto"] == 17 and e["node_port"] >= NAT_PORT_MIN

        # REST: a masquerading daemon serves the same view
        from cilium_tpu.agent import Daemon, DaemonConfig

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                masquerade=True,
                                node_ip="192.168.0.1",
                                non_masquerade_cidrs=("10.0.0.0/8",)))
        d.add_endpoint("app-1", ("10.0.2.1",), ["k8s:app=app"])
        sock = str(tmp_path / "api.sock")
        server = APIServer(d, sock)
        server.start()
        try:
            c = APIClient(sock)
            assert c.map_get("nat") == []  # no egress traffic yet
        finally:
            server.stop()
            d.shutdown()
