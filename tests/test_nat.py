"""Egress masquerade (SNAT schema + stage; SURVEY.md §2a row 3 NAT)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.core.packets import (
    COL_DIR,
    COL_DST_IP3,
    COL_FAMILY,
    COL_SRC_IP3,
    N_COLS,
)
from cilium_tpu.service.nat import NATConfig, snat_stage_jit


def _rows(entries):
    out = np.zeros((len(entries), N_COLS), dtype=np.uint32)
    for i, (src, dst, dirn) in enumerate(entries):
        out[i, COL_SRC_IP3] = src
        out[i, COL_DST_IP3] = dst
        out[i, COL_DIR] = dirn
        out[i, COL_FAMILY] = 4
    return out


POD = 0x0A000201  # 10.0.2.1
PEER = 0x0A000101  # 10.0.1.1 (cluster-internal)
WORLD = 0x08080808  # 8.8.8.8
NODE = 0xC0A80001  # 192.168.0.1


class TestSNAT:
    def test_egress_to_world_masquerades(self):
        t = NATConfig(node_ip="192.168.0.1").compile()
        hdr, masq = snat_stage_jit(t, jnp.asarray(_rows([
            (POD, WORLD, 1),   # egress to world: masquerade
            (POD, PEER, 1),    # egress cluster-internal: keep
            (WORLD, POD, 0),   # ingress: never
        ])))
        hdr = np.asarray(hdr)
        assert list(np.asarray(masq)) == [True, False, False]
        assert hdr[0, COL_SRC_IP3] == NODE
        assert hdr[1, COL_SRC_IP3] == POD
        assert hdr[2, COL_SRC_IP3] == WORLD

    def test_empty_exclusions_masquerade_everything(self):
        """r03 review: an empty non-masquerade list padded with a
        zero row matched every destination and silently disabled
        SNAT."""
        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=()).compile()
        hdr, masq = snat_stage_jit(t, jnp.asarray(_rows([
            (POD, WORLD, 1), (POD, PEER, 1)])))
        assert list(np.asarray(masq)) == [True, True]

    def test_masquerade_without_node_ip_rejected(self):
        from cilium_tpu.agent import Daemon, DaemonConfig

        with pytest.raises(ValueError, match="node_ip"):
            Daemon(DaemonConfig(backend="interpreter",
                                masquerade=True))

    def test_disabled_is_identity(self):
        t = NATConfig(node_ip="192.168.0.1", enabled=False).compile()
        rows = _rows([(POD, WORLD, 1)])
        hdr, masq = snat_stage_jit(t, jnp.asarray(rows))
        np.testing.assert_array_equal(np.asarray(hdr), rows)
        assert not np.asarray(masq).any()

    def test_disabled_is_identity_ct_aware_path(self):
        """ADVICE r03 (low): apply_masquerade (the CT-aware stage the
        loader dispatches) must honor NATTensors.enabled like
        snat_stage does."""
        from cilium_tpu.datapath.verdict import apply_masquerade_jit
        from cilium_tpu.testing.fixtures import build_world

        world = build_world(n_identities=8, n_rules=2,
                            ct_capacity=1 << 10)
        t = NATConfig(node_ip="192.168.0.1", enabled=False).compile()
        rows = _rows([(POD, WORLD, 1)])
        hdr = apply_masquerade_jit(world.state.ct, t,
                                   jnp.asarray(rows), jnp.uint32(5))
        np.testing.assert_array_equal(np.asarray(hdr), rows)
        # interpreter backend parity
        from cilium_tpu.datapath.loader import InterpreterLoader

        il = InterpreterLoader()
        np.testing.assert_array_equal(il.masquerade(t, rows, 5), rows)

    def test_inbound_reply_is_never_masqueraded(self):
        """r03 review: stateless SNAT corrupted replies of INBOUND
        connections.  The CT-aware stage keeps their source, and the
        reply still matches the existing CT entry (TRACE, not a new
        flow).  Both backends agree."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, TCP_ACK, make_batch
        from cilium_tpu.core.packets import COL_SRC_IP3
        from cilium_tpu.monitor.api import MSG_TRACE

        outs = {}
        for backend in ("tpu", "interpreter"):
            d = Daemon(DaemonConfig(backend=backend,
                                    ct_capacity=1 << 12,
                                    masquerade=True,
                                    node_ip="192.168.0.1"))
            ep = d.add_endpoint("srv-1", ("10.0.2.1",),
                                ["k8s:app=srv"])
            d.policy_import([{
                "endpointSelector": {"matchLabels": {"app": "srv"}},
                "ingress": [{"fromEntities": ["world"],
                             "toPorts": [{"ports": [
                                 {"port": "443",
                                  "protocol": "TCP"}]}]}],
            }])
            d.start()
            # inbound connection from the world
            evb1 = d.process_batch(make_batch([dict(
                src="8.8.8.8", dst="10.0.2.1", sport=50000, dport=443,
                proto=6, flags=TCP_SYN, ep=ep.id, dir=0)]).data,
                now=10)
            assert list(evb1.verdict) == [1]
            # the pod's reply: egress to a non-internal destination —
            # the naive masquerade would rewrite it
            evb2 = d.process_batch(make_batch([dict(
                src="10.0.2.1", dst="8.8.8.8", sport=443, dport=50000,
                proto=6, flags=TCP_ACK, ep=ep.id, dir=1)]).data,
                now=11)
            outs[backend] = (list(evb2.verdict), list(evb2.msg_type),
                             int(evb2.hdr[0, COL_SRC_IP3]))
            d.shutdown()
        for backend, (verdict, msg, src) in outs.items():
            assert verdict == [1], backend
            assert msg == [MSG_TRACE], backend  # matched existing CT
            assert src == POD, (backend, hex(src))  # source KEPT
        assert outs["tpu"] == outs["interpreter"]

    def test_daemon_masquerade_end_to_end(self):
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                masquerade=True,
                                node_ip="192.168.0.1"))
        ep = d.add_endpoint("client-1", ("10.0.2.1",),
                            ["k8s:app=client"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "client"}},
            "egress": [{"toEntities": ["world"]}],
        }])
        d.start()
        evb = d.process_batch(make_batch([dict(
            src="10.0.2.1", dst="8.8.8.8", sport=41000, dport=443,
            proto=6, flags=TCP_SYN, ep=ep.id, dir=1)]).data, now=10)
        assert list(evb.verdict) == [1]
        # the monitor sees the post-NAT source (node IP)
        from cilium_tpu.core.packets import COL_SRC_IP3

        assert int(evb.hdr[0, COL_SRC_IP3]) == NODE
        d.shutdown()

    def test_ct_tracks_post_nat_tuple(self):
        """The CT entry carries the post-NAT tuple so replies (to the
        node IP) match it — the reverse-translation anchor."""
        from cilium_tpu.datapath import datapath_step_jit
        from cilium_tpu.datapath.conntrack import ct_entries_from_snapshot
        from cilium_tpu.testing.fixtures import build_world

        world = build_world(n_identities=16, n_rules=2,
                            ct_capacity=1 << 10)
        t = NATConfig(node_ip="192.168.0.1",
                      non_masquerade_cidrs=("10.0.0.0/8",)).compile()
        rows = _rows([(POD, WORLD, 1)])
        rows[0, 8] = 41000  # sport
        rows[0, 9] = 53  # dport
        rows[0, 10] = 17  # udp
        hdr, _ = snat_stage_jit(t, jnp.asarray(rows))
        out, state = datapath_step_jit(world.state, hdr,
                                       jnp.uint32(10))
        entries = ct_entries_from_snapshot(np.asarray(state.ct.table))
        srcs = {e["src"] for e in entries}
        assert "192.168.0.1" in srcs  # post-NAT source tracked
