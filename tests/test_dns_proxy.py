"""Wire-level DNS proxy (reference: pkg/fqdn/dnsproxy): UDP queries
verdict against the dns L7 rules, denied names answer REFUSED,
allowed answers feed the fqdn cache and mint the identities toFQDNs
selectors match.
"""

import socket
import struct
import threading
import time

import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.datapath.verdict import (REASON_FORWARDED,
                                         REASON_POLICY_DEFAULT_DENY)
from cilium_tpu.proxy.dnslistener import (parse_answers, parse_query,
                                          refused_response)

NS = "k8s:io.kubernetes.pod.namespace=default"


def _query(name: str, txid=0x1234, qtype=1) -> bytes:
    q = struct.pack("!HHHHHH", txid, 0x0100, 1, 0, 0, 0)
    for label in name.split("."):
        q += bytes([len(label)]) + label.encode()
    return q + b"\x00" + struct.pack("!HH", qtype, 1)


def _answer(query: bytes, ips, ttl=60) -> bytes:
    """Stub resolver response: echo question + one A RR per ip,
    owner via compression pointer to the question name."""
    txid = query[:2]
    hdr = txid + struct.pack("!HHHHH", 0x8180, 1, len(ips), 0, 0)
    # question section copied verbatim
    i = 12
    while query[i] != 0:
        i += 1 + query[i]
    question = query[12:i + 5]
    body = b""
    for ip in ips:
        body += (b"\xc0\x0c"  # pointer to offset 12 (the qname)
                 + struct.pack("!HHIH", 1, 1, ttl, 4)
                 + socket.inet_aton(ip))
    return hdr + question + body


class StubResolver:
    """A UDP resolver answering every A query from a fixed table."""

    def __init__(self, table):
        self.table = table
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.settimeout(0.2)
        self.address = self.sock.getsockname()
        self._stop = threading.Event()
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                buf, client = self.sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                return
            _, name, _ = parse_query(buf)
            self.sock.sendto(_answer(buf, self.table.get(name, [])),
                             client)

    def close(self):
        self._stop.set()
        self.sock.close()


def _world():
    d = Daemon(DaemonConfig(backend="interpreter",
                            ct_capacity=1 << 12))
    d.add_endpoint("cli", ("10.0.9.9",), ["k8s:app=cli", NS])
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "cli"}},
        "egress": [{
            "toPorts": [{
                "ports": [{"port": "53", "protocol": "UDP"}],
                "rules": {"dns": [{"matchPattern": "*.example.com"}]},
            }],
        }, {
            "toFQDNs": [{"matchName": "api.example.com"}],
            "toPorts": [{"ports": [{"port": "443",
                                    "protocol": "TCP"}]}],
        }],
    }])
    return d


def _dns_ask(addr, name: str) -> bytes:
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as c:
        c.settimeout(3.0)
        c.sendto(_query(name), addr)
        resp, _ = c.recvfrom(4096)
    return resp


class TestWireParsing:
    def test_query_roundtrip(self):
        txid, name, qtype = parse_query(_query("api.example.com"))
        assert (txid, name, qtype) == (0x1234, "api.example.com", 1)

    def test_answers_with_compression(self):
        q = _query("api.example.com")
        resp = _answer(q, ["203.0.113.7", "203.0.113.8"], ttl=90)
        assert parse_answers(resp) == [
            ("api.example.com", "203.0.113.7", 90),
            ("api.example.com", "203.0.113.8", 90)]

    def test_refused_echoes_question(self):
        q = _query("evil.test")
        r = refused_response(q)
        assert r[:2] == q[:2]
        flags = struct.unpack("!H", r[2:4])[0]
        assert flags & 0x8000 and flags & 0xF == 5
        _, name, _ = parse_query(r)
        assert name == "evil.test"


class TestDNSProxyEndToEnd:
    def test_allowed_query_feeds_fqdn_and_policy(self):
        d = _world()
        resolver = StubResolver(
            {"api.example.com": ["203.0.113.7"]})
        try:
            addrs = d.start_dns_proxy(resolver.address)
            assert addrs, "a DNS redirect port must exist"
            addr = next(iter(addrs.values()))
            resp = _dns_ask(addr, "api.example.com")
            assert parse_answers(resp) == [
                ("api.example.com", "203.0.113.7", 60)]
            # the observed answer minted a toFQDNs identity: traffic
            # to the resolved IP now forwards
            deadline = time.time() + 5
            while time.time() < deadline:
                ep = d.endpoints.lookup_by_ip("10.0.9.9")
                ev = d.process_batch(make_batch([
                    dict(src="10.0.9.9", dst="203.0.113.7",
                         sport=41000, dport=443, proto=6,
                         flags=TCP_SYN, ep=ep.id, dir=1)
                ]).data, now=50)
                if int(ev.reason[0]) == REASON_FORWARDED:
                    break
                time.sleep(0.1)
            assert int(ev.reason[0]) == REASON_FORWARDED
        finally:
            resolver.close()
            stats = d.stop_dns_proxy()
            assert sum(s["queries"] for s in stats.values()) == 1

    def test_denied_name_refused_and_never_resolves(self):
        d = _world()
        resolver = StubResolver({"evil.test": ["198.51.100.66"]})
        try:
            addrs = d.start_dns_proxy(resolver.address)
            addr = next(iter(addrs.values()))
            resp = _dns_ask(addr, "evil.test")
            flags = struct.unpack("!H", resp[2:4])[0]
            assert flags & 0xF == 5  # REFUSED
            # nothing observed -> the IP stays outside every peer set
            ep = d.endpoints.lookup_by_ip("10.0.9.9")
            ev = d.process_batch(make_batch([
                dict(src="10.0.9.9", dst="198.51.100.66",
                     sport=42000, dport=443, proto=6, flags=TCP_SYN,
                     ep=ep.id, dir=1)
            ]).data, now=50)
            assert int(ev.reason[0]) == REASON_POLICY_DEFAULT_DENY
        finally:
            resolver.close()
            d.stop_dns_proxy()
