"""The cilium connectivity-test analogue (BASELINE config 1): the full
scenario matrix must pass on both backends, and the CLI verb exits 0.
"""

import pytest

from cilium_tpu.testing.connectivity import (format_results,
                                             run_connectivity_tests)


@pytest.mark.parametrize("backend", ["tpu", "interpreter"])
def test_connectivity_matrix(backend):
    res = run_connectivity_tests(backend)
    failed = [r for r in res if not r.ok]
    assert not failed, format_results(res)
    # the matrix covers the BASELINE config-1 surface
    scenarios = {r.scenario for r in res}
    assert {"no-policies", "client-ingress-l3", "client-ingress-l4",
            "all-ingress-deny", "client-egress-l4",
            "to-entities-world", "echo-ingress-l7",
            "echo-ingress-mutual-auth"} <= scenarios


def test_cli_verb_exits_zero(capsys):
    from cilium_tpu.cli.main import main
    rc = main(["connectivity", "test"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Test Summary" in out and "FAIL" not in out
