"""Local redirect policy (CiliumLocalRedirectPolicy analogue):
traffic to a frontend address redirects to node-LOCAL backends
resolved by selector (the node-local DNS cache pattern), riding the
ordinary service DNAT path.
"""

import ipaddress

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import make_batch
from cilium_tpu.core.packets import COL_DPORT, COL_DST_IP3

LRP = {
    "kind": "CiliumLocalRedirectPolicy",
    "metadata": {"name": "nodelocaldns", "namespace": "kube-system"},
    "spec": {
        "redirectFrontend": {"addressMatcher": {
            "ip": "169.254.20.10",
            "toPorts": [{"port": "53", "protocol": "UDP"}],
        }},
        "redirectBackend": {
            "localEndpointSelector": {
                "matchLabels": {"k8s-app": "node-local-dns"}},
            "toPorts": [{"port": "5353"}],
        },
    },
}


def _ip(word):
    return str(ipaddress.IPv4Address(int(word)))


def _world():
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
    client = d.add_endpoint("app", ("10.0.1.1",), ["k8s:app=web"])
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toEntities": ["all"]}],
    }])
    return d, client


def _dns(ep, sport):
    return make_batch([
        dict(src="10.0.1.1", dst="169.254.20.10", sport=sport,
             dport=53, proto=17, flags=0, ep=ep.id, dir=1)
    ]).data


class TestLocalRedirect:
    def test_redirects_to_local_backend(self):
        d, client = _world()
        d.add_endpoint(
            "dns-cache", ("10.0.0.53",),
            ["k8s:k8s-app=node-local-dns",
             "k8s:io.kubernetes.pod.namespace=kube-system"])
        hub = d.k8s_watchers()
        hub.dispatch("add", LRP)
        ev = d.process_batch(_dns(client, 40000), now=5)
        assert _ip(ev.hdr[0, COL_DST_IP3]) == "10.0.0.53"
        assert int(ev.hdr[0, COL_DPORT]) == 5353

    def test_backend_appears_later(self):
        """Policy lands before the local backend pod: installs as soon
        as the endpoint churn resyncs the selector."""
        d, client = _world()
        hub = d.k8s_watchers()
        hub.dispatch("add", LRP)
        # no local backend yet: traffic passes through un-redirected
        ev = d.process_batch(_dns(client, 41000), now=5)
        assert _ip(ev.hdr[0, COL_DST_IP3]) == "169.254.20.10"
        d.add_endpoint(
            "dns-cache", ("10.0.0.53",),
            ["k8s:k8s-app=node-local-dns",
             "k8s:io.kubernetes.pod.namespace=kube-system"])
        ev2 = d.process_batch(_dns(client, 41001), now=6)
        assert _ip(ev2.hdr[0, COL_DST_IP3]) == "10.0.0.53"

    def test_backend_removal_withdraws_redirect(self):
        d, client = _world()
        dns = d.add_endpoint(
            "dns-cache", ("10.0.0.53",),
            ["k8s:k8s-app=node-local-dns",
             "k8s:io.kubernetes.pod.namespace=kube-system"])
        hub = d.k8s_watchers()
        hub.dispatch("add", LRP)
        assert d.endpoints.remove(dns.id)
        ev = d.process_batch(_dns(client, 42000), now=5)
        # withdrawn, not blackholed via the dead backend
        assert _ip(ev.hdr[0, COL_DST_IP3]) == "169.254.20.10"

    def test_policy_delete_removes_redirect(self):
        d, client = _world()
        d.add_endpoint(
            "dns-cache", ("10.0.0.53",),
            ["k8s:k8s-app=node-local-dns",
             "k8s:io.kubernetes.pod.namespace=kube-system"])
        hub = d.k8s_watchers()
        hub.dispatch("add", LRP)
        hub.dispatch("delete", LRP)
        ev = d.process_batch(_dns(client, 43000), now=5)
        assert _ip(ev.hdr[0, COL_DST_IP3]) == "169.254.20.10"
