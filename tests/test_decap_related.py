"""Overlay decap (VXLAN/Geneve) + CT_RELATED (ICMP errors).

SURVEY.md §2a row 2 (overlay ingest adapters) and VERDICT r02 weak #7
(CT_RELATED defined but never produced).  Native and Python parsers
must agree; the datapath must relate ICMP errors to the original flow
and agree with the oracle.
"""

import struct

import numpy as np
import pytest

from cilium_tpu import native
from cilium_tpu.core.packets import (
    COL_DPORT,
    COL_DST_IP3,
    COL_FLAGS,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    FLAG_RELATED,
    GENEVE_PORT,
    VXLAN_PORT,
    TCP_SYN,
)


def _ipv4(src, dst, proto, payload, ttl=64):
    total = 20 + len(payload)
    hdr = struct.pack("!BBHHHBBH4s4s", 0x45, 0, total, 0, 0, ttl,
                      proto, 0, bytes(src), bytes(dst))
    return hdr + payload


def _udp(sport, dport, payload):
    return struct.pack("!HHHH", sport, dport, 8 + len(payload), 0) + payload


def _tcp(sport, dport, flags=0x02):
    return struct.pack("!HHIIBBHHH", sport, dport, 0, 0, 0x50, flags,
                       65535, 0, 0)


def _eth(inner, ethertype=0x0800):
    return b"\x00" * 12 + struct.pack("!H", ethertype) + inner


def _frames(*frames):
    return b"".join(struct.pack("<I", len(f)) + f for f in frames)


A = bytes([10, 0, 1, 1])
B = bytes([10, 0, 2, 1])
R = bytes([10, 0, 9, 9])  # a router emitting ICMP errors


class TestOverlayDecap:
    def _check(self, outer_payload_builder):
        inner = _ipv4(A, B, 6, _tcp(40000, 5432, TCP_SYN))
        outer = _ipv4(bytes([192, 168, 0, 1]), bytes([192, 168, 0, 2]),
                      17, outer_payload_builder(_eth(inner)))
        buf = _frames(_eth(outer))
        wide = native.parse_frames_py(buf)
        assert len(wide) == 1
        row = wide[0]
        # the row carries the INNER packet
        assert row[COL_SRC_IP3] == int.from_bytes(A, "big")
        assert row[COL_DST_IP3] == int.from_bytes(B, "big")
        assert row[COL_SPORT] == 40000 and row[COL_DPORT] == 5432
        assert row[COL_PROTO] == 6
        # native parser agrees
        nat = native.parse_frames(buf)
        np.testing.assert_array_equal(np.asarray(nat), wide)
        # packed fast path decaps too
        rows, n, skipped = native.parse_frames_packed(buf)
        assert n == 1 and skipped == 0
        from cilium_tpu.core.packets import pack_rows

        np.testing.assert_array_equal(np.asarray(rows), pack_rows(wide))

    def test_vxlan(self):
        self._check(lambda eth: _udp(
            51000, VXLAN_PORT,
            struct.pack("!II", 0x08000000, 42 << 8) + eth))

    def test_geneve(self):
        self._check(lambda eth: _udp(
            51000, GENEVE_PORT,
            struct.pack("!BBHI", 0, 0, 0x6558, 7 << 8) + eth))

    def test_nested_overlay_bounded_identically(self):
        """r03 review: native decap recursed unbounded while Python
        stops after 2 levels; both must emit the same row for a
        3-level encapsulation."""
        inner = _ipv4(A, B, 6, _tcp(40000, 5432, TCP_SYN))
        pkt = inner
        for level in range(3):
            vni = struct.pack("!II", 0x08000000, (level + 1) << 8)
            pkt = _ipv4(bytes([172, 16, 0, level + 1]),
                        bytes([172, 16, 0, level + 2]), 17,
                        _udp(50000 + level, VXLAN_PORT,
                             vni + _eth(pkt)))
        buf = _frames(_eth(pkt))
        wide_py = native.parse_frames_py(buf)
        wide_nat = native.parse_frames(buf)
        np.testing.assert_array_equal(np.asarray(wide_nat), wide_py)
        rows, n, skipped = native.parse_frames_packed(buf)
        from cilium_tpu.core.packets import pack_rows

        np.testing.assert_array_equal(np.asarray(rows),
                                      pack_rows(wide_py))

    def test_plain_udp_not_decapped(self):
        pkt = _ipv4(A, B, 17, _udp(51000, 53, b"\x00" * 16))
        wide = native.parse_frames_py(_frames(_eth(pkt)))
        assert wide[0][COL_DPORT] == 53
        assert wide[0][COL_SRC_IP3] == int.from_bytes(A, "big")


class TestRelatedParse:
    def test_icmp_error_carries_inner_tuple(self):
        # original egress: A:40000 -> B:53/UDP; router R returns
        # ICMP dest-unreachable embedding that packet
        orig = _ipv4(A, B, 17, _udp(40000, 53, b"x" * 8))
        icmp = struct.pack("!BBHI", 3, 1, 0, 0) + orig[:28]
        err = _ipv4(R, A, 1, icmp)
        buf = _frames(_eth(err))
        wide = native.parse_frames_py(buf)
        row = wide[0]
        assert row[COL_FLAGS] == FLAG_RELATED
        assert row[COL_SRC_IP3] == int.from_bytes(A, "big")
        assert row[COL_DST_IP3] == int.from_bytes(B, "big")
        assert row[COL_SPORT] == 40000 and row[COL_DPORT] == 53
        assert row[COL_PROTO] == 17
        nat = native.parse_frames(buf)
        np.testing.assert_array_equal(np.asarray(nat), wide)

    def test_icmp_echo_not_related(self):
        echo = _ipv4(A, B, 1, struct.pack("!BBHI", 8, 0, 0, 0))
        wide = native.parse_frames_py(_frames(_eth(echo)))
        assert wide[0][COL_FLAGS] == 0
        assert wide[0][COL_DPORT] == 8  # type in dport


RULES = [{
    "endpointSelector": {"matchLabels": {"app": "client"}},
    "egress": [
        {"toEntities": ["world"],
         "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}]}]},
    ],
    # ingress enforcing (nothing matches): only CT-related/established
    # traffic may come back in — exactly what RELATED must bypass
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "nosuch"}}]},
    ],
}]


class TestRelatedDatapath:
    def _daemon(self, backend):
        from cilium_tpu.agent import Daemon, DaemonConfig

        d = Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES)
        d.start()
        return d, ep

    def _run(self, backend):
        from cilium_tpu.core import make_batch

        d, ep = self._daemon(backend)
        # 1. original egress DNS query: allowed, creates CT
        evb = d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=53,
            proto=17, flags=0, ep=ep.id, dir=1)]).data, now=10)
        assert list(evb.verdict) == [1]
        # 2. ICMP error about that flow arrives INGRESS from a router
        #    the policy never allowed: row carries the inner tuple +
        #    FLAG_RELATED (what the ingest parser produces)
        rel = make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=53,
            proto=17, flags=FLAG_RELATED, ep=ep.id, dir=0)]).data
        evb2 = d.process_batch(rel, now=20)
        # 3. an UNRELATED ICMP error (no matching flow) is dropped
        unrel = make_batch([dict(
            src="10.0.1.1", dst="10.0.2.9", sport=41111, dport=53,
            proto=17, flags=FLAG_RELATED, ep=ep.id, dir=0)]).data
        evb3 = d.process_batch(unrel, now=21)
        return (list(evb2.verdict), list(evb2.ct_state),
                list(evb3.verdict))

    def test_related_forwarded_tpu(self):
        from cilium_tpu.datapath.conntrack import CT_RELATED

        verdict, ct, unrel_verdict = self._run("tpu")
        assert verdict == [1]
        assert ct == [CT_RELATED]
        assert unrel_verdict == [0]  # no flow to relate: default deny

    def test_backend_parity(self):
        assert self._run("tpu") == self._run("interpreter")

    def test_related_does_not_refresh_or_create(self):
        from cilium_tpu.core import make_batch

        d, ep = self._daemon("tpu")
        d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=53,
            proto=17, flags=0, ep=ep.id, dir=1)]).data, now=10)
        from cilium_tpu.datapath.conntrack import ct_live_count

        live_before = ct_live_count(d.loader.state.ct)
        # a related error for an EXPIRED-candidate flow must not
        # create a new entry for the unrelated inner tuple
        d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.7", sport=42222, dport=53,
            proto=17, flags=FLAG_RELATED, ep=ep.id, dir=0)]).data,
            now=20)
        assert ct_live_count(d.loader.state.ct) == live_before