"""Hubble completion (SURVEY.md §2b row 27): seven parser, relay,
and the gRPC Observer API surface.
"""

import os
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.flow import Observer, Relay, SevenParser
from cilium_tpu.flow.seven import MSG_L7


RULES_L7 = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                      "rules": {"http": [{"method": "GET",
                                          "path": "/ok"}]}}]},
    ],
}]


def _daemon_with_l7(**cfg):
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12, **cfg))
    web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES_L7)
    d.start()
    return d, web, db


class TestSevenParser:
    def test_proxy_records_become_l7_flows(self):
        d, web, db = _daemon_with_l7()
        evb = d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=80,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        port = int(evb.proxy_port[0])
        d.handle_l7_http(port, [
            {"method": "GET", "path": "/ok", "host": "db"},
            {"method": "POST", "path": "/ok"},
        ], src_identity=web.identity.numeric_id)

        flows = d.observer.get_flows(number=10)
        l7_flows = [f for f in flows if f.l7 is not None]
        assert len(l7_flows) == 2
        allowed = [f for f in l7_flows if f.verdict_name == "FORWARDED"]
        denied = [f for f in l7_flows if f.verdict_name == "DROPPED"]
        assert len(allowed) == 1 and len(denied) == 1
        assert allowed[0].l7["http"]["method"] == "GET"
        assert allowed[0].l7["http"]["url"] == "/ok"
        assert allowed[0].l7["http"]["code"] == 200
        assert denied[0].l7["http"]["code"] == 403
        assert allowed[0].event_type == MSG_L7
        # enriched with the requesting identity
        assert allowed[0].source.identity == web.identity.numeric_id
        d.shutdown()

    def test_flow_json_carries_l7(self):
        d, web, db = _daemon_with_l7()
        evb = d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=80,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        d.handle_l7_http(int(evb.proxy_port[0]),
                         [{"method": "GET", "path": "/ok"}])
        f = [x for x in d.observer.get_flows(number=10)
             if x.l7 is not None][0]
        j = f.to_dict()
        assert j["Type"] == "L7"
        assert j["l7"]["http"]["url"] == "/ok"
        d.shutdown()

    def test_dns_records(self):
        obs = Observer(capacity=64)
        seven = SevenParser(obs)
        from cilium_tpu.proxy.featurize import KIND_DNS
        from cilium_tpu.proxy.proxy import L7Record

        seven.consume(L7Record(kind=KIND_DNS, verdict=0,
                               proxy_port=10053, src_row=0,
                               timestamp=time.time(),
                               qname="evil.com"))
        f = obs.get_flows(number=1)[0]
        assert f.l7["dns"]["query"] == "evil.com"
        assert f.l7["dns"]["rcode"] == 5  # refused


class TestRelay:
    def test_merges_and_stamps_nodes(self):
        a, b = Observer(capacity=64), Observer(capacity=64)
        sa, sb = SevenParser(a), SevenParser(b)
        from cilium_tpu.proxy.featurize import KIND_HTTP
        from cilium_tpu.proxy.proxy import L7Record

        t0 = time.time()
        for i, (p, t) in enumerate(((sa, t0 + 1), (sb, t0 + 2),
                                    (sa, t0 + 3))):
            p.consume(L7Record(kind=KIND_HTTP, verdict=1,
                               proxy_port=10000, src_row=0,
                               timestamp=t, method="GET",
                               path=f"/r{i}", status=200))
        relay = Relay({"node-a": a, "node-b": b})
        flows = relay.get_flows(number=10)
        assert len(flows) == 3
        assert flows[0]["l7"]["http"]["url"] == "/r2"  # newest first
        assert flows[0]["node_name"] == "node-a"
        assert flows[1]["node_name"] == "node-b"
        status = relay.server_status()
        assert status["num_connected_nodes"] == 2
        assert status["num_flows"] == 3
        # GetNodes (hubble list nodes): per-peer availability
        nodes = relay.nodes()
        assert [n["name"] for n in nodes] == ["node-a", "node-b"]
        assert all(n["state"] == "connected" for n in nodes)
        assert nodes[0]["num_flows"] == 2 and nodes[1]["num_flows"] == 1

        class Dead:
            def server_status(self):
                raise ConnectionError("gone")

        relay.add_peer("node-c", Dead())
        assert relay.nodes()[2]["state"] == "unavailable"


class TestObserverGRPC:
    def test_get_flows_over_grpc(self, tmp_path):
        from cilium_tpu.flow.grpc_server import ObserverClient, serve

        d, web, db = _daemon_with_l7()
        evb = d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=80,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        d.handle_l7_http(int(evb.proxy_port[0]),
                         [{"method": "GET", "path": "/ok"}])

        addr = f"unix://{tmp_path}/hubble.sock"
        server = serve(d.observer, addr)
        try:
            client = ObserverClient(addr)
            flows = client.get_flows(number=10)
            assert len(flows) >= 2  # the L3/L4 redirect + the L7 flow
            l7 = [f for f in flows if f.get("l7")]
            assert l7 and l7[0]["l7"]["http"]["url"] == "/ok"
            status = client.server_status()
            assert status["seen_flows"] >= 2
            client.close()
        finally:
            server.stop(grace=0.2)
            d.shutdown()

    def test_daemon_config_serves_hubble(self, tmp_path):
        from cilium_tpu.flow.grpc_server import ObserverClient

        addr = f"unix://{tmp_path}/hubble2.sock"
        d, web, db = _daemon_with_l7(hubble_listen=addr)
        d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=80,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=10)
        client = ObserverClient(addr)
        assert client.server_status()["seen_flows"] >= 1
        client.close()
        d.shutdown()

    def test_relay_over_grpc_peers(self, tmp_path):
        """The hubble-relay shape: relay peers are gRPC clients to two
        agents' Observer servers."""
        from cilium_tpu.flow.grpc_server import ObserverClient, serve

        obs_a, obs_b = Observer(capacity=64), Observer(capacity=64)
        from cilium_tpu.proxy.featurize import KIND_HTTP
        from cilium_tpu.proxy.proxy import L7Record

        SevenParser(obs_a).consume(L7Record(
            kind=KIND_HTTP, verdict=1, proxy_port=1, src_row=0,
            timestamp=time.time(), method="GET", path="/a", status=200))
        SevenParser(obs_b).consume(L7Record(
            kind=KIND_HTTP, verdict=1, proxy_port=1, src_row=0,
            timestamp=time.time() + 1, method="GET", path="/b",
            status=200))
        sa = serve(obs_a, f"unix://{tmp_path}/a.sock")
        sb = serve(obs_b, f"unix://{tmp_path}/b.sock")
        try:
            relay = Relay({
                "a": ObserverClient(f"unix://{tmp_path}/a.sock"),
                "b": ObserverClient(f"unix://{tmp_path}/b.sock"),
            })
            flows = relay.get_flows(number=10)
            assert [f["node_name"] for f in flows] == ["b", "a"]
        finally:
            sa.stop(grace=0.2)
            sb.stop(grace=0.2)
