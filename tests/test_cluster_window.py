"""Pipelined cluster data channel (ISSUE 17 tentpole): the send
window, the cumulative-ack codec, and exact crash accounting with
the window OPEN.

Acceptance:
(a) window-vs-sync byte equivalence: ``encode_rows(..., seq=None)``
    is byte-identical to the PR 13 wire, a ``forward_window=1``
    router never enables a window, and the legacy per-frame ack
    sizes never collide with the cumulative ack's;
(b) seeded mid-window crash property: a fake worker over a real
    socketpair acks cumulatively up to an arbitrary point then
    dies — at EVERY kill point the sender-side identity
    ``acked + handed_back == sent`` holds exactly (nothing in
    flight is ever silently lost), and each ack's admitted delta
    matches exactly the frames it retires;
(c) the router's windowed accounting: delivery settles on the ack
    (forwarded/latency/inflight), a broken window's frames re-enter
    the queue in order, ``remove_node`` migrates slots + residual
    queue with the ledger exact, and the ``ack_flush`` control op
    is a pinned contract (CTA011);
(d) the queue-depth autoscaler's scale-DOWN half: `ticks` cold
    samples retire one node, never below ``min_nodes``.

Named to sort early (the tier-1 budget-truncation convention)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from cilium_tpu.cluster.transport import (ACK_SIZE, ACK_TRACED_SIZE,
                                          CUM_ACK_MIN_SIZE,
                                          FrameError, SendWindow,
                                          decode_rows_ex,
                                          decode_rows_seq,
                                          encode_rows, pack_ack,
                                          pack_cum_ack, recv_frame,
                                          send_frame, shutdown_close,
                                          unpack_cum_ack)

pytestmark = pytest.mark.cluster


# -- the send window ---------------------------------------------------
class TestSendWindow:
    def test_sequences_are_monotonic_from_one(self):
        w = SendWindow(4)
        r = np.zeros((3, 4), dtype=np.uint32)
        assert w.add(r, 0.0) == 1
        assert w.add(r, 0.0) == 2
        assert w.inflight_frames == 2
        assert w.inflight_rows == 6

    def test_full_at_window(self):
        w = SendWindow(2)
        r = np.zeros((1, 4), dtype=np.uint32)
        assert not w.full
        w.add(r, 0.0)
        assert not w.full
        w.add(r, 0.0)
        assert w.full

    def test_retire_contiguous_prefix_only(self):
        w = SendWindow(8)
        rows = [np.zeros((i + 1, 4), dtype=np.uint32)
                for i in range(4)]
        for r in rows:
            w.add(r, 0.0)
        out = w.retire(2)
        assert [e[0] for e in out] == [1, 2]
        assert w.inflight_frames == 2
        assert w.inflight_rows == 3 + 4
        # re-acking an already-retired seq is a no-op
        assert w.retire(2) == []

    def test_drop_unregisters_failed_send(self):
        w = SendWindow(8)
        r = np.zeros((5, 4), dtype=np.uint32)
        s1 = w.add(r, 0.0)
        s2 = w.add(r, 0.0)
        assert w.drop(s1) is True
        assert w.drop(s1) is False
        assert w.inflight_frames == 1
        assert w.inflight_rows == 5
        # the surviving entry retires normally
        assert [e[0] for e in w.retire(s2)] == [s2]

    def test_take_all_empties(self):
        w = SendWindow(8)
        r = np.zeros((2, 4), dtype=np.uint32)
        w.add(r, 0.0)
        w.add(r, 0.0)
        out = w.take_all()
        assert [e[0] for e in out] == [1, 2]
        assert w.inflight_frames == 0
        assert w.inflight_rows == 0

    def test_window_must_be_positive(self):
        with pytest.raises(ValueError):
            SendWindow(0)


# -- the cumulative-ack codec ------------------------------------------
class TestCumAckCodec:
    def test_roundtrip_no_echoes(self):
        blob = pack_cum_ack(7, 3, 384, 1000, 900, 50, 10)
        (seq, frames, admitted, sub, ver, shed, rec), echoes = \
            unpack_cum_ack(blob)
        assert (seq, frames, admitted) == (7, 3, 384)
        assert (sub, ver, shed, rec) == (1000, 900, 50, 10)
        assert echoes == []

    def test_roundtrip_with_echoes(self):
        want = [(11, 1.5, 2.5), (12, 3.0, 4.0)]
        blob = pack_cum_ack(9, 2, 64, 1, 2, 3, 4,
                            echoes=tuple(want))
        hdr, echoes = unpack_cum_ack(blob)
        assert hdr == (9, 2, 64, 1, 2, 3, 4)
        assert [(t, r, a) for t, r, a in echoes] == want

    def test_short_payload_is_loud(self):
        with pytest.raises(FrameError):
            unpack_cum_ack(b"\x00" * (CUM_ACK_MIN_SIZE - 1))

    def test_wrong_kind_is_loud(self):
        blob = bytearray(pack_cum_ack(1, 1, 1, 1, 1, 1, 1))
        blob[0] = 0x01
        with pytest.raises(FrameError):
            unpack_cum_ack(bytes(blob))

    def test_torn_echo_block_is_loud(self):
        blob = pack_cum_ack(1, 1, 1, 1, 1, 1, 1,
                            echoes=((5, 1.0, 2.0),))
        with pytest.raises(FrameError):
            unpack_cum_ack(blob[:-4])

    def test_sizes_never_collide_with_legacy_acks(self):
        """The sync per-frame ack (36 or 60 bytes) and the cumulative
        ack (>= 57, kind-tagged) can share a channel in tests."""
        assert CUM_ACK_MIN_SIZE not in (ACK_SIZE, ACK_TRACED_SIZE)
        assert len(pack_cum_ack(1, 1, 1, 1, 1, 1, 1)) \
            == CUM_ACK_MIN_SIZE


# -- window-vs-sync wire equivalence -----------------------------------
class TestWireEquivalence:
    def test_unsequenced_frame_is_pr13_byte_identical(self):
        """``seq=None`` keeps the PR 13 wire EXACT: kind-1 wide /
        kind-2 packed header then raw row bytes, nothing else."""
        wide = np.arange(32, dtype=np.uint32).reshape(2, 16)
        want = struct.pack(">BIIII", 1, 2, 16, 0, 0) + wide.tobytes()
        assert encode_rows(wide) == want
        packed = np.arange(8, dtype=np.uint32).reshape(2, 4)
        want = struct.pack(">BIIII", 2, 2, 4, 7, 1) + packed.tobytes()
        assert encode_rows(packed, packed_meta=(7, 1)) == want

    def test_sequenced_frame_roundtrips_and_downgrades(self):
        rows = np.arange(16, dtype=np.uint32).reshape(4, 4)
        blob = encode_rows(rows, packed_meta=(3, 0), seq=42)
        got, meta, trace, seq = decode_rows_seq(blob)
        assert np.array_equal(got, rows)
        assert meta == (3, 0)
        assert trace is None
        assert seq == 42
        # the pre-pipelining decode surface simply drops the seq
        got2, meta2, _ = decode_rows_ex(blob)
        assert np.array_equal(got2, rows)
        assert meta2 == (3, 0)

    def test_sequenced_traced_frame_carries_both(self):
        rows = np.zeros((2, 16), dtype=np.uint32)
        blob = encode_rows(rows, trace=(99, 1.0, 2.0), seq=5)
        got, meta, trace, seq = decode_rows_seq(blob)
        assert np.array_equal(got, rows)
        assert meta is None
        assert trace == (99, 1.0, 2.0)
        assert seq == 5

    def test_torn_seq_block_is_loud(self):
        rows = np.zeros((1, 4), dtype=np.uint32)
        blob = encode_rows(rows, packed_meta=(0, 0), seq=1)
        hdr = struct.calcsize(">BIIII")
        with pytest.raises(FrameError):
            decode_rows_seq(blob[:hdr + 4])


# -- exact crash accounting at every kill point ------------------------
class TestMidWindowCrashProperty:
    """A fake worker on the far end of a real socketpair implements
    the coalesced-ack protocol, admits frames, acks cumulatively at
    a random cadence, then DIES at a random point — sometimes with
    admitted-but-unflushed frames (the SIGKILL-between-admit-and-ack
    hole the cumulative protocol must close).  The sender-side
    identity must hold at EVERY kill point."""

    @staticmethod
    def _run_one(seed: int):
        rng = np.random.default_rng(seed)
        k = int(rng.integers(1, 12))             # frames to send
        ack_every = int(rng.integers(1, 5))      # worker cadence
        die_after = int(rng.integers(0, k + 1))  # frames admitted
        flush_tail = bool(rng.integers(0, 2))    # ack the tail first?
        sizes = [int(rng.integers(1, 64)) for _ in range(k)]
        parent, worker = socket.socketpair()

        def run_worker():
            admitted_since = frames_since = 0
            ledger_rows = 0
            last_seq = 0
            try:
                for _ in range(die_after):
                    payload = recv_frame(worker)
                    if payload is None:
                        return
                    rows, _meta, _tr, seq = decode_rows_seq(payload)
                    ledger_rows += len(rows)
                    admitted_since += len(rows)
                    frames_since += 1
                    last_seq = seq
                    if frames_since >= ack_every:
                        send_frame(worker, pack_cum_ack(
                            last_seq, frames_since, admitted_since,
                            ledger_rows, ledger_rows, 0, 0))
                        admitted_since = frames_since = 0
                if flush_tail and frames_since:
                    send_frame(worker, pack_cum_ack(
                        last_seq, frames_since, admitted_since,
                        ledger_rows, ledger_rows, 0, 0))
            finally:
                # SIGKILL stand-in: the channel just dies
                shutdown_close(worker)

        t = threading.Thread(target=run_worker, daemon=True)
        t.start()

        win = SendWindow(16)
        total = 0
        send_failed = 0
        for n in sizes:
            rows = np.zeros((n, 4), dtype=np.uint32)
            seq = win.add(rows, time.monotonic())
            total += n
            try:
                send_frame(parent, encode_rows(
                    rows, packed_meta=(0, 0), seq=seq))
            except OSError:
                # a dead peer mid-send: the frame never reached the
                # worker — unregister it (the forwarder's requeue
                # owns those rows alone, ProcessNode.submit's
                # contract)
                win.drop(seq)
                send_failed += n
        acked = 0
        final_word = None
        while True:
            try:
                payload = recv_frame(parent)
            except (FrameError, OSError):
                break  # torn frame / reset: the channel is dead
            if payload is None:
                break
            (seq, _frames, admitted, sub, _v, _s,
             _r), _echoes = unpack_cum_ack(payload)
            entries = win.retire(seq)
            retired_rows = sum(len(e[1]) for e in entries)
            # each ack's admitted DELTA covers exactly the frames it
            # retires — the piece that makes the ledger exact
            assert admitted == retired_rows, seed
            acked += retired_rows
            final_word = sub
        handed_back = win.take_all()
        requeued = sum(len(e[1]) for e in handed_back)
        # THE identity: at every kill point, every row is acked,
        # handed back for requeue/crash accounting, or a counted
        # failed send — never silently lost
        assert acked + requeued + send_failed == total, seed
        # the last cumulative ack is the final word: its running
        # ledger equals exactly the rows the sender retired
        if final_word is not None:
            assert final_word == acked, seed
        shutdown_close(parent)
        t.join(timeout=10)

    def test_ledger_identity_at_every_kill_point(self):
        for seed in range(24):
            self._run_one(seed)


# -- router windowed accounting (fake nodes, no serving build) ---------
class _WinNode:
    """Records the pipelined node surface; acks synchronously from
    ``submit`` when ``echo`` (the in-order happy path)."""

    alive = True

    def __init__(self, name="w0", echo=True):
        self.name = name
        self.echo = echo
        self.window = None
        self.on_ack = None
        self.on_broken = None
        self.sent = []
        self.flushes = 0

    def enable_window(self, window, on_ack=None, on_broken=None):
        self.window = window
        self.on_ack = on_ack
        self.on_broken = on_broken

    def submit(self, rows, trace=None, t_enq=None):
        self.sent.append((rows, t_enq, trace))
        if self.echo and self.on_ack is not None:
            self.on_ack([(len(rows), t_enq if t_enq is not None
                          else time.monotonic(), trace)])
        return len(rows)

    def ack_flush(self):
        self.flushes += 1
        return None

    def drain_window(self, timeout=30.0):
        return True

    def transport_stats(self):
        return {"acks": len(self.sent), "acks-coalesced": 0,
                "window-stalls": 0, "inflight-frames": 0,
                "window": self.window or 1}


class _SyncNode:
    alive = True

    def __init__(self, name="s0"):
        self.name = name
        self.got = 0

    def submit(self, rows):
        self.got += len(rows)
        return len(rows)


def _rows(n=128, sport0=1024):
    rows = np.zeros((n, 16), dtype=np.uint32)
    rows[:, 13] = 4  # COL_FAMILY
    rows[:, 8] = sport0 + np.arange(n)  # COL_SPORT: spread the flows
    return rows


def _wait(pred, timeout=30.0, tick=0.005):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


class TestRouterWindowed:
    def test_window_one_never_enables_a_window(self):
        """forward_window=1 IS the sync protocol: the router must
        not touch ``enable_window`` even on a capable node."""
        from cilium_tpu.cluster.router import ClusterRouter

        node = _WinNode()
        r = ClusterRouter([node], forward_depth=4096,
                          forward_window=1)
        r.start()
        assert r.submit(_rows()) == 128
        assert _wait(lambda: node.window is None
                     and len(node.sent) > 0)
        snap = r.stop(drain=True)
        assert node.window is None
        assert snap["forward-window"] == 1
        assert (snap["submitted"] == sum(snap["forwarded"])
                + snap["router-overflow"])

    def test_windowed_delivery_settles_on_the_ack(self):
        from cilium_tpu.cluster.router import ClusterRouter

        node = _WinNode()
        r = ClusterRouter([node], forward_depth=4096,
                          forward_window=8)
        r.start()
        assert node.window == 8
        assert r.submit(_rows()) == 128
        assert _wait(lambda: r.snapshot()["forwarded"][0] == 128)
        snap = r.snapshot()
        assert snap["inflight"] == [0]
        assert snap["forward-latency-us"]["count"] >= 1
        assert snap["window"]["acks"] == len(node.sent)
        snap = r.stop(drain=True)
        assert node.flushes >= 1  # stop forces the coalescer's hand
        assert (snap["submitted"] == sum(snap["forwarded"])
                + snap["router-overflow"]
                + snap["failover-dropped"])

    def test_incapable_node_stays_sync_under_windowed_router(self):
        from cilium_tpu.cluster.router import ClusterRouter

        node = _SyncNode()
        r = ClusterRouter([node], forward_depth=4096,
                          forward_window=8)
        r.start()
        assert r.submit(_rows()) == 128
        assert _wait(lambda: node.got == 128)
        snap = r.stop(drain=True)
        assert snap["forwarded"][0] == 128

    def test_broken_window_requeues_in_order(self):
        """A dead channel's sent-but-unacked frames re-enter the
        queue AT THE FRONT (order preserved) and the node parks
        suspect — failover's migration or stop's sweep accounts
        them; nothing vanishes."""
        from cilium_tpu.cluster.router import ClusterRouter

        node = _WinNode(echo=False)  # never acks: frames hang open
        r = ClusterRouter([node], forward_depth=4096,
                          forward_window=8)
        r.start()
        assert r.submit(_rows()) == 128
        assert _wait(lambda: len(node.sent) > 0)
        assert r.snapshot()["inflight"][0] == 128
        # the channel dies: ProcessNode would hand the window back
        node.on_broken([(rows, t_enq, tr)
                        for rows, t_enq, tr in node.sent])
        snap = r.snapshot()
        assert snap["inflight"] == [0]
        assert snap["pending"] == [128]
        assert snap["forwarded"] == [0]
        # the handed-back frames drain at stop: this fake can no
        # longer ack, so stop counts them failover_dropped — the
        # ledger still closes exactly
        node.alive = False
        snap = r.stop(drain=True)
        assert (snap["submitted"] == sum(snap["forwarded"])
                + snap["router-overflow"]
                + snap["failover-dropped"])

    def test_remove_node_migrates_slots_and_queue(self):
        from cilium_tpu.cluster.router import ClusterRouter

        victim, survivor = _SyncNode("v0"), _SyncNode("s1")
        victim.alive = False  # parked: its queue holds still
        r = ClusterRouter([victim, survivor], forward_depth=4096)
        r.start()
        sent = 0
        for i in range(8):
            sent += r.submit(_rows(sport0=1024 + 128 * i))
        assert _wait(lambda: r.snapshot()["pending"][1] == 0
                     and r.snapshot()["inflight"][1] == 0)
        queued = r.snapshot()["pending"][0]
        assert queued > 0  # the parked victim holds a backlog
        moved = r.remove_node(0)
        assert moved  # it owned slots; they all moved
        snap = r.snapshot()
        assert snap["retired"] == [True, False]
        assert 0 not in snap["slot-owner"]
        assert snap["pending"][0] == 0  # residual queue migrated
        # the survivor drains the migrated rows
        assert _wait(lambda: survivor.got + r.snapshot()
                     ["failover-dropped"] >= sent)
        snap = r.stop(drain=True)
        assert victim.got + survivor.got == sum(snap["forwarded"])
        assert (snap["submitted"] == sum(snap["forwarded"])
                + snap["router-overflow"]
                + snap["failover-dropped"])

    def test_remove_last_live_node_refuses(self):
        from cilium_tpu.cluster.router import ClusterRouter
        from cilium_tpu.serving import ServingError

        node = _SyncNode()
        r = ClusterRouter([node], forward_depth=64)
        r.start()
        with pytest.raises(ServingError):
            r.remove_node(0)
        r.stop(drain=False)


# -- the ack-flush control op is a pinned contract (CTA011) ------------
class TestAckFlushOpContract:
    def test_ack_flush_op_registered_with_timeout(self):
        from cilium_tpu.cluster.nodehost import (OP_TIMEOUTS,
                                                 _NodeHost)
        assert OP_TIMEOUTS["ack_flush"] > 0
        assert "ack_flush" in _NodeHost._OPS


# -- autoscaler scale-down ---------------------------------------------
class _FakeRouter:
    forward_depth = 100

    def __init__(self):
        self.pending = [0, 0]

    def snapshot(self):
        return {"pending": list(self.pending)}


class _FakeNode:
    alive = True


class _FakeCluster:
    _stopped = False

    def __init__(self, n=2):
        self.router = _FakeRouter()
        self.nodes = [_FakeNode() for _ in range(n)]
        self.added = 0
        self.removed = 0

    def add_node(self):
        self.added += 1
        self.nodes.append(_FakeNode())

    def remove_node(self, name=None):
        self.removed += 1
        self.nodes.pop()


class TestAutoscalerScaleDown:
    def test_cold_streak_retires_one_node(self):
        from cilium_tpu.cluster.scale import ClusterAutoscaler

        c = _FakeCluster(n=2)
        a = ClusterAutoscaler(c, high_frac=0.5, ticks=2,
                              max_nodes=4, interval_s=999.0,
                              low_frac=0.1, min_nodes=1)
        a._tick()
        assert c.removed == 0  # one cold sample is not a streak
        a._tick()
        assert c.removed == 1
        assert a.triggered_down == 1
        assert a.stats()["cold-streak"] == 0  # streak reset at fire

    def test_never_below_min_nodes(self):
        from cilium_tpu.cluster.scale import ClusterAutoscaler

        c = _FakeCluster(n=2)
        a = ClusterAutoscaler(c, high_frac=0.5, ticks=1,
                              max_nodes=4, interval_s=999.0,
                              low_frac=0.1, min_nodes=2)
        for _ in range(4):
            a._tick()
        assert c.removed == 0

    def test_low_frac_zero_disables_scale_in(self):
        from cilium_tpu.cluster.scale import ClusterAutoscaler

        c = _FakeCluster(n=3)
        a = ClusterAutoscaler(c, high_frac=0.5, ticks=1,
                              max_nodes=4, interval_s=999.0)
        for _ in range(4):
            a._tick()
        assert c.removed == 0

    def test_hot_wins_over_cold(self):
        from cilium_tpu.cluster.scale import ClusterAutoscaler

        c = _FakeCluster(n=2)
        c.router.pending = [80, 0]  # hot AND (trivially) not cold
        a = ClusterAutoscaler(c, high_frac=0.5, ticks=1,
                              max_nodes=4, interval_s=999.0,
                              low_frac=0.9, min_nodes=1)
        a._tick()
        assert c.added == 1
        assert c.removed == 0
