"""Multi-chip serving end-to-end on the 8-device virtual CPU mesh
(PR 2 tentpole): flow-routed dispatch through per-shard serve steps,
flow-affine conntrack, router-overflow accounting, and per-chip event
rings drained round-robin with no event loss.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch
from cilium_tpu.datapath.verdict import REASON_ROUTE_OVERFLOW
from cilium_tpu.monitor.api import (DROP_REASON_NAMES, MSG_DROP,
                                    MSG_POLICY_VERDICT, DropNotify,
                                    materialize)
from cilium_tpu.parallel import make_mesh

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]

# db with EGRESS enforcement on (an endpoint with no egress section
# is egress-allow-all): only an irrelevant port is whitelisted, so a
# db-sourced reply can pass its egress hook ONLY via the CT reply
# fast path — which lives on the shard the forward packet landed on
RULES_EGRESS_ENFORCED = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
    "egress": [{
        "toEndpoints": [{"matchLabels": {"app": "db"}}],
        "toPorts": [{"ports": [{"port": "1", "protocol": "TCP"}]}],
    }],
}]


def _world(ladder=(64, 256)):
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                            flow_ring_capacity=1 << 13,
                            serving_bucket_ladder=ladder))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _traffic(db_id, base_sport, n=64):
    # half allowed NEW flows, half scan-drops
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base_sport + i,
             dport=5432 if i % 2 == 0 else 9999,
             proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)
    ]).data


class TestShardedServing:
    def test_events_survive_the_sharded_path(self):
        """Every drop + policy verdict reaches the monitor through the
        per-chip rings; totals match the single-chip semantics and
        nothing is lost."""
        d, db = _world()
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(ring_capacity=1 << 10, drain_every=2,
                        trace_sample=0, packed=True,
                        mesh=make_mesh(8))
        for i in range(6):
            info = d.serve_batch(_traffic(db.id, 20000 + 100 * i),
                                 now=10 + i)
            assert info["mode"] == "sharded-packed"
        stats = d.stop_serving()
        d.shutdown()
        assert stats["lost"] == 0
        assert stats["shards"] == 8
        assert stats["route-overflow"] == 0
        msg = np.concatenate([b.msg_type for b in got])
        assert int((msg == MSG_POLICY_VERDICT).sum()) == 6 * 32
        assert int((msg == MSG_DROP).sum()) == 6 * 32
        # padding never leaks an event (all-zero header row)
        for b in got:
            assert (b.hdr.sum(axis=1) != 0).all()

    def test_flow_affine_conntrack(self):
        """The acceptance property: a reply is forwarded ONLY because
        it lands on the shard whose private CT holds the entry its
        forward packet created.  Control: same-shaped packets whose
        tuples never had a forward drop at db's egress-enforced hook,
        so a misrouted reply could not pass."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                flow_ring_capacity=1 << 13,
                                serving_bucket_ladder=(64, 256)))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES_EGRESS_ENFORCED)
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(ring_capacity=1 << 10, drain_every=2,
                        trace_sample=1, packed=True,
                        mesh=make_mesh(8))
        fwd = make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=30000 + i,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0)
            for i in range(32)]).data
        d.serve_batch(fwd, now=100)
        # replies at db's EGRESS hook (enforced: only port 1 is
        # whitelisted — the CT REPLY fast path is the only way out)
        rep = make_batch([
            dict(src="10.0.2.1", dst="10.0.1.1", sport=5432,
                 dport=30000 + i, proto=6, flags=TCP_ACK,
                 ep=db.id, dir=1)
            for i in range(32)]).data
        d.serve_batch(rep, now=101)
        # control: identical shape, sports that never had a forward
        ctrl = make_batch([
            dict(src="10.0.2.1", dst="10.0.1.1", sport=5432,
                 dport=50000 + i, proto=6, flags=TCP_ACK,
                 ep=db.id, dir=1)
            for i in range(32)]).data
        d.serve_batch(ctrl, now=102)
        stats = d.stop_serving()
        d.shutdown()
        assert stats["lost"] == 0

        def verdicts_for(dport_base):
            out = []
            for b in got:
                m = ((b.hdr[:, 9] >= dport_base)
                     & (b.hdr[:, 9] < dport_base + 32)
                     & (b.hdr[:, 8] == 5432))
                out.extend(int(v) for v in b.verdict[m])
            return out

        reply_v = verdicts_for(30000)
        ctrl_v = verdicts_for(50000)
        assert len(reply_v) == 32 and all(v != 0 for v in reply_v), \
            "replies must ride the CT entry their forward created"
        assert len(ctrl_v) == 32 and all(v == 0 for v in ctrl_v), \
            "no-forward control must default-deny"

    def test_route_overflow_counted_and_decoded(self):
        """One elephant flow overwhelms its shard's block
        (headroom=1): the loss is counted in the metricsmap as
        REASON_ROUTE_OVERFLOW and every overflowed packet decodes as
        a DROP through monitor -> flow layers."""
        d, db = _world(ladder=(64,))
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(ring_capacity=1 << 10, drain_every=2,
                        trace_sample=0, packed=True,
                        mesh=make_mesh(8), shard_headroom=1)
        # 64 packets of ONE flow: all hash to one shard, block is
        # 64/8 = 8 -> 56 must overflow
        one_flow = make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=33333,
                 dport=5432, proto=6, flags=TCP_ACK, ep=db.id, dir=0)
        ] * 64).data
        d.serve_batch(one_flow, now=10)
        stats = d.stop_serving()
        assert stats["route-overflow"] == 56
        # metricsmap: the RSS-queue-overflow counter (ingress column)
        assert int(d.loader.metrics()[REASON_ROUTE_OVERFLOW, 0]) == 56
        # monitor plane: DROP events with the reason
        drops = [b for b in got
                 if (np.asarray(b.reason) == REASON_ROUTE_OVERFLOW).any()]
        assert drops
        n = sum(int((np.asarray(b.reason)
                     == REASON_ROUTE_OVERFLOW).sum()) for b in got)
        assert n == 56
        ev = materialize(drops[0], 0)
        assert DropNotify(ev).reason_name == "Shard queue overflow"
        assert DROP_REASON_NAMES[REASON_ROUTE_OVERFLOW] == \
            "Shard queue overflow"
        # flow layer (`cilium-tpu monitor` / hubble JSON)
        flows = [f.to_dict() for f in d.observer.get_flows(number=8192)]
        ovf = [f for f in flows
               if f.get("drop_reason") == REASON_ROUTE_OVERFLOW]
        assert ovf
        assert ovf[0]["drop_reason_desc"] == "QUEUE_OVERFLOW"
        assert ovf[0]["verdict"] == "DROPPED"
        d.shutdown()

    def test_sharded_ingress_runtime_end_to_end(self):
        """submit() -> batcher -> flow-routed sharded dispatch: every
        admitted packet verdicts, telemetry reports the sharded mode,
        and the loader returns to single-device placement on stop."""
        d, db = _world(ladder=(64, 256))
        d.start_serving(trace_sample=0, ingress=True, packed=True,
                        mesh=make_mesh(8))
        rng = np.random.default_rng(5)
        sent = 0
        for k in range(8):
            n = max(int(rng.poisson(100)), 1)
            chunk = _traffic(db.id, 40000 + 300 * k, n)
            sent += d.submit(chunk)
        stats = d.stop_serving()
        fe = stats["front-end"]
        assert fe["verdicts"] == fe["admitted"] == sent
        assert stats["lost"] == 0
        assert stats["shards"] == 8
        # the sharded leg re-packs after routing: h2d telemetry
        # reports 16 B rows (padding included, so bytes per REAL
        # packet exceeds 16 but stays far under the wide 64)
        assert fe["h2d"]["packed-batches"] >= 1
        assert fe["h2d"]["wide-batches"] == 0
        # sharded mode exited cleanly: the default single-chip debug
        # path still works on the SAME loader (placement restored)
        out = d.process_batch(_traffic(db.id, 60000, 16), now=999)
        assert len(out) == 16
        assert d.loader._serving_mesh is None
        d.shutdown()

    def test_ladder_mesh_mismatch_rejected(self):
        d, db = _world(ladder=(4, 256))  # 4 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            d.start_serving(mesh=make_mesh(8))
        d.shutdown()
