"""IPv4 fragment tracking (reference: bpf/lib/ipv4.h fragment
handling + pkg/maps/fragmap): the first fragment records its L4
header; mid-fragments resolve ports through the tracker; an orphan
mid-fragment drops (DROP_FRAG_NOT_FOUND)."""

import struct

import numpy as np

from cilium_tpu import native
from cilium_tpu.core.packets import (
    COL_DPORT,
    COL_FLAGS,
    COL_PROTO,
    COL_SPORT,
    TCP_ACK,
    pack_rows,
)


def _ipv4(src, dst, proto, payload, ipid=0, frag_off=0, mf=False):
    fo = (frag_off & 0x1FFF) | (0x2000 if mf else 0)
    total = 20 + len(payload)
    hdr = struct.pack("!BBHHHBBH4s4s", 0x45, 0, total, ipid, fo, 64,
                      proto, 0, bytes(src), bytes(dst))
    return hdr + payload


def _tcp(sport, dport, flags=TCP_ACK):
    return struct.pack("!HHIIBBHHH", sport, dport, 0, 0, 0x50, flags,
                       65535, 0, 0)


def _eth(inner):
    return b"\x00" * 12 + struct.pack("!H", 0x0800) + inner


def _frames(*frames):
    return b"".join(struct.pack("<I", len(f)) + f for f in frames)


A = bytes([10, 7, 1, 1])
B = bytes([10, 7, 2, 1])


class TestFragmentTracking:
    def test_mid_fragment_inherits_first_fragment_ports(self):
        first = _ipv4(A, B, 6, _tcp(41000, 5432), ipid=0x1234, mf=True)
        mid = _ipv4(A, B, 6, b"\x00" * 32, ipid=0x1234, frag_off=185)
        buf = _frames(_eth(first), _eth(mid))
        rows = native.parse_frames_py(buf)
        assert rows.shape[0] == 2
        # both rows carry the flow's ports — the mid-fragment resolved
        # through the tracker despite having no L4 header on the wire
        assert list(rows[:, COL_SPORT]) == [41000, 41000]
        assert list(rows[:, COL_DPORT]) == [5432, 5432]
        assert rows[1, COL_FLAGS] == 0  # no TCP flags on a fragment

    def test_native_parser_agrees(self):
        first = _ipv4(A, B, 6, _tcp(42000, 443), ipid=0x77, mf=True)
        mid = _ipv4(A, B, 6, b"\x00" * 16, ipid=0x77, frag_off=3)
        orphan = _ipv4(A, B, 17, b"\x00" * 16, ipid=0x78, frag_off=3)
        buf = _frames(_eth(first), _eth(mid), _eth(orphan))
        py = native.parse_frames_py(buf)
        nat = native.parse_frames(buf)
        if nat is not None:
            np.testing.assert_array_equal(np.asarray(nat), py)
        assert py.shape[0] == 2  # the orphan dropped

    def test_orphan_mid_fragment_drops(self):
        orphan = _ipv4(A, B, 6, b"\x00" * 16, ipid=0x9999, frag_off=5)
        rows = native.parse_frames_py(_frames(_eth(orphan)))
        assert rows.shape[0] == 0

    def test_packed_parser_resolves_fragments(self):
        first = _ipv4(A, B, 6, _tcp(43000, 80), ipid=0x55, mf=True)
        mid = _ipv4(A, B, 6, b"\x00" * 24, ipid=0x55, frag_off=4)
        orphan = _ipv4(A, B, 6, b"\x00" * 24, ipid=0x56, frag_off=4)
        buf = _frames(_eth(first), _eth(mid), _eth(orphan))
        py_rows, py_n, py_sk = native.parse_frames_packed_py(buf)
        assert py_n == 2 and py_sk == 1
        ports = np.asarray(py_rows)[:2, 2]
        assert list(ports >> 16) == [43000, 43000]
        assert list(ports & 0xFFFF) == [80, 80]
        if native.available():
            nat_rows, n, sk = native.parse_frames_packed(buf)
            assert (n, sk) == (py_n, py_sk)
            np.testing.assert_array_equal(np.asarray(nat_rows)[:n],
                                          np.asarray(py_rows)[:py_n])

    def test_fragments_straddle_parse_calls(self):
        first = _ipv4(A, B, 6, _tcp(44000, 8080), ipid=0xAB, mf=True)
        native.parse_frames_py(_frames(_eth(first)))
        mid = _ipv4(A, B, 6, b"\x00" * 8, ipid=0xAB, frag_off=2)
        rows = native.parse_frames_py(_frames(_eth(mid)))
        assert rows.shape[0] == 1 and rows[0, COL_SPORT] == 44000

    def test_different_ipid_does_not_alias(self):
        f1 = _ipv4(A, B, 6, _tcp(45000, 80), ipid=1, mf=True)
        f2 = _ipv4(A, B, 6, _tcp(46000, 81), ipid=2, mf=True)
        m1 = _ipv4(A, B, 6, b"\x00" * 8, ipid=1, frag_off=2)
        m2 = _ipv4(A, B, 6, b"\x00" * 8, ipid=2, frag_off=2)
        rows = native.parse_frames_py(_frames(_eth(f1), _eth(f2),
                                              _eth(m1), _eth(m2)))
        assert list(rows[:, COL_SPORT]) == [45000, 46000, 45000, 46000]


class TestFragmentPoisoning:
    def test_icmp_quoted_header_cannot_poison_tracker(self):
        """Review r04: a forged ICMP error quoting a fake first
        fragment must NOT record attacker ports into the tracker."""
        from cilium_tpu.core.pcap import _FRAGS

        victim_src, victim_dst = bytes([10, 7, 3, 1]), bytes([10, 7, 4, 1])
        # attacker's ICMP error quotes a FIRST-fragment header for the
        # victim's datagram id with chosen ports 6666->7777
        quoted = _ipv4(victim_src, victim_dst, 6,
                       _tcp(6666, 7777), ipid=0xBEEF, mf=True)
        icmp = struct.pack("!BBHI", 3, 0, 0, 0) + quoted
        err = _ipv4(bytes([10, 9, 9, 9]), victim_src, 1, icmp)
        native.parse_frames_py(_frames(_eth(err)))
        key = (victim_src, victim_dst, 6,
               struct.pack("!H", 0xBEEF))
        assert _FRAGS.lookup(key) is None  # nothing recorded
        # the victim's real mid-fragment therefore DROPS (no tracked
        # first fragment) instead of resolving to attacker ports
        mid = _ipv4(victim_src, victim_dst, 6, b"\x00" * 16,
                    ipid=0xBEEF, frag_off=2)
        rows = native.parse_frames_py(_frames(_eth(mid)))
        assert rows.shape[0] == 0

    def test_inner_fragment_resolution_packed_matches_python(self):
        """Review r04: decapped INNER fragments must resolve on the
        packed fast path too (and an unresolvable inner mid-fragment
        falls back to the outer row, both parsers)."""
        from cilium_tpu.core.packets import VXLAN_PORT

        def vxlan(inner):
            payload = struct.pack("!II", 0x08000000, 42 << 8) + _eth(inner)
            udp = struct.pack("!HHHH", 51000, VXLAN_PORT,
                              8 + len(payload), 0) + payload
            return _ipv4(bytes([192, 168, 5, 1]), bytes([192, 168, 5, 2]),
                         17, udp)

        first = _ipv4(A, B, 6, _tcp(47000, 5432), ipid=0xC1, mf=True)
        mid = _ipv4(A, B, 6, b"\x00" * 24, ipid=0xC1, frag_off=4)
        orphan = _ipv4(A, B, 6, b"\x00" * 24, ipid=0xC2, frag_off=4)
        buf = _frames(_eth(vxlan(first)), _eth(vxlan(mid)),
                      _eth(vxlan(orphan)))
        py_rows, py_n, py_sk = native.parse_frames_packed_py(buf)
        py_rows = np.asarray(py_rows)[:py_n]
        # first + mid resolve to the inner flow; the orphan falls back
        # to the OUTER row (vxlan UDP tuple)
        assert py_n == 3 and py_sk == 0
        assert list(py_rows[:2, 2] >> 16) == [47000, 47000]
        assert (py_rows[2, 2] >> 16) == 51000
        if native.available():
            nat_rows, n, sk = native.parse_frames_packed(buf)
            assert (n, sk) == (py_n, py_sk)
            np.testing.assert_array_equal(np.asarray(nat_rows)[:n],
                                          py_rows)
