"""Multi-process sharded datapath under jax.distributed (SURVEY.md
§2c rows 33-34: per-node sharding / multi-host).

Spawns 2 processes x 4 virtual CPU devices; both join one distributed
runtime, build the global 8-device mesh, and run the full sharded
step.  Validates the program a 2-host pod slice would run, with the
collectives crossing the process boundary.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.xfail(
    reason="jax 0.4.37 CPU backend cannot run cross-process "
           "collectives ('Multiprocess computations aren't "
           "implemented on the CPU backend', raised from "
           "device_put in both children) — an environment limit, "
           "not a code fault; the program is the one a 2-host pod "
           "slice runs",
    strict=False)
def test_two_process_sharded_step():
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    n_proc, dev_per_proc = 2, 4
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={dev_per_proc}"
    ).strip()
    env.pop("CILIUM_TPU_DRYRUN_CHILD", None)

    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "cilium_tpu.testing.multihost_child",
             coordinator, str(n_proc), str(pid), str(dev_per_proc)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        for pid in range(n_proc)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))

    assert all(o["n_devices"] == n_proc * dev_per_proc for o in outs)
    # psum-replicated counters: every process reports the same GLOBAL
    # forwarded/dropped totals, covering the whole sharded batch
    assert outs[0]["forwarded"] == outs[1]["forwarded"] > 0
    assert outs[0]["dropped"] == outs[1]["dropped"]
    total = outs[0]["forwarded"] + outs[0]["dropped"] + outs[0]["overflow"]
    assert total == 32 * n_proc * dev_per_proc
