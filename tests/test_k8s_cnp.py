"""CNP adapter + named ports (SURVEY.md §2b rows 11, 10; VERDICT r02
items 5 and 8): upstream-format CiliumNetworkPolicy objects import
into the repository, namespaced correctly, deletable by identity
labels; named ports resolve against the endpoint port registry.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.k8s import CNPWatcher, cnp_identity_labels, rules_from_cnp
from cilium_tpu.labels import LabelSet
from cilium_tpu.policy.api import PortProtocol


CNP = {
    "apiVersion": "cilium.io/v2",
    "kind": "CiliumNetworkPolicy",
    "metadata": {"name": "allow-web-to-db", "namespace": "prod",
                 "uid": "abc-123"},
    "spec": {
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "5432",
                                     "protocol": "TCP"}]}]},
        ],
    },
}


class TestCNPTranslation:
    def test_subject_and_peers_are_namespaced(self):
        rules = rules_from_cnp(CNP)
        assert len(rules) == 1
        r = rules[0]
        sel = dict(r.endpoint_selector.match_labels)
        assert sel["k8s:io.kubernetes.pod.namespace"] == "prod"
        peer = dict(r.ingress[0].from_endpoints[0].match_labels)
        assert peer["k8s:io.kubernetes.pod.namespace"] == "prod"

    def test_derived_labels_identify_the_cnp(self):
        r = rules_from_cnp(CNP)[0]
        assert "k8s:io.cilium.k8s.policy.name=allow-web-to-db" in r.labels
        assert "k8s:io.cilium.k8s.policy.namespace=prod" in r.labels
        assert "k8s:io.cilium.k8s.policy.uid=abc-123" in r.labels

    def test_explicit_namespace_not_overridden(self):
        cnp = {**CNP, "spec": {
            "endpointSelector": {"matchLabels": {
                "app": "db", "k8s:io.kubernetes.pod.namespace": "other"}},
            "ingress": [{"fromEndpoints": [{}]}],
        }}
        r = rules_from_cnp(cnp)[0]
        sel = dict(r.endpoint_selector.match_labels)
        assert sel["k8s:io.kubernetes.pod.namespace"] == "other"

    def test_specs_plural(self):
        cnp = {**CNP}
        cnp.pop("spec", None)
        cnp = {**cnp, "specs": [CNP["spec"], CNP["spec"]]}
        assert len(rules_from_cnp(cnp)) == 2

    def test_clusterwide_skips_namespacing(self):
        ccnp = {**CNP, "kind": "CiliumClusterwideNetworkPolicy"}
        r = rules_from_cnp(ccnp)[0]
        sel = dict(r.endpoint_selector.match_labels)
        assert "k8s:io.kubernetes.pod.namespace" not in sel

    def test_rejects_non_cnp(self):
        with pytest.raises(ValueError, match="not a CNP"):
            rules_from_cnp({"kind": "NetworkPolicy", "metadata": {}})


class TestCNPWatcher:
    def test_add_update_delete_lifecycle(self):
        d = Daemon(DaemonConfig(backend="interpreter"))
        w = CNPWatcher(d.repo)
        w.on_add(CNP)
        assert len(d.repo.rules()) == 1
        # update: replace with a 2-spec object
        cnp2 = {**CNP}
        cnp2.pop("spec", None)
        cnp2 = {**cnp2, "specs": [CNP["spec"], CNP["spec"]]}
        w.on_update(cnp2)
        assert len(d.repo.rules()) == 2
        w.on_delete(CNP)
        assert d.repo.rules() == []

    def test_cnp_through_policy_import_and_enforced(self):
        """The e2e replay: an upstream-format CNP through `policy
        import`, then packets verdict per its rules."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        ns = "k8s:io.kubernetes.pod.namespace=prod"
        web = d.add_endpoint("web-1", ("10.0.1.1",),
                             ["k8s:app=web", ns])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db", ns])
        d.policy_import(CNP)  # kind-detected, k8s-translated
        d.start()
        evb = d.process_batch(make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=40000,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
            dict(src="10.0.1.1", dst="10.0.2.1", sport=40001,
                 dport=80, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
        ]).data, now=10)
        assert list(evb.verdict) == [1, 0]


class TestNamedPorts:
    def test_parse_accepts_valid_names(self):
        pp = PortProtocol.from_dict({"port": "http-metrics",
                                     "protocol": "TCP"})
        assert pp.is_named
        assert pp.port_range() is None
        assert pp.port_range({"http-metrics": 9100}) == (9100, 9100)

    def test_parse_rejects_bad_names(self):
        for bad in ("Has-Upper", "-lead", "trail-", "a--b",
                    "way-too-long-port-name", "1234567890123456"):
            with pytest.raises(ValueError):
                PortProtocol.from_dict({"port": bad, "protocol": "TCP"})

    def test_named_port_resolves_against_endpoint_registry(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"],
                            named_ports={"postgres": 5432})
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [
                {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
                 "toPorts": [{"ports": [{"port": "postgres",
                                         "protocol": "TCP"}]}]},
            ],
        }])
        d.start()
        evb = d.process_batch(make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=40000,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
            dict(src="10.0.1.1", dst="10.0.2.1", sport=40001,
                 dport=5433, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
        ]).data, now=10)
        assert list(evb.verdict) == [1, 0]

    def test_unresolved_named_port_matches_nothing(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [
                {"fromEndpoints": [{}],
                 "toPorts": [{"ports": [{"port": "nosuch",
                                         "protocol": "TCP"}]}]},
            ],
        }])
        d.start()
        evb = d.process_batch(make_batch([
            dict(src="10.0.9.9", dst="10.0.2.1", sport=40000,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
        ]).data, now=10)
        assert list(evb.verdict) == [0]  # enforcing, nothing matches

    def test_late_endpoint_binds_the_name_for_itself_only(self):
        """A named port binds strictly per endpoint (r05, upstream
        semantics): a later endpoint defining the name enforces under
        its OWN binding, and the name never leaks onto an endpoint
        that does not define it — even one with identical labels."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [
                {"fromEndpoints": [{}],
                 "toPorts": [{"ports": [{"port": "postgres",
                                         "protocol": "TCP"}]}]},
            ],
        }])
        d.start()
        pkt = make_batch([dict(
            src="10.0.9.9", dst="10.0.2.1", sport=40000, dport=5432,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data
        assert list(d.process_batch(pkt, now=10).verdict) == [0]
        db2 = d.add_endpoint("db-2", ("10.0.2.2",), ["k8s:app=db"],
                             named_ports={"postgres": 5432})
        pkt2 = make_batch([
            # db-2 defines the name: its own ingress allows 5432
            dict(src="10.0.9.9", dst="10.0.2.2", sport=40002,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db2.id, dir=0),
            # db-1 does not: the name still matches nothing there
            dict(src="10.0.9.9", dst="10.0.2.1", sport=40003,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id, dir=0),
        ]).data
        assert list(d.process_batch(pkt2, now=20).verdict) == [1, 0]
