"""Golden policy-resolution tests.

Modeled on upstream cilium ``pkg/policy``'s resolve/repository/mapstate
tests (SURVEY.md §4): construct rule sets + identities in memory and
assert the resolved verdicts — no datapath needed.
"""

import pytest

from cilium_tpu.labels import LabelSet
from cilium_tpu.identity import CachingIdentityAllocator, ID_WORLD, ID_HOST
from cilium_tpu.policy import (
    DIR_EGRESS,
    DIR_INGRESS,
    PROTO_ICMP,
    PROTO_OTHER,
    PROTO_TCP,
    PROTO_UDP,
    PolicyRepository,
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
    rules_from_obj,
)

WEB = LabelSet.parse("k8s:app=web")
DB = LabelSet.parse("k8s:app=db")
OTHER = LabelSet.parse("k8s:app=other")


@pytest.fixture
def repo():
    alloc = CachingIdentityAllocator()
    r = PolicyRepository(alloc)
    return r


def setup_ids(repo):
    alloc = repo.allocator
    return {
        "web": alloc.allocate(WEB).numeric_id,
        "db": alloc.allocate(DB).numeric_id,
        "other": alloc.allocate(OTHER).numeric_id,
    }


L3_L4_RULE = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
    "labels": ["db-ingress"],
}]


def test_l3_l4_allow(repo):
    ids = setup_ids(repo)
    repo.add_obj(L3_L4_RULE)
    pol = repo.resolve(DB)
    # web -> db:5432/TCP allowed
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 5432)
    assert v == VERDICT_ALLOW
    # wrong port denied (default-deny engaged)
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 80)
    assert v == VERDICT_DEFAULT_DENY
    # wrong proto denied
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_UDP, 5432)
    assert v == VERDICT_DEFAULT_DENY
    # other identity denied
    v, _ = pol.lookup(DIR_INGRESS, ids["other"], PROTO_TCP, 5432)
    assert v == VERDICT_DEFAULT_DENY
    # egress unaffected: no egress rules -> default allow
    v, _ = pol.lookup(DIR_EGRESS, ids["other"], PROTO_TCP, 1)
    assert v == VERDICT_ALLOW


def test_non_selected_endpoint_default_allow(repo):
    ids = setup_ids(repo)
    repo.add_obj(L3_L4_RULE)
    pol = repo.resolve(WEB)  # rule selects db, not web
    v, _ = pol.lookup(DIR_INGRESS, ids["other"], PROTO_TCP, 22)
    assert v == VERDICT_ALLOW


def test_l3_only_rule_allows_all_ports(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "web"}}]}],
    }])
    pol = repo.resolve(DB)
    for proto, port in [(PROTO_TCP, 80), (PROTO_UDP, 53), (PROTO_ICMP, 8),
                        (PROTO_OTHER, 0)]:
        v, _ = pol.lookup(DIR_INGRESS, ids["web"], proto, port)
        assert v == VERDICT_ALLOW, (proto, port)
    v, _ = pol.lookup(DIR_INGRESS, ids["other"], PROTO_TCP, 80)
    assert v == VERDICT_DEFAULT_DENY


def test_l4_only_wildcard_peer(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"toPorts": [{"ports": [{"port": "443",
                                             "protocol": "TCP"}]}]}],
    }])
    pol = repo.resolve(DB)
    # anyone can reach 443/TCP, including world
    for ident in (ids["web"], ids["other"], ID_WORLD, 0):
        v, _ = pol.lookup(DIR_INGRESS, ident, PROTO_TCP, 443)
        assert v == VERDICT_ALLOW
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 444)
    assert v == VERDICT_DEFAULT_DENY


def test_deny_takes_precedence(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{}]}],  # allow all endpoints
        "ingressDeny": [{
            "fromEndpoints": [{"matchLabels": {"app": "other"}}],
        }],
    }])
    pol = repo.resolve(DB)
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 80)
    assert v == VERDICT_ALLOW
    v, _ = pol.lookup(DIR_INGRESS, ids["other"], PROTO_TCP, 80)
    assert v == VERDICT_DENY


def test_deny_narrow_port_within_broad_allow(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "web"}}]}],
        "ingressDeny": [{
            "fromEndpoints": [{"matchLabels": {"app": "web"}}],
            "toPorts": [{"ports": [{"port": "22", "protocol": "TCP"}]}],
        }],
    }])
    pol = repo.resolve(DB)
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 80)
    assert v == VERDICT_ALLOW
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 22)
    assert v == VERDICT_DENY
    v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_UDP, 22)
    assert v == VERDICT_ALLOW


def test_port_range(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{
            "fromEndpoints": [{"matchLabels": {"app": "web"}}],
            "toPorts": [{"ports": [{"port": "8000", "endPort": 8999,
                                    "protocol": "TCP"}]}],
        }],
    }])
    pol = repo.resolve(DB)
    for port, want in [(7999, VERDICT_DEFAULT_DENY), (8000, VERDICT_ALLOW),
                       (8500, VERDICT_ALLOW), (8999, VERDICT_ALLOW),
                       (9000, VERDICT_DEFAULT_DENY)]:
        v, _ = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, port)
        assert v == want, port


def test_proto_any_expands_to_tcp_udp_sctp(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{
            "fromEndpoints": [{"matchLabels": {"app": "web"}}],
            "toPorts": [{"ports": [{"port": "53", "protocol": "ANY"}]}],
        }],
    }])
    pol = repo.resolve(DB)
    assert pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 53)[0] == VERDICT_ALLOW
    assert pol.lookup(DIR_INGRESS, ids["web"], PROTO_UDP, 53)[0] == VERDICT_ALLOW
    # port rules never cover ICMP/OTHER
    assert pol.lookup(DIR_INGRESS, ids["web"], PROTO_ICMP, 53)[0] == VERDICT_DEFAULT_DENY
    assert pol.lookup(DIR_INGRESS, ids["web"], PROTO_OTHER, 53)[0] == VERDICT_DEFAULT_DENY


def test_l7_redirect(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{
            "fromEndpoints": [{"matchLabels": {"app": "web"}}],
            "toPorts": [{
                "ports": [{"port": "80", "protocol": "TCP"}],
                "rules": {"http": [{"method": "GET", "path": "/public"}]},
            }],
        }],
    }])
    pol = repo.resolve(DB)
    v, proxy = pol.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 80)
    assert v == VERDICT_REDIRECT
    assert proxy >= 10000
    assert pol.redirects


def test_entities_and_cidr(repo):
    ids = setup_ids(repo)
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEntities": ["host"]},
            {"fromCIDR": ["192.168.0.0/16"],
             "toPorts": [{"ports": [{"port": "9000", "protocol": "TCP"}]}]},
        ],
    }])
    pol = repo.resolve(DB)
    assert pol.lookup(DIR_INGRESS, ID_HOST, PROTO_TCP, 1)[0] == VERDICT_ALLOW
    cidr_id = repo.allocator.allocate_cidr("192.168.0.0/16").numeric_id
    assert pol.lookup(DIR_INGRESS, cidr_id, PROTO_TCP, 9000)[0] == VERDICT_ALLOW
    assert pol.lookup(DIR_INGRESS, cidr_id, PROTO_TCP, 9001)[0] == VERDICT_DEFAULT_DENY


def test_match_expressions(repo):
    alloc = repo.allocator
    a = alloc.allocate(LabelSet.parse("k8s:env=prod", "k8s:app=a"))
    b = alloc.allocate(LabelSet.parse("k8s:env=dev", "k8s:app=b"))
    repo.add_obj([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{
            "fromEndpoints": [{
                "matchExpressions": [
                    {"key": "env", "operator": "In", "values": ["prod"]},
                ],
            }],
        }],
    }])
    pol = repo.resolve(DB)
    assert pol.lookup(DIR_INGRESS, a.numeric_id, PROTO_TCP, 1)[0] == VERDICT_ALLOW
    assert pol.lookup(DIR_INGRESS, b.numeric_id, PROTO_TCP, 1)[0] == VERDICT_DEFAULT_DENY


def test_revision_bumps_and_cache_invalidation(repo):
    ids = setup_ids(repo)
    rev0 = repo.revision
    repo.add_obj(L3_L4_RULE)
    assert repo.revision == rev0 + 1
    pol1 = repo.resolve(DB)
    pol2 = repo.resolve(DB)
    assert pol1 is pol2  # distillery cache hit
    repo.delete_by_labels(["db-ingress"])
    pol3 = repo.resolve(DB)
    assert pol3 is not pol1
    # rule gone: default allow again
    v, _ = pol3.lookup(DIR_INGRESS, ids["web"], PROTO_TCP, 5432)
    assert v == VERDICT_ALLOW
