"""Binary flow.proto wire encoding (VERDICT r03 item 5).

Golden test pins the byte-exact encoding of one known flow; the
round-trip goes through the schema-less protobuf decoder; the gRPC
Observer serves BOTH encodings on the same method paths.
"""

import numpy as np
import pytest

from cilium_tpu.flow.flow import Flow, FlowEndpoint
from cilium_tpu.flow.proto import (
    decode_get_flows_request,
    decode_message,
    decode_varint,
    encode_flow,
    encode_get_flows_request,
    encode_get_flows_response,
    encode_varint,
)


def _flow() -> Flow:
    return Flow(
        time=1700000000.5, uuid=42, verdict=1, drop_reason=0,
        event_type=9, is_reply=False, traffic_direction=0, proto=6,
        flags=0x12, length=64,
        source=FlowEndpoint(ip="10.0.1.1", port=40000, identity=4321,
                            labels=("k8s:app=web",),
                            pod_name="default/web-0", endpoint_id=2),
        destination=FlowEndpoint(ip="10.0.2.1", port=5432,
                                 identity=4400,
                                 labels=("k8s:app=db",),
                                 pod_name="default/db-0",
                                 endpoint_id=1))


GOLDEN_HEX = (
    "0a0c0880e2cfaa061080cab5ee0110012a160a0831302e302e312e311208"
    "31302e302e322e311801320f0a0d08c0b80210b82a1a04100128014222080210"
    "e1211a0764656661756c74220b6b38733a6170703d7765622a057765622d304a"
    "20080110b0221a0764656661756c74220a6b38733a6170703d64622a0464622d"
    "3050015a066e6f64652d319a01020809b00101d20100920202343282ea302d31"
    "302e302e312e313a3430303030202d3e2031302e302e322e313a353433322054"
    "435020464f52574152444544")


class TestWirePrimitives:
    def test_varint_round_trip(self):
        for n in (0, 1, 127, 128, 300, 2 ** 32 - 1, 2 ** 56):
            data = encode_varint(n)
            got, off = decode_varint(data, 0)
            assert got == n and off == len(data)

    def test_high_field_number_tag(self):
        # field 100000 (Summary) needs a 3-byte tag varint
        tag = encode_varint((100000 << 3) | 2)
        assert tag == bytes.fromhex("82ea30")


class TestFlowEncoding:
    def test_golden_bytes(self):
        """Byte-exact known-flow encoding (field numbers per
        api/v1/flow/flow.proto)."""
        assert encode_flow(_flow(), node_name="node-1").hex() == \
            GOLDEN_HEX

    def test_round_trip_through_generic_decoder(self):
        msg = decode_message(encode_flow(_flow(), node_name="node-1"))
        # time = 1: Timestamp{seconds=1, nanos=2}
        ts = decode_message(msg[1][0])
        assert ts[1] == [1700000000] and ts[2] == [500000000]
        assert msg[2] == [1]  # Verdict FORWARDED
        ip = decode_message(msg[5][0])
        assert ip[1] == [b"10.0.1.1"] and ip[2] == [b"10.0.2.1"]
        assert ip[3] == [1]  # IPv4
        l4 = decode_message(msg[6][0])
        tcp = decode_message(l4[1][0])  # oneof TCP = 1
        assert tcp[1] == [40000] and tcp[2] == [5432]
        flags = decode_message(tcp[3][0])
        assert flags == {2: [1], 5: [1]}  # SYN + ACK
        src = decode_message(msg[8][0])
        assert src[1] == [2] and src[2] == [4321]
        assert src[3] == [b"default"] and src[5] == [b"web-0"]
        assert src[4] == [b"k8s:app=web"]
        dst = decode_message(msg[9][0])
        assert dst[2] == [4400]
        assert msg[10] == [1]  # FlowType L3_L4
        assert msg[11] == [b"node-1"]
        ev = decode_message(msg[19][0])
        assert ev[1] == [9]  # CiliumEventType PolicyVerdictNotify
        assert msg[22] == [1]  # TrafficDirection INGRESS
        assert decode_message(msg[26][0]) == {}  # BoolValue false
        assert msg[34] == [b"42"]
        assert msg[100000][0].decode().endswith("TCP FORWARDED")

    def test_drop_flow_carries_drop_reason(self):
        f = _flow()
        f.verdict = 2
        f.drop_reason = 1  # policy denied
        msg = decode_message(encode_flow(f))
        assert msg[2] == [2]  # DROPPED
        assert msg[3] == [1]  # deprecated raw code
        assert msg[25] == [133]  # DropReason POLICY_DENIED

    def test_icmp_and_udp_l4(self):
        f = _flow()
        f.proto = 17
        l4 = decode_message(decode_message(encode_flow(f))[6][0])
        udp = decode_message(l4[2][0])
        assert udp[1] == [40000] and udp[2] == [5432]
        f.proto = 1
        f.destination.port = 3  # ICMP type rides the dport column
        l4 = decode_message(decode_message(encode_flow(f))[6][0])
        icmp = decode_message(l4[3][0])
        assert icmp[1] == [3]

    def test_l7_http_record(self):
        f = _flow()
        f.l7 = {"type": "REQUEST",
                "http": {"code": 0, "method": "GET", "url": "/x",
                         "protocol": "HTTP/1.1"}}
        msg = decode_message(encode_flow(f))
        assert msg[10] == [2]  # FlowType L7
        l7 = decode_message(msg[15][0])
        assert l7[1] == [1]  # REQUEST
        http = decode_message(l7[101][0])
        assert http[2] == [b"GET"] and http[3] == [b"/x"]

    def test_truncated_field_raises(self):
        """r04 review: a corrupt request must error, not decode to
        partial filters (a dropped verdict filter would return ALL
        flows)."""
        good = encode_get_flows_request(number=7)
        # declare a length-delimited field longer than the payload
        bad = good + bytes.fromhex("2aff01")  # field 5, len 255, EOF
        with pytest.raises((ValueError, IndexError)):
            decode_message(bad)

    def test_request_round_trip(self):
        raw = encode_get_flows_request(
            number=50, whitelist=[{"source_ip": "10.0.1.1",
                                   "verdict": 2}],
            blacklist=[{"destination_ip": "10.0.2.2"}])
        req = decode_get_flows_request(raw)
        assert req["number"] == 50
        assert req["whitelist"] == [{"source_ip": "10.0.1.1",
                                     "verdict": 2}]
        assert req["blacklist"] == [{"destination_ip": "10.0.2.2"}]

    def test_unsupported_filter_field_matches_nothing(self):
        """A filter carrying a field this implementation can't evaluate
        must match NO flows — a blacklist on an unknown field must not
        become exclude-everything (review r04)."""
        from cilium_tpu.flow.observer import FlowFilter
        from cilium_tpu.flow.proto import _msg_field, _str_field

        from cilium_tpu.flow.proto import _varint_field

        # FlowFilter field 9 (source_pod) is not implemented
        raw = (_varint_field(1, 10)
               + _msg_field(4, _str_field(9, "default/web-0")))
        req = decode_get_flows_request(raw)
        [f] = req["blacklist"]
        assert f.get("unsupported") is True
        assert not FlowFilter(**f).mask(
            type("R", (), {})(), np.arange(3)).any()


class TestBinaryObserver:
    def test_binary_and_json_clients_share_one_server(self, tmp_path):
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch
        from cilium_tpu.flow.grpc_server import (BinaryObserverClient,
                                                 ObserverClient, serve)

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                                node_name="n1"))
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.process_batch(make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=5432,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data, now=5)
        addr = f"unix://{tmp_path}/hubble.sock"
        server = serve(d.observer, addr, node_name="n1")
        try:
            # binary surface: a stock-stub-shaped client
            bc = BinaryObserverClient(addr)
            msgs = bc.get_flows(number=10)
            assert len(msgs) == 1
            flow = decode_message(msgs[0][1][0])  # response.flow = 1
            ip = decode_message(flow[5][0])
            assert ip[1] == [b"10.0.1.1"]
            assert msgs[0][1000] == [b"n1"]  # response.node_name
            st = bc.server_status()
            assert st["seen_flows"] >= 1
            bc.close()
            # JSON surface still serves on the same method path
            jc = ObserverClient(addr)
            flows = jc.get_flows(number=10)
            assert flows and flows[0]["IP"]["source"] == "10.0.1.1"
            jc.close()
        finally:
            server.stop(grace=0.5)

    def test_binary_verdict_filter_maps_wire_enum(self, tmp_path):
        """r04 review: wire DROPPED(2) must match BOTH internal drop
        codes (explicit deny AND default deny), and wire FORWARDED(1)
        only the allows."""
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch
        from cilium_tpu.flow.grpc_server import (BinaryObserverClient,
                                                 serve)

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"app": "web"}}]}]}])
        d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        mk = lambda src, sport: make_batch([dict(
            src=src, dst="10.0.2.1", sport=sport, dport=5432,
            proto=6, flags=TCP_SYN, ep=db.id, dir=0)]).data
        d.process_batch(mk("10.0.1.1", 40000), now=5)  # allow
        d.process_batch(mk("10.9.9.9", 40001), now=6)  # default deny
        addr = f"unix://{tmp_path}/hb2.sock"
        server = serve(d.observer, addr)
        try:
            bc = BinaryObserverClient(addr)
            dropped = bc.get_flows(number=10,
                                   whitelist=[{"verdict": 2}])
            fwd = bc.get_flows(number=10, whitelist=[{"verdict": 1}])
            assert len(dropped) == 1 and len(fwd) == 1
            drop_flow = decode_message(dropped[0][1][0])
            assert drop_flow[2] == [2]  # wire DROPPED
            bc.close()
            # blacklist excludes (r04 review: it was decoded then
            # silently ignored)
            from cilium_tpu.flow.observer import FlowFilter

            flows = d.observer.get_flows(
                number=10, blacklist=[FlowFilter(verdict=1)])
            assert flows and all(f.verdict != 1 for f in flows)
        finally:
            server.stop(grace=0.5)
