"""Transparent encryption (reference: upstream --enable-wireguard,
pkg/wireguard): RFC-vector-validated X25519 + ChaCha20-Poly1305,
node-pair session keys derived from registry-published public keys,
sealed batch transport with replay protection and epoch rotation.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.encryption import (DecryptError, EncryptedChannel,
                                   EncryptionManager, NodeKeypair,
                                   derive_session_keys)
from cilium_tpu.kvstore import InMemoryKVStore
from cilium_tpu.native import crypto


class TestRFCVectors:
    def test_x25519_vector1(self):
        k = bytes.fromhex("a546e36bf0527c9d3b16154b82465edd"
                          "62144c0ac1fc5a18506a2244ba449ac4")
        u = bytes.fromhex("e6db6867583030db3594c1a424b15f7c"
                          "726624ec26b3353b10a903a6d0ab1c4c")
        want = ("c3da55379de9c6908e94ea4df28d084f"
                "32eccf03491c71f754b4075577a28552")
        assert crypto.x25519(k, u).hex() == want
        assert crypto._x25519_py(k, u).hex() == want

    def test_x25519_dh(self):
        ask = bytes.fromhex("77076d0a7318a57d3c16c17251b26645"
                            "df4c2f87ebc0992ab177fba51db92c2a")
        bsk = bytes.fromhex("5dab087e624a8a4b79e17f8b83800ee6"
                            "6f3bb1292618b6fd1c2f8b27ff88e0eb")
        shared = ("4a5d9d5ba4ce2de1728e3bf480350f25"
                  "e07e21c947d19e3376f09b3c1e161742")
        apk = crypto.x25519_base(ask)
        bpk = crypto.x25519_base(bsk)
        assert crypto.x25519(ask, bpk).hex() == shared
        assert crypto.x25519(bsk, apk).hex() == shared

    def test_aead_vector(self):
        key = bytes(range(0x80, 0xA0))
        nonce = bytes.fromhex("070000004041424344454647")
        aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
        pt = (b"Ladies and Gentlemen of the class of '99: If I could "
              b"offer you only one tip for the future, sunscreen "
              b"would be it.")
        ct = crypto.aead_seal(key, nonce, aad, pt)
        assert ct[-16:].hex() == "1ae10b594f09e26a7e902ecbd0600691"
        assert crypto.aead_open(key, nonce, aad, ct) == pt
        # tamper -> reject
        bad = ct[:10] + bytes([ct[10] ^ 1]) + ct[11:]
        assert crypto.aead_open(key, nonce, aad, bad) is None

    def test_native_matches_python(self):
        import os
        if not crypto.available():
            pytest.skip("no native crypto (g++ missing)")
        rng = np.random.default_rng(3)
        for i in range(8):
            k, p = bytes(rng.bytes(32)), bytes(rng.bytes(32))
            assert crypto.x25519(k, p) == crypto._x25519_py(k, p)
            key, nonce = bytes(rng.bytes(32)), bytes(rng.bytes(12))
            aad, pt = bytes(rng.bytes(7 * i)), bytes(rng.bytes(119 * i + 1))
            ct = crypto.aead_seal(key, nonce, aad, pt)
            assert ct == crypto._aead_seal_py(key, nonce, aad, pt)
            assert crypto._aead_open_py(key, nonce, aad, ct) == pt


class TestChannel:
    def _pair(self, epoch=0):
        a, b = NodeKeypair(), NodeKeypair()
        return (EncryptedChannel(a, b.public, epoch),
                EncryptedChannel(b, a.public, epoch))

    def test_directional_keys_agree(self):
        a, b = NodeKeypair(), NodeKeypair()
        a_send, a_recv = derive_session_keys(a, b.public)
        b_send, b_recv = derive_session_keys(b, a.public)
        assert a_send == b_recv and a_recv == b_send
        assert a_send != a_recv  # directions keyed apart

    def test_seal_open_roundtrip(self):
        ca, cb = self._pair()
        for i in range(5):
            msg = bytes([i]) * (100 + i)
            assert cb.open(ca.seal(msg)) == msg
            assert ca.open(cb.seal(msg[::-1])) == msg[::-1]

    def test_tamper_rejected(self):
        ca, cb = self._pair()
        frame = bytearray(ca.seal(b"payload"))
        frame[-1] ^= 1
        with pytest.raises(DecryptError, match="authentication"):
            cb.open(bytes(frame))

    def test_replay_rejected(self):
        ca, cb = self._pair()
        f1 = ca.seal(b"one")
        f2 = ca.seal(b"two")
        assert cb.open(f1) == b"one"
        assert cb.open(f2) == b"two"
        with pytest.raises(DecryptError, match="replay"):
            cb.open(f1)
        # a forged seq must not advance the replay window
        f3 = ca.seal(b"three")
        forged = bytearray(f3)
        forged[8:16] = (999).to_bytes(8, "little")
        with pytest.raises(DecryptError, match="authentication"):
            cb.open(bytes(forged))
        assert cb.open(f3) == b"three"

    def test_epoch_rotation(self):
        ca, cb = self._pair()
        old = ca.seal(b"old-epoch")
        ca.rotate(1)
        cb.rotate(1)
        with pytest.raises(DecryptError, match="epoch"):
            cb.open(old)
        assert cb.open(ca.seal(b"new-epoch")) == b"new-epoch"

    def test_wrong_peer_rejected(self):
        a, b, m = NodeKeypair(), NodeKeypair(), NodeKeypair()
        ca = EncryptedChannel(a, b.public)
        cm = EncryptedChannel(m, a.public)  # mallory knows a's pubkey
        with pytest.raises(DecryptError):
            cm.open(ca.seal(b"secret"))


class TestManagerEndToEnd:
    def test_registry_exchange_and_encrypted_ingest(self, tmp_path):
        """Two daemons exchange pubkeys via the shared kvstore's node
        registry; node0 seals a packed batch buffer; node1 opens it,
        parses through the NATIVE ingest path, and verdicts it — the
        full encrypted node-to-node plane."""
        from cilium_tpu import native
        from cilium_tpu.core.ingest import frames_from_batch
        from cilium_tpu.datapath.verdict import REASON_FORWARDED

        kv = InMemoryKVStore()
        d0 = Daemon(DaemonConfig(node_name="node0",
                                 backend="interpreter",
                                 enable_encryption=True,
                                 encryption_key_path=str(
                                     tmp_path / "n0.key")),
                    kvstore=kv)
        d1 = Daemon(DaemonConfig(node_name="node1",
                                 backend="interpreter",
                                 enable_encryption=True),
                    kvstore=kv)
        assert d0.encryption is not None
        # key persists across restart
        again = NodeKeypair.load_or_create(str(tmp_path / "n0.key"))
        assert again.public == d0.encryption.keypair.public

        web = d1.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        d1.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{}],
        }])
        d1.upsert_ipcache("10.0.9.9/32", 4242)
        batch = make_batch([
            dict(src="10.0.9.9", dst="10.0.1.1", sport=41000 + i,
                 dport=80, proto=6, flags=TCP_SYN, ep=web.id, dir=0)
            for i in range(32)
        ]).data
        wire = frames_from_batch(batch)

        # the DAEMON surface: seal on node0, decrypt-then-datapath
        # on node1 (the wg-device transmit/receive legs)
        sealed = d0.seal_batch("node1", wire)
        assert sealed != wire and len(sealed) == len(wire) + 32

        ev = d1.ingest_encrypted("node0", sealed, ep=web.id,
                                 direction=0, now=50)
        assert int((ev.reason == REASON_FORWARDED).sum()) == 32
        st = d1.encryption.status()
        assert st["peers"]["node0"]["opened"] == 1
        # a replayed frame is rejected at the daemon surface too
        from cilium_tpu.encryption import DecryptError
        with pytest.raises(DecryptError):
            d1.ingest_encrypted("node0", sealed, ep=web.id)

    def test_low_order_pubkey_rejected(self):
        """A peer publishing a low-order point must fail channel
        setup, not silently derive keys from an all-zero secret."""
        from cilium_tpu.native.crypto import LowOrderPointError
        kv = InMemoryKVStore()
        d0 = Daemon(DaemonConfig(node_name="node0",
                                 backend="interpreter",
                                 enable_encryption=True), kvstore=kv)
        # forge a registry entry with an all-zero pubkey
        from cilium_tpu.encryption import PUBKEY_FIELD
        d0.node_registry.register("evil", {PUBKEY_FIELD: "00" * 32})
        with pytest.raises(LowOrderPointError):
            d0.encryption.channel("evil")

    def test_unknown_peer_raises(self):
        kv = InMemoryKVStore()
        d0 = Daemon(DaemonConfig(node_name="node0",
                                 backend="interpreter",
                                 enable_encryption=True), kvstore=kv)
        with pytest.raises(KeyError):
            d0.encryption.channel("ghost")
