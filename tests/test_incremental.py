"""Incremental tensor updates (SURVEY.md §7 hard part #3).

Identity churn must patch device tensors in place: no re-resolve, no
``compile_policy``, no re-attach.  The gate tests here are the round-3
"done" criteria: attach-count stays flat under churn, the patched
tensors match a from-scratch recompile bit for bit, and patched
verdicts agree with the oracle.
"""

import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.labels import LabelSet


RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"role": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
        {"fromCIDR": ["192.168.0.0/16"],
         "toPorts": [{"ports": [{"port": "8080", "protocol": "TCP"}]}]},
    ],
    "ingressDeny": [
        {"fromEndpoints": [{"matchLabels": {"role": "banned"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
    ],
}]


def _mk(backend="tpu"):
    d = Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12))
    d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    d.start()
    return d


def _pkt(src, dst, dport, ep, flags=TCP_SYN, sport=40000):
    return dict(src=src, dst=dst, sport=sport, dport=dport, proto=6,
                flags=flags, ep=ep, dir=0)


class TestIncrementalIdentityChurn:
    def test_attach_count_flat_under_churn(self):
        d = _mk()
        db = d.endpoints.list()[0]
        attaches_before = d.loader.attach_count
        idents = []
        for i in range(20):
            ident = d.allocator.allocate(
                LabelSet.parse(f"k8s:app=w{i}", "k8s:role=web"))
            idents.append(ident)
            d.upsert_ipcache(f"10.1.0.{i + 1}/32", ident.numeric_id)
        # no re-attach happened — every event was an in-place patch
        assert d.loader.attach_count == attaches_before
        # and the datapath actually honors the patched rows
        evb = d.process_batch(make_batch([
            _pkt("10.1.0.1", "10.0.2.1", 5432, db.id),   # web: allow
            _pkt("10.1.0.1", "10.0.2.1", 9999, db.id),   # other port: deny
        ]).data, now=10)
        assert list(evb.verdict) == [1, 0]

    def test_patch_matches_full_recompile(self):
        """Bit-exact gate: after N patched adds, the device verdict
        tensor equals what a from-scratch compile produces."""
        from cilium_tpu.policy.compiler import compile_policy

        d = _mk()
        for i in range(8):
            ident = d.allocator.allocate(
                LabelSet.parse(f"k8s:app=w{i}", "k8s:role=web"))
            d.upsert_ipcache(f"10.1.0.{i + 1}/32", ident.numeric_id)
        patched = np.asarray(d.loader.state.policy.verdict)
        # recompile from the SAME resolved policies + row map
        fresh = compile_policy(list(d.loader._policies),
                               d.loader.row_map)
        np.testing.assert_array_equal(patched, fresh.verdict)

    def test_removal_resets_row(self):
        d = _mk()
        db = d.endpoints.list()[0]
        ident = d.allocator.allocate(
            LabelSet.parse("k8s:app=w0", "k8s:role=web"))
        d.upsert_ipcache("10.1.0.1/32", ident.numeric_id)
        evb = d.process_batch(make_batch([
            _pkt("10.1.0.1", "10.0.2.1", 5432, db.id)]).data, now=10)
        assert list(evb.verdict) == [1]
        attaches = d.loader.attach_count
        d.allocator.release(ident)
        assert d.loader.attach_count == attaches  # patched, not rebuilt
        # the released identity's row no longer allows 5432 (fresh flow)
        evb = d.process_batch(make_batch([
            _pkt("10.1.0.1", "10.0.2.1", 5432, db.id, sport=41000)
        ]).data, now=20)
        assert list(evb.verdict) == [0]

    def test_deny_identity_patch(self):
        d = _mk()
        db = d.endpoints.list()[0]
        ident = d.allocator.allocate(
            LabelSet.parse("k8s:app=evil", "k8s:role=banned"))
        d.upsert_ipcache("10.9.0.1/32", ident.numeric_id)
        evb = d.process_batch(make_batch([
            _pkt("10.9.0.1", "10.0.2.1", 5432, db.id)]).data, now=10)
        assert list(evb.verdict) == [2]  # explicit deny

    def test_tpu_matches_interpreter_after_churn(self):
        """Divergence gate under churn: both backends, same patches,
        same verdicts."""
        results = {}
        for backend in ("tpu", "interpreter"):
            d = _mk(backend)
            db = d.endpoints.list()[0]
            for i in range(6):
                ident = d.allocator.allocate(
                    LabelSet.parse(f"k8s:app=w{i}", "k8s:role=web"))
                d.upsert_ipcache(f"10.1.0.{i + 1}/32", ident.numeric_id)
            bad = d.allocator.allocate(
                LabelSet.parse("k8s:app=evil", "k8s:role=banned"))
            d.upsert_ipcache("10.9.0.1/32", bad.numeric_id)
            evb = d.process_batch(make_batch([
                _pkt("10.1.0.3", "10.0.2.1", 5432, db.id),
                _pkt("10.1.0.3", "10.0.2.1", 80, db.id),
                _pkt("10.9.0.1", "10.0.2.1", 5432, db.id),
                _pkt("192.168.7.7", "10.0.2.1", 8080, db.id),
            ]).data, now=10)
            results[backend] = list(evb.verdict)
        assert results["tpu"] == results["interpreter"]

    def test_patch_latency_much_cheaper_than_regen(self):
        """The point of the patch path: identity events cost ~ms, not a
        full compile.  Compare one patched add against one full
        regeneration on the same daemon."""
        d = _mk()
        # a realistically sized identity space: full regeneration has
        # to recompile every row; the patch touches one
        for i in range(400):
            ident = d.allocator.allocate(
                LabelSet.parse(f"k8s:app=m{i}", "k8s:role=web"))
            d.upsert_ipcache(f"10.2.{i // 250}.{i % 250 + 1}/32",
                             ident.numeric_id)

        # best-of-3 patch timing: a loaded 1-core CI host can inflate
        # any single measurement by scheduler noise
        patch_dt = float("inf")
        for i in range(3):
            ident = d.allocator.allocate(
                LabelSet.parse(f"k8s:app=wx{i}", "k8s:role=web"))
            t0 = time.perf_counter()
            d.upsert_ipcache(f"10.1.9.{9 + i}/32", ident.numeric_id)
            patch_dt = min(patch_dt, time.perf_counter() - t0)

        t0 = time.perf_counter()
        d.endpoints._regenerate_all()
        regen_dt = time.perf_counter() - t0
        # generous bound: patches must be at least 3x cheaper (in
        # practice ~100x on the 10k-identity set); guards regressions
        # that silently reroute churn through compile_policy
        assert patch_dt < regen_dt / 3, (patch_dt, regen_dt)


class TestLPMUpsert:
    def _roundtrip(self, base, upserts):
        """lpm_upsert over `base` must equal compile_lpm of the union."""
        from cilium_tpu.datapath.lpm import compile_lpm, lpm_upsert
        import jax.numpy as jnp
        from cilium_tpu.datapath.lpm import DeviceLPM, lookup_v4

        t = compile_lpm(dict(base))
        merged = dict(base)
        for cidr, val in upserts:
            patches = lpm_upsert(t, cidr, val)
            merged[cidr] = val
            if patches is None:
                t = compile_lpm(merged)
        want = compile_lpm(merged)
        # compare lookups over a probe set (tables may differ in block
        # allocation order; semantics must match)
        probes = []
        import ipaddress

        for cidr in merged:
            net = ipaddress.ip_network(cidr)
            lo = int(net.network_address)
            probes += [lo, lo + net.num_addresses - 1,
                       lo + net.num_addresses // 2]
        probes += [0, 0xFFFFFFFF, 0x0A000001]
        ips = jnp.asarray(np.array(probes, dtype=np.uint32))
        got = np.asarray(lookup_v4(jnp.asarray(t.l1), jnp.asarray(t.l2),
                                   jnp.asarray(t.l3), ips))
        exp = np.asarray(lookup_v4(jnp.asarray(want.l1),
                                   jnp.asarray(want.l2),
                                   jnp.asarray(want.l3), ips))
        np.testing.assert_array_equal(got, exp)

    def test_host_route_into_value_region(self):
        self._roundtrip({"10.0.0.0/8": 1}, [("10.1.2.3/32", 7)])

    def test_host_route_into_existing_blocks(self):
        self._roundtrip({"10.0.0.0/8": 1, "10.1.2.0/24": 3},
                        [("10.1.2.3/32", 7), ("10.1.2.4/32", 8)])

    def test_slash24_upsert(self):
        self._roundtrip({"10.0.0.0/8": 1}, [("10.5.6.0/24", 9)])

    def test_short_prefix_upsert(self):
        self._roundtrip({}, [("172.16.0.0/12", 4)])

    def test_short_prefix_over_children_falls_back(self):
        from cilium_tpu.datapath.lpm import compile_lpm, lpm_upsert

        t = compile_lpm({"10.1.2.0/24": 3})
        # /8 would have to paint over the child pointer -> rebuild
        assert lpm_upsert(t, "10.0.0.0/8", 5) is None

    def test_short_prefix_never_clobbers_sibling_values(self):
        """r03 review: a shorter prefix painted over a same-level
        more-specific VALUE (not just pointers) broke LPM; now any
        non-/32 takes the rebuild path."""
        from cilium_tpu.datapath.lpm import compile_lpm, lpm_upsert

        t = compile_lpm({"10.1.0.0/16": 7})
        assert lpm_upsert(t, "10.0.0.0/8", 9) is None
        # and the host mirror was not corrupted by the attempt
        assert int(t.l1[0x0A01]) == 7
        # full-roundtrip sanity via the rebuild path
        self._roundtrip({"10.1.0.0/16": 7}, [("10.0.0.0/8", 9)])

    def test_many_host_routes_until_padding_exhausts(self):
        """Pods keep landing in fresh /16s; when the block padding runs
        out lpm_upsert signals rebuild instead of corrupting."""
        from cilium_tpu.datapath.lpm import compile_lpm, lpm_upsert

        t = compile_lpm({"0.0.0.0/0": 1})
        merged = {"0.0.0.0/0": 1}
        rebuilt = 0
        for i in range(40):
            cidr = f"10.{i}.0.1/32"
            patches = lpm_upsert(t, cidr, i + 2)
            merged[cidr] = i + 2
            if patches is None:
                rebuilt += 1
                t = compile_lpm(merged)
        assert rebuilt >= 1  # padding (8 blocks) must have exhausted
        self._roundtrip(merged, [])

    def test_failed_upsert_leaves_tensors_untouched(self):
        """ADVICE r03 (low): when l3 padding is exhausted but l2 has
        headroom, lpm_upsert must NOT allocate the l2 block and point
        l1 at it before returning None — a partial mutation leaks a
        block per failed upsert."""
        from cilium_tpu.datapath.lpm import compile_lpm, lpm_upsert

        # 1 l2 block, 2 l3 blocks; block_pad=2 -> l2 has headroom
        # (1/2 used) while l3 is exhausted (2/2 used)
        t = compile_lpm({"10.0.0.1/32": 5, "10.0.1.1/32": 6},
                        block_pad=2)
        l1, l2, l3 = t.l1.copy(), t.l2.copy(), t.l3.copy()
        # fresh hi16 -> wants one l2 block (available) AND one l3
        # block (exhausted): must fail with zero side effects
        assert lpm_upsert(t, "10.9.0.1/32", 7) is None
        np.testing.assert_array_equal(t.l1, l1)
        np.testing.assert_array_equal(t.l2, l2)
        np.testing.assert_array_equal(t.l3, l3)
