"""Serving front end: admission queue, adaptive batcher, drain
runtime, backpressure, and latency telemetry (cilium_tpu/serving).

Acceptance (ISSUE 1): under Poisson-ish arrival load the serving
runtime sustains >= 90% of the offline serve_batch throughput at high
load, bounds batch shapes to the configured bucket ladder, and
reports non-zero shed counters as monitor drop events
(REASON_INGRESS_OVERFLOW) when offered load exceeds capacity — all on
CPU.
"""

import ipaddress
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.agent.config import load_config
from cilium_tpu.core.packets import (COL_DPORT, COL_DST_IP3, COL_EP,
                                     COL_FAMILY, COL_FLAGS, COL_LEN,
                                     COL_PROTO, COL_SPORT, COL_SRC_IP3,
                                     N_COLS, TCP_ACK)
from cilium_tpu.datapath.verdict import REASON_INGRESS_OVERFLOW
from cilium_tpu.monitor.api import (DROP_REASON_NAMES, MSG_DROP,
                                    DropNotify, materialize,
                                    synth_drop_batch)
from cilium_tpu.serving import (AdaptiveBatcher, IngressQueue,
                                LatencyHistogram,
                                ServingAlreadyActiveError,
                                ServingBackendError,
                                ServingNotStartedError, ServingRuntime,
                                validate_serving_config)

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]

SRC = int(ipaddress.IPv4Address("10.0.1.1"))
DST = int(ipaddress.IPv4Address("10.0.2.1"))


def _daemon(queue=8192, ladder=(256, 1024), wait_us=1000.0,
            policy="drop-tail"):
    d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12,
                            flow_ring_capacity=1 << 13,
                            serving_queue_depth=queue,
                            serving_bucket_ladder=ladder,
                            serving_max_wait_us=wait_us,
                            serving_overflow_policy=policy))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(RULES)
    return d, db


def _traffic(db_id, n, rng, sport_pool=2048):
    """Established-flow-shaped rows (bounded sport universe)."""
    rows = np.zeros((n, N_COLS), dtype=np.uint32)
    rows[:, COL_SRC_IP3] = SRC
    rows[:, COL_DST_IP3] = DST
    rows[:, COL_SPORT] = 1024 + rng.integers(0, sport_pool, n)
    rows[:, COL_DPORT] = 5432
    rows[:, COL_PROTO] = 6
    rows[:, COL_FLAGS] = TCP_ACK
    rows[:, COL_LEN] = 512
    rows[:, COL_FAMILY] = 4
    rows[:, COL_EP] = db_id
    return rows


class TestIngressQueue:
    def test_drop_tail_sheds_the_arrival_overflow(self):
        q = IngressQueue(100, "drop-tail")
        rows = np.arange(150 * N_COLS, dtype=np.uint32).reshape(150, -1)
        assert q.offer(rows[:60]) == 60
        assert q.offer(rows[60:]) == 40  # room for 40 of 90
        assert q.pending == 100
        assert q.shed == 50
        shed_rows, count = q.take_sheds()
        assert count == 50
        # drop-tail: the TAIL of the arriving chunk shed
        np.testing.assert_array_equal(shed_rows, rows[100:])
        # accounting drains: second call reports nothing
        assert q.take_sheds() == (None, 0)

    def test_drop_oldest_evicts_the_head(self):
        q = IngressQueue(100, "drop-oldest")
        rows = np.arange(160 * N_COLS, dtype=np.uint32).reshape(160, -1)
        assert q.offer(rows[:100]) == 100
        assert q.offer(rows[100:]) == 60  # all admitted; oldest shed
        assert q.pending == 100
        shed_rows, count = q.take_sheds()
        assert count == 60
        np.testing.assert_array_equal(shed_rows, rows[:60])
        got, _ = q.take(100)
        np.testing.assert_array_equal(got, rows[60:])

    def test_take_is_fifo_with_chunk_granular_arrivals(self):
        q = IngressQueue(1000)
        a = np.full((30, N_COLS), 1, dtype=np.uint32)
        b = np.full((50, N_COLS), 2, dtype=np.uint32)
        q.offer(a, t=10.0)
        q.offer(b, t=11.0)
        got, arrivals = q.take(40)
        assert len(got) == 40
        assert [c for c, _ in arrivals] == [30, 10]
        assert [t for _, t in arrivals] == [10.0, 11.0]
        assert q.pending == 40
        got2, arr2 = q.take(100)
        assert len(got2) == 40 and arr2 == [(40, 11.0)]

    def test_offer_copies_producer_buffers(self):
        """A producer refills its chunk buffer right after offer();
        the queue must have taken a copy, not a view."""
        q = IngressQueue(1000)
        buf = np.full((50, N_COLS), 1, dtype=np.uint32)
        q.offer(buf, t=0.0)
        buf[:] = 99  # producer reuses its buffer
        got, _ = q.take(50)
        assert (got == 1).all(), "queued rows aliased caller memory"

    def test_oversized_chunk_still_bounded(self):
        for policy in ("drop-tail", "drop-oldest"):
            q = IngressQueue(64, policy)
            rows = np.zeros((200, N_COLS), dtype=np.uint32)
            assert q.offer(rows) == 64
            assert q.shed == 136


class TestAdaptiveBatcher:
    def test_bucket_selection_walks_the_ladder(self):
        b = AdaptiveBatcher((256, 1024, 4096), 1000.0)
        assert b.bucket_for(1) == 256
        assert b.bucket_for(256) == 256
        assert b.bucket_for(257) == 1024
        assert b.bucket_for(4096) == 4096
        assert b.bucket_for(9999) == 4096  # callers take at most max

    def test_full_bucket_flushes_immediately(self):
        q = IngressQueue(1 << 14)
        b = AdaptiveBatcher((256, 1024), 1e6)  # 1s deadline: irrelevant
        q.offer(np.zeros((1024, N_COLS), dtype=np.uint32), t=0.0)
        batch = b.assemble(q, now=0.0)
        assert batch is not None and batch.n_valid == 1024
        assert len(batch.hdr) == 1024
        assert batch.valid.all()

    def test_partial_waits_for_the_deadline_then_pads(self):
        q = IngressQueue(1 << 14)
        b = AdaptiveBatcher((256, 1024), 500.0)  # 500us
        rows = np.ones((100, N_COLS), dtype=np.uint32)
        q.offer(rows, t=0.0)
        assert b.assemble(q, now=0.0) is None  # not due yet
        assert b.assemble(q, now=0.0002) is None
        batch = b.assemble(q, now=0.001)  # deadline passed
        assert batch is not None
        assert batch.n_valid == 100 and len(batch.hdr) == 256
        assert batch.valid[:100].all() and not batch.valid[100:].any()
        assert (batch.hdr[100:] == 0).all()  # padding rows are zeros

    def test_force_flush_ignores_the_deadline(self):
        q = IngressQueue(1 << 14)
        b = AdaptiveBatcher((256,), 1e6)
        q.offer(np.ones((7, N_COLS), dtype=np.uint32), t=0.0)
        batch = b.assemble(q, now=0.0, force=True)
        assert batch is not None and batch.n_valid == 7

    def test_consecutive_batches_get_fresh_buffers(self):
        """Ownership transfer: batch N's hdr (retained by serve_batch
        for the drain-time event join, possibly feeding an async h2d)
        must survive batch N+1 assembling the same bucket size."""
        q = IngressQueue(1 << 14)
        b = AdaptiveBatcher((256,), 0.0)
        q.offer(np.full((256, N_COLS), 7, dtype=np.uint32), t=0.0)
        first = b.assemble(q, now=1.0)
        q.offer(np.full((256, N_COLS), 9, dtype=np.uint32), t=0.0)
        second = b.assemble(q, now=1.0)
        assert first.hdr is not second.hdr
        assert (first.hdr == 7).all() and (second.hdr == 9).all()


class TestServingConfigValidation:
    def test_rejects_non_power_of_two_bucket(self):
        with pytest.raises(ValueError, match="power of two"):
            validate_serving_config(4096, (256, 1000), 100.0,
                                    "drop-tail")

    def test_rejects_unsorted_or_duplicate_ladder(self):
        with pytest.raises(ValueError, match="ascending"):
            validate_serving_config(4096, (1024, 256), 100.0,
                                    "drop-tail")
        with pytest.raises(ValueError, match="ascending"):
            validate_serving_config(4096, (256, 256), 100.0,
                                    "drop-tail")

    def test_rejects_queue_smaller_than_largest_bucket(self):
        with pytest.raises(ValueError, match="smaller than"):
            validate_serving_config(512, (256, 1024), 100.0,
                                    "drop-tail")

    def test_rejects_unknown_policy_and_negative_wait(self):
        with pytest.raises(ValueError, match="drop-tail"):
            validate_serving_config(4096, (256,), 100.0, "drop-front")
        with pytest.raises(ValueError, match=">= 0"):
            validate_serving_config(4096, (256,), -1.0, "drop-tail")

    def test_daemon_construction_validates_and_normalizes(self):
        with pytest.raises(ValueError, match="power of two"):
            Daemon(DaemonConfig(backend="interpreter",
                                serving_bucket_ladder=(100,)))
        # env-sourced strings normalize to ints at construction
        cfg = load_config(env={
            "CILIUM_TPU_SERVING_BUCKET_LADDER": "256,1024",
            "CILIUM_TPU_SERVING_QUEUE_DEPTH": "2048",
            "CILIUM_TPU_SERVING_MAX_WAIT_US": "750",
        })
        cfg.backend = "interpreter"
        d = Daemon(cfg)
        assert d.config.serving_bucket_ladder == (256, 1024)
        assert d.config.serving_queue_depth == 2048
        assert d.config.serving_max_wait_us == 750.0


class TestLatencyHistogram:
    def test_percentiles_interpolate_with_conservative_option(self):
        h = LatencyHistogram()
        assert h.percentile(0.5) is None
        for us in (10, 10, 10, 1000):
            h.record(us)
        # default: linear interpolation within the [8, 16) bucket
        assert 8 <= h.percentile(0.5) < 16
        # upper=True keeps the conservative bucket-bound read
        assert h.percentile(0.5, upper=True) == 16  # 2^4 >= 10
        assert h.percentile(0.99) >= 512  # in the 1000's bucket
        assert h.percentile(0.99, upper=True) >= 1000
        snap = h.snapshot()
        assert snap["count"] == 4 and snap["max"] == 1000
        assert snap["p50"] <= snap["p95"] <= snap["p99"]


class TestTypedErrors:
    def test_serve_batch_before_start_raises_typed(self):
        d, _db = _daemon()
        with pytest.raises(ServingNotStartedError):
            d.serve_batch(np.zeros((4, N_COLS), np.uint32))
        # the typed error IS a RuntimeError: pre-existing callers keep
        # working
        with pytest.raises(RuntimeError):
            d.serve_batch(np.zeros((4, N_COLS), np.uint32))
        d.shutdown()

    def test_submit_without_ingress_mode_raises_typed(self):
        d, _db = _daemon()
        with pytest.raises(ServingNotStartedError, match="ingress"):
            d.submit(np.zeros((4, N_COLS), np.uint32))
        d.start_serving(trace_sample=0)  # ring path only, no ingress
        with pytest.raises(ServingNotStartedError, match="ingress"):
            d.submit(np.zeros((4, N_COLS), np.uint32))
        d.stop_serving()
        d.shutdown()

    def test_double_start_raises_typed(self):
        d, _db = _daemon()
        d.start_serving(trace_sample=0)
        with pytest.raises(ServingAlreadyActiveError):
            d.start_serving()
        d.stop_serving()
        d.shutdown()

    def test_interpreter_backend_raises_typed(self):
        d = Daemon(DaemonConfig(backend="interpreter"))
        with pytest.raises(ServingBackendError, match="tpu"):
            d.start_serving()
        d.shutdown()

    def test_malformed_submit_bounces_at_the_door(self):
        """Wrong column count / dtype must raise at submit(), never
        detonate inside the drain thread batches later."""
        d, db = _daemon()
        d.start_serving(trace_sample=0, ingress=True)
        with pytest.raises(ValueError, match="column"):
            d.submit(np.zeros((4, 3), dtype=np.uint32))
        with pytest.raises(ValueError, match="integer"):
            d.submit(np.zeros((4, N_COLS), dtype=np.float32))
        # the loop is alive and well-formed traffic still serves
        rng = np.random.default_rng(11)
        assert d.submit(_traffic(db.id, 100, rng)) == 100
        fe = d.stop_serving()["front-end"]
        assert fe["verdicts"] == 100 and "error" not in fe
        d.shutdown()

    def test_drain_loop_death_is_visible(self):
        """If a dispatch fault kills the loop, submit() must raise
        and the snapshot must carry the error — never a silent
        blackhole."""
        from cilium_tpu.serving import ServingError

        def exploding(hdr, valid, n):
            raise RuntimeError("device on fire")

        rt = ServingRuntime(dispatch=exploding, queue_depth=256,
                            bucket_ladder=(256,), max_wait_us=0.0)
        rt.start()
        rt.submit(np.zeros((10, N_COLS), dtype=np.uint32))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and rt._error is None:
            time.sleep(0.005)
        with pytest.raises(ServingError, match="device on fire"):
            rt.submit(np.zeros((10, N_COLS), dtype=np.uint32))
        snap = rt.stop()
        assert "device on fire" in snap["error"]

    def test_idle_period_not_recorded_as_latency(self):
        """After a burst, the runtime idles; the last batch's
        end-to-end latency must be stamped at the idle tick, not at
        stop() an arbitrary time later."""
        rt = ServingRuntime(dispatch=lambda h, v, n: None,
                            queue_depth=256, bucket_ladder=(256,),
                            max_wait_us=0.0)
        rt.start()
        rt.submit(np.zeros((10, N_COLS), dtype=np.uint32))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if rt.stats.latency.count:
                break
            time.sleep(0.005)
        assert rt.stats.latency.count == 1, \
            "completion not stamped while idle"
        time.sleep(0.3)  # idle period that must NOT become latency
        rt.stop()
        assert rt.stats.latency.max_us < 0.2e6

    def test_runtime_submit_after_stop_raises(self):
        """A chunk offered after the final drain would queue forever,
        neither dispatched nor shed-counted — it must raise instead."""
        rt = ServingRuntime(dispatch=lambda h, v, n: None,
                            queue_depth=256, bucket_ladder=(256,),
                            max_wait_us=0.0)
        rt.start()
        rt.stop()
        with pytest.raises(ServingNotStartedError):
            rt.submit(np.zeros((4, N_COLS), np.uint32))

    def test_stop_serving_is_idempotent(self):
        d, db = _daemon()
        assert d.stop_serving() == {"windows": 0, "events": 0,
                                    "lost": 0}
        d.start_serving(trace_sample=0, ingress=True)
        rng = np.random.default_rng(0)
        d.submit(_traffic(db.id, 300, rng))
        first = d.stop_serving()
        assert first["front-end"]["verdicts"] == 300
        again = d.stop_serving()  # second stop: clean no-op
        assert again == {"windows": 0, "events": 0, "lost": 0}
        assert d.serving_stats() == {"active": False}
        d.shutdown()


class TestShapeDiscipline:
    def test_batch_shapes_never_exceed_the_ladder(self):
        """Recompile guard: every hdr handed to serve_batch is exactly
        one of the configured bucket shapes, no matter how ragged the
        arrival chunks are."""
        d, db = _daemon(ladder=(256, 1024), wait_us=200.0)
        shapes = []
        inner = d.serve_batch

        def spy(hdr, now=None, valid=None):
            shapes.append(tuple(hdr.shape))
            return inner(hdr, now=now, valid=valid)

        d.serve_batch = spy
        d.start_serving(trace_sample=0, ingress=True)
        rng = np.random.default_rng(1)
        for _ in range(40):  # ragged Poisson-ish chunk sizes
            n = max(int(rng.poisson(300)), 1)
            d.submit(_traffic(db.id, n, rng))
        stats = d.stop_serving()
        d.shutdown()
        fe = stats["front-end"]
        assert fe["batches"] > 0
        allowed = {(b, N_COLS) for b in (256, 1024)}
        assert set(shapes) <= allowed, f"off-ladder shapes: {shapes}"
        assert set(map(int, fe["batch-shapes"])) <= {256, 1024}
        # nothing lost: every admitted packet was dispatched
        assert fe["verdicts"] == fe["admitted"]

    def test_low_load_flushes_padded_on_the_deadline(self):
        d, db = _daemon(ladder=(256, 1024), wait_us=500.0)
        d.start_serving(trace_sample=0, ingress=True)
        rng = np.random.default_rng(2)
        d.submit(_traffic(db.id, 10, rng))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if d.serving_stats().get("batches"):
                break
            time.sleep(0.005)
        stats = d.stop_serving()
        d.shutdown()
        fe = stats["front-end"]
        # flushed without more traffic, padded to the SMALLEST bucket
        assert fe["batch-shapes"] == {"256": 1}
        assert fe["verdicts"] == 10 and fe["padded-rows"] == 246
        assert fe["pad-efficiency"] == pytest.approx(10 / 256, abs=1e-4)
        assert fe["queue-wait-us"]["count"] == 1

    def test_padding_rows_touch_neither_metrics_nor_events(self):
        d, db = _daemon(ladder=(256,), wait_us=0.0)
        got = []
        d.monitor.register("t", got.append)
        before = d.loader.metrics().sum()
        d.start_serving(trace_sample=0, ingress=True)
        rng = np.random.default_rng(3)
        d.submit(_traffic(db.id, 40, rng))
        d.stop_serving()
        d.shutdown()
        # metrics counted exactly the 40 real rows, not the padding
        assert d.loader.metrics().sum() - before == 40
        # no event carries a padding row (all-zero header)
        for b in got:
            assert (b.hdr.sum(axis=1) != 0).all()


class TestBackpressureAndSheds:
    def test_overflow_sheds_surface_as_monitor_drop_events(self):
        """The satellite end-to-end: shed -> REASON_INGRESS_OVERFLOW
        drop event -> flow layer -> `cilium-tpu monitor` rendering."""
        d, db = _daemon(queue=1024, ladder=(256, 1024), wait_us=100.0)
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(trace_sample=0, ingress=True)
        rng = np.random.default_rng(4)
        # one chunk twice the queue depth: sheds regardless of how
        # fast the drain loop runs
        chunk = _traffic(db.id, 2048, rng)
        accepted = d.submit(chunk)
        assert accepted <= 1024
        stats = d.stop_serving()
        d.shutdown()
        fe = stats["front-end"]
        assert fe["shed"] >= 1024
        assert fe["shed"] == fe["submitted"] - fe["admitted"]
        assert fe["shed-events"] == fe["shed"]  # retention not capped
        # monitor plane: DROP events with the new reason
        drops = [b for b in got
                 if (np.asarray(b.msg_type) == MSG_DROP).any()
                 and (np.asarray(b.reason)
                      == REASON_INGRESS_OVERFLOW).any()]
        assert drops, "sheds never reached the monitor plane"
        n_shed_events = sum(
            int((np.asarray(b.reason)
                 == REASON_INGRESS_OVERFLOW).sum()) for b in got)
        assert n_shed_events == fe["shed"]
        ev = materialize(drops[0], 0)
        assert DropNotify(ev).reason_name == "Ingress queue overflow"
        # flow layer (what `cilium-tpu monitor` / `flows` render)
        flows = [f.to_dict() for f in d.observer.get_flows(number=8192)]
        shed_flows = [f for f in flows if f.get("drop_reason")
                      == REASON_INGRESS_OVERFLOW]
        assert shed_flows
        assert shed_flows[0]["drop_reason_desc"] == \
            "INGRESS_QUEUE_OVERFLOW"
        assert shed_flows[0]["verdict"] == "DROPPED"

    def test_drop_oldest_policy_admits_fresh_traffic(self):
        # the runtime standalone (not started): drive the queue
        # directly so the drain cannot race the assertions
        dispatched = []
        rt = ServingRuntime(
            dispatch=lambda hdr, valid, n: dispatched.append(n),
            queue_depth=1024, bucket_ladder=(1024,), max_wait_us=1e6,
            overflow_policy="drop-oldest")
        old = _traffic(2, 1024, np.random.default_rng(5))
        new = _traffic(2, 512, np.random.default_rng(6))
        assert rt.submit(old) == 1024
        assert rt.submit(new) == 512  # admitted by evicting oldest
        assert rt.queue.shed == 512
        rows, _ = rt.queue.take(2048)
        np.testing.assert_array_equal(rows[-512:], new)

    def test_reason_survives_the_ring_wire_format(self):
        """REASON_INGRESS_OVERFLOW fits the ring's 4-bit reason field
        (ring row -> decode keeps the code)."""
        import jax.numpy as jnp

        from cilium_tpu.datapath.verdict import (EV_DROP, N_OUT,
                                                 OUT_EVENT, OUT_REASON)
        from cilium_tpu.monitor.ring import EventRing, ring_append, \
            ring_drain

        assert REASON_INGRESS_OVERFLOW <= 0xF
        out = np.zeros((4, N_OUT), dtype=np.uint32)
        out[:, OUT_EVENT] = EV_DROP
        out[:, OUT_REASON] = REASON_INGRESS_OVERFLOW
        ring = EventRing.create(16)
        ring = ring_append(ring, jnp.asarray(out), jnp.uint32(0),
                           trace_sample=0)
        rows, total, lost = ring_drain(ring)
        assert total == 4 and lost == 0
        assert (rows[:, OUT_REASON] == REASON_INGRESS_OVERFLOW).all()
        assert DROP_REASON_NAMES[REASON_INGRESS_OVERFLOW] == \
            "Ingress queue overflow"

    def test_synth_drop_batch_shape(self):
        hdr = _traffic(3, 5, np.random.default_rng(7))
        b = synth_drop_batch(hdr, REASON_INGRESS_OVERFLOW, 1.5)
        assert len(b) == 5
        assert (b.msg_type == MSG_DROP).all()
        assert (b.reason == REASON_INGRESS_OVERFLOW).all()
        assert (b.verdict == 0).all() and b.timestamp == 1.5


class TestServingAPI:
    def test_serving_stats_over_api_cli_and_metrics(self, tmp_path):
        from cilium_tpu.api.client import APIClient
        from cilium_tpu.api.server import APIServer
        from cilium_tpu.cli.main import main as cli_main

        d, db = _daemon()
        sock = str(tmp_path / "cilium.sock")
        server = APIServer(d, sock)
        server.start()
        try:
            c = APIClient(sock)
            assert c.serving_stats() == {"active": False}
            d.start_serving(trace_sample=0, ingress=True)
            rng = np.random.default_rng(9)
            d.submit(_traffic(db.id, 500, rng))
            deadline = time.monotonic() + 5.0
            st = {}
            while time.monotonic() < deadline:
                st = c.serving_stats()
                if st.get("verdicts"):
                    break
                time.sleep(0.01)
            assert st["active"] is True
            assert st["verdicts"] == 500
            assert st["queue-depth"] == 8192
            assert "ring" in st and "latency-us" in st
            # the CLI verb renders the same surface
            assert cli_main(["--socket", sock, "serving",
                             "stats"]) == 0
            # prometheus exposition carries the serving counters
            assert "cilium_serving_verdicts_total 500" in c.metrics()
            d.stop_serving()
        finally:
            server.stop()
            d.shutdown()


class TestServingThroughput:
    def test_sustains_90pct_of_offline_under_poisson_load(self):
        """The acceptance gate: offered load above capacity, the
        runtime keeps >= 90% of the offline serve_batch rate, stays
        on the bucket ladder, and sheds are counted.

        Gate statistic (ISSUE 11 satellite — this gate failed
        intermittently on the unmodified base tree): the legs run
        PAIRED, offline/serving back to back with the order
        ALTERNATING per rep (whichever leg runs second in a pair
        reads a few percent faster — thermal/cache settling), and
        the gate takes the BEST of {per-pair ratios, best-vs-best} —
        a throttle window that slows one whole pair cancels out of
        that pair's ratio instead of failing the suite, while the
        absolute pps is RECORDED (printed) but never asserted: on a
        shared CPU runner an absolute floor measures the machine's
        scheduling weather, not the front end."""
        B = 8192
        queue = 4 * B
        d, db = _daemon(queue=queue, ladder=(2048, B), wait_us=1000.0)
        rng = np.random.default_rng(8)
        n_batches = 12
        target = n_batches * B

        shapes = set()
        inner = d.serve_batch

        def spy(hdr, now=None, valid=None):
            shapes.add(tuple(hdr.shape))
            return inner(hdr, now=now, valid=valid)

        d.serve_batch = spy
        # compile both ladder shapes up front (shared by both sides)
        d.start_serving(trace_sample=0)
        for b in (2048, B):
            d.serve_batch(_traffic(db.id, b, rng),
                          valid=np.ones(b, dtype=bool))
        d.stop_serving()
        valid = np.ones(B, dtype=bool)
        # pre-generated traffic for BOTH sides: neither pays
        # generation inside its timed loop
        pre = [_traffic(db.id, B, rng) for _ in range(n_batches)]
        chunks = [_traffic(db.id, max(int(rng.poisson(B // 2)), 1),
                           rng) for _ in range(16)]

        def leg_offline() -> float:
            # offline ceiling: perfect pre-assembled full buckets
            d.start_serving(trace_sample=0)
            t0 = time.perf_counter()
            for h in pre:
                d.serve_batch(h, valid=valid)
            off_dt = time.perf_counter() - t0
            d.stop_serving()
            return target / off_dt

        shed_state = {"shed": 0, "events": 0, "fe": None}

        def leg_serving() -> float:
            # serving: one oversized chunk first (guaranteed sheds:
            # offered 2x the queue depth in one doorbell), then
            # Poisson chunks keeping the queue saturated until the
            # target volume is admitted
            d.start_serving(trace_sample=0, ingress=True)
            q = d._serving["runtime"].queue
            admitted = i = 0
            t0 = time.perf_counter()
            admitted += d.submit(_traffic(db.id, 2 * queue, rng))
            while admitted < target:
                c = chunks[i % len(chunks)]
                i += 1
                got = d.submit(c)
                admitted += got
                if got < len(c):
                    # backpressure: refill once half the queue drained
                    while q.pending > queue // 2:
                        time.sleep(0.002)
            fe = d.stop_serving()["front-end"]
            dt = time.perf_counter() - t0
            assert fe["verdicts"] == fe["admitted"] >= target
            shed_state["shed"] += fe["shed"]
            shed_state["events"] += fe["shed-events"]
            shed_state["fe"] = fe
            return fe["verdicts"] / dt

        offline_pps = serving_pps = 0.0
        pair_ratios = []
        for rep in range(3):
            legs = [leg_offline, leg_serving]
            if rep % 2:
                legs.reverse()
            a, b = legs[0](), legs[1]()
            off, srv = (a, b) if rep % 2 == 0 else (b, a)
            offline_pps = max(offline_pps, off)
            serving_pps = max(serving_pps, srv)
            pair_ratios.append(srv / off)
        shed, shed_events = shed_state["shed"], shed_state["events"]
        fe = shed_state["fe"]
        d.shutdown()

        ratio = max(pair_ratios + [serving_pps / offline_pps])
        # recorded, not asserted: the absolute numbers are weather
        print(f"serving sustained {serving_pps:.0f} pps vs offline "
              f"{offline_pps:.0f} pps; pair ratios "
              f"{[round(r, 3) for r in pair_ratios]}")
        assert ratio >= 0.9, (
            f"serving/offline ratio {ratio:.3f} < 0.9 in EVERY "
            f"interleaved pair (pairs {pair_ratios}; serving "
            f"{serving_pps:.0f} vs offline {offline_pps:.0f} pps)")
        # offered load exceeded capacity: sheds are non-zero and
        # surfaced as drop events
        assert shed >= queue  # the oversized chunk alone sheds this
        assert shed_events > 0
        # shape discipline held under load
        assert shapes <= {(2048, N_COLS), (B, N_COLS)}
        # telemetry is live
        assert fe["verdicts-per-sec"] > 0
        assert fe["queue-wait-us"]["count"] > 0
        assert fe["latency-us"]["p50"] is not None
