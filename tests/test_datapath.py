"""Datapath unit tests: LPM, conntrack, and the fused verdict pipeline.

Modeled on the reference's bpf/tests golden-packet strategy (SURVEY.md
§4): craft packets, run the pipeline, assert verdicts + CT state.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cilium_tpu.core import (
    HeaderBatch,
    TCP_ACK,
    TCP_FIN,
    TCP_SYN,
    make_batch,
    synth_batch,
)
from cilium_tpu.core.pcap import read_pcap, write_pcap
from cilium_tpu.datapath import (
    CT_ESTABLISHED,
    CT_NEW,
    CT_REPLY,
    CTTable,
    DeviceLPM,
    compile_lpm,
)
from cilium_tpu.datapath.lpm import lpm_lookup_jit
from cilium_tpu.datapath.conntrack import (
    ct_gc,
    ct_keys_jit,
    ct_live_count,
    ct_lookup_jit,
    ct_update_jit,
)


def _words(ips):
    from cilium_tpu.core.packets import ip_to_words
    return jnp.asarray(np.array([ip_to_words(i) for i in ips],
                                dtype=np.uint32))


class TestLPM:
    def test_longest_prefix_wins(self):
        t = compile_lpm({
            "10.0.0.0/8": 1,
            "10.1.0.0/16": 2,
            "10.1.2.0/24": 3,
            "10.1.2.3/32": 4,
            "0.0.0.0/0": 9,
        })
        dev = DeviceLPM.from_tensors(t)
        ips = ["10.2.0.1", "10.1.9.9", "10.1.2.250", "10.1.2.3", "8.8.8.8"]
        fam = jnp.full(len(ips), 4, dtype=jnp.int32)
        got = lpm_lookup_jit(dev, _words(ips), fam)
        assert list(np.asarray(got)) == [1, 2, 3, 4, 9]

    def test_default_on_miss(self):
        t = compile_lpm({"192.168.0.0/16": 7}, default=0)
        dev = DeviceLPM.from_tensors(t)
        fam = jnp.full(2, 4, dtype=jnp.int32)
        got = lpm_lookup_jit(dev, _words(["192.168.3.4", "1.2.3.4"]), fam)
        assert list(np.asarray(got)) == [7, 0]

    def test_ipv6(self):
        t = compile_lpm({
            "2001:db8::/32": 5,
            "2001:db8:1::/48": 6,
            "::/0": 1,
        })
        dev = DeviceLPM.from_tensors(t)
        ips = ["2001:db8:1::42", "2001:db8:ffff::1", "fe80::1"]
        fam = jnp.full(3, 6, dtype=jnp.int32)
        got = lpm_lookup_jit(dev, _words(ips), fam)
        assert list(np.asarray(got)) == [6, 5, 1]

    def test_mid_prefix_lengths(self):
        # /12 and /20 exercise the l1-range and l2-range painting
        t = compile_lpm({"172.16.0.0/12": 3, "172.16.16.0/20": 4})
        dev = DeviceLPM.from_tensors(t)
        fam = jnp.full(3, 4, dtype=jnp.int32)
        got = lpm_lookup_jit(
            dev, _words(["172.31.255.1", "172.16.20.1", "172.32.0.1"]), fam)
        assert list(np.asarray(got)) == [3, 4, 0]


class TestConntrack:
    def _mk(self, **kw):
        defaults = dict(src="10.0.0.1", dst="10.0.0.2", sport=1234,
                        dport=80, proto=6, flags=TCP_SYN)
        defaults.update(kw)
        return defaults

    def test_new_then_established_then_reply(self):
        ct = CTTable.create(1 << 12)
        now = jnp.uint32(100)
        syn = make_batch([self._mk()])
        hdr = jnp.asarray(syn.data)
        fwd, rev = ct_keys_jit(hdr)
        res, slot, is_rep = ct_lookup_jit(ct, fwd, rev, now)
        assert int(res[0]) == CT_NEW
        ct = ct_update_jit(ct, hdr, fwd, res, slot, is_rep,
                       do_create=jnp.array([True]),
                       proxy_port=jnp.zeros(1, jnp.uint32), now=now)
        assert ct_live_count(ct) == 1

        # same direction again -> ESTABLISHED (entry exists)
        res2, _, _ = ct_lookup_jit(ct, fwd, rev, now)
        assert int(res2[0]) == CT_ESTABLISHED

        # reply direction -> REPLY.  The entry was created at the
        # ingress hook (dir=0); the reply leaves via the egress hook
        # (dir=1) — the reverse key flips tuple AND direction.
        synack = make_batch([self._mk(src="10.0.0.2", dst="10.0.0.1",
                                      sport=80, dport=1234, dir=1,
                                      flags=TCP_SYN | TCP_ACK)])
        rhdr = jnp.asarray(synack.data)
        rfwd, rrev = ct_keys_jit(rhdr)
        res3, slot3, isrep3 = ct_lookup_jit(ct, rfwd, rrev, now)
        assert int(res3[0]) == CT_REPLY and bool(isrep3[0])

    def test_expiry_and_gc(self):
        ct = CTTable.create(1 << 12)
        now = jnp.uint32(100)
        udp = make_batch([self._mk(proto=17, flags=0)])
        hdr = jnp.asarray(udp.data)
        fwd, rev = ct_keys_jit(hdr)
        res, slot, is_rep = ct_lookup_jit(ct, fwd, rev, now)
        ct = ct_update_jit(ct, hdr, fwd, res, slot, is_rep,
                       do_create=jnp.array([True]),
                       proxy_port=jnp.zeros(1, jnp.uint32), now=now)
        # within lifetime -> hit; past lifetime -> miss
        res2, _, _ = ct_lookup_jit(ct, fwd, rev, jnp.uint32(120))
        assert int(res2[0]) == CT_ESTABLISHED
        res3, _, _ = ct_lookup_jit(ct, fwd, rev, jnp.uint32(999))
        assert int(res3[0]) == CT_NEW
        ct, n = ct_gc(ct, jnp.uint32(999))
        assert int(n) == 1 and ct_live_count(ct) == 0

    def test_batch_insert_many_flows(self):
        ct = CTTable.create(1 << 14)
        now = jnp.uint32(50)
        batch = synth_batch(2048, np.random.default_rng(7), n_hosts=5000)
        hdr = jnp.asarray(batch.data)
        fwd, rev = ct_keys_jit(hdr)
        res, slot, is_rep = ct_lookup_jit(ct, fwd, rev, now)
        ct = ct_update_jit(ct, hdr, fwd, res, slot, is_rep,
                       do_create=jnp.ones(2048, bool),
                       proxy_port=jnp.zeros(2048, jnp.uint32), now=now)
        # every distinct tuple that was NEW must now be findable
        res2, _, _ = ct_lookup_jit(ct, fwd, rev, now)
        assert int(jnp.sum(res2 == CT_NEW)) == 0
        assert int(ct.dropped) == 0


class TestPcapRoundTrip:
    def test_write_read(self, tmp_path):
        batch = synth_batch(64, np.random.default_rng(3))
        p = str(tmp_path / "t.pcap")
        write_pcap(p, batch)
        back = read_pcap(p)
        assert len(back) == 64
        for col in ("COL_SRC_IP3", "COL_DST_IP3", "COL_SPORT", "COL_DPORT",
                    "COL_PROTO", "COL_FLAGS", "COL_LEN"):
            import cilium_tpu.core.packets as P
            c = getattr(P, col)
            np.testing.assert_array_equal(back.data[:, c],
                                          batch.data[:, c], err_msg=col)
