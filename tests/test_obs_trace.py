"""Observability: sampled per-packet trace spans (cilium_tpu/obs).

Covers the PR-4 tentpole acceptance properties:

- DETERMINISM: same seed + same packet stream => the identical
  sampled-trace set (the replayable-chaos property, applied to
  tracing);
- CORRECTNESS: seven stage timestamps monotonic (PR 5 split the old
  ``device`` stamp into ``dispatch-ret`` + true window-join
  ``device``), the six stage intervals telescope to the recorded
  end-to-end latency (sum <= e2e, within 10%);
- ZERO OVERHEAD OFF: sampling disabled leaves no tracer object in
  the pipeline — the hot path pays one ``is not None`` branch;
- NO SILENT LOSS: spans whose packet dies mid-pipeline (drop-oldest
  eviction, contained dispatch failures, recovery sweeps) are
  counted dropped, never stuck incomplete;
- the chaos e2e: a demotion-crossing trace is retrievable with its
  ``demoted`` annotation, and the compile-event log holds the
  one-executable-per-(rung, mode) invariant across the ladder walk.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.obs import SpanTracer
from cilium_tpu.obs.trace import SPAN_STAGES, validate_obs_config
from cilium_tpu.serving import DispatchFailedError, ServingRuntime

pytestmark = pytest.mark.obs

COLS = 16


def _chunks(rng, n_chunks=12, lo=20, hi=120):
    sizes = rng.integers(lo, hi, size=n_chunks)
    return [np.full((s, COLS), i, dtype=np.uint32)
            for i, s in enumerate(sizes)]


def _run_stream(chunks, sample, seed=0, dispatch=None):
    """One runtime session over ``chunks``; returns the tracer."""
    tracer = SpanTracer(sample, seed=seed, capacity=1024)
    if dispatch is None:
        def dispatch(hdr, valid, n_valid, **kw):
            return {"h2d_bytes": hdr.nbytes, "mode": "wide",
                    "batch_id": 3}
    rt = ServingRuntime(dispatch, queue_depth=1 << 14,
                        bucket_ladder=(64, 256),
                        max_wait_us=300.0, expected_cols=COLS,
                        tracer=tracer)
    rt.start()
    for c in chunks:
        rt.submit(c)
        time.sleep(0.002)
    rt.stop()
    return rt, tracer


class TestSamplingDeterminism:
    def test_same_seed_same_stream_identical_sampled_set(self):
        rng = np.random.default_rng(11)
        chunks = _chunks(rng)
        _, tr_a = _run_stream(chunks, sample=7, seed=3)
        _, tr_b = _run_stream(chunks, sample=7, seed=3)
        seqs_a = sorted(t["seq"] for t in tr_a.snapshot(1024)["traces"])
        seqs_b = sorted(t["seq"] for t in tr_b.snapshot(1024)["traces"])
        assert seqs_a and seqs_a == seqs_b
        # and the set is exactly the arithmetic progression over the
        # admitted sequence: (seq + seed) % sample == 0
        total = sum(len(c) for c in chunks)
        assert seqs_a == [s for s in range(total) if (s + 3) % 7 == 0]

    def test_seed_shifts_the_sampled_set(self):
        rng = np.random.default_rng(12)
        chunks = _chunks(rng)
        _, tr_a = _run_stream(chunks, sample=7, seed=0)
        _, tr_b = _run_stream(chunks, sample=7, seed=1)
        a = {t["seq"] for t in tr_a.snapshot(1024)["traces"]}
        b = {t["seq"] for t in tr_b.snapshot(1024)["traces"]}
        assert a and b and a.isdisjoint(b)

    def test_spans_monotonic_and_stage_sum_telescopes(self):
        rng = np.random.default_rng(13)
        _, tracer = _run_stream(_chunks(rng), sample=5)
        traces = tracer.snapshot(1024)["traces"]
        assert traces
        for t in traces:
            ts = t["timestamps"]
            assert len(ts) == len(SPAN_STAGES) == 7
            assert all(ts[i + 1] >= ts[i] for i in range(6)), t
            assert t["monotonic"]
            stage_sum = sum(t["stages-us"].values())
            # the intervals telescope: their sum IS the end-to-end
            # latency (well within the 10% acceptance bound)
            assert stage_sum <= t["e2e-us"] + 1e-3
            assert abs(stage_sum - t["e2e-us"]) <= 0.1 * t["e2e-us"] \
                + 1e-3
        # no span leaked: every started span completed or was counted
        st = tracer.stats()
        assert st["started"] == st["completed"] + st["dropped"]
        assert st["dropped"] == 0

    def test_disabled_sampling_is_structurally_free(self):
        """sample=0 => NO tracer object anywhere in the pipeline:
        the hot path's entire cost is `queue.tracer is None` (the
        bench guard measures the residue; this pins the structure)."""
        def dispatch(hdr, valid, n_valid, **kw):
            return None

        rt = ServingRuntime(dispatch, queue_depth=4096,
                            bucket_ladder=(64,), max_wait_us=300.0,
                            expected_cols=COLS)
        rt.start()
        rt.submit(np.zeros((64, COLS), dtype=np.uint32))
        time.sleep(0.05)
        snap = rt.stop()
        assert rt.queue.tracer is None
        assert rt._tracer is None
        assert "trace" not in snap
        assert rt._prev_spans == ()

    def test_drop_oldest_eviction_counts_spans(self):
        """Spans shed by drop-oldest (or swept by stop) are counted
        dropped — started always reconciles."""
        tracer = SpanTracer(2, capacity=256)
        blocked = []

        def dispatch(hdr, valid, n_valid, **kw):
            blocked.append(n_valid)
            time.sleep(0.05)  # slow consumer: the queue overflows
            return None

        rt = ServingRuntime(dispatch, queue_depth=128,
                            bucket_ladder=(128,), max_wait_us=100.0,
                            overflow_policy="drop-oldest",
                            expected_cols=COLS, tracer=tracer)
        rt.start()
        for _ in range(40):
            rt.submit(np.zeros((64, COLS), dtype=np.uint32))
        rt.stop()
        st = tracer.stats()
        assert st["started"] == st["completed"] + st["dropped"]
        assert st["dropped"] > 0  # overflow definitely evicted spans

    def test_contained_dispatch_failure_drops_spans(self):
        """A DispatchFailedError batch becomes recovery drops; its
        spans are counted dropped, not leaked incomplete."""
        tracer = SpanTracer(4, capacity=256)
        calls = []

        def dispatch(hdr, valid, n_valid, **kw):
            calls.append(n_valid)
            if len(calls) == 1:
                raise DispatchFailedError("contained")
            return None

        rt = ServingRuntime(dispatch, queue_depth=4096,
                            bucket_ladder=(64,), max_wait_us=200.0,
                            expected_cols=COLS, tracer=tracer)
        rt.start()
        rt.submit(np.zeros((64, COLS), dtype=np.uint32))
        time.sleep(0.1)
        rt.submit(np.zeros((64, COLS), dtype=np.uint32))
        time.sleep(0.1)
        snap = rt.stop()
        st = tracer.stats()
        assert snap["fault-tolerance"]["recovery-dropped"] == 64
        assert st["dropped"] >= 1
        assert st["started"] == st["completed"] + st["dropped"]

    def test_annotations_ride_the_span(self):
        rng = np.random.default_rng(14)
        _, tracer = _run_stream(_chunks(rng), sample=5)
        t = tracer.snapshot(4)["traces"][0]
        assert t["bucket"] in (64, 256)
        assert t["mode"] == "wide"
        assert t["batch-id"] == 3  # from the dispatch info dict
        assert 0 <= t["batch-pos"] < t["bucket"]

    def test_validate_obs_config_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="serving_trace_sample"):
            validate_obs_config(-1, None, 16)
        with pytest.raises(ValueError, match="profile_batches"):
            validate_obs_config(0, "/tmp/x", 0)
        assert validate_obs_config(64, None, 16) == (64, None, 16)

    def test_span_sample_requires_ingress(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 10,
                                flow_ring_capacity=1 << 10))
        with pytest.raises(ValueError, match="ingress"):
            d.start_serving(trace_sample=0, span_sample=8)
        d.shutdown()


RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]


def _fwd(db_id, n=64, base=20000):
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base + i,
             dport=5432, proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _wait(pred, timeout=60.0, tick=0.002):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


@pytest.mark.chaos
class TestTraceE2EDemotion:
    def test_trace_crosses_demotion_with_monotonic_stages(self):
        """THE acceptance e2e: serving_trace_sample=64 over a real
        tpu-backend session retrieves complete traces (seven
        monotonic stamps, stage-sum within 10% of e2e) INCLUDING one
        that
        crossed a single->wide ladder demotion (its batch was
        retried on the demoted rung, so the span carries
        demoted=True and the wide mode), and the compile-event log
        holds one executable per (rung, mode) over the walk.

        Same world/bucket as test_serving_faults so the XLA
        executables are shared across the suite."""
        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            serving_dispatch_deadline_ms=0.0,
            serving_restart_budget=4,
            serving_restart_backoff_ms=1.0,
            serving_demote_threshold=2,
            serving_promote_after=1000,
            serving_trace_sample=64,
            fault_injection="loader.serve_packed=1x2@1",
            fault_seed=1))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        d.start_serving(trace_sample=0, ingress=True, packed=True,
                        drain_every=2)
        rt = d._serving["runtime"]
        d.submit(_fwd(db.id))  # warm (packed)
        assert _wait(lambda: rt.stats.verdicts >= 64)
        d.submit(_fwd(db.id, base=21000))  # fault 1: contained drop
        assert _wait(lambda: rt.stats.recovery_dropped >= 64)
        d.submit(_fwd(db.id, base=22000))  # fault 2: demote + retry
        assert _wait(lambda: rt.stats.verdicts >= 128)
        assert d.serving_stats()["mode"] == "wide"
        # a few more batches so post-demotion traces complete
        d.submit(_fwd(db.id, base=23000))
        assert _wait(lambda: rt.stats.verdicts >= 192)
        # spans now complete ASYNCHRONOUSLY (the event-join worker
        # stamps device/join at true window-join time); the idle-tick
        # drain flushes the last window once traffic pauses, so wait
        # for the ledger to reconcile before snapshotting
        tracer = d._serving["tracer"]
        assert _wait(lambda: (lambda st:
                              st["started"] == st["completed"]
                              + st["dropped"])(tracer.stats()))
        tr = d.debug_traces(limit=256)
        assert tr["enabled"] and tr["sample"] == 64
        complete = tr["traces"]
        assert len(complete) >= 1
        for t in complete:
            assert t["monotonic"], t
            s = sum(t["stages-us"].values())
            assert s <= t["e2e-us"] + 1e-3
            assert abs(s - t["e2e-us"]) <= 0.1 * t["e2e-us"] + 1e-3
        # at least one trace CROSSED the demotion: retried on the
        # demoted rung, annotated demoted + wide
        crossed = [t for t in complete if t["demoted"]]
        assert crossed and all(t["mode"] == "wide" for t in crossed)
        # the span ledger reconciles: the faulted batch's spans are
        # dropped, everything else completed
        st = tr
        assert st["started"] == st["completed"] + st["dropped"]
        assert st["dropped"] >= 1  # the contained-failure batch
        # compile-event log: one executable per (rung, mode) over
        # the packed -> wide walk (events appear only for compiles
        # this process actually paid — a warm jit cache legitimately
        # records none; violations flag same-key regrowth either way)
        comp = tr["compile"]
        assert comp["violations"] == 0
        assert all(k["compiles"] == 1 for k in comp["by-key"])
        modes = {k["mode"] for k in comp["by-key"]}
        # "gather" = the occupancy-bounded ring-drain executables
        # (PR 5) — bucketed rungs under the same one-per-key guard
        assert modes <= {"packed", "wide", "gather"}
        # prometheus: the obs series ride the unified registry
        prom = d.registry.render()
        assert "cilium_obs_spans_completed_total" in prom
        assert "cilium_serving_compile_violations_total 0" in prom
        assert "cilium_serving_latency_us_bucket" in prom
        fe = d.stop_serving()["front-end"]
        ft = fe["fault-tolerance"]
        assert fe["submitted"] == (fe["verdicts"] + fe["shed"]
                                   + ft["recovery-dropped"])
        d.shutdown()


@pytest.mark.chaos
class TestTraceShardAttribution:
    def test_sharded_spans_carry_owning_shard(self):
        """Sharded dispatch annotates each span with the chip its
        packet was flow-routed to (routed position // block — the
        same mapping the router's orig index encodes), so a slow
        trace is attributable to a shard.  Distinct flows spread, so
        the sampled set must cover more than one shard."""
        from cilium_tpu.parallel import make_mesh

        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            serving_trace_sample=4))
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        d.start_serving(trace_sample=0, ingress=True,
                        mesh=make_mesh(8), drain_every=2)
        rt = d._serving["runtime"]
        for k in range(3):
            d.submit(_fwd(db.id, base=20000 + 100 * k))
        assert _wait(lambda: rt.stats.verdicts >= 192)
        tr = d.debug_traces(limit=64)
        traces = tr["traces"]
        assert traces
        assert all(t["mode"].startswith("sharded") for t in traces)
        shards = {t["shard"] for t in traces}
        assert all(0 <= s < 8 for s in shards), shards
        assert len(shards) > 1, "spans should span multiple shards"
        d.stop_serving()
        d.shutdown()

    def test_route_overflow_spans_dropped_not_completed(self):
        """A sampled packet the router drops (full shard block) must
        land in the tracer's DROPPED count, never as a completed
        trace — a committed span would report a fake e2e latency for
        a packet the device never verdicted."""
        from cilium_tpu.parallel import make_mesh

        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            serving_trace_sample=1))  # sample EVERY packet
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        # headroom 1 + one elephant flow: all 64 rows route to ONE
        # shard whose block is 64/8 = 8 rows -> 56 deterministic
        # router drops (the test_serving_sharded overflow scenario)
        d.start_serving(trace_sample=0, ingress=True,
                        mesh=make_mesh(8), shard_headroom=1,
                        drain_every=2)
        rt = d._serving["runtime"]
        elephant = make_batch([
            dict(src="10.0.1.1", dst="10.0.2.1", sport=7777,
                 dport=5432, proto=6, flags=TCP_SYN, ep=db.id,
                 dir=0)] * 64).data
        d.submit(elephant)
        tracer = d._serving["tracer"]
        assert _wait(lambda: tracer.stats()["completed"]
                     + tracer.stats()["dropped"] >= 64)
        st = tracer.stats()
        assert st["started"] == 64
        assert st["dropped"] == 56, st
        assert st["completed"] == 8, st
        tr = d.debug_traces(limit=64)
        assert all(t["shard"] >= 0 for t in tr["traces"])
        d.stop_serving()
        d.shutdown()


class TestAssemblyFailureEviction:
    def test_spans_evicted_when_staging_raises(self):
        """Spans claimed by take_into are evicted if batch assembly
        dies before the batch exists — a drain-loop restart must not
        pop them into (and corrupt) a later batch, and the ledger
        stays exact."""
        from cilium_tpu.serving.batcher import AdaptiveBatcher
        from cilium_tpu.serving.ingress import IngressQueue

        tracer = SpanTracer(1, seed=0)
        q = IngressQueue(1 << 10)
        q.tracer = tracer
        q.offer(np.zeros((8, COLS), dtype=np.uint32))
        b = AdaptiveBatcher((64,), max_wait_us=0.0)
        boom = RuntimeError("arena died")

        class ExplodingArena:
            def slot(self, *a, **kw):
                raise boom

        b.arena = ExplodingArena()
        with pytest.raises(RuntimeError):
            b.assemble(q, force=True)
        st = tracer.stats()
        assert st["started"] == 8
        assert st["dropped"] == 8, st
        assert q.pop_dequeued_spans() == []  # nothing orphaned
