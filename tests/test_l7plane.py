"""The L7 proxy plane (ISSUE 16): REDIRECT as a first-class serving
verdict with an L7 worker pool.

Acceptance (tier-1, chaos-marked): a seeded ``l7.parse`` worker death
mid-parse is healed by the watchdog-restart idiom, the redirect
ledger (``redirected == l7_allowed + l7_denied + l7_shed +
l7_failed``) closes EXACTLY, the serving executables never recompile,
and a DNS answer observed by an L7 worker mints an identity that
visibly flips a device verdict under live load.

Suite layout:
- TestPoolLedger: L7WorkerPool loss discipline in isolation (shed,
  containment, death/restart, budget-terminal, stop exactness);
- TestPlaneOffline: L7Plane.ingest grouping + the DNS answer leg
  against a real daemon's redirect verdicts (offline path);
- TestServingChaosE2E: THE acceptance test;
- TestFQDNChurnUnderServing: satellite 3 — repeated mints flip
  verdicts mid-serving, generation monotone, interpreter oracle;
- TestRedirectFlowStamp: satellite 6 — REDIRECTED flows carry
  proxy_port through monitor -> flow -> exporter;
- TestL7AbuseScenario: the CTA010-contract scenario end to end;
- TestProxyLedgerLint: CTA012's declaration chain, statically.
"""

import json
import threading
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.core.packets import COL_DPORT, COL_SPORT
from cilium_tpu.flow import FlowExporter, Observer
from cilium_tpu.infra import faults
from cilium_tpu.policy.mapstate import (VERDICT_ALLOW,
                                        VERDICT_REDIRECT)
from cilium_tpu.proxy.worker import L7Task, L7WorkerPool
from cilium_tpu.serving.l7plane import L7Plane

pytestmark = pytest.mark.chaos

# the fqdn-loop policy shape (test_fqdn.py): DNS egress is
# L7-inspected (REDIRECT to the dns proxy), and traffic may flow only
# to IPs the allowed names resolved to
RULES_DNS = [{
    "endpointSelector": {"matchLabels": {"app": "client"}},
    "egress": [
        {"toEntities": ["world"],
         "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}],
                      "rules": {"dns": [
                          {"matchName": "example.com"},
                          {"matchPattern": "*.corp.io"}]}}]},
        {"toFQDNs": ["example.com"],
         "toPorts": [{"ports": [{"port": "443",
                                 "protocol": "TCP"}]}]},
        {"toFQDNs": ["*.corp.io"],
         "toPorts": [{"ports": [{"port": "8443",
                                 "protocol": "TCP"}]}]},
    ],
}]


def _wait(pred, timeout=30.0, tick=0.002):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


def _dns_rows(ep, n=64, base=20000):
    # unique sports: every packet a NEW flow, so every redirect
    # verdict emits an event the plane can ingest
    return make_batch([
        dict(src="10.0.1.1", dst="8.8.8.8", sport=base + i, dport=53,
             proto=17, flags=TCP_SYN, ep=ep, dir=1)
        for i in range(n)]).data


def _probe_rows(ep, dst, dport=443, n=64, base=50000):
    return make_batch([
        dict(src="10.0.1.1", dst=dst, sport=base + i, dport=dport,
             proto=6, flags=TCP_SYN, ep=ep, dir=1)
        for i in range(n)]).data


def _probe_verdicts(got, sport_lo, sport_hi, dport):
    """Scan captured event batches for probe rows -> {sport: verdict}."""
    out = {}
    for b in list(got):
        hdr = np.asarray(b.hdr)
        m = ((hdr[:, COL_DPORT] == dport)
             & (hdr[:, COL_SPORT] >= sport_lo)
             & (hdr[:, COL_SPORT] < sport_hi))
        if not m.any():
            continue
        for sp, v in zip(hdr[m, COL_SPORT].tolist(),
                         np.asarray(b.verdict)[m].tolist()):
            out[int(sp)] = int(v)
    return out


def _assert_l7_ledger(l7):
    assert l7["redirected"] == (l7["l7-allowed"] + l7["l7-denied"]
                                + l7["l7-shed"] + l7["l7-failed"]), l7
    assert l7["ledger-exact"], l7
    return l7


# ---------------------------------------------------------------------
class TestPoolLedger:
    """The pool's no-silent-loss contract in isolation — every loss
    path counted, the ledger exact post-stop."""

    def test_clean_drain_closes_ledger(self):
        p = L7WorkerPool(lambda t: (t.rows, 0), workers=2,
                         queue_depth=64)
        p.start()
        for _ in range(16):
            assert p.submit(L7Task(port=10000, rows=4))
        st = p.stop()
        assert st["redirected"] == 64 == st["l7-allowed"]
        assert st["tasks-done"] == 16
        _assert_l7_ledger(st)

    def test_overflow_sheds_oldest_counted(self):
        started, gate = threading.Event(), threading.Event()

        def handle(t):
            started.set()
            gate.wait(10)
            return (t.rows, 0)

        p = L7WorkerPool(handle, workers=1, queue_depth=2)
        p.start()
        p.submit(L7Task(port=1, rows=1))
        assert started.wait(10)  # in flight: the queue is empty again
        p.submit(L7Task(port=1, rows=2))  # queued [2]
        p.submit(L7Task(port=1, rows=4))  # queued [2, 4]
        p.submit(L7Task(port=1, rows=8))  # overflow: evicts rows=2
        st = p.stats()
        assert st["queue-overflows"] == 1
        assert st["l7-shed"] == 2
        assert "queue full" in st["last-drop-cause"]
        gate.set()
        st = p.stop()
        assert st["l7-allowed"] == 1 + 4 + 8
        assert st["redirected"] == 15
        _assert_l7_ledger(st)

    def test_handler_exception_contained_no_restart(self):
        def handle(t):
            if t.port == 666:
                raise ValueError("bad payload")
            return (t.rows, 0)

        p = L7WorkerPool(handle, workers=1, queue_depth=8)
        p.start()
        p.submit(L7Task(port=666, rows=5))
        p.submit(L7Task(port=1, rows=3))
        st = p.stop()
        assert st["l7-failed"] == 5 and st["l7-allowed"] == 3
        assert st["worker-restarts"] == 0  # contained, not a death
        assert "ValueError" in st["last-drop-cause"]
        _assert_l7_ledger(st)

    def test_handler_accounting_clamped(self):
        # a handler that under- or over-reports cannot break the
        # ledger: short rows count failed, excess is clamped
        p = L7WorkerPool(
            lambda t: (1, 1) if t.port == 1 else (9, 9), workers=1)
        p.start()
        p.submit(L7Task(port=1, rows=5))  # short by 3
        p.submit(L7Task(port=2, rows=4))  # over-reported: clamp to 4
        st = p.stop()
        assert st["l7-failed"] == 3
        assert st["l7-allowed"] + st["l7-denied"] == 2 + 4
        assert st["redirected"] == 9
        _assert_l7_ledger(st)

    def test_worker_death_restarts_and_counts_rows(self):
        inj = faults.arm("l7.parse=1x1@1")  # 2nd parse dies
        try:
            p = L7WorkerPool(lambda t: (t.rows, 0), workers=1,
                             restart_budget=3)
            p.start()
            for _ in range(3):
                p.submit(L7Task(port=1, rows=2))
            # the restart must land BEFORE stop: a worker dying
            # during stop() is the sweep's business, not a restart
            assert _wait(
                lambda: p.stats()["worker-restarts"] >= 1, 10)
            st = p.stop()
        finally:
            faults.disarm(inj)
        assert st["worker-restarts"] == 1
        assert st["l7-failed"] == 2  # the in-flight task's rows
        assert st["l7-allowed"] == 4
        assert "worker died" in st["last-drop-cause"] \
            or "InjectedFault" in st["last-drop-cause"]
        _assert_l7_ledger(st)

    def test_restart_budget_terminal_sheds_and_fires_incident(self):
        inj = faults.arm("l7.parse=1")  # every parse dies
        fired = []
        try:
            p = L7WorkerPool(lambda t: (t.rows, 0), workers=1,
                             restart_budget=1,
                             on_terminal=fired.append)
            p.start()
            p.submit(L7Task(port=1, rows=2))  # death 1: restart
            p.submit(L7Task(port=1, rows=2))  # death 2: terminal
            assert _wait(
                lambda: p.stats().get("error") is not None, 10)
            # a terminal pool sheds new offers, counted
            assert p.submit(L7Task(port=1, rows=2)) is False
            st = p.stop()
        finally:
            faults.disarm(inj)
        assert len(fired) == 1 and "budget" in fired[0]
        assert st["worker-restarts"] == 1
        assert st["l7-failed"] == 4 and st["l7-shed"] == 2
        assert "budget" in st["error"]
        _assert_l7_ledger(st)

    def test_stop_without_drain_sheds_queued(self):
        p = L7WorkerPool(lambda t: (t.rows, 0), workers=1,
                         queue_depth=8)
        # never started: everything stays queued until the stop sweep
        for _ in range(3):
            p.submit(L7Task(port=1, rows=4))
        st = p.stop(drain=False)
        assert st["l7-shed"] == 12 and st["redirected"] == 12
        assert "without drain" in st["last-drop-cause"]
        _assert_l7_ledger(st)


# ---------------------------------------------------------------------
class TestPlaneOffline:
    """L7Plane against a real daemon's redirect verdicts (offline
    process_batch path): ingest selection/grouping, kind dispatch via
    the listener table, and the DNS answer leg's identity mint."""

    def _world(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES_DNS)
        d.start()
        return d, ep

    def test_ingest_selects_redirects_and_answers_mint(self):
        d, ep = self._world()
        try:
            evb = d.process_batch(make_batch([
                dict(src="10.0.1.1", dst="8.8.8.8", sport=40001,
                     dport=53, proto=17, flags=TCP_SYN, ep=ep.id,
                     dir=1),
                dict(src="10.0.1.1", dst="93.184.216.34",
                     sport=40002, dport=443, proto=6, flags=TCP_SYN,
                     ep=ep.id, dir=1),  # unresolved: denied, ignored
            ]).data, now=5)
            assert int(evb.verdict[0]) == VERDICT_REDIRECT
            assert int(evb.verdict[1]) != VERDICT_REDIRECT
            plane = L7Plane(
                d.proxy,
                request_source=lambda port, kind, task:
                    ["example.com"] * task.rows,
                dns_resolver=lambda q: (["93.184.216.34"], 300))
            plane.start()
            assert plane.ingest(evb) == 1  # only the redirect row
            st = plane.stop()
            assert st["redirected"] == 1 == st["l7-allowed"]
            assert st["dns-answers"] == 1
            assert st["batches-ingested"] == 1
            _assert_l7_ledger(st)
            # the answer minted: the next offline verdict flips
            evb2 = d.process_batch(_probe_rows(ep.id,
                                               "93.184.216.34", n=1),
                                   now=6)
            assert int(evb2.verdict[0]) == VERDICT_ALLOW
        finally:
            d.shutdown()

    def test_resolver_failure_counted_never_fatal(self):
        d, ep = self._world()
        try:
            evb = d.process_batch(_dns_rows(ep.id, n=2), now=5)
            assert all(int(v) == VERDICT_REDIRECT
                       for v in evb.verdict)

            def broken(_q):
                raise RuntimeError("resolver down")

            plane = L7Plane(
                d.proxy,
                request_source=lambda port, kind, task:
                    ["example.com"] * task.rows,
                dns_resolver=broken)
            plane.start()
            assert plane.ingest(evb) == 2
            st = plane.stop()
            # the verdict ledger is untouched by the answer leg
            assert st["l7-allowed"] == 2
            assert st["dns-resolve-errors"] == 2
            assert st["dns-answers"] == 0
            _assert_l7_ledger(st)
        finally:
            d.shutdown()

    def test_default_source_synthesizes_and_rules_apply(self):
        """No request source installed: the default synthesizes one
        request per row and the port's REAL rules still decide — an
        http /public-only rule denies the synthetic GET /."""
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        d.add_endpoint("client", ("10.0.1.9",), ["k8s:app=client"])
        ep = d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels":
                                   {"app": "client"}}],
                "toPorts": [{"ports": [{"port": "80",
                                        "protocol": "TCP"}],
                             "rules": {"http": [
                                 {"method": "GET",
                                  "path": "/public"}]}}]}],
        }])
        d.start()
        try:
            evb = d.process_batch(make_batch([
                dict(src="10.0.1.9", dst="10.0.1.1",
                     sport=40000 + i, dport=80, proto=6,
                     flags=TCP_SYN, ep=ep.id, dir=0)
                for i in range(4)]).data, now=5)
            assert all(int(v) == VERDICT_REDIRECT
                       for v in evb.verdict)
            plane = L7Plane(d.proxy)
            plane.start()
            assert plane.ingest(evb) == 4
            st = plane.stop()
            assert st["l7-denied"] == 4  # GET / vs /public-only
            assert st["l7-allowed"] == 0
            _assert_l7_ledger(st)
        finally:
            d.shutdown()


# ---------------------------------------------------------------------
class TestServingChaosE2E:
    """THE ISSUE 16 acceptance test: seeded L7 worker death mid-parse
    -> watchdog restart; redirect ledger exact; zero
    serving-executable recompiles; a DNS-answer-driven identity mint
    visibly flips a device verdict under live load."""

    @staticmethod
    def _dispatch_compiles(daemon):
        # the churn-gate idiom: gather rungs are occupancy-dependent
        return sum(e["compiles"]
                   for e in daemon.loader.compile_log.snapshot(
                       limit=0)["by-key"]
                   if e["mode"] != "gather")

    def test_worker_death_mint_flip_zero_recompiles(self):
        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            map_pressure_interval=0.0,
            fault_injection="l7.parse=1x1@1", fault_seed=1,
            l7_workers=2, l7_queue_depth=64))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES_DNS)
        # the request/answer seams, installed BEFORE start_serving:
        # every redirected dns row asks for example.com, and allowed
        # queries resolve -> observe_answer -> live identity mint
        d.l7_request_source = \
            lambda port, kind, task: ["example.com"] * task.rows
        d.l7_dns_resolver = lambda q: (["93.184.216.34"], 300)
        got = []
        d.monitor.register("t", got.append)
        d.start()
        d.start_serving(trace_sample=0, ingress=True, drain_every=1)
        rt = d._serving["runtime"]
        plane = d._l7plane
        try:
            gen0 = d.loader.table_stats()["generation"]
            # PRE-MINT probe: 64 flows to the not-yet-resolved IP —
            # all denied (and this warms the serving executable)
            d.submit(_probe_rows(ep.id, "93.184.216.34",
                                 base=50000))
            assert _wait(lambda: rt.stats.verdicts >= 64)
            pre = _probe_verdicts(got, 50000, 50064, 443)
            assert _wait(lambda: len(_probe_verdicts(
                got, 50000, 50064, 443)) == 64)
            pre = _probe_verdicts(got, 50000, 50064, 443)
            assert all(v != VERDICT_ALLOW for v in pre.values()), pre
            # FREEZE: nothing after this point may recompile a
            # serving executable (the mint rides the patch path)
            compiles0 = self._dispatch_compiles(d)

            # the redirect load: 4 one-task batches; the seeded
            # l7.parse=1x1@1 kills a worker on the SECOND parse
            for r in range(4):
                d.submit(_dns_rows(ep.id, base=20000 + r * 100))
            assert _wait(lambda: rt.stats.verdicts >= 64 * 5)
            assert _wait(lambda: plane.pool.pending == 0)
            assert _wait(
                lambda: plane.pool.restarts >= 1), plane.stats()
            # the mint landed, live, through the patch path
            assert _wait(lambda: len(d.fqdn.entries()) >= 1)
            assert _wait(lambda: d.loader.table_stats()["generation"]
                         > gen0)

            # POST-MINT probe under continued load: the device
            # verdict flipped mid-serving
            d.submit(_dns_rows(ep.id, base=21000))
            d.submit(_probe_rows(ep.id, "93.184.216.34",
                                 base=51000))
            assert _wait(lambda: len(_probe_verdicts(
                got, 51000, 51064, 443)) == 64)
            post = _probe_verdicts(got, 51000, 51064, 443)
            assert all(v == VERDICT_ALLOW
                       for v in post.values()), post

            assert self._dispatch_compiles(d) == compiles0, \
                "a serving executable recompiled mid-serving"
            st = d.stop_serving()
            fe, l7 = st["front-end"], st["l7"]
            ft = fe["fault-tolerance"]
            assert fe["submitted"] == (fe["verdicts"] + fe["shed"]
                                       + ft["recovery-dropped"])
            # the redirect ledger, exact under the worker death:
            # exactly one task's rows were claimed by the corpse
            _assert_l7_ledger(l7)
            assert l7["worker-restarts"] == 1
            assert l7["l7-failed"] == 64
            assert l7["redirected"] == 64 * 5  # 5 dns batches
            assert l7["dns-answers"] >= 1
            assert d._l7_last is l7
        finally:
            d.shutdown()


# ---------------------------------------------------------------------
class TestFQDNChurnUnderServing:
    """Satellite 3: the fqdn -> ipcache -> identity-mint pipeline
    under live serving churn — each round's DNS answer must flip the
    device verdict for its IP within the update-visible bound, the
    table generation is monotone, and the interpreter oracle agrees
    with every post-mint verdict."""

    ROUNDS = 3

    def test_repeated_mints_flip_verdicts_generation_monotone(self):
        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13,
            serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            map_pressure_interval=0.0,
            l7_workers=2, l7_queue_depth=64))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES_DNS)
        current = ["r0.corp.io"]  # the per-round query name
        table = {f"r{i}.corp.io": f"198.51.100.{10 + i}"
                 for i in range(self.ROUNDS)}
        d.l7_request_source = \
            lambda port, kind, task: [current[0]] * task.rows
        d.l7_dns_resolver = lambda q: ([table[q]], 300) \
            if q in table else None
        got = []
        d.monitor.register("t", got.append)
        d.start()
        d.start_serving(trace_sample=0, ingress=True, drain_every=1)
        rt = d._serving["runtime"]
        plane = d._l7plane
        gens = [d.loader.table_stats()["generation"]]
        try:
            served = 0
            for r in range(self.ROUNDS):
                name, ip = f"r{r}.corp.io", table[f"r{r}.corp.io"]
                current[0] = name
                d.submit(_dns_rows(ep.id, base=20000 + r * 100))
                served += 64
                # the update-visible bound: entry minted + published
                assert _wait(lambda: any(
                    name in e["names"] for e in d.fqdn.entries())), \
                    (r, d.fqdn.entries())
                assert _wait(
                    lambda: d.loader.table_stats()["generation"]
                    > gens[-1])
                gens.append(d.loader.table_stats()["generation"])
                # the flip, observed on live-served probe flows
                base = 52000 + r * 100
                d.submit(_probe_rows(ep.id, ip, dport=8443,
                                     base=base))
                served += 64
                assert _wait(lambda: len(_probe_verdicts(
                    got, base, base + 64, 8443)) == 64)
                pv = _probe_verdicts(got, base, base + 64, 8443)
                assert all(v == VERDICT_ALLOW
                           for v in pv.values()), (r, pv)
            assert _wait(lambda: rt.stats.verdicts >= served)
            assert _wait(lambda: plane.pool.pending == 0)
            st = d.stop_serving()
            l7 = _assert_l7_ledger(st["l7"])
            assert l7["redirected"] == 64 * self.ROUNDS
            assert l7["l7-allowed"] == 64 * self.ROUNDS
            assert l7["dns-answers"] >= self.ROUNDS
            assert gens == sorted(gens) and len(set(gens)) == \
                len(gens)  # strictly monotone: one flip per mint

            # the interpreter oracle: same policy + the same observed
            # answers must produce the same post-mint verdicts
            probes = make_batch(
                [dict(src="10.0.1.1", dst=ip, sport=60000 + i,
                      dport=8443, proto=6, flags=TCP_SYN, ep=ep.id,
                      dir=1)
                 for i, ip in enumerate(table.values())]
                + [dict(src="10.0.1.1", dst="198.51.100.99",
                        sport=60099, dport=8443, proto=6,
                        flags=TCP_SYN, ep=ep.id, dir=1)]).data
            tpu_v = [int(v) for v in
                     d.process_batch(probes.copy(), now=99).verdict]
            di = Daemon(DaemonConfig(backend="interpreter",
                                     ct_capacity=1 << 12))
            epi = di.add_endpoint("client-1", ("10.0.1.1",),
                                  ["k8s:app=client"])
            assert epi.id == ep.id
            di.policy_import(RULES_DNS)
            di.start()
            for name, ip in table.items():
                di.proxy.observe_answer(name, [ip], ttl=300)
            int_v = [int(v) for v in
                     di.process_batch(probes.copy(), now=99).verdict]
            di.shutdown()
            assert tpu_v == int_v
            assert int_v[:-1] == [VERDICT_ALLOW] * self.ROUNDS
            assert int_v[-1] != VERDICT_ALLOW  # unresolved control
        finally:
            d.shutdown()


# ---------------------------------------------------------------------
class TestRedirectFlowStamp:
    """Satellite 6: a REDIRECT verdict decodes monitor -> flow with
    the proxy port stamped, renders in the summary, and survives the
    JSONL exporter."""

    def test_redirected_flow_carries_proxy_port(self, tmp_path):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES_DNS)
        d.start()
        try:
            evb = d.process_batch(_dns_rows(ep.id, n=1), now=5)
            assert int(evb.verdict[0]) == VERDICT_REDIRECT
            port = int(evb.proxy_port[0])
            assert port > 0
            obs = Observer(capacity=64)
            obs.consume(evb)
            fl = obs.get_flows(number=1)[0]
            assert fl.verdict == VERDICT_REDIRECT
            assert fl.proxy_port == port
            fd = fl.to_dict()
            assert fd["verdict"] == "REDIRECTED"
            assert fd["proxy_port"] == port
            assert f" to-proxy:{port}" in fl.summary()
            # and through the exporter (the hubble JSONL shape)
            p = str(tmp_path / "flows.log")
            ex = FlowExporter(p)
            ex.consume(evb)
            ex.close()
            rec = json.loads(open(p).read().splitlines()[0])
            assert rec["flow"]["proxy_port"] == port
            assert rec["flow"]["verdict"] == "REDIRECTED"
        finally:
            d.shutdown()

    def test_non_redirect_flows_stay_unstamped(self):
        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 12))
        ep = d.add_endpoint("client-1", ("10.0.1.1",),
                            ["k8s:app=client"])
        d.policy_import(RULES_DNS)
        d.start()
        try:
            evb = d.process_batch(
                _probe_rows(ep.id, "203.0.113.1", n=1), now=5)
            assert int(evb.verdict[0]) != VERDICT_REDIRECT
            obs = Observer(capacity=8)
            obs.consume(evb)
            fl = obs.get_flows(number=1)[0]
            assert fl.proxy_port == 0
            assert "proxy_port" not in fl.to_dict()
            assert "to-proxy" not in fl.summary()
        finally:
            d.shutdown()


# ---------------------------------------------------------------------
@pytest.mark.scenario
class TestL7AbuseScenario:
    """The l7_abuse scenario (CTA010 contract) end to end: the sweep's
    redirect slice detours through the pool, the synthetic GET / is
    denied by the /public-only rule, and every declared criterion
    passes."""

    def test_criteria_pass_and_ledger_closes(self):
        from cilium_tpu.testing.workloads import (make_scenario,
                                                  run_scenario,
                                                  scenario_daemon)

        sc = make_scenario("l7_abuse", seed=11, n_packets=1024,
                           batch=256)
        d = scenario_daemon(sc, map_pressure_interval=0.0)
        d.start()
        try:
            r = run_scenario(d, sc)
            assert r["passed"], r["checks"]
            m = r["metrics"]
            assert m["l7_ledger_exact"]
            # slack for random-sport tuple collisions: a repeated
            # tuple is CT-established and emits no verdict event
            assert m["l7_redirected"] >= (
                1024 // sc.redirect_every) * 9 // 10
            assert m["l7_redirected"] == (
                m["l7_allowed"] + m["l7_denied"] + m["l7_shed"]
                + m["l7_failed"])
            assert m["l7_denied"] > 0  # GET / vs /public-only
        finally:
            d.shutdown()

    def test_stream_shape(self):
        from cilium_tpu.core.packets import COL_FLAGS
        from cilium_tpu.testing.workloads import make_scenario

        sc = make_scenario("l7_abuse", seed=3, n_packets=512,
                           batch=128)
        rows = np.concatenate(list(sc.iter_batches(ep=5)))
        assert len(rows) == 512
        # every redirect_every-th packet aims at the open L7 port
        on_port = rows[:, COL_DPORT] == sc.redirect_port
        assert int(on_port.sum()) >= 512 // sc.redirect_every
        assert (rows[:, COL_FLAGS] == TCP_SYN).all()


# ---------------------------------------------------------------------
class TestProxyLedgerLint:
    """CTA012 (analysis/proxy_lint.py): the ledger's declaration ->
    stats -> metrics -> fault-site chain, statically."""

    def test_live_repo_clean(self):
        from cilium_tpu.analysis import Repo, repo_root
        from cilium_tpu.analysis.proxy_lint import check

        assert check(Repo(repo_root())) == []

    def test_dropped_counter_and_site_are_findings(self, tmp_path):
        from cilium_tpu.analysis import Repo
        from cilium_tpu.analysis.proxy_lint import check

        mod = tmp_path / "cilium_tpu" / "proxy"
        mod.mkdir(parents=True)
        (mod / "worker.py").write_text(
            "class P:\n"
            "    def __init__(self):\n"
            "        self.redirected = 0\n"
            "        self.l7_allowed = 0\n"
            "        self.l7_denied = 0\n"
            "        self.l7_failed = 0\n")
        msgs = " | ".join(f.message for f in check(Repo(
            str(tmp_path))))
        assert "l7_shed" in msgs  # the dropped counter
        assert "l7.parse" in msgs  # the unarmed fault site
        assert "ledger-exact" in msgs  # the missing stat key

    def test_check_bench_schema(self, tmp_path):
        from cilium_tpu.analysis.proxy_lint import check_bench

        good = {
            "schema": "bench-l7-v1",
            "redirect_overhead": {
                "baseline_pps": 100.0, "candidate_pps": 90.0,
                "ratio_median": 0.9, "ratio_best": 0.92},
            "parse_latency_by_plugin": {
                "http": {"p50": 1.0, "p95": 2.0, "p99": 3.0,
                         "max": 4.0, "count": 5}},
            "offline_http": {"pps": 1.0},
        }
        p = tmp_path / "BENCH_l7.json"
        p.write_text(json.dumps(good))
        assert check_bench(str(p)) == []
        del good["redirect_overhead"]["ratio_median"]
        del good["parse_latency_by_plugin"]["http"]["p99"]
        good["schema"] = "bench-l7-v0"
        p.write_text(json.dumps(good))
        bad = check_bench(str(p))
        assert any("ratio_median" in b for b in bad)
        assert any("percentile" in b for b in bad)
        assert any("schema" in b for b in bad)
