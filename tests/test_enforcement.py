"""Per-endpoint policy enforcement modes + runtime options
(VERDICT r03 item 6; reference: pkg/option PolicyEnforcement and
endpoint options Debug/DropNotification/TraceNotification, plus
--monitor-aggregation).

Divergence gate: the TPU backend and the interpreter (oracle) backend
must agree on every packet in every mode.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_ACK, TCP_FIN, TCP_SYN, make_batch
from cilium_tpu.monitor.api import MSG_DROP, MSG_POLICY_VERDICT, MSG_TRACE

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
    ],
}]


def _daemon(backend, **kw):
    return Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12,
                               **kw))


def _world(backend):
    """One daemon with one db endpoint per enforcement mode + a web
    peer; RULES select only app=db."""
    d = _daemon(backend)
    eps = {}
    for mode in ("default", "always", "never"):
        ep = d.add_endpoint(f"db-{mode}", (f"10.0.2.{len(eps) + 1}",),
                            ["k8s:app=db"])
        assert d.endpoints.update_config(ep.id, enforcement=mode)
        eps[mode] = ep
    web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
    d.policy_import(RULES)
    return d, eps, web


def _traffic(eps, web):
    rows = []
    for i, (mode, ep) in enumerate(sorted(eps.items())):
        dst = ep.ips[0]
        # allowed-by-rule, denied-by-default, and unmatched-port flows
        rows += [
            dict(src="10.0.1.1", dst=dst, sport=41000 + i, dport=5432,
                 proto=6, flags=TCP_SYN, ep=ep.id, dir=0),
            dict(src="10.9.9.9", dst=dst, sport=42000 + i, dport=5432,
                 proto=6, flags=TCP_SYN, ep=ep.id, dir=0),
            dict(src="10.0.1.1", dst=dst, sport=43000 + i, dport=80,
                 proto=6, flags=TCP_SYN, ep=ep.id, dir=0),
        ]
    # the web endpoint has NO selecting rule: default vs always differ
    rows.append(dict(src="10.0.2.1", dst="10.0.1.1", sport=5432,
                     dport=44000, proto=6, flags=TCP_SYN, ep=web.id,
                     dir=0))
    return make_batch(rows)


class TestEnforcementModes:
    def test_tpu_matches_interpreter_across_modes(self):
        outs = {}
        for backend in ("tpu", "interpreter"):
            d, eps, web = _world(backend)
            batch = _traffic(eps, web)
            evb = d.process_batch(batch.data, now=10)
            outs[backend] = (list(evb.verdict), list(evb.reason),
                             list(evb.msg_type))
        assert outs["tpu"] == outs["interpreter"]

    def test_mode_semantics(self):
        d, eps, web = _world("tpu")
        batch = _traffic(eps, web)
        evb = d.process_batch(batch.data, now=10)
        v = {i: int(x) for i, x in enumerate(evb.verdict)}
        # rows 0-2: always-mode db ep (sorted order: always first)
        assert v[0] == 1  # rule allows web->5432
        assert v[1] == 0  # unknown peer: default-deny
        assert v[2] == 0  # port 80: default-deny
        # rows 3-5: default mode — same as always when a rule selects
        assert (v[3], v[4], v[5]) == (1, 0, 0)
        # rows 6-8: never mode — everything allowed
        assert (v[6], v[7], v[8]) == (1, 1, 1)
        # row 9: web ep, no selecting rule, default mode -> allow
        assert v[9] == 1

    def test_always_applies_without_any_rule(self):
        """always = default-deny even when NO rule selects the
        endpoint (the difference from default mode)."""
        for backend in ("tpu", "interpreter"):
            d = _daemon(backend)
            ep = d.add_endpoint("lonely", ("10.0.3.1",),
                                ["k8s:app=lonely"])
            assert d.endpoints.update_config(ep.id,
                                             enforcement="always")
            pkt = make_batch([dict(src="10.9.9.9", dst="10.0.3.1",
                                   sport=40000, dport=443, proto=6,
                                   flags=TCP_SYN, ep=ep.id, dir=0)])
            evb = d.process_batch(pkt.data, now=5)
            assert list(evb.verdict) == [0], backend
            assert list(evb.msg_type) == [MSG_DROP], backend

    def test_patch_mode_takes_effect_immediately(self):
        d, eps, web = _world("tpu")
        ep = eps["default"]
        pkt = make_batch([dict(src="10.9.9.9", dst=ep.ips[0],
                               sport=45000, dport=5432, proto=6,
                               flags=TCP_SYN, ep=ep.id, dir=0)])
        assert list(d.process_batch(pkt.data, now=10).verdict) == [0]
        assert d.endpoints.update_config(ep.id, enforcement="never")
        assert list(d.process_batch(pkt.data, now=11).verdict) == [1]
        # rendered in the endpoint API view
        assert d.endpoints.get(ep.id).to_dict()[
            "policy-enforcement"] == "never"

    def test_invalid_mode_and_option_rejected(self):
        d = _daemon("interpreter")
        ep = d.add_endpoint("x", ("10.0.4.1",), ["k8s:app=x"])
        with pytest.raises(ValueError, match="enforcement"):
            d.endpoints.update_config(ep.id, enforcement="sometimes")
        with pytest.raises(ValueError, match="unknown endpoint options"):
            d.endpoints.update_config(ep.id, options={"Bogus": True})
        # r04 review: an invalid mode combined with valid options must
        # not half-apply the options behind the 400
        with pytest.raises(ValueError, match="enforcement"):
            d.endpoints.update_config(
                ep.id, enforcement="sometimes",
                options={"DropNotification": False})
        assert d.endpoints.get(ep.id).options["DropNotification"] is True

    def test_enforcement_survives_checkpoint_restore(self, tmp_path):
        """r04 review: restore() must round-trip per-endpoint
        enforcement + options — resetting 'always' to 'default' on
        restart silently changes verdicts."""
        state_dir = str(tmp_path / "state")
        d = _daemon("tpu", state_dir=state_dir)
        ep = d.add_endpoint("lonely", ("10.0.3.1",), ["k8s:app=lonely"])
        assert d.endpoints.update_config(
            ep.id, enforcement="always",
            options={"DropNotification": False})
        d.checkpoint(state_dir)

        d2 = _daemon("tpu", state_dir=state_dir)
        assert d2.restore(state_dir)
        got = d2.endpoints.get(ep.id)
        assert got.enforcement == "always"
        assert got.options["DropNotification"] is False
        pkt = make_batch([dict(src="10.9.9.9", dst="10.0.3.1",
                               sport=40000, dport=443, proto=6,
                               flags=TCP_SYN, ep=ep.id, dir=0)])
        assert list(d2.process_batch(pkt.data, now=5).verdict) == [0]


class TestEventOptions:
    def _flow(self, d, ep, flags=TCP_SYN, dport=22, sport=40000):
        return make_batch([dict(src="10.9.9.9", dst=ep.ips[0],
                                sport=sport, dport=dport, proto=6,
                                flags=flags, ep=ep.id, dir=0)])

    def test_drop_notification_off_suppresses_monitor_drops(self):
        d = _daemon("tpu")
        ep = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        seen = []
        d.monitor.register("t", lambda b: seen.extend(b.msg_type))
        assert d.endpoints.update_config(
            ep.id, options={"DropNotification": False})
        evb = d.process_batch(self._flow(d, ep).data, now=5)
        # the datapath still DROPS (verdict + metrics) ...
        assert list(evb.verdict) == [0]
        # ... but the monitor plane saw nothing
        assert MSG_DROP not in seen

    def test_trace_notification_off_suppresses_traces_only(self):
        d = _daemon("tpu")
        ep = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        seen = []
        d.monitor.register("t", lambda b: seen.extend(b.msg_type))
        assert d.endpoints.update_config(
            ep.id, options={"TraceNotification": False})
        syn = make_batch([dict(src="10.0.1.1", dst="10.0.2.1",
                               sport=40000, dport=5432, proto=6,
                               flags=TCP_SYN, ep=ep.id, dir=0)])
        d.process_batch(syn.data, now=5)
        ack = make_batch([dict(src="10.0.1.1", dst="10.0.2.1",
                               sport=40000, dport=5432, proto=6,
                               flags=TCP_ACK, ep=ep.id, dir=0)])
        d.process_batch(ack.data, now=6)
        assert MSG_TRACE not in seen
        # verdict events still flow
        assert MSG_POLICY_VERDICT in seen or MSG_DROP in seen

    def test_aggregation_medium_with_debug_override(self):
        """monitor-aggregation=medium drops mid-flow ACK traces;
        Debug=True exempts an endpoint."""
        d = _daemon("tpu", monitor_aggregation="medium")
        web = d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        seen = []
        d.monitor.register("t", lambda b: seen.append(
            (list(b.msg_type), list(b.hdr[:, 14]))))
        mk = lambda ep, flags: make_batch([dict(
            src="10.0.1.1", dst="10.0.2.1", sport=40000, dport=5432,
            proto=6, flags=flags, ep=ep.id, dir=0)])
        d.process_batch(mk(db, TCP_SYN).data, now=5)   # verdict event
        d.process_batch(mk(db, TCP_ACK).data, now=6)   # boring trace
        flat = [m for ms, _ in seen for m in ms]
        assert MSG_TRACE not in flat  # aggregated away
        # Debug exempts: same flow keeps tracing
        assert d.endpoints.update_config(db.id, options={"Debug": True})
        d.process_batch(mk(db, TCP_ACK).data, now=7)
        flat = [m for ms, _ in seen for m in ms]
        assert MSG_TRACE in flat
        # FIN traces always pass aggregation
        assert d.endpoints.update_config(db.id, options={"Debug": False})
        d.process_batch(mk(db, TCP_ACK | TCP_FIN).data, now=8)
        assert MSG_TRACE in [m for ms, _ in seen[-1:] for m in ms]

    def test_rest_patch_endpoint_config(self, tmp_path):
        """PATCH /endpoint/{id}/config over the unix-socket REST API."""
        from cilium_tpu.api import APIClient, APIServer

        d = _daemon("tpu")
        ep = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        sock = str(tmp_path / "api.sock")
        server = APIServer(d, sock)
        server.start()
        try:
            c = APIClient(sock)
            got = c._request("PATCH", f"/endpoint/{ep.id}/config",
                             {"policy-enforcement": "always",
                              "options": {"Debug": True}})
            assert got["updated"] is True
            view = d.endpoints.get(ep.id).to_dict()
            assert view["policy-enforcement"] == "always"
            assert view["options"]["Debug"] is True
            pkt = make_batch([dict(src="10.9.9.9", dst="10.0.2.1",
                                   sport=40000, dport=443, proto=6,
                                   flags=TCP_SYN, ep=ep.id, dir=0)])
            assert list(d.process_batch(pkt.data, now=5).verdict) == [0]
        finally:
            server.stop()

    def test_patch_config_monitor_aggregation(self):
        d = _daemon("tpu")
        assert d.patch_config({"monitor-aggregation": "medium"}) == {
            "monitor-aggregation": "medium"}
        with pytest.raises(ValueError):
            d.patch_config({"monitor-aggregation": "verbose"})
