"""CiliumCIDRGroup (reference: pkg/policy CIDRGroupRef + the
CiliumCIDRGroup CRD, cilium 1.13+): ``cidrGroupRef`` entries in
fromCIDRSet/toCIDRSet expand against the live group cache, re-expand
on group churn, and fail CLOSED when the group vanishes.
"""

import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.datapath.verdict import (REASON_FORWARDED,
                                         REASON_POLICY_DEFAULT_DENY)
from cilium_tpu.policy.api import rule_to_dict

NS = "k8s:io.kubernetes.pod.namespace=default"


def _daemon():
    d = Daemon(DaemonConfig(backend="interpreter", ct_capacity=1 << 12))
    d.add_endpoint("cli", ("10.0.9.9",), ["k8s:app=cli", NS])
    return d


def _group(cidrs, name="partners"):
    return {"kind": "CiliumCIDRGroup",
            "metadata": {"name": name},
            "spec": {"externalCIDRs": list(cidrs)}}


def _cnp(ref="partners"):
    return {
        "kind": "CiliumNetworkPolicy",
        "metadata": {"name": "allow-partners",
                     "namespace": "default"},
        "spec": {
            "endpointSelector": {"matchLabels": {"app": "cli"}},
            "egress": [{"toCIDRSet": [{"cidrGroupRef": ref}]}],
        },
    }


def _flow(d, dst, sport, now):
    ep = d.endpoints.lookup_by_ip("10.0.9.9")
    ev = d.process_batch(make_batch([
        dict(src="10.0.9.9", dst=dst, sport=sport, dport=443,
             proto=6, flags=TCP_SYN, ep=ep.id, dir=1)
    ]).data, now=now)
    return int(ev.reason[0])


def _cidrs(d):
    egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
    return {c["cidr"] for c in egress["toCIDRSet"]}


class TestCIDRGroups:
    def test_ref_expands_and_enforces(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        hub.dispatch("add", _cnp())
        assert _cidrs(d) == {"203.0.113.0/24"}
        assert _flow(d, "203.0.113.7", 41000, 50) == REASON_FORWARDED
        assert _flow(d, "198.51.100.7", 41001,
                     51) == REASON_POLICY_DEFAULT_DENY

    def test_group_churn_re_expands(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        hub.dispatch("add", _cnp())
        assert _flow(d, "198.51.100.7", 41010,
                     50) == REASON_POLICY_DEFAULT_DENY
        hub.dispatch("update", _group(["203.0.113.0/24",
                                       "198.51.100.0/24"]))
        assert _cidrs(d) == {"203.0.113.0/24", "198.51.100.0/24"}
        assert _flow(d, "198.51.100.7", 41011, 51) == REASON_FORWARDED

    def test_missing_group_fails_closed(self):
        d = _daemon()
        hub = d.k8s_watchers()
        # CNP lands BEFORE its group: matches nothing, not everything
        hub.dispatch("add", _cnp())
        assert _cidrs(d) == {"0.0.0.0/32"}
        assert _flow(d, "203.0.113.7", 41020,
                     50) == REASON_POLICY_DEFAULT_DENY
        # the group appears: dependents re-expand
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        assert _flow(d, "203.0.113.7", 41021, 51) == REASON_FORWARDED
        # and vanishes again: fail closed
        hub.dispatch("delete", _group([]))
        assert _cidrs(d) == {"0.0.0.0/32"}
        assert _flow(d, "203.0.113.9", 41022,
                     52) == REASON_POLICY_DEFAULT_DENY

    def test_plain_cidrs_ride_alongside_refs(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        cnp = _cnp()
        cnp["spec"]["egress"][0]["toCIDRSet"].append(
            {"cidr": "192.0.2.0/24"})
        hub.dispatch("add", cnp)
        assert _cidrs(d) == {"203.0.113.0/24", "192.0.2.0/24"}

    def test_except_carveouts_survive_expansion(self):
        """The ref entry's 'except' list applies to every expanded
        CIDR — dropping it would WIDEN the policy."""
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        cnp = _cnp()
        cnp["spec"]["egress"][0]["toCIDRSet"] = [
            {"cidrGroupRef": "partners",
             "except": ["203.0.113.128/25"]}]
        hub.dispatch("add", cnp)
        egress = rule_to_dict(d.repo.rules()[0])["egress"][0]
        assert egress["toCIDRSet"] == [
            {"cidr": "203.0.113.0/24",
             "except": ["203.0.113.128/25"]}]
        assert _flow(d, "203.0.113.7", 41030, 50) == REASON_FORWARDED
        assert _flow(d, "203.0.113.200", 41031,
                     51) == REASON_POLICY_DEFAULT_DENY

    def test_unrelated_group_churn_skips_reimport(self):
        d = _daemon()
        hub = d.k8s_watchers()
        hub.dispatch("add", _group(["203.0.113.0/24"]))
        hub.dispatch("add", _cnp())
        rev = d.repo.revision
        hub.dispatch("add", _group(["10.99.0.0/16"], name="other"))
        assert d.repo.revision == rev

    def test_direct_import_rejected(self):
        d = _daemon()
        with pytest.raises(ValueError, match="cidrGroupRef"):
            d.policy_import([{
                "endpointSelector": {"matchLabels": {"app": "cli"}},
                "egress": [{"toCIDRSet": [
                    {"cidrGroupRef": "partners"}]}],
            }])
