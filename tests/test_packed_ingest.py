"""Packed ingest path: the 16 B/packet h2d wire format.

The packed pipeline exists for end-to-end ingest bandwidth (SURVEY.md
§7 hard part #4): the wide [N, 16] u32 tensor costs 64 B/packet over
the host->device link; IPv4 traffic ships as [N, 4] packed rows and
unpacks on device inside the fused step.  These tests pin:

- native packed parse == Python fallback == pack_rows(wide parse)
- device unpack is the exact inverse of host pack
- datapath_step_packed produces identical verdicts + CT state to
  datapath_step on the wide tensor
- the event-ring cursor survives the 2^32 wrap (64-bit count as two
  u32 words)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cilium_tpu import native
from cilium_tpu.core.ingest import frames_from_batch, parse_frames
from cilium_tpu.core.packets import (
    COL_DIR,
    COL_EP,
    COL_FAMILY,
    N_COLS,
    PACKED_COLS,
    pack_rows,
    synth_batch,
    unpack_hdr,
)


def _v4_batch(n=512, seed=0):
    batch = synth_batch(n, np.random.default_rng(seed)).data
    return batch


def test_native_packed_matches_python_fallback():
    batch = _v4_batch()
    buf = frames_from_batch(batch)
    got = native.parse_frames_packed(buf)
    assert got is not None, "native library must build in CI"
    rows_n, n_n, sk_n = got
    rows_p, n_p, sk_p = native.parse_frames_packed_py(buf)
    assert n_n == n_p and sk_n == sk_p
    np.testing.assert_array_equal(np.asarray(rows_n), np.asarray(rows_p))


def test_packed_parse_equals_packed_wide_parse():
    batch = _v4_batch(1024, seed=3)
    buf = frames_from_batch(batch)
    wide = parse_frames(buf)
    rows, n, skipped = native.parse_frames_packed(buf)
    assert n == len(wide) and skipped == 0
    np.testing.assert_array_equal(np.asarray(rows), pack_rows(wide))


def test_packed_skips_non_ipv4_and_counts():
    import struct

    batch = _v4_batch(8, seed=1)
    buf = frames_from_batch(batch)
    # splice in one IPv6 frame: eth (type 0x86DD) + minimal v6 header
    v6 = b"\x00" * 12 + b"\x86\xdd" + bytes([0x60] + [0] * 39)
    buf = buf + struct.pack("<I", len(v6)) + v6
    rows, n, skipped = native.parse_frames_packed(buf)
    assert n == 8
    assert skipped == 1


def _icmp_error_frame():
    """Eth + IPv4 ICMP dest-unreachable embedding an original UDP
    packet 10.0.0.9:5353 -> 10.0.0.7:53."""
    import struct

    inner = struct.pack("!BBHHHBBH4s4s", 0x45, 0, 28, 0, 0, 64, 17, 0,
                        bytes([10, 0, 0, 9]), bytes([10, 0, 0, 7]))
    inner += struct.pack("!HHHH", 5353, 53, 8, 0)
    icmp = struct.pack("!BBHI", 3, 1, 0, 0) + inner
    ip = struct.pack("!BBHHHBBH4s4s", 0x45, 0, 20 + len(icmp), 0, 0, 64,
                     1, 0, bytes([10, 0, 0, 7]), bytes([10, 0, 0, 9]))
    eth = b"\x00" * 12 + b"\x08\x00" + ip + icmp
    return struct.pack("<I", len(eth)) + eth


def test_packed_icmp_error_carries_related_bit_native_and_python():
    """r04: the packed format gained a RELATED flag (bit 15 of the
    length half-word), so ICMP errors carry the EMBEDDED tuple + the
    bit on the fast path too — unpacking round-trips to exactly the
    wide parser's transform (FLAG_RELATED + inner 5-tuple), and the
    datapath relates instead of policy-evaluating a forged-looking
    outer tuple."""
    from cilium_tpu.core.packets import (COL_DST_IP3, COL_FLAGS,
                                         COL_PROTO, COL_SRC_IP3,
                                         FLAG_RELATED, pack_rows)

    buf = _icmp_error_frame()
    rows_n, n_n, sk_n = native.parse_frames_packed(buf)
    rows_p, n_p, sk_p = native.parse_frames_packed_py(buf)
    assert (n_n, sk_n) == (1, 0) and (n_p, sk_p) == (1, 0)
    np.testing.assert_array_equal(np.asarray(rows_n), np.asarray(rows_p))
    wide = native.parse_frames_py(buf)
    assert int(wide[0, COL_SRC_IP3]) == 0x0A000009  # embedded tuple
    assert int(wide[0, COL_DST_IP3]) == 0x0A000007
    assert int(wide[0, COL_PROTO]) == 17
    assert int(wide[0, COL_FLAGS]) == FLAG_RELATED
    # packed == pack(wide): the bit survives the 16 B format
    np.testing.assert_array_equal(np.asarray(rows_n), pack_rows(wide))
    meta = int(rows_n[0, 3])
    assert meta & (1 << 15)
    assert meta >> 24 == 17  # embedded proto, not outer ICMP
    # and unpacking restores FLAG_RELATED for the device pipeline
    import jax.numpy as jnp

    from cilium_tpu.core.packets import unpack_hdr

    hdr = np.asarray(unpack_hdr(jnp.asarray(np.asarray(rows_n)),
                                jnp.uint32(0), jnp.uint32(0)))
    assert int(hdr[0, COL_FLAGS]) == FLAG_RELATED
    assert int(hdr[0, COL_PROTO]) == 17


def test_packed_overflow_counts_only_valid_rows():
    """ADVICE r03 (low): once the out buffer is full, malformed /
    skipped frames must NOT count as overflow — a buffer sized exactly
    for the valid rows never spuriously raises."""
    import ctypes
    import struct

    batch = _v4_batch(8, seed=2)
    buf = frames_from_batch(batch)
    # append a skippable IPv6 frame AFTER 8 valid v4 frames
    v6 = b"\x00" * 12 + b"\x86\xdd" + bytes([0x60] + [0] * 39)
    buf = buf + struct.pack("<I", len(v6)) + v6
    out = np.empty((8, PACKED_COLS), dtype=np.uint32)  # exactly-sized
    rows, n, skipped = native.parse_frames_packed(buf, out)
    assert n == 8 and skipped == 1  # no spurious overflow raise


def test_undersized_out_buffer_raises():
    """Silent truncation would be undetectable packet loss; both the
    native and Python paths must raise instead (r03 review)."""
    batch = _v4_batch(64)
    buf = frames_from_batch(batch)
    out = np.empty((10, PACKED_COLS), dtype=np.uint32)
    with pytest.raises(ValueError, match="too small"):
        native.parse_frames_packed(buf, out)
    with pytest.raises(ValueError, match="too small"):
        native.parse_frames_packed_py(buf, out)


def test_reused_out_buffer_returns_view():
    batch = _v4_batch(64)
    buf = frames_from_batch(batch)
    out = np.empty((256, PACKED_COLS), dtype=np.uint32)
    rows, n, _ = native.parse_frames_packed(buf, out)
    assert n == 64
    assert rows.base is out  # view into the reused transfer buffer


def test_unpack_is_inverse_of_pack():
    batch = _v4_batch(256, seed=7)
    batch[:, COL_EP] = 5
    batch[:, COL_DIR] = 1
    packed = pack_rows(batch)
    wide = np.asarray(unpack_hdr(jnp.asarray(packed), 5, 1))
    np.testing.assert_array_equal(wide, batch)


def test_step_packed_matches_step_wide():
    from cilium_tpu.datapath import datapath_step_jit
    from cilium_tpu.datapath.verdict import datapath_step_packed_jit
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 12)
    batch = _v4_batch(512, seed=11)
    packed = pack_rows(batch)
    now = jnp.uint32(100)

    out_w, st_w = datapath_step_jit(world.state, jnp.asarray(batch), now)

    world2 = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 12)
    out_p, st_p = datapath_step_packed_jit(
        world2.state, jnp.asarray(packed), now, jnp.uint32(0),
        jnp.uint32(0))

    np.testing.assert_array_equal(np.asarray(out_w), np.asarray(out_p))
    np.testing.assert_array_equal(np.asarray(st_w.ct.table),
                                  np.asarray(st_p.ct.table))
    np.testing.assert_array_equal(np.asarray(st_w.metrics),
                                  np.asarray(st_p.metrics))


def test_serve_step_packed_streams_events():
    from cilium_tpu.monitor.ring import (EventRing, ring_drain,
                                         serve_step_packed_jit)
    from cilium_tpu.testing.fixtures import build_world

    world = build_world(n_identities=64, n_rules=4, ct_capacity=1 << 12)
    batch = _v4_batch(512, seed=13)
    packed = jnp.asarray(pack_rows(batch))
    ring = EventRing.create(1 << 10)
    z = jnp.uint32(0)
    state, ring = serve_step_packed_jit(world.state, ring, packed,
                                        jnp.uint32(100), z, z, z)
    rows, total, lost = ring_drain(ring)
    assert total > 0 and lost == 0
    assert len(rows) == total


def test_ring_cursor_survives_u32_wrap():
    """ADVICE r02 (medium): a u32 event count wraps after 2^32 events
    and drain misreads a full ring as nearly empty.  The cursor is two
    u32 words; force lo near the wrap and check the carry + drain
    accounting."""
    from cilium_tpu.datapath.verdict import N_OUT, OUT_EVENT, EV_DROP
    from cilium_tpu.monitor.ring import (EventRing, ring_append_jit,
                                         ring_drain)

    cap = 256
    ring = EventRing.create(cap)
    # pretend 2^32 - 100 events have already flowed (ring full: the buf
    # holds the last `cap` of them)
    filled = jnp.zeros((cap, ring.buf.shape[1]), dtype=jnp.uint32)
    ring = EventRing(buf=filled,
                     cursor=jnp.asarray([2**32 - 100, 0], dtype=jnp.uint32))
    out = jnp.full((512, N_OUT), EV_DROP, dtype=jnp.uint32)
    out = out.at[:, OUT_EVENT].set(EV_DROP)  # every row kept
    ring = ring_append_jit(ring, out, jnp.uint32(1), trace_sample=0)
    rows, total, lost = ring_drain(ring)
    assert total == 2**32 - 100 + 512  # > 2^32: carried into hi word
    assert int(np.asarray(ring.cursor[1])) == 1
    assert lost == total - cap
    assert len(rows) <= cap
