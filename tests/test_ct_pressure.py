"""Map-pressure monitor + graceful degradation (ISSUE 12,
datapath/pressure.py): the CT/NAT pressure floor, the adaptive
CT-GC response, the `map-pressure` incident + sysdump capture, and
the REASON_NAT_EXHAUSTED end-to-end decode.

Named to sort early per the tier-1 budget-truncation convention."""

import time

import numpy as np
import pytest

from cilium_tpu.datapath.pressure import (MapPressureMonitor,
                                          validate_pressure_config)
from cilium_tpu.testing.workloads import (make_scenario, run_scenario,
                                          scenario_daemon)


def _wait(pred, timeout=30.0, tick=0.005):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


# ---------------------------------------------------------------------
class TestConfigValidation:
    def test_pressure_knob_errors(self):
        with pytest.raises(ValueError, match="map_pressure_interval"):
            validate_pressure_config(-1, 0.85, 0.7, 1.0)
        with pytest.raises(ValueError, match="ct_pressure_threshold"):
            validate_pressure_config(5, 1.5, 0.7, 1.0)
        with pytest.raises(ValueError, match="ct_pressure_clear"):
            validate_pressure_config(5, 0.85, 0.9, 1.0)
        with pytest.raises(ValueError,
                           match="ct_gc_pressure_interval"):
            validate_pressure_config(5, 0.85, 0.7, 0)

    def test_daemon_validates_at_construction(self):
        from cilium_tpu.agent import Daemon, DaemonConfig

        with pytest.raises(ValueError, match="ct_pressure_clear"):
            Daemon(DaemonConfig(backend="interpreter",
                                ct_pressure_clear=0.95,
                                ct_pressure_threshold=0.9))
        with pytest.raises(ValueError, match="nat_pool_capacity"):
            Daemon(DaemonConfig(backend="interpreter",
                                nat_pool_capacity=100))  # not 2^k
        with pytest.raises(ValueError, match="nat_pool_capacity"):
            Daemon(DaemonConfig(backend="interpreter",
                                nat_pool_capacity=1 << 16))


# ---------------------------------------------------------------------
class TestMonitorStateMachine:
    """Unit surface: scripted samples drive enter/exit with
    hysteresis and exactly one incident per episode."""

    def _monitor(self, samples):
        it = iter(samples)
        calls = {"accel": [], "restore": 0, "incidents": []}

        def sample_fn():
            return next(it)

        mon = MapPressureMonitor(
            sample_fn,
            on_accelerate=lambda s: calls["accel"].append(s),
            on_restore=lambda: calls.__setitem__(
                "restore", calls["restore"] + 1),
            record_incident=lambda kind, det: calls[
                "incidents"].append((kind, det)),
            ct_threshold=0.85, ct_clear=0.70,
            gc_pressure_interval_s=0.5)
        return mon, calls

    @staticmethod
    def _s(occ, drops=0, nat=0):
        return {"ct": {"capacity": 100, "occupied": int(occ * 100),
                       "occupancy": occ, "insert-drops": drops},
                "nat": {"capacity": 64, "failures": nat}}

    def test_occupancy_threshold_enters_and_hysteresis_exits(self):
        mon, calls = self._monitor([
            self._s(0.2), self._s(0.9), self._s(0.8),
            self._s(0.75), self._s(0.6), self._s(0.9)])
        mon.sample()
        assert mon.state == "ok"
        mon.sample()
        assert mon.state == "pressure"
        assert calls["accel"] == [0.5]
        assert [k for k, _ in calls["incidents"]] == ["map-pressure"]
        mon.sample()  # 0.8: above clear — still pressure, no new
        mon.sample()  # 0.75: still above clear
        assert mon.state == "pressure"
        assert len(calls["incidents"]) == 1  # one per episode
        mon.sample()  # 0.6: clears
        assert mon.state == "ok" and calls["restore"] == 1
        mon.sample()  # re-enters: a NEW episode, a NEW incident
        assert mon.state == "pressure"
        assert mon.episodes == 2
        assert len(calls["incidents"]) == 2

    def test_insert_drop_delta_triggers(self):
        mon, calls = self._monitor([
            self._s(0.1, drops=5),  # baseline sample seeds deltas
            self._s(0.1, drops=5),  # no NEW drops: ok
            self._s(0.1, drops=9),  # +4: pressure
            self._s(0.1, drops=9),  # quiet + under clear: exits
        ])
        mon.sample()
        mon.sample()
        assert mon.state == "ok"
        mon.sample()
        assert mon.state == "pressure"
        assert mon.last["ct"]["insert-drop-delta"] == 4
        mon.sample()
        assert mon.state == "ok"

    def test_nat_failure_delta_triggers(self):
        mon, _calls = self._monitor([
            self._s(0.1), self._s(0.1, nat=3)])
        mon.sample()
        mon.sample()
        assert mon.state == "pressure"
        assert mon.last["nat"]["failure-delta"] == 3

    def test_interpreter_occupancy_none_keys_on_counters(self):
        s = {"ct": {"capacity": 0, "occupied": 7, "occupancy": None,
                    "insert-drops": 0},
             "nat": {"capacity": None, "failures": 0}}
        mon, _ = self._monitor([s, s])
        mon.sample()
        mon.sample()
        assert mon.state == "ok"

    def test_stats_shape(self):
        mon, _ = self._monitor([self._s(0.5)])
        mon.sample()
        st = mon.stats()
        for key in ("state", "episodes", "samples", "accelerated",
                    "ct", "nat", "ct-threshold", "ct-clear"):
            assert key in st, key


# ---------------------------------------------------------------------
class TestLoaderPressureSurface:
    def test_interpreter_map_pressure_shape(self):
        from cilium_tpu.agent import Daemon, DaemonConfig

        d = Daemon(DaemonConfig(backend="interpreter"))
        p = d.loader.map_pressure(10)
        assert p["ct"]["occupancy"] is None
        assert p["nat"]["failures"] == 0
        d.shutdown()

    def test_tpu_map_pressure_counts_entries(self):
        from cilium_tpu.agent import Daemon, DaemonConfig
        from cilium_tpu.core import TCP_SYN, make_batch

        d = Daemon(DaemonConfig(backend="tpu", ct_capacity=1 << 10,
                                map_pressure_interval=0.0))
        ep = d.add_endpoint("srv", ("10.0.2.1",), ["k8s:app=srv"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "srv"}},
            "ingress": [{"fromEntities": ["world"]}]}])
        d.start()
        p0 = d.loader.map_pressure(d._now())
        assert p0["ct"]["occupied"] == 0
        rows = make_batch([dict(
            src=f"8.8.{i // 250}.{i % 250 + 1}", dst="10.0.2.1",
            sport=30000 + i, dport=443, proto=6, flags=TCP_SYN,
            ep=ep.id, dir=0) for i in range(64)]).data
        d.process_batch(rows)
        p1 = d.loader.map_pressure(d._now())
        assert p1["ct"]["occupied"] == 64
        assert p1["ct"]["occupancy"] == pytest.approx(64 / 1024)
        d.shutdown()


# ---------------------------------------------------------------------
@pytest.mark.chaos
@pytest.mark.scenario
class TestSynFloodPressureLeg:
    """The acceptance leg: syn_flood demonstrably drives CT
    insert-drop pressure, the controller accelerates the aging sweep
    and records a `map-pressure` incident with a sysdump bundle, and
    the packet ledger stays exact through the storm."""

    def test_syn_flood_end_to_end(self, tmp_path):
        sc = make_scenario("syn_flood", seed=3, n_flows=3072,
                           batch=512)
        d = scenario_daemon(sc, map_pressure_interval=0.1,
                            ct_gc_pressure_interval=0.25,
                            sysdump_dir=str(tmp_path))
        d.start()
        try:
            normal = d.controllers.get("ct-gc")._interval
            assert normal == d.config.ct_gc_interval
            r = run_scenario(d, sc)
            assert r["passed"], r["checks"]
            m = r["metrics"]
            assert m["ledger_exact"]
            assert m["ct_insert_drops"] > 0
            assert m["ct_occupancy"] >= 0.85
            # the monitor noticed (bounded poll: the controller
            # samples every 100ms)
            assert _wait(lambda: d.pressure.stats()["state"]
                         == "pressure", timeout=10)
            st = d.pressure.stats()
            assert st["accelerated"] and st["episodes"] >= 1
            assert st["ct"]["insert-drops"] > 0
            # the aging sweep ACCELERATED (the adaptive-GC response)
            assert _wait(lambda: d.controllers.get("ct-gc")
                         ._interval == 0.25, timeout=10)
            # ...and actually swept under the accelerated cadence
            gc = d.controllers.get("ct-gc").status
            n0 = gc.success_count
            assert _wait(lambda: gc.success_count > n0, timeout=10)
            # ONE map-pressure incident, with a sysdump bundle
            assert _wait(lambda: d.flightrec.stats()
                         ["incidents-by-kind"].get("map-pressure",
                                                   0) >= 1,
                         timeout=10)
            assert _wait(lambda: d.flightrec.list_bundles(),
                         timeout=10)
            bundle = d.flightrec.list_bundles()[0]["path"]
            from cilium_tpu.analysis.sysdump_lint import check_bundle

            assert check_bundle(bundle) == []
            import json

            with open(bundle) as f:
                body = json.load(f)
            assert body["pressure"]["state"] == "pressure"
            # pressure state rides serving stats + GET /serving shape
            d.start_serving(trace_sample=0, ingress=True,
                            packed=True)
            try:
                pr = d.serving_stats()["pressure"]
                assert pr["state"] == "pressure"
                assert pr["ct"]["insert-drops"] > 0
            finally:
                d.stop_serving()
        finally:
            d.shutdown()

    def test_patch_config_keeps_acceleration_mid_episode(self):
        """Review regression: a `ct-gc-interval` patch DURING a live
        pressure episode must not silently cancel the accelerated
        sweep (the monitor only accelerates on the OK->PRESSURE
        transition, so a reset here would stick until the episode
        re-entered)."""
        sc = make_scenario("syn_flood", seed=5, n_flows=2048,
                           batch=512)
        d = scenario_daemon(sc, map_pressure_interval=0.1,
                            ct_gc_pressure_interval=0.25)
        d.start()
        try:
            r = run_scenario(d, sc)
            assert r["metrics"]["ct_insert_drops"] > 0
            assert _wait(lambda: d.pressure.stats()["accelerated"],
                         timeout=10)
            assert _wait(lambda: d.controllers.get("ct-gc")
                         ._interval == 0.25, timeout=10)
            d.patch_config({"ct-gc-interval": 60.0})
            assert d.config.ct_gc_interval == 60.0
            # still accelerated: the episode owns the cadence
            assert d.controllers.get("ct-gc")._interval == 0.25
            # once the episode would exit, restore targets the NEW
            # configured cadence
            d._ct_gc_restore()
            assert d.controllers.get("ct-gc")._interval == 60.0
        finally:
            d.shutdown()

    def test_registry_series_after_sample(self):
        sc = make_scenario("syn_flood", seed=5, n_flows=2048,
                           batch=512)
        d = scenario_daemon(sc, map_pressure_interval=0.1)
        d.start()
        try:
            r = run_scenario(d, sc)
            assert r["metrics"]["ct_insert_drops"] > 0
            # wait until the sampler has caught up to the FINAL drop
            # count — a mid-run sample can satisfy a bare > 0 check
            # and leave the render one 0.1 s tick stale
            assert _wait(lambda: (d.pressure.last or {}).get(
                "ct", {}).get("insert-drops", 0)
                >= r["metrics"]["ct_insert_drops"], timeout=10)
            prom = d.registry.render()
            assert "cilium_ct_occupancy " in prom
            assert "cilium_ct_insert_drops_total " in prom
            assert "cilium_nat_pool_failures_total " in prom
            assert "cilium_map_pressure 1" in prom
            drops = int(float(next(
                line.split()[1] for line in prom.splitlines()
                if line.startswith("cilium_ct_insert_drops_total "))))
            assert drops >= r["metrics"]["ct_insert_drops"]
        finally:
            d.shutdown()

    def test_follow_mode_rate_keys_cover_pressure(self):
        from cilium_tpu.cli.main import _SERVING_RATE_KEYS

        paths = {keys for keys, _label in _SERVING_RATE_KEYS}
        assert ("pressure", "ct", "insert-drops") in paths
        assert ("pressure", "nat", "failures") in paths


# ---------------------------------------------------------------------
@pytest.mark.scenario
class TestNatExhaustionLeg:
    """The acceptance leg: nat_exhaustion drops count as
    REASON_NAT_EXHAUSTED end-to-end — metricsmap -> monitor -> flow
    -> CLI decode tables — and surface as NAT pool pressure."""

    def test_nat_exhaustion_end_to_end(self):
        from cilium_tpu.datapath.verdict import REASON_NAT_EXHAUSTED
        from cilium_tpu.flow.flow import DROP_REASON_DESC
        from cilium_tpu.monitor.api import DROP_REASON_NAMES

        sc = make_scenario("nat_exhaustion", seed=7)
        d = scenario_daemon(sc, map_pressure_interval=0.1)
        d.start()
        try:
            r = run_scenario(d, sc)
            assert r["passed"], r["checks"]
            m = r["metrics"]
            assert m["nat_failures"] > 0
            # metricsmap
            assert m["drops_by_reason"].get(
                REASON_NAT_EXHAUSTED, 0) > 0
            mm = d.loader.metrics()
            assert mm[REASON_NAT_EXHAUSTED].sum() > 0
            # monitor -> flow: the observer holds DROP flows with the
            # NAT reason and the hubble JSON renders its desc
            flows = [f for f in d.observer.get_flows(number=2000)
                     if f.drop_reason == REASON_NAT_EXHAUSTED]
            assert flows, "no NAT-exhausted flows reached the ring"
            fd = flows[0].to_dict()
            assert fd["drop_reason_desc"] == \
                DROP_REASON_DESC[REASON_NAT_EXHAUSTED]
            # CLI decode table (monitor/api)
            assert DROP_REASON_NAMES[REASON_NAT_EXHAUSTED] \
                == "No mapping for NAT masquerade"
            # the pool-pressure surface: loader sample + nat_status
            p = d.loader.map_pressure(d._now())
            assert p["nat"]["failures"] == m["nat_failures"]
            assert p["nat"]["capacity"] == 256
            ns = d.loader.nat_status(d._now())
            assert ns["alloc-failed"] == m["nat_failures"]
            # the monitor entered pressure off the NAT deltas
            assert _wait(lambda: d.pressure.stats()["episodes"] >= 1,
                         timeout=10)
        finally:
            d.shutdown()

    def test_interpreter_backend_parity(self):
        """The same ramp on the oracle backend: same reason, pool
        failures counted (generation/metrics parity discipline)."""
        from cilium_tpu.datapath.verdict import REASON_NAT_EXHAUSTED

        sc = make_scenario("nat_exhaustion", seed=7, n_flows=512,
                           batch=128)
        d = scenario_daemon(sc, backend="interpreter",
                            map_pressure_interval=0.0)
        d.start()
        try:
            r = run_scenario(d, sc)
            m = r["metrics"]
            assert m["nat_failures"] > 0
            assert m["drops_by_reason"].get(
                REASON_NAT_EXHAUSTED, 0) > 0
        finally:
            d.shutdown()
