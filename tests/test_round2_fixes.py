"""Round-2 parity/robustness fixes.

Covers: route_by_flow overflow accounting (the RSS-queue-overflow
analogue), interpreter-backend CT checkpoint/restore and cross-backend
snapshot portability, endpoint-id bounds vs the fixed ep_policy table,
and ICMP type-as-port semantics incl. the upstream `icmps` rule field.
"""

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_SYN, make_batch
from cilium_tpu.core.packets import N_COLS, COL_DPORT, COL_PROTO
from cilium_tpu.datapath.verdict import MAX_ENDPOINTS, REASON_ROUTE_OVERFLOW
from cilium_tpu.monitor.api import MSG_DROP, MSG_POLICY_VERDICT


def _mk_daemon(backend="tpu", **kw) -> Daemon:
    return Daemon(DaemonConfig(backend=backend, ct_capacity=1 << 12, **kw))


def _pkt(src, dst, dport, ep, dirn=0, flags=TCP_SYN, sport=40000, proto=6):
    return dict(src=src, dst=dst, sport=sport, dport=dport, proto=proto,
                flags=flags, ep=ep, dir=dirn)


class TestRouteOverflow:
    def test_skewed_batch_overflow_is_counted(self):
        """One elephant flow: every packet hashes to a single shard, so
        a small block must overflow and the loss must be visible."""
        from cilium_tpu.parallel import route_by_flow

        n = 256
        data = np.zeros((n, N_COLS), dtype=np.uint32)
        data[:, 3] = 0x0A000001  # same src
        data[:, 7] = 0x0A000002  # same dst -> same flow hash
        data[:, 8] = 40000
        data[:, 9] = 443
        data[:, COL_PROTO] = 6
        routed, valid, orig, n_overflow = route_by_flow(data, 8, block=16)
        assert n_overflow == n - 16
        assert int(valid.sum()) == 16
        assert int((orig >= 0).sum()) == 16

    def test_no_overflow_on_uniform_traffic(self):
        from cilium_tpu.core.packets import synth_batch
        from cilium_tpu.parallel import route_by_flow

        batch = synth_batch(512, np.random.default_rng(0))
        routed, valid, orig, n_overflow = route_by_flow(batch.data, 8)
        assert n_overflow == 0
        assert int(valid.sum()) == 512

    def test_overflow_lands_in_metricsmap(self):
        from cilium_tpu.parallel import add_route_overflow
        from cilium_tpu.testing.fixtures import build_world

        world = build_world(n_identities=8, n_rules=2,
                            ct_capacity=1 << 10)
        state = add_route_overflow(world.state, 37)
        m = np.asarray(state.metrics)
        assert int(m[REASON_ROUTE_OVERFLOW, 0]) == 37
        # zero is a no-op returning the same state
        assert add_route_overflow(state, 0) is state


RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
    ],
}]


class TestInterpreterCheckpoint:
    def test_interpreter_ct_survives_checkpoint(self, tmp_path):
        """Backend parity: the interpreter daemon checkpoints CT too
        (round-1 hole: ct_snapshot raised NotImplementedError)."""
        d = _mk_daemon(backend="interpreter")
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        evb = d.process_batch(make_batch([
            _pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=10)
        assert list(evb.verdict) == [1]
        d.checkpoint(str(tmp_path))

        d2 = _mk_daemon(backend="interpreter")
        assert d2.restore(str(tmp_path))
        # established entry restored: reply direction forwards as TRACE
        # without any policy lookup
        from cilium_tpu.monitor.api import MSG_TRACE

        evb2 = d2.process_batch(make_batch([
            _pkt("10.0.2.1", "10.0.1.1", 40000, db.id, dirn=1,
                 sport=5432, flags=0x10)]).data, now=20)
        assert list(evb2.verdict) == [1]
        assert list(evb2.msg_type) == [MSG_TRACE]

    def test_cross_backend_snapshot_roundtrip(self, tmp_path):
        """A CT snapshot from the interpreter restores into the TPU
        backend (dense rows re-placed by device hash) and vice versa."""
        d = _mk_daemon(backend="interpreter")
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        d.process_batch(make_batch([
            _pkt("10.0.1.1", "10.0.2.1", 5432, db.id)]).data, now=10)
        d.checkpoint(str(tmp_path))

        d2 = _mk_daemon(backend="tpu")
        assert d2.restore(str(tmp_path))
        from cilium_tpu.monitor.api import MSG_TRACE

        evb = d2.process_batch(make_batch([
            _pkt("10.0.2.1", "10.0.1.1", 40000, db.id, dirn=1,
                 sport=5432, flags=0x10)]).data, now=20)
        assert list(evb.verdict) == [1]
        assert list(evb.msg_type) == [MSG_TRACE]

    def test_corrupt_ct_snapshot_does_not_abort_restore(self, tmp_path):
        d = _mk_daemon(backend="tpu")
        d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        d.policy_import(RULES)
        d.checkpoint(str(tmp_path))
        (tmp_path / "ct.npz").write_bytes(b"not an npz")
        d2 = _mk_daemon(backend="tpu")
        assert d2.restore(str(tmp_path))  # identities/rules intact
        assert d2.repo.revision >= 1
        assert len(d2.endpoints.list()) == 1


class TestEndpointIdBounds:
    def test_out_of_range_ep_id_rejected(self):
        d = _mk_daemon(backend="interpreter")
        with pytest.raises(ValueError, match="out of range"):
            d.endpoints.add("bad", ("10.0.9.9",),
                            __import__("cilium_tpu").labels.LabelSet.parse(
                                "k8s:app=x"), ep_id=MAX_ENDPOINTS)


class TestICMPSemantics:
    def test_icmps_rule_allows_type_not_port(self):
        """Upstream `icmps` field: allow echo request (type 8) only.
        Type 0 (echo reply as a NEW flow) stays denied, and TCP port 8
        is NOT allowed (no class-space sharing with ICMP)."""
        d = _mk_daemon()
        web = d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                "icmps": [{"fields": [{"type": 8, "family": "IPv4"}]}],
            }],
        }])
        evb = d.process_batch(make_batch([
            _pkt("10.0.1.1", "10.0.2.1", 8, db.id, proto=1, flags=0,
                 sport=0),   # echo request: allowed
            _pkt("10.0.1.2", "10.0.2.1", 0, db.id, proto=1, flags=0,
                 sport=0),   # echo reply as NEW flow: denied
            _pkt("10.0.1.1", "10.0.2.1", 8, db.id),  # TCP :8 denied
        ]).data, now=10)
        assert list(evb.verdict) == [1, 0, 0]
        assert list(evb.msg_type) == [MSG_POLICY_VERDICT, MSG_DROP,
                                      MSG_DROP]

    def test_icmp_type_zero_exact(self):
        """icmp_type=0 must NOT wildcard (port '0' convention)."""
        from cilium_tpu.policy.api import _icmp_port_rules

        (pr,) = _icmp_port_rules([{"fields": [{"type": 0}]}])
        (pp,) = pr.ports
        assert pp.port_range() == (0, 0)


class TestVectorizedCTPlacement:
    def test_many_flows_place_and_lookup(self):
        """Vectorized snapshot placement: every row findable by the
        device probe; drop count correct under forced pressure."""
        from cilium_tpu.datapath.conntrack import (
            KEY_WORDS, ROW_WORDS, ST_ESTABLISHED, V_EXPIRES, V_STATE,
            _hash_np, ct_table_from_rows)

        rng = np.random.default_rng(12)
        n = 5000
        rows = np.zeros((n, ROW_WORDS), dtype=np.uint32)
        rows[:, :KEY_WORDS] = rng.integers(
            1, 2**32, (n, KEY_WORDS), dtype=np.uint32)
        rows[:, V_STATE] = ST_ESTABLISHED
        rows[:, V_EXPIRES] = 10_000
        # 30% load: no pressure drops expected (at 60%+ the 16-slot
        # probe window genuinely saturates — for the sequential placer
        # too — and drops are counted, see below)
        table, dropped = ct_table_from_rows(rows, 1 << 14)
        assert dropped == 0
        # every key must be reachable within the probe window
        hs = _hash_np(rows[:, :KEY_WORDS])
        mask = (1 << 14) - 1
        for i in range(0, n, 97):
            found = False
            for step in range(16):
                s = int((hs[i] + np.uint32(step)) & mask)
                if (table[s, :KEY_WORDS] == rows[i, :KEY_WORDS]).all():
                    found = True
                    break
            assert found, f"row {i} not reachable by probe"
        # pressure: tiny table must drop the overflow, counted
        _t, dropped = ct_table_from_rows(rows, 1 << 8)
        assert dropped == n - (_t[:, V_STATE] != 0).sum() \
            and dropped > 0
