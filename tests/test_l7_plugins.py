"""L7 protocol plugin registry: cassandra/memcached ride the generic
seam (reference: proxylib plugin parsers — cassandra query_action/
query_table, memcache command/key rules)."""

import numpy as np
import pytest

from cilium_tpu.policy.api import L7Rules
from cilium_tpu.proxy import L7Proxy
from cilium_tpu.proxy.plugins import parse_cql
from cilium_tpu.proxy.registry import (L7Protocol, featurize_generic,
                                       get, names, next_kind, register)


def _proxy(rules_dict, port=11000):
    l7 = L7Rules.from_dict(rules_dict)
    proxy = L7Proxy()
    proxy.update([type("P", (), {"redirects": [(port, "t", l7)]})()])
    return proxy


class TestCassandra:
    def test_schema_key_rides_l7rules_extra(self):
        l7 = L7Rules.from_dict({"cassandra": [
            {"queryAction": "select", "queryTable": "ks.users"}]})
        assert not l7.is_empty
        assert l7.extra_by_name["cassandra"][0]["queryAction"] == "select"

    def test_exact_action_table_verdicts(self):
        proxy = _proxy({"cassandra": [
            {"queryAction": "select", "queryTable": "ks.users"},
            {"queryAction": "insert", "queryTable": "ks.audit"},
        ]})
        allow = proxy.handle("cassandra", 11000, [
            {"action": "select", "table": "ks.users"},   # rule 1
            {"action": "insert", "table": "ks.audit"},   # rule 2
            {"action": "select", "table": "ks.secrets"}, # no rule
            {"action": "drop-table", "table": "ks.users"},  # no rule
        ])
        assert allow.tolist() == [1, 1, 0, 0]

    def test_query_strings_parse_and_verdict(self):
        proxy = _proxy({"cassandra": [
            {"queryAction": "select", "queryTable": "ks.users"}]})
        allow = proxy.handle("cassandra", 11000, [
            {"query": "SELECT name FROM ks.users WHERE id = 1"},
            {"query": "DELETE FROM ks.users WHERE id = 1"},
        ])
        assert allow.tolist() == [1, 0]

    def test_regex_table_takes_host_fallback(self):
        proxy = _proxy({"cassandra": [
            {"queryAction": "select", "queryTable": "ks\\.(users|posts)"}]})
        allow = proxy.handle("cassandra", 11000, [
            {"action": "select", "table": "ks.posts"},
            {"action": "select", "table": "ks.secrets"},
        ])
        assert allow.tolist() == [1, 0]
        assert proxy.host_fallback_checked > 0

    def test_parse_cql(self):
        assert parse_cql("INSERT INTO ks.t (a) VALUES (1)") == {
            "action": "insert", "table": "ks.t"}
        assert parse_cql("UPDATE ks.t SET a = 1") == {
            "action": "update", "table": "ks.t"}
        assert parse_cql("") == {}


class TestMemcached:
    def test_command_and_exact_key(self):
        proxy = _proxy({"memcached": [
            {"command": "get", "keyExact": "session/1"}]})
        allow = proxy.handle("memcached", 11000, [
            {"command": "get", "key": "session/1"},
            {"command": "set", "key": "session/1"},
            {"command": "get", "key": "session/2"},
        ])
        assert allow.tolist() == [1, 0, 0]

    def test_key_prefix_fallback(self):
        proxy = _proxy({"memcached": [
            {"command": "get", "keyPrefix": "public/"}]})
        allow = proxy.handle("memcached", 11000, [
            {"command": "get", "key": "public/motd"},
            {"command": "get", "key": "private/motd"},
        ])
        assert allow.tolist() == [1, 0]


class TestRegistrySeam:
    def test_builtin_plugins_registered(self):
        assert {"cassandra", "memcached"} <= set(names())

    def test_fourth_protocol_needs_only_registration(self):
        # a toy "redis"-ish protocol defined ENTIRELY here: commands +
        # key, no edits to featurize/l7policy/proxy
        kind = next_kind()
        cmds = {"get": 1, "set": 2}
        proto = register(L7Protocol(
            name="toyredis", kind=kind,
            featurize=lambda reqs, port, src_row=0: featurize_generic(
                kind, reqs, port, src_row,
                method_of=lambda r: cmds.get(r.get("cmd", ""), 0),
                f0_of=lambda r: r.get("key", "")),
            compile_rule=lambda rule: (
                "row", [cmds.get(rule.get("cmd", ""), 0),
                        *__import__("cilium_tpu.proxy.featurize",
                                    fromlist=["fnv64"]).fnv64(
                            rule.get("key", "")), 0, 0]),
        ))
        assert get("toyredis") is proto
        proxy = _proxy({"toyredis": [{"cmd": "get", "key": "k1"}]})
        allow = proxy.handle("toyredis", 11000, [
            {"cmd": "get", "key": "k1"},
            {"cmd": "set", "key": "k1"},
        ])
        assert allow.tolist() == [1, 0]

    def test_conflicting_kind_rejected(self):
        with pytest.raises(ValueError):
            register(L7Protocol(
                name="clasher", kind=16,  # cassandra's kind
                featurize=lambda *a: None,
                compile_rule=lambda r: ("row", [0, 0, 0, 0, 0])))

    def test_unregistered_protocol_rules_mean_default_deny(self):
        proxy = _proxy({"nosuchproto": [{"anything": "x"}]})
        with pytest.raises(KeyError):
            proxy.handle("nosuchproto", 11000, [{"x": 1}])

    def test_access_records_carry_plugin_fields(self):
        records = []
        proxy = _proxy({"memcached": [
            {"command": "get", "keyExact": "k"}]})
        proxy.on_record(records.append)
        proxy.handle("memcached", 11000, [{"command": "get", "key": "k"}])
        [rec] = records
        assert rec.method == "get" and rec.path == "k"
        assert rec.verdict == 1


class TestUpstreamL7ProtoSchema:
    def test_l7proto_key_maps_to_plugin(self):
        """Review r04: the upstream api.PortRuleL7 spelling
        ({l7proto, l7}) must reach the registered parser."""
        l7 = L7Rules.from_dict({"l7proto": "cassandra",
                                "l7": [{"queryAction": "select",
                                        "queryTable": "ks.users"}]})
        assert l7.extra_by_name["cassandra"][0]["queryTable"] == "ks.users"
        proxy = _proxy({"l7proto": "memcached",
                        "l7": [{"command": "get", "keyExact": "k"}]})
        allow = proxy.handle("memcached", 11000,
                             [{"command": "get", "key": "k"},
                              {"command": "set", "key": "k"}])
        assert allow.tolist() == [1, 0]

    def test_non_list_rules_rejected_clearly(self):
        with pytest.raises(ValueError, match="must be a list"):
            L7Rules.from_dict({"cassandra": "select"})


class TestWireParsers:
    """The proxylib OnData analogue: raw protocol bytes -> verdicts."""

    def test_cql_query_frame_bytes(self):
        import struct

        from cilium_tpu.proxy.plugins import parse_cql_frames

        q = b"SELECT * FROM ks.users WHERE id = 1"
        frame = (bytes([0x04, 0, 0, 0, 0x07])  # v4 request, QUERY
                 + struct.pack(">i", len(q) + 4)  # body length
                 + struct.pack(">i", len(q)) + q)
        [req] = parse_cql_frames([frame])
        assert req == {"action": "select", "table": "ks.users"}
        proxy = _proxy({"cassandra": [
            {"queryAction": "select", "queryTable": "ks.users"}]})
        allow = proxy.handle_bytes("cassandra", 11000, [frame])
        assert allow.tolist() == [1]
        # a DELETE frame against the same policy is denied
        q2 = b"DELETE FROM ks.users WHERE id = 1"
        frame2 = (bytes([0x04, 0, 0, 0, 0x07])
                  + struct.pack(">i", len(q2) + 4)
                  + struct.pack(">i", len(q2)) + q2)
        assert proxy.handle_bytes("cassandra", 11000,
                                  [frame2]).tolist() == [0]
        # non-QUERY opcodes and garbage match no rule -> denied
        assert proxy.handle_bytes(
            "cassandra", 11000,
            [bytes([0x04, 0, 0, 0, 0x05]) + b"\x00" * 4,
             b"xx"]).tolist() == [0, 0]

    def test_memcache_text_lines(self):
        proxy = _proxy({"memcached": [
            {"command": "get", "keyPrefix": "public/"}]})
        allow = proxy.handle_bytes("memcached", 11000, [
            b"get public/motd\r\n",
            b"get private/motd\r\n",
            b"set public/motd 0 60 5\r\nhello\r\n",
            b"",
        ])
        assert allow.tolist() == [1, 0, 0, 0]

    def test_plugin_without_wire_parser_raises(self):
        proxy = _proxy({"toyredis2": [{"cmd": "get"}]})
        with pytest.raises(KeyError):
            proxy.handle_bytes("toyredis2", 11000, [b"x"])
