"""Chaos suite: the serving plane under injected faults (ISSUE 3).

Acceptance:
(a) an injected dispatch hang is detected within the configured
    deadline and the runtime recovers without operator action;
(b) sharded -> single-chip demotion preserves established CT flows
    (replies still pass);
(c) ``submitted == verdicts + shed + recovery_dropped`` holds EXACTLY
    under every fault schedule, with the drops visible as decoded
    events through monitor -> flow -> CLI.

Discipline: every schedule is SEEDED (infra/faults.py draws replay),
and no test sleeps longer than the watchdog deadline it exercises —
progress is observed by polling with a bounded budget.
"""

import json
import time

import numpy as np
import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.core import TCP_ACK, TCP_SYN, make_batch
from cilium_tpu.core.packets import COL_DIR, N_COLS
from cilium_tpu.datapath.verdict import (N_REASONS,
                                         REASON_DISPATCH_TIMEOUT,
                                         REASON_RECOVERY_DROP)
from cilium_tpu.flow.flow import DROP_REASON_DESC
from cilium_tpu.infra import faults
from cilium_tpu.monitor.api import (DROP_REASON_NAMES, MSG_DROP,
                                    DropNotify, materialize)
from cilium_tpu.serving import (DispatchFailedError, FallbackLadder,
                                IngressQueue, ServingError,
                                ServingRuntime,
                                validate_recovery_config)

pytestmark = pytest.mark.chaos

RULES = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
}]

# db egress-enforced: a db-sourced reply passes its egress hook ONLY
# via the CT reply fast path (same construction as the sharded
# flow-affinity proof in test_serving_sharded.py) — the CT-continuity
# oracle for demotion
RULES_EGRESS_ENFORCED = [{
    "endpointSelector": {"matchLabels": {"app": "db"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"app": "web"}}],
        "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}],
    }],
    "egress": [{
        "toEndpoints": [{"matchLabels": {"app": "db"}}],
        "toPorts": [{"ports": [{"port": "1", "protocol": "TCP"}]}],
    }],
}]


def _daemon(fault_spec=None, rules=RULES, **over):
    # ONE ladder rung: every distinct bucket is an XLA compile, and
    # this suite's job is fault schedules, not shape coverage
    cfg = dict(backend="tpu", ct_capacity=1 << 12,
               flow_ring_capacity=1 << 13,
               serving_queue_depth=4096,
               serving_bucket_ladder=(64,),
               serving_max_wait_us=500.0,
               serving_dispatch_deadline_ms=500.0,
               serving_restart_budget=4,
               serving_restart_backoff_ms=1.0,
               serving_demote_threshold=2,
               serving_promote_after=3,
               serving_promote_cooldown_s=0.05,
               fault_injection=fault_spec, fault_seed=1)
    cfg.update(over)
    d = Daemon(DaemonConfig(**cfg))
    d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
    db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import(rules)
    return d, db


def _fwd(db_id, n=64, base=20000):
    return make_batch([
        dict(src="10.0.1.1", dst="10.0.2.1", sport=base + i,
             dport=5432, proto=6, flags=TCP_SYN, ep=db_id, dir=0)
        for i in range(n)]).data


def _rep(db_id, n=64, base=20000):
    return make_batch([
        dict(src="10.0.2.1", dst="10.0.1.1", sport=5432,
             dport=base + i, proto=6, flags=TCP_ACK, ep=db_id, dir=1)
        for i in range(n)]).data


def _wait(pred, timeout=30.0, tick=0.002):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(tick)
    return True


def _assert_ledger(fe):
    ft = fe["fault-tolerance"]
    assert fe["submitted"] == (fe["verdicts"] + fe["shed"]
                               + ft["recovery-dropped"]), (
        f"ledger broken: {fe['submitted']} != {fe['verdicts']} + "
        f"{fe['shed']} + {ft['recovery-dropped']}")
    return ft


# ---------------------------------------------------------------------
class TestFaultFramework:
    def test_spec_parses_and_replays_deterministically(self):
        a = faults.FaultInjector("loader.serve=0.5", seed=9)
        b = faults.FaultInjector("loader.serve=0.5", seed=9)
        pattern = []
        for inj in (a, b):
            hits = []
            for _ in range(32):
                try:
                    inj.check("loader.serve")
                    hits.append(0)
                except faults.InjectedFault:
                    hits.append(1)
            pattern.append(hits)
        assert pattern[0] == pattern[1]
        assert 0 < sum(pattern[0]) < 32  # actually probabilistic

    def test_count_and_skip_limits(self):
        inj = faults.FaultInjector("serving.dispatch=1x2@1")
        inj.check("serving.dispatch")  # skipped (inert warmup pass)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                inj.check("serving.dispatch")
        inj.check("serving.dispatch")  # count exhausted: no-op
        assert inj.fired["serving.dispatch"] == 2

    def test_hang_sleeps_and_aborts(self):
        inj = faults.FaultInjector("serving.dispatch=1~0.08")
        t0 = time.monotonic()
        inj.check("serving.dispatch")
        assert time.monotonic() - t0 >= 0.07
        t0 = time.monotonic()
        inj.check("serving.dispatch", abort=lambda: True)
        assert time.monotonic() - t0 < 0.05  # cancelled stall

    def test_unknown_site_and_bad_entries_raise(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.FaultInjector("serving.disptach=1")
        with pytest.raises(ValueError, match="bad fault spec"):
            faults.FaultInjector("serving.dispatch")
        with pytest.raises(ValueError, match="not in"):
            faults.FaultInjector("serving.dispatch=1.5")

    def test_daemon_arms_validates_and_disarms(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            _daemon(fault_spec="no.such.site=1")
        d, _db = _daemon(fault_spec="loader.serve=0x0")
        assert faults.active() is d._fault_injector
        d.shutdown()
        assert faults.active() is None

    def test_disarmed_check_is_a_noop(self):
        faults.disarm()
        faults.check("serving.dispatch")  # nothing armed: no-op

    def test_recovery_config_validation(self):
        with pytest.raises(ValueError, match="deadline"):
            validate_recovery_config(-1, 8, 10, 3, 64, 5.0)
        with pytest.raises(ValueError, match="budget"):
            validate_recovery_config(0, -1, 10, 3, 64, 5.0)
        with pytest.raises(ValueError, match="demote_threshold"):
            validate_recovery_config(0, 0, 0, 0, 64, 5.0)


# ---------------------------------------------------------------------
class TestQueueMemcpyAtomicity:
    def test_faulted_take_into_loses_nothing(self):
        """The dequeue memcpy site kills the consumer WITHOUT losing
        rows: nothing is popped until every copy landed, so the rows
        are still queued for the restarted drain thread."""
        q = IngressQueue(1024)
        rows = np.arange(3 * 50 * N_COLS,
                         dtype=np.uint32).reshape(150, N_COLS)
        for i in range(3):  # three chunks
            q.offer(rows[i * 50:(i + 1) * 50])
        out = np.zeros((128, N_COLS), dtype=np.uint32)
        inj = faults.arm("serving.queue.take=1x1@1")  # 2nd chunk copy
        try:
            with pytest.raises(faults.InjectedFault):
                q.take_into(out)
            assert q.pending == 150  # exception-atomic: all retained
            got, arrivals = q.take_into(out)  # retry drains normally
            assert got == 128
            np.testing.assert_array_equal(out, rows[:128])
            assert q.pending == 22
        finally:
            faults.disarm(inj)


# ---------------------------------------------------------------------
class TestDeadThreadRecovery:
    def test_restart_accounts_and_recovers(self):
        """One dispatch raises -> the drain thread dies -> the
        watchdog restarts it; the lost batch is counted + surfaced as
        REASON_RECOVERY_DROP events (monitor AND metricsmap), later
        traffic flows, and the ledger balances exactly."""
        d, db = _daemon(fault_spec="serving.dispatch=1x1")
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(trace_sample=0, ingress=True, drain_every=2)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        d.submit(rows.copy())  # this batch dies with the thread
        assert _wait(lambda: rt.stats.restarts >= 1, timeout=20)
        d.submit(rows.copy())  # post-restart traffic flows
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=30)
        fe = d.stop_serving()["front-end"]
        ft = _assert_ledger(fe)
        assert ft["restarts"] == 1
        assert ft["recovery-dropped"] == 64
        assert "InjectedFault" in ft["last-restart-cause"]
        # decoded all the way: monitor events carry the reason, the
        # DropNotify name renders, the metricsmap counts it
        drops = np.concatenate(
            [b.reason[b.msg_type == MSG_DROP] for b in got])
        assert int((drops == REASON_RECOVERY_DROP).sum()) == 64
        ev = next(materialize(b, i)
                  for b in got
                  for i in range(len(b))
                  if b.reason[i] == REASON_RECOVERY_DROP)
        assert DropNotify(ev).reason_name == "Recovery drop"
        assert DROP_REASON_DESC[REASON_RECOVERY_DROP] == \
            "RECOVERY_DROP"
        m = d.loader.metrics()
        assert int(m[REASON_RECOVERY_DROP].sum()) == 64
        d.shutdown()

    def test_submit_keeps_working_during_the_recovery_window(self):
        """A supervised death must not bounce producers: the queue is
        intact and the watchdog is healing the consumer."""
        d, db = _daemon(fault_spec="serving.dispatch=1x1",
                        serving_restart_backoff_ms=50.0)
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        d.submit(rows.copy())
        # wait for the corpse (error set), then submit INTO the window
        assert _wait(lambda: rt._error is not None
                     or rt.stats.restarts >= 1, timeout=20)
        assert d.submit(rows.copy()) == 64  # no raise
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=30)
        fe = d.stop_serving()["front-end"]
        _assert_ledger(fe)
        d.shutdown()


# ---------------------------------------------------------------------
class TestHangDetection:
    def test_hang_deadlined_and_recovered(self):
        """A wedged dispatch (3s stall, 150ms deadline) is detected at
        ~deadline, its batch counted as REASON_DISPATCH_TIMEOUT, and
        the runtime recovers without operator action — well before
        the stall would have ended."""
        d, db = _daemon(fault_spec="serving.dispatch=1x1@1~3",
                        serving_dispatch_deadline_ms=150.0)
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(trace_sample=0, ingress=True, drain_every=2)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        d.submit(rows.copy())  # warm: first dispatch pays the compile
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=30)
        t0 = time.monotonic()
        d.submit(rows.copy())  # the hang
        assert _wait(lambda: rt.stats.restarts >= 1, timeout=5)
        detect = time.monotonic() - t0
        # detection at ~deadline + watchdog tick (and far inside the
        # 3s stall); generous slack for a loaded CI box
        assert detect < 1.5, f"hang detected only after {detect:.3f}s"
        d.submit(rows.copy())  # recovered: traffic flows again
        assert _wait(lambda: rt.stats.verdicts >= 128, timeout=30)
        fe = d.stop_serving()["front-end"]
        ft = _assert_ledger(fe)
        assert ft["dispatch-timeouts"] == 1
        assert ft["timeout-dropped"] == 64
        drops = np.concatenate(
            [b.reason[b.msg_type == MSG_DROP] for b in got])
        assert int((drops == REASON_DISPATCH_TIMEOUT).sum()) == 64
        assert int(d.loader.metrics()[
            REASON_DISPATCH_TIMEOUT].sum()) == 64
        d.shutdown()


# ---------------------------------------------------------------------
class TestRestartBudget:
    def test_budget_exhaustion_goes_terminal_with_exact_ledger(self):
        """A persistent fault burns the budget, the runtime goes
        terminal (submit raises), and stop() still accounts every
        queued row — no silent loss even at the end of the line."""
        d, db = _daemon(fault_spec="serving.dispatch=1",
                        serving_restart_budget=2)
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        # keep offering load so every restarted loop faults again;
        # terminal is reached when submit starts raising
        with pytest.raises(ServingError, match="died"):
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30:
                d.submit(rows.copy())
                time.sleep(0.005)
            raise AssertionError("runtime never went terminal")
        assert rt.restarts >= 2
        # the watchdog stamps the terminal cause when it sees the
        # last corpse (may land just after submit started bouncing)
        assert _wait(lambda: "budget" in (rt._error or ""),
                     timeout=5)
        fe = d.stop_serving()["front-end"]
        ft = _assert_ledger(fe)
        assert fe["verdicts"] == 0  # every dispatch faulted
        assert ft["recovery-dropped"] == fe["submitted"]
        d.shutdown()


# ---------------------------------------------------------------------
class TestStopOverACorpse:
    """Satellite: stop() after a drain-thread death must still flush
    sheds, stamp the last completion, and count queued rows."""

    def test_stop_flushes_sheds_stamps_completion_counts_queue(self):
        import threading

        recovered = []
        calls = {"n": 0}
        release = threading.Event()

        def dispatch(hdr, valid, n_valid, packed_meta=None):
            calls["n"] += 1
            if calls["n"] == 2:
                # hold the loop here until the test has queued the
                # overflow + the never-to-dispatch rows, THEN die
                release.wait(10)
                raise RuntimeError("boom")

        sheds = []
        rt = ServingRuntime(
            dispatch, queue_depth=256, bucket_ladder=(64,),
            max_wait_us=100.0,
            on_shed=lambda rows, n: sheds.append(n),
            on_recovery_drop=lambda rows, n, r: recovered.append(
                (n, r)))  # unsupervised: budget 0 -> death is final
        rt.start()
        rows = np.ones((64, N_COLS), dtype=np.uint32)
        rt.submit(rows)  # batch 1 dispatches fine
        assert _wait(lambda: rt.stats.batches == 1, timeout=10)
        rt.submit(rows)  # batch 2 will kill the loop
        assert _wait(lambda: calls["n"] == 2, timeout=10)
        # rows that will never dispatch + a guaranteed overflow shed
        rt.submit(np.ones((300, N_COLS), dtype=np.uint32))
        release.set()
        assert _wait(lambda: rt._error is not None, timeout=10)
        snap = rt.stop()
        # 428 submitted = 64 dispatched + 44 shed (300 into a 256-cap
        # queue) + 320 recovery (batch 2 + the 256 swept rows); the
        # assertion is the LEDGER, not the constants
        ft = snap["fault-tolerance"]
        assert snap["submitted"] == (snap["verdicts"] + snap["shed"]
                                     + ft["recovery-dropped"])
        assert snap["verdicts"] == 64
        assert snap["shed"] == 44
        assert ft["recovery-dropped"] == 320
        assert sum(n for n, _r in recovered) == 320
        assert all(r == REASON_RECOVERY_DROP for _n, r in recovered)
        assert sum(sheds) == 44  # sheds flushed as events at stop
        # the completed batch's latency was stamped despite the corpse
        assert snap["latency-us"]["count"] >= 1
        assert "error" in snap

    def test_idle_wait_is_config_derived(self):
        """Satellite: the hard-coded 50ms idle tick is gone — a 40ms
        dispatch deadline derives a 10ms idle wait, so sub-50ms
        watchdog deadlines are honorable."""
        d, _db = _daemon(serving_dispatch_deadline_ms=40.0)
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        assert rt._idle_wait_s == pytest.approx(0.01)
        d.stop_serving()
        d.shutdown()
        # default deadline (1000ms): the legacy 50ms tick
        d2, _db2 = _daemon()
        d2.start_serving(trace_sample=0, ingress=True)
        assert d2._serving["runtime"]._idle_wait_s == \
            pytest.approx(0.05)
        d2.stop_serving()
        d2.shutdown()


# ---------------------------------------------------------------------
class TestLadderStateMachine:
    def test_hysteresis_and_floor(self):
        lad = FallbackLadder(["sharded", "single", "wide"],
                             demote_threshold=3, promote_after=2,
                             cooldown_s=10.0)
        assert not lad.record_failure("a")
        assert not lad.record_failure("b")
        lad.record_success()  # flapping resets the streak
        assert not lad.record_failure("c")
        assert not lad.record_failure("d")
        assert lad.record_failure("e")  # 3 consecutive -> demote
        assert lad.demote() == "single"
        # cooldown gates promotion even after sustained health
        lad.last_change = time.monotonic()
        assert not lad.record_success()
        assert not lad.record_success()
        lad.last_change = time.monotonic() - 11.0
        lad.ok_streak = 0
        lad.record_success()
        assert lad.record_success()
        assert lad.promote() == "sharded"
        # at the floor, failures never demote (they escalate)
        lad2 = FallbackLadder(["wide"], demote_threshold=1)
        assert lad2.at_floor
        assert not lad2.record_failure("x")

    def test_rungs_follow_session_config(self):
        d, _db = _daemon()
        d.start_serving(trace_sample=0, ingress=True)  # no mesh/pack
        assert d._serving["ladder"].rungs == ("wide",)
        d.stop_serving()
        d.start_serving(trace_sample=0, ingress=True, packed=True)
        assert d._serving["ladder"].rungs == ("single", "wide")
        d.stop_serving()
        d.shutdown()


class TestLadderDemotion:
    def test_packed_demotes_to_wide_then_promotes_back(self):
        """Two packed-path faults demote single -> wide (the
        triggering batch retried on the demoted rung, not lost);
        sustained health + cooldown promote back."""
        d, db = _daemon(fault_spec="loader.serve_packed=1x2@1")
        d.start_serving(trace_sample=0, ingress=True, packed=True,
                        drain_every=2)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        d.submit(rows.copy())  # warm (packed)
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=30)
        d.submit(rows.copy())  # fault 1: contained drop
        assert _wait(lambda: rt.stats.recovery_dropped >= 64,
                     timeout=30)
        d.submit(rows.copy())  # fault 2: demote + retry (saved)
        assert _wait(lambda: rt.stats.verdicts >= 128, timeout=60)
        st = d.serving_stats()
        assert st["mode"] == "wide"
        assert st["ladder"]["demotions"] == 1
        assert rt.stats.restarts == 0  # contained: no restart burned
        # heal: promote_after=3 healthy batches + 50ms cooldown
        for i in range(5):
            d.submit(rows.copy())
            assert _wait(
                lambda i=i: rt.stats.verdicts >= 128 + (i + 1) * 64,
                timeout=30)
            time.sleep(0.02)
        assert _wait(
            lambda: d.serving_stats()["mode"] == "single", timeout=10)
        assert d.serving_stats()["ladder"]["promotions"] == 1
        fe = d.stop_serving()["front-end"]
        _assert_ledger(fe)
        d.shutdown()

    def test_sharded_demotion_preserves_established_ct(self):
        """THE acceptance property (b): flows established while
        sharded still pass their replies after demotion to
        single-chip — db's egress hook is enforced, so a reply can
        only pass via the CT entry carried across by
        snapshot + ct_restore."""
        d, db = _daemon(fault_spec="loader.serve_sharded=1x2@1",
                        rules=RULES_EGRESS_ENFORCED,
                        serving_promote_after=1000)
        from cilium_tpu.parallel import make_mesh

        got = []
        d.monitor.register("t", got.append)
        # 4 chips: the CT-continuity property is mesh-size-invariant
        # and the sharded serve step's compile is the suite's single
        # biggest cost
        d.start_serving(ring_capacity=1 << 10, trace_sample=1,
                        ingress=True, packed=True,
                        drain_every=2, mesh=make_mesh(4))
        rt = d._serving["runtime"]
        d.submit(_fwd(db.id))  # establish 64 flows, sharded (warm)
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=60)
        assert d.serving_stats()["mode"] == "sharded"
        d.submit(_fwd(db.id, base=40000))  # fault 1: contained
        assert _wait(lambda: rt.stats.recovery_dropped >= 64,
                     timeout=60)
        d.submit(_fwd(db.id, base=41000))  # fault 2: demote + retry
        assert _wait(lambda: rt.stats.verdicts >= 128, timeout=90)
        st = d.serving_stats()
        assert st["mode"] in ("single", "wide")
        assert st["ladder"]["demotions"] == 1
        # demotion stored a CT snapshot and restored it
        assert st["ct-snapshot"]["trigger"] == "demotion"
        assert st["ct-snapshot"]["entries"] >= 64
        # replies of the PRE-DEMOTION flows on the demoted rung
        got.clear()
        d.submit(_rep(db.id))
        assert _wait(lambda: rt.stats.verdicts >= 192, timeout=60)
        fe = d.stop_serving()["front-end"]
        _assert_ledger(fe)
        rep_fwd = rep_drop = 0
        for b in got:
            m = b.hdr[:, COL_DIR] == 1
            rep_fwd += int((b.msg_type[m] != MSG_DROP).sum())
            rep_drop += int((b.msg_type[m] == MSG_DROP).sum())
        assert rep_drop == 0 and rep_fwd == 64, (
            f"CT continuity broken: {rep_drop} replies dropped, "
            f"{rep_fwd} forwarded")
        d.shutdown()


# ---------------------------------------------------------------------
class TestRandomFaultSchedule:
    @pytest.mark.parametrize("seed", [3, 11])
    def test_no_silent_loss_under_random_faults(self, seed):
        """Acceptance (c): a seeded random schedule over several sites
        — raises, contained packed failures, queue memcpy faults —
        and the ledger still balances EXACTLY at stop, with every
        recovery drop surfaced as a decoded event."""
        d, db = _daemon(
            fault_spec=("serving.dispatch=0.05;"
                        "loader.serve_packed=0.1;"
                        "serving.queue.take=0.02"),
            fault_seed=seed,
            serving_restart_budget=64,
            serving_demote_threshold=3)
        got = []
        d.monitor.register("t", got.append)
        d.start_serving(trace_sample=0, ingress=True, packed=True,
                        drain_every=2)
        rt = d._serving["runtime"]
        rows = _fwd(db.id)
        submitted = 0
        for i in range(30):
            try:
                submitted += d.submit(rows.copy())
            except ServingError:
                break  # terminal (budget gone): stop still accounts
            # bounded pacing, far under the 500ms deadline
            _wait(lambda: rt.queue.pending < 2048, timeout=1.0)
        _wait(lambda: rt.queue.pending == 0, timeout=30)
        fe = d.stop_serving()["front-end"]
        ft = _assert_ledger(fe)
        assert fe["submitted"] == submitted
        # the schedule actually bit (seeded: deterministic)
        assert ft["recovery-dropped"] > 0
        # every recovery drop surfaced as a decoded DROP event
        drops = (np.concatenate(
            [b.reason[b.msg_type == MSG_DROP] for b in got])
            if got else np.zeros(0))
        n_rec = int(np.isin(drops, (REASON_DISPATCH_TIMEOUT,
                                    REASON_RECOVERY_DROP)).sum())
        assert n_rec == ft["recovery-events"]
        assert ft["recovery-events"] == ft["recovery-dropped"]
        d.shutdown()


# ---------------------------------------------------------------------
class TestSurfacing:
    def test_reason_codes_fit_the_ring_wire_format(self):
        """The 4-bit ring reason field covers the reserved recovery
        codes (N_REASONS=13 -> 3 codes of headroom; 12 is the
        cluster router's REASON_CLUSTER_OVERFLOW)."""
        import jax.numpy as jnp

        from cilium_tpu.datapath.verdict import (EV_DROP, N_OUT,
                                                 OUT_EVENT,
                                                 OUT_REASON)
        from cilium_tpu.monitor.ring import EventRing, ring_append, \
            ring_drain

        assert N_REASONS == 13 and N_REASONS <= 0xF + 1
        for reason in (REASON_DISPATCH_TIMEOUT, REASON_RECOVERY_DROP):
            out = np.zeros((4, N_OUT), dtype=np.uint32)
            out[:, OUT_EVENT] = EV_DROP
            out[:, OUT_REASON] = reason
            ring = EventRing.create(16)
            ring = ring_append(ring, jnp.asarray(out), jnp.uint32(0),
                               trace_sample=0)
            rows, total, _lost = ring_drain(ring)
            assert total == 4
            assert (rows[:, OUT_REASON] == reason).all()
            assert reason in DROP_REASON_NAMES
            assert reason in DROP_REASON_DESC

    def test_stats_prometheus_and_health_surfacing(self):
        """Fault counters reach GET /serving, prometheus, the node
        registry (health plane), and the CLI rendering path."""
        from cilium_tpu.api.server import _metrics_text
        from cilium_tpu.kvstore import InMemoryKVStore

        kv = InMemoryKVStore()
        d = Daemon(DaemonConfig(
            backend="tpu", ct_capacity=1 << 12,
            flow_ring_capacity=1 << 13, serving_queue_depth=4096,
            serving_bucket_ladder=(64,),
            serving_max_wait_us=500.0,
            fault_injection="serving.dispatch=1x1", fault_seed=1,
            serving_restart_backoff_ms=1.0), kvstore=kv)
        d.add_endpoint("web", ("10.0.1.1",), ["k8s:app=web"])
        db = d.add_endpoint("db", ("10.0.2.1",), ["k8s:app=db"])
        d.policy_import(RULES)
        d.start_serving(trace_sample=0, ingress=True)
        rt = d._serving["runtime"]
        d.submit(_fwd(db.id))
        assert _wait(lambda: rt.stats.restarts >= 1, timeout=20)
        d.submit(_fwd(db.id))
        assert _wait(lambda: rt.stats.verdicts >= 64, timeout=30)
        d.ct_snapshot_now()
        st = d.serving_stats()
        assert st["mode"] == "wide"
        assert st["fault-tolerance"]["restarts"] == 1
        assert st["ct-snapshot"]["entries"] >= 64
        prom = _metrics_text(d)
        assert "cilium_serving_restarts_total 1" in prom
        assert "cilium_serving_recovery_dropped_total 64" in prom
        assert "cilium_ct_snapshot_age_seconds" in prom
        # health plane: the node registry carries the fault state
        d.node_registry.annotate(d.config.node_name,
                                 d._node_fault_info())
        node = next(n for n in d.node_registry.nodes()
                    if n["name"] == d.config.node_name)
        assert node["serving-mode"] == "wide"
        assert node["serving-restarts"] == 1
        assert "ct-snapshot-age-seconds" in node
        # status() carries the same compact section
        assert d.status()["serving"]["serving-restarts"] == 1
        d.stop_serving()
        d.shutdown()

    def test_ct_snapshot_restore_round_trip(self):
        """ct_snapshot_now + restore_ct_snapshot: established flows
        survive a loader CT reload from the retained snapshot."""
        d, db = _daemon(rules=RULES_EGRESS_ENFORCED)
        d.process_batch(_fwd(db.id))  # establish flows (offline path)
        info = d.ct_snapshot_now(trigger="manual")
        assert info["entries"] >= 64 and info["trigger"] == "manual"
        # clobber the live CT, then restore from the snapshot
        from cilium_tpu.datapath.conntrack import ROW_WORDS

        d.loader.ct_restore(np.zeros((0, ROW_WORDS), dtype=np.uint32))
        assert d.restore_ct_snapshot()
        out = d.process_batch(_rep(db.id))
        assert int((out.msg_type == MSG_DROP).sum()) == 0
        d.shutdown()

    def test_dispatch_failed_error_is_a_serving_error(self):
        assert issubclass(DispatchFailedError, ServingError)
        j = json.dumps  # the ladder dict must be JSON-serializable
        lad = FallbackLadder(["wide"])
        j(lad.to_dict())
