"""Fault-injection suite for the distributed control plane (VERDICT
r03 row 39: "no fault-injection suite").

The invariants under injected kvstore faults (transient errors,
AMBIGUOUS commits that applied before raising, partitions, watch lag)
are the reference protocol's: one numeric per label set across nodes,
no lost allocations after heal, replicas converge.  Reference:
pkg/allocator + pkg/kvstore retry/backoff behavior against flaky etcd.
"""

import threading

import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.kvstore import InMemoryKVStore
from cilium_tpu.kvstore.allocator import KVStoreAllocatorBackend
from cilium_tpu.labels import LabelSet
from cilium_tpu.testing.chaos import ChaosKVStore, retry


class TestAllocatorUnderFaults:
    def test_transient_faults_converge_to_one_numeric(self):
        """Two nodes allocating the same keys through a 25%-failure
        store (half the failures ambiguous) must still agree — the
        write-then-verify protocol is re-entrant."""
        kv = InMemoryKVStore()
        ca = ChaosKVStore(kv, fail_rate=0.25, seed=1)
        cb = ChaosKVStore(kv, fail_rate=0.25, seed=2)
        a = KVStoreAllocatorBackend(ca, node="a", lease_ttl=0.2)
        b = KVStoreAllocatorBackend(cb, node="b", lease_ttl=0.2)
        for i in range(20):
            key = f"k8s:app=svc{i};"
            na = retry(lambda: a.allocate(key), backoff=0.05)
            nb = retry(lambda: b.allocate(key), backoff=0.05)
            assert na == nb, f"{key}: split-brain numeric {na} vs {nb}"
        assert ca.injected > 0 and ca.ambiguous > 0  # faults really hit

    def test_concurrent_same_key_racers_under_faults(self):
        """The duplicate-identity race (r03 ADVICE) stays closed while
        ops fail randomly around both racers."""
        kv = InMemoryKVStore()
        stores = [ChaosKVStore(kv, fail_rate=0.2, seed=s)
                  for s in range(4)]
        backends = [KVStoreAllocatorBackend(s, node=f"n{i}", lease_ttl=0.2)
                    for i, s in enumerate(stores)]
        results = {}

        def worker(i):
            results[i] = retry(
                lambda: backends[i].allocate("k8s:app=contended;"),
                attempts=20, backoff=0.05,
                swallow=(ConnectionError, TimeoutError))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        nums = set(results.values())
        assert len(results) == 4 and len(nums) == 1, results

    def test_partition_fails_cleanly_then_heals(self):
        kv = InMemoryKVStore()
        chaos = ChaosKVStore(kv, seed=3)
        a = KVStoreAllocatorBackend(chaos, node="a", lease_ttl=0.2)
        before = a.allocate("k8s:app=pre;")
        chaos.partition(True)
        with pytest.raises(ConnectionError):
            a.allocate("k8s:app=during;")
        chaos.partition(False)
        after = a.allocate("k8s:app=during;")
        assert after != before
        # pre-partition state survived the outage
        assert a.allocate("k8s:app=pre;") == before

    def test_ambiguous_commit_does_not_leak_duplicate_masters(self):
        """An allocate that raised AFTER applying (etcd commit-then-
        timeout) must not mint a second numeric on retry."""
        kv = InMemoryKVStore()
        chaos = ChaosKVStore(kv, fail_rate=0.5, seed=7)
        a = KVStoreAllocatorBackend(chaos, node="a", lease_ttl=0.2)
        num = retry(lambda: a.allocate("k8s:app=amb;"), attempts=30,
                    backoff=0.05,
                    swallow=(ConnectionError, TimeoutError))
        chaos.fail_rate = 0.0
        assert a.allocate("k8s:app=amb;") == num
        # exactly ONE master numeric points at this label set
        owners = [k for k, v in kv.list_prefix(
            "cilium/state/identities/").items()
            if "/id/" in k and v == b"k8s:app=amb;"]
        assert len(owners) == 1, owners


class TestDaemonsUnderWatchLag:
    def test_replication_converges_despite_watch_lag(self):
        """Identity replication rides a LAGGED watch: node B still
        converges to A's allocations (eventual consistency, the etcd
        watch-behind case)."""
        import time

        kv = InMemoryKVStore()
        lag = ChaosKVStore(kv, watch_delay=0.05, seed=4)
        da = Daemon(DaemonConfig(node_name="a", backend="interpreter"),
                    kvstore=kv)
        db = Daemon(DaemonConfig(node_name="b", backend="interpreter"),
                    kvstore=lag)
        web = da.allocator.allocate(LabelSet.parse("k8s:app=web"))
        deadline = time.time() + 5
        got = None
        while time.time() < deadline:
            got = db.allocator.lookup_by_id(web.numeric_id)
            if got is not None:
                break
            time.sleep(0.02)
        assert got is not None and got.labels == web.labels
