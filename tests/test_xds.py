"""xDS policy push surface (reference: pkg/envoy/xds SotW NPDS —
versioned snapshots, ACK by version echo, NACK by error detail)."""

import threading

import pytest

from cilium_tpu.agent import Daemon, DaemonConfig
from cilium_tpu.proxy.xds import TYPE_URL, XDSCache, policy_resource


def _daemon():
    d = Daemon(DaemonConfig(backend="interpreter"))
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "web"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                          "rules": {"http": [{"method": "GET",
                                              "path": "/api"}]}}]},
        ],
    }])
    d.add_endpoint("db-1", ("10.0.2.1",), ["k8s:app=db"])
    # a web endpoint so the fromEndpoints selector materializes into
    # concrete identity entries in the pushed resource
    d.add_endpoint("web-1", ("10.0.1.1",), ["k8s:app=web"])
    return d


class TestXDSCache:
    def test_attach_publishes_versioned_snapshot(self):
        d = _daemon()
        assert d.xds.version >= 1
        resp = d.xds.discover({})
        assert resp["type_url"] == TYPE_URL
        [res] = [r for r in resp["resources"] if "app=db" in r["name"]]
        assert res["ingress_enforcing"] is True
        [l7] = res["l7"]
        assert l7["rules"]["http"] == [{"method": "GET", "path": "/api",
                                        "host": "", "headers": []}]
        assert any(e["proxy_port"] == l7["proxy_port"]
                   for e in res["ingress"])

    def test_ack_blocks_until_change_then_pushes(self):
        d = _daemon()
        first = d.xds.discover({})
        v = first["version_info"]
        # ACK of the current version + no change -> timeout (None)
        assert d.xds.discover({"version_info": v}, timeout=0.05) is None

        got = {}

        def subscribe():
            got["resp"] = d.xds.discover({"version_info": v},
                                         timeout=5.0)

        t = threading.Thread(target=subscribe)
        t.start()
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"app": "admin"}}]}],
        }])
        d.endpoints.regenerate()
        t.join(timeout=5.0)
        resp = got["resp"]
        assert resp is not None
        assert int(resp["version_info"]) > int(v)

    def test_nack_recorded_and_last_good_version_stands(self):
        d = _daemon()
        resp = d.xds.discover({})
        v = resp["version_info"]
        assert d.xds.discover(
            {"version_info": "0", "response_nonce": resp["nonce"],
             "error_detail": "bad resource"}, timeout=0.05
        )["version_info"] == v  # stale version -> immediate re-push
        assert d.xds.nacks and d.xds.nacks[0][1] == "bad resource"

    def test_resource_name_subscription_filters(self):
        d = _daemon()
        resp = d.xds.discover({})
        names = [r["name"] for r in resp["resources"]]
        assert len(names) >= 2
        only = d.xds.discover({"resource_names": [names[0]]})
        assert [r["name"] for r in only["resources"]] == [names[0]]

    def test_unchanged_attach_does_not_bump_version(self):
        d = _daemon()
        v = d.xds.version
        d.endpoints.regenerate()  # same policies -> same snapshot
        assert d.xds.version == v

    def test_grpc_stream(self, tmp_path):
        grpc = pytest.importorskip("grpc")
        import json

        from cilium_tpu.proxy.xds import serve_xds

        d = _daemon()
        addr = f"unix://{tmp_path}/xds.sock"
        server = serve_xds(d.xds, addr)
        try:
            ch = grpc.insecure_channel(addr)
            stream = ch.stream_stream(
                "/cilium.NetworkPolicyDiscoveryService/"
                "StreamNetworkPolicies",
                request_serializer=lambda o: json.dumps(o).encode(),
                response_deserializer=lambda b: json.loads(b.decode()))
            resps = stream(iter([{"type_url": TYPE_URL}]))
            first = next(iter(resps))
            assert first["resources"]
            ch.close()
        finally:
            server.stop(0)


def test_grpc_stream_pushes_after_quiet_period(tmp_path):
    """Review r04: an ACKed subscriber must receive updates that land
    AFTER a quiet long-poll interval (the stream re-arms with the same
    request instead of abandoning the watch)."""
    import json
    import threading
    import time

    grpc = pytest.importorskip("grpc")
    from cilium_tpu.proxy.xds import serve_xds

    d = _daemon()
    addr = f"unix://{tmp_path}/xds2.sock"
    server = serve_xds(d.xds, addr)
    try:
        ch = grpc.insecure_channel(addr)
        stream = ch.stream_stream(
            "/cilium.NetworkPolicyDiscoveryService/StreamNetworkPolicies",
            request_serializer=lambda o: json.dumps(o).encode(),
            response_deserializer=lambda b: json.loads(b.decode()))
        v = d.xds.discover({})["version_info"]
        # subscribe ACKing the current version: nothing to push yet
        resps = stream(iter([{"version_info": v}]))
        got = {}

        def consume():
            got["resp"] = next(iter(resps))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # idle past at least one poll slice
        d.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"app": "ops"}}]}],
        }])
        d.endpoints.regenerate()
        t.join(timeout=10.0)
        assert int(got["resp"]["version_info"]) > int(v)
        ch.close()
    finally:
        server.stop(0)
