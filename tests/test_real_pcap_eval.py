"""Real-dataset eval harness (VERDICT r04 item 4): an on-disk labeled
pcap in the CIC-IDS2017 CSV schema replays through the wire parsers ->
datapath -> features, trains on the time-ordered head, and reports AUC
on the held-out tail.  The golden capture in tests/data/ is the
in-repo stand-in for the real dataset (same schema, same plumbing).
"""

import os

import numpy as np
import pytest

DATA = os.path.join(os.path.dirname(__file__), "data")
PCAP = os.path.join(DATA, "golden_cic.pcap")
CSV = os.path.join(DATA, "golden_cic.csv")


def test_evaluate_real_dataset_on_golden_capture():
    from cilium_tpu.ml.evaluate import evaluate_real_dataset

    r = evaluate_real_dataset(PCAP, CSV, n_identities=64,
                              epochs=2, batch=1024, train_frac=0.7)
    assert r["source"] == "real-pcap"
    assert r["packets"] == 6144
    assert r["train_packets"] == 4300
    assert r["eval_packets"] == 1844
    assert r["eval_attack_packets"] > 100
    # the golden capture's attacks are learnable through the real
    # parse->datapath->feature path; far above chance proves the
    # plumbing (labels aligned to packets, direction heuristic, CT
    # state) is sound end to end
    assert r["anomaly_auc"] > 0.85, r


def test_csv_labels_align_through_the_pcap_reader():
    from cilium_tpu.core.pcap import read_pcap
    from cilium_tpu.ml.evaluate import load_labels

    hdr = read_pcap(PCAP).data
    labels = load_labels(CSV, hdr)
    assert len(labels) == len(hdr)
    frac = float(labels.mean())
    assert 0.25 < frac < 0.40  # the golden mix is ~30% attack


def test_main_gates_on_env_files(monkeypatch, capsys):
    from cilium_tpu.ml import evaluate

    monkeypatch.setenv("CILIUM_TPU_CIC_PCAP", PCAP)
    monkeypatch.setenv("CILIUM_TPU_CIC_LABELS", CSV)
    found = evaluate._find_real_dataset()
    assert found == (PCAP, CSV)
    monkeypatch.delenv("CILIUM_TPU_CIC_PCAP")
    monkeypatch.delenv("CILIUM_TPU_CIC_LABELS")
    assert evaluate._find_real_dataset() == (None, None)
